"""Micro-batching BFS query server: coalesce, execute once, fan out.

The serving model follows what BLEST-style batched traversal engines and
the repo's own batched multi-source measurements (BENCHMARKS.md config 5)
say about TPU BFS throughput: one batched program over S sources costs
barely more than one source, so the way to serve a stream of independent
queries is to admit them into a bounded queue, coalesce up to ``max_batch``
sources per tick into ONE call of the batched engine, and fan the rows back
out per request.  The whole loop is a single daemon thread; JAX dispatch
stays single-threaded (device work parallelism comes from the batch axis,
not host threads).

Robustness semantics:

  * **backpressure** — a full admission queue raises :class:`AdmissionError`
    at submit time instead of queueing unboundedly;
  * **deadlines** — a request whose deadline expires before its batch is
    formed completes with :class:`QueryTimeout`; an expired-in-flight
    request still gets its (correct) answer, since the batch was already
    paid for — expiry can never yield a wrong answer, only a late or
    missing one;
  * **cancellation** — ``future.cancel()`` before batch formation works;
    cancelled requests are skipped at batch time;
  * **retry** — a TRANSIENT device-path failure (tunnel drop, dispatch
    timeout, UNAVAILABLE window — the classifier lives in
    :mod:`bfs_tpu.resilience.retry`) is retried with capped exponential
    backoff + jitter, bounded by the batch's earliest request deadline,
    before any degradation; a permanent failure (shape error, OOM, plain
    bug) skips the retries entirely;
  * **degradation** — graphs at or under ``oracle_max_vertices`` vertices,
    and any batch whose device path fails permanently (or exhausts its
    retries), are served by the sequential oracle (canonical min-parent,
    bit-exact with the engines) when the host graph is available.

Every reply carries a :class:`~bfs_tpu.utils.metrics.QueryRecord`; the
server-level :class:`~bfs_tpu.utils.metrics.ServeMetrics` aggregates the
latency/batching/cache statistics the loadgen prints.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass, field

import numpy as np

from .. import knobs
from ..analysis.runtime import make_lock
from ..graph.csr import INF_DIST
from ..models.bfs import check_sources
from ..models.multisource import MultiBfsResult, collapse_multi_source
from ..obs.spans import span as obs_span
from ..resilience.faults import fault_point
from ..resilience.retry import RetryPolicy, retry_call
from ..utils.metrics import QueryRecord, ServeMetrics
from .executor import (
    ExecutableCache,
    bucket_for,
    build_batch_runner,
    run_oracle_batch,
)
from .health import HungCallError, ServeHealth
from .registry import ENGINES, GraphRegistry

logger = logging.getLogger(__name__)

#: Default device-path retry shape: short delays (a serving tick is
#: latency-bound) and few attempts; callers pass ``retry_policy`` for a
#: different shape or ``RetryPolicy(max_attempts=1)`` to disable retries.
DEFAULT_RETRY_POLICY = RetryPolicy(
    max_attempts=3, base_delay_s=0.02, max_delay_s=0.5
)


class ServeError(RuntimeError):
    """Base class for serving-layer failures."""


class AdmissionError(ServeError):
    """The bounded admission queue is full — retry later (backpressure)."""


class QueryTimeout(ServeError):
    """The request's deadline expired before its batch was formed."""


class ServerClosed(ServeError):
    """The server was shut down before the request could be served."""


class CircuitOpenError(ServeError):
    """The executable's circuit is open and no degraded path exists (the
    graph was registered layout-only, so there is no host oracle)."""


@dataclass
class ServeReply:
    """One served query.  ``dist``/``parent`` are int32[V] for single-source
    and collapsed multi-source queries, int32[S, V] for ``mode='tree'``."""

    graph: str
    engine: str
    mode: str
    sources: np.ndarray
    dist: np.ndarray
    parent: np.ndarray
    num_levels: int
    record: QueryRecord


def _parent_chain(parent: np.ndarray, u: int, v: int) -> list | None:
    """Path ``[u, ..., v]`` from a single-source parent tree rooted at
    ``u`` (walks v's parent pointers back to the root)."""
    chain = [int(v)]
    cur = int(v)
    limit = int(parent.shape[-1])
    while cur != u:
        cur = int(parent[cur])
        if cur < 0 or len(chain) > limit:
            return None
        chain.append(cur)
    return chain[::-1]


@dataclass
class DistReply:
    """One point-distance query (``query_dist``).  ``method`` records the
    tier that produced the answer: ``'labels'`` (tight certificate —
    provably exact), ``'exact'`` (traversal fallback), or
    ``'labels_verified'`` (a sampled tight answer that was ALSO checked
    against the traversal before shipping)."""

    graph: str
    u: int
    v: int
    dist: int
    method: str
    landmark: int | None = None
    path: list | None = None


@dataclass
class _Request:
    graph: str
    engine: str
    mode: str  # 'single' | 'tree' | 'collapse'
    sources: np.ndarray
    future: Future
    submitted_at: float
    deadline: float | None
    oracle: bool  # tiny-graph degradation decided at admission
    rec: object = None  # pinned RegisteredGraph snapshot (epoch at admission)
    pinned: bool = False  # pin outstanding; released once via _unpin
    cache_key: tuple | None = None
    record: QueryRecord = field(default_factory=QueryRecord)


# Batch padding lives with the executable cache it keys:
# :func:`bfs_tpu.serve.executor.bucket_for` (the coalescing budget, not the
# bucket function, bounds the input; a single oversized multi-source query
# is allowed through as its own batch).


class BfsServer:
    """In-process BFS query-serving engine over a :class:`GraphRegistry`.

    ``tick_s`` is the coalescing window: after the first request of a tick
    arrives the batcher waits up to ``tick_s`` for more before executing
    (0 = greedy drain of whatever is already queued, the test default).
    """

    def __init__(
        self,
        registry: GraphRegistry | None = None,
        *,
        engine: str = "pull",
        max_batch: int = 32,
        tick_s: float = 0.0,
        queue_depth: int = 256,
        result_cache_size: int = 256,
        exe_cache_size: int = 64,
        oracle_max_vertices: int = 0,
        metrics: ServeMetrics | None = None,
        retry_policy: RetryPolicy | None = None,
        breaker_failures: int = 3,
        breaker_cooldown_s: float = 5.0,
        watchdog_s: float = 60.0,
        watchdog_multiplier: float = 8.0,
        watchdog_min_s: float = 1.0,
        watchdog_compile_floor_s: float = 1200.0,
        verify_sample: int = 0,
    ):
        if engine not in ENGINES:
            raise ValueError(f"unknown engine {engine!r}; use one of {ENGINES}")
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.registry = (
            registry if registry is not None else GraphRegistry(metrics=self.metrics)
        )
        # Lock-guarded handoff: registry.metrics is shared state and a
        # second server attaching to the same registry raced the bare
        # read-then-write this used to be (found by the LCK pass).
        self.registry.attach_metrics(self.metrics)
        self.default_engine = engine
        self.max_batch = int(max_batch)
        self.tick_s = float(tick_s)
        self.queue_depth = int(queue_depth)
        self.oracle_max_vertices = int(oracle_max_vertices)
        self.retry_policy = (
            retry_policy if retry_policy is not None else DEFAULT_RETRY_POLICY
        )
        self.exe_cache = ExecutableCache(exe_cache_size, metrics=self.metrics)
        # The self-healing authority (ISSUE 9): circuit breaker per
        # compiled executable, hung-call watchdog, sampled on-device
        # integrity checks.  One object so the device path consults one
        # gate; all its state transitions land in self.metrics.
        self._health = ServeHealth(  # immutable after init
            metrics=self.metrics,
            breaker_failures=breaker_failures,
            breaker_cooldown_s=breaker_cooldown_s,
            watchdog_s=watchdog_s,
            watchdog_multiplier=watchdog_multiplier,
            watchdog_min_s=watchdog_min_s,
            compile_floor_s=watchdog_compile_floor_s,
            verify_sample=verify_sample,
        )
        # Epoch-retirement hook: per-epoch breaker cells / latency windows
        # / checkers die with the epoch's device state, so periodic hot
        # swaps never grow health state (or the report payload) unboundedly.
        # A LISTENER, not an attribute overwrite — servers sharing one
        # registry each subscribe their own health; close() detaches.
        self.registry.add_retire_listener(self._health.forget_epoch)
        # Label oracle tier (ISSUE 20): per-(name, epoch) landmark
        # distance-label indexes built at register() time when
        # BFS_TPU_LABELS is on.  The retire listener drops an epoch's
        # index with its device state — an epoch bump can never serve
        # stale labels.
        self._labels: dict[tuple, object] = {}  # guarded-by: _lock
        self._label_tick = 0  # guarded-by: _lock (verify sampling)
        self.registry.add_retire_listener(self._drop_label_epoch)
        # Direction policy resolved ONCE: a malformed BFS_TPU_DIRECTION /
        # alpha / beta knob fails server construction loudly instead of
        # raising inside every tick (which would silently degrade every
        # query to the host oracle).
        from ..models.direction import resolve_direction

        self._direction_key = resolve_direction().key()  # immutable after init
        self._lock = make_lock("server._lock")
        self._cond = threading.Condition(self._lock)  # holding _cond == holding _lock
        self._result_cache: OrderedDict[tuple, tuple] = OrderedDict()  # guarded-by: _lock
        self._result_cache_size = int(result_cache_size)  # immutable after init
        self._pending: deque[_Request] = deque()  # guarded-by: _lock
        self._paused = False  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self._thread = threading.Thread(
            target=self._serve_loop, name="bfs-serve", daemon=True
        )
        self._thread.start()

    # ----------------------------------------------------------- lifecycle --
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=30)
        with self._cond:
            drained = list(self._pending)
            self._pending.clear()
        for req in drained:
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(ServerClosed("server closed"))
            self._unpin(req)
        # Detach the health hook: a shared registry outlives this server
        # and must not call into its dead ServeHealth.
        self.registry.remove_retire_listener(self._health.forget_epoch)
        self.registry.remove_retire_listener(self._drop_label_epoch)
        with self._lock:
            self._labels.clear()

    def pause(self) -> None:
        """Hold batch formation (admission continues) — lets tests and
        maintenance windows stage a known set of requests per tick."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    # ----------------------------------------------------------- admission --
    def register(self, name: str, graph, **kw):
        """Register — or HOT-SWAP — a graph.  Re-registering an existing
        name creates a new epoch (see :meth:`GraphRegistry.register`):
        queries admitted after this call see the new graph, in-flight
        queries finish on the snapshot they were admitted under, and the
        old epoch's device operands are released when its last in-flight
        reference drops.  Executable and result caches need no purge —
        their keys carry the epoch, so old entries can never serve the
        new graph and age out of their LRUs naturally.

        With ``BFS_TPU_LABELS=<K>`` and a host graph, registration also
        builds (or warm-loads from the layout store's sidecar) the
        landmark distance-label index for the NEW epoch — the hot-swap
        contract extends to the label tier: point queries admitted after
        this call answer from the new index, the old one dies with its
        epoch."""
        rec = self.registry.register(name, graph, **kw)
        self._maybe_build_labels(rec)
        return rec

    def unregister(self, name: str) -> None:
        """Drop a graph AND every cache derived from it.  Use this (not
        ``registry.unregister``) on a server: the compiled executables and
        result LRU entries are keyed by graph name, and a later
        re-registration under the same name must never serve answers — or
        run programs — computed against the old graph."""
        self.registry.unregister(name)
        self.exe_cache.drop_graph(name)
        with self._lock:
            for key in [k for k in self._result_cache if k[0] == name]:
                del self._result_cache[key]
            for key in [k for k in self._labels if k[0] == name]:
                del self._labels[key]

    def query(self, graph: str, source: int, **kw) -> Future:
        """Single-source shortest-path query; reply rows are 1-D."""
        return self.submit(graph, [int(source)], mode="single", **kw)

    def query_multi(
        self, graph: str, sources, *, collapse: bool = True, **kw
    ) -> Future:
        """Multi-source query: ``collapse=True`` serves the oracle's
        multi-source semantics (``dist[v] = min_s dist_s[v]``), else
        independent per-source trees (``mode='tree'``)."""
        return self.submit(
            graph, sources, mode="collapse" if collapse else "tree", **kw
        )

    # ------------------------------------------------------- label tier --
    def _drop_label_epoch(self, name: str, epoch: int) -> None:
        # Retire listener: fires under the registry lock — touch only our
        # own state, never call back into the registry.
        with self._lock:
            self._labels.pop((name, epoch), None)

    def _label_oracle(self, name: str, epoch: int):
        with self._lock:
            return self._labels.get((name, epoch))

    def _maybe_build_labels(self, rec) -> None:
        """Build/load the label index for a freshly registered epoch.
        Label availability is best-effort: a build failure or a budget
        reject logs, bumps a counter, and the server keeps serving
        exact-only — the tier may only ever ADD speed."""
        k = knobs.get("BFS_TPU_LABELS")
        if not k:
            return
        if rec.graph is None:
            self.metrics.bump("label_build_skipped")
            return
        from .labels import LabelBudgetError, build_label_oracle

        try:
            oracle, info = build_label_oracle(
                rec.graph, k, cache=self.registry.layout_cache
            )
        except LabelBudgetError as exc:
            logger.warning("label index over budget: %s", exc)
            self.metrics.bump("label_budget_rejects")
            return
        except Exception:
            logger.warning(
                "label index build failed; serving exact-only",
                exc_info=True,
            )
            self.metrics.bump("label_build_errors")
            return
        with self._lock:
            self._labels[(rec.name, rec.epoch)] = oracle
        self.metrics.bump("label_builds")
        self.metrics.bump(
            "label_build_cache_hits" if info.get("cache") == "hit"
            else "label_build_cache_misses"
        )

    def query_dist(self, graph: str, u: int, v: int, *,
                   want_path: bool = False, **kw) -> Future:
        """Point query ``dist(u, v)`` — the label oracle tier.

        Tight label answers (provably exact via the triangle-inequality
        certificate) resolve IMMEDIATELY from the device-resident index —
        no traversal, no batch queue, same fast-path shape as a result
        cache hit.  Non-tight pairs, and graphs registered without labels
        (``BFS_TPU_LABELS=off``), chain onto the exact traversal path
        (:meth:`query` from ``u``, every robustness property included).
        Every ``BFS_TPU_LABELS_VERIFY``-th tight answer is ALSO re-derived
        through the exact path and cross-checked before shipping; a
        mismatch quarantines the index (label_verify_failures) and the
        exact answer ships instead — sampled verification, like every
        other serve reply.  Returns a Future resolving to
        :class:`DistReply`; ``want_path`` additionally reconstructs a
        shortest path (label tier: through the certifying landmark;
        fallback: from the traversal's parent tree)."""
        u, v = int(u), int(v)
        rec = self.registry.get(graph)
        check_sources(rec.num_vertices, np.asarray([u, v], dtype=np.int32))
        oracle = self._label_oracle(graph, rec.epoch)
        if oracle is not None:
            d, tight, best_k = oracle.dist_one(u, v)
            if tight:
                self.metrics.bump("label_hits")
                path = oracle.path(u, v) if want_path else None
                verify_every = knobs.get("BFS_TPU_LABELS_VERIFY")
                if verify_every > 0:
                    with self._lock:
                        self._label_tick += 1
                        sample = self._label_tick % verify_every == 0
                    if sample:
                        return self._verify_label_answer(
                            graph, rec.epoch, u, v, d, best_k, path, **kw
                        )
                fut: Future = Future()
                fut.set_result(DistReply(
                    graph, u, v, d, "labels",
                    landmark=int(oracle.index.landmarks[best_k]),
                    path=path,
                ))
                return fut
            self.metrics.bump("label_fallbacks")
        else:
            self.metrics.bump("label_misses")
        return self._exact_dist(graph, u, v, want_path, **kw)

    def query_path(self, graph: str, u: int, v: int, **kw) -> Future:
        """Shortest-path point query; sugar for ``query_dist(...,
        want_path=True)`` — exact path through the certifying landmark
        when the label bound is tight, traversal parent-chain otherwise."""
        return self.query_dist(graph, u, v, want_path=True, **kw)

    def _exact_dist(self, graph: str, u: int, v: int, want_path: bool,
                    **kw) -> Future:
        """Chain a point query onto the exact traversal path."""
        outer: Future = Future()
        inner = self.submit(graph, [u], mode="single", **kw)

        def _done(f: Future):
            try:
                reply = f.result()
            except BaseException as exc:
                outer.set_exception(exc)
                return
            try:
                d = int(reply.dist[v])
                path = (
                    _parent_chain(reply.parent, u, v)
                    if want_path and d < INF_DIST else None
                )
                outer.set_result(DistReply(
                    graph, u, v, d, "exact", path=path
                ))
            except BaseException as exc:  # defensive: never hang the future
                outer.set_exception(exc)

        inner.add_done_callback(_done)
        return outer

    def _verify_label_answer(self, graph: str, epoch: int, u: int, v: int,
                             label_d: int, best_k: int, path,
                             **kw) -> Future:
        """Sampled cross-check: re-derive the answer through the exact
        path and compare before shipping.  A mismatch drops the epoch's
        index (it can never be trusted again) and ships the EXACT answer."""
        outer: Future = Future()
        inner = self._exact_dist(graph, u, v, False, **kw)

        def _done(f: Future):
            try:
                exact = f.result()
            except BaseException as exc:
                outer.set_exception(exc)
                return
            if exact.dist != label_d:
                self.metrics.bump("label_verify_failures")
                logger.error(
                    "label answer mismatch on %s: dist(%d,%d) labels=%d "
                    "exact=%d — quarantining the label index",
                    graph, u, v, label_d, exact.dist,
                )
                self._drop_label_epoch(graph, epoch)
                outer.set_result(exact)
                return
            self.metrics.bump("label_verifies")
            outer.set_result(DistReply(
                graph, u, v, label_d, "labels_verified", path=path
            ))

        inner.add_done_callback(_done)
        return outer

    def submit(
        self,
        graph: str,
        sources,
        *,
        mode: str = "single",
        engine: str | None = None,
        timeout_s: float | None = None,
    ) -> Future:
        """Admit one query; returns a :class:`concurrent.futures.Future`
        resolving to a :class:`ServeReply` (or raising
        :class:`QueryTimeout` / :class:`ServerClosed`).

        Raises :class:`AdmissionError` immediately when the bounded queue
        is full, and ``ValueError``/``KeyError`` for malformed requests —
        admission errors are the caller's, never the batcher's."""
        if mode not in ("single", "tree", "collapse"):
            raise ValueError(f"unknown mode {mode!r}")
        engine = engine or self.default_engine
        if engine not in ENGINES:
            raise ValueError(f"unknown engine {engine!r}; use one of {ENGINES}")
        # Pin the CURRENT epoch at admission: this is the snapshot the
        # caller observed, and the pin is what keeps it alive (layouts +
        # device operands) through a hot swap until the reply lands.
        rec = self.registry.pin(graph)
        req: _Request | None = None
        try:
            sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
            if sources.ndim != 1:
                raise ValueError("sources must be a scalar or 1-D sequence")
            if mode == "single" and sources.shape[0] != 1:
                raise ValueError("mode='single' takes exactly one source")
            check_sources(rec.num_vertices, sources)
            now = time.monotonic()
            future: Future = Future()
            oracle = (
                rec.graph is not None
                and rec.num_vertices <= self.oracle_max_vertices
            )
            req = _Request(
                graph=graph,
                engine=engine,
                mode=mode,
                sources=sources,
                future=future,
                submitted_at=now,
                deadline=(now + float(timeout_s)) if timeout_s is not None else None,
                oracle=oracle,
                rec=rec,
                pinned=True,
            )
            req.cache_key = (
                graph, rec.epoch, engine, mode, tuple(sources.tolist())
            )
            cached = self._result_cache_get(req.cache_key)
            if cached is not None:
                dist, parent, num_levels = cached
                self.metrics.bump("result_cache_hits")
                rec_q = QueryRecord(
                    graph=graph,
                    engine=engine,
                    status="result_cache",
                    epoch=rec.epoch,
                    num_sources=int(sources.shape[0]),
                    result_cache_hit=True,
                )
                self.metrics.record_query(rec_q, ts=time.monotonic())
                future.set_result(
                    ServeReply(graph, engine, mode, sources, dist, parent,
                               num_levels, rec_q)
                )
                self._unpin(req)
                return future
            self.metrics.bump("result_cache_misses")
            with self._cond:
                if self._closed:
                    raise ServerClosed("server is closed")
                if len(self._pending) >= self.queue_depth:
                    self.metrics.bump("rejected")
                    raise AdmissionError(
                        f"admission queue full ({self.queue_depth} pending)"
                    )
                self._pending.append(req)
                self._cond.notify_all()
        except BaseException:
            # Rejected/invalid requests never reached the queue: balance
            # the admission pin before the error propagates.
            if req is not None:
                self._unpin(req)
            else:
                self.registry.unpin(rec)
            raise
        return future

    def _unpin(self, req: _Request) -> None:
        """Release a request's epoch pin exactly once (every completion
        path — reply, timeout, cancel, close, batch failure — funnels
        through here; idempotent so overlapping paths are safe)."""
        if req.pinned:
            req.pinned = False
            self.registry.unpin(req.rec)

    # --------------------------------------------------------- result cache --
    def _result_cache_get(self, key):
        with self._lock:
            hit = self._result_cache.get(key)
            if hit is not None:
                self._result_cache.move_to_end(key)
            return hit

    def _result_cache_put(self, key, value) -> None:
        if self._result_cache_size <= 0 or key is None:
            return
        with self._lock:
            self._result_cache[key] = value
            self._result_cache.move_to_end(key)
            while len(self._result_cache) > self._result_cache_size:
                self._result_cache.popitem(last=False)

    # ------------------------------------------------------------- batching --
    def _serve_loop(self) -> None:
        while True:
            with self._cond:
                while not self._closed and (self._paused or not self._pending):
                    self._cond.wait(timeout=0.1)
                if self._closed:
                    return
                first = self._pending.popleft()
            if self.tick_s > 0:
                # Coalescing window: give concurrent submitters a tick to
                # land in the same batch before the shapes are fixed.
                time.sleep(self.tick_s)
            batch = [first]
            budget = self.max_batch - first.sources.shape[0]
            with self._cond:
                keep: deque[_Request] = deque()
                while self._pending:
                    req = self._pending.popleft()
                    compatible = (
                        req.rec is first.rec  # same graph AND same epoch:
                        # a batch never mixes snapshots across a hot swap
                        and req.engine == first.engine
                        and req.oracle == first.oracle
                        and req.sources.shape[0] <= budget
                    )
                    if compatible:
                        batch.append(req)
                        budget -= req.sources.shape[0]
                    else:
                        keep.append(req)
                self._pending.extendleft(reversed(keep))
            try:
                # One span per executed tick batch: with the eviction
                # markers and the metrics snapshot this is the serve
                # loop's complete Perfetto story (coalesce -> execute ->
                # fan out); empty ticks never reach here, so the buffer
                # only grows with real work.
                with obs_span(
                    "serve.batch",
                    graph=batch[0].graph,
                    engine=batch[0].engine,
                    requests=len(batch),
                ):
                    self._execute_batch(batch)
            except Exception as exc:  # defensive: the loop must survive
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(exc)
            finally:
                # Every request that entered a tick releases its epoch pin
                # here, whatever path it took (reply, timeout, cancel,
                # batch failure) — _unpin is idempotent, and this is the
                # hook that lets a swapped-out epoch free its HBM.
                for req in batch:
                    self._unpin(req)

    def _execute_batch(self, batch: list[_Request]) -> None:
        formed_at = time.monotonic()
        live: list[_Request] = []
        for req in batch:
            if not req.future.set_running_or_notify_cancel():
                self.metrics.bump("cancelled")
                continue
            if req.deadline is not None and formed_at > req.deadline:
                self._finish_timeout(req, formed_at)
                continue
            live.append(req)
        if not live:
            return
        first = live[0]
        all_sources = np.concatenate([r.sources for r in live])
        padded = bucket_for(all_sources.shape[0])
        # The batch executes against the epoch its requests were ADMITTED
        # under (every req in a batch shares one pinned rec — the coalescer
        # requires it): a hot swap between admission and execution must not
        # change the answer.
        rec = first.rec
        # One circuit per compiled executable; the exe key adds the
        # direction policy because that is a compile-time input, not a
        # health property.
        circuit_key = (first.graph, rec.epoch, first.engine, padded)
        exe_key = (
            first.graph, rec.epoch, first.engine, padded,
            self._direction_key,
        )
        compile_hit: bool | None = None
        status = "ok"
        device_attempted = False
        t0 = time.monotonic()

        def _oracle_tick():
            # The sequential fallback, shared by every degraded path.
            # Padding exists only for compiled-shape stability; the
            # sequential path runs the real sources, nothing more.
            self.metrics.bump("oracle_served")
            return run_oracle_batch(rec.graph, all_sources), "oracle", \
                all_sources.shape[0]

        try:
            if first.oracle:
                result, status, padded = _oracle_tick()
            elif not self._health.allow(circuit_key):
                # Circuit open: this executable failed permanently
                # ``breaker_failures`` ticks in a row (or was quarantined
                # by a failed integrity verdict).  Short-circuit straight
                # to the degraded path — no retry loop, no watchdog wait —
                # until the cooldown admits a canary.
                self.metrics.bump("breaker_short_circuits")
                if rec.graph is None:
                    raise CircuitOpenError(
                        f"circuit open for {circuit_key} and graph "
                        f"{first.graph!r} was registered layout-only — no "
                        "host oracle to degrade to"
                    )
                result, status, padded = _oracle_tick()
            else:
                sources_padded = np.concatenate(
                    [all_sources,
                     np.full(padded - all_sources.shape[0], all_sources[0],
                             dtype=np.int32)]
                )
                deadlines = [r.deadline for r in live if r.deadline is not None]

                def _device_tick():
                    def _guarded():
                        nonlocal compile_hit
                        # The direction policy (resolved ONCE at server
                        # init — a malformed knob fails construction,
                        # never a tick) is part of the executable key
                        # (ISSUE 7): today the relay batch runner reads
                        # the same env at build, so the key keeps a
                        # stale-program reuse impossible when the knob
                        # changes across server restarts; when the batch
                        # programs grow in-program switching the key is
                        # already right.  Auto-switching itself is an
                        # IN-program lax.cond — steady-state ticks never
                        # retrace however often the schedule flips
                        # direction.
                        runner, compile_hit = self.exe_cache.get(
                            exe_key,
                            lambda: build_batch_runner(
                                self.registry, first.graph, first.engine,
                                padded, epoch=rec.epoch,
                            ),
                        )
                        # ``raise:serve.batch`` = a classified-permanent
                        # device fault; ``delay:serve.batch:N`` = a wedged
                        # XLA call the watchdog must catch.
                        fault_point("serve.batch")
                        return runner(sources_padded)

                    # The watchdog deadline is p99-informed per circuit
                    # key and tightened by the batch's earliest request
                    # deadline — a wedged call times out (HungCallError,
                    # permanent) instead of freezing the serve thread.
                    # The BUILD runs inside the guarded call too: a wedged
                    # compile must degrade the tick, not freeze the loop —
                    # a cold tick's budget is floored at compile_floor_s
                    # so an honest minutes-long compile never trips it.
                    return self._health.run_guarded(
                        circuit_key, _guarded, deadlines,
                        describe=f"device batch ({first.graph}/{first.engine})",
                        cold=exe_key not in self.exe_cache,
                    )

                retried = {"n": 0}

                def _on_retry(attempt, exc, delay):
                    retried["n"] += 1
                    self.metrics.bump("device_retries")

                # Transient failures (tunnel drop, UNAVAILABLE window) get
                # a bounded backoff retry BEFORE any oracle degradation —
                # previously one flake degraded the whole tick.  Bounded by
                # the batch's earliest deadline: a tick with 50 ms left
                # must not sleep 500 ms to find out.
                device_attempted = True
                # ISSUE 14 hung-call resume: a watchdog timeout abandons
                # only the attempt THREAD; when the runner is a
                # checkpointing SegmentedBatchRunner its completed
                # segments survive as in-process epochs, so another
                # attempt RESUMES mid-traversal instead of recomputing
                # from the roots.  Re-attempt only while progress
                # actually advances (a wedge at the same superstep twice
                # means the device path is dead — degrade) and the batch
                # deadline has not passed.
                resume_progress = None
                while True:
                    try:
                        result = retry_call(
                            _device_tick,
                            policy=self.retry_policy,
                            deadline_s=(
                                min(deadlines) - time.monotonic()
                                if deadlines else None
                            ),
                            on_retry=_on_retry,
                            describe=(
                                f"device batch ({first.graph}/"
                                f"{first.engine})"
                            ),
                        )
                        break
                    except HungCallError:
                        runner0 = self.exe_cache.peek(exe_key)
                        prog_fn = getattr(runner0, "ckpt_progress", None)
                        progress = prog_fn() if callable(prog_fn) else None
                        past_deadline = bool(deadlines) and (
                            time.monotonic() >= min(deadlines)
                        )
                        if (
                            progress is None
                            or progress == resume_progress
                            or past_deadline
                        ):
                            raise
                        resume_progress = progress
                        self.metrics.bump("ckpt_hung_resumes")
                if retried["n"]:
                    self.metrics.bump("device_retry_successes")
                self._health.record_success(circuit_key)
                # Sampled production integrity check: every Kth executed
                # device tick re-verifies one answered root on device
                # (~28-byte verdict pull).  A failed verdict is proof the
                # executable is wrong — quarantine it (force-open the
                # circuit AND drop the cached runner so the half-open
                # canary rebuilds rather than re-probes the same artifact)
                # and re-run this batch on the fallback path.
                verdict = self._health.maybe_verify(rec, result, all_sources)
                if verdict is not None:
                    # maybe_verify only samples when rec.graph is present,
                    # so the oracle re-run below always has a host graph.
                    self._health.quarantine(
                        circuit_key, f"integrity verdict {verdict}"
                    )
                    self.exe_cache.drop_key(exe_key)
                    # A proven-wrong executable may already have fed the
                    # result LRU on unsampled ticks (verify_sample > 1):
                    # purge this graph epoch's cached answers too, or the
                    # quarantine serves known-bad results as cache hits.
                    with self._lock:
                        for k in [
                            k for k in self._result_cache
                            if k[0] == first.graph and k[1] == rec.epoch
                        ]:
                            del self._result_cache[k]
                    result, status, padded = _oracle_tick()
                    compile_hit = None
        except Exception as exc:
            if device_attempted:
                # Permanent failure or exhausted transient retries: one
                # more consecutive strike against this executable (after
                # ``breaker_failures`` of them the circuit opens and later
                # ticks skip straight to the degraded path).
                self._health.record_failure(circuit_key, repr(exc))
            if rec.graph is None:
                raise
            # Device path failed permanently (OOM, lowering, a real bug) or
            # exhausted its transient retries: degrade to the sequential
            # oracle EXACTLY ONCE rather than failing the whole tick.
            self.metrics.bump("device_errors")
            result, status, padded = _oracle_tick()
            compile_hit = None
        service_s = time.monotonic() - t0
        self.metrics.bump("batches")

        row = 0
        for req in live:
            s = req.sources.shape[0]
            rows = slice(row, row + s)
            row += s
            sub = MultiBfsResult(
                sources=req.sources,
                dist=result.dist[rows],
                parent=result.parent[rows],
                num_levels=result.num_levels,
            )
            if req.mode == "collapse":
                dist, parent = collapse_multi_source(sub)
            elif req.mode == "single":
                dist, parent = sub.dist[0], sub.parent[0]
            else:
                dist, parent = sub.dist, sub.parent
            done = time.monotonic()
            req.record = QueryRecord(
                graph=req.graph,
                engine=req.engine,
                status=status,
                epoch=rec.epoch,
                num_sources=s,
                batch_size=padded,
                supersteps=result.num_levels,
                queue_wait_s=formed_at - req.submitted_at,
                service_s=service_s,
                total_s=done - req.submitted_at,
                compile_hit=compile_hit,
            )
            reply = ServeReply(
                req.graph, req.engine, req.mode, req.sources,
                dist, parent, result.num_levels, req.record,
            )
            self._result_cache_put(req.cache_key, (dist, parent, result.num_levels))
            self.metrics.record_query(req.record, ts=done)
            req.future.set_result(reply)

    def _finish_timeout(self, req: _Request, now: float) -> None:
        req.record = QueryRecord(
            graph=req.graph,
            engine=req.engine,
            status="timeout",
            num_sources=int(req.sources.shape[0]),
            queue_wait_s=now - req.submitted_at,
            total_s=now - req.submitted_at,
        )
        self.metrics.bump("timeouts")
        self.metrics.record_query(req.record, ts=now)
        req.future.set_exception(
            QueryTimeout(
                f"deadline expired after {req.record.total_s * 1e3:.1f} ms "
                "in queue"
            )
        )

    # -------------------------------------------------------------- reports --
    def report(self) -> dict:
        out = self.metrics.report()
        epochs = {}
        for n in self.registry.names():
            # names() and epoch() are two lock acquisitions: a concurrent
            # unregister between them must shrink the snapshot, not crash
            # the monitoring caller.
            try:
                epochs[n] = self.registry.epoch(n)
            except KeyError:
                continue
        out["registry"] = {
            "graphs": list(epochs),
            "epochs": epochs,
            "resident_bytes": self.registry.resident_bytes(),
            "resident": [list(k) for k in self.registry.resident_keys()],
            "evictions": self.registry.evictions,
            "evictions_deferred": self.registry.evictions_deferred,
            "budget_bytes": self.registry.device_budget_bytes,
        }
        out["executables_cached"] = len(self.exe_cache)
        with self._lock:
            out["labels"] = {
                f"{name}@{epoch}": oracle.report()
                for (name, epoch), oracle in self._labels.items()
            }
        # Breaker snapshot (per-circuit state/failures/open-for) + watchdog
        # budgets + integrity sampling state — the self-healing view the
        # chaos driver asserts its transitions against.
        out["health"] = self._health.report()
        return out
