"""Registry-resident semiring algorithms (ISSUE 16).

The serve layer's BFS path uploads a graph's device operands once per
(graph, engine) epoch and reuses them for every query; the semiring
algorithms ride the same residency: :func:`registry_sssp` and
:func:`registry_cc` pin the current epoch (so a concurrent hot-swap or
LRU eviction cannot retire the operands mid-traversal), acquire the
push/pull operands through :meth:`GraphRegistry.acquire_for`, and run
the fused algo programs against them — no per-call H2B upload, and the
registry's HBM budget governs the algorithms exactly as it governs BFS.

Weights need no residency of their own: the SSSP arm recomputes them
on device from the resident edge endpoints
(:func:`bfs_tpu.algo.substrate.edge_weights`).
"""

from __future__ import annotations

from ..algo.cc import CcResult, cc_device, cc_device_pull
from ..algo.sssp import SsspResult, sssp_device
from .registry import GraphRegistry

__all__ = ["registry_sssp", "registry_cc"]


def _num_vertices(registry: GraphRegistry, rec, engine: str) -> int:
    # acquire_for has already built+memoized the layout; both the
    # DeviceGraph (push) and PullGraph (pull) carry the real unpadded V.
    return int(registry._layout_for(rec, engine).num_vertices)


def registry_sssp(
    registry: GraphRegistry,
    name: str,
    source: int = 0,
    **kwargs,
) -> SsspResult:
    """Weighted SSSP on a registered graph's resident push operands.
    ``kwargs`` pass through to :func:`bfs_tpu.algo.sssp.sssp_device`
    (max_weight / delta / max_rounds / packed)."""
    rec = registry.pin(name)
    try:
        src_dev, dst_dev = registry.acquire_for(rec, "push")
        return sssp_device(
            src_dev, dst_dev, _num_vertices(registry, rec, "push"),
            source, **kwargs,
        )
    finally:
        registry.unpin(rec)


def registry_cc(
    registry: GraphRegistry,
    name: str,
    *,
    engine: str = "push",
    max_rounds: int | None = None,
) -> CcResult:
    """Connected components on a registered graph's resident operands
    (``engine`` = push | pull; both reach the same label fixpoint)."""
    if engine not in ("push", "pull"):
        raise ValueError(
            f"unknown engine {engine!r}; registry CC runs 'push' or 'pull'"
        )
    rec = registry.pin(name)
    try:
        operands = registry.acquire_for(rec, engine)
        v = _num_vertices(registry, rec, engine)
        if engine == "pull":
            ell0, folds = operands
            return cc_device_pull(ell0, folds, v, max_rounds=max_rounds)
        src_dev, dst_dev = operands
        return cc_device(src_dev, dst_dev, v, max_rounds=max_rounds)
    finally:
        registry.unpin(rec)
