"""Hash-routed serve fleet (ISSUE 20): N replicas behind one thin router.

One :class:`~bfs_tpu.serve.server.BfsServer` is a single serial batch
loop; read-heavy point-query traffic wants N of them.  The fleet model:

* **replicas** — N in-process ``BfsServer`` instances, each with its OWN
  :class:`~bfs_tpu.serve.registry.GraphRegistry` (own device residency
  book-keeping, own health authority), all sharing ONE content-addressed
  on-disk :class:`~bfs_tpu.cache.layout.LayoutCache` (process-safe:
  atomic tmp+rename writes, first builder wins).  A real multi-process
  fleet shares exactly the same store — the router here is the
  single-host tier of ROADMAP item 5.
* **routing** — deterministic hash of (graph, sources) picks the primary
  replica, so repeated queries land on the same result/executable caches;
  everything else about admission (backpressure, deadlines, breakers,
  watchdog) is the replica's own machinery, reused as-is.
* **failover** — a replica that rejects at admission or fails a routed
  query is retried on the next replica in the ring; ``BFS_TPU_ROUTER_FAILURES``
  consecutive failures open a router-side breaker for
  ``BFS_TPU_ROUTER_COOLDOWN_S`` (a closed/dead replica is routed around
  permanently).  Deadline expiry is the CALLER's budget, never a replica
  fault — it does not failover and does not count against the breaker.
* **epoch rolls** — ``register`` walks the replicas SEQUENTIALLY: the
  first pays the (disk-cached) build, the rest warm-hit the shared
  bundles — a fleet-wide hot swap without a thundering-herd rebuild.
  During the roll replicas serve mixed epochs; every answer is computed
  against one consistent snapshot, which is the same guarantee a single
  server gives mid-swap.
"""

from __future__ import annotations

import hashlib
import logging
import time
from concurrent.futures import Future

import numpy as np

from .. import knobs
from ..analysis.runtime import make_lock
from ..utils.metrics import ServeMetrics
from .registry import GraphRegistry
from .server import BfsServer, QueryTimeout, ServeError

logger = logging.getLogger(__name__)


class NoReplicaAvailable(ServeError):
    """Every replica is dead, breaker-open, or rejected the query."""


class _ReplicaState:
    __slots__ = ("failures", "open_until", "dead")

    def __init__(self):
        self.failures = 0
        self.open_until = 0.0
        self.dead = False


class FleetRouter:
    """Thin hash-by-graph router over N in-process serve replicas.

    Construct with ``replicas=N`` (each replica gets a fresh registry
    wired to the shared ``layout_cache``), or inject pre-built
    ``servers`` for tests.  ``**server_kw`` is forwarded to every
    constructed :class:`BfsServer`."""

    def __init__(
        self,
        replicas: int = 2,
        *,
        layout_cache=None,
        metrics: ServeMetrics | None = None,
        servers: list | None = None,
        failure_threshold: int | None = None,
        cooldown_s: float | None = None,
        **server_kw,
    ):
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self._failure_threshold = (
            failure_threshold if failure_threshold is not None
            else knobs.get("BFS_TPU_ROUTER_FAILURES")
        )
        self._cooldown_s = (
            cooldown_s if cooldown_s is not None
            else knobs.get("BFS_TPU_ROUTER_COOLDOWN_S")
        )
        if servers is not None:
            self.servers = tuple(servers)  # immutable: death lives in _state
        else:
            if replicas < 1:
                raise ValueError(f"need >= 1 replica (got {replicas})")
            self.servers = tuple(
                BfsServer(GraphRegistry(layout_cache=layout_cache),
                          **server_kw)
                for _ in range(int(replicas))
            )
        self._state = [_ReplicaState() for _ in self.servers]
        self._lock = make_lock("router._lock")

    # ----------------------------------------------------------- lifecycle --
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self) -> None:
        for srv in self.servers:
            srv.close()

    @property
    def num_replicas(self) -> int:
        return len(self.servers)

    def alive(self) -> list[int]:
        with self._lock:
            return [i for i, st in enumerate(self._state) if not st.dead]

    def kill_replica(self, i: int) -> None:
        """Induced replica failure (chaos/tests): close the server and
        route around it permanently."""
        with self._lock:
            self._state[i].dead = True
        self.metrics.bump("router_replicas_killed")
        self.servers[i].close()

    # ------------------------------------------------------------- rolling --
    def register(self, name: str, graph, **kw) -> list:
        """Fleet-wide register / hot swap — a SEQUENTIAL roll: replica 0
        pays the (sidecar-cached) layout and label builds, later replicas
        warm-hit the shared on-disk store.  Returns the per-replica
        epoch records."""
        recs = []
        for i, srv in enumerate(self.servers):
            with self._lock:
                dead = self._state[i].dead
            if dead:
                continue
            recs.append(srv.register(name, graph, **kw))
            self.metrics.bump("router_rolling_registers")
        if not recs:
            raise NoReplicaAvailable("no live replica to register on")
        return recs

    def unregister(self, name: str) -> None:
        for i, srv in enumerate(self.servers):
            with self._lock:
                dead = self._state[i].dead
            if not dead:
                srv.unregister(name)

    # ------------------------------------------------------------- routing --
    def _ring(self, graph: str, sources) -> list[int]:
        """Primary-first replica order for one query: deterministic hash
        of (graph, sources) — repeated queries hit the same replica's
        result/executable caches — then the rest of the ring for
        failover."""
        seed = f"{graph}:{','.join(str(int(s)) for s in np.atleast_1d(sources))}"
        h = int.from_bytes(
            hashlib.blake2b(seed.encode(), digest_size=8).digest(), "big"
        )
        n = len(self.servers)
        start = h % n
        return [(start + i) % n for i in range(n)]

    def _usable(self, i: int, now: float) -> bool:
        with self._lock:
            st = self._state[i]
            return not st.dead and st.open_until <= now

    def _record_failure(self, i: int, why: str) -> None:
        self.metrics.bump("router_replica_failures")
        with self._lock:
            st = self._state[i]
            st.failures += 1
            if st.failures >= self._failure_threshold:
                st.failures = 0
                st.open_until = time.monotonic() + self._cooldown_s
                opened = True
            else:
                opened = False
        if opened:
            self.metrics.bump("router_breaker_opens")
            logger.warning(
                "router breaker OPEN on replica %d for %.1fs (%s)",
                i, self._cooldown_s, why,
            )

    def _record_success(self, i: int) -> None:
        with self._lock:
            self._state[i].failures = 0

    def _candidates(self, graph: str, sources) -> list[int]:
        now = time.monotonic()
        ring = self._ring(graph, sources)
        candidates = [i for i in ring if self._usable(i, now)]
        if not candidates:
            # Last resort: breaker-open replicas are still better than a
            # guaranteed reject (dead ones are not).
            live = set(self.alive())
            candidates = [i for i in ring if i in live]
        if not candidates:
            self.metrics.bump("router_rejected")
            raise NoReplicaAvailable("every replica is dead")
        return candidates

    def submit(self, graph: str, sources, *, mode: str = "single",
               engine: str | None = None,
               timeout_s: float | None = None) -> Future:
        """Route one query; failover walks the ring.  Returns a Future
        with the winning replica's reply.  Raises
        :class:`NoReplicaAvailable` when every replica is unusable or
        rejected; malformed requests (ValueError/KeyError) propagate from
        the primary without failover — they would fail everywhere."""
        self.metrics.bump("router_submits")
        candidates = self._candidates(graph, sources)
        outer: Future = Future()
        kw = dict(mode=mode, engine=engine, timeout_s=timeout_s)
        self._failover_chain(
            outer, candidates,
            lambda srv: srv.submit(graph, sources, **kw),
        )
        return outer

    def _failover_chain(self, outer: Future, candidates: list[int],
                        call) -> None:
        """Run ``call(replica)`` down the candidate ring: a replica that
        rejects at admission OR whose future completes with a ServeError
        (closed mid-query, open circuit with no degraded path) fails over
        to the next.  Deadline expiry (QueryTimeout) is the caller's
        budget, never a replica fault — it propagates unretried."""
        i = candidates[0]
        rest = candidates[1:]
        try:
            inner = call(self.servers[i])
        except QueryTimeout:
            raise  # the caller's budget, not a replica fault
        except ServeError as exc:
            self._record_failure(i, repr(exc))
            if rest:
                self.metrics.bump("router_failovers")
                self._failover_chain(outer, rest, call)
                return
            self.metrics.bump("router_rejected")
            outer.set_exception(
                NoReplicaAvailable(f"all replicas rejected: {exc!r}")
            )
            return

        def _done(f: Future):
            exc = f.exception()
            if exc is None:
                self._record_success(i)
                outer.set_result(f.result())
                return
            if isinstance(exc, ServeError) and not isinstance(
                exc, QueryTimeout
            ):
                self._record_failure(i, repr(exc))
                if rest:
                    self.metrics.bump("router_failovers")
                    try:
                        self._failover_chain(outer, rest, call)
                    except BaseException as retry_exc:
                        # A raise inside a done-callback would otherwise
                        # be swallowed and leave ``outer`` unresolved.
                        outer.set_exception(retry_exc)
                    return
            outer.set_exception(exc)

        inner.add_done_callback(_done)

    # ------------------------------------------------------- query sugar --
    def query(self, graph: str, source: int, **kw) -> Future:
        return self.submit(graph, [int(source)], mode="single", **kw)

    def query_dist(self, graph: str, u: int, v: int, **kw) -> Future:
        """Point query through the label tier of the routed replica (hash
        on the (u, v) pair so both tiers' caches stay replica-local),
        with the same admission- and completion-time failover as
        :meth:`submit`."""
        self.metrics.bump("router_point_queries")
        candidates = self._candidates(graph, [u, v])
        outer: Future = Future()
        self._failover_chain(
            outer, candidates,
            lambda srv: srv.query_dist(graph, u, v, **kw),
        )
        return outer

    # -------------------------------------------------------------- report --
    def report(self) -> dict:
        now = time.monotonic()
        with self._lock:
            states = [
                {
                    "dead": st.dead,
                    "breaker_open": st.open_until > now,
                    "consecutive_failures": st.failures,
                }
                for st in self._state
            ]
        return {
            "router": {
                **self.metrics.report()["counters"],
                "replicas": states,
                "failure_threshold": self._failure_threshold,
                "cooldown_s": self._cooldown_s,
            },
            "replicas": [srv.report() for srv in self.servers],
        }
