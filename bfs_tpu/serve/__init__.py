"""bfs_tpu.serve — long-lived, in-process BFS query serving.

The batch engines answer "run S searches now"; this package answers "keep
answering searches forever": register a graph once (layout + device
operands memoized, evicted LRU under an HBM budget), then stream
single-source and multi-source queries through a micro-batcher that
coalesces them into the batched multi-source engine and never recompiles
in steady state.

    from bfs_tpu.serve import BfsServer

    server = BfsServer()
    server.register("g", graph)
    reply = server.query("g", 0).result()
    reply.dist, reply.parent          # canonical min-parent BFS tree

Components: :class:`GraphRegistry` (epoch-versioned layouts + residency:
re-registering a name hot-swaps the graph while in-flight queries finish
on their admission-time snapshot), :class:`ExecutableCache` (compiled
programs keyed by (graph, epoch, engine, batch shape, direction)),
:class:`BfsServer` (admission queue, micro-batching, deadlines,
transient-failure retry with backoff (:mod:`bfs_tpu.resilience.retry`),
result LRU, oracle degradation), :class:`ServeHealth` (ISSUE 9: circuit
breaker per executable, hung-call watchdog, sampled on-device integrity
checks — the self-healing layer), :class:`LabelOracle` +
``BfsServer.query_dist`` (ISSUE 20: landmark distance-label tier — point
queries answer from a precomputed device-resident label index when the
tightness certificate holds, exact-traversal fallback otherwise), and
:class:`FleetRouter` (ISSUE 20: N replicas behind a deterministic
hash-by-graph router with failover and rolling epoch swaps over the
shared on-disk caches).
"""

from .algo import registry_cc, registry_sssp
from .registry import ENGINES, GraphRegistry, RegisteredGraph
from .executor import ExecutableCache, build_batch_runner, run_oracle_batch
from .health import HungCallError, ServeHealth, run_with_deadline
from .labels import LabelBudgetError, LabelIndex, LabelOracle, build_label_index
from .router import FleetRouter, NoReplicaAvailable
from .server import (
    DEFAULT_RETRY_POLICY,
    AdmissionError,
    BfsServer,
    CircuitOpenError,
    DistReply,
    QueryTimeout,
    ServeError,
    ServeReply,
    ServerClosed,
)

__all__ = [
    "DEFAULT_RETRY_POLICY",
    "DistReply",
    "FleetRouter",
    "LabelBudgetError",
    "LabelIndex",
    "LabelOracle",
    "NoReplicaAvailable",
    "build_label_index",
    "ENGINES",
    "GraphRegistry",
    "RegisteredGraph",
    "ExecutableCache",
    "build_batch_runner",
    "run_oracle_batch",
    "AdmissionError",
    "BfsServer",
    "CircuitOpenError",
    "HungCallError",
    "QueryTimeout",
    "ServeError",
    "ServeHealth",
    "ServeReply",
    "ServerClosed",
    "registry_cc",
    "registry_sssp",
    "run_with_deadline",
]
