"""Self-healing serve: breaker wiring, hung-call watchdog, integrity checks.

The serving loop (PR 1) trusted every device call three ways, and each
trust was a way to serve wrong — or no — answers under a real fault:

  * a **permanently failing executable** (bad lowering, a poisoned compile
    cache entry, a driver wedged into an error state) burned a full retry
    loop + oracle degradation on EVERY tick it touched;
  * a **hung XLA call** (the round-4 ledger's tunnel stalls; a device
    lockup) blocked the single serve thread forever — one wedged dispatch
    froze every queue on every graph;
  * a **silently wrong result** (bit flips in HBM, a miscompiled kernel —
    the faults cluster-scale BFS work like Compression-and-Sieve takes as
    given) was fanned out to callers unchecked.

:class:`ServeHealth` is the one object the server consults on the device
path, composing three defenses:

  * **circuit breaker** — one :class:`~bfs_tpu.resilience.retry.CircuitBreaker`
    circuit per ``(graph, epoch, engine, bucket)`` executable.  After
    ``breaker_failures`` consecutive permanent failures the circuit opens
    and ticks short-circuit straight to the oracle/degraded path; after
    ``breaker_cooldown_s`` the next tick is admitted as the half-open
    CANARY batch, closing the circuit on success.  Every transition lands
    a ``ServeMetrics`` counter, an obs-registry counter, and an instant
    span marker.
  * **hung-call watchdog** — each device batch call runs under a deadline
    on a disposable daemon thread (:func:`run_with_deadline`).  The budget
    is p99-informed per circuit key (``multiplier × observed p99``, with
    the configured default before enough history exists) and, when the
    batch carries request deadlines, tightened to the earliest deadline
    plus a small grace — a wedged call times out with
    :class:`HungCallError` (classified PERMANENT: re-dispatching a wedged
    program is not a recovery strategy), trips the breaker, and the tick
    degrades instead of freezing the server.  A COLD tick (the executable
    is not yet cached, so the guarded call includes the AOT lower/compile
    — minutes at bench scale) raises the budget to ``compile_floor_s``:
    still finite (a wedged compile must not freeze the server either),
    but far above any honest build.  The wedged thread is left
    to die with the process (daemon; there is no portable way to kill it)
    — what matters is that the serve loop moved on.
  * **sampled integrity checks** — every ``verify_sample``-th executed
    device tick re-verifies ONE answered root with the PR 2
    :class:`~bfs_tpu.oracle.device.DeviceChecker` (the VERDICT comes back
    as a ~28-byte pull; the sampled row's dist/parent are re-shipped to
    device for the check — the result state was already fanned out to
    host).  A failed verdict is treated as proof the executable is wrong:
    the circuit is force-opened (quarantine), the cached runner is
    dropped, the batch re-runs on the fallback path, and
    ``integrity_failures`` is emitted.  ``raise:serve.verify`` fault
    injection is interpreted as a failed verdict, so the quarantine path
    is exercisable without real corruption.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from ..analysis.runtime import make_lock
from ..obs import get_registry, instant
from ..resilience.faults import FaultInjected, fault_point
from ..resilience.retry import CircuitBreaker, PermanentError
from ..utils.metrics import percentile


class HungCallError(PermanentError):
    """A device batch call exceeded its watchdog budget.  Permanent by
    class: the call may still be running (the thread cannot be killed),
    and re-dispatching against a wedged device only stacks more hung work
    — the tick degrades and the breaker decides about the next one."""


def run_with_deadline(fn, timeout_s: float, describe: str = "call"):
    """Run ``fn()`` on a disposable daemon thread, waiting ``timeout_s``.

    Returns ``fn``'s result or raises its exception; raises
    :class:`HungCallError` when the deadline passes first.  The worker
    thread is abandoned on timeout (daemon — it dies with the process);
    its eventual result, if any, is discarded.  A fresh thread per call
    keeps a wedged call from poisoning a shared worker — thread spawn is
    microseconds against a device batch's milliseconds."""
    box: dict = {}
    done = threading.Event()

    def _run():
        try:
            box["value"] = fn()
        except BaseException as exc:  # delivered to the waiter below
            box["error"] = exc
        done.set()

    worker = threading.Thread(
        target=_run, name="bfs-serve-watchdog-call", daemon=True
    )
    worker.start()
    if not done.wait(timeout_s):
        raise HungCallError(
            f"{describe}: no result within the {timeout_s:.3f}s watchdog "
            "budget (call abandoned on its worker thread)"
        )
    if "error" in box:
        raise box["error"]
    return box["value"]


class _LatencyWindow:
    """Bounded per-key service-time history feeding the watchdog budget;
    fields guarded by the owning :class:`ServeHealth`'s lock."""

    __slots__ = ("samples",)

    def __init__(self, maxlen: int = 128):
        self.samples: deque = deque(maxlen=maxlen)


class ServeHealth:
    """Per-server health authority: breaker + watchdog + integrity.

    One instance per :class:`~bfs_tpu.serve.BfsServer`; consulted only
    from the serve loop (but internally locked — metrics readers and
    tests may probe concurrently).  ``watchdog_s <= 0`` disables the
    watchdog entirely; ``verify_sample <= 0`` disables integrity
    sampling; the breaker is always on (an open circuit needs
    ``breaker_failures`` PERMANENT failures, which the healthy path never
    produces).
    """

    #: Samples required before the p99 budget replaces the default.
    MIN_SAMPLES = 8

    def __init__(
        self,
        *,
        metrics,
        breaker_failures: int = 3,
        breaker_cooldown_s: float = 5.0,
        watchdog_s: float = 60.0,
        watchdog_multiplier: float = 8.0,
        watchdog_min_s: float = 1.0,
        compile_floor_s: float = 1200.0,
        verify_sample: int = 0,
    ):
        self.metrics = metrics  # ServeMetrics is internally locked
        self.watchdog_s = float(watchdog_s)  # immutable after init
        self.watchdog_multiplier = float(watchdog_multiplier)  # immutable after init
        self.watchdog_min_s = float(watchdog_min_s)  # immutable after init
        # Budget floor for guarded calls that include an AOT compile (the
        # cold tick for a new epoch/bucket): generous against the round-5
        # ledger's ~830 s bench-scale compile, still finite so a wedged
        # compile times out instead of freezing the serve loop forever.
        self.compile_floor_s = float(compile_floor_s)  # immutable after init
        self.verify_sample = int(verify_sample)  # immutable after init
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_failures,
            cooldown_s=breaker_cooldown_s,
            on_transition=self._on_transition,
        )
        self._lock = make_lock("health._lock")
        self._latency: dict[tuple, _LatencyWindow] = {}  # guarded-by: _lock
        self._ticks = 0  # executed device ticks, drives sampling — guarded-by: _lock
        # (name, epoch) -> DeviceChecker; small LRU (epochs churn on swap).
        self._checkers: OrderedDict = OrderedDict()  # guarded-by: _lock

    # ----------------------------------------------------------- breaker --
    def _on_transition(self, key, old: str, new: str, reason: str) -> None:
        counter = {
            "open": "breaker_opened",
            "half_open": "breaker_half_open",
            "closed": "breaker_closed",
        }[new]
        self.metrics.bump(counter)
        get_registry().counter(counter)
        instant(
            "serve.breaker",
            key="/".join(str(p) for p in key),
            transition=f"{old}->{new}", reason=reason,
        )

    def allow(self, key) -> bool:
        """May this tick touch the device path for ``key``?  False =
        short-circuit to the degraded path (circuit open, cooldown not
        elapsed, or another canary already in flight)."""
        return self.breaker.allow(key)

    def record_success(self, key) -> None:
        self.breaker.record_success(key)

    def record_failure(self, key, reason: str = "") -> None:
        self.breaker.record_failure(key, reason)

    def quarantine(self, key, reason: str) -> None:
        """Force-open the circuit for a PROVEN-wrong executable."""
        self.breaker.force_open(key, reason)

    def forget_epoch(self, name: str, epoch: int) -> None:
        """Drop every per-key cell for one retired ``(graph, epoch)``:
        circuit cells, latency windows, the sampled checker.  Wired to
        :attr:`GraphRegistry.on_retire` so a long-lived server doing
        periodic hot swaps — the streaming-graph shape — does not grow
        its health state (and ``report()['health']``) with every swap.
        Keys are ``(graph, epoch, engine, bucket)``; retirement fires
        after the epoch's last pin drops, so no in-flight tick can
        recreate what this prunes."""
        self.breaker.forget(lambda k: k[0] == name and k[1] == epoch)
        with self._lock:
            for k in [
                k for k in self._latency
                if k[0] == name and k[1] == epoch
            ]:
                del self._latency[k]
            self._checkers.pop((name, epoch), None)

    # ---------------------------------------------------------- watchdog --
    def budget_s(self, key) -> float:
        """The p99-informed watchdog budget for one circuit key: the
        configured default until :data:`MIN_SAMPLES` service times exist,
        then ``multiplier × p99`` floored at ``watchdog_min_s`` — tight
        enough to catch a wedge within a few healthy-tick lengths, loose
        enough that the occasional fallback recompile inside a runner
        (the packed-cap latch) does not false-positive."""
        with self._lock:
            win = self._latency.get(key)
            samples = list(win.samples) if win is not None else []
        if len(samples) < self.MIN_SAMPLES:
            return self.watchdog_s
        return max(self.watchdog_min_s, self.watchdog_multiplier * percentile(samples, 99))

    def timeout_for(self, key, deadlines, now: float | None = None) -> float | None:
        """The effective watchdog timeout for one batch, or None when the
        watchdog is disabled.  Derived from the batch's earliest request
        deadline plus a grace of ``watchdog_min_s`` (a wedged call never
        outlives the deadline its callers are waiting on by more than the
        grace), bounded above by the per-key p99-informed budget."""
        if self.watchdog_s <= 0:
            return None
        budget = self.budget_s(key)
        if deadlines:
            now = time.monotonic() if now is None else now
            remaining = max(0.0, min(deadlines) - now)
            budget = min(budget, remaining + self.watchdog_min_s)
        return max(self.watchdog_min_s, budget)

    def observe_latency(self, key, seconds: float) -> None:
        with self._lock:
            win = self._latency.get(key)
            if win is None:
                win = self._latency[key] = _LatencyWindow()
            win.samples.append(float(seconds))

    def run_guarded(self, key, fn, deadlines, describe: str = "device batch",
                    cold: bool = False):
        """Run one device batch attempt under the watchdog; successful
        calls feed the latency window the budget derives from.  A timeout
        bumps ``watchdog_timeouts`` and raises :class:`HungCallError`
        (permanent — the caller's breaker bookkeeping sees it like any
        other permanent failure).

        ``cold=True`` marks a call that includes the executable build
        (cache miss): the timeout is floored at ``compile_floor_s`` so an
        honest minutes-long compile is never false-positived, while a
        truly wedged compile still times out instead of freezing the
        serve loop — request deadlines do NOT tighten a cold tick below
        the floor (the compile is unskippable work the next tick would
        re-pay anyway)."""
        timeout_s = self.timeout_for(key, deadlines)
        if cold and timeout_s is not None:
            timeout_s = max(timeout_s, self.compile_floor_s)
        t0 = time.monotonic()
        if timeout_s is None:
            out = fn()
        else:
            try:
                out = run_with_deadline(fn, timeout_s, describe=describe)
            except HungCallError:
                self.metrics.bump("watchdog_timeouts")
                get_registry().counter("watchdog_timeouts")
                instant(
                    "serve.watchdog",
                    key="/".join(str(p) for p in key),
                    budget_s=round(timeout_s, 3),
                )
                raise
        if not cold:
            # Cold durations include the AOT build: one compile-sized
            # sample at the p99 interpolation point would inflate the
            # warm-tick budget to ~multiplier × compile time for the
            # next ~window of ticks, defeating the catch-a-wedge-within-
            # a-few-healthy-tick-lengths contract.
            self.observe_latency(key, time.monotonic() - t0)
        return out

    # --------------------------------------------------------- integrity --
    #: Resident DeviceChecker bound: one per actively-sampled graph name
    #: plus transient swap overlap.  Each checker pins its OWN copy of the
    #: epoch's edge arrays on device (8·E bytes), OUTSIDE the registry's
    #: HBM budget — the cap is what bounds that unbudgeted footprint.
    MAX_CHECKERS = 4

    def _checker(self, rec):
        """Memoized DeviceChecker for one graph epoch.

        The checker's edge-array upload is a second, registry-invisible
        device copy of the graph, so retention is aggressive: inserting a
        CURRENT epoch's checker drops every other epoch of the same name
        (a replaced epoch's checker is only ever needed again for batches
        already in flight across a swap — those rebuild transiently and
        age out), and the LRU is capped at :data:`MAX_CHECKERS` overall."""
        from ..oracle.device import DeviceChecker

        ckey = (rec.name, rec.epoch)
        with self._lock:
            hit = self._checkers.get(ckey)
            if hit is not None:
                self._checkers.move_to_end(ckey)
                return hit
        checker = DeviceChecker.from_graph(rec.graph)
        with self._lock:
            checker = self._checkers.setdefault(ckey, checker)
            self._checkers.move_to_end(ckey)
            if not rec.retired:
                for k in [
                    k for k in self._checkers
                    if k[0] == rec.name and k != ckey
                ]:
                    del self._checkers[k]
            while len(self._checkers) > self.MAX_CHECKERS:
                self._checkers.popitem(last=False)
        return checker

    def maybe_verify(self, rec, result, sources) -> dict | None:
        """Every ``verify_sample``-th executed device tick, re-verify one
        answered root against the BreadthFirstPaths invariants on device.

        Returns None when sampling skipped this tick or the verdict was
        clean; a non-empty verdict dict when the sampled root FAILED —
        the caller quarantines the executable and re-runs the batch on
        the fallback path.  Requires the host graph (edge arrays); a
        layout-only registration is never sampled.

        Cost per sample: the verdict itself is the ~28-byte pull, but the
        sampled row's dist/parent (already fanned out to host) are
        re-shipped to device for the check — an O(V) H2D transfer.  Size
        ``verify_sample`` accordingly; verifying against the pre-pull
        device state would shrink this to the advertised pull alone and
        is the known follow-up."""
        if self.verify_sample <= 0 or rec.graph is None:
            return None
        with self._lock:
            self._ticks += 1
            ticks = self._ticks
        if ticks % self.verify_sample:
            return None
        n = int(sources.shape[0])
        row = ticks % n  # rotate through the batch's real rows

        def _run_check():
            fault_point("serve.verify")
            return self._checker(rec).check(
                result.dist[row], result.parent[row], int(sources[row])
            )

        try:
            if self.watchdog_s > 0:
                # The check is DEVICE work on the serve thread (edge
                # upload on a cold checker, O(V) row re-ship, verdict
                # pull): unguarded, a wedge here would freeze the loop —
                # the exact failure mode the watchdog removes from the
                # batch path.  A cold checker's budget covers its build
                # (compile floor); a hung check lands in the generic
                # handler below as check-couldn't-run, and the wedged
                # device then strikes the breaker on the next batch.
                with self._lock:
                    warm = (rec.name, rec.epoch) in self._checkers
                budget = (
                    max(self.watchdog_min_s, self.watchdog_s)
                    if warm else self.compile_floor_s
                )
                verdict = run_with_deadline(
                    _run_check, budget,
                    describe=f"integrity check ({rec.name}/{rec.epoch})",
                )
            else:
                verdict = _run_check()
        except FaultInjected:
            # Injected corruption: the chaos schedule's stand-in for a
            # wrong on-device answer — same consequence as a real one.
            verdict = {"injected_fault": 1}
        except Exception as exc:
            # The CHECK failing to run is not evidence the answer is
            # wrong (e.g. a transport blip on the 28-byte pull): count
            # it, keep serving, let the next sample try again.
            self.metrics.bump("integrity_check_errors")
            get_registry().counter("integrity_check_errors")
            instant("serve.integrity_error", graph=rec.name, error=repr(exc))
            return None
        self.metrics.bump("integrity_checks")
        get_registry().counter("integrity_checks")
        if not verdict:
            return None
        self.metrics.bump("integrity_failures")
        get_registry().counter("integrity_failures")
        instant(
            "serve.integrity_failure",
            graph=rec.name, epoch=rec.epoch,
            source=int(sources[row]), verdict=dict(verdict),
        )
        return verdict

    # ------------------------------------------------------------ report --
    def report(self) -> dict:
        """JSON-ready breaker snapshot + watchdog budget state."""
        with self._lock:
            budgets = {
                "/".join(str(p) for p in key): {
                    "samples": len(win.samples),
                    "p99_s": percentile(win.samples, 99) if win.samples else None,
                }
                for key, win in self._latency.items()
            }
            ticks = self._ticks
        return {
            "breaker": self.breaker.snapshot(),
            "watchdog_budgets": budgets,
            "verify_sample": self.verify_sample,
            "verified_ticks": ticks,
        }
