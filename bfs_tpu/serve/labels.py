"""Landmark distance-label oracle tier (ISSUE 20): stop paying a full
traversal per point query.

The serve path answered every ``dist(u, v)`` with a full level-synchronous
traversal from ``u`` — the wrong shape for heavy read traffic (the paper's
own workload).  The bit-packed multi-source machinery
(:mod:`bfs_tpu.models.multisource`) makes a few-hundred-root sweep cheap,
so at ``register()`` time we precompute K landmark BFS forests once and
answer point queries from the resulting **distance labels** in one tiny
batched gather+min program:

* **schema** — ``dist: uint16[K, V]`` (0xFFFF = unreachable sentinel) is
  the device-resident half; ``parent: int32[K, V]`` + ``landmarks:
  int32[K]`` stay on host for path reconstruction.  Every graph the
  framework builds is undirected (``Graph.from_undirected_edges``), so one
  forward label set serves both query directions.
* **tightness certificate** — for undirected graphs the labels bound the
  true distance both ways: ``upper = min_k(d[k,u] + d[k,v])`` and
  ``lower = max_k |d[k,u] - d[k,v]|`` (the ALT bound).  When
  ``upper == max(lower, 1)`` (or ``u == v``) the bound is PROVABLY exact
  and the label answer ships; the walk u->landmark->v of that length is a
  shortest path, which is what :meth:`LabelOracle.path` reconstructs.  A
  landmark reaching exactly one of ``u, v`` certifies the pair
  disconnected (exact ``INF_DIST``).  Anything else falls back to the
  exact traversal — labels may only ever make answers FASTER, never
  wrong.
* **content addressing** — the index is a pure function of (graph
  content, K, label code version), cached as a sidecar bundle next to the
  layout bundle (:func:`bfs_tpu.cache.layout.load_or_build_labels`) and
  budget-gated like the serve registry (``BFS_TPU_LABELS_GB``).
* **resilience** — the K-root sweep is chunked and each finished chunk is
  a durable epoch in the superstep-checkpoint store, so a killed
  precompute resumes at the last chunk boundary bit-identically.  Built
  rows are sample-verified with the :class:`DeviceChecker` before the
  index is trusted.
"""

from __future__ import annotations

import hashlib
import logging
import os
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs
from ..analysis.runtime import traced
from ..graph.csr import Graph, INF_DIST, NO_PARENT

logger = logging.getLogger(__name__)

#: Bump on any change to the label math or array schema — part of the
#: sidecar bundle key, so old bundles simply miss.
LABELS_VERSION = 1

#: uint16 unreachable sentinel inside the device-resident label rows.
LABEL_INF = 0xFFFF

#: Landmark roots swept per multi-source chunk (and per checkpoint epoch);
#: 64 matches the packed fused-word batch the engine digests best.
DEFAULT_CHUNK = 64


class LabelBudgetError(ValueError):
    """The label index does not fit ``BFS_TPU_LABELS_GB`` — the server
    drops to exact-only serving rather than evicting engine arrays."""


@dataclass(frozen=True)
class LabelIndex:
    """One graph's landmark distance labels (host-side arrays)."""

    landmarks: np.ndarray  # int32[K]
    dist: np.ndarray       # uint16[K, V], LABEL_INF = unreachable
    parent: np.ndarray     # int32[K, V], NO_PARENT = unreached
    num_vertices: int

    @property
    def k(self) -> int:
        return int(self.landmarks.shape[0])

    @property
    def device_bytes(self) -> int:
        """Bytes the resident half (dist rows) costs on device."""
        return int(self.dist.nbytes)

    @property
    def nbytes(self) -> int:
        return int(
            self.dist.nbytes + self.parent.nbytes + self.landmarks.nbytes
        )


def labels_to_arrays(idx: LabelIndex) -> dict:
    return {
        "dims": np.asarray(
            [LABELS_VERSION, idx.k, idx.num_vertices], dtype=np.int64
        ),
        "landmarks": np.asarray(idx.landmarks, dtype=np.int32),
        "dist": np.asarray(idx.dist, dtype=np.uint16),
        "parent": np.asarray(idx.parent, dtype=np.int32),
    }


def labels_from_arrays(arrays: dict) -> LabelIndex:
    dims = np.asarray(arrays["dims"])
    if int(dims[0]) != LABELS_VERSION:
        raise ValueError(
            f"label bundle version {int(dims[0])} != {LABELS_VERSION}"
        )
    return LabelIndex(
        landmarks=np.asarray(arrays["landmarks"]),
        dist=np.asarray(arrays["dist"]),
        parent=np.asarray(arrays["parent"]),
        num_vertices=int(dims[2]),
    )


# ------------------------------------------------------------- sampling --

def sample_landmarks(graph: Graph, k: int) -> np.ndarray:
    """K degree-weighted landmark roots, int32, deterministic per graph
    content (seeded from the same blake2b the cache key uses — a rebuild
    of the same graph always picks the same landmarks, so the sidecar key
    needs only (graph, K)).  High-degree hubs sit on many shortest paths,
    which is what makes the tightness certificate fire; zero-degree
    vertices are never useful landmarks and are excluded.  K is clamped
    to the number of usable roots."""
    from ..cache.layout import graph_content_hash

    if k < 1:
        raise ValueError(f"need k >= 1 landmarks (got {k})")
    v = int(graph.num_vertices)
    src = np.asarray(graph.src).reshape(-1)
    src = src[(src >= 0) & (src < v)]  # drop DeviceGraph sentinel padding
    deg = np.bincount(src, minlength=v).astype(np.float64)
    usable = np.flatnonzero(deg > 0)
    if usable.size == 0:
        # Edgeless graph: every pair is trivially u==v or disconnected;
        # any vertex works as the single landmark.
        return np.zeros((min(k, graph.num_vertices),), dtype=np.int32)
    seed = int.from_bytes(
        hashlib.blake2b(
            graph_content_hash(graph).encode(), digest_size=8
        ).digest(),
        "big",
    )
    rng = np.random.default_rng(seed)
    k_eff = min(int(k), int(usable.size))
    p = deg[usable] / deg[usable].sum()
    picked = rng.choice(usable, size=k_eff, replace=False, p=p)
    return np.sort(picked).astype(np.int32)


# ---------------------------------------------------------------- build --

def build_label_index(
    graph: Graph,
    k: int,
    *,
    engine: str = "pull",
    chunk: int = DEFAULT_CHUNK,
    ckpt_dir: str | os.PathLike | None = None,
    verify_rows: int = 2,
) -> LabelIndex:
    """Sweep K landmark roots through the multi-source engine and pack the
    forests into a :class:`LabelIndex`.

    The sweep runs in ``chunk``-root slices; when superstep checkpointing
    is on (``BFS_TPU_CKPT``), every finished slice is saved as a durable
    epoch keyed on (graph content, K, engine, chunk) — a killed build
    resumes at the last chunk boundary and the finished index is
    bit-identical to an uninterrupted one (the multi-source engine is
    deterministic).  ``verify_rows`` sampled forests are re-checked with
    the :class:`DeviceChecker` before the index is returned."""
    from ..models.multisource import bfs_multi
    from ..resilience.superstep_ckpt import SuperstepCheckpointer

    landmarks = sample_landmarks(graph, k)
    kk, v = int(landmarks.shape[0]), int(graph.num_vertices)
    chunk = max(1, int(chunk))
    dist16 = np.full((kk, v), LABEL_INF, dtype=np.uint16)
    parent = np.full((kk, v), NO_PARENT, dtype=np.int32)

    if ckpt_dir is None:
        from ..config import cache_root

        ckpt_dir = os.path.join(cache_root(), "ckpt")
    from ..cache.layout import graph_content_hash

    ckpt = SuperstepCheckpointer(
        ckpt_dir,
        {
            "kind": "labels",
            "graph": graph_content_hash(graph),
            "k": kk,
            "engine": engine,
            "chunk": chunk,
        },
    )
    start = 0
    if ckpt.enabled:
        found = ckpt.load_latest()
        if found is not None:
            ep, arrays, _ = found
            dist16[:] = np.asarray(arrays["dist"], dtype=np.uint16)
            parent[:] = np.asarray(arrays["parent"], dtype=np.int32)
            start = int(ep)
            logger.info(
                "label precompute resuming at chunk %d/%d",
                start, -(-kk // chunk),
            )

    for ci in range(start, -(-kk // chunk)):
        roots = landmarks[ci * chunk : (ci + 1) * chunk]
        res = bfs_multi(graph, roots, engine=engine)
        d = np.asarray(res.dist)
        reach = d != INF_DIST
        if reach.any() and int(d[reach].max()) >= LABEL_INF:
            raise ValueError(
                f"graph eccentricity {int(d[reach].max())} exceeds the "
                f"uint16 label range; label tier unavailable"
            )
        rows = slice(ci * chunk, ci * chunk + roots.shape[0])
        dist16[rows] = np.where(reach, d, LABEL_INF).astype(np.uint16)
        parent[rows] = np.asarray(res.parent)
        # Chunk boundary = durable epoch = kill point (fault boundary
        # fires inside save_epoch AFTER the write, even in off mode).
        ckpt.save_epoch(ci + 1, {"dist": dist16, "parent": parent})
    if ckpt.enabled:
        ckpt.clear()

    idx = LabelIndex(
        landmarks=landmarks, dist=dist16, parent=parent, num_vertices=v
    )
    _verify_rows(graph, idx, verify_rows)
    return idx


def _verify_rows(graph: Graph, idx: LabelIndex, rows: int) -> None:
    """Sample-verify built forests with the DeviceChecker — the same
    verdict program every serve reply goes through.  A violation means
    the index can never be trusted: raise, do not serve."""
    if rows < 1 or idx.k == 0:
        return
    from ..oracle.device import DeviceChecker

    checker = DeviceChecker.from_graph(graph)
    take = np.linspace(0, idx.k - 1, min(int(rows), idx.k)).astype(int)
    for r in np.unique(take):
        d = idx.dist[r].astype(np.int32)
        d = np.where(idx.dist[r] == LABEL_INF, INF_DIST, d)
        bad = checker.check(
            d, idx.parent[r], np.asarray([idx.landmarks[r]], dtype=np.int32)
        )
        if bad:
            raise ValueError(
                f"label row for landmark {int(idx.landmarks[r])} failed "
                f"device verification: {bad}"
            )


# -------------------------------------------------------- device lookup --

@jax.jit
@traced("labels._label_bounds")
def _label_bounds(dist16, u, v):
    """One batched label lookup: gather both label columns, reduce over
    the landmark axis, emit the distance plus the tightness certificate.

    Returns ``(dist, tight, best_k, upper, lower)`` over the pair batch:
    ``tight`` marks answers that are PROVABLY exact — ``u == v``, the
    sandwich ``upper == max(lower, 1)`` (a walk of length d(u,v) is a
    shortest path, and d >= 1 off-diagonal), or a landmark seeing exactly
    one endpoint (certified disconnected, ``dist == INF_DIST``)."""
    du = dist16[:, u].astype(jnp.int32)  # [K, B]
    dv = dist16[:, v].astype(jnp.int32)
    fu = du != LABEL_INF
    fv = dv != LABEL_INF
    both = fu & fv
    up = jnp.where(both, du + dv, INF_DIST)
    upper = jnp.min(up, axis=0)
    best_k = jnp.argmin(up, axis=0).astype(jnp.int32)
    lower = jnp.max(jnp.where(both, jnp.abs(du - dv), 0), axis=0)
    unreach = jnp.any(fu != fv, axis=0)
    same = u == v
    covered = jnp.any(both, axis=0)
    tight = same | unreach | (
        covered & (upper == jnp.maximum(lower, 1))
    )
    dist = jnp.where(same, 0, jnp.where(unreach, INF_DIST, upper))
    return dist, tight, best_k, upper, lower


# ---------------------------------------------------------------- oracle --

class LabelOracle:
    """Device-resident query object over one :class:`LabelIndex`.

    Holds the uint16 dist rows on device (budget-gated) and the parent
    forest on host; answers batched ``dist``/``path`` point queries in one
    compiled gather+min (:func:`_label_bounds`, registered as
    ``serve.label_lookup`` in the IR program registry)."""

    def __init__(self, index: LabelIndex, *, budget_bytes: int | None = None):
        if budget_bytes is not None and index.device_bytes > budget_bytes:
            raise LabelBudgetError(
                f"label index is {index.device_bytes >> 20} MB on device, "
                f"over the {budget_bytes >> 20} MB budget "
                f"(BFS_TPU_LABELS_GB)"
            )
        self.index = index
        self._dist_dev = jax.device_put(np.asarray(index.dist))
        self.queries = 0
        self.tight_hits = 0

    @property
    def k(self) -> int:
        return self.index.k

    @property
    def device_bytes(self) -> int:
        return self.index.device_bytes

    def bounds(self, u, v):
        """``(dist, tight, best_k, upper, lower)`` as host numpy arrays
        over the pair batch — one device round trip."""
        u = np.atleast_1d(np.asarray(u, dtype=np.int32))
        v = np.atleast_1d(np.asarray(v, dtype=np.int32))
        if u.shape != v.shape:
            raise ValueError("u and v batches must have equal shape")
        nv = self.index.num_vertices
        if u.size and (
            int(min(u.min(), v.min())) < 0
            or int(max(u.max(), v.max())) >= nv
        ):
            raise ValueError(f"query vertex outside [0, {nv})")
        out = jax.device_get(_label_bounds(self._dist_dev, u, v))
        dist, tight, best_k, upper, lower = (np.asarray(a) for a in out)
        self.queries += int(u.size)
        self.tight_hits += int(tight.sum())
        return dist, tight, best_k, upper, lower

    def dist(self, u, v):
        """``(dist, tight, best_k)`` for a pair batch; ``dist`` entries
        are exact wherever ``tight`` holds and an upper bound elsewhere
        (callers MUST fall back on non-tight pairs)."""
        d, tight, best_k, _, _ = self.bounds(u, v)
        return d, tight, best_k

    def dist_one(self, u: int, v: int):
        d, tight, best_k = self.dist([u], [v])
        return int(d[0]), bool(tight[0]), int(best_k[0])

    def path(self, u: int, v: int):
        """An EXACT shortest path ``[u, ..., v]`` when the certificate is
        tight and the pair connected, else None (caller falls back to a
        traversal).  The u->landmark and landmark->v legs come from the
        host parent forest; their concatenation has length
        ``d(k,u) + d(k,v) == d(u,v)``, hence is a shortest path."""
        if u == v:
            return [int(u)]
        d, tight, best_k, _, _ = self.bounds([u], [v])
        if not bool(tight[0]) or int(d[0]) >= INF_DIST:
            return None
        row = self.index.parent[int(best_k[0])]
        lm = int(self.index.landmarks[int(best_k[0])])
        a = self._chain(row, int(u), lm)
        b = self._chain(row, int(v), lm)
        if a is None or b is None:
            return None
        return a + b[::-1][1:]

    def _chain(self, parent_row, start: int, landmark: int):
        chain = [start]
        cur = start
        limit = self.index.num_vertices
        while cur != landmark:
            cur = int(parent_row[cur])
            if cur < 0 or len(chain) > limit:
                return None
            chain.append(cur)
        return chain

    def report(self) -> dict:
        return {
            "k": self.k,
            "device_bytes": self.device_bytes,
            "queries": self.queries,
            "tight_hits": self.tight_hits,
        }


def labels_budget_bytes() -> int:
    """The resident-label budget in bytes (``BFS_TPU_LABELS_GB``)."""
    return int(knobs.get("BFS_TPU_LABELS_GB") * (1 << 30))


def build_label_oracle(
    graph: Graph,
    k: int,
    *,
    cache=None,
    engine: str = "pull",
    ckpt_dir: str | os.PathLike | None = None,
):
    """``(LabelOracle, info)`` — the server's register-time entry point:
    the sidecar-cached index (:func:`bfs_tpu.cache.layout
    .load_or_build_labels`) wrapped in a budget-gated device oracle.
    Raises :class:`LabelBudgetError` over budget — callers keep serving
    exact-only."""
    from ..cache.layout import load_or_build_labels

    t0 = time.perf_counter()
    idx, info = load_or_build_labels(
        graph, k, cache=cache, engine=engine, ckpt_dir=ckpt_dir
    )
    oracle = LabelOracle(idx, budget_bytes=labels_budget_bytes())
    info = dict(info)
    info["total_seconds"] = time.perf_counter() - t0
    return oracle, info
