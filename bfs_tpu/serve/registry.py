"""Graph registry: build layouts once, keep device operands under a budget.

The cold-path tax the serving layer exists to amortize is two-fold
(VERDICT round 5: 434 s layout build + ~830 s compile before the first
timed repeat): the HOST layout (ELL packing / dst-sorted edge arrays) and
the DEVICE operand upload.  The registry owns both:

  * host layouts are built once per ``(graph, engine)`` and memoized for
    the registry's lifetime — they are cheap host RAM; with a
    ``layout_cache`` the build also goes through the persistent on-disk
    bundle store (:mod:`bfs_tpu.cache.layout`), so a SECOND process
    registering the same graph loads the finished layout in seconds
    instead of rebuilding it (ISSUE 2: the 434 s cold relay build);
  * device operands (the multi-GB HBM residents at bench scale) are
    tracked in an LRU keyed ``(graph, engine)`` against an explicit byte
    budget.  Evicting a pull entry calls
    :func:`bfs_tpu.graph.ell.drop_device_operands` — the release hook that
    was dead code until this subsystem — AND drops the registry's own
    reference to the returned ``(ell0, folds)`` tuple, which is what
    actually lets the runtime free the HBM.  The next
    :meth:`GraphRegistry.acquire` re-uploads.

The registry is synchronous and lock-guarded; the serving loop is its only
hot caller, but registration can happen from any thread.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from ..graph.csr import DeviceGraph, Graph, build_device_graph
from ..graph.ell import PullGraph, build_pull_graph, device_ell, drop_device_operands

ENGINES = ("pull", "push", "relay")


@dataclass
class RegisteredGraph:
    """One registered graph: the host graph plus lazily built layouts."""

    name: str
    graph: Graph | None  # host graph; None when registered from a layout
    num_vertices: int = 0
    num_edges: int = 0
    layouts: dict = field(default_factory=dict)  # engine -> layout object


def _pull_device_bytes(pg: PullGraph) -> int:
    """HBM bytes :func:`device_ell` will pin for this layout (int32)."""
    return 4 * pg.padded_slots


def _push_device_bytes(dg: DeviceGraph) -> int:
    return 4 * (int(np.asarray(dg.src).size) + int(np.asarray(dg.dst).size))


class GraphRegistry:
    """Named graphs + memoized layouts + budgeted device-operand residency.

    ``device_budget_bytes`` caps the summed size of resident device
    operands across all graphs/engines; ``None`` means unlimited (single
    graph, the common case).  The budget never blocks the entry being
    acquired — a single layout larger than the budget is allowed in alone,
    everything else is evicted around it.
    """

    def __init__(
        self,
        *,
        device_budget_bytes: int | None = None,
        metrics=None,
        layout_cache=None,
    ):
        self._lock = threading.RLock()
        self._graphs: dict[str, RegisteredGraph] = {}  # guarded-by: _lock
        # (name, engine) -> (bytes, operands-ref); insertion order = LRU.
        self._resident: OrderedDict[tuple[str, str], tuple[int, object]] = (
            OrderedDict()
        )  # guarded-by: _lock
        self.device_budget_bytes = device_budget_bytes  # immutable after init
        self.metrics = metrics  # guarded-by: _lock
        self.evictions = 0  # guarded-by: _lock
        # Persistent layout bundles: a LayoutCache, a directory path, or
        # None (in-process memoization only — the default, so tests and
        # embedders opt in to disk writes explicitly).
        if isinstance(layout_cache, str):
            from ..cache.layout import LayoutCache

            layout_cache = LayoutCache(layout_cache)
        self.layout_cache = layout_cache

    # ------------------------------------------------------------- graphs --
    def register(
        self,
        name: str,
        graph: Graph | DeviceGraph | PullGraph,
        *,
        engines: tuple[str, ...] = (),
    ) -> RegisteredGraph:
        """Register ``graph`` under ``name``; optionally pre-build layouts.

        Accepts a host :class:`Graph` (all engines available), or a prebuilt
        :class:`PullGraph` / single-shard :class:`DeviceGraph` (that engine
        only; no oracle fallback without the host graph)."""
        with self._lock:
            if name in self._graphs:
                raise ValueError(f"graph {name!r} already registered")
            if isinstance(graph, PullGraph):
                rec = RegisteredGraph(
                    name, None, graph.num_vertices, graph.num_edges,
                    {"pull": graph},
                )
            elif isinstance(graph, DeviceGraph):
                if graph.num_shards != 1:
                    raise ValueError("serve registry takes single-shard graphs")
                rec = RegisteredGraph(
                    name, None, graph.num_vertices, graph.num_edges,
                    {"push": graph},
                )
            elif isinstance(graph, Graph):
                rec = RegisteredGraph(
                    name, graph, graph.num_vertices, graph.num_edges
                )
            else:
                raise TypeError(f"cannot register {type(graph).__name__}")
            self._graphs[name] = rec
        for engine in engines:
            self.layout(name, engine)
        return rec

    def get(self, name: str) -> RegisteredGraph:
        with self._lock:
            try:
                return self._graphs[name]
            except KeyError:
                raise KeyError(f"graph {name!r} is not registered") from None

    def names(self) -> list[str]:
        with self._lock:
            return list(self._graphs)

    def unregister(self, name: str) -> None:
        """Drop a graph entirely: evict its device operands, forget layouts.

        On a :class:`~bfs_tpu.serve.BfsServer`, call ``server.unregister``
        instead — the server also holds compiled executables and result-LRU
        entries keyed by this name that must be invalidated with it."""
        with self._lock:
            for key in [k for k in self._resident if k[0] == name]:
                self._evict(key)
            self._graphs.pop(name, None)

    # ------------------------------------------------------------ layouts --
    def layout(self, name: str, engine: str):
        """The memoized host layout for ``(graph, engine)``, built on first
        use: :class:`PullGraph`, dst-sorted :class:`DeviceGraph`, or a
        :class:`~bfs_tpu.models.bfs.RelayEngine`."""
        if engine not in ENGINES:
            raise ValueError(f"unknown engine {engine!r}; use one of {ENGINES}")
        rec = self.get(name)
        with self._lock:
            layout = rec.layouts.get(engine)
        if layout is not None:
            return layout
        if rec.graph is None:
            raise ValueError(
                f"graph {name!r} was registered as a prebuilt "
                f"{list(rec.layouts)[0]!r} layout; engine {engine!r} needs "
                "the host Graph"
            )
        if engine == "pull":
            layout = self._build_pull(rec.graph)
        elif engine == "push":
            layout = build_device_graph(rec.graph)
        else:  # relay: the engine object IS the layout (it owns its tensors)
            from ..models.bfs import RelayEngine

            layout = RelayEngine(self._build_relay_layout(rec.graph))
        with self._lock:
            # Lost-race double build is possible without holding the lock
            # through the (expensive) build; keep the first one stored.
            layout = rec.layouts.setdefault(engine, layout)
        return layout

    def attach_metrics(self, metrics) -> None:
        """Adopt a metrics sink unless one is already attached.  The
        lock-guarded form of the ``if registry.metrics is None:
        registry.metrics = ...`` handoff servers used to do bare — two
        servers attaching to one shared registry raced it (LCK001)."""
        with self._lock:
            if self.metrics is None:
                self.metrics = metrics

    def _note_disk(self, info: dict) -> None:
        with self._lock:  # metrics ref is shared; snapshot it under the lock
            metrics = self.metrics
        if metrics is not None and info.get("cache") == "hit":
            metrics.bump("layout_disk_hits")
        elif metrics is not None and info.get("cache") == "miss":
            metrics.bump("layout_disk_misses")

    def _build_pull(self, graph: Graph) -> PullGraph:
        if self.layout_cache is None:
            return build_pull_graph(graph)
        from ..cache.layout import load_or_build_pull

        pg, info = load_or_build_pull(graph, cache=self.layout_cache)
        self._note_disk(info)
        return pg

    def _build_relay_layout(self, graph: Graph):
        """The RelayEngine constructor arg: the disk-cached RelayGraph when
        a layout cache is configured, else the host graph (the engine
        builds the layout itself)."""
        if self.layout_cache is None:
            return graph
        from ..cache.layout import load_or_build_relay

        rg, info = load_or_build_relay(graph, cache=self.layout_cache)
        self._note_disk(info)
        return rg

    # ---------------------------------------------------------- residency --
    def acquire(self, name: str, engine: str):
        """Device operands for ``(graph, engine)``, uploading within budget.

        Returns the operand handle the executor passes to the compiled
        program: ``(ell0, folds)`` for pull, ``(src, dst)`` device arrays
        for push, the :class:`RelayEngine` itself for relay.  Marks the
        entry most-recently-used and evicts LRU entries (via
        :func:`drop_device_operands` for pull) until the budget holds."""
        import jax.numpy as jnp

        layout = self.layout(name, engine)
        key = (name, engine)
        with self._lock:
            if key in self._resident:
                self._resident.move_to_end(key)
                return self._resident[key][1]
            if engine == "pull":
                nbytes = _pull_device_bytes(layout)
            elif engine == "push":
                nbytes = _push_device_bytes(layout)
            else:
                rg = layout.relay_graph
                nbytes = int(rg.vperm_masks.nbytes + rg.net_masks.nbytes)
            self._make_room(nbytes, keep=key)
            if engine == "pull":
                operands = device_ell(layout)
            elif engine == "push":
                operands = (jnp.asarray(layout.src), jnp.asarray(layout.dst))
            else:
                operands = layout  # tensors uploaded at engine init
            self._resident[key] = (nbytes, operands)
            return operands

    # bfs_tpu: holds _lock
    def _make_room(self, incoming: int, *, keep) -> None:
        if self.device_budget_bytes is None:
            return
        while (
            self._resident
            and self.resident_bytes() + incoming > self.device_budget_bytes
        ):
            victim = next(k for k in self._resident if k != keep)
            self._evict(victim)

    # bfs_tpu: holds _lock
    def _evict(self, key: tuple[str, str]) -> None:
        name, engine = key
        nbytes = self._resident[key][0]
        self._resident.pop(key)  # drops OUR reference to the operands
        rec = self._graphs.get(name)
        layout = rec.layouts.get(engine) if rec else None
        if layout is None:
            pass
        elif engine == "pull":
            drop_device_operands(layout)
        elif engine == "relay":
            # The engine object pins its mask tensors and compiled
            # executables; rebuilding from the host graph is the release
            # path (the RelayGraph host layout would be the thing to keep,
            # but the engine memoizes it internally — drop the whole
            # object and rebuild on next acquire).
            rec.layouts.pop(engine, None)
        # push: the device (src, dst) pair lived only in the resident entry.
        self.evictions += 1
        if self.metrics is not None:
            self.metrics.bump("evictions")
        # HBM-budget thrash was invisible (ISSUE 6 satellite): every
        # eviction now lands a trace marker AND a registry counter, so a
        # serve process churning its device residency shows up in both the
        # Perfetto timeline and the metrics snapshot, not just as slow
        # re-uploads.
        from ..obs import get_registry, instant

        instant("registry.evict", graph=name, engine=engine, bytes=nbytes)
        get_registry().counter("graph_evictions")
        get_registry().counter("graph_evicted_bytes", nbytes)

    def release(self, name: str, engine: str | None = None) -> None:
        """Explicitly evict one graph's device operands (all engines when
        ``engine`` is None).  Host layouts stay memoized."""
        with self._lock:
            for key in [
                k
                for k in self._resident
                if k[0] == name and (engine is None or k[1] == engine)
            ]:
                self._evict(key)

    def resident_bytes(self) -> int:
        with self._lock:  # RLock: also safe from _make_room's hot path
            return sum(b for b, _ in self._resident.values())

    def resident_keys(self) -> list[tuple[str, str]]:
        with self._lock:
            return list(self._resident)
