"""Graph registry: epoch-versioned graphs, budgeted device residency.

The cold-path tax the serving layer exists to amortize is two-fold
(VERDICT round 5: 434 s layout build + ~830 s compile before the first
timed repeat): the HOST layout (ELL packing / dst-sorted edge arrays) and
the DEVICE operand upload.  The registry owns both:

  * host layouts are built once per ``(graph epoch, engine)`` and memoized
    for the epoch's lifetime — they are cheap host RAM; with a
    ``layout_cache`` the build also goes through the persistent on-disk
    bundle store (:mod:`bfs_tpu.cache.layout`), so a SECOND process
    registering the same graph loads the finished layout in seconds
    instead of rebuilding it (ISSUE 2: the 434 s cold relay build);
  * device operands (the multi-GB HBM residents at bench scale) are
    tracked in an LRU keyed ``(name, epoch, engine)`` against an explicit
    byte budget.  Evicting a pull entry calls
    :func:`bfs_tpu.graph.ell.drop_device_operands` — the release hook that
    was dead code until this subsystem — AND drops the registry's own
    reference to the returned ``(ell0, folds)`` tuple, which is what
    actually lets the runtime free the HBM.  The next
    :meth:`GraphRegistry.acquire` re-uploads.

**Epochs (ISSUE 9).**  ``register(name, graph)`` on an existing name no
longer raises — it creates a NEW EPOCH: the current-epoch pointer swaps
atomically, every later admission sees the new snapshot, and the old
epoch's layouts/operands stay alive exactly as long as in-flight work
holds a pin on them.  The contract:

  * :meth:`pin` returns the current :class:`RegisteredGraph` with its
    ref-count bumped; :meth:`unpin` drops it.  The serving layer pins at
    admission and unpins when the reply (or timeout/cancel) lands, so a
    query admitted before a swap is answered against the snapshot it was
    admitted under — hot graph swap without wrong or torn answers.
  * A replaced epoch with pins retires LAZILY: the moment its last pin
    drops, its device operands are evicted and its layouts forgotten
    (``epochs_retired``).  With no pins it retires at swap time.
  * The HBM-budget evictor (:meth:`_make_room`) SKIPS entries whose epoch
    is pinned and counts ``eviction_deferred`` — a graph serving an
    in-flight batch is never evicted mid-tick, so the relay engine (whose
    eviction path drops the whole engine object) cannot be yanked out
    from under a running superstep loop.  The budget may transiently
    overshoot; the next unpinned acquire settles it.

The registry is synchronous and lock-guarded; the serving loop is its only
hot caller, but registration can happen from any thread.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from .. import knobs
from ..analysis.runtime import make_lock
from ..graph.csr import DeviceGraph, Graph, build_device_graph
from ..graph.ell import PullGraph, build_pull_graph, device_ell, drop_device_operands

ENGINES = ("pull", "push", "relay")

#: Knob env keying resident engine operands — DERIVED from the registry
#: (``affects`` contains ``serve``); KNB002 proves membership against
#: bfs_tpu/knobs.py.  A knob flip between acquires (tests flipping
#: BFS_TPU_PACKED, an operator retuning direction thresholds) must never
#: reuse operands resolved under the old flavor — the same stale-key
#: contract the lint caches and the bench journal enforce.
ENGINE_FLAVOR_ENV = knobs.flavor_env("serve")


def _engine_env_fingerprint() -> str:
    """blake2b-6 over the raw serve-affecting knob values — the fourth
    element of the resident-operand LRU key."""
    parts = ";".join(
        f"{n}={knobs.raw(n) or ''}" for n in ENGINE_FLAVOR_ENV
    )
    return hashlib.blake2b(parts.encode(), digest_size=6).hexdigest()


@dataclass
class RegisteredGraph:
    """One registered graph EPOCH: the host graph plus lazily built
    layouts.  ``pins``/``retired`` are guarded by the owning registry's
    lock (this object carries no lock of its own)."""

    name: str
    graph: Graph | None  # host graph; None when registered from a layout
    num_vertices: int = 0
    num_edges: int = 0
    layouts: dict = field(default_factory=dict)  # engine -> layout object
    epoch: int = 0
    pins: int = 0  # in-flight references (registry-lock guarded)
    retired: bool = False  # replaced by a newer epoch (registry-lock guarded)
    #: Resources fully released — ``_retire`` ran, or ``unregister``
    #: force-dropped the record.  Makes release idempotent: a late unpin
    #: after unregister must not re-run ``_retire`` (which would
    #: double-count ``epochs_retired`` and re-fire retire listeners).
    released: bool = False  # registry-lock guarded


def _pull_device_bytes(pg: PullGraph) -> int:
    """HBM bytes :func:`device_ell` will pin for this layout (int32)."""
    return 4 * pg.padded_slots


def _push_device_bytes(dg: DeviceGraph) -> int:
    return 4 * (int(np.asarray(dg.src).size) + int(np.asarray(dg.dst).size))


class GraphRegistry:
    """Named graph epochs + memoized layouts + budgeted device residency.

    ``device_budget_bytes`` caps the summed size of resident device
    operands across all graphs/engines; ``None`` means unlimited (single
    graph, the common case).  The budget never blocks the entry being
    acquired — a single layout larger than the budget is allowed in alone,
    everything else (unpinned) is evicted around it.
    """

    def __init__(
        self,
        *,
        device_budget_bytes: int | None = None,
        metrics=None,
        layout_cache=None,
    ):
        self._lock = make_lock("registry._lock", "rlock")
        self._graphs: dict[str, RegisteredGraph] = {}  # guarded-by: _lock
        # Replaced epochs still pinned by in-flight work, keyed
        # (name, epoch); entries leave when their last pin drops.
        self._retired: dict[tuple[str, int], RegisteredGraph] = {}  # guarded-by: _lock
        # (name, epoch, engine, env fingerprint) -> (bytes,
        # operands-ref); order = LRU.
        self._resident: OrderedDict[
            tuple[str, int, str, str], tuple[int, object]
        ] = OrderedDict()  # guarded-by: _lock
        self.device_budget_bytes = device_budget_bytes  # immutable after init
        self.metrics = metrics  # guarded-by: _lock
        self.evictions = 0  # guarded-by: _lock
        self.evictions_deferred = 0  # guarded-by: _lock
        # Info dict of the most recent relay layout load-or-build (builder
        # flavor, build/load seconds, per-stage timings) for register-time
        # reporting; {} until the first relay layout is built.
        self.last_layout_info: dict = {}  # guarded-by: _lock
        # Persistent layout bundles: a LayoutCache, a directory path, or
        # None (in-process memoization only — the default, so tests and
        # embedders opt in to disk writes explicitly).
        if isinstance(layout_cache, str):
            from ..cache.layout import LayoutCache

            layout_cache = LayoutCache(layout_cache)
        self.layout_cache = layout_cache
        # Retire listeners: each ``fn(name, epoch)`` fires (under the
        # registry lock) once per epoch whose device state is released —
        # at swap time, on the last unpin of a replaced epoch, and for
        # every epoch dropped by :meth:`unregister`.  A LIST, not a slot:
        # multiple servers legitimately share one registry (the same
        # reason ``attach_metrics`` is a guarded handoff), and each points
        # a listener at its own ``ServeHealth.forget_epoch``.  Listeners
        # must never call back into the registry.
        self._retire_listeners: list = []  # guarded-by: _lock
        # Per-name epoch counters that SURVIVE unregister: an in-flight
        # query pinned to the old incarnation's epoch N must never resolve
        # against a re-registered graph that reused N.
        self._next_epoch: dict[str, int] = {}  # guarded-by: _lock

    def add_retire_listener(self, fn) -> None:
        """Subscribe ``fn(name, epoch)`` to epoch retirements (idempotent
        per callable; see the constructor comment for firing semantics)."""
        with self._lock:
            if fn not in self._retire_listeners:
                self._retire_listeners.append(fn)

    def remove_retire_listener(self, fn) -> None:
        """Unsubscribe — a closing server detaches its health hook so a
        shared registry never calls into a dead server."""
        with self._lock:
            if fn in self._retire_listeners:
                self._retire_listeners.remove(fn)

    # ------------------------------------------------------------- graphs --
    def register(
        self,
        name: str,
        graph: Graph | DeviceGraph | PullGraph,
        *,
        engines: tuple[str, ...] = (),
    ) -> RegisteredGraph:
        """Register ``graph`` under ``name``; optionally pre-build layouts.

        Accepts a host :class:`Graph` (all engines available), or a prebuilt
        :class:`PullGraph` / single-shard :class:`DeviceGraph` (that engine
        only; no oracle fallback without the host graph).

        Re-registering an existing name is a HOT SWAP: the new graph
        becomes the next epoch, later admissions see it immediately, and
        in-flight work pinned to the old epoch finishes against the old
        snapshot (whose resources are released when its last pin drops)."""
        if isinstance(graph, PullGraph):
            make = lambda e: RegisteredGraph(  # noqa: E731
                name, None, graph.num_vertices, graph.num_edges,
                {"pull": graph}, epoch=e,
            )
        elif isinstance(graph, DeviceGraph):
            if graph.num_shards != 1:
                raise ValueError("serve registry takes single-shard graphs")
            make = lambda e: RegisteredGraph(  # noqa: E731
                name, None, graph.num_vertices, graph.num_edges,
                {"push": graph}, epoch=e,
            )
        elif isinstance(graph, Graph):
            make = lambda e: RegisteredGraph(  # noqa: E731
                name, graph, graph.num_vertices, graph.num_edges, epoch=e,
            )
        else:
            raise TypeError(f"cannot register {type(graph).__name__}")
        with self._lock:
            old = self._graphs.get(name)
            # Epochs are monotonic per NAME — drawn from a counter that
            # survives unregister, never old.epoch + 1: if numbering
            # restarted at 0 after an unregister/re-register cycle, an
            # in-flight query pinned to the old incarnation's epoch N
            # would silently resolve to the new graph's epoch N and be
            # answered against the wrong snapshot.
            e = self._next_epoch.get(name, 0)
            self._next_epoch[name] = e + 1
            rec = make(e)
            self._graphs[name] = rec
            if old is not None:
                old.retired = True
                if old.pins <= 0:
                    self._retire(old)
                else:
                    self._retired[(name, old.epoch)] = old
                self._bump("epochs_swapped")
                from ..obs import instant

                instant(
                    "registry.swap", graph=name, epoch=rec.epoch,
                    old_epoch=old.epoch, old_pins=old.pins,
                )
        for engine in engines:
            self._layout_for(rec, engine)
        return rec

    def get(self, name: str) -> RegisteredGraph:
        """The CURRENT epoch for ``name``."""
        with self._lock:
            try:
                return self._graphs[name]
            except KeyError:
                raise KeyError(f"graph {name!r} is not registered") from None

    def pin(self, name: str) -> RegisteredGraph:
        """Atomically fetch the current epoch and bump its ref-count.
        The caller MUST balance with :meth:`unpin` (the serving layer pins
        at admission, unpins when the reply lands) — the pin is what keeps
        a swapped-out epoch's snapshot alive for in-flight work."""
        with self._lock:
            rec = self.get(name)
            rec.pins += 1
            return rec

    def unpin(self, rec: RegisteredGraph) -> None:
        """Drop one pin; a retired epoch whose last pin drops releases its
        device operands and layouts here."""
        with self._lock:
            rec.pins -= 1
            if rec.retired and rec.pins <= 0:
                self._retire(rec)

    def get_epoch(self, name: str, epoch: int) -> RegisteredGraph:
        """A SPECIFIC epoch — current or still-pinned retired.  KeyError
        once the epoch is gone (retired with no pins, or unregistered)."""
        with self._lock:
            rec = self._rec_for(name, epoch)
            if rec is None:
                raise KeyError(
                    f"graph {name!r} epoch {epoch} is gone (retired or "
                    "unregistered with no pins outstanding)"
                )
            return rec

    def names(self) -> list[str]:
        with self._lock:
            return list(self._graphs)

    def epoch(self, name: str) -> int:
        """Current epoch number for ``name`` (0 = never swapped)."""
        return self.get(name).epoch

    def unregister(self, name: str) -> None:
        """Drop a graph entirely — every epoch: evict device operands,
        forget layouts.  This is the FORCED path (pins do not defer it;
        in-flight queries on an unregistered graph may fail, which is the
        operator's stated intent — use ``register`` for a safe swap).

        On a :class:`~bfs_tpu.serve.BfsServer`, call ``server.unregister``
        instead — the server also holds compiled executables and result-LRU
        entries keyed by this name that must be invalidated with it."""
        with self._lock:
            for key in [k for k in self._resident if k[0] == name]:
                self._evict(key)
            dropped = []
            rec = self._graphs.pop(name, None)
            if rec is not None:
                dropped.append(rec)
            for k in [k for k in self._retired if k[0] == name]:
                dropped.append(self._retired.pop(k))
            for r in dropped:
                # Mark fully released so a still-in-flight pin's eventual
                # unpin is a no-op — without this, unpin would run _retire
                # a second time (double epochs_retired, double listener
                # fire, and a sweep that could evict a re-registered
                # incarnation's live residents).
                r.retired = True
                r.released = True
                r.layouts.clear()
                for fn in list(self._retire_listeners):
                    fn(name, r.epoch)

    # bfs_tpu: holds _lock
    def _rec_for(self, name: str, epoch: int) -> RegisteredGraph | None:
        rec = self._graphs.get(name)
        if rec is not None and rec.epoch == epoch:
            return rec
        return self._retired.get((name, epoch))

    # bfs_tpu: holds _lock
    def _retire(self, rec: RegisteredGraph) -> None:
        """Release a replaced epoch: evict its resident operands, forget
        its layouts.  Called at swap time (no pins) or from the last
        :meth:`unpin`; idempotent via ``rec.released`` (an unpin landing
        after :meth:`unregister` already dropped the record must not
        release it twice)."""
        if rec.released:
            return
        rec.released = True
        for key in [
            k
            for k in self._resident
            if k[0] == rec.name and k[1] == rec.epoch
        ]:
            self._evict(key, rec)
        self._retired.pop((rec.name, rec.epoch), None)
        rec.layouts.clear()
        self._bump("epochs_retired")
        for fn in list(self._retire_listeners):
            fn(rec.name, rec.epoch)

    # bfs_tpu: holds _lock
    def _bump(self, counter: str, by: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.bump(counter, by)
        from ..obs import get_registry

        get_registry().counter(counter, by)

    # ------------------------------------------------------------ layouts --
    def layout(self, name: str, engine: str):
        """The memoized host layout for the CURRENT epoch of ``name``:
        :class:`PullGraph`, dst-sorted :class:`DeviceGraph`, or a
        :class:`~bfs_tpu.models.bfs.RelayEngine`."""
        return self._layout_for(self.get(name), engine)

    def _layout_for(self, rec: RegisteredGraph, engine: str):
        if engine not in ENGINES:
            raise ValueError(f"unknown engine {engine!r}; use one of {ENGINES}")
        with self._lock:
            layout = rec.layouts.get(engine)
        if layout is not None:
            return layout
        if rec.graph is None:
            raise ValueError(
                f"graph {rec.name!r} was registered as a prebuilt "
                f"{list(rec.layouts)[0]!r} layout; engine {engine!r} needs "
                "the host Graph"
            )
        if engine == "pull":
            layout = self._build_pull(rec.graph)
        elif engine == "push":
            layout = build_device_graph(rec.graph)
        else:  # relay: the engine object IS the layout (it owns its tensors)
            from ..models.bfs import RelayEngine

            layout = RelayEngine(self._build_relay_layout(rec.graph))
        with self._lock:
            # Lost-race double build is possible without holding the lock
            # through the (expensive) build; keep the first one stored.
            layout = rec.layouts.setdefault(engine, layout)
        return layout

    def layout_info(self) -> dict:
        """Snapshot of the most recent relay layout build/load info
        (builder flavor, seconds, per-stage timings); {} before any."""
        with self._lock:
            return dict(self.last_layout_info)

    def attach_metrics(self, metrics) -> None:
        """Adopt a metrics sink unless one is already attached.  The
        lock-guarded form of the ``if registry.metrics is None:
        registry.metrics = ...`` handoff servers used to do bare — two
        servers attaching to one shared registry raced it (LCK001)."""
        with self._lock:
            if self.metrics is None:
                self.metrics = metrics

    def _note_disk(self, info: dict) -> None:
        with self._lock:  # metrics ref is shared; snapshot it under the lock
            metrics = self.metrics
        if metrics is not None and info.get("cache") == "hit":
            metrics.bump("layout_disk_hits")
        elif metrics is not None and info.get("cache") == "miss":
            metrics.bump("layout_disk_misses")

    def _build_pull(self, graph: Graph) -> PullGraph:
        if self.layout_cache is None:
            return build_pull_graph(graph)
        from ..cache.layout import load_or_build_pull

        pg, info = load_or_build_pull(graph, cache=self.layout_cache)
        self._note_disk(info)
        return pg

    def _build_relay_layout(self, graph: Graph):
        """The RelayEngine constructor arg: the disk-cached RelayGraph when
        a layout cache is configured, else the host graph (the engine
        builds the layout itself).  The build info (builder flavor,
        build/load seconds, per-stage timings) is kept in
        ``last_layout_info`` so register-time surfaces (`bfs-tpu-serve`,
        the load generator) can print what graph registration cost."""
        if self.layout_cache is None:
            return graph
        from ..cache.layout import load_or_build_relay

        rg, info = load_or_build_relay(graph, cache=self.layout_cache)
        self._note_disk(info)
        with self._lock:
            self.last_layout_info = dict(info)
        return rg

    # ---------------------------------------------------------- residency --
    def acquire(self, name: str, engine: str):
        """Device operands for the CURRENT epoch of ``(graph, engine)``."""
        return self.acquire_for(self.get(name), engine)

    def acquire_epoch(self, name: str, epoch: int, engine: str):
        """Device operands for a SPECIFIC epoch — the form batch runners
        bound to a pinned snapshot use, so a tick formed before a swap
        executes against its admission-time graph."""
        return self.acquire_for(self.get_epoch(name, epoch), engine)

    def acquire_for(self, rec: RegisteredGraph, engine: str):
        """Device operands for one epoch, uploading within budget.

        Returns the operand handle the executor passes to the compiled
        program: ``(ell0, folds)`` for pull, ``(src, dst)`` device arrays
        for push, the :class:`RelayEngine` itself for relay.  Marks the
        entry most-recently-used and evicts LRU entries (via
        :func:`drop_device_operands` for pull) until the budget holds —
        skipping entries whose epoch is pinned by in-flight work."""
        import jax.numpy as jnp

        layout = self._layout_for(rec, engine)
        key = (rec.name, rec.epoch, engine, _engine_env_fingerprint())
        with self._lock:
            if key in self._resident:
                self._resident.move_to_end(key)
                # A residency hit still settles any deferred-eviction
                # overshoot: _make_room with 0 incoming evicts unpinned
                # LRU entries until the budget holds again.
                self._make_room(0, keep=key)
                return self._resident[key][1]
            if engine == "pull":
                nbytes = _pull_device_bytes(layout)
            elif engine == "push":
                nbytes = _push_device_bytes(layout)
            else:
                rg = layout.relay_graph
                nbytes = int(rg.vperm_masks.nbytes + rg.net_masks.nbytes)
            # Make room BEFORE the out-of-lock upload: evicting victims
            # only after the new operands are resident would peak HBM at
            # budget + incoming — the overshoot the budget exists to
            # prevent.  A concurrent acquire racing this window can still
            # transiently overshoot; the hit-path settle reclaims it.
            self._make_room(nbytes, keep=key)
        # The H2D upload runs OUTSIDE the lock: the serve watchdog abandons
        # a wedged device call wherever it stands, and an abandoned worker
        # that died holding this lock would freeze every pin/report/
        # register on every graph — the exact whole-server wedge the
        # watchdog exists to prevent.  A concurrent duplicate upload is
        # harmless (keep-first below; device_ell memoizes on the layout).
        if engine == "pull":
            operands = device_ell(layout)
        elif engine == "push":
            operands = (jnp.asarray(layout.src), jnp.asarray(layout.dst))
        else:
            operands = layout  # tensors uploaded at engine init
        with self._lock:
            if key in self._resident:  # lost an upload race: keep first
                self._resident.move_to_end(key)
                return self._resident[key][1]
            if rec.released:
                # The epoch was released while we uploaded outside the
                # lock (a watchdog-abandoned tick's last unpin ran
                # _retire, or an unregister force-dropped the record):
                # its resident keys are already evicted and the release
                # will never run again — caching now would leak the dead
                # snapshot's device arrays for the registry's lifetime.
                # Hand the operands to this (only) caller without
                # inserting.
                return operands
            # Room was made before the upload; re-running _make_room here
            # would double-count a deferral for this one acquire.
            self._resident[key] = (nbytes, operands)
            return operands

    # bfs_tpu: holds _lock
    def _pinned(self, key: tuple[str, int, str, str]) -> bool:
        rec = self._rec_for(key[0], key[1])
        return rec is not None and rec.pins > 0

    # bfs_tpu: holds _lock
    def _make_room(self, incoming: int, *, keep) -> None:
        if self.device_budget_bytes is None:
            return
        while (
            self._resident
            and self.resident_bytes() + incoming > self.device_budget_bytes
        ):
            victim = next(
                (
                    k
                    for k in self._resident
                    if k != keep and not self._pinned(k)
                ),
                None,
            )
            if victim is None:
                if not any(k != keep for k in self._resident):
                    # ``keep`` alone exceeds the budget: that is the
                    # documented single-oversized-layout allowance, not a
                    # deferral — counting it would bump eviction_deferred
                    # on EVERY tick of a supported steady state.
                    return
                # Every other entry is serving an in-flight batch: a
                # mid-tick eviction would yank the relay engine (or churn
                # pull/push re-uploads) out from under running work.
                # Defer — transient budget overshoot, settled by the next
                # unpinned acquire — and make the deferral visible.  Only
                # an actual upload (incoming > 0) counts: the hit-path
                # settle probes with 0 on every tick, and counting those
                # would tick the event counter (and flood the trace with
                # markers) at tick rate for as long as the pins persist.
                if incoming > 0:
                    self.evictions_deferred += 1
                    self._bump("eviction_deferred")
                    from ..obs import instant

                    instant(
                        "registry.evict_deferred",
                        graph=keep[0], engine=keep[2], bytes=incoming,
                    )
                return
            self._evict(victim)

    # bfs_tpu: holds _lock
    def _evict(self, key: tuple[str, int, str, str], rec=None) -> None:
        name, epoch, engine = key[0], key[1], key[2]
        nbytes = self._resident[key][0]
        self._resident.pop(key)  # drops OUR reference to the operands
        # ``rec`` comes from _retire's swap-time path: an unpinned old
        # epoch is already out of _graphs (the new rec replaced it) and
        # never entered _retired, so _rec_for can't see it — without the
        # explicit rec the release hooks below silently skip and an
        # externally-held layout keeps its device memo alive.
        if rec is None:
            rec = self._rec_for(name, epoch)
        layout = rec.layouts.get(engine) if rec else None
        if layout is None:
            pass
        elif engine == "pull":
            drop_device_operands(layout)
        elif engine == "relay":
            # The engine object pins its mask tensors and compiled
            # executables; rebuilding from the host graph is the release
            # path (the RelayGraph host layout would be the thing to keep,
            # but the engine memoizes it internally — drop the whole
            # object and rebuild on next acquire).
            rec.layouts.pop(engine, None)
        # push: the device (src, dst) pair lived only in the resident entry.
        self.evictions += 1
        if self.metrics is not None:
            self.metrics.bump("evictions")
        # HBM-budget thrash was invisible (ISSUE 6 satellite): every
        # eviction now lands a trace marker AND a registry counter, so a
        # serve process churning its device residency shows up in both the
        # Perfetto timeline and the metrics snapshot, not just as slow
        # re-uploads.
        from ..obs import get_registry, instant

        instant("registry.evict", graph=name, engine=engine, bytes=nbytes)
        get_registry().counter("graph_evictions")
        get_registry().counter("graph_evicted_bytes", nbytes)

    def release(self, name: str, engine: str | None = None) -> None:
        """Explicitly evict one graph's device operands across all epochs
        (all engines when ``engine`` is None).  Host layouts stay
        memoized.  Explicit = forced: pins do not defer this path."""
        with self._lock:
            for key in [
                k
                for k in self._resident
                if k[0] == name and (engine is None or k[2] == engine)
            ]:
                self._evict(key)

    def resident_bytes(self) -> int:
        with self._lock:  # RLock: also safe from _make_room's hot path
            return sum(b for b, _ in self._resident.values())

    def resident_keys(self) -> list[tuple[str, int, str]]:
        """Resident operand identities as (name, epoch, engine), in LRU
        order.  The internal map key additionally carries the engine-env
        fingerprint (:func:`_engine_env_fingerprint`) so a knob-flavor
        change can never reuse a stale engine — but that is a cache-
        correctness detail, not part of the observable identity (the
        same triple may appear once per resident env flavor)."""
        with self._lock:
            return [(k[0], k[1], k[2]) for k in self._resident]
