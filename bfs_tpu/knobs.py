"""The typed env-knob registry: every ``BFS_TPU_*`` name the framework
reads, in one table, with a parser, a default, a doc line and — the part
the linter proves — an ``affects`` set naming which content-addressed
cache keys and journal config keys the knob must participate in.

Motivation (ISSUE 19): the framework's behavior is steered by ~50 env
knobs read across ~25 modules, but the flavor-env tuples keying the
IR/HLO/Pallas lint caches, the probe-verdict key, the bench run-journal
config and the serve resident-engine key were each a hand-maintained
list.  PR 15 shipped (and hot-fixed) exactly the resulting bug class: a
warm cache hit replayed under a knob value it was never keyed on.  This
module makes the key membership a DECLARED property of each knob; the
consumers derive their tuples from it (:func:`flavor_env`), and the
fifth analyzer rung (:mod:`bfs_tpu.analysis.knobs`, ``bfs-tpu-lint
--knobs``) proves registry <-> read sites and registry <-> key builders
stay in sync both ways.

Accessors:

* :func:`get` — the typed read: unset/empty falls back to the registered
  default, anything else goes through the knob's parser, and a bad value
  raises :class:`KnobError` NAMING the knob — a typo'd knob must never
  silently change what a capture measured (the resolve_direction
  contract, applied uniformly).
* :func:`raw` — the unparsed read (``os.environ.get``, None when unset)
  for the path knobs where unset-vs-explicitly-empty differ
  (``BFS_TPU_EXE_CACHE=""`` means *disabled*, unset means *default
  dir*) and for key builders that hash raw strings.

``affects`` domains (each a derived tuple somewhere — KNB002 verifies):

* ``ir`` / ``hlo`` / ``pal`` — the analysis result caches
  (``analysis/ir.py`` ``_FLAVOR_ENV``, ``analysis/hlo.py``
  ``_HLO_FLAVOR_ENV``, ``analysis/pallas.py`` ``_PAL_FLAVOR_ENV``).
* ``probe`` — the probe-verdict key (``cache/layout.py`` ``_PROBE_ENV``).
* ``journal`` — the bench :class:`RunJournal` config
  (``resilience/journal.py`` ``ENV_CONFIG_KEYS`` via ``journal_key``).
* ``serve`` — the serve registry's resident-engine key
  (``serve/registry.py`` ``ENGINE_FLAVOR_ENV``).

This module is PURE STDLIB and imports nothing from ``bfs_tpu`` — it is
imported by ops/, graph/, utils/ and the analysis package, so it must
never pull jax (or anything heavy) into an importer.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field

_INT32_MAX = 2**31 - 1


class KnobError(ValueError):
    """A ``BFS_TPU_*`` env value its registered parser rejects.  The
    message always names the knob (KNB005 pins this)."""

    def __init__(self, name: str, raw: str, why: str):
        self.knob = name
        super().__init__(f"{name}={raw!r}: {why}")


# --------------------------------------------------------------- parsers --
# Each parser maps a non-empty raw string to the knob's typed value and
# raises ValueError (wrapped into KnobError by parse_value) on anything
# outside the knob's documented domain.  Loose legacy spellings ("any
# non-0 means on") are deliberately tightened to the documented set.

def _enum(*choices):
    def parse(raw: str):
        if raw not in choices:
            raise ValueError(f"use one of {' | '.join(choices)}")
        return raw
    return parse


def _flag(true_values=("1",), false_values=("0",)):
    """Strict boolean: returns True/False, rejects everything else."""
    def parse(raw: str):
        if raw in true_values:
            return True
        if raw in false_values:
            return False
        allowed = " | ".join((*false_values, *true_values))
        raise ValueError(f"use one of {allowed}")
    return parse


def _int(minimum=None):
    def parse(raw: str):
        v = int(raw)
        if minimum is not None and v < minimum:
            raise ValueError(f"must be >= {minimum} (got {v})")
        return v
    return parse


def _float(minimum=None, exclusive=True):
    def parse(raw: str):
        v = float(raw)
        if minimum is not None and (v <= minimum if exclusive else v < minimum):
            op = ">" if exclusive else ">="
            raise ValueError(f"must be {op} {minimum} (got {v})")
        return v
    return parse


def _parse_tristate(raw: str):
    """'' = auto (resolved by capability/fit), '0' = forced off,
    '1' = forced on."""
    if raw not in ("", "0", "1"):
        raise ValueError("use '' (auto) | 0 | 1")
    return raw


def _parse_delta(raw: str):
    """Delta-stepping bucket width: int (non-positive means one bucket),
    or inf/infinite/single for plain frontier Bellman-Ford."""
    if raw.lower() in ("inf", "infinite", "single"):
        return _INT32_MAX
    v = int(raw)
    if v <= 0:
        return _INT32_MAX
    return min(v, _INT32_MAX)


def _parse_mesh(raw: str):
    """'rxc' (or a bare integer c, meaning 1xc) -> the raw spec,
    validated; '' = the 1D degenerate 1 x num_devices."""
    if raw == "":
        return ""
    s = raw.strip().lower()
    if "x" in s:
        rs, _, cs = s.partition("x")
        r, c = int(rs), int(cs)
    else:
        r, c = 1, int(s)
    if r < 1 or c < 1:
        raise ValueError("both mesh axes must be >= 1")
    return raw


def _parse_ckpt(raw: str):
    """off | every[:k] | auto — the resolve_ckpt grammar; the full
    CkptConfig construction stays in resilience/superstep_ckpt.py."""
    mode, _, arg = raw.strip().partition(":")
    if mode not in ("off", "every", "auto"):
        raise ValueError("use off | every:<k> | auto")
    if mode == "every":
        if arg and int(arg) < 1:
            raise ValueError("every:<k> needs k >= 1")
    elif arg:
        raise ValueError("only 'every' takes an argument")
    return raw.strip()


def _parse_labels(raw: str):
    """off | <K> — landmark distance-label count; 'off' (or 0) parses to
    0 = no label tier, any positive int is the landmark budget K."""
    s = raw.strip().lower()
    if s in ("off", "0"):
        return 0
    v = int(s)
    if v < 1:
        raise ValueError("use off | <K> with K >= 1")
    return v


def _parse_fault(raw: str):
    """kill:<phase>[:nth] | raise:<phase>[:nth] | phase:<phase>[:nth] |
    delay:<phase>[:seconds]; '' = no fault.  Full parsing (nth/seconds
    disambiguation) stays in resilience/faults.py."""
    if raw.strip() == "":
        return ""
    action, _, rest = raw.strip().partition(":")
    if action == "phase":
        action = "kill"
    if action not in ("kill", "raise", "delay") or not rest:
        raise ValueError(
            "use kill:<phase>[:nth] | raise:<phase>[:nth] | "
            "phase:<phase>[:nth] | delay:<phase>[:seconds]"
        )
    return raw.strip()


def _parse_log_level(raw: str):
    """A stdlib logging level name or a numeric level."""
    up = raw.strip().upper()
    if up in ("DEBUG", "INFO", "WARNING", "WARN", "ERROR",
              "CRITICAL", "FATAL", "NOTSET"):
        return up
    if up.isdigit():
        return int(up)
    raise ValueError("use a logging level name (DEBUG/INFO/...) or number")


def _parse_transfer_guard(raw: str):
    """'' /0/off/false/allow = off (None); 1/on/true/disallow =
    'disallow'; any explicit jax guard level name passes through
    (``disallow_explicit`` for paranoia runs)."""
    s = raw.strip().lower()
    if s in ("", "0", "off", "false", "allow"):
        return None
    if s in ("1", "on", "true", "disallow"):
        return "disallow"
    if re.fullmatch(r"[a-z_]+", s):
        return s
    raise ValueError("use 0/off | 1/disallow | log | a jax guard level name")


def _parse_lock_order(raw: str):
    """'' /0/off/false = off (None); raise = raise at the violating
    acquisition; 1/on/true/record = record only."""
    s = raw.strip().lower()
    if s in ("", "0", "off", "false"):
        return None
    if s == "raise":
        return "raise"
    if s in ("1", "on", "true", "record"):
        return "record"
    raise ValueError("use 0/off | 1/record | raise")


def _parse_str(raw: str):
    return raw


# -------------------------------------------------------------- registry --

@dataclass(frozen=True)
class Knob:
    """One registered env knob.

    ``default`` is the RAW string substituted when the env var is unset
    or empty, then parsed like any explicit value — so the default is
    provably inside the parser's domain (KNB005).  ``canary`` is a raw
    value the parser must REJECT (None only for freeform ``str``/``path``
    knobs, which accept everything).  ``scope`` is ``'call'`` (read at
    call/resolve time — may change between runs in one process) or
    ``'import'`` (baked into module constants at import; KNB003 allows a
    module-level read only for these).  ``journal_key`` names the knob's
    field in the bench RunJournal config (required iff ``'journal'`` in
    ``affects``)."""

    name: str
    kind: str  # enum | flag | tristate | int | float | spec | str | path
    default: str
    parse: callable
    doc: str
    affects: frozenset = frozenset()
    scope: str = "call"
    canary: str | None = None
    journal_key: str | None = None


def _k(name, kind, default, parse, doc, *, affects=(), scope="call",
       canary=None, journal_key=None) -> Knob:
    return Knob(
        name=name, kind=kind, default=default, parse=parse, doc=doc,
        affects=frozenset(affects), scope=scope, canary=canary,
        journal_key=journal_key,
    )


#: The flavor domains: every knob that changes which traced-program
#: flavors get built must key all three lint caches — the jaxpr pass, the
#: compiled-HLO pass and the Pallas kernel pass all analyze the flavor
#: the env selects.
_FLAVOR = ("ir", "hlo", "pal")

KNOBS: dict[str, Knob] = {k.name: k for k in (
    # -- traversal arm selection ------------------------------------------
    _k("BFS_TPU_DIRECTION", "enum", "auto", _enum("push", "pull", "auto"),
       "traversal body: force push or pull, or switch per superstep on "
       "the alpha/beta thresholds",
       affects=(*_FLAVOR, "journal", "serve"), canary="sideways",
       journal_key="direction"),
    _k("BFS_TPU_DIRECTION_ALPHA", "float", "14.0", _float(0.0),
       "direction switch: enter pull when frontier out-edge mass * alpha "
       "exceeds unexplored mass",
       affects=(*_FLAVOR, "journal", "serve"), canary="fast",
       journal_key="direction_alpha"),
    _k("BFS_TPU_DIRECTION_BETA", "float", "24.0", _float(0.0),
       "direction switch: stay in pull while frontier occupancy * beta "
       "exceeds n",
       affects=(*_FLAVOR, "journal", "serve"), canary="-1",
       journal_key="direction_beta"),
    _k("BFS_TPU_PACKED", "tristate", "", _parse_tristate,
       "packed level:6|parent:26 state words: '' = auto by fit, 0/1 "
       "force",
       affects=(*_FLAVOR, "journal", "serve"), canary="2",
       journal_key="packed"),
    _k("BFS_TPU_PALLAS", "tristate", "", _parse_tristate,
       "hand-written Pallas kernels: '' = auto by backend, 0/1 force",
       affects=(*_FLAVOR, "serve"), canary="2"),
    _k("BFS_TPU_ROWMIN", "enum", "auto", _enum("auto", "pallas", "xla"),
       "packed row-min kernel arm; auto = measured per phase at engine "
       "init on TPU",
       affects=(*_FLAVOR, "journal", "serve"), canary="cuda",
       journal_key="rowmin_kernel"),
    _k("BFS_TPU_STATE_UPDATE", "enum", "auto", _enum("auto", "pallas", "xla"),
       "packed state-update kernel arm; same selection contract as "
       "ROWMIN",
       affects=(*_FLAVOR, "journal", "serve"), canary="cuda",
       journal_key="state_update_kernel"),
    _k("BFS_TPU_EXPANSION", "enum", "auto", _enum("auto", "gather", "mxu"),
       "dense-frontier expansion arm: Benes relay gather or "
       "BFS-as-masked-matmul on the MXU",
       affects=(*_FLAVOR, "journal", "serve"), canary="dense",
       journal_key="expansion"),
    _k("BFS_TPU_MXU_KERNEL", "enum", "auto", _enum("auto", "pallas", "xla"),
       "mxu expansion arm implementation: fused Pallas kernel or its "
       "bit-identical XLA twin",
       affects=(*_FLAVOR, "probe", "journal", "serve"), canary="mosaic",
       journal_key="mxu_kernel"),
    _k("BFS_TPU_MXU_TILE_GB", "float", "4", _float(0.0),
       "adjacency-tile storage budget; an over-budget graph rejects "
       "forced mxu and auto falls back to gather",
       affects=_FLAVOR, canary="huge"),
    _k("BFS_TPU_TILES", "enum", "resident", _enum("resident", "stream", "auto"),
       "where the mxu arm's adjacency tiles live: device-resident, "
       "host-streamed superblocks, or auto by fit",
       affects=(*_FLAVOR, "journal", "serve"), canary="hbm",
       journal_key="tiles"),
    _k("BFS_TPU_TILES_BUILD", "enum", "device", _enum("device", "host"),
       "adjacency-tile builder arm; host is the pinned oracle, "
       "bit-identical",
       affects=_FLAVOR, canary="gpu"),
    _k("BFS_TPU_STREAM_CACHE_GB", "float", "1", _float(0.0),
       "streamed-tiles HBM superblock cache budget (LRU, single "
       "oversized allowance)",
       affects=(*_FLAVOR, "journal", "serve"), canary="big",
       journal_key="stream_cache_gb"),
    _k("BFS_TPU_STREAM_VERIFY", "flag", "0", _flag(),
       "re-fingerprint streamed superblocks on every cache hit; corrupt "
       "entries are dropped and re-fetched",
       affects=_FLAVOR, canary="yes"),
    _k("BFS_TPU_SSSP_DELTA", "spec", "64", _parse_delta,
       "delta-stepping bucket width (int, or inf/single for plain "
       "frontier Bellman-Ford); non-positive = one bucket",
       affects=(*_FLAVOR, "journal", "serve"), canary="wide",
       journal_key="sssp_delta"),
    _k("BFS_TPU_CKPT", "spec", "off", _parse_ckpt,
       "superstep checkpointing: off | every:<k> | auto (Young/Daly "
       "interval) — selects fused vs segmented programs",
       affects=_FLAVOR, canary="sometimes"),
    # -- serve label oracle / fleet router --------------------------------
    _k("BFS_TPU_LABELS", "spec", "off", _parse_labels,
       "landmark distance-label oracle tier: off | <K> landmark roots "
       "precomputed at serve register() time; point queries answer from "
       "labels when the tightness certificate holds",
       affects=("journal", "serve"), canary="many",
       journal_key="labels"),
    _k("BFS_TPU_LABELS_GB", "float", "2", _float(0.0),
       "device budget for the resident label index (uint16[K,V]); an "
       "over-budget index serves exact-only",
       canary="big"),
    _k("BFS_TPU_LABELS_VERIFY", "int", "0", _int(0),
       "sample-verify every Nth tight label answer against the exact "
       "traversal; a mismatch quarantines the index (0 = off)",
       canary="-1"),
    _k("BFS_TPU_ROUTER_FAILURES", "int", "2", _int(1),
       "fleet router per-replica breaker: consecutive submit failures "
       "before the replica is routed around",
       canary="0"),
    _k("BFS_TPU_ROUTER_COOLDOWN_S", "float", "2.0", _float(0.0),
       "fleet router breaker cooldown before an opened replica is "
       "retried",
       canary="slow"),
    # -- sharded exchange / mesh ------------------------------------------
    _k("BFS_TPU_EXCHANGE", "enum", "auto", _enum("auto", "bitmap", "delta", "flat"),
       "sharded frontier exchange arm: sieved bitmaps, word-list deltas "
       "on sparse levels, or the flat oracle",
       affects=(*_FLAVOR, "journal"), canary="zip",
       journal_key="exchange"),
    _k("BFS_TPU_EXCHANGE_DIV", "int", "8", _int(1),
       "exchange word-list budget divisor B = ceil(kw/div); larger cuts "
       "deeper but engages on sparser levels only",
       affects=(*_FLAVOR, "journal"), canary="0",
       journal_key="exchange_div"),
    _k("BFS_TPU_MESH", "spec", "", _parse_mesh,
       "2D tile-grid mesh shape 'rxc' (bare c = 1xc); unset = the 1D "
       "degenerate 1 x num_devices",
       affects=_FLAVOR, canary="3by2"),
    # -- kernel geometry (baked into module constants at import) ----------
    _k("BFS_TPU_TM", "flag", "1", _flag(),
       "tile-major (transposed) relay kernel layout; 0 = row-major "
       "legacy layout",
       affects=("pal",), scope="import", canary="2"),
    _k("BFS_TPU_LANE_COMPACT", "flag", "0", _flag(),
       "lane-compacted relay kernel variant (disables tile-major when "
       "set)",
       affects=_FLAVOR, canary="2"),
    _k("BFS_TPU_TILE_ROWS", "int", "2048", _int(1),
       "relay kernel rows per grid tile",
       affects=("pal",), scope="import", canary="8k"),
    _k("BFS_TPU_OUTER_TT", "int", "64", _int(1),
       "relay kernel outer tile repeat factor",
       affects=("pal",), scope="import", canary="fast"),
    _k("BFS_TPU_DMA_DEPTH", "int", "2", _int(1),
       "relay kernel manual-DMA pipeline depth (clamped to >= 2 at the "
       "read site)",
       affects=("pal",), scope="import", canary="deep"),
    _k("BFS_TPU_GUARDS", "flag", "1", _flag(),
       "bounds-guard predicates inside the relay kernels; 0 only for "
       "kernel micro-benchmarks",
       affects=("pal",), scope="import", canary="2"),
    _k("BFS_TPU_PAL_VMEM_MB", "float", "16", _float(0.0),
       "per-core VMEM budget the Pallas lint proves residency against "
       "and the probe keys on",
       affects=("pal", "probe"), canary="lots"),
    _k("BFS_TPU_PULL_CHUNK_MB", "float", "128", _float(0.0),
       "pull-arm gather chunk size (module constant)",
       affects=_FLAVOR, scope="import", canary="chunky"),
    # -- probe / selection control ----------------------------------------
    _k("BFS_TPU_PROBE_BUDGET", "float", "600", _float(0.0),
       "phase-probe wall-clock budget in seconds before coarse mode",
       canary="lots"),
    _k("BFS_TPU_PROBE_COARSE", "flag", "0", _flag(),
       "force the coarse (cheap) phase probe",
       canary="yes"),
    _k("BFS_TPU_PHASE_PROBE", "enum", "", _enum("", "force"),
       "force the per-phase kernel probe even off-TPU",
       canary="maybe"),
    # -- layout-build arms (byte-identical outputs; deliberately NOT in
    # any cache key — the bundle content hash covers them) -----------------
    _k("BFS_TPU_LAYOUT_BUILD", "enum", "device", _enum("device", "host"),
       "layout-bundle builder arm; host is the pinned oracle, "
       "bit-identical",
       canary="tpu"),
    _k("BFS_TPU_LAYOUT_SEGMENTS", "enum", "auto", _enum("auto", "xla", "host"),
       "relay segment-build arm inside the layout builder",
       canary="gpu"),
    _k("BFS_TPU_LAYOUT_ROUTE", "enum", "auto", _enum("auto", "native", "jax"),
       "Benes route computation arm: native extension or pure-JAX",
       canary="numpy"),
    _k("BFS_TPU_HUGEPAGES", "flag", "1", _flag(),
       "try transparent-hugepage advice for the pinned host tile store",
       canary="yes"),
    # -- cache / journal plumbing (paths and switches; never part of a
    # content key — they select WHERE artifacts live, not what they are) --
    _k("BFS_TPU_CACHE_DIR", "path", "", _parse_str,
       "root directory for all persistent artifact caches (default "
       "<repo>/.bench_cache)"),
    _k("BFS_TPU_JOURNAL_DIR", "path", "", _parse_str,
       "run-journal directory (default <cache root>/journal)"),
    _k("BFS_TPU_EXE_CACHE", "path", "", _parse_str,
       "serialized-executable cache dir; explicitly empty = disabled, "
       "unset = <cache root>/exe"),
    _k("BFS_TPU_IR_CACHE", "path", "", _parse_str,
       "IR-lint result cache dir (default <repo>/.bench_cache/ir)"),
    _k("BFS_TPU_HLO_CACHE", "path", "", _parse_str,
       "HLO-lint result cache dir (default <repo>/.bench_cache/hlo)"),
    _k("BFS_TPU_PAL_CACHE", "path", "", _parse_str,
       "Pallas-lint result cache dir (default <repo>/.bench_cache/pal)"),
    _k("BFS_TPU_KNB_CACHE", "path", "", _parse_str,
       "knob-lint result cache dir (default <repo>/.bench_cache/knb)"),
    _k("BFS_TPU_TILES_CACHE", "flag", "0", _flag(),
       "persist built adjacency-tile bundles in the layout store "
       "sidecar",
       canary="yes"),
    _k("BFS_TPU_JOURNAL", "flag", "1", _flag(),
       "bench run journal (crash-resume medians); 0 disables",
       canary="off"),
    # -- observability / debugging ----------------------------------------
    _k("BFS_TPU_LOG", "spec", "INFO", _parse_log_level,
       "stdlib logging level for the project loggers",
       canary="CHATTY"),
    _k("BFS_TPU_SPANS", "flag", "1", _flag(),
       "phase-span telemetry ledger; 0 disables",
       canary="yes"),
    _k("BFS_TPU_BUILD_LOG", "flag", "0", _flag(),
       "per-build layout/relay build-step logging (bench turns it on)",
       canary="verbose"),
    _k("BFS_TPU_TRANSFER_GUARD", "spec", "", _parse_transfer_guard,
       "jax transfer guard over the hot regions: 0/off | 1/disallow | "
       "log | any explicit jax level",
       canary="never ever"),
    _k("BFS_TPU_LOCK_ORDER", "spec", "", _parse_lock_order,
       "lock-order recorder on the serve locks: 0/off | 1/record | "
       "raise",
       canary="maybe"),
    # -- fault injection / resilience -------------------------------------
    _k("BFS_TPU_FAULT", "spec", "", _parse_fault,
       "fault injection: kill|raise|phase:<phase>[:nth] | "
       "delay:<phase>[:seconds]",
       canary="explode"),
    _k("BFS_TPU_CKPT_MTBF_S", "float", "600.0", _float(0.0),
       "mean-time-between-failures prior for the auto checkpoint "
       "interval",
       canary="-3"),
    # -- analysis-pass budgets --------------------------------------------
    _k("BFS_TPU_IR_HBM_GB", "float", "16", _float(0.0),
       "per-device HBM budget the IR/HLO lint proves footprints against",
       affects=_FLAVOR, canary="lots"),
)}


# -------------------------------------------------------------- accessors --

def parse_value(name: str, raw: str):
    """Parse ``raw`` as knob ``name``; raises :class:`KnobError` (naming
    the knob) on an unregistered name or a value outside the domain."""
    k = KNOBS.get(name)
    if k is None:
        raise KnobError(name, raw, "not a registered knob (bfs_tpu/knobs.py)")
    try:
        return k.parse(raw)
    except KnobError:
        raise
    except (ValueError, TypeError) as exc:
        raise KnobError(name, raw, str(exc) or "invalid value") from exc


def get(name: str):
    """The typed read: unset/empty -> the registered default, else the
    parsed env value; a bad value raises :class:`KnobError`."""
    k = KNOBS.get(name)
    if k is None:
        raise KnobError(name, "", "not a registered knob (bfs_tpu/knobs.py)")
    value = os.environ.get(name)
    if value is None or value == "":
        value = k.default
    return parse_value(name, value)


def raw(name: str) -> str | None:
    """The unparsed read (``None`` when unset) — for path knobs where
    unset and explicitly-empty mean different things, and for key
    builders that hash raw strings.  The name must still be registered."""
    if name not in KNOBS:
        raise KnobError(name, "", "not a registered knob (bfs_tpu/knobs.py)")
    return os.environ.get(name)


def flavor_env(domain: str) -> tuple:
    """Sorted tuple of knob names declaring ``domain`` in ``affects`` —
    the derived replacement for every hand-maintained flavor list."""
    return tuple(sorted(
        k.name for k in KNOBS.values() if domain in k.affects
    ))


def journal_map() -> dict:
    """``{journal config key: knob name}`` for the journal-affecting
    knobs (sorted by config key)."""
    pairs = sorted(
        (k.journal_key, k.name)
        for k in KNOBS.values() if "journal" in k.affects
    )
    return dict(pairs)
