"""Beneš routing networks: compile a static permutation to butterfly masks.

The relay engine (see :mod:`bfs_tpu.graph.relay`) moves per-edge frontier
bits from src-grouped to dst-grouped order every superstep.  That move is a
fixed permutation, so it is compiled ONCE into a Beneš network — 2·log2(N)-1
stages of conditional pair swaps — whose control masks are computed by the
native router (native/benes.cpp) and applied on device as pure elementwise
ops over bit-packed int32 words (:func:`bfs_tpu.ops.relay.apply_benes`).

Conventions shared with the C++ router and the XLA applier:
  * stage ``s`` of a size-``N=2^k`` network has pair distance
    ``N >> (s+1)`` for ``s < k`` and ``N >> (2k-1-s)`` after;
  * a stage swaps ``x[i] <-> x[i+d]`` iff mask bit ``i`` is set, mask bits
    stored only at the lower index of each pair;
  * bits pack little-endian into uint32 words;
  * the network computes ``y[j] = x[perm[j]]``.
"""

from __future__ import annotations

import atexit
import contextlib
import ctypes
import os
import signal
import threading

import numpy as np

from .. import knobs
from ..utils.native_loader import NativeLib

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _register(lib: ctypes.CDLL) -> None:
    lib.benes_route.restype = ctypes.c_int32
    lib.benes_route.argtypes = [
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS"),
        ctypes.c_int32,
    ]
    lib.benes_route_i32.restype = ctypes.c_int32
    lib.benes_route_i32.argtypes = [
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS"),
    ]
    lib.benes_route_i32_v2.restype = ctypes.c_int32
    lib.benes_route_i32_v2.argtypes = [
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS"),
        ctypes.c_int32,
    ]


_LIB = NativeLib(
    src=os.path.join(_REPO_ROOT, "native", "benes.cpp"),
    so=os.path.join(_REPO_ROOT, "native", "build", "libbenes.so"),
    register=_register,
)


def native_available() -> bool:
    return _LIB.available()


def num_stages(n: int) -> int:
    return 2 * (int(n).bit_length() - 1) - 1


def stage_distance(n: int, s: int) -> int:
    k = int(n).bit_length() - 1
    return n >> (s + 1) if s < k else n >> (2 * k - 1 - s)


def route(perm: np.ndarray, *, bit_major: bool = False) -> np.ndarray:
    """Compute Beneš masks for ``perm`` (``y[j] = x[perm[j]]``).

    ``len(perm)`` must be a power of two >= 2.  Returns
    ``uint32[num_stages, n/32]`` packed masks (``n//32`` >= 1).
    ``bit_major`` packs mask element e at (word ``e % nw``, bit ``e // nw``)
    — the layout :func:`bfs_tpu.ops.relay.apply_benes` consumes; the default
    word-major layout matches :func:`apply_network_numpy`'s default.
    """
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native benes router unavailable")
    perm = np.ascontiguousarray(perm, dtype=np.int64)
    n = int(perm.shape[0])
    if n < 2 or n & (n - 1):
        raise ValueError(f"network size {n} is not a power of two >= 2")
    words = max(n // 32, 1)
    masks = np.zeros(num_stages(n) * words, dtype=np.uint32)
    if lib.benes_route(n, perm, masks, int(bit_major)) != 0:
        raise ValueError("perm is not a bijection")
    return masks.reshape(num_stages(n), words)


_NR_HUGEPAGES = "/proc/sys/vm/nr_hugepages"


def _reserve_hugepages(n: int) -> int | None:
    """Best-effort explicit 2MB huge-page reservation for the native
    router's working set (a/b/inv = 20 bytes/slot; native/benes.cpp
    ``HugeBuf`` prefers ``mmap(MAP_HUGETLB)``).  The build VM's kernel
    grants ZERO transparent huge pages in madvise mode (verified via
    smaps_rollup), so without an explicit pool the route's pointer chase
    pays a 4KB-page walk on nearly every random access — measured +21-26%
    route throughput with the pool.

    Raises the SYSTEM-WIDE ``/proc/sys/vm/nr_hugepages`` sysctl (~5 GB at
    net 2^28); :func:`route_std` restores the previous value after routing
    (the router's hugetlb mappings are freed by then), with an
    atexit + SIGTERM fallback restore for abnormal exits (ADVICE r4).  A
    SIGKILL / OOM-kill can still strand the reservation — recovery is
    ``echo 0 > /proc/sys/vm/nr_hugepages`` (or the prior value).  Returns
    the prior value when the sysctl was raised, else None.  Set
    ``BFS_TPU_HUGEPAGES=0`` to skip entirely (the router falls back to 4KB
    pages).  Needs root; silently a no-op without it."""
    if not knobs.get("BFS_TPU_HUGEPAGES"):
        return None
    try:
        pages = (20 * n + (2 << 20) - 1) // (2 << 20) + 16
        with open(_NR_HUGEPAGES, "r+") as f:
            prev = int(f.read())
            if prev < pages:
                f.seek(0)
                f.write(str(pages))
                return prev
    except (OSError, ValueError):
        pass
    return None


def _restore_hugepages(prev: int | None) -> None:
    if prev is None:
        return
    try:
        with open(_NR_HUGEPAGES, "w") as f:
            f.write(str(prev))
    except (OSError, ValueError):
        pass


# One outstanding raised-sysctl value per process, guarded by a reentrant
# lock (ADVICE r4: the bare _HOLD_DEPTH/_HOLD_PREV globals were not
# thread-safe, and nothing restored the sysctl on SIGTERM/interpreter
# exit).  _ACTIVE_PREV is the value to write back; the atexit + SIGTERM
# hooks restore it on abnormal exits.
_HP_LOCK = threading.RLock()
_HOLD_DEPTH = 0
_HOLD_ACQUIRED = False  # the hold (not a frame) owns an acquired raise
_ACTIVE_PREV: int | None = None
_EMERGENCY_INSTALLED = False


def _emergency_restore(*_args) -> None:
    # Signal-handler-safe: a plain swap + file write, no locks.
    global _ACTIVE_PREV
    prev, _ACTIVE_PREV = _ACTIVE_PREV, None
    _restore_hugepages(prev)


def _install_emergency_restore() -> None:
    global _EMERGENCY_INSTALLED
    if _EMERGENCY_INSTALLED:
        return
    _EMERGENCY_INSTALLED = True
    atexit.register(_emergency_restore)
    try:
        prev_handler = signal.getsignal(signal.SIGTERM)

        def _on_sigterm(signum, frame):
            _emergency_restore()
            if callable(prev_handler):
                prev_handler(signum, frame)
            elif prev_handler is signal.SIG_IGN:
                pass  # preserve the process's ignored-TERM disposition
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                signal.raise_signal(signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):
        pass  # signal handlers are only settable from the main thread


def _acquire_hugepages(n: int) -> bool:
    """Raise the sysctl for an ``n``-slot route.  Returns True iff THIS
    call raised it (the caller must then :func:`_release_hugepages`)."""
    global _ACTIVE_PREV
    with _HP_LOCK:
        if _ACTIVE_PREV is not None:
            return False  # another caller in this process holds the raise
        prev = _reserve_hugepages(n)
        if prev is None:
            return False
        _ACTIVE_PREV = prev
        _install_emergency_restore()
        return True


def _release_hugepages() -> None:
    global _ACTIVE_PREV
    with _HP_LOCK:
        prev, _ACTIVE_PREV = _ACTIVE_PREV, None
    _restore_hugepages(prev)


@contextlib.contextmanager
def hugepage_reservation(n: int):
    """Hold ONE huge-page reservation across several :func:`route_std`
    calls (a layout build routes the net and then the vperm): repeated
    reserve/free cycles pay kernel compaction per route and the later
    reservations can fall short on a fragmented allocator, silently losing
    the 2MB-page speedup.  ``route_std`` skips its own per-call
    reservation while a hold is active.  Same ``n >= 2^24`` gate as
    route_std's own reservation: small builds (test graphs) stay sysctl
    no-ops."""
    global _HOLD_DEPTH, _HOLD_ACQUIRED
    with _HP_LOCK:
        if _HOLD_DEPTH == 0 and n >= (1 << 24):
            # Ownership lives in the shared hold state, NOT this frame:
            # with overlapping holds from different threads the acquiring
            # frame may exit first, and whichever frame brings the depth
            # back to zero must do the release.
            _HOLD_ACQUIRED = _acquire_hugepages(n)
        _HOLD_DEPTH += 1
    try:
        yield
    finally:
        with _HP_LOCK:
            _HOLD_DEPTH -= 1
            release = _HOLD_DEPTH == 0 and _HOLD_ACQUIRED
            if release:
                _HOLD_ACQUIRED = False
        if release:
            _release_hugepages()


def route_std(perm: np.ndarray, *, trusted: bool = False) -> np.ndarray:
    """Layout-v4 router: Beneš masks in STANDARD (word-major) packing — mask
    element ``e`` at word ``e >> 5``, bit ``e & 31`` — via the iterative int32
    native router (``benes_route_i32``).  This is the packing the v4 device
    kernels consume directly; no transpose pass.  ``len(perm)`` must be a
    power of two in [32, 2^30]."""
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native benes router unavailable")
    perm = np.ascontiguousarray(perm, dtype=np.int32)
    n = int(perm.shape[0])
    if n < 32 or n & (n - 1):
        raise ValueError(f"network size {n} is not a power of two >= 32")
    acquired = n >= (1 << 24) and _acquire_hugepages(n)
    try:
        words = n // 32
        masks = np.zeros(num_stages(n) * words, dtype=np.uint32)
        rc = lib.benes_route_i32_v2(n, perm, masks, int(trusted))
    finally:
        if acquired:
            _release_hugepages()
    if rc == -2:
        raise MemoryError(
            f"native router could not allocate its ~{20 * n >> 20} MiB "
            "working set"
        )
    if rc != 0:
        raise ValueError("perm is not a bijection")
    return masks.reshape(num_stages(n), words)


def pad_perm(perm_partial: np.ndarray, n: int, used_inputs: np.ndarray) -> np.ndarray:
    """Complete a partial mapping to a bijection on ``n`` slots.

    ``perm_partial``: int64[n] with -1 at outputs that do not care;
    ``used_inputs``: bool[n] marking inputs already consumed.  Unassigned
    outputs are matched to unused inputs in order.
    """
    perm = np.asarray(perm_partial, dtype=np.int64).copy()
    free_outputs = np.flatnonzero(perm < 0)
    free_inputs = np.flatnonzero(~np.asarray(used_inputs, dtype=bool))
    if free_outputs.shape[0] != free_inputs.shape[0]:
        raise ValueError("partial permutation is not completable")
    perm[free_outputs] = free_inputs
    return perm


def apply_network_numpy(
    masks: np.ndarray, x: np.ndarray, *, bit_major: bool = False
) -> np.ndarray:
    """Reference applier on an element array (testing / fallback)."""
    n = x.shape[0]
    nw = max(n // 32, 1)
    x = x.copy()
    for s in range(masks.shape[0]):
        d = stage_distance(n, s)
        i = np.arange(n)
        if bit_major:
            bits = (masks[s, i % nw] >> (i // nw)) & 1
        else:
            bits = (masks[s, i >> 5] >> (i & 31)) & 1
        swap = ((i & d) == 0) & (bits == 1)
        idx = i[swap]
        x[idx], x[idx + d] = x[idx + d].copy(), x[idx].copy()
    return x


def pack_bits(bits: np.ndarray) -> np.ndarray:
    """bool/int8[n] -> uint32[n/32] little-endian (n must be a multiple of 32)."""
    b = np.asarray(bits, dtype=np.uint8).reshape(-1, 32).astype(np.uint32)
    return (b << np.arange(32, dtype=np.uint32)).sum(axis=1, dtype=np.uint32)


def unpack_bits(words: np.ndarray) -> np.ndarray:
    w = np.asarray(words, dtype=np.uint32)
    return ((w[:, None] >> np.arange(32, dtype=np.uint32)) & 1).astype(np.uint8).reshape(-1)
