"""Synthetic graph generators: R-MAT (Graph500 kernel-1 style), G(n, m).

The reference ships only fixed datasets (test-sets/, SURVEY.md §2.6); the
R-MAT generator covers the BASELINE.json scale-20/scale-24 configs and plays
the role algs4's unused ``GraphGenerator.java`` would have.
"""

from __future__ import annotations

import numpy as np

from .csr import Graph


def rmat_edges(
    scale: int,
    edge_factor: int = 16,
    *,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 1,
    permute_labels: bool = True,
) -> np.ndarray:
    """Vectorised R-MAT edge generator (Graph500 parameters by default).

    Returns an ``int64[E, 2]`` array of undirected edge endpoints for a graph
    of ``2**scale`` vertices and ``edge_factor * 2**scale`` edges. Self-loops
    and duplicates are kept, as in the Graph500 reference generator.
    """
    n = 1 << scale
    m = edge_factor * n
    rng = np.random.default_rng(seed)
    ab = a + b
    c_norm = c / (1.0 - ab)
    a_norm = a / ab
    src = np.zeros(m, dtype=np.int64)
    dst = np.zeros(m, dtype=np.int64)
    for bit in range(scale):
        src_bit = rng.random(m) > ab
        dst_bit = np.where(src_bit, rng.random(m) > c_norm, rng.random(m) > a_norm)
        src |= src_bit.astype(np.int64) << bit
        dst |= dst_bit.astype(np.int64) << bit
    if permute_labels:
        perm = rng.permutation(n)
        src = perm[src]
        dst = perm[dst]
    return np.stack([src, dst], axis=1)


def rmat_graph(scale: int, edge_factor: int = 16, **kwargs) -> Graph:
    edges = rmat_edges(scale, edge_factor, **kwargs)
    return Graph.from_undirected_edges(1 << scale, edges.astype(np.int32))


def snap_shape_edges(
    num_vertices: int, num_edges: int, *, seed: int = 0
) -> np.ndarray:
    """R-MAT-skewed directed edge list with an ARBITRARY (non-power-of-two)
    vertex count — the shape of real SNAP social graphs (BASELINE.json
    config 4: LiveJournal / soc-Pokec).  Edges are drawn in the enclosing
    power-of-two id space for the heavy-tailed degree distribution, then
    folded into ``[0, V)``; label permutation spreads the hubs."""
    scale = max(int(num_vertices - 1).bit_length(), 1)
    per = num_edges // (1 << scale) + 1  # per * 2^scale >= num_edges always
    edges = rmat_edges(scale, per, seed=seed)[:num_edges]
    return edges % num_vertices


def gnm_graph(num_vertices: int, num_edges: int, *, seed: int = 0) -> Graph:
    """Uniform random undirected multigraph with ``num_edges`` edges."""
    rng = np.random.default_rng(seed)
    pairs = rng.integers(0, num_vertices, size=(num_edges, 2), dtype=np.int64)
    return Graph.from_undirected_edges(num_vertices, pairs.astype(np.int32))


def path_graph(num_vertices: int) -> Graph:
    """A simple path 0-1-2-...-(V-1); worst-case diameter for level-sync BFS."""
    u = np.arange(num_vertices - 1, dtype=np.int32)
    return Graph.from_undirected_edges(num_vertices, np.stack([u, u + 1], axis=1))


def star_graph(num_vertices: int, hub: int = 0) -> Graph:
    """A star: ``hub`` joined to every other vertex.  Maximum fan-out in
    one superstep — the combine's worst-case segment density, and the
    semiring algorithms' canonical tie-break stressor (every leaf path
    runs through the hub)."""
    leaves = np.array(
        [v for v in range(num_vertices) if v != hub], dtype=np.int32
    )
    hubs = np.full(leaves.shape, hub, dtype=np.int32)
    return Graph.from_undirected_edges(
        num_vertices, np.stack([hubs, leaves], axis=1)
    )
