"""Vertex record, Color lattice, and the text wire format.

Bit-for-bit parity with the reference's single distributed data type:

  * ``Color`` (Color.java:18,25,30): WHITE = unvisited, GRAY = frontier,
    BLACK = done.  The ordinal order is load-bearing in the reference
    ("NOTE: DO NOT RE-ORDER !", Color.java:6) because the reducer merges by
    max ordinal (BfsSpark.java:103); we keep the same ordering so merge
    semantics and serialized names agree.
  * ``Vertex`` (Vertex.java:28-36): id, neighbours set, path list, distance,
    color.  Text wire format ``id|[n1, n2]|[p1, p2]|distance|COLOR`` produced
    by ``toString`` (Vertex.java:122-125) and parsed by the ``Vertex(String)``
    ctor (Vertex.java:51-64).  Distances use ``Integer.MAX_VALUE`` (2**31-1)
    for "unreached" (GraphFileUtil.java:55).

In the TPU engine, per-vertex state lives in flat device arrays
(dist/parent/frontier) — this module is the host-side serialization boundary
used for superstep state dumps, checkpoints, and golden tests (the
``problemFile_i`` capability, BfsSpark.java:115-116).  Paths are materialised
lazily from parent pointers instead of being carried per-record (the
reference's per-record path lists are the root cause of its OOM,
SURVEY.md §7 hard-part (c)).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np

from .csr import Graph, INF_DIST, NO_PARENT


class Color(enum.IntEnum):
    """Visit lattice; ordinal order matters for the darkest-color merge
    (Color.java:6, BfsSpark.java:103)."""

    WHITE = 0
    GRAY = 1
    BLACK = 2


@dataclass(frozen=True)
class Vertex:
    """Host-side vertex record matching Vertex.java:28-36.

    ``neighbours`` is kept sorted for deterministic serialization (Java's
    HashSet order is hash-dependent; any order parses back identically).
    """

    id: int
    neighbours: tuple[int, ...]
    path: tuple[int, ...]
    distance: int
    color: Color

    @classmethod
    def parse(cls, line: str) -> "Vertex":
        """Parse the bar wire format (Vertex.java:51-64 parity): tolerant of
        spaces after commas and empty bracket lists."""
        parts = line.strip().split("|")
        if len(parts) != 5:
            raise ValueError(f"malformed vertex line (need 5 bar-fields): {line!r}")
        vid = int(parts[0])
        neighbours = _parse_int_list(parts[1])
        path = _parse_int_list(parts[2])
        distance = int(parts[3])
        color = Color[parts[4].strip()]
        return cls(vid, tuple(sorted(neighbours)), tuple(path), distance, color)

    def serialize(self) -> str:
        """Emit ``id|[n1, n2]|[p1, p2]|distance|COLOR`` exactly like Java
        collection ``toString`` joined with bars (Vertex.java:122-125)."""
        return "|".join(
            [
                str(self.id),
                _fmt_int_list(self.neighbours),
                _fmt_int_list(self.path),
                str(self.distance),
                self.color.name,
            ]
        )

    def with_color(self, color: Color) -> "Vertex":
        """Parity with ``setColor`` (Vertex.java:90), immutably."""
        return Vertex(self.id, self.neighbours, self.path, self.distance, color)


def _parse_int_list(text: str) -> list[int]:
    text = text.strip()
    if not (text.startswith("[") and text.endswith("]")):
        raise ValueError(f"expected bracketed list, got {text!r}")
    inner = text[1:-1].strip()
    if not inner:
        return []
    return [int(tok.strip()) for tok in inner.split(",")]


def _fmt_int_list(values) -> str:
    return "[" + ", ".join(str(int(v)) for v in values) + "]"


# ---------------------------------------------------------------------------
# Engine-state <-> Vertex-record conversion (the state-dump capability)
# ---------------------------------------------------------------------------


def colors_from_state(dist: np.ndarray, frontier: np.ndarray) -> np.ndarray:
    """Derive the 3-state color from engine arrays: frontier = GRAY,
    visited-not-frontier = BLACK, unreached = WHITE (Color.java semantics)."""
    dist = np.asarray(dist)
    frontier = np.asarray(frontier)
    colors = np.full(dist.shape, int(Color.WHITE), dtype=np.int8)
    colors[(dist != INF_DIST) & ~frontier] = int(Color.BLACK)
    colors[frontier] = int(Color.GRAY)
    return colors


def path_to(parent: np.ndarray, v: int, *, source: int | None = None) -> list[int]:
    """Reconstruct source→v path by walking parent pointers — the lazy
    equivalent of per-record path lists (BreadthFirstPaths.java:159-168
    ``pathTo`` back-walk).  Returns [] if v is unreached."""
    parent = np.asarray(parent)
    if v < 0 or v >= parent.shape[0] or parent[v] == NO_PARENT:
        return []
    path = [int(v)]
    while parent[path[-1]] != path[-1]:
        path.append(int(parent[path[-1]]))
        if len(path) > parent.shape[0]:
            raise ValueError("parent pointers contain a cycle")
    path.reverse()
    if source is not None and path[0] != source:
        return []
    return path


def state_to_vertices(
    graph: Graph,
    dist: np.ndarray,
    parent: np.ndarray,
    frontier: np.ndarray,
    *,
    source: int = 0,
) -> list[Vertex]:
    """Render full engine state as Vertex records, one per vertex.

    Quirk parity: the reference initialises every unreached vertex with the
    *source's* path list ``[source]`` (GraphFileUtil.java:55, a shared-list
    quirk), so unreached vertices serialize with path ``[source]`` here too.
    """
    dist = np.asarray(dist)[: graph.num_vertices]
    parent = np.asarray(parent)[: graph.num_vertices]
    frontier = np.asarray(frontier)[: graph.num_vertices]
    colors = colors_from_state(dist, frontier)
    out = []
    for v in range(graph.num_vertices):
        nbrs = tuple(int(x) for x in np.unique(graph.adj(v)))
        if dist[v] == INF_DIST:
            path = (source,)
        else:
            path = tuple(path_to(parent, v))
        out.append(Vertex(v, nbrs, path, int(dist[v]), Color(int(colors[v]))))
    return out


def serialize_state(graph, dist, parent, frontier, *, source: int = 0) -> str:
    """Newline-joined vertex lines — the ``problemFile_i`` file format
    (GraphFileUtil.java:68, BfsSpark.java:115-116)."""
    return "\n".join(
        v.serialize()
        for v in state_to_vertices(graph, dist, parent, frontier, source=source)
    )


def initial_state_vertices(graph: Graph, source: int = 0) -> list[Vertex]:
    """The iteration-0 file contents (GraphFileUtil.java:50-56): source GRAY
    with path [source], distance 0; all others WHITE, Integer.MAX_VALUE."""
    out = []
    for v in range(graph.num_vertices):
        nbrs = tuple(int(x) for x in np.unique(graph.adj(v)))
        if v == source:
            out.append(Vertex(v, nbrs, (source,), 0, Color.GRAY))
        else:
            out.append(Vertex(v, nbrs, (source,), INF_DIST, Color.WHITE))
    return out


def parse_state(text: str, num_vertices: int):
    """Parse a ``problemFile_i``-style dump back into engine arrays
    ``(dist, parent, frontier)`` — the resume half of checkpoint parity
    (BfsSpark.java:62 re-reads the previous superstep file).

    The parent of a reached vertex is recovered from the second-to-last path
    element (the wire format carries paths, not parents).
    """
    dist = np.full(num_vertices, INF_DIST, dtype=np.int32)
    parent = np.full(num_vertices, NO_PARENT, dtype=np.int32)
    frontier = np.zeros(num_vertices, dtype=bool)
    for line in text.strip().splitlines():
        if not line.strip():
            continue
        vx = Vertex.parse(line)
        dist[vx.id] = vx.distance
        if vx.color != Color.WHITE and vx.path:
            parent[vx.id] = vx.path[-2] if len(vx.path) >= 2 else vx.path[-1]
        frontier[vx.id] = vx.color == Color.GRAY
    return dist, parent, frontier
