"""Graph containers: edge lists and CSR adjacency, TPU-friendly padded device form.

Capability parity with the reference's graph layer:
  * ``algs4 Graph`` (sequential-libs/algs4.jar!/Graph.java:59,85-94,145-148) —
    adjacency-list undirected graph built from (V, E, edge pairs); `addEdge`
    inserts both directions.  Here: :class:`Graph` + :func:`build_csr`.
  * ``GraphFileUtil.convert`` bi-directing (GraphFileUtil.java:64-65) —
    :func:`Graph.from_undirected_edges`.

TPU-first differences from the reference:
  * The distributed representation is NOT per-vertex records shipped through a
    shuffle (Vertex.java:22 ``Serializable``); it is flat ``(src, dst)`` edge
    arrays sorted by destination, padded to a static shape with sentinel edges
    so the whole BFS compiles to one XLA program (static shapes, MXU/VPU-
    friendly segmented reductions).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import numpy as np

INT32_MAX = np.int32(np.iinfo(np.int32).max)
#: Distance of an unreached vertex.  Matches Java ``Integer.MAX_VALUE`` used by
#: GraphFileUtil.java:55 so text state dumps are bit-identical.
INF_DIST = int(INT32_MAX)
#: Parent of a vertex with no parent yet (source's parent is itself).
NO_PARENT = -1


@dataclass(frozen=True)
class Graph:
    """A directed multigraph as flat edge arrays (int32), plus lazy CSR.

    ``num_vertices`` is V; ``src``/``dst`` hold E directed edges.  Undirected
    inputs are stored bi-directed (both (u,v) and (v,u)), mirroring
    ``Graph.addEdge`` (Graph.java:145-148).
    """

    num_vertices: int
    src: np.ndarray
    dst: np.ndarray

    def __post_init__(self):
        object.__setattr__(self, "src", np.ascontiguousarray(self.src, dtype=np.int32))
        object.__setattr__(self, "dst", np.ascontiguousarray(self.dst, dtype=np.int32))
        if self.src.shape != self.dst.shape or self.src.ndim != 1:
            raise ValueError("src/dst must be 1-D arrays of equal length")
        if self.num_edges and (
            int(min(self.src.min(initial=0), self.dst.min(initial=0))) < 0
            or int(max(self.src.max(initial=0), self.dst.max(initial=0))) >= self.num_vertices
        ):
            raise ValueError("edge endpoint out of range")

    @property
    def num_edges(self) -> int:
        """Directed edge count (an undirected input counts twice), matching the
        paper's bi-directed E column (docs/BigData_Project.pdf §1.5)."""
        return int(self.src.shape[0])

    @classmethod
    def from_undirected_edges(cls, num_vertices: int, edges: np.ndarray) -> "Graph":
        """Insert every undirected edge in both directions
        (GraphFileUtil.java:64-65, Graph.java:145-148 parity)."""
        edges = np.asarray(edges, dtype=np.int32).reshape(-1, 2)
        src = np.concatenate([edges[:, 0], edges[:, 1]])
        dst = np.concatenate([edges[:, 1], edges[:, 0]])
        return cls(num_vertices, src, dst)

    @classmethod
    def from_directed_edges(cls, num_vertices: int, edges: np.ndarray) -> "Graph":
        edges = np.asarray(edges, dtype=np.int32).reshape(-1, 2)
        return cls(num_vertices, edges[:, 0].copy(), edges[:, 1].copy())

    # -- CSR (adjacency-list) view: the oracle's native format ---------------
    def csr(self) -> tuple[np.ndarray, np.ndarray]:
        """Return ``(indptr int64[V+1], indices int32[E])`` with each vertex's
        neighbours sorted ascending (deterministic, unlike algs4's Bag order)."""
        if not hasattr(self, "_csr_cache"):
            # (src, dst) order == _sorted_by_dst with the roles swapped.
            indices, _ = _sorted_by_dst(self.dst, self.src)
            counts = np.bincount(self.src, minlength=self.num_vertices)
            indptr = np.zeros(self.num_vertices + 1, dtype=np.int64)
            np.cumsum(counts, out=indptr[1:])
            object.__setattr__(self, "_csr_cache", (indptr, indices))
        return self._csr_cache

    def degree(self, v: int) -> int:
        """Parity with ``Graph.degree`` (Graph.java:169-172)."""
        indptr, _ = self.csr()
        return int(indptr[v + 1] - indptr[v])

    def adj(self, v: int) -> np.ndarray:
        """Parity with ``Graph.adj`` (Graph.java:158-161); sorted ascending."""
        indptr, indices = self.csr()
        return indices[indptr[v] : indptr[v + 1]]


@dataclass(frozen=True)
class DeviceGraph:
    """Static-shape, padded edge arrays ready for the XLA BFS engine.

    * Edges are sorted by ``dst`` (then ``src``) so ``segment_min`` runs with
      ``indices_are_sorted=True`` and writes are sequential in HBM.
    * Padding edges are ``(sentinel, sentinel)`` where ``sentinel == V``; all
      state arrays have V+1 slots and slot V is never a real vertex, so padded
      lanes are inert without masks.
    * ``num_shards > 1`` pre-splits edges into equal contiguous blocks (the
      vertex-cut analogue of Spark's hash-partitioned RDD blocks,
      SURVEY.md §2.4) for `shard_map` over a device mesh.
    """

    num_vertices: int
    num_edges: int  # real (unpadded) directed edges
    src: np.ndarray  # int32[num_shards, padded_e // num_shards] or [padded_e]
    dst: np.ndarray
    num_shards: int = 1

    @property
    def padded_edges(self) -> int:
        return int(self.src.size)

    @property
    def sentinel(self) -> int:
        return self.num_vertices


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _sorted_by_dst(src: np.ndarray, dst: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Edges sorted by (dst, src).  Uses the native radix sort
    (native/graph_gen.cpp) when available — ~20x faster than np.lexsort on
    10^8 edges — with identical output (both are stable (dst, src) orders)."""
    try:
        from .native_gen import native_available, sort_edges_by_dst_native

        if native_available() and src.size > 100_000:
            return sort_edges_by_dst_native(src.copy(), dst.copy())
    except Exception:
        pass
    order = np.lexsort((src, dst))
    return src[order], dst[order]


def build_device_graph(
    graph: Graph, *, num_shards: int = 1, block: int = 1024
) -> DeviceGraph:
    """Sort edges by destination, pad with sentinel edges, optionally shard.

    Sharding is round-robin over dst-sorted edges so each shard sees a similar
    dst range distribution — contiguous blocks would skew `segment_min` output
    density per device. Each shard is then re-sorted so `indices_are_sorted`
    still holds per-shard.
    """
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    src, dst = _sorted_by_dst(graph.src, graph.dst)
    sentinel = np.int32(graph.num_vertices)
    e = graph.num_edges
    per_shard = pad_to_multiple(max(pad_to_multiple(e, num_shards) // num_shards, 1), block)
    total = per_shard * num_shards
    pad = total - e
    src = np.concatenate([src, np.full(pad, sentinel, dtype=np.int32)])
    dst = np.concatenate([dst, np.full(pad, sentinel, dtype=np.int32)])
    if num_shards > 1:
        # Strided split keeps per-shard dst distributions balanced.
        src = src.reshape(per_shard, num_shards).T
        dst = dst.reshape(per_shard, num_shards).T
        # Re-sort each shard by dst so segment_min stays sorted per shard.
        for s in range(num_shards):
            o = np.lexsort((src[s], dst[s]))
            src[s] = src[s][o]
            dst[s] = dst[s][o]
        src = np.ascontiguousarray(src)
        dst = np.ascontiguousarray(dst)
    return DeviceGraph(
        num_vertices=graph.num_vertices,
        num_edges=e,
        src=src,
        dst=dst,
        num_shards=num_shards,
    )


def unpad_edges(dg: DeviceGraph) -> tuple[np.ndarray, np.ndarray]:
    """Strip sentinel padding from a DeviceGraph of any shard count: the real
    ``(src, dst)`` host arrays, in stored (per-shard dst-sorted) order."""
    flat_src = dg.src.reshape(-1)
    flat_dst = dg.dst.reshape(-1)
    keep = flat_dst != dg.sentinel
    return flat_src[keep], flat_dst[keep]


def reshard(dg: DeviceGraph, num_shards: int, *, block: int = 1024) -> DeviceGraph:
    """Re-partition an existing DeviceGraph into a new shard count."""
    src, dst = unpad_edges(dg)
    g = Graph(dg.num_vertices, src, dst)
    return build_device_graph(g, num_shards=num_shards, block=block)
