"""ELL-packed pull adjacency: the TPU-native layout for frontier relaxation.

Motivation (measured on TPU v5e): XLA lowers ``segment_min``/scatter-min to a
scalar loop (~0.1 Gedges/s), while dense 2-D gathers and row reductions run
near memory bandwidth.  So instead of the push-style
``segment_min(where(frontier[src], src, INF), dst)`` — the direct analogue of
the reference's shuffle+reduce (BfsSpark.java:90-108) — the pull engine asks,
for every destination vertex, "what is the minimum *active* in-neighbour?"
with only gathers and row-mins:

  * Level 0: in-neighbour lists packed into a dense ``[R0, K]`` matrix of
    source ids (ELL format), one or more rows per vertex, padded with a
    sentinel.  ``cand_row[r] = min_k F[ell0[r, k]]`` where ``F[u] = u`` if
    ``u`` is on the frontier else INF — one gather + one row-min.
  * Degree skew (R-MAT hubs have 10^5 in-edges) is folded by recursion:
    rows of one vertex are themselves grouped K-at-a-time by index matrices
    ``[R_i, K]`` until exactly one row per vertex remains.  Depth is
    ``ceil(log_K(max_indegree))`` — at most 3-4 levels in practice.

Every vertex owns >= 1 row at every level and rows are vertex-major, so the
final level has exactly one row per vertex in id order.  The layout is
static per graph (built once on host, NumPy), so every superstep is the same
fixed-shape XLA program: no data-dependent shapes, no scatter, no host
round-trips.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .csr import DeviceGraph, Graph, pad_to_multiple

#: Default ELL row width: padding waste is bounded by V*(K-1) slots while
#: fold depth stays ceil(log_K(max_indegree)).
DEFAULT_K = 32


@dataclass(frozen=True)
class PullGraph:
    """Static pull-mode adjacency for one edge shard.

    ``ell0``: int32[R0p, K] — source-vertex ids, sentinel-padded (sentinel =
    ``num_vertices``; slot V of the frontier table is always inactive), rows
    vertex-major, padded to R0p rows (padding rows are all-sentinel).

    ``folds``: tuple of int32[R_ip, K] index matrices.  ``folds[i]`` gathers
    from the previous level's row-min output *extended by one INF slot at its
    end* (index = previous padded row count), so padding entries select INF.
    After the last fold, rows 0..V-1 are the vertices in id order.
    """

    num_vertices: int
    num_edges: int  # real directed edges packed into ell0
    ell0: np.ndarray
    folds: tuple[np.ndarray, ...] = field(default_factory=tuple)

    @property
    def k(self) -> int:
        return int(self.ell0.shape[1])

    @property
    def padded_slots(self) -> int:
        return int(self.ell0.size) + sum(int(f.size) for f in self.folds)


def device_ell(pg: "PullGraph"):
    """Device operands for the pull engine, TRANSPOSED to ``[K, rows]``.

    TPU tiles 2-D int32 as (8, 128): the natural [rows, K=32] layout pads
    its minor dimension 32 -> 128 — a 4.0x HBM expansion on the index
    operands AND every gather temp (the LiveJournal-shape single-chip
    pull cell OOMed at 15.92/15.75 GB from exactly this padding —
    VERDICT r4 #7).  [K, rows] puts the huge dimension minor and the
    row-min reduce over the MAJOR axis (ops/pull._rowmin_level)."""
    cached = getattr(pg, "_device_ell", None)
    if cached is not None:
        return cached
    import jax.numpy as jnp

    ell0 = jnp.asarray(np.ascontiguousarray(np.asarray(pg.ell0).T))
    folds = tuple(
        jnp.asarray(np.ascontiguousarray(np.asarray(f).T)) for f in pg.folds
    )
    # Memoized on the (frozen, slot-less) layout object like
    # parallel/sharded._own_word_table_dev: the transpose copy + HBM
    # upload must not repeat per search in callers' hot loops.
    object.__setattr__(pg, "_device_ell", (ell0, folds))
    return ell0, folds


def device_ell_sharded(spg: "ShardedPullGraph"):
    """Sharded twin of :func:`device_ell`: [n, R, K] -> [n, K, R]."""
    cached = getattr(spg, "_device_ell", None)
    if cached is not None:
        return cached
    import jax.numpy as jnp

    ell0 = jnp.asarray(
        np.ascontiguousarray(np.asarray(spg.ell0).transpose(0, 2, 1))
    )
    folds = tuple(
        jnp.asarray(np.ascontiguousarray(np.asarray(f).transpose(0, 2, 1)))
        for f in spg.folds
    )
    object.__setattr__(spg, "_device_ell", (ell0, folds))
    return ell0, folds


def drop_device_operands(pg) -> None:
    """Release the HBM operands memoized by :func:`device_ell` /
    :func:`device_ell_sharded`.

    The memo pins multi-GB device buffers for the lifetime of the host
    layout object (at the LiveJournal-shape scale the full operand set is
    most of a chip's HBM) — a long-lived process that keeps the layout
    around but switches engines, or holds several graphs (the serve
    registry's eviction path, serve/registry.py), calls this between uses.
    The next ``device_ell*`` call re-uploads.

    NOTE: clearing the memo only removes THIS reference.  The HBM is freed
    once callers ALSO drop their own references to the previously returned
    ``(ell0, folds)`` tuple (and to anything derived that aliases it); a
    caller that keeps the tuple alive keeps the buffers alive."""
    if getattr(pg, "_device_ell", None) is not None:
        object.__setattr__(pg, "_device_ell", None)


def pull_to_arrays(pg: "PullGraph") -> dict[str, np.ndarray]:
    """Flatten a PullGraph to name -> ndarray for the persistent layout
    cache (bfs_tpu/cache/layout.py); inverse is :func:`pull_from_arrays`."""
    return dict(
        num_vertices=np.int64(pg.num_vertices),
        num_edges=np.int64(pg.num_edges),
        ell0=pg.ell0,
        num_folds=np.int64(len(pg.folds)),
        **{f"fold{i}": f for i, f in enumerate(pg.folds)},
    )


def pull_from_arrays(z) -> "PullGraph":
    """Rebuild a PullGraph from any name -> array mapping (npz, memmaps)."""
    nf = int(z["num_folds"])
    return PullGraph(
        num_vertices=int(z["num_vertices"]),
        num_edges=int(z["num_edges"]),
        ell0=z["ell0"],
        folds=tuple(z[f"fold{i}"] for i in range(nf)),
    )


@dataclass(frozen=True)
class ShardedPullGraph:
    """ELL pull layout partitioned by destination vertex over mesh shards.

    The multi-device layout for the TPU-fast pull engine: shard ``s`` owns
    the contiguous vertex block ``[s*block, (s+1)*block)`` and holds the ELL
    in-adjacency of exactly those destinations, with GLOBAL source-vertex
    ids.  Per superstep each device gathers from a replicated global
    frontier table and produces candidates for its own block only; the new
    frontier is exchanged as a bit-packed bitmap all-gather (1 bit/vertex
    over ICI) — the TPU-first replacement for the reference's Spark shuffle
    of Vertex records (BfsSpark.java:90-110) that scales per-chip edge
    memory as E/n (SURVEY.md §5 long-context row).

    All shards share identical shapes (stacked on axis 0) so the engine is
    one `shard_map` program:
      * ``ell0``: int32[n, R0, K] — global src ids, sentinel-padded
        (sentinel = ``n*block``, the one always-inactive frontier slot).
      * ``folds``: tuple of int32[n, R_i, K] — same fold recursion as
        :class:`PullGraph`, per-shard, padded to common depth (shards that
        converge early get identity folds) and common row counts.  Fold
        padding entries index the INF slot appended at the previous level's
        padded row count.
    After the last fold, rows ``0..block-1`` of shard ``s`` are its owned
    vertices in id order.
    """

    num_vertices: int  # real V (unpadded)
    num_edges: int  # real directed edges across all shards
    num_shards: int
    block: int  # owned vertices per shard, padded; multiple of 32
    ell0: np.ndarray
    folds: tuple[np.ndarray, ...] = field(default_factory=tuple)

    @property
    def k(self) -> int:
        return int(self.ell0.shape[2])

    @property
    def padded_vertices(self) -> int:
        return self.num_shards * self.block


def _group_rows(counts: np.ndarray, k: int):
    """Pack per-group items (stored contiguously, group-major) into rows of
    width ``k``: every group gets ``max(ceil(count/k), 1)`` rows, numbered
    globally in group order.  Returns ``(row_of_item, col_of_item,
    rows_per_group)``."""
    total = int(counts.sum())
    rows_per_group = np.maximum((counts + k - 1) // k, 1)
    group_start = np.zeros(counts.shape[0] + 1, dtype=np.int64)
    np.cumsum(counts, out=group_start[1:])
    row_offset = np.zeros(counts.shape[0] + 1, dtype=np.int64)
    np.cumsum(rows_per_group, out=row_offset[1:])
    item_group = np.repeat(np.arange(counts.shape[0], dtype=np.int64), counts)
    pos_in_group = np.arange(total, dtype=np.int64) - group_start[item_group]
    row_of_item = row_offset[item_group] + pos_in_group // k
    col_of_item = pos_in_group % k
    return row_of_item, col_of_item, rows_per_group


def build_pull_graph(
    graph: Graph | DeviceGraph,
    *,
    k: int = DEFAULT_K,
    row_multiple: int = 64,
) -> PullGraph:
    """Pack a graph's in-adjacency (edges grouped by dst) into ELL levels.

    Works from either a host :class:`Graph` or a dst-sorted single-shard
    :class:`DeviceGraph` (its sentinel padding edges are dropped).
    ``row_multiple`` pads each level's row count for clean (sublane, lane)
    tiling; final-level rows beyond V are harmless padding.
    """
    if k < 2:
        raise ValueError("ELL width k must be >= 2")
    if isinstance(graph, DeviceGraph):
        if graph.num_shards != 1:
            raise ValueError("build_pull_graph expects a single-shard DeviceGraph")
        flat_src = graph.src.reshape(-1)
        flat_dst = graph.dst.reshape(-1)
        keep = flat_dst != graph.sentinel
        src, dst = flat_src[keep], flat_dst[keep]
        v = graph.num_vertices
    else:
        from .csr import _sorted_by_dst

        src, dst = _sorted_by_dst(graph.src, graph.dst)
        v = graph.num_vertices
    e = int(src.shape[0])
    sentinel = np.int32(v)

    # ---- level 0: pack edge sources by destination vertex ----
    counts = np.bincount(dst, minlength=v).astype(np.int64) if e else np.zeros(v, np.int64)
    row_of, col_of, rows_per_v = _group_rows(counts, k)
    r0 = int(rows_per_v.sum())
    r0_padded = pad_to_multiple(r0, row_multiple)
    ell0 = np.full((r0_padded, k), sentinel, dtype=np.int32)
    ell0[row_of, col_of] = src

    # ---- fold levels: group each vertex's rows, K at a time ----
    folds: list[np.ndarray] = []
    level_rows = rows_per_v  # per-vertex row count at the current level
    prev_padded = r0_padded  # padded row count of the current level
    prev_max = int(level_rows.max()) + 1
    while int(level_rows.max()) > 1:
        if int(level_rows.max()) >= prev_max:  # k >= 2 strictly shrinks rows
            raise RuntimeError("ELL fold recursion failed to converge")
        prev_max = int(level_rows.max())
        row_of, col_of, next_rows = _group_rows(level_rows, k)
        r_next = int(next_rows.sum())
        r_next_padded = pad_to_multiple(r_next, row_multiple)
        # Items are the previous level's real rows 0..sum(level_rows)-1 in
        # order; the INF slot appended to the previous cand output sits at
        # index prev_padded.
        fold = np.full((r_next_padded, k), prev_padded, dtype=np.int32)
        fold[row_of, col_of] = np.arange(int(level_rows.sum()), dtype=np.int32)
        folds.append(fold)
        level_rows = next_rows
        prev_padded = r_next_padded

    return PullGraph(num_vertices=v, num_edges=e, ell0=ell0, folds=tuple(folds))


def _shard_levels(src_global: np.ndarray, dst_local: np.ndarray, block: int, k: int):
    """One shard's unpadded ELL recursion.  Returns ``[level0, fold1, ...]``
    as int64 matrices with natural row counts; ``-1`` marks INF/sentinel
    entries (resolved to the unified padded indices by the caller)."""
    counts = (
        np.bincount(dst_local, minlength=block).astype(np.int64)
        if dst_local.size
        else np.zeros(block, np.int64)
    )
    row_of, col_of, rows_per = _group_rows(counts, k)
    lvl0 = np.full((int(rows_per.sum()), k), -1, dtype=np.int64)
    lvl0[row_of, col_of] = src_global
    levels = [lvl0]
    level_rows = rows_per
    while int(level_rows.max()) > 1:
        prev_real = int(level_rows.sum())
        row_of, col_of, next_rows = _group_rows(level_rows, k)
        fold = np.full((int(next_rows.sum()), k), -1, dtype=np.int64)
        fold[row_of, col_of] = np.arange(prev_real, dtype=np.int64)
        levels.append(fold)
        level_rows = next_rows
    return levels


def build_sharded_pull_graph(
    graph: Graph | DeviceGraph,
    num_shards: int,
    *,
    k: int = DEFAULT_K,
    block_multiple: int = 1024,
    row_multiple: int = 64,
) -> ShardedPullGraph:
    """Partition a graph's in-adjacency into per-destination-block ELL shards
    with uniform stacked shapes (see :class:`ShardedPullGraph`).

    ``block_multiple`` keeps the per-shard vertex block a multiple of 32 (for
    bit-packing) and of the (8,128) tile lane count."""
    if k < 2:
        raise ValueError("ELL width k must be >= 2")
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    if block_multiple % 32 != 0:
        raise ValueError("block_multiple must be a multiple of 32")
    from .csr import _sorted_by_dst, unpad_edges

    if isinstance(graph, DeviceGraph):
        # Any shard count: strip sentinel padding and re-sort globally (a
        # multi-shard DeviceGraph is only dst-sorted per shard).
        src, dst = _sorted_by_dst(*unpad_edges(graph))
    else:
        src, dst = _sorted_by_dst(graph.src, graph.dst)
    v = graph.num_vertices
    e = int(src.shape[0])
    block = pad_to_multiple(max((v + num_shards - 1) // num_shards, 1), block_multiple)
    sentinel = np.int64(num_shards * block)

    # Edges are dst-sorted: shard boundaries are one searchsorted.
    bounds = np.searchsorted(dst, np.arange(num_shards + 1, dtype=np.int64) * block)
    shard_levels = [
        _shard_levels(
            src[bounds[s] : bounds[s + 1]].astype(np.int64),
            dst[bounds[s] : bounds[s + 1]].astype(np.int64) - s * block,
            block,
            k,
        )
        for s in range(num_shards)
    ]

    # Unify fold depth: shards that converged early get identity folds
    # (each of the block's final vertex rows folds just itself).
    depth = max(len(lv) for lv in shard_levels)
    ident = np.full((block, k), -1, dtype=np.int64)
    ident[:, 0] = np.arange(block, dtype=np.int64)
    for lv in shard_levels:
        while len(lv) < depth:
            lv.append(ident)

    # Unify row counts per level, then resolve -1 markers: level 0 sentinels
    # point at the always-inactive frontier slot; fold sentinels point at the
    # INF slot appended after the previous level's PADDED rows.
    stacked = []
    prev_rows = None
    for i in range(depth):
        rows = pad_to_multiple(max(lv[i].shape[0] for lv in shard_levels), row_multiple)
        fill = sentinel if i == 0 else np.int64(prev_rows)
        level = np.full((num_shards, rows, k), fill, dtype=np.int64)
        for s, lv in enumerate(shard_levels):
            m = lv[i].copy()
            m[m < 0] = fill
            level[s, : m.shape[0]] = m
        stacked.append(level.astype(np.int32))
        prev_rows = rows

    return ShardedPullGraph(
        num_vertices=v,
        num_edges=e,
        num_shards=num_shards,
        block=block,
        ell0=stacked[0],
        folds=tuple(stacked[1:]),
    )
