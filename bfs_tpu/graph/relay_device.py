"""Device-side relay layout construction: layout v4 built by XLA programs.

The host builder (:func:`bfs_tpu.graph.relay.build_relay_graph`) is the last
giant cold cost on the critical path — 506 s of host NumPy + native routing
at s24 against a 0.75 s solve — so every NEW graph pays ~8 minutes before
its first superstep (ROADMAP item 3).  GPU BFS frameworks build their
device-resident representations on-accelerator with sort/scan primitives for
exactly this reason (arxiv 1408.1605, 2606.05081).  This module rebuilds the
class/slot/permutation construction as JAX device code and pipelines it:

  * **width classing** — the ``{2^k, 3*2^(k-1)}`` degree-class rule as an
    exact integer ``searchsorted`` over a static candidate table
    (:func:`relay.width_candidates`) instead of float ``log2`` — bit-equal
    to `_class_width` and safe under jax's default 32-bit floats;
  * **relabeling / out-positions** — one stable ``lax.sort`` per side plus
    a boundary ``cummax`` rank, replacing per-class Python placement loops;
  * **L1/L2 slot assignment** — a stable two-key sort by (relabeled dst,
    original src) for the canonical min-parent rank, a stable one-key sort
    for the free L2 rank, and the class-table lookup as a ~60-entry
    ``searchsorted`` gather;
  * **permutation assembly + identity padding** — scatters plus a
    cumsum-rank matching of free outputs to free inputs (ascending, exactly
    the host `_pad_identity` tie-break);
  * **mask pair-compaction + stage tables** — `_compact_and_table`'s Python
    stage loop as one staged XLA program per network;
  * **sparse CSR** — a stable sort by relabeled src (the host counting
    sort's order exactly);
  * **pipelining** — the vperm assembly/route, sparse CSR and compaction
    run on a worker thread INSIDE the big-net route's window (the route is
    walker-bound on one core; the sequential host builder serializes all
    of it after the route).

Two MEASURED arms exist per concern, selected the way this repo selects
every kernel (probe/knob, honest default):

  * **segments** (``BFS_TPU_LAYOUT_SEGMENTS=auto|xla|host``): the XLA
    programs above, or the shared vectorized host segment functions
    (``relay.seg_*`` — the exact code the host builder composes).  ``auto``
    picks ``xla`` on accelerator backends and ``host`` on the CPU backend,
    where XLA's scatter/sort primitives measure 5-13x slower than the
    native radix/bincount helpers (same physical cores, no transfer — see
    ARCHITECTURE §18 for the numbers).  Both arms are bit-identical.
  * **route** (``BFS_TPU_LAYOUT_ROUTE=auto|native|jax``): the native C++
    cycle walker (O(n log n) work, measured fastest everywhere we can
    measure), or a pure-JAX parallel Beneš router — pointer-jumping
    orbit-min cycle coloring, O(n log² n) — with NO native dependency.
    Masks from the two arms differ bit-wise (any valid coloring routes the
    permutation) but are semantically equivalent; every NON-mask field is
    bit-identical to the host builder either way.

Everything lands in the same :class:`~bfs_tpu.graph.relay.RelayGraph` /
``relay_to_arrays`` schema, so disk bundles, serializers, the sparse rank
flavor and every engine are unchanged.
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import functools
import os
import threading
import time
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import benes
from .. import knobs
from .csr import DeviceGraph, Graph, INF_DIST
from .relay import (
    COMPACT_MIN_D,
    RelayGraph,
    StageSpec,
    _compact_and_table,
    _ensure_build_log,
    _gather,
    _phase,
    _width_class_map,
    extract_edges,
    seg_classes,
    seg_classes_from_counts,
    seg_csr,
    seg_degrees,
    seg_l1_slots,
    seg_l2_slots,
    seg_net_assembly,
    seg_relabel,
    seg_vperm_assembly,
    width_candidates,
)

#: Candidate width table shipped to the device (int32: covers degrees to
#: 2^30; a graph with a larger in/out degree falls back to the host
#: builder — the metadata step detects the overflow and raises).
_CANDIDATES = width_candidates(1 << 30).astype(np.int32)

#: The builder's compiled-program memo: (name, arg avals, statics) ->
#: AOT-compiled executable.  Programs compile once per shape set per
#: process; the jax persistent compilation cache
#: (config.enable_compile_cache) lets later processes load them from disk.
_COMPILED: dict = {}


def resolve_segments(segments: str | None = None) -> str:
    """``xla`` (on-device programs) or ``host`` (shared vectorized numpy
    segments): explicit arg > ``BFS_TPU_LAYOUT_SEGMENTS`` > backend
    default (xla on accelerators, host on the CPU backend — measured)."""
    segments = segments or knobs.get("BFS_TPU_LAYOUT_SEGMENTS")
    if segments in ("", "auto"):
        return "host" if jax.default_backend() == "cpu" else "xla"
    if segments not in ("xla", "host"):
        raise ValueError(
            f"unknown segment arm {segments!r}; use auto|xla|host"
        )
    return segments


def resolve_route(route: str | None = None) -> str:
    """The route arm: explicit arg > ``BFS_TPU_LAYOUT_ROUTE`` > native
    where available (measured fastest on the build CPU), else jax."""
    route = route or knobs.get("BFS_TPU_LAYOUT_ROUTE")
    if route in ("", "auto"):
        return "native" if benes.native_available() else "jax"
    if route not in ("native", "jax"):
        raise ValueError(f"unknown route arm {route!r}; use auto|native|jax")
    return route


# --------------------------------------------------------------------------
# Device programs (the ``xla`` segment arm).  Each is a pure jittable
# function marked hot (no host transfers inside — policed by the AST lint)
# and registered in analysis/ir.PROGRAM_SPECS via :func:`ir_operands`.
# --------------------------------------------------------------------------

# bfs_tpu: hot traced
def _degree_hist_program(src, dst, candidates, *, num_vertices: int):
    """Per-vertex width-class indices + per-width histograms.

    Degrees beyond the candidate table scatter out of bounds and DROP, so
    ``hist.sum() < V`` on the host flags the (absurd-degree) overflow."""
    v = num_vertices
    one = jnp.int32(1)
    indeg = jnp.zeros(v, jnp.int32).at[dst].add(one)
    outdeg = jnp.zeros(v, jnp.int32).at[src].add(one)
    nc = candidates.shape[0]
    in_widx = jnp.searchsorted(
        candidates, jnp.maximum(indeg, 1), side="left"
    ).astype(jnp.int32)
    out_widx = jnp.searchsorted(
        candidates, jnp.maximum(outdeg, 1), side="left"
    ).astype(jnp.int32)
    in_hist = jnp.zeros(nc, jnp.int32).at[in_widx].add(one, mode="drop")
    out_hist = jnp.zeros(nc, jnp.int32).at[out_widx].add(one, mode="drop")
    return in_widx, out_widx, in_hist, out_hist


def _rank_in_runs(keys_sorted, idx):
    """Stable rank within equal-key runs of an ascending-sorted key array:
    ``idx - run_start`` via a boundary cummax."""
    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), keys_sorted[1:] != keys_sorted[:-1]]
    )
    run_start = lax.cummax(jnp.where(boundary, idx, 0))
    return idx - run_start


def _place(widx, va_by_widx):
    """Class-major, original-id-minor placement: position = class slot
    start + stable rank within the width group (the device form of the
    builders' per-class placement loops)."""
    n = widx.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    ws, order = lax.sort((widx, idx), num_keys=1, is_stable=True)
    pos_sorted = va_by_widx[ws] + _rank_in_runs(ws, idx)
    return jnp.zeros(n, jnp.int32).at[order].set(pos_sorted)


# bfs_tpu: hot traced
def _relabel_program(in_widx, out_widx, in_va, out_va, *, vr: int):
    """old->new relabeling (dst side) + out-order positions (src side)."""
    v = in_widx.shape[0]
    old2new = _place(in_widx, in_va)
    new2old = jnp.full(vr, -1, jnp.int32).at[old2new].set(
        jnp.arange(v, dtype=jnp.int32)
    )
    outpos_of_old = _place(out_widx, out_va)
    return new2old, old2new, outpos_of_old


def _base_stride(ids, va_bounds, sa, count, width, vmaj):
    """Per-id slot table lookup: class index by ``searchsorted`` over the
    contiguous class starts (~60 entries), then the rank-major
    (``base = sa + p``, ``stride = count``) or vertex-major
    (``base = sa + p*width``, ``stride = 1``) formula elementwise."""
    ci = jnp.searchsorted(va_bounds, ids, side="right") - 1
    p = ids - va_bounds[ci]
    base = jnp.where(vmaj[ci], sa[ci] + p * width[ci], sa[ci] + p)
    stride = jnp.where(vmaj[ci], 1, count[ci])
    return base, stride


# bfs_tpu: hot traced
def _slots_program(
    src, dst, old2new, outpos_of_old,
    in_va_b, in_sa, in_cnt, in_w, in_vm,
    out_va_b, out_sa, out_cnt, out_w, out_vm,
    *, m1: int,
):
    """L1/L2 slot assignment.

    L1: edges stable-sorted by (relabeled dst, ORIGINAL src) — the one
    REQUIRED order (rank == canonical min-parent).  L2: stable sort by src
    out-position alone; the within-row rank is free, and stability makes
    it exactly the host `_rank_by_count` edge-order counting rank."""
    e = src.shape[0]
    idx = jnp.arange(e, dtype=jnp.int32)
    dstn = old2new[dst]
    ds, ss, order1 = lax.sort((dstn, src, idx), num_keys=2, is_stable=True)
    r1 = _rank_in_runs(ds, idx)
    base1, stride1 = _base_stride(ds, in_va_b, in_sa, in_cnt, in_w, in_vm)
    l1_sorted = base1 + r1 * stride1
    src_l1 = jnp.full(m1, INF_DIST, jnp.int32).at[l1_sorted].set(ss)
    l1_by_edge = jnp.zeros(e, jnp.int32).at[order1].set(l1_sorted)

    srcpos = outpos_of_old[src]
    sp, order2 = lax.sort((srcpos, idx), num_keys=1, is_stable=True)
    r2 = _rank_in_runs(sp, idx)
    base2, stride2 = _base_stride(sp, out_va_b, out_sa, out_cnt, out_w, out_vm)
    l2_by_edge = jnp.zeros(e, jnp.int32).at[order2].set(base2 + r2 * stride2)
    return src_l1, l1_by_edge, l2_by_edge, dstn, old2new[src]


def _pad_identity_traced(perm, used):
    """Traced `_pad_identity`: identity wiring where both pair members are
    free, then free outputs matched to free inputs ASCENDING (cumsum
    ranks) — the host tie-break exactly."""
    n = perm.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    both = (perm < 0) & ~used
    perm = jnp.where(both, idx, perm)
    used = used | both
    fo = perm < 0
    fi = ~used
    ro = jnp.cumsum(fo.astype(jnp.int32)) - 1
    ri = jnp.cumsum(fi.astype(jnp.int32)) - 1
    pos_by_rank = (
        jnp.zeros(n, jnp.int32)
        .at[jnp.where(fo, ro, n)]
        .set(idx, mode="drop")
    )
    target = pos_by_rank[jnp.where(fi, ri, 0)]
    return perm.at[jnp.where(fi, target, n)].set(idx, mode="drop")


# bfs_tpu: hot traced
def _net_assembly_program(l1_by_edge, l2_by_edge, *, n: int):
    """Big-network permutation assembly + identity padding."""
    net = jnp.full(n, -1, jnp.int32).at[l1_by_edge].set(l2_by_edge)
    used = jnp.zeros(n, bool).at[l2_by_edge].set(True)
    return _pad_identity_traced(net, used)


# bfs_tpu: hot traced
def _vperm_assembly_program(
    outpos_of_old, old2new, *, vp: int, vr: int, out_vb: int
):
    """vperm assembly: real out positions <- relabeled owner id, dummy
    positions (ascending) <- the guaranteed-zero inputs [vr, vp)."""
    vfront = (
        jnp.full(out_vb, -1, jnp.int32).at[outpos_of_old].set(old2new)
    )
    real = jnp.zeros(out_vb, bool).at[outpos_of_old].set(True)
    dummy_rank = jnp.cumsum((~real).astype(jnp.int32)) - 1
    vfront = jnp.where(real, vfront, vr + dummy_rank)
    vperm = jnp.concatenate([vfront, jnp.full(vp - out_vb, -1, jnp.int32)])
    used = jnp.zeros(vp, bool).at[vfront].set(True)
    return _pad_identity_traced(vperm, used)


# bfs_tpu: hot traced
def _csr_program(srcn, dstn, l1_by_edge, *, vr: int):
    """Sparse-path CSR grouped by relabeled src: a stable sort reproduces
    the host counting sort's edge order exactly."""
    e = srcn.shape[0]
    idx = jnp.arange(e, dtype=jnp.int32)
    _, order = lax.sort((srcn, idx), num_keys=1, is_stable=True)
    counts = jnp.zeros(vr, jnp.int32).at[srcn].add(jnp.int32(1))
    cum = jnp.cumsum(counts)
    indptr = jnp.concatenate([jnp.zeros(1, jnp.int32), cum, cum[-1:]])
    return indptr, dstn[order], l1_by_edge[order]


def _pack_words(bits):
    """bool[n] -> uint32[n/32], standard little-endian packing."""
    b = bits.reshape(-1, 32).astype(jnp.uint32)
    return (b << jnp.arange(32, dtype=jnp.uint32)[None, :]).sum(
        axis=1, dtype=jnp.uint32
    )


# bfs_tpu: hot traced
def _route_level_program(perm, d, iters):
    """One Beneš level: 2-color the input/output pair constraint cycles and
    derive the two stage masks + the next-level sub-permutation.

    Coloring: along a constraint cycle, outputs alternate between the
    output-pair matching (``j <-> j^d``) and the shared-input matching
    (``j <-> inv[perm[j]^d]``); two steps (``f``) preserve the subnetwork
    side, so each cycle splits into exactly two f-orbits.  Pointer-jumping
    ``min`` over ``iters >= log2(orbit)`` doublings yields a canonical
    orbit representative; the orbit whose representative is SMALLER than
    its pair-orbit's goes through the upper subnetwork — a deterministic
    pure function of the permutation (identity cycles color upper, so
    all-pad pairs route switch-free like the native router).
    """
    n = perm.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    inv = jnp.zeros(n, jnp.int32).at[perm].set(idx)
    f = inv[perm[idx ^ d] ^ d]

    def body(_, rg):
        r, g = rg
        return jnp.minimum(r, r[g]), g[g]

    r, _ = lax.fori_loop(0, iters, body, (idx, f))
    color = r > r[idx ^ d]  # True: routed through the lower subnetwork
    low = (idx & d) == 0
    obits = color & low            # output-stage swap bits (lower index)
    ibits = color[inv] & low       # input-stage swap bits (lower index)
    dst = jnp.where(obits[idx & ~d], idx ^ d, idx)
    i0 = perm[dst]
    perm_next = jnp.where(ibits[i0 & ~d], i0 ^ d, i0)
    return _pack_words(ibits), _pack_words(obits), perm_next


# bfs_tpu: hot traced
def _route_mid_program(perm):
    """The middle (d=1) stage: swap a pair iff its final sub-permutation
    crosses it."""
    n = perm.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    return _pack_words(((idx & 1) == 0) & (perm != idx))


# bfs_tpu: hot traced
def _compact_program(masks, *, n: int):
    """`_compact_and_table`'s stage loop as one staged program: pair-compact
    every stage with d >= COMPACT_MIN_D (keep the word rows at
    ``(row & (d >> 5)) == 0``) and reduce each stage's stored nonzero word
    range ``[first, last+1)`` (``(0, 0)`` when all-zero)."""
    parts = []
    nz = []
    for s in range(benes.num_stages(n)):
        d = benes.stage_distance(n, s)
        w = masks[s]
        if d >= COMPACT_MIN_D:
            dw = d >> 5
            w = w.reshape(-1, 2, dw)[:, 0, :].reshape(-1)
        nzv = w != 0
        first = jnp.argmax(nzv).astype(jnp.int32)
        last = jnp.int32(w.shape[0]) - 1 - jnp.argmax(nzv[::-1]).astype(
            jnp.int32
        )
        rng = jnp.where(
            jnp.any(nzv),
            jnp.stack([first, last + 1]),
            jnp.zeros(2, jnp.int32),
        )
        parts.append(w)
        nz.append(rng)
    return jnp.concatenate(parts), jnp.stack(nz)


# --------------------------------------------------------------------------
# AOT compile memo (one compile per program per shape set per process; the
# persistent compilation cache carries them across processes).
# --------------------------------------------------------------------------

@contextlib.contextmanager
def _persist_small_compiles():
    """The builder's programs compile in well under the persistent cache's
    default 5 s write floor (config.enable_compile_cache) — drop the floor
    to 0 around builder compiles so fresh processes load them from disk
    instead of re-tracing, and restore it after."""
    try:
        if not jax.config.jax_compilation_cache_dir:
            yield
            return
        prev = jax.config.jax_persistent_cache_min_compile_time_secs
    except AttributeError:  # knob absent on this jax version
        yield
        return
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        yield
    finally:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev
        )


#: Serializes builder compiles: the overlapped pipeline compiles from two
#: threads, and both the persistent-cache floor swap (global jax config)
#: and the ``compile_seconds`` accumulation need exclusion.
_COMPILE_LOCK = threading.Lock()

#: The overlapped pipeline's worker pool, pre-started at import (the
#: import itself happens outside the timed build) so a cold first build
#: never pays thread spawn latency on its critical path.  Two workers:
#: two concurrent builds in one process (a serve registry racing two
#: graphs) each still get a live worker.
#: Shared build-overlap pool: this builder's tail track AND the sharded
#: builder's per-shard adjacency fills (relay.build_sharded_relay_graph,
#: ISSUE 11) ride it — host numpy work overlapped with the native route's
#: single-walker window.
_TRACK_POOL = concurrent.futures.ThreadPoolExecutor(
    max_workers=2, thread_name_prefix="relay-build"
)
_TRACK_POOL.submit(lambda: None)  # start a worker thread eagerly


def _compiled(name: str, fn: Callable, args, statics: dict, times: dict):
    """AOT lower+compile memo; compile seconds accumulate SEPARATELY from
    the stage execution times (``times['compile_seconds']``) — a
    once-per-shape artifact cost like the engines' own AOT programs."""
    key = (
        name,
        tuple((tuple(a.shape), str(a.dtype)) for a in args),
        tuple(sorted(statics.items())),
    )
    hit = _COMPILED.get(key)
    if hit is None:
        with _COMPILE_LOCK:
            hit = _COMPILED.get(key)
            if hit is None:
                t0 = time.perf_counter()
                with _persist_small_compiles():
                    # statics bind as partial kwargs (closed-over Python
                    # ints), so the jit signature is shape-only; the
                    # executable is memoized here, never re-traced.
                    hit = (
                        jax.jit(functools.partial(fn, **statics))
                        .lower(*args)
                        .compile()
                    )
                _COMPILED[key] = hit
                times["compile_seconds"] = (
                    times.get("compile_seconds", 0.0)
                    + time.perf_counter() - t0
                )
    return hit


def route_masks_device(
    perm, *, n: int, times: dict | None = None, _capture: dict | None = None
):
    """Pure-JAX Beneš router: STANDARD-packed masks ``uint32[stages, n/32]``
    for ``y[j] = x[perm[j]]`` (same convention as
    :func:`bfs_tpu.graph.benes.route_std`, different — but equivalent —
    switch settings).  ``perm`` may be a device or host int32 array."""
    if n < 32 or n & (n - 1):
        raise ValueError(f"network size {n} is not a power of two >= 32")
    times = {} if times is None else times
    k = n.bit_length() - 1
    perm = jnp.asarray(perm, jnp.int32)
    if _capture is not None:
        _capture["layout.route_level"] = (
            _route_level_program,
            (perm, jnp.int32(n >> 1), jnp.int32(n.bit_length())),
            {},
        )
        _capture["layout.route_mid"] = (_route_mid_program, (perm,), {})
    level = _compiled(
        "layout.route_level", _route_level_program,
        (perm, jnp.int32(0), jnp.int32(0)), {}, times,
    )
    masks_in, masks_out = [], []
    for l in range(k - 1):
        d = n >> (l + 1)
        m_in, m_out, perm = level(
            perm, jnp.int32(d), jnp.int32(max(d.bit_length(), 1))
        )
        masks_in.append(m_in)
        masks_out.append(m_out)
    mid = _compiled(
        "layout.route_mid", _route_mid_program, (perm,), {}, times
    )(perm)
    return jnp.stack(masks_in + [mid] + masks_out[::-1])


# --------------------------------------------------------------------------
# The builder.
# --------------------------------------------------------------------------

def _class_device_tables(classes):
    """The ~60-entry per-class lookup arrays `_base_stride` gathers."""
    return (
        np.array([c.va for c in classes], dtype=np.int32),
        np.array([c.sa for c in classes], dtype=np.int32),
        np.array([c.count for c in classes], dtype=np.int32),
        np.array([c.width for c in classes], dtype=np.int32),
        np.array([c.vertex_major for c in classes], dtype=bool),
    )


def _va_by_widx(classes, widths) -> np.ndarray:
    """Class slot start per candidate-width index (0 where absent)."""
    cmap = _width_class_map(classes, widths)
    out = np.zeros(_CANDIDATES.shape[0], dtype=np.int32)
    for wv in np.asarray(widths).tolist():
        out[int(np.searchsorted(_CANDIDATES, wv))] = cmap[int(wv)].va
    return out


def _stage_table(n: int, nz: np.ndarray) -> tuple[StageSpec, ...]:
    """StageSpec tuple from the compaction program's per-stage nonzero
    ranges, with the host builder's 1024-word block quantization where the
    stored word count is block-aligned."""
    table = []
    offset = 0
    for s in range(benes.num_stages(n)):
        d = benes.stage_distance(n, s)
        compact = d >= COMPACT_MIN_D
        nwords = n // 64 if compact else n // 32
        lo, hi = int(nz[s, 0]), int(nz[s, 1])
        if nwords % 1024 == 0 and hi > 0:
            lo = (lo // 1024) * 1024
            hi = ((hi - 1) // 1024 + 1) * 1024
        table.append(
            StageSpec(
                d=d, offset=offset, nwords=nwords, compact=compact,
                lo=lo, hi=hi,
            )
        )
        offset += nwords
    return tuple(table)


def _route_and_compact(perm, n, route, arm, name, times, _capture):
    """Route one network and compact its masks (either arms).  Runs on the
    caller's thread — the builder overlaps the big-net call with the
    vperm/CSR work on the main thread.  IR capture records the NET
    network's programs only: both tracks share these program names, and a
    last-writer-wins race between threads would make the captured operand
    shapes nondeterministic."""
    from ..obs.spans import span as obs_span

    if name != "net":
        _capture = None

    with obs_span(f"layout.device.route_{name}"), _phase(f"dev {name} route"):
        t0 = time.perf_counter()
        if route == "native":
            masks_full = benes.route_std(np.asarray(perm), trusted=True)
        else:
            masks_full = jax.block_until_ready(
                route_masks_device(perm, n=n, times=times, _capture=_capture)
            )
        times[f"route_{name}"] = time.perf_counter() - t0
    with _phase(f"dev {name} compact"):
        t0 = time.perf_counter()
        if arm == "xla":
            args = (jnp.asarray(masks_full),)
            if _capture is not None:
                _capture["layout.device_compact"] = (
                    _compact_program, args, dict(n=n)
                )
            exe = _compiled(
                "layout.device_compact", _compact_program, args,
                dict(n=n), times,
            )
            masks_d, nz = jax.block_until_ready(exe(*args))
            masks, table = np.asarray(masks_d), _stage_table(
                n, np.asarray(nz)
            )
        else:
            masks, table = _compact_and_table(np.asarray(masks_full), n)
        times[f"compact_{name}"] = time.perf_counter() - t0
    return masks, table


def build_relay_graph_device(
    graph: Graph | DeviceGraph,
    *,
    route: str | None = None,
    segments: str | None = None,
    stage_times: dict | None = None,
    _capture: dict | None = None,
) -> RelayGraph:
    """Build the full relay layout with the device pipeline (see module
    docstring).  Bit-compatible with :func:`relay.build_relay_graph`:
    identical classes/slots/permutations always; identical masks under the
    ``native`` route arm.

    ``stage_times`` (optional dict) is filled with per-stage wall seconds
    plus ``compile_seconds`` (first-touch program compiles, amortized per
    shape by the in-process memo and the persistent compilation cache) and
    the resolved ``route``/``segments`` arms.  ``_capture`` collects each
    XLA program's (fn, args, statics) for the analysis/ir registry.
    """
    _ensure_build_log()
    times: dict[str, Any] = stage_times if stage_times is not None else {}
    route = resolve_route(route)
    arm = "xla" if _capture is not None else resolve_segments(segments)
    times["route"] = route
    times["segments"] = arm
    times.setdefault("compile_seconds", 0.0)
    if route == "native" and not benes.native_available():
        raise RuntimeError("route='native' needs the native benes router")

    from ..obs.spans import span as obs_span

    def staged(name, program, args, statics):
        if _capture is not None:
            _capture[name] = (program, args, statics)
        exe = _compiled(name, program, args, statics, times)
        with _phase(f"dev {name}"):
            t0 = time.perf_counter()
            out = jax.block_until_ready(exe(*args))
            times[name] = times.get(name, 0.0) + time.perf_counter() - t0
        return out

    def timed(name, fn):
        with _phase(f"dev {name}"):
            t0 = time.perf_counter()
            out = fn()
            times[name] = times.get(name, 0.0) + time.perf_counter() - t0
        return out

    # ---- ingest + classes (shapes for everything later) --------------------
    t0 = time.perf_counter()
    src_h, dst_h, v, e = extract_edges(graph)
    if arm == "xla":
        src = jax.device_put(src_h)
        dst = jax.device_put(dst_h)
        cand = jax.device_put(_CANDIDATES)
    times["ingest"] = time.perf_counter() - t0

    if arm == "xla":
        in_widx, out_widx, in_hist, out_hist = staged(
            "layout.device_hist", _degree_hist_program,
            (src, dst, cand), dict(num_vertices=v),
        )
        t0 = time.perf_counter()
        in_hist = np.asarray(in_hist)
        out_hist = np.asarray(out_hist)
        if int(in_hist.sum()) != v or int(out_hist.sum()) != v:
            raise RuntimeError(
                "graph degree exceeds the device builder's 2^30 width "
                "table; use the host builder"
            )
        in_w = out_w = None
        # The device histograms ARE the per-width counts; the class/sizing
        # math lives in ONE place (`seg_classes_from_counts`) shared with
        # the host builder.
        meta = seg_classes_from_counts(
            _CANDIDATES[in_hist > 0].astype(np.int64),
            in_hist[in_hist > 0].astype(np.int64),
            _CANDIDATES[out_hist > 0].astype(np.int64),
            out_hist[out_hist > 0].astype(np.int64),
            v,
        )
        times["classes"] = time.perf_counter() - t0
    else:
        in_w, out_w = timed("degrees", lambda: seg_degrees(src_h, dst_h, v))
        meta = timed("classes", lambda: seg_classes(in_w, out_w, v))

    # ---- relabel ------------------------------------------------------------
    if arm == "xla":
        in_va = jax.device_put(_va_by_widx(meta.in_classes, meta.widths))
        out_va = jax.device_put(_va_by_widx(meta.out_classes, meta.owidths))
        in_tabs = tuple(
            jax.device_put(a) for a in _class_device_tables(meta.in_classes)
        )
        out_tabs = tuple(
            jax.device_put(a) for a in _class_device_tables(meta.out_classes)
        )
        new2old, old2new, outpos_of_old = staged(
            "layout.device_relabel", _relabel_program,
            (in_widx, out_widx, in_va, out_va), dict(vr=meta.vr),
        )
    else:
        new2old, old2new, outpos_of_old = timed(
            "relabel", lambda: seg_relabel(in_w, out_w, meta)
        )

    # ---- overlapped tail: net route || (vperm network + sparse CSR) --------
    # The big-net chain (L1/L2 slots -> net assembly -> route -> compact)
    # stays on the MAIN thread — the critical path never waits on a thread
    # handoff.  A worker builds/routes/compacts the vperm network and the
    # sparse CSR — everything the sequential host builder serializes after
    # the net route — but is GATED on the net route actually starting: the
    # route is walker-bound on one core, so that window is when a second
    # core is genuinely free (running the worker any earlier measurably
    # inflates the critical path's own slot sorts on a 2-core build host —
    # memory-bandwidth contention, not CPU).
    box: dict[str, Any] = {}
    route_started = threading.Event()

    def tail_track():
        route_started.wait()
        if "slots" not in box:
            return  # main track failed before reaching its route
        src_l1, l1_by_edge, dstn, srcn = box["slots"]
        if arm == "xla":
            vperm = staged(
                "layout.device_vperm_assembly", _vperm_assembly_program,
                (outpos_of_old, old2new),
                dict(vp=meta.vp, vr=meta.vr, out_vb=meta.out_vb),
            )
        else:
            vperm = timed(
                "vperm_assembly",
                lambda: seg_vperm_assembly(outpos_of_old, old2new, meta),
            )
        box["vperm"] = _route_and_compact(
            vperm, meta.vp, route, arm, "vperm", times, _capture
        )
        if arm == "xla":
            box["csr"] = staged(
                "layout.device_csr", _csr_program,
                (srcn, dstn, l1_by_edge), dict(vr=meta.vr),
            )
        else:
            def host_csr():
                sn = _gather(old2new, src_h)
                return seg_csr(sn, dstn, l1_by_edge, meta)

            box["csr"] = timed("csr", host_csr)

    # The hold sizes for BOTH routes (n + vp, not the host builder's
    # sequential max): the vperm route runs INSIDE the net route's window
    # here, and an exhausted pool would silently drop the second route's
    # MAP_HUGETLB mapping to 4K pages — losing the measured +21-26% router
    # speedup on exactly the cold path this builder exists to speed up.
    with benes.hugepage_reservation(meta.n + meta.vp):
        worker = _TRACK_POOL.submit(tail_track)
        try:
            if arm == "xla":
                src_l1, l1_by_edge, l2_by_edge, dstn, srcn = staged(
                    "layout.device_slots", _slots_program,
                    (src, dst, old2new, outpos_of_old, *in_tabs, *out_tabs),
                    dict(m1=meta.m1),
                )
                box["slots"] = (src_l1, l1_by_edge, dstn, srcn)
                net = staged(
                    "layout.device_net_assembly", _net_assembly_program,
                    (l1_by_edge, l2_by_edge), dict(n=meta.n),
                )
            else:
                src_l1, l1_by_edge, dstn = timed(
                    "slots_l1",
                    lambda: seg_l1_slots(src_h, dst_h, old2new, meta),
                )
                box["slots"] = (src_l1, l1_by_edge, dstn, None)
                l2_by_edge = timed(
                    "slots_l2",
                    lambda: seg_l2_slots(src_h, outpos_of_old, meta),
                )
                net = timed(
                    "net_assembly",
                    lambda: seg_net_assembly(l1_by_edge, l2_by_edge, meta),
                )
            route_started.set()
            net_masks, net_table = _route_and_compact(
                net, meta.n, route, arm, "net", times, _capture
            )
        except BaseException:
            # Unblock + drain the worker WITHOUT masking the main-track
            # error (its own failure, if any, is secondary here).
            route_started.set()
            concurrent.futures.wait([worker])
            raise
        worker.result()  # join; re-raises a worker-track failure
    vperm_masks, vperm_table = box["vperm"]
    adj_indptr, adj_dst, adj_slot = box["csr"]

    # ---- finalize: host-resident dataclass ---------------------------------
    def finalize():
        return RelayGraph(
            num_vertices=v,
            num_edges=e,
            vr=meta.vr,
            new2old=np.asarray(new2old),
            old2new=np.asarray(old2new),
            vperm_masks=np.asarray(vperm_masks),
            vperm_table=vperm_table,
            vperm_size=meta.vp,
            out_classes=meta.out_classes,
            out_space=meta.out_vb,
            net_masks=np.asarray(net_masks),
            net_table=net_table,
            net_size=meta.n,
            m1=meta.m1,
            m2=meta.m2,
            in_classes=meta.in_classes,
            src_l1=np.asarray(src_l1),
            adj_indptr=np.asarray(adj_indptr, dtype=np.int32),
            adj_dst=np.asarray(adj_dst),
            adj_slot=np.asarray(adj_slot),
        )

    return timed("finalize", finalize)


def ir_operands(graph: Graph | DeviceGraph) -> dict:
    """name -> (fn, args, statics) for every device-builder XLA program at
    ``graph``'s scale — the analysis/ir registry lowers exactly these
    (route=jax so the router programs are captured without the native
    dependency)."""
    capture: dict = {}
    build_relay_graph_device(graph, route="jax", _capture=capture)
    return capture
