"""Bit-packed tiled adjacency for the MXU expansion arm (ISSUE 15).

The relay pipeline expands the frontier through Beneš bit routing — dense,
gather-free, but every bit moves on the VPU while the MXU sits idle.  BLEST
(arxiv 2512.21967) and "Graph Traversal on Tensor Cores" (arxiv 2606.05081)
both reformulate dense-frontier expansion as tiled boolean matrix products
over bit-packed adjacency tiles; this module is the LAYOUT half of that
arm (ops/relay_mxu.py is the kernel half).

Geometry (all in the RELAY relabeled id space — the frontier words the
fused programs already carry feed the tiles directly, no repacking):

  * a **tile** is a 128 (src rows) x 128 (dst bits) block of the adjacency
    matrix, stored bit-packed as ``uint32[128, 4]`` — tile ``t``, row
    ``i``, word ``j``, bit ``b`` set iff edge
    ``(u = row_idx[t]*128 + i,  v = col_id[t]*128 + 32*j + b)`` exists.
    2 KB per stored tile; EMPTY tiles are never stored (CSR-of-tiles), so
    the layout costs ``nt * 2 KB`` where ``nt`` is the number of nonempty
    128x128 blocks — dense/community graphs sit near the bitmap floor,
    scale-free tails degrade toward one tile per edge (the budget gate in
    ops/relay_mxu.resolve_expansion is what keeps a hostile graph from
    OOMing the arm into existence).
  * tiles are sorted by ``(col_id, row_idx)`` and grouped into **column
    superblocks** of 128 column-tiles (= 16384 destinations = one 128x128
    uint32 output block, the MXU-aligned unit the kernel's grid walks);
    ``sb_indptr[g]`` bounds superblock ``g``'s tile span.
  * ``keys2d[rb, i]`` is the ORIGINAL id of src row ``u = rb*128 + i`` as
    uint32 (``PACKED_SENTINEL`` at relabel dummies and padding) — the
    candidate VALUE the expansion emits per destination is the MINIMUM
    original id over contributing frontier sources, i.e. exactly the
    canonical min-parent every engine and the oracle share.  One extra
    all-sentinel row block (and one all-zero frontier pad block) backs the
    ``row_idx = row_blocks`` padding convention.

The host builder is the PINNED ORACLE; the device builder runs the heavy
per-edge stages (tile coding, the (col, row, bit) sort, dedup flags) as
jitted XLA programs and is bit-identical to it (tests/test_expansion_mxu).
Bundles are stored as a SIDECAR next to the relay layout bundle
(cache/layout.load_or_build_tiles) with the same byte-stable conventions —
the relay bundle schema itself is untouched.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

#: Tile geometry: 128 src rows x 128 dst bits (4 uint32 words per row).
TILE = 128
TILE_WORDS = TILE // 32
#: Column-superblock: 128 column-tiles = one (128, 128) uint32 output
#: block per kernel grid step — the MXU-aligned unit (PAL002 mxu=True).
SB_TILES = 128
SB_VERTS = SB_TILES * TILE  # 16384 destinations

#: Unreached/min-identity sentinel — the packed-state lattice top
#: (ops/packed.PACKED_SENTINEL), redeclared as a plain numpy scalar so this
#: module never imports jax at layout-build time.
KEY_SENTINEL = np.uint32(0xFFFFFFFF)

TILES_VERSION = 1


def round_up(x: int, m: int) -> int:
    return -(-int(x) // m) * m


@dataclass(frozen=True)
class AdjTiles:
    """CSR-of-tiles adjacency for one expansion target.

    ``cols`` is the destination id space (single-chip: the relay ``vr``;
    sharded: the shard's owned ``block``); ``rows`` the source id space
    (single-chip ``vr``, sharded the GLOBAL ``n*block``).  ``vtp``/``rtp``
    are their 16384-/128-padded extents; ``nt`` the real tile count
    (arrays are padded to ``ntp >= 1`` with inert tiles whose ``row_idx``
    points at the guaranteed-zero frontier pad block and whose ``col_id``
    is the dropped overflow segment)."""

    rows: int
    cols: int
    rtp: int
    vtp: int
    nt: int
    tiles: np.ndarray  # uint32[ntp, TILE, TILE_WORDS]
    row_idx: np.ndarray  # int32[ntp]; pad = rtp // TILE
    col_id: np.ndarray  # int32[ntp]; pad = vtp // TILE
    sb_indptr: np.ndarray  # int32[vtp // SB_VERTS + 1]
    keys2d: np.ndarray  # uint32[rtp // TILE + 1, TILE]

    @property
    def ntp(self) -> int:
        return int(self.tiles.shape[0])

    @property
    def nbytes(self) -> int:
        return int(
            self.tiles.nbytes + self.row_idx.nbytes + self.col_id.nbytes
            + self.sb_indptr.nbytes + self.keys2d.nbytes
        )


def keys_from_new2old(new2old: np.ndarray, rows: int) -> np.ndarray:
    """uint32[rtp//TILE + 1, TILE] original-id key table: ``new2old``
    where real, ``KEY_SENTINEL`` at dummies/padding, one extra sentinel
    pad block (the ``row_idx`` padding target)."""
    rtp = round_up(rows, TILE)
    n2o = np.asarray(new2old)
    keys = np.full(rtp + TILE, KEY_SENTINEL, dtype=np.uint32)
    real = n2o >= 0
    keys[: n2o.shape[0]][real] = n2o[real].astype(np.uint32)
    return keys.reshape(-1, TILE)


def _finalize(
    rows: int, cols: int, nt: int,
    tiles: np.ndarray, row_idx: np.ndarray, col_id: np.ndarray,
    keys2d: np.ndarray,
) -> AdjTiles:
    """Shared tail of both builders: pad to ``ntp >= 1`` with inert tiles
    and derive the superblock index.  Everything here is a deterministic
    function of the sorted tile list, so host and device arms converge to
    byte-identical arrays."""
    rtp = round_up(rows, TILE)
    vtp = round_up(max(cols, 1), SB_VERTS)
    if nt == 0:
        tiles = np.zeros((1, TILE, TILE_WORDS), dtype=np.uint32)
        row_idx = np.array([rtp // TILE], dtype=np.int32)
        col_id = np.array([vtp // TILE], dtype=np.int32)
    sb = np.searchsorted(
        np.asarray(col_id[:max(nt, 0)]) // SB_TILES,
        np.arange(vtp // SB_VERTS + 1),
        side="left",
    ).astype(np.int32)
    return AdjTiles(
        rows=int(rows), cols=int(cols), rtp=rtp, vtp=vtp, nt=int(nt),
        tiles=np.ascontiguousarray(tiles, dtype=np.uint32),
        row_idx=np.ascontiguousarray(row_idx, dtype=np.int32),
        col_id=np.ascontiguousarray(col_id, dtype=np.int32),
        sb_indptr=sb,
        keys2d=np.ascontiguousarray(keys2d, dtype=np.uint32),
    )


def _check_budget(nt: int, budget_bytes: int | None) -> None:
    need = int(nt) * TILE * TILE_WORDS * 4
    if budget_bytes is not None and need > budget_bytes:
        raise ValueError(
            f"adjacency tile layout needs {need >> 20} MB "
            f"({nt} tiles x 2 KB), over the {budget_bytes >> 20} MB "
            "budget (BFS_TPU_MXU_TILE_GB) — a scale-free tail this "
            "sparse belongs on the gather arm"
        )


def build_adj_tiles_host(
    src: np.ndarray, dst: np.ndarray, *, rows: int, cols: int,
    keys2d: np.ndarray, budget_bytes: int | None = None,
) -> AdjTiles:
    """THE pinned oracle builder: (src, dst) edge lists (relay-space ids,
    ``src < rows``, ``dst < cols``) -> the tiled layout.  Duplicate edges
    OR onto the same bit, so multigraph inputs are handled identically to
    the device arm's dedup pass.  ``budget_bytes`` rejects (before the
    tile allocation) layouts whose nonempty-tile count would exceed it."""
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    if src.shape[0] == 0:
        return _finalize(rows, cols, 0, None, None, None, keys2d)
    cb = dst >> 7
    rb = src >> 7
    code = cb * (round_up(rows, TILE) // TILE + 1) + rb
    order = np.argsort(code, kind="stable")
    cs = code[order]
    first = np.concatenate([[True], cs[1:] != cs[:-1]])
    tile_of = np.cumsum(first) - 1
    nt = int(tile_of[-1]) + 1
    _check_budget(nt, budget_bytes)
    row_idx = rb[order][first].astype(np.int32)
    col_id = cb[order][first].astype(np.int32)
    tiles = np.zeros(nt * TILE * TILE_WORDS, dtype=np.uint32)
    i = src[order] & (TILE - 1)
    vloc = dst[order] & (TILE - 1)
    flat = tile_of * (TILE * TILE_WORDS) + i * TILE_WORDS + (vloc >> 5)
    np.bitwise_or.at(tiles, flat, np.uint32(1) << (vloc & 31).astype(np.uint32))
    return _finalize(
        rows, cols, nt, tiles.reshape(nt, TILE, TILE_WORDS), row_idx,
        col_id, keys2d,
    )


def build_adj_tiles_device(
    src: np.ndarray, dst: np.ndarray, *, rows: int, cols: int,
    keys2d: np.ndarray, budget_bytes: int | None = None,
) -> AdjTiles:
    """Device arm: the per-edge heavy stages — tile coding, the
    (col_tile, row_tile, in-tile bit) sort, the first-of-tile and
    duplicate-edge flags, and the bit scatter — run as jitted XLA
    programs (PR 10 builder-pipeline style: one trace per shape via the
    module jit cache); only the data-dependent ``nt`` is read back
    between the two programs.  Bit-identical to the host oracle."""
    import jax.numpy as jnp

    src = np.asarray(src, dtype=np.int32)
    dst = np.asarray(dst, dtype=np.int32)
    if src.shape[0] == 0:
        return _finalize(rows, cols, 0, None, None, None, keys2d)
    cb_s, rb_s, lb_s, first, dup = [
        np.asarray(a) for a in _dev_sort(jnp.asarray(src), jnp.asarray(dst))
    ]
    nt = int(first.sum())
    _check_budget(nt, budget_bytes)
    tile_of = np.cumsum(first) - 1
    row_idx = rb_s[first.astype(bool)].astype(np.int32)
    col_id = cb_s[first.astype(bool)].astype(np.int32)
    tiles = np.asarray(
        _dev_scatter(
            jnp.asarray(tile_of.astype(np.int32)), jnp.asarray(lb_s),
            jnp.asarray(dup), nt,
        )
    )
    return _finalize(
        rows, cols, nt, tiles.reshape(nt, TILE, TILE_WORDS), row_idx,
        col_id, keys2d,
    )


_DEV_CACHE: dict = {}


def _dev_sort(src, dst):
    """Jitted sort stage: (col_tile, row_tile, in-tile bit id) three-key
    sort + first-of-tile and exact-duplicate flags.  int32 keys only —
    the flat tile code overflows int32 at scale, which is exactly why
    this is a multi-key ``lax.sort`` and not a coded argsort."""
    import jax
    import jax.numpy as jnp

    fn = _DEV_CACHE.get("sort")
    if fn is None:

        def _sort(src, dst):
            cb = dst >> 7
            rb = src >> 7
            lb = (src & (TILE - 1)) * TILE + (dst & (TILE - 1))
            cb_s, rb_s, lb_s = jax.lax.sort((cb, rb, lb), num_keys=3)
            newt = jnp.concatenate(
                [
                    jnp.ones(1, jnp.int32),
                    (
                        (cb_s[1:] != cb_s[:-1]) | (rb_s[1:] != rb_s[:-1])
                    ).astype(jnp.int32),
                ]
            )
            dup = jnp.concatenate(
                [
                    jnp.zeros(1, jnp.int32),
                    (
                        (cb_s[1:] == cb_s[:-1])
                        & (rb_s[1:] == rb_s[:-1])
                        & (lb_s[1:] == lb_s[:-1])
                    ).astype(jnp.int32),
                ]
            )
            return cb_s, rb_s, lb_s, newt, dup

        fn = jax.jit(_sort)
        _DEV_CACHE["sort"] = fn
    return fn(src, dst)


def _dev_scatter(tile_of, lb_s, dup, nt: int):
    """Jitted bit-scatter stage: every first-occurrence edge contributes
    ``1 << bit`` to its word — after dedup the bits are unique, so a sum
    scatter IS the bitwise OR the oracle computes."""
    import jax
    import jax.numpy as jnp

    fn = _DEV_CACHE.get("scatter")
    if fn is None:

        def _scatter(tile_of, lb_s, dup, nt):
            word = tile_of * (TILE * TILE_WORDS) + (
                (lb_s // TILE) * TILE_WORDS + ((lb_s % TILE) >> 5)
            )
            bit = jnp.uint32(1) << (lb_s % TILE & 31).astype(jnp.uint32)
            word = jnp.where(dup == 0, word, nt * TILE * TILE_WORDS)
            return (
                jnp.zeros(nt * TILE * TILE_WORDS, jnp.uint32)
                .at[word]
                .add(jnp.where(dup == 0, bit, 0), mode="drop")
            )

        fn = jax.jit(_scatter, static_argnums=(3,))
        _DEV_CACHE["scatter"] = fn
    return fn(tile_of, lb_s, dup, nt)


def resolve_tiles_builder(builder: str | None = None) -> str:
    """``BFS_TPU_TILES_BUILD=device|host`` (default device — the PR 10
    convention; host is the pinned oracle)."""
    from .. import knobs

    builder = builder or knobs.get("BFS_TPU_TILES_BUILD")
    if builder not in ("device", "host"):
        raise ValueError(
            f"unknown tiles builder {builder!r}; use device|host"
        )
    return builder


def _relay_edges(rg):
    """(src, dst) relay-relabeled edge arrays from a RelayGraph's sparse
    CSR (adj_indptr rows ascend with relabeled src; adj_dst is the
    relabeled destination)."""
    deg = np.diff(np.asarray(rg.adj_indptr[: rg.vr + 1], dtype=np.int64))
    src = np.repeat(np.arange(rg.vr, dtype=np.int64), deg)
    return src, np.asarray(rg.adj_dst, dtype=np.int64)


def build_adj_tiles_from_relay(
    rg, builder: str | None = None, budget_bytes: int | None = None,
) -> AdjTiles:
    """The single-chip layout: rows == cols == the relay ``vr``; keys are
    ``new2old`` (the candidate the expansion emits is the min ORIGINAL
    id over contributing frontier sources — the canonical parent)."""
    src, dst = _relay_edges(rg)
    keys2d = keys_from_new2old(rg.new2old, rg.vr)
    build = (
        build_adj_tiles_device
        if resolve_tiles_builder(builder) == "device"
        else build_adj_tiles_host
    )
    try:
        return build(
            src, dst, rows=rg.vr, cols=rg.vr, keys2d=keys2d,
            budget_bytes=budget_bytes,
        )
    except ValueError:
        raise  # over-budget is a decision, not an availability failure
    except Exception:
        if build is build_adj_tiles_host:
            raise
        # Same availability contract as the relay device builder: a
        # device-arm failure falls back to the oracle, never to "no arm".
        return build_adj_tiles_host(
            src, dst, rows=rg.vr, cols=rg.vr, keys2d=keys2d,
            budget_bytes=budget_bytes,
        )


def build_adj_tiles_sharded(
    srg, builder: str | None = None, budget_bytes: int | None = None,
) -> list:
    """Per-shard tile layouts for the sharded relay: shard ``s`` owns the
    LOCAL destination block, sources span the GLOBAL relabeled space (the
    all-gathered frontier words are the kernel's input, exactly as for
    the dense Beneš body).  Keys are the global ``new2old``."""
    n = srg.num_shards
    gtot = n * srg.block
    keys2d = keys_from_new2old(srg.new2old, gtot)
    build = (
        build_adj_tiles_device
        if resolve_tiles_builder(builder) == "device"
        else build_adj_tiles_host
    )
    out = []
    for s in range(n):
        indptr = np.asarray(srg.adj_indptr[s], dtype=np.int64)
        deg = np.diff(indptr[: gtot + 1])
        src = np.repeat(np.arange(gtot, dtype=np.int64), deg)
        dst = np.asarray(srg.adj_dst[s], dtype=np.int64)[: src.shape[0]]
        try:
            at = build(
                src, dst, rows=gtot, cols=srg.block, keys2d=keys2d,
                budget_bytes=budget_bytes,
            )
        except ValueError:
            raise
        except Exception:
            if build is build_adj_tiles_host:
                raise
            at = build_adj_tiles_host(
                src, dst, rows=gtot, cols=srg.block, keys2d=keys2d,
                budget_bytes=budget_bytes,
            )
        out.append(at)
    return out


def num_superblocks(at: AdjTiles) -> int:
    """Column-superblock count of a layout (one 16384-destination output
    block each — the kernel grid extent AND the streaming transfer unit
    of bfs_tpu/stream)."""
    return int(at.vtp // SB_VERTS)


def sb_span(at: AdjTiles, g: int) -> tuple[int, int]:
    """Tile span ``[lo, hi)`` of column superblock ``g``.  Spans cover
    REAL tiles only: padding tiles carry ``col_id = vtp // TILE`` (the
    dropped overflow segment), which searchsorted places past every
    span — ``sb_indptr[num_superblocks] == nt``."""
    return int(at.sb_indptr[g]), int(at.sb_indptr[g + 1])


def sb_row_blocks(at: AdjTiles, g: int) -> np.ndarray:
    """Ascending unique frontier ROW BLOCKS superblock ``g``'s tiles
    read (``row_idx`` values, each naming one 4-word block of the padded
    frontier).  This is the demand-derivation input of the streamed arm:
    the kernel's per-tile early-out skips a tile iff its frontier block
    is all zero, so a superblock whose every row block is dead is — by
    the same predicate — untouched, and its 2 KB tiles need never reach
    HBM."""
    lo, hi = sb_span(at, g)
    return np.unique(np.asarray(at.row_idx[lo:hi]))


def tile_occupancy_hist(at: AdjTiles) -> dict:
    """Per-tile set-bit histogram over power-of-two buckets — the density
    evidence the bench ships in ``details.expansion`` (a layout living in
    the 1-16 bucket is one-edge-per-tile scale-free tail; 4096+ is the
    dense-community regime the MXU arm exists for)."""
    pops = np.array(
        [
            int(np.unpackbits(t.view(np.uint8)).sum())
            for t in np.asarray(at.tiles[: max(at.nt, 0)])
        ],
        dtype=np.int64,
    )
    edges = [1, 16, 64, 256, 1024, 4096, TILE * TILE + 1]
    hist = {}
    for lo, hi in zip(edges[:-1], edges[1:]):
        hist[f"{lo}-{hi - 1}"] = int(((pops >= lo) & (pops < hi)).sum())
    return {
        "tiles": int(at.nt),
        "tile_bytes": int(at.nt) * TILE * TILE_WORDS * 4,
        "edge_bits": int(pops.sum()) if pops.size else 0,
        "mean_fill": float(pops.mean() / (TILE * TILE)) if pops.size else 0.0,
        "buckets": hist,
    }


# --------------------------------------------------------------------------
# Byte-stable sidecar schema (cache/layout.load_or_build_tiles stores these
# next to — never inside — the relay layout bundle).
# --------------------------------------------------------------------------

def tiles_to_arrays(at: AdjTiles) -> dict[str, np.ndarray]:
    return {
        "dims": np.array(
            [TILES_VERSION, at.rows, at.cols, at.rtp, at.vtp, at.nt],
            dtype=np.int64,
        ),
        "tiles": at.tiles,
        "row_idx": at.row_idx,
        "col_id": at.col_id,
        "sb_indptr": at.sb_indptr,
        "keys2d": at.keys2d,
    }


def tiles_from_arrays(z) -> AdjTiles:
    dims = np.asarray(z["dims"])
    if int(dims[0]) != TILES_VERSION:
        raise ValueError(f"adj-tiles schema version {int(dims[0])}")
    return AdjTiles(
        rows=int(dims[1]), cols=int(dims[2]), rtp=int(dims[3]),
        vtp=int(dims[4]), nt=int(dims[5]),
        tiles=np.asarray(z["tiles"], dtype=np.uint32),
        row_idx=np.asarray(z["row_idx"], dtype=np.int32),
        col_id=np.asarray(z["col_id"], dtype=np.int32),
        sb_indptr=np.asarray(z["sb_indptr"], dtype=np.int32),
        keys2d=np.asarray(z["keys2d"], dtype=np.uint32),
    )
