"""ctypes bindings for the native data-loader (native/graph_gen.cpp).

Accelerated host-side graph plumbing: R-MAT generation, destination-major
edge sorting (what :func:`bfs_tpu.graph.csr.build_device_graph` needs), and
Sedgewick text parsing (GraphFileUtil.java:45-69 / Graph.java:85-94 parity).
Each entry point has a NumPy fallback in :mod:`bfs_tpu.graph.generators` /
:mod:`bfs_tpu.graph.io`; callers guard with :func:`native_available`.

NOTE: the native R-MAT uses its own counter-based PRNG, so for a given seed
it produces a *different* (statistically equivalent) graph than the NumPy
generator.  Within one backend, results are deterministic by seed.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from ..utils.native_loader import NativeLib

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_I32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_I64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_U8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _register(lib: ctypes.CDLL) -> None:
    lib.rmat_edges.restype = None
    lib.rmat_edges.argtypes = [
        ctypes.c_int32, ctypes.c_int64,
        ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ctypes.c_uint64, ctypes.c_int32, _I32, _I32,
    ]
    lib.sort_edges_by_dst.restype = None
    lib.sort_edges_by_dst.argtypes = [ctypes.c_int64, _I32, _I32]
    lib.sort_rank_pairs.restype = None
    lib.sort_rank_pairs.argtypes = [ctypes.c_int64, _I32, _I32, _I32, _I32]
    lib.gather_i32.restype = None
    lib.gather_i32.argtypes = [ctypes.c_int64, _I32, _I32, _I32]
    lib.scatter_i32.restype = None
    lib.scatter_i32.argtypes = [ctypes.c_int64, _I32, _I32, _I32]
    lib.slot_assign_i32.restype = None
    lib.slot_assign_i32.argtypes = [ctypes.c_int64, _I32, _I32, _I32, _I32, _I32]
    lib.rank_by_count.restype = None
    lib.rank_by_count.argtypes = [ctypes.c_int64, _I32, ctypes.c_int64, _I32]
    lib.bincount_i32.restype = None
    lib.bincount_i32.argtypes = [ctypes.c_int64, _I32, ctypes.c_int64, _I32]
    lib.csr_fill.restype = None
    lib.csr_fill.argtypes = [
        ctypes.c_int64, ctypes.c_int64, _I32, _I32, _I32, _I32, _I32, _I32,
    ]
    lib.mark_u8.restype = None
    lib.mark_u8.argtypes = [ctypes.c_int64, _I32, _U8]
    lib.pad_identity_i32.restype = None
    lib.pad_identity_i32.argtypes = [ctypes.c_int64, _I32, _U8]
    lib.sedgewick_header.restype = ctypes.c_int64
    lib.sedgewick_header.argtypes = [ctypes.c_char_p, _I64, _I64]
    lib.sedgewick_edges.restype = ctypes.c_int64
    lib.sedgewick_edges.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, _I32, _I32,
    ]


_LIB = NativeLib(
    src=os.path.join(_REPO_ROOT, "native", "graph_gen.cpp"),
    so=os.path.join(_REPO_ROOT, "native", "build", "libgraph_gen.so"),
    register=_register,
)


def native_available() -> bool:
    return _LIB.available()


def rmat_edges_native(
    scale: int,
    edge_factor: int = 16,
    *,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 1,
    permute_labels: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """Native R-MAT: returns ``(src, dst)`` int32 arrays of the undirected
    endpoint pairs (same contract as generators.rmat_edges, columnar)."""
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    m = edge_factor << scale
    src = np.empty(m, dtype=np.int32)
    dst = np.empty(m, dtype=np.int32)
    lib.rmat_edges(scale, m, a, b, c, seed, int(permute_labels), src, dst)
    return src, dst


def sort_rank_pairs_native(
    key_hi: np.ndarray, key_lo: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Stable sort by ``(key_hi, key_lo)``: returns ``(order, rank)`` where
    ``order[i]`` is the original index of the i-th record in sorted order and
    ``rank[i]`` its position within its run of equal ``key_hi`` values — the
    native replacement for ``np.lexsort`` + ``_rank_within_groups`` in the
    relay layout build (minutes -> seconds at 2*10^8 edges)."""
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    key_hi = np.ascontiguousarray(key_hi, dtype=np.int32)
    key_lo = np.ascontiguousarray(key_lo, dtype=np.int32)
    n = key_hi.shape[0]
    order = np.empty(n, dtype=np.int32)
    rank = np.empty(n, dtype=np.int32)
    lib.sort_rank_pairs(n, key_hi, key_lo, order, rank)
    return order, rank


def gather_i32_native(table: np.ndarray, idx: np.ndarray) -> np.ndarray:
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    table = np.ascontiguousarray(table, dtype=np.int32)
    idx = np.ascontiguousarray(idx, dtype=np.int32)
    out = np.empty(idx.shape[0], dtype=np.int32)
    lib.gather_i32(idx.shape[0], table, idx, out)
    return out


def scatter_i32_native(out: np.ndarray, idx: np.ndarray, val: np.ndarray) -> None:
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    idx = np.ascontiguousarray(idx, dtype=np.int32)
    val = np.ascontiguousarray(val, dtype=np.int32)
    assert out.dtype == np.int32 and out.flags.c_contiguous
    lib.scatter_i32(idx.shape[0], idx, val, out)


def slot_assign_native(base, stride, idx, rank) -> np.ndarray:
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    base = np.ascontiguousarray(base, dtype=np.int32)
    stride = np.ascontiguousarray(stride, dtype=np.int32)
    idx = np.ascontiguousarray(idx, dtype=np.int32)
    rank = np.ascontiguousarray(rank, dtype=np.int32)
    out = np.empty(idx.shape[0], dtype=np.int32)
    lib.slot_assign_i32(idx.shape[0], base, stride, idx, rank, out)
    return out


def rank_by_count_native(key: np.ndarray, nk: int) -> np.ndarray:
    """rank[i] = number of earlier records with the same key — the
    arbitrary-within-group rank used where ordering is free (L2 slots)."""
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    key = np.ascontiguousarray(key, dtype=np.int32)
    out = np.empty(key.shape[0], dtype=np.int32)
    lib.rank_by_count(key.shape[0], key, int(nk), out)
    return out


def bincount_i32_native(key: np.ndarray, nk: int) -> np.ndarray:
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    key = np.ascontiguousarray(key, dtype=np.int32)
    out = np.empty(int(nk), dtype=np.int32)
    lib.bincount_i32(key.shape[0], key, int(nk), out)
    return out


def csr_fill_native(srcn, dstn, slotv, nk: int):
    """Counting-sort CSR: returns (indptr int32[nk+2], adj_dst, adj_slot)
    grouped by srcn with arbitrary within-row order."""
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    srcn = np.ascontiguousarray(srcn, dtype=np.int32)
    dstn = np.ascontiguousarray(dstn, dtype=np.int32)
    slotv = np.ascontiguousarray(slotv, dtype=np.int32)
    n = srcn.shape[0]
    indptr = np.empty(int(nk) + 2, dtype=np.int32)
    adj_dst = np.empty(n, dtype=np.int32)
    adj_slot = np.empty(n, dtype=np.int32)
    lib.csr_fill(n, int(nk), srcn, dstn, slotv, indptr, adj_dst, adj_slot)
    return indptr, adj_dst, adj_slot


def mark_u8_native(idx: np.ndarray, used: np.ndarray) -> None:
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    idx = np.ascontiguousarray(idx, dtype=np.int32)
    assert used.dtype == np.uint8 and used.flags.c_contiguous
    lib.mark_u8(idx.shape[0], idx, used)


def pad_identity_native(perm: np.ndarray, used: np.ndarray) -> None:
    """In-place identity-first bijection completion (see graph/relay.py
    _pad_identity for the routing rationale); ``used`` updated too."""
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    assert perm.dtype == np.int32 and perm.flags.c_contiguous
    assert used.dtype == np.uint8 and used.flags.c_contiguous
    lib.pad_identity_i32(perm.shape[0], perm, used)


def sort_edges_by_dst_native(
    src: np.ndarray, dst: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Stable sort of the (src, dst) pair arrays by (dst, src); returns the
    sorted arrays (in-place when the inputs are already contiguous int32)."""
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    src = np.ascontiguousarray(src, dtype=np.int32)
    dst = np.ascontiguousarray(dst, dtype=np.int32)
    lib.sort_edges_by_dst(src.shape[0], src, dst)
    return src, dst


def read_sedgewick_native(path: str) -> tuple[int, np.ndarray, np.ndarray]:
    """Parse a Sedgewick graph file natively.  Returns ``(V, src, dst)`` with
    the E *undirected* pairs (caller bi-directs, GraphFileUtil.java:64-65)."""
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError("native graph_gen unavailable")
    v = np.zeros(1, dtype=np.int64)
    e = np.zeros(1, dtype=np.int64)
    if lib.sedgewick_header(path.encode(), v, e) != 0:
        raise ValueError(f"malformed Sedgewick header in {path!r}")
    num_v, num_e = int(v[0]), int(e[0])
    src = np.empty(num_e, dtype=np.int32)
    dst = np.empty(num_e, dtype=np.int32)
    got = lib.sedgewick_edges(path.encode(), num_v, num_e, src, dst)
    if got != num_e:
        raise ValueError(f"malformed Sedgewick edge list in {path!r}")
    return num_v, src, dst
