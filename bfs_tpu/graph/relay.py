"""Relay layout v4: degree-class dense adjacency + Beneš-routed bit shuffle.

The fully gather-free BFS data layout.  Measured reality on TPU v5e
(tools/microbench_gather.py): dense vector ops run at memory bandwidth while
every XLA gather/scatter runs at ~0.1 G/s, so the engine may not index by
edge at runtime AT ALL.  Everything data-dependent becomes dense math over
static layouts:

  * **src side (broadcast)** — vertices bucketed by OUT-degree class; a
    vertex's frontier bit is broadcast to its out-edge slots (the mapper
    emitting a candidate per neighbour, BfsSpark.java:73-79, as pure word
    replication).
  * **the shuffle** — per-edge bits move from src-grouped to dst-grouped
    slot order through a bit-packed Beneš network (2*log2 N - 1 dense
    butterfly stages, masks precomputed by native/benes.cpp).  This is the
    reference's `reduceByKey` shuffle (BfsSpark.java:90) compiled into a
    routing circuit.
  * **dst side (reduce)** — vertices bucketed by IN-degree class and
    RELABELED so classes are contiguous; the reducer's min-merge becomes a
    min-active-slot scan per class.  Within a dst row slots ascend by
    ORIGINAL src id, so min slot == canonical min-parent.

v4 changes vs the round-2 layout (LAYOUT_VERSION 3):

  * **Standard (word-major) packing everywhere**: element ``e`` lives at
    (word ``e >> 5``, bit ``e & 31``).  This is what the native router
    emits, so the router's bit-major transpose pass is gone; classes are
    32-aligned so the broadcast becomes pure word replication and the
    row-min a word-level scan — the round-2 pack/unpack kernels disappear.
  * **Pair-compacted masks**: a stage with element distance d only has
    switch bits at the lower index of each pair ((e & d) == 0), so for
    d >= 32*128 the mask rows at (row & (d/4096)) != 0 are structurally
    zero; they are dropped from storage, cutting streamed mask bytes ~29%
    (tools/mask_sparsity.py measurement round 3).
  * **Identity tail**: pad slots beyond max(m1, m2) are wired
    input==output, which the router colors switch-free; each stage stores
    its nonzero word range so kernels skip the dead tail entirely.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple

import numpy as np

from . import benes
from .csr import DeviceGraph, Graph, INF_DIST

#: Bump when the slot ordering / mask layout changes; layout caches
#: (bench.py .bench_cache) key on it.
LAYOUT_VERSION = 4

#: Stages with element distance >= COMPACT_MIN_D store only the words at the
#: lower index of each word pair (see StageSpec.compact).  d >= 4096 makes
#: the word distance >= 128 (a whole 128-lane row), so the compact view is a
#: contiguous row slice — clean for both XLA reshapes and Pallas DMA.
COMPACT_MIN_D = 4096


class StageSpec(NamedTuple):
    """Static per-stage metadata for a stored Beneš network.

    ``d``: element distance of the butterfly.
    ``offset``: word offset of this stage's mask data in the flat array.
    ``nwords``: stored words (n/32 full, n/64 compact).
    ``compact``: pair-compacted storage (only words at (w & d>>5) == 0).
    ``lo``/``hi``: [lo, hi) nonzero word range within the stored words —
    kernels skip blocks outside it (identity-wired tail routes switch-free).
    """

    d: int
    offset: int
    nwords: int
    compact: bool
    lo: int
    hi: int


@dataclass(frozen=True)
class ClassSlice:
    """One degree class: vertices/positions [va, vb) own slots [sa, sb).

    ``count`` is the PADDED count (multiple of 32 for rank-major classes);
    ``real`` the real vertex count; ``width`` the padded slot width.
    Slot layout: rank-major ``slot = sa + r*count + p`` (a word is 32
    consecutive p at one rank — broadcast replicates whole words, row-min
    scans words at stride count/32); vertex-major ``slot = sa + p*width + r``
    (width % 32 == 0 — a row is width/32 consecutive words), used for the few
    huge-width classes where rank-major padding would explode.
    """

    width: int
    va: int
    vb: int  # va + count
    sa: int
    sb: int
    real: int
    vertex_major: bool = False

    @property
    def count(self) -> int:
        return self.vb - self.va


def _class_width(deg: np.ndarray) -> np.ndarray:
    """Degree-class width: degree rounded up to {2^k, 3*2^(k-1)} — one
    mantissa bit instead of pure powers of two.  Worst-case padding stays
    just under 50% vs 100% for pow2; on the scale-24 R-MAT this keeps the
    slot count ~1.25E, which decides whether the Beneš network fits the next
    power of two."""
    x = np.maximum(np.asarray(deg, dtype=np.int64), 1)
    p2 = np.int64(1) << np.int64(
        np.ceil(np.log2(x.astype(np.float64)))
    ).astype(np.int64)
    p2 = np.maximum(p2, 1)
    three_quarter = (p2 // 4) * 3
    return np.where((p2 >= 4) & (x <= three_quarter), three_quarter, p2)


def _pow2_at_least(n: int) -> int:
    n = max(int(n), 32)
    return 1 << (n - 1).bit_length()


def _round32(x: int) -> int:
    return (int(x) + 31) & ~31


def _build_classes(widths: np.ndarray, counts: np.ndarray) -> list[ClassSlice]:
    """Aligned class slices from per-width real counts (widths ascending).

    Vertex-major iff width >= max(count, 32) (few huge-width vertices: pad
    the width to a multiple of 32); otherwise rank-major (pad the count).
    Rank-major classes come first so the padded vertex ranges stay 32-aligned
    even when vertex-major classes have unpadded counts.
    """
    order = np.argsort(widths, kind="stable")
    rank_major = [
        (int(widths[i]), int(counts[i]))
        for i in order
        if not widths[i] >= max(counts[i], 32)
    ]
    vertex_major = [
        (int(widths[i]), int(counts[i]))
        for i in order
        if widths[i] >= max(counts[i], 32)
    ]
    slices: list[ClassSlice] = []
    va = 0
    sa = 0
    for w, c in rank_major:
        cp = _round32(c)
        slices.append(
            ClassSlice(width=w, va=va, vb=va + cp, sa=sa, sb=sa + w * cp,
                       real=c, vertex_major=False)
        )
        va += cp
        sa += w * cp
    for w, c in vertex_major:
        wp = _round32(w)
        slices.append(
            ClassSlice(width=wp, va=va, vb=va + c, sa=sa, sb=sa + wp * c,
                       real=c, vertex_major=True)
        )
        va += c
        sa += wp * c
    return slices


def _sort_rank(key_hi: np.ndarray, key_lo: np.ndarray):
    """(order, rank-within-hi-runs) sorted by (key_hi, key_lo) — native radix
    when available, np.lexsort fallback."""
    try:
        from .native_gen import native_available, sort_rank_pairs_native

        if native_available():
            return sort_rank_pairs_native(key_hi, key_lo)
    except Exception:
        pass
    order = np.lexsort((key_lo, key_hi))
    hs = np.asarray(key_hi)[order]
    n = hs.shape[0]
    if n == 0:
        return order.astype(np.int32), np.zeros(0, np.int32)
    starts = np.flatnonzero(np.concatenate([[True], hs[1:] != hs[:-1]]))
    sor = starts[np.searchsorted(starts, np.arange(n), side="right") - 1]
    return order.astype(np.int32), (np.arange(n) - sor).astype(np.int32)


def _vertex_tables(classes: list[ClassSlice], num_ids: int):
    """Per-(relabeled id / out-position) slot tables: slot(id, r) =
    base[id] + r * stride[id].  Rank-major: base = sa + p, stride = count;
    vertex-major: base = sa + p*width, stride = 1."""
    base = np.zeros(num_ids, dtype=np.int64)
    stride = np.ones(num_ids, dtype=np.int64)
    for cs in classes:
        p = np.arange(cs.count, dtype=np.int64)
        if cs.vertex_major:
            base[cs.va : cs.vb] = cs.sa + p * cs.width
            stride[cs.va : cs.vb] = 1
        else:
            base[cs.va : cs.vb] = cs.sa + p
            stride[cs.va : cs.vb] = cs.count
    return base, stride


def _compact_and_table(
    masks: np.ndarray, n: int
) -> tuple[np.ndarray, tuple[StageSpec, ...]]:
    """Pair-compact the router's word-major masks and build the stage table.

    For each stage with d >= COMPACT_MIN_D, keep only the word rows at
    (row & (d >> 12)) == 0 (the rest are structurally zero: switch bits live
    at the lower pair index).  Also records each stage's nonzero word range
    so appliers can skip the identity-wired tail."""
    nw = n // 32
    stages = masks.shape[0]
    parts = []
    table = []
    offset = 0
    for s in range(stages):
        d = benes.stage_distance(n, s)
        w = masks[s]
        if d >= COMPACT_MIN_D:
            dw = d >> 5
            w = w.reshape(-1, 2, dw)[:, 0, :].reshape(-1)
        nz = np.flatnonzero(
            w.reshape(-1, 1024).any(axis=1)
            if w.shape[0] % 1024 == 0
            else w
        )
        if w.shape[0] % 1024 == 0:
            lo = int(nz[0]) * 1024 if nz.size else 0
            hi = int(nz[-1] + 1) * 1024 if nz.size else 0
        else:
            lo = int(nz[0]) if nz.size else 0
            hi = int(nz[-1] + 1) if nz.size else 0
        parts.append(w)
        table.append(
            StageSpec(d=d, offset=offset, nwords=int(w.shape[0]),
                      compact=d >= COMPACT_MIN_D, lo=lo, hi=hi)
        )
        offset += int(w.shape[0])
    return np.concatenate(parts), tuple(table)


@dataclass(frozen=True)
class RelayGraph:
    """Static relay layout v4 for one graph (single shard).

    All vertex-indexed engine state lives in the RELABELED id space of size
    ``vr`` (``new2old``/``old2new``; -1 at padding dummies); parent VALUES
    are L1 slot indices mapped to original src ids host-side via ``src_l1``.
    """

    num_vertices: int  # real V
    num_edges: int
    vr: int  # padded relabeled vertex space (multiple of 32)
    new2old: np.ndarray  # int32[vr]; -1 at dummies
    old2new: np.ndarray  # int32[V]
    # src side
    vperm_masks: np.ndarray  # uint32 flat
    vperm_table: tuple[StageSpec, ...]
    vperm_size: int
    out_classes: tuple[ClassSlice, ...]  # over out-order positions
    out_space: int  # used out positions (sum of class counts)
    # shuffle
    net_masks: np.ndarray  # uint32 flat
    net_table: tuple[StageSpec, ...]
    net_size: int
    m1: int
    m2: int
    # dst side
    in_classes: tuple[ClassSlice, ...]  # over relabeled vertex space
    src_l1: np.ndarray  # int32[m1] — ORIGINAL src id per L1 slot, INF padding
    # sparse-path adjacency (relabeled CSR with per-edge L1 slot), built lazily
    # by engines that want the hybrid small-frontier path.


def build_relay_graph(graph: Graph | DeviceGraph) -> RelayGraph:
    """Build the full relay layout (host side, once per graph).

    Requires the native Beneš router; raises RuntimeError when unavailable.
    """
    if not benes.native_available():
        raise RuntimeError("relay engine requires the native benes router")
    if isinstance(graph, DeviceGraph):
        if graph.num_shards != 1:
            raise ValueError("build_relay_graph expects a single-shard graph")
        flat_src = graph.src.reshape(-1)
        flat_dst = graph.dst.reshape(-1)
        keep = flat_dst != graph.sentinel
        src = flat_src[keep].astype(np.int64)
        dst = flat_dst[keep].astype(np.int64)
        v = graph.num_vertices
    else:
        src = graph.src.astype(np.int64)
        dst = graph.dst.astype(np.int64)
        v = graph.num_vertices
    e = int(src.shape[0])

    indeg = np.bincount(dst, minlength=v)
    outdeg = np.bincount(src, minlength=v)
    in_w = _class_width(indeg)  # zero-indeg vertices get one INF slot
    out_w = _class_width(outdeg)

    # ---- dst side: aligned classes over the relabeled vertex space --------
    widths, counts = np.unique(in_w, return_counts=True)
    in_classes = _build_classes(widths, counts)
    vr = _round32(in_classes[-1].vb) if in_classes else 32
    m1 = in_classes[-1].sb if in_classes else 0

    # relabel: class-major, old-id-minor; dummies at padded class tails
    new2old = np.full(vr, -1, dtype=np.int64)
    old2new = np.empty(v, dtype=np.int64)
    order = np.argsort(in_w, kind="stable")  # stable: old-id-minor
    width_of_class = {}
    for cs in in_classes:
        width_of_class[(cs.width if not cs.vertex_major else None, cs.va)] = cs
    # assign per class in ascending width order (order is sorted by width)
    pos = 0
    for cs in sorted(in_classes, key=lambda c: c.va):
        ids = order[pos : pos + cs.real]
        new2old[cs.va : cs.va + cs.real] = ids
        old2new[ids] = cs.va + np.arange(cs.real)
        pos += cs.real
    assert pos == v

    # ---- src side: aligned classes over out-order positions ---------------
    owidths, ocounts = np.unique(out_w, return_counts=True)
    out_classes = _build_classes(owidths, ocounts)
    out_space = out_classes[-1].vb if out_classes else 0
    m2 = out_classes[-1].sb if out_classes else 0

    outpos_of_old = np.empty(v, dtype=np.int64)
    oorder = np.argsort(out_w, kind="stable")
    pos = 0
    for cs in sorted(out_classes, key=lambda c: c.va):
        ids = oorder[pos : pos + cs.real]
        outpos_of_old[ids] = cs.va + np.arange(cs.real)
        pos += cs.real
    assert pos == v

    # ---- L1 slots: edges sorted by (dst_new, src); rank = in-row position --
    dstn = old2new[dst]
    order1, rank1 = _sort_rank(dstn.astype(np.int32), src.astype(np.int32))
    base1, stride1 = _vertex_tables(in_classes, vr)
    ds = dstn[order1]
    l1_sorted = base1[ds] + rank1.astype(np.int64) * stride1[ds]
    src_l1 = np.full(m1, INF_DIST, dtype=np.int32)
    src_l1[l1_sorted] = src[order1].astype(np.int32)  # ORIGINAL ids

    # ---- L2 slots: edges sorted by (src out-position, dst) -----------------
    srcpos = outpos_of_old[src]
    order2, rank2 = _sort_rank(srcpos.astype(np.int32), dstn.astype(np.int32))
    base2, stride2 = _vertex_tables(out_classes, out_classes[-1].vb)
    sp = srcpos[order2]
    l2_sorted = base2[sp] + rank2.astype(np.int64) * stride2[sp]

    # ---- big network: L1 slot <- L2 slot -----------------------------------
    n = _pow2_at_least(max(m1, m2))
    net = np.full(n, -1, dtype=np.int64)
    l1_by_edge = np.empty(e, dtype=np.int64)
    l1_by_edge[order1] = l1_sorted
    l2_by_edge = np.empty(e, dtype=np.int64)
    l2_by_edge[order2] = l2_sorted
    net[l1_by_edge] = l2_by_edge
    used = np.zeros(n, dtype=bool)
    used[l2_by_edge] = True
    _pad_identity(net, used, n)
    net_masks_full = benes.route_std(net)
    net_masks, net_table = _compact_and_table(net_masks_full, n)
    del net_masks_full

    # ---- small network: vertex-space words -> out-order words --------------
    # Dummy out positions (padded rank-major class tails) must read zero:
    # wire them to the guaranteed-zero input region [vr, vp).
    out_vb = out_classes[-1].vb
    dummies = out_vb - v
    vp = _pow2_at_least(max(vr + dummies, out_vb, 32 * 128 * 2))
    vperm = np.full(vp, -1, dtype=np.int64)
    real_mask = np.zeros(out_vb, dtype=bool)
    pos = 0
    for cs in sorted(out_classes, key=lambda c: c.va):
        real_mask[cs.va : cs.va + cs.real] = True
        pos += cs.real
    # real out positions <- relabeled id of their vertex
    out_real_positions = np.flatnonzero(real_mask)
    vperm[out_real_positions] = old2new[
        _out_position_owner(out_classes, oorder)
    ]
    dummy_positions = np.flatnonzero(~real_mask)
    vperm[dummy_positions] = vr + np.arange(dummy_positions.shape[0])
    used = np.zeros(vp, dtype=bool)
    used[vperm[vperm >= 0]] = True
    _pad_identity(vperm, used, vp)
    vperm_masks_full = benes.route_std(vperm)
    vperm_masks, vperm_table = _compact_and_table(vperm_masks_full, vp)
    del vperm_masks_full

    return RelayGraph(
        num_vertices=v,
        num_edges=e,
        vr=vr,
        new2old=new2old.astype(np.int32),
        old2new=old2new.astype(np.int32),
        vperm_masks=vperm_masks,
        vperm_table=vperm_table,
        vperm_size=vp,
        out_classes=tuple(out_classes),
        out_space=out_vb,
        net_masks=net_masks,
        net_table=net_table,
        net_size=n,
        m1=m1,
        m2=m2,
        in_classes=tuple(in_classes),
        src_l1=src_l1,
    )


def _out_position_owner(out_classes, oorder: np.ndarray) -> np.ndarray:
    """Original vertex id owning each REAL out position, in ascending
    position order (dummies excluded)."""
    parts = []
    pos = 0
    for cs in sorted(out_classes, key=lambda c: c.va):
        parts.append(oorder[pos : pos + cs.real])
        pos += cs.real
    return np.concatenate(parts) if parts else np.zeros(0, np.int64)


def _pad_identity(perm: np.ndarray, used: np.ndarray, n: int) -> None:
    """Complete a partial mapping to a bijection, wiring free outputs to free
    inputs IDENTITY-first: output j takes input j wherever both are free.
    Identity-wired pads route switch-free through the Beneš coloring, which
    is what makes each stage's tail word range all-zero (StageSpec.lo/hi)."""
    free_out = perm < 0
    both = free_out & ~used
    idx = np.flatnonzero(both)
    perm[idx] = idx
    used[idx] = True
    free_outputs = np.flatnonzero(perm < 0)
    free_inputs = np.flatnonzero(~used)
    if free_outputs.shape[0] != free_inputs.shape[0]:
        raise ValueError("partial permutation is not completable")
    perm[free_outputs] = free_inputs


def valid_slot_words(src_l1: np.ndarray, net_size: int) -> np.ndarray:
    """Static valid-slot bitmask (STANDARD packing): uint32[net_size/32], bit
    set iff that L1 slot holds a real edge.  Beneš pad routing may deliver
    stray 1-bits to padded slots; this mask zeroes them before the row-min."""
    m1 = src_l1.shape[0]
    bits = np.zeros(net_size, dtype=bool)
    bits[:m1] = src_l1 != np.int32(INF_DIST)
    return np.packbits(
        bits.reshape(-1, 32), axis=1, bitorder="little"
    ).view(np.uint32).reshape(-1)
