"""Relay layout v4: degree-class dense adjacency + Beneš-routed bit shuffle.

The fully gather-free BFS data layout.  Measured reality on TPU v5e
(tools/microbench_gather.py): dense vector ops run at memory bandwidth while
every XLA gather/scatter runs at ~0.1 G/s, so the engine may not index by
edge at runtime AT ALL.  Everything data-dependent becomes dense math over
static layouts:

  * **src side (broadcast)** — vertices bucketed by OUT-degree class; a
    vertex's frontier bit is broadcast to its out-edge slots (the mapper
    emitting a candidate per neighbour, BfsSpark.java:73-79, as pure word
    replication).
  * **the shuffle** — per-edge bits move from src-grouped to dst-grouped
    slot order through a bit-packed Beneš network (2*log2 N - 1 dense
    butterfly stages, masks precomputed by native/benes.cpp).  This is the
    reference's `reduceByKey` shuffle (BfsSpark.java:90) compiled into a
    routing circuit.
  * **dst side (reduce)** — vertices bucketed by IN-degree class and
    RELABELED so classes are contiguous; the reducer's min-merge becomes a
    min-active-slot scan per class.  Within a dst row slots ascend by
    ORIGINAL src id, so min slot == canonical min-parent.

v4 changes vs the round-2 layout (LAYOUT_VERSION 3):

  * **Standard (word-major) packing everywhere**: element ``e`` lives at
    (word ``e >> 5``, bit ``e & 31``).  This is what the native router
    emits, so the router's bit-major transpose pass is gone; classes are
    32-aligned so the broadcast becomes pure word replication and the
    row-min a word-level scan — the round-2 pack/unpack kernels disappear.
  * **Pair-compacted masks**: a stage with element distance d only has
    switch bits at the lower index of each pair ((e & d) == 0), so for
    d >= 32*128 the mask rows at (row & (d/4096)) != 0 are structurally
    zero; they are dropped from storage, cutting streamed mask bytes ~29%
    (tools/mask_sparsity.py measurement round 3).
  * **Identity tail**: pad slots beyond max(m1, m2) are wired
    input==output and each stage stores its nonzero word range so kernels
    can skip dead blocks.  NOTE: pads route switch-free only where BOTH
    members of a top-stage pair are pads (live <= n/2); at the bench's
    m1 ~ 0.94n the ranges rarely shrink — the real mask-byte win is the
    pair compaction above.
"""

from __future__ import annotations

import logging
import time as _time
from dataclasses import dataclass
from typing import NamedTuple

import numpy as np

logger = logging.getLogger(__name__)

# BFS_TPU_BUILD_LOG=1 turns on the per-phase build timing logs without the
# caller configuring logging (a bare handler at INFO on this module only).
# Checked lazily at each build so callers that set the flag after this
# module is first imported (e.g. a process that imports relay early and
# decides on logging later, as bench.main does) still get the stamps.
# Reversible: setting BFS_TPU_BUILD_LOG=0 (or unsetting it) before the next
# build removes the handler and resets the level, and the install/remove is
# lock-guarded so concurrent first builds cannot double-install the handler.
_build_log_lock = __import__("threading").Lock()
_build_log_handler: logging.Handler | None = None
_build_log_prev_level: int | None = None


def _ensure_build_log():
    global _build_log_handler, _build_log_prev_level
    from .. import knobs

    enabled = knobs.get("BFS_TPU_BUILD_LOG")
    with _build_log_lock:
        if enabled:
            if _build_log_handler is None:
                _h = logging.StreamHandler()
                _h.setFormatter(logging.Formatter("%(asctime)s %(message)s"))
                logger.addHandler(_h)
                _build_log_handler = _h
                _build_log_prev_level = logger.level
            logger.setLevel(logging.INFO)
        elif _build_log_handler is not None:
            # Only undo what this latch installed: remove OUR handler and
            # restore the level the logger had before we raised it, so an
            # application-configured handler/level is left untouched.
            logger.removeHandler(_build_log_handler)
            _build_log_handler = None
            logger.setLevel(_build_log_prev_level)
            _build_log_prev_level = None


_ensure_build_log()


class _phase:
    """Build-phase timer: logs at INFO (enable with BFS_TPU_BUILD_LOG=1 or
    logging config) so the <300 s layout-build budget stays accountable."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self.t0 = _time.perf_counter()
        return self

    def __exit__(self, *exc):
        logger.info(
            "layout phase %-22s %.1fs", self.name,
            _time.perf_counter() - self.t0,
        )

from . import benes
from .csr import DeviceGraph, Graph, INF_DIST

#: Bump when the slot ordering / mask layout changes; layout caches
#: (bench.py .bench_cache) key on it.
LAYOUT_VERSION = 4

#: Stages with element distance >= COMPACT_MIN_D store only the words at the
#: lower index of each word pair (see StageSpec.compact).  d >= 4096 makes
#: the word distance >= 128 (a whole 128-lane row), so the compact view is a
#: contiguous row slice — clean for both XLA reshapes and Pallas DMA.
COMPACT_MIN_D = 4096


class StageSpec(NamedTuple):
    """Static per-stage metadata for a stored Beneš network.

    ``d``: element distance of the butterfly.
    ``offset``: word offset of this stage's mask data in the flat array.
    ``nwords``: stored words (n/32 full, n/64 compact).
    ``compact``: pair-compacted storage (only words at (w & d>>5) == 0).
    ``lo``/``hi``: [lo, hi) nonzero word range within the stored words —
    kernels skip blocks outside it (identity-wired tail routes switch-free).
    """

    d: int
    offset: int
    nwords: int
    compact: bool
    lo: int
    hi: int


@dataclass(frozen=True)
class ClassSlice:
    """One degree class: vertices/positions [va, vb) own slots [sa, sb).

    ``count`` is the PADDED count (multiple of 32 for rank-major classes);
    ``real`` the real vertex count; ``width`` the padded slot width.
    Slot layout: rank-major ``slot = sa + r*count + p`` (a word is 32
    consecutive p at one rank — broadcast replicates whole words, row-min
    scans words at stride count/32); vertex-major ``slot = sa + p*width + r``
    (width % 32 == 0 — a row is width/32 consecutive words), used for the few
    huge-width classes where rank-major padding would explode.
    """

    width: int
    va: int
    vb: int  # va + count
    sa: int
    sb: int
    real: int
    vertex_major: bool = False
    real_width: int = -1  # pre-padding width (== width for rank-major)

    @property
    def count(self) -> int:
        return self.vb - self.va


def _class_width(deg: np.ndarray) -> np.ndarray:
    """Degree-class width: degree rounded up to {2^k, 3*2^(k-1)} — one
    mantissa bit instead of pure powers of two.  Worst-case padding stays
    just under 50% vs 100% for pow2; on the scale-24 R-MAT this keeps the
    slot count ~1.25E, which decides whether the Beneš network fits the next
    power of two."""
    x = np.maximum(np.asarray(deg, dtype=np.int64), 1)
    p2 = np.int64(1) << np.int64(
        np.ceil(np.log2(x.astype(np.float64)))
    ).astype(np.int64)
    p2 = np.maximum(p2, 1)
    three_quarter = (p2 // 4) * 3
    return np.where((p2 >= 4) & (x <= three_quarter), three_quarter, p2)


# --------------------------------------------------------------------------
# Shared classing helpers.  `_class_width` above is the closed-form rule;
# the helpers below are the TABLE form of the same math — a static ascending
# candidate list plus searchsorted — which (a) is exact integer arithmetic
# (no float log2), so the device builder (graph/relay_device.py) can run it
# under jax's default 32-bit floats, and (b) turns the per-class Python
# loops of the builders into single vectorized passes (the sharded
# builder's per-shard classing below reuses them host-side).
# --------------------------------------------------------------------------

def width_candidates(max_width: int = 1 << 31) -> np.ndarray:
    """Every value `_class_width` can produce, ascending: {2^k, 3*2^(k-1)}.
    ``width = candidates[searchsorted(candidates, degree)]`` — the smallest
    candidate >= degree — is exactly `_class_width(degree)`."""
    out = [1, 2]
    k = 2
    while (1 << k) <= max_width:
        out.append(3 << (k - 2))  # 3*2^(k-1) for the next power of two
        out.append(1 << k)
        k += 1
    return np.array([c for c in out if c <= max_width], dtype=np.int64)


def width_index(deg: np.ndarray, candidates: np.ndarray) -> np.ndarray:
    """Index into ``candidates`` of `_class_width(deg)` (exact, integer)."""
    x = np.maximum(np.asarray(deg, dtype=np.int64), 1)
    return np.searchsorted(candidates, x, side="left").astype(np.int32)


def ranked_placement(
    group: np.ndarray, base_by_group: np.ndarray
) -> np.ndarray:
    """``pos[i] = base_by_group[group[i]] + rank``, where rank is item
    ``i``'s stable rank within its group ordered by (group, original
    index).  The vectorized form of the builders' per-class placement
    loops (one `_sort_rank` pass instead of a Python loop over classes)."""
    n = int(np.asarray(group).shape[0])
    order, rank = _sort_rank(
        np.asarray(group, dtype=np.int32), np.arange(n, dtype=np.int32)
    )
    out = np.empty(n, dtype=np.int64)
    out[order] = base_by_group[np.asarray(group)[order]] + rank
    return out


def _pow2_at_least(n: int) -> int:
    n = max(int(n), 32)
    return 1 << (n - 1).bit_length()


def _round32(x: int) -> int:
    return (int(x) + 31) & ~31


def _build_classes(widths: np.ndarray, counts: np.ndarray) -> list[ClassSlice]:
    """Aligned class slices from per-width real counts (widths ascending).

    Vertex-major iff width >= max(count, 32) (few huge-width vertices: pad
    the width to a multiple of 32); otherwise rank-major (pad the count).
    Rank-major classes come first so the padded vertex ranges stay 32-aligned
    even when vertex-major classes have unpadded counts.
    """
    order = np.argsort(widths, kind="stable")
    rank_major = [
        (int(widths[i]), int(counts[i]))
        for i in order
        if not widths[i] >= max(counts[i], 32)
    ]
    vertex_major = [
        (int(widths[i]), int(counts[i]))
        for i in order
        if widths[i] >= max(counts[i], 32)
    ]
    slices: list[ClassSlice] = []
    va = 0
    sa = 0
    for w, c in rank_major:
        cp = _round32(c)
        slices.append(
            ClassSlice(width=w, va=va, vb=va + cp, sa=sa, sb=sa + w * cp,
                       real=c, vertex_major=False, real_width=w)
        )
        va += cp
        sa += w * cp
    for w, c in vertex_major:
        wp = _round32(w)
        slices.append(
            ClassSlice(width=wp, va=va, vb=va + c, sa=sa, sb=sa + wp * c,
                       real=c, vertex_major=True, real_width=w)
        )
        va += c
        sa += wp * c
    return slices


def _gather(table: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """int32 gather with the native fast path (numpy fancy indexing is the
    layout build's second-biggest cost after routing on the 1-core VM)."""
    try:
        from .native_gen import gather_i32_native, native_available

        if native_available() and table.dtype == np.int32:
            return gather_i32_native(table, idx)
    except Exception:
        pass
    return table[idx]


def _scatter(out: np.ndarray, idx: np.ndarray, val: np.ndarray) -> None:
    try:
        from .native_gen import native_available, scatter_i32_native

        if native_available() and out.dtype == np.int32:
            scatter_i32_native(out, idx, val)
            return
    except Exception:
        pass
    out[idx] = val


def _slot_assign(base, stride, idx, rank) -> np.ndarray:
    try:
        from .native_gen import native_available, slot_assign_native

        if native_available():
            return slot_assign_native(base, stride, idx, rank)
    except Exception:
        pass
    return base[idx] + rank * stride[idx]


def _rank_by_count(key: np.ndarray, nk: int) -> np.ndarray:
    """Arbitrary-but-stable rank within each key group (native one-pass; a
    cumcount fallback otherwise)."""
    try:
        from .native_gen import native_available, rank_by_count_native

        if native_available():
            return rank_by_count_native(key, nk)
    except Exception:
        pass
    order = np.argsort(key, kind="stable")
    ks = key[order]
    starts = np.flatnonzero(np.concatenate([[True], ks[1:] != ks[:-1]]))
    sor = starts[np.searchsorted(starts, np.arange(ks.shape[0]), side="right") - 1]
    rank = np.empty_like(order)
    rank[order] = (np.arange(ks.shape[0]) - sor).astype(np.int32)
    return rank.astype(np.int32)


def _mark_used(idx: np.ndarray, used: np.ndarray) -> None:
    """used[idx] = 1 on a uint8 array (native scatter fast path)."""
    try:
        from .native_gen import mark_u8_native, native_available

        if native_available():
            mark_u8_native(idx, used)
            return
    except Exception:
        pass
    used[np.asarray(idx)] = 1


def _csr_fill(srcn, dstn, slotv, nk: int):
    """Counting-sort CSR grouped by ``srcn`` (arbitrary within-row order);
    returns (indptr int32[nk+2], adj_dst, adj_slot)."""
    try:
        from .native_gen import csr_fill_native, native_available

        if native_available():
            return csr_fill_native(srcn, dstn, slotv, nk)
    except Exception:
        pass
    order = np.argsort(srcn, kind="stable")
    indptr = np.zeros(nk + 2, dtype=np.int64)
    np.cumsum(np.bincount(srcn, minlength=nk), out=indptr[1 : nk + 1])
    indptr[nk + 1] = indptr[nk]
    return (
        indptr.astype(np.int32),
        np.asarray(dstn)[order].astype(np.int32),
        np.asarray(slotv)[order].astype(np.int32),
    )


def _sort_rank(key_hi: np.ndarray, key_lo: np.ndarray):
    """(order, rank-within-hi-runs) sorted by (key_hi, key_lo) — native radix
    when available, np.lexsort fallback."""
    try:
        from .native_gen import native_available, sort_rank_pairs_native

        if native_available():
            return sort_rank_pairs_native(key_hi, key_lo)
    except Exception:
        pass
    order = np.lexsort((key_lo, key_hi))
    hs = np.asarray(key_hi)[order]
    n = hs.shape[0]
    if n == 0:
        return order.astype(np.int32), np.zeros(0, np.int32)
    starts = np.flatnonzero(np.concatenate([[True], hs[1:] != hs[:-1]]))
    sor = starts[np.searchsorted(starts, np.arange(n), side="right") - 1]
    return order.astype(np.int32), (np.arange(n) - sor).astype(np.int32)


def _vertex_tables(classes: list[ClassSlice], num_ids: int):
    """Per-(relabeled id / out-position) slot tables: slot(id, r) =
    base[id] + r * stride[id].  Rank-major: base = sa + p, stride = count;
    vertex-major: base = sa + p*width, stride = 1."""
    base = np.zeros(num_ids, dtype=np.int32)
    stride = np.ones(num_ids, dtype=np.int32)
    for cs in classes:
        p = np.arange(cs.count, dtype=np.int32)
        if cs.vertex_major:
            base[cs.va : cs.vb] = cs.sa + p * cs.width
            stride[cs.va : cs.vb] = 1
        else:
            base[cs.va : cs.vb] = cs.sa + p
            stride[cs.va : cs.vb] = cs.count
    return base, stride


def _compact_and_table(
    masks: np.ndarray, n: int
) -> tuple[np.ndarray, tuple[StageSpec, ...]]:
    """Pair-compact the router's word-major masks and build the stage table.

    For each stage with d >= COMPACT_MIN_D, keep only the word rows at
    (row & (d >> 12)) == 0 (the rest are structurally zero: switch bits live
    at the lower pair index).  Also records each stage's nonzero word range
    so appliers can skip the identity-wired tail."""
    nw = n // 32
    stages = masks.shape[0]
    parts = []
    table = []
    offset = 0
    for s in range(stages):
        d = benes.stage_distance(n, s)
        w = masks[s]
        if d >= COMPACT_MIN_D:
            dw = d >> 5
            w = w.reshape(-1, 2, dw)[:, 0, :].reshape(-1)
        nz = np.flatnonzero(
            w.reshape(-1, 1024).any(axis=1)
            if w.shape[0] % 1024 == 0
            else w
        )
        if w.shape[0] % 1024 == 0:
            lo = int(nz[0]) * 1024 if nz.size else 0
            hi = int(nz[-1] + 1) * 1024 if nz.size else 0
        else:
            lo = int(nz[0]) if nz.size else 0
            hi = int(nz[-1] + 1) if nz.size else 0
        parts.append(w)
        table.append(
            StageSpec(d=d, offset=offset, nwords=int(w.shape[0]),
                      compact=d >= COMPACT_MIN_D, lo=lo, hi=hi)
        )
        offset += int(w.shape[0])
    return np.concatenate(parts), tuple(table)


@dataclass(frozen=True)
class RelayGraph:
    """Static relay layout v4 for one graph (single shard).

    All vertex-indexed engine state lives in the RELABELED id space of size
    ``vr`` (``new2old``/``old2new``; -1 at padding dummies); parent VALUES
    are L1 slot indices mapped to original src ids host-side via ``src_l1``.
    """

    num_vertices: int  # real V
    num_edges: int
    vr: int  # padded relabeled vertex space (multiple of 32)
    new2old: np.ndarray  # int32[vr]; -1 at dummies
    old2new: np.ndarray  # int32[V]
    # src side
    vperm_masks: np.ndarray  # uint32 flat
    vperm_table: tuple[StageSpec, ...]
    vperm_size: int
    out_classes: tuple[ClassSlice, ...]  # over out-order positions
    out_space: int  # used out positions (sum of class counts)
    # shuffle
    net_masks: np.ndarray  # uint32 flat
    net_table: tuple[StageSpec, ...]
    net_size: int
    m1: int
    m2: int
    # dst side
    in_classes: tuple[ClassSlice, ...]  # over relabeled vertex space
    src_l1: np.ndarray  # int32[m1] — ORIGINAL src id per L1 slot, INF padding
    # sparse-path adjacency: CSR over RELABELED src ids with, per out-edge,
    # the relabeled dst and that edge's L1 slot.  The hybrid engine gathers
    # these for small frontiers instead of paying the full-net superstep
    # (supersteps 0 and the >=3 tail carry <2% of the edges at scale 24 —
    # tools/measure_r3.py level profile).
    adj_indptr: np.ndarray  # int32[vr + 2] (last entry repeated)
    adj_dst: np.ndarray  # int32[E]
    adj_slot: np.ndarray  # int32[E]


def extract_edges(graph: Graph | DeviceGraph):
    """Host edge extraction shared by both builders: ``(src, dst, v, e)``."""
    if isinstance(graph, DeviceGraph):
        if graph.num_shards != 1:
            raise ValueError("build_relay_graph expects a single-shard graph")
        flat_src = np.asarray(graph.src).reshape(-1)
        flat_dst = np.asarray(graph.dst).reshape(-1)
        keep = flat_dst != graph.sentinel
        src = flat_src[keep].astype(np.int32)
        dst = flat_dst[keep].astype(np.int32)
        v = graph.num_vertices
    else:
        src = np.asarray(graph.src).astype(np.int32)
        dst = np.asarray(graph.dst).astype(np.int32)
        v = graph.num_vertices
    return src, dst, int(v), int(src.shape[0])


def seg_degrees(src: np.ndarray, dst: np.ndarray, v: int):
    """Per-vertex degree-class widths (zero-indeg vertices get one INF
    slot) — native bincount fast path."""
    try:
        from .native_gen import bincount_i32_native, native_available

        if native_available():
            indeg = bincount_i32_native(dst, v).astype(np.int64)
            outdeg = bincount_i32_native(src, v).astype(np.int64)
        else:
            raise RuntimeError
    except Exception:
        indeg = np.bincount(dst, minlength=v)
        outdeg = np.bincount(src, minlength=v)
    return _class_width(indeg), _class_width(outdeg)


class LayoutMeta(NamedTuple):
    """Static layout metadata derived from the two degree histograms — the
    shapes every later segment (and the device builder's programs) key on."""

    in_classes: tuple
    out_classes: tuple
    widths: np.ndarray
    counts: np.ndarray
    owidths: np.ndarray
    ocounts: np.ndarray
    vr: int
    m1: int
    m2: int
    out_vb: int
    n: int
    vp: int


def seg_classes_from_counts(
    widths: np.ndarray, counts: np.ndarray,
    owidths: np.ndarray, ocounts: np.ndarray, v: int,
) -> LayoutMeta:
    """Aligned classes + every derived static size from per-width counts.
    The ONE home of the sizing formulas (vr/m1/m2/net/vperm): the host
    builder reaches it through `seg_classes`, the device builder through
    its histogram program — a drift between two copies would silently
    break device/host bit-parity."""
    in_classes = _build_classes(widths, counts)
    vr = _round32(in_classes[-1].vb) if in_classes else 32
    m1 = in_classes[-1].sb if in_classes else 0
    out_classes = _build_classes(owidths, ocounts)
    out_vb = out_classes[-1].vb if out_classes else 0
    m2 = out_classes[-1].sb if out_classes else 0
    n = _pow2_at_least(max(m1, m2))
    dummies = out_vb - v
    vp = _pow2_at_least(max(vr + dummies, out_vb, 32 * 128 * 2))
    return LayoutMeta(
        in_classes=tuple(in_classes), out_classes=tuple(out_classes),
        widths=widths, counts=counts, owidths=owidths, ocounts=ocounts,
        vr=vr, m1=m1, m2=m2, out_vb=out_vb, n=n, vp=vp,
    )


def seg_classes(in_w: np.ndarray, out_w: np.ndarray, v: int) -> LayoutMeta:
    """Degree widths -> aligned classes + every derived static size."""
    widths, counts = np.unique(in_w, return_counts=True)
    owidths, ocounts = np.unique(out_w, return_counts=True)
    return seg_classes_from_counts(widths, counts, owidths, ocounts, v)


def seg_relabel_in(in_w: np.ndarray, meta: LayoutMeta):
    """Class-major, old-id-minor relabeling (dst side): one vectorized
    `ranked_placement` pass (the shared classing helper) instead of a
    Python loop over classes."""
    v = int(in_w.shape[0])
    in_map = _width_class_map(meta.in_classes, meta.widths)
    in_va = np.array(
        [in_map[int(wv)].va for wv in meta.widths], dtype=np.int64
    )
    old2new = ranked_placement(
        np.searchsorted(meta.widths, in_w), in_va
    ).astype(np.int32)
    new2old = np.full(meta.vr, -1, dtype=np.int32)
    new2old[old2new] = np.arange(v, dtype=np.int32)
    return new2old, old2new


def seg_relabel_out(out_w: np.ndarray, meta: LayoutMeta):
    """Out-order positions (src side), same vectorized placement."""
    out_map = _width_class_map(meta.out_classes, meta.owidths)
    out_va = np.array(
        [out_map[int(wv)].va for wv in meta.owidths], dtype=np.int64
    )
    return ranked_placement(
        np.searchsorted(meta.owidths, out_w), out_va
    ).astype(np.int32)


def seg_relabel(in_w: np.ndarray, out_w: np.ndarray, meta: LayoutMeta):
    """Both sides of the relabeling (see `seg_relabel_in`/`_out`)."""
    new2old, old2new = seg_relabel_in(in_w, meta)
    return new2old, old2new, seg_relabel_out(out_w, meta)


def seg_l1_slots(src, dst, old2new, meta: LayoutMeta):
    """L1 slots: edges sorted by (dst_new, src); rank = in-row position
    (the one REQUIRED sort: rank order == canonical min-parent)."""
    dstn = _gather(old2new, dst)
    order1, rank1 = _sort_rank(dstn, src)
    base1, stride1 = _vertex_tables(meta.in_classes, meta.vr)
    ds = _gather(dstn, order1)
    l1_sorted = _slot_assign(base1, stride1, ds, rank1)  # slots < 2^28
    src_l1 = np.full(meta.m1, INF_DIST, dtype=np.int32)
    _scatter(src_l1, l1_sorted, _gather(src, order1))  # ORIGINAL ids
    l1_by_edge = np.empty(src.shape[0], dtype=np.int32)
    _scatter(l1_by_edge, order1, l1_sorted)
    return src_l1, l1_by_edge, dstn


def seg_l2_slots(src, outpos_of_old, meta: LayoutMeta):
    """L2 slots: edges grouped by src out-position.  The within-row rank is
    FREE (the big network routes any permutation and the broadcast fills
    every rank slot of a source with the same bit), so a single counting
    pass replaces the full (srcpos, dst) radix sort (measured
    272 s -> ~3 s at s25), assigning slots directly in edge order."""
    srcpos = _gather(outpos_of_old, src)
    rank2 = _rank_by_count(srcpos, meta.out_classes[-1].vb)
    base2, stride2 = _vertex_tables(meta.out_classes, meta.out_classes[-1].vb)
    return _slot_assign(base2, stride2, srcpos, rank2)


def seg_net_assembly(l1_by_edge, l2_by_edge, meta: LayoutMeta):
    """Big network permutation: L1 slot <- L2 slot, identity-padded."""
    net = np.full(meta.n, -1, dtype=np.int32)
    _scatter(net, l1_by_edge, l2_by_edge)
    used = np.zeros(meta.n, dtype=np.uint8)
    _mark_used(l2_by_edge, used)
    _pad_identity(net, used, meta.n)
    return net


def seg_vperm_assembly(outpos_of_old, old2new, meta: LayoutMeta):
    """Small network permutation: vertex-space words -> out-order words.
    Dummy out positions (padded rank-major class tails) must read zero:
    wire them to the guaranteed-zero input region [vr, vp)."""
    vperm = np.full(meta.vp, -1, dtype=np.int32)
    real_mask = np.zeros(meta.out_vb, dtype=bool)
    real_mask[outpos_of_old] = True
    # real out positions <- relabeled id of their owning vertex
    vperm[outpos_of_old] = old2new
    dummy_positions = np.flatnonzero(~real_mask)
    vperm[dummy_positions] = meta.vr + np.arange(dummy_positions.shape[0])
    used = np.zeros(meta.vp, dtype=np.uint8)
    _mark_used(vperm[vperm >= 0], used)
    _pad_identity(vperm, used, meta.vp)
    return vperm


def seg_csr(srcn, dstn, l1_by_edge, meta: LayoutMeta):
    """Sparse-path CSR over relabeled src ids.  Within-row order is free
    (the sparse superstep re-sorts its own gathered candidates), so a
    counting placement replaces the third full edge sort of the build."""
    return _csr_fill(srcn, dstn, l1_by_edge, meta.vr)


def build_relay_graph(graph: Graph | DeviceGraph) -> RelayGraph:
    """Build the full relay layout (host side, once per graph).

    Requires the native Beneš router; raises RuntimeError when unavailable.
    The body is a sequential composition of the ``seg_*`` segment functions
    above — the device builder (graph/relay_device.py) composes the SAME
    segments as its measured host arm, overlapped with the routes.
    """
    _ensure_build_log()
    if not benes.native_available():
        raise RuntimeError("relay engine requires the native benes router")
    src, dst, v, e = extract_edges(graph)

    with _phase("degrees"):
        in_w, out_w = seg_degrees(src, dst, v)

    meta = seg_classes(in_w, out_w, v)
    new2old, old2new, outpos_of_old = seg_relabel(in_w, out_w, meta)

    with _phase("l1 slots"):
        src_l1, l1_by_edge, dstn = seg_l1_slots(src, dst, old2new, meta)
    with _phase("l2 slots"):
        l2_by_edge = seg_l2_slots(src, outpos_of_old, meta)
    with _phase("net perm assembly"):
        net = seg_net_assembly(l1_by_edge, l2_by_edge, meta)

    # One huge-page reservation held across BOTH routes (net + vperm):
    # per-route reserve/free cycles pay kernel compaction twice and the
    # second reservation can fall short on a fragmented allocator; the hold
    # covers the LARGER of the two routed networks (vp can exceed n on
    # vertex-heavy, edge-sparse graphs).
    with benes.hugepage_reservation(max(meta.n, meta.vp)):
        with _phase("net route"):
            net_masks_full = benes.route_std(net, trusted=True)
        with _phase("net compact"):
            net_masks, net_table = _compact_and_table(net_masks_full, meta.n)
            del net_masks_full
        with _phase("vperm route"):
            vperm = seg_vperm_assembly(outpos_of_old, old2new, meta)
            vperm_masks_full = benes.route_std(vperm, trusted=True)
            vperm_masks, vperm_table = _compact_and_table(
                vperm_masks_full, meta.vp
            )
            del vperm_masks_full

    with _phase("sparse CSR"):
        srcn = _gather(old2new, src)
        adj_indptr, adj_dst, adj_slot = seg_csr(srcn, dstn, l1_by_edge, meta)

    return RelayGraph(
        num_vertices=v,
        num_edges=e,
        vr=meta.vr,
        new2old=new2old,
        old2new=old2new,
        vperm_masks=vperm_masks,
        vperm_table=vperm_table,
        vperm_size=meta.vp,
        out_classes=meta.out_classes,
        out_space=meta.out_vb,
        net_masks=net_masks,
        net_table=net_table,
        net_size=meta.n,
        m1=meta.m1,
        m2=meta.m2,
        in_classes=meta.in_classes,
        src_l1=src_l1,
        adj_indptr=adj_indptr.astype(np.int32),
        adj_dst=adj_dst,
        adj_slot=adj_slot,
    )


@dataclass(frozen=True)
class ShardedRelayGraph:
    """Per-shard relay layouts (v4) with ONE unified class structure.

    The multi-device TPU-fast layout: shard ``s`` owns a contiguous block of
    the (globally relabeled) vertex space and holds the relay pipeline for
    exactly its owned destinations — its own vperm network, degree-class
    broadcast, Beneš edge net and src-id tables — while all shards share the
    SAME static shapes (class slices, network sizes, stage tables), so one
    `shard_map` program runs everywhere and only the mask/table DATA differs
    per device (stacked on axis 0).  Ownership is CLASS-BALANCED (each
    in-degree class dealt across shards — see the builder), so the shared
    shapes are ~1/n of the single-chip layout instead of approaching it on
    skewed graphs.  The per-superstep exchange is the
    bit-packed frontier all-gather (1 bit/vertex over ICI); with v4's
    standard packing the gathered words ARE the global standard-packed
    frontier (relabeling is shard-major), so they feed each shard's vperm
    directly with no repacking at all.
    """

    num_vertices: int
    num_edges: int
    num_shards: int
    block: int  # owned vertex slots per shard (multiple of 32)
    new2old: np.ndarray  # int32[n*block]; -1 at dummies
    old2new: np.ndarray  # int32[V]
    vperm_masks: np.ndarray  # uint32[n, vperm_words]
    vperm_table: tuple[StageSpec, ...]
    vperm_size: int
    out_classes: tuple[ClassSlice, ...]
    out_space: int
    net_masks: np.ndarray  # uint32[n, net_words]
    net_table: tuple[StageSpec, ...]
    net_size: int
    m1: int
    m2: int
    in_classes: tuple[ClassSlice, ...]  # over local [0, block)
    src_l1: np.ndarray  # int32[n, m1]; ORIGINAL src ids, INF padding
    # Per-shard dst-owned adjacency (ROADMAP item 1 / ISSUE 11): shard s's
    # CSR over GLOBAL relabeled src ids holding, per edge into an owned
    # destination, the LOCAL dst id [0, block) and that edge's L1 slot —
    # the operands the sharded push (sparse gather) superstep needs, so
    # the direction-optimizing schedule runs across the mesh.  Rows are
    # padded to the max per-shard edge count for uniform SPMD shapes.
    # ``outdeg`` is the per-GLOBAL-new-id out-degree table (0 at dummies)
    # the Beamer predicate reads.  None on layouts built before this
    # field existed (dense-only fallback).
    adj_indptr: np.ndarray | None = None  # int32[n, n*block + 2]
    adj_dst: np.ndarray | None = None  # int32[n, emax]; LOCAL dst ids
    adj_slot: np.ndarray | None = None  # int32[n, emax]; L1 slots
    outdeg: np.ndarray | None = None  # int32[n*block]


def _merge_tables(tables: list[tuple[StageSpec, ...]]) -> tuple[StageSpec, ...]:
    """Shared static stage table for stacked per-shard masks: identical
    layout (same net size -> same offsets), per-stage nonzero range = union
    over shards."""
    out = []
    for specs in zip(*tables):
        st = specs[0]
        out.append(
            st._replace(
                lo=min(s.lo for s in specs), hi=max(s.hi for s in specs)
            )
        )
    return tuple(out)


def _unified_classes(widths: np.ndarray, per_shard_counts: np.ndarray):
    """Aligned classes from per-width counts maxed over shards.
    ``per_shard_counts``: [num_widths, n]."""
    return _build_classes(widths, per_shard_counts.max(axis=1))


def build_sharded_relay_graph(
    graph: Graph | DeviceGraph, num_shards: int
) -> ShardedRelayGraph:
    """Build per-shard relay layouts (v4) with a unified static structure.

    Ownership is CLASS-BALANCED (per-shard class structure): each
    in-degree class is dealt across the shards in equal contiguous chunks
    (ascending original id within a chunk), so every shard's per-width
    class count is within 1 of ``count/n`` and the shared static envelope
    (max over shards) is TIGHT.  The old contiguous-original-id partition
    let a skewed degree distribution concentrate a class in one shard,
    making the unified max-over-shards counts approach the SINGLE-CHIP
    class sizes — every shard then padded, routed and row-minned close to
    the whole graph's slot space, the x8 padded-work amplification behind
    the non-monotone sharded scaling of BENCHMARKS row 12 (VERDICT r5
    weak #5).  With balanced classes, per-shard slots shrink ~1/n and the
    compact frontier exchange stays flat (it ships real words only,
    parallel/sharded._own_word_table).

    Vertices are relabeled within each shard so in-degree classes are
    contiguous; the global new-id space is the concatenation of shard
    blocks (ownership itself is an arbitrary bijection — every consumer
    goes through ``old2new``/``new2old``).
    """
    _ensure_build_log()
    if not benes.native_available():
        raise RuntimeError("relay engine requires the native benes router")
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    from .csr import _sorted_by_dst, unpad_edges

    if isinstance(graph, DeviceGraph):
        src, dst = _sorted_by_dst(*unpad_edges(graph))
    else:
        src, dst = _sorted_by_dst(graph.src, graph.dst)
    src = src.astype(np.int64)
    dst = dst.astype(np.int64)
    v = graph.num_vertices
    e = int(src.shape[0])
    n = num_shards

    indeg = np.bincount(dst, minlength=v)
    in_w = _class_width(indeg)

    # ---- class-balanced ownership (see docstring) --------------------------
    shard_of_old = np.empty(v, dtype=np.int64)
    order_v = np.argsort(in_w, kind="stable")
    pos = 0
    for wv, cnt in zip(*np.unique(in_w, return_counts=True)):
        ids = order_v[pos : pos + cnt]
        shard_of_old[ids] = (np.arange(cnt, dtype=np.int64) * n) // cnt
        pos += cnt
    assert pos == v

    # ---- unified in-classes: per-width counts maxed over shards ------------
    # (The max is now within 1 of the mean by construction.)
    widths_all = np.unique(in_w)
    nwidths = int(widths_all.shape[0])
    in_widx = np.searchsorted(widths_all, in_w).astype(np.int64)
    counts = (
        np.bincount(shard_of_old * nwidths + in_widx, minlength=n * nwidths)
        .reshape(n, nwidths)
        .T
    )
    in_classes = _unified_classes(widths_all, counts)
    block = _round32(in_classes[-1].vb)
    m1 = in_classes[-1].sb

    # ---- relabel: shard-major, class-major, old-id-minor -------------------
    # One vectorized `ranked_placement` pass over (shard, width) groups
    # replaces the old per-shard-per-width Python loop (the classing
    # helpers the device builder extracted, reused host-side — ISSUE 10):
    # a vertex's new id is shard base + class slot start + its stable rank
    # within the (shard, width) group, ordered by old id.
    width_to_class = _width_class_map(in_classes, widths_all)
    va_by_widx = np.array(
        [width_to_class[int(wv)].va for wv in widths_all], dtype=np.int64
    )
    group_base = (
        np.arange(n, dtype=np.int64)[:, None] * block + va_by_widx[None, :]
    ).reshape(-1)
    old2new = ranked_placement(shard_of_old * nwidths + in_widx, group_base)
    new2old = np.full(n * block, -1, dtype=np.int64)
    new2old[old2new] = np.arange(v, dtype=np.int64)

    # ---- edge shard slices: grouped by the OWNER of the destination --------
    # Ownership is class-balanced (not contiguous in original ids), so the
    # per-shard edge sets come from a stable group-by instead of a
    # searchsorted over the dst-sorted array; dst order is preserved
    # within each shard's slice.
    owner_e = shard_of_old[dst]
    order_e = np.argsort(owner_e, kind="stable")
    src = src[order_e]
    dst = dst[order_e]
    bounds = np.concatenate(
        [[0], np.cumsum(np.bincount(owner_e, minlength=n))]
    ).astype(np.int64)

    # ---- unified out-classes over per-shard out-degrees --------------------
    out_sparse = []
    owidth_counts: dict[int, int] = {}
    for s in range(n):
        es, ee = bounds[s], bounds[s + 1]
        uids, ucounts = np.unique(src[es:ee], return_counts=True)
        w = _class_width(ucounts)
        out_sparse.append((uids, w))
        for wv, c in zip(*np.unique(w, return_counts=True)):
            owidth_counts[int(wv)] = max(owidth_counts.get(int(wv), 0), int(c))
    owidths = np.array(sorted(owidth_counts), dtype=np.int64)
    ocounts = np.array([owidth_counts[int(w)] for w in owidths], dtype=np.int64)
    out_classes = _build_classes(owidths, ocounts)
    out_vb = out_classes[-1].vb
    m2 = out_classes[-1].sb
    out_width_to_class = _width_class_map(out_classes, owidths)

    # ---- network sizes (shared across shards) ------------------------------
    net_size = _pow2_at_least(max(m1, m2))
    gtot = n * block
    max_dummies = max(
        int(out_vb - u.shape[0]) for u, _ in out_sparse
    )
    vp = _pow2_at_least(max(gtot + max_dummies, out_vb, 32 * 128 * 2))

    base1, stride1 = _vertex_tables(in_classes, block)
    base2, stride2 = _vertex_tables(out_classes, out_vb)

    vperm_masks_l, vperm_tables = [], []
    net_masks_l, net_tables = [], []
    src_l1 = np.full((n, m1), INF_DIST, dtype=np.int32)
    adj_parts: list = []  # futures during the loop, (indptr, dst, slot) after

    # Static out-class lookup tables for the vectorized per-shard classing
    # below (shared helpers with the device builder — ISSUE 10 satellite):
    # position -> owning class (classes are contiguous [va, vb)) -> width
    # index, plus each width's class slot start.
    va_by_owidx = np.array(
        [out_width_to_class[int(w)].va for w in owidths], dtype=np.int64
    )
    ova_bounds = np.array([c.va for c in out_classes], dtype=np.int64)
    owidx_of_cls = np.searchsorted(
        owidths, np.array([c.real_width for c in out_classes], dtype=np.int64)
    )
    owidx_of_pos = owidx_of_cls[
        np.searchsorted(ova_bounds, np.arange(out_vb), side="right") - 1
    ]

    # One huge-page hold across all 2n per-shard routes (see the
    # single-shard builder for why per-route reserve/free cycles lose).
    with benes.hugepage_reservation(max(net_size, vp)):
        for s in range(n):
            uids_s, uw_s = out_sparse[s]
            # out positions for this shard's sources (ascending ORIGINAL id
            # within each width class): one ranked_placement pass instead
            # of the per-width Python loop.
            owidx_s = np.searchsorted(owidths, uw_s).astype(np.int64)
            outpos_s = ranked_placement(owidx_s, va_by_owidx)
            outpos_of_old = np.full(v, -1, dtype=np.int64)
            outpos_of_old[uids_s] = outpos_s
            vperm = np.full(vp, -1, dtype=np.int32)
            vperm[outpos_s] = old2new[uids_s]
            # Dummy out positions: tails of classes PRESENT in this shard
            # get dummy ids first, walked in ascending-width class order
            # with positions ascending within a class (the old
            # dummy_cursor sequence, which is NOT ascending-position when
            # a small-width vertex-major class follows a larger rank-major
            # va); then positions of absent classes, ascending.
            front = vperm[:out_vb]
            cnt_by_owidx = np.bincount(owidx_s, minlength=owidths.shape[0])
            present = cnt_by_owidx[owidx_of_pos] > 0
            tail = np.flatnonzero((front < 0) & present)
            tail = tail[np.argsort(owidx_of_pos[tail], kind="stable")]
            front[tail] = gtot + np.arange(tail.shape[0], dtype=np.int64)
            missing = np.flatnonzero(front < 0)
            vperm[missing] = (
                gtot + tail.shape[0] + np.arange(missing.shape[0])
            )
            used = np.zeros(vp, dtype=bool)
            used[vperm[vperm >= 0]] = True
            _pad_identity(vperm, used, vp)
            vm_full = benes.route_std(vperm, trusted=True)
            vm, vt = _compact_and_table(vm_full, vp)
            del vm_full
            vperm_masks_l.append(vm)
            vperm_tables.append(vt)

            # ---- L1/L2 slots for this shard's edges ----------------------------
            es, ee = bounds[s], bounds[s + 1]
            s_src, s_dst = src[es:ee], dst[es:ee]
            dstn = old2new[s_dst] - s * block  # local [0, block)
            o1, r1 = _sort_rank(dstn.astype(np.int32), s_src.astype(np.int32))
            ds = dstn[o1]
            l1_sorted = base1[ds] + r1.astype(np.int64) * stride1[ds]
            src_l1[s, l1_sorted] = s_src[o1].astype(np.int32)

            srcpos = outpos_of_old[s_src]
            o2, r2 = _sort_rank(srcpos.astype(np.int32), dstn.astype(np.int32))
            sp = srcpos[o2]
            l2_sorted = base2[sp] + r2.astype(np.int64) * stride2[sp]

            net = np.full(net_size, -1, dtype=np.int64)
            l1_by_edge = np.empty(ee - es, dtype=np.int64)
            l1_by_edge[o1] = l1_sorted
            l2_by_edge = np.empty(ee - es, dtype=np.int64)
            l2_by_edge[o2] = l2_sorted

            # ---- per-shard dst-owned adjacency (the push body's CSR) ---
            # Grouped by GLOBAL relabeled src id — the all-gathered
            # frontier's id space — holding (local dst, L1 slot) per
            # edge; the within-row order is free (the push superstep
            # re-sorts its gathered candidates by (dst, slot)), so the
            # shared counting-sort fill (`_csr_fill`, native fast path)
            # does it in one pass, same as the single-chip builder's
            # sparse CSR segment.  Submitted to the device builder's
            # worker pool BEFORE this shard's net route starts (the
            # PR 10 overlap idiom: the route is walker-bound on one
            # core, the fill is numpy on another), resolved after the
            # loop.
            from .relay_device import _TRACK_POOL

            srcn_g = old2new[s_src].astype(np.int32)
            adj_parts.append(
                _TRACK_POOL.submit(
                    _csr_fill, srcn_g, dstn.astype(np.int32),
                    l1_by_edge.astype(np.int32), gtot,
                )
            )

            net[l1_by_edge] = l2_by_edge
            used = np.zeros(net_size, dtype=bool)
            used[l2_by_edge] = True
            _pad_identity(net, used, net_size)
            nm_full = benes.route_std(net, trusted=True)
            nm, nt = _compact_and_table(nm_full, net_size)
            del nm_full
            net_masks_l.append(nm)
            net_tables.append(nt)

    # Resolve the overlapped adjacency fills (re-raises a worker failure).
    adj_parts = [p.result() for p in adj_parts]

    # Uniform SPMD shapes for the adjacency rows: pad every shard's edge
    # arrays to the max per-shard count (padded tail entries are never
    # addressed — each shard's indptr bounds its own real entries).
    emax = max(1, max(p[1].shape[0] for p in adj_parts))
    adj_indptr = np.stack([p[0] for p in adj_parts])
    adj_dst = np.zeros((n, emax), np.int32)
    adj_slot = np.zeros((n, emax), np.int32)
    for s, (_, d_s, sl_s) in enumerate(adj_parts):
        adj_dst[s, : d_s.shape[0]] = d_s
        adj_slot[s, : sl_s.shape[0]] = sl_s
    outdeg_new = np.zeros(gtot, np.int32)
    outdeg_new[old2new] = np.bincount(src, minlength=v).astype(np.int32)

    return ShardedRelayGraph(
        num_vertices=v,
        num_edges=e,
        num_shards=n,
        block=block,
        new2old=new2old.astype(np.int32),
        old2new=old2new.astype(np.int32),
        vperm_masks=np.stack(vperm_masks_l),
        vperm_table=_merge_tables(vperm_tables),
        vperm_size=vp,
        out_classes=tuple(out_classes),
        out_space=out_vb,
        net_masks=np.stack(net_masks_l),
        net_table=_merge_tables(net_tables),
        net_size=net_size,
        m1=m1,
        m2=m2,
        in_classes=tuple(in_classes),
        src_l1=src_l1,
        adj_indptr=adj_indptr,
        adj_dst=adj_dst,
        adj_slot=adj_slot,
        outdeg=outdeg_new,
    )


def _width_class_map(classes, widths: np.ndarray):
    """Map REAL (pre-padding) width -> its ClassSlice."""
    del widths
    return {int(c.real_width): c for c in classes}


def _pad_identity(perm: np.ndarray, used: np.ndarray, n: int) -> None:
    """Complete a partial mapping to a bijection, wiring free outputs to free
    inputs IDENTITY-first: output j takes input j wherever both are free.
    Where both members of a stage pair are pads, identity wiring routes
    switch-free (StageSpec.lo/hi shrink); mixed live/pad pairs still switch.
    ``used`` is uint8 (or bool) and is updated in place; the native two-scan
    replaces the numpy multi-pass at big nets."""
    try:
        from .native_gen import native_available, pad_identity_native

        if (
            native_available()
            and used.dtype == np.uint8
            and perm.dtype == np.int32
        ):
            pad_identity_native(perm, used)
            return
    except Exception:
        pass
    free_out = perm < 0
    unused = used == 0  # dtype-safe (uint8 bitwise ~ would misfire)
    both = free_out & unused
    idx = np.flatnonzero(both)
    perm[idx] = idx
    used[idx] = 1
    free_outputs = np.flatnonzero(perm < 0)
    free_inputs = np.flatnonzero(used == 0)
    if free_outputs.shape[0] != free_inputs.shape[0]:
        raise ValueError("partial permutation is not completable")
    perm[free_outputs] = free_inputs
    used[free_inputs] = 1


# --------------------------------------------------------------------------
# Serialization: RelayGraph <-> flat numpy arrays.  The persistent layout
# cache (bfs_tpu/cache/layout.py) stores exactly this mapping as one on-disk
# bundle; keeping the converters next to the dataclass means a field added
# to RelayGraph fails loudly here instead of silently dropping from bundles.
# --------------------------------------------------------------------------

def classes_to_rows(classes) -> np.ndarray:
    """Pack ClassSlice tuples into an int64[n, 8] row table."""
    return np.array(
        [
            [c.width, c.va, c.vb, c.sa, c.sb, c.real, int(c.vertex_major),
             c.real_width]
            for c in classes
        ],
        dtype=np.int64,
    ).reshape(-1, 8)


def rows_to_classes(rows: np.ndarray) -> tuple[ClassSlice, ...]:
    return tuple(
        ClassSlice(
            width=int(r[0]), va=int(r[1]), vb=int(r[2]), sa=int(r[3]),
            sb=int(r[4]), real=int(r[5]), vertex_major=bool(r[6]),
            real_width=int(r[7]),
        )
        for r in np.asarray(rows).tolist()
    )


def table_to_rows(table) -> np.ndarray:
    """Pack StageSpec tuples into an int64[n, 6] row table."""
    return np.array(
        [[t.d, t.offset, t.nwords, int(t.compact), t.lo, t.hi] for t in table],
        dtype=np.int64,
    ).reshape(-1, 6)


def rows_to_table(rows: np.ndarray) -> tuple[StageSpec, ...]:
    return tuple(
        StageSpec(
            d=int(r[0]), offset=int(r[1]), nwords=int(r[2]),
            compact=bool(r[3]), lo=int(r[4]), hi=int(r[5]),
        )
        for r in np.asarray(rows).tolist()
    )


def relay_to_arrays(rg: RelayGraph) -> dict[str, np.ndarray]:
    """Flatten a RelayGraph to name -> ndarray (scalars as 0-d arrays)."""
    return dict(
        num_vertices=np.int64(rg.num_vertices),
        num_edges=np.int64(rg.num_edges),
        vr=np.int64(rg.vr),
        new2old=rg.new2old,
        old2new=rg.old2new,
        vperm_masks=rg.vperm_masks,
        vperm_table=table_to_rows(rg.vperm_table),
        vperm_size=np.int64(rg.vperm_size),
        out_classes=classes_to_rows(rg.out_classes),
        out_space=np.int64(rg.out_space),
        net_masks=rg.net_masks,
        net_table=table_to_rows(rg.net_table),
        net_size=np.int64(rg.net_size),
        m1=np.int64(rg.m1),
        m2=np.int64(rg.m2),
        in_classes=classes_to_rows(rg.in_classes),
        src_l1=rg.src_l1,
        adj_indptr=rg.adj_indptr,
        adj_dst=rg.adj_dst,
        adj_slot=rg.adj_slot,
    )


def relay_from_arrays(z) -> RelayGraph:
    """Inverse of :func:`relay_to_arrays`.  ``z`` is any mapping of
    name -> array (an npz file, a dict of memmaps, ...); big arrays are
    taken as-is, so memmap-backed loads stay lazy."""
    return RelayGraph(
        num_vertices=int(z["num_vertices"]),
        num_edges=int(z["num_edges"]),
        vr=int(z["vr"]),
        new2old=z["new2old"],
        old2new=z["old2new"],
        vperm_masks=z["vperm_masks"],
        vperm_table=rows_to_table(z["vperm_table"]),
        vperm_size=int(z["vperm_size"]),
        out_classes=rows_to_classes(z["out_classes"]),
        out_space=int(z["out_space"]),
        net_masks=z["net_masks"],
        net_table=rows_to_table(z["net_table"]),
        net_size=int(z["net_size"]),
        m1=int(z["m1"]),
        m2=int(z["m2"]),
        in_classes=rows_to_classes(z["in_classes"]),
        src_l1=z["src_l1"],
        adj_indptr=np.asarray(z["adj_indptr"], dtype=np.int32),
        adj_dst=z["adj_dst"],
        adj_slot=z["adj_slot"],
    )


def valid_slot_words(src_l1: np.ndarray, net_size: int) -> np.ndarray:
    """Static valid-slot bitmask (STANDARD packing): uint32[net_size/32], bit
    set iff that L1 slot holds a real edge.  Beneš pad routing may deliver
    stray 1-bits to padded slots; this mask zeroes them before the row-min."""
    m1 = src_l1.shape[0]
    bits = np.zeros(net_size, dtype=bool)
    bits[:m1] = src_l1 != np.int32(INF_DIST)
    return np.packbits(
        bits.reshape(-1, 32), axis=1, bitorder="little"
    ).view(np.uint32).reshape(-1)
