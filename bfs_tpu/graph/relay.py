"""Relay layout: degree-class dense adjacency + Beneš-routed bit shuffle.

The fully gather-free BFS data layout.  Measured reality on TPU v5e
(tools/microbench_gather.py): dense vector ops run at ~200 Gint32/s while
every XLA gather/scatter runs at ~0.12 G/s, so the engine may not index by
edge at runtime AT ALL.  Everything data-dependent becomes dense math over
static layouts:

  * **src side (broadcast)** — vertices bucketed by power-of-two OUT-degree
    class; a vertex's frontier bit is broadcast to its out-edge slots by a
    dense ``[Nc, 1] -> [Nc, Wc]`` tile per class (the mapper emitting a
    candidate per neighbour, BfsSpark.java:73-79, as pure broadcast).
  * **the shuffle** — per-edge bits move from src-grouped to dst-grouped
    slot order through a bit-packed Beneš network (2·log2 N - 1 dense
    butterfly stages, masks precomputed by native/benes.cpp).  This is the
    reference's `reduceByKey` shuffle (BfsSpark.java:90) compiled into a
    routing circuit.
  * **dst side (reduce)** — vertices bucketed by IN-degree class and
    RELABELED so classes are contiguous in vertex-id space; the reducer's
    min-merge becomes ``min(where(bit, src_id, INF), axis=1)`` per class —
    a dense row-min.  ``src_id`` tables store ORIGINAL ids so the canonical
    min-parent tie-break is preserved across relabeling.

A small second Beneš network reorders the [V] frontier bit-vector from
(relabeled) vertex order to out-class order before broadcasting.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from . import benes
from .csr import DeviceGraph, Graph, INF_DIST

#: Bump when the slot ordering / mask layout changes; layout caches
#: (bench.py .bench_cache) key on it.
LAYOUT_VERSION = 3


def _next_pow2(x: np.ndarray) -> np.ndarray:
    x = np.maximum(np.asarray(x, dtype=np.int64), 1)
    return np.int64(1) << np.int64(np.ceil(np.log2(x.astype(np.float64)))).astype(np.int64)


def _class_width(deg: np.ndarray) -> np.ndarray:
    """Degree-class width: degree rounded up to {2^k, 3*2^(k-1)} — one
    mantissa bit instead of pure powers of two.  Worst-case padding stays
    just under 50% (deg = 2^k + 1 -> width 3*2^(k-1)) vs 100% for pow2, and
    the average is far lower: on the scale-24 R-MAT net this keeps the slot
    count m1 ~= 1.13E instead of 1.45E, which decides whether the Benes
    network fits the next-lower power of two (halving every stage's traffic
    when it does)."""
    p2 = _next_pow2(deg)
    x = np.maximum(np.asarray(deg, dtype=np.int64), 1)
    three_quarter = (p2 // 4) * 3
    return np.where((p2 >= 4) & (x <= three_quarter), three_quarter, p2)


def _pow2_at_least(n: int) -> int:
    n = max(int(n), 32)
    return 1 << (n - 1).bit_length()


@dataclass(frozen=True)
class ClassSlice:
    """One degree class: vertices [va, vb) own slots [sa, sb), width w.

    ``vertex_major`` picks the slot ordering inside the class — chosen so
    the on-device 2-D view always has a LARGE trailing dimension (TPU
    (8,128) tiling makes small trailing dims pad ~100x):
      * vertex-major (slot = sa + p*w + r): view [Nc, w], reduce axis 1 —
        used when w >= Nc;
      * rank-major (slot = sa + r*Nc + p): view [w, Nc], reduce axis 0 —
        used when Nc > w (the common many-small-vertices classes).
    """

    width: int
    va: int
    vb: int
    sa: int
    sb: int
    vertex_major: bool = True

    @property
    def count(self) -> int:
        return self.vb - self.va


@dataclass(frozen=True)
class RelayGraph:
    """Static relay layout for one graph (single shard).

    All vertex-indexed engine state lives in the RELABELED id space
    (``new2old``/``old2new``); parent VALUES stay original ids.
    """

    num_vertices: int
    num_edges: int
    new2old: np.ndarray  # int32[V]
    old2new: np.ndarray  # int32[V]
    # src side
    vperm_masks: np.ndarray  # uint32[stages, Vp/32] — vertex-order -> out-order bits
    vperm_size: int
    out_classes: tuple[ClassSlice, ...]  # over out-order positions
    # shuffle
    net_masks: np.ndarray  # uint32[stages, N/32]
    net_size: int
    m2: int  # L2 (broadcast) slots actually used
    # dst side
    in_classes: tuple[ClassSlice, ...]  # over new-id vertex space
    src_l1: np.ndarray  # int32[M1] — ORIGINAL src id per L1 slot, INF padding


def _class_slices(widths_sorted: np.ndarray) -> list[ClassSlice]:
    """Contiguous runs of equal width -> ClassSlice list (slot offsets by
    cumulative width); orientation per class by the larger dimension."""
    slices = []
    slot = 0
    va = 0
    n = widths_sorted.shape[0]
    boundaries = np.flatnonzero(np.diff(widths_sorted)) + 1
    for vb in list(boundaries) + [n]:
        w = int(widths_sorted[va])
        nc = vb - va
        sb = slot + nc * w
        slices.append(
            ClassSlice(
                width=w, va=int(va), vb=int(vb), sa=int(slot), sb=int(sb),
                vertex_major=w >= nc,
            )
        )
        slot = sb
        va = vb
    return slices


def _slot_of(cs: ClassSlice, vertex_pos: np.ndarray, rank: np.ndarray) -> np.ndarray:
    """Slot id for (class-relative vertex position, within-vertex rank)."""
    if cs.vertex_major:
        return cs.sa + vertex_pos * cs.width + rank
    return cs.sa + rank * cs.count + vertex_pos


def _edge_slots(classes, pos_sorted, rank_sorted):
    """Slot ids for edges: ``pos_sorted`` is each edge's vertex position in
    class ordering; ``rank_sorted`` its within-vertex rank."""
    out = np.empty(pos_sorted.shape[0], dtype=np.int64)
    for cs in classes:
        sel = (pos_sorted >= cs.va) & (pos_sorted < cs.vb)
        out[sel] = _slot_of(cs, pos_sorted[sel] - cs.va, rank_sorted[sel])
    return out


def _rank_within_groups(group_sorted: np.ndarray) -> np.ndarray:
    """For a sorted group-id array, the rank of each element within its
    group (0-based)."""
    n = group_sorted.shape[0]
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    starts = np.flatnonzero(np.concatenate([[True], group_sorted[1:] != group_sorted[:-1]]))
    start_of = starts[np.searchsorted(starts, np.arange(n), side="right") - 1]
    return np.arange(n, dtype=np.int64) - start_of


@dataclass(frozen=True)
class ShardedRelayGraph:
    """Per-shard relay layouts with ONE unified class structure.

    The multi-device TPU-fast layout: shard ``s`` owns a contiguous block of
    the (globally relabeled) vertex space and holds the relay pipeline for
    exactly its owned destinations — its own vperm network, degree-class
    broadcast, Beneš edge net and src-id tables — while all shards share the
    SAME static shapes (class slices, network sizes), so one `shard_map`
    program runs everywhere and only the mask/table DATA differs per device
    (stacked on axis 0).  The per-superstep exchange is the bit-packed
    frontier all-gather of the sharded pull engine (1 bit/vertex over ICI);
    each shard's vperm network absorbs the packed all-gather layout, so the
    gathered words feed the butterflies directly with no unpack/repack.

    Unification pads each shard's degree classes to the max count over
    shards (dummy positions are routed guaranteed-zero inputs) and the
    owned-vertex block to a common multiple of 32.  ``new2old`` is -1 at
    dummy vertex slots.
    """

    num_vertices: int  # real V
    num_edges: int  # directed edges across all shards
    num_shards: int
    block: int  # owned vertex slots per shard (multiple of 32)
    new2old: np.ndarray  # int32[n*block]; -1 at dummies
    old2new: np.ndarray  # int32[V]
    vperm_masks: np.ndarray  # uint32[n, Sv, Vp/32]
    vperm_size: int
    out_classes: tuple[ClassSlice, ...]  # unified, over out-order positions
    net_masks: np.ndarray  # uint32[n, S, N/32]
    net_size: int
    m2: int
    in_classes: tuple[ClassSlice, ...]  # unified, over local [0, block)
    src_l1: np.ndarray  # int32[n, M1]; ORIGINAL src ids, INF padding


def _unified_class_slices(width_count_pairs) -> tuple[list[ClassSlice], int]:
    """Slices for a (width, count) list sorted by width; returns (slices,
    total positions)."""
    slices = []
    slot = 0
    va = 0
    for w, c in width_count_pairs:
        sb = slot + c * w
        slices.append(
            ClassSlice(width=int(w), va=int(va), vb=int(va + c),
                       sa=int(slot), sb=int(sb), vertex_major=w >= c)
        )
        slot = sb
        va += c
    return slices, va


def build_sharded_relay_graph(
    graph: Graph | DeviceGraph, num_shards: int
) -> ShardedRelayGraph:
    """Build per-shard relay layouts with a unified static structure.

    Vertices are partitioned into ``num_shards`` contiguous original-id
    ranges (the sharded pull engine's ownership rule), then relabeled within
    each shard so in-degree classes are contiguous; the global new-id space
    is the concatenation of shard blocks.
    """
    if not benes.native_available():
        raise RuntimeError("relay engine requires the native benes router")
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    from .csr import _sorted_by_dst, unpad_edges

    if isinstance(graph, DeviceGraph):
        src, dst = _sorted_by_dst(*unpad_edges(graph))
    else:
        src, dst = _sorted_by_dst(graph.src, graph.dst)
    src = src.astype(np.int64)
    dst = dst.astype(np.int64)
    v = graph.num_vertices
    e = int(src.shape[0])
    n = num_shards
    vblock = max((v + n - 1) // n, 1)

    indeg = np.bincount(dst, minlength=v)
    in_w = _class_width(indeg)  # >= 1; zero-indeg vertices get one INF slot

    # ---- unified in-classes: per-width counts maxed over shards ----------
    shard_of_old = np.minimum(np.arange(v, dtype=np.int64) // vblock, n - 1)
    widths_all = np.unique(in_w)
    cin = {}
    for w in widths_all.tolist():
        per_shard = np.bincount(shard_of_old[in_w == w], minlength=n)
        cin[w] = int(per_shard.max())
    block0 = sum(cin.values())
    pad = (-block0) % 32
    if pad:
        cin[1] = cin.get(1, 0) + pad
    in_pairs = sorted(cin.items())
    in_classes, block = _unified_class_slices(in_pairs)
    m1 = in_classes[-1].sb if in_classes else 0

    # ---- global relabel: shard-major, in-class-major, old-id-minor -------
    # Shard s's real width-w vertices occupy the first count_s(w) positions
    # of the unified class; the rest are dummies (-1 in new2old).
    new2old = np.full(n * block, -1, dtype=np.int64)
    old2new = np.empty(v, dtype=np.int64)
    in_widths_arr = np.array([w for w, _ in in_pairs], dtype=np.int64)
    in_va_arr = np.array([cs.va for cs in in_classes], dtype=np.int64)
    order = np.lexsort((np.arange(v), in_w, shard_of_old))  # shard, width, id
    ow = in_w[order]
    os_ = shard_of_old[order]
    # rank within each (shard, width) run (keys are sorted by construction)
    widx = np.searchsorted(in_widths_arr, ow)
    run_key = os_ * in_widths_arr.shape[0] + widx
    rank = _rank_within_groups(run_key)
    pos = os_ * block + in_va_arr[widx] + rank
    new2old[pos] = order
    old2new[order] = pos

    # ---- edge shard slices (dst-sorted, contiguous original ownership) ---
    bounds = np.searchsorted(dst, np.arange(n + 1, dtype=np.int64) * vblock)
    bounds[-1] = e

    # ---- unified out-classes over per-shard out-degrees ------------------
    # outdeg_s(u) = edges u -> (dst in shard s); vertices with none get NO
    # slots.  Kept sparse per shard (only src ids that appear): the dense
    # form would be O(n^2 * block).
    out_sparse = []  # per shard: (new ids with >=1 edge, ascending; widths)
    cout: dict[int, int] = {}
    for s in range(n):
        es, ee = bounds[s], bounds[s + 1]
        uids, ucounts = np.unique(old2new[src[es:ee]], return_counts=True)
        w = _class_width(ucounts)
        out_sparse.append((uids, w))
        for wv, c in zip(*np.unique(w, return_counts=True)):
            cout[int(wv)] = max(cout.get(int(wv), 0), int(c))
    out_pairs = sorted(cout.items())
    out_classes, out_space = _unified_class_slices(out_pairs)
    m2 = out_classes[-1].sb if out_classes else 0

    # ---- vperm geometry: the all-gathered packed words feed the network --
    # Packed layout: vertex (shard s', local e) sits at word s'*nw + e%nw,
    # bit e//nw; as a network element that is (e//nw)*NW + s'*nw + (e%nw)
    # with NW = Vp/32 >= n*nw (tail words are zero padding).  Dummy class
    # positions must receive guaranteed-zero inputs, so Vp also covers the
    # worst-case dummy count.
    nw = block // 32
    dmax = 0
    for _, uw in out_sparse:
        d = sum(c - int(np.count_nonzero(uw == wv)) for wv, c in out_pairs)
        dmax = max(dmax, d)
    vp = _pow2_at_least(max(n * block, out_space, v + dmax))
    nww = vp // 32
    new_ids = np.flatnonzero(new2old >= 0).astype(np.int64)  # real vertices
    eloc = new_ids % block
    e_net_real = (eloc // nw) * nww + (new_ids // block) * nw + (eloc % nw)
    e_net_all = np.full(n * block, -1, dtype=np.int64)
    e_net_all[new_ids] = e_net_real
    zero_pool = np.setdiff1d(
        np.arange(vp, dtype=np.int64), e_net_real, assume_unique=False
    )

    out_va = {cs.width: cs.va for cs in out_classes}
    vperm_stages = benes.num_stages(vp)
    net_size = _pow2_at_least(max(m1, m2))
    net_stages = benes.num_stages(net_size)
    vperm_masks = np.zeros((n, vperm_stages, vp // 32), dtype=np.uint32)
    net_masks = np.zeros((n, net_stages, net_size // 32), dtype=np.uint32)
    src_l1 = np.full((n, m1), INF_DIST, dtype=np.int32)
    outpos = np.full(n * block, -1, dtype=np.int64)  # reused per shard

    for s in range(n):
        uids_s, uw_s = out_sparse[s]
        # out-order positions for this shard's width>0 vertices
        outpos[:] = -1
        perm = np.full(vp, -1, dtype=np.int64)
        zp_used = 0
        for wv, c in out_pairs:
            ids = uids_s[uw_s == wv]  # ascending new ids
            va = out_va[wv]
            outpos[ids] = va + np.arange(ids.shape[0])
            perm[va : va + ids.shape[0]] = e_net_all[ids]
            ndum = c - ids.shape[0]
            if ndum:
                perm[va + ids.shape[0] : va + c] = zero_pool[
                    zp_used : zp_used + ndum
                ]
                zp_used += ndum
        used = np.zeros(vp, dtype=bool)
        used[perm[perm >= 0]] = True
        vperm_masks[s] = benes.route(
            benes.pad_perm(perm, vp, used), bit_major=True
        )

        # ---- big net: L2 (broadcast slots) -> L1 (dst-grouped slots) -----
        es, ee = bounds[s], bounds[s + 1]
        s_src, s_dst = src[es:ee], dst[es:ee]
        dstn = old2new[s_dst] - s * block  # local new ids in [0, block)
        ord1 = np.lexsort((s_src, dstn))
        rank1 = _rank_within_groups(dstn[ord1])
        l1_pos = np.empty(ee - es, dtype=np.int64)
        l1_pos[ord1] = _edge_slots(in_classes, dstn[ord1], rank1)
        src_l1[s, l1_pos] = s_src.astype(np.int32)  # ORIGINAL ids

        srcpos = outpos[old2new[s_src]]
        ord2 = np.lexsort((s_dst, srcpos))
        rank2 = _rank_within_groups(srcpos[ord2])
        l2_pos = np.empty(ee - es, dtype=np.int64)
        l2_pos[ord2] = _edge_slots(out_classes, srcpos[ord2], rank2)

        net = np.full(net_size, -1, dtype=np.int64)
        net[l1_pos] = l2_pos
        used = np.zeros(net_size, dtype=bool)
        used[l2_pos] = True
        net_masks[s] = benes.route(
            benes.pad_perm(net, net_size, used), bit_major=True
        )

    return ShardedRelayGraph(
        num_vertices=v,
        num_edges=e,
        num_shards=n,
        block=block,
        new2old=new2old.astype(np.int32),
        old2new=old2new.astype(np.int32),
        vperm_masks=vperm_masks,
        vperm_size=vp,
        out_classes=tuple(out_classes),
        net_masks=net_masks,
        net_size=net_size,
        m2=m2,
        in_classes=tuple(in_classes),
        src_l1=src_l1,
    )


def build_relay_graph(graph: Graph | DeviceGraph) -> RelayGraph:
    """Build the full relay layout (host side, once per graph).

    Requires the native Beneš router; raises RuntimeError when unavailable.
    """
    if not benes.native_available():
        raise RuntimeError("relay engine requires the native benes router")
    if isinstance(graph, DeviceGraph):
        if graph.num_shards != 1:
            raise ValueError("build_relay_graph expects a single-shard graph")
        flat_src = graph.src.reshape(-1)
        flat_dst = graph.dst.reshape(-1)
        keep = flat_dst != graph.sentinel
        src, dst = flat_src[keep].astype(np.int64), flat_dst[keep].astype(np.int64)
        v = graph.num_vertices
    else:
        src, dst = graph.src.astype(np.int64), graph.dst.astype(np.int64)
        v = graph.num_vertices
    e = int(src.shape[0])

    indeg = np.bincount(dst, minlength=v)
    outdeg = np.bincount(src, minlength=v)
    in_w = _class_width(indeg)  # zero-indeg vertices get one INF slot
    out_w = _class_width(outdeg)

    # ---- relabel by (in-class width, old id): in-classes contiguous -------
    new2old = np.lexsort((np.arange(v), in_w)).astype(np.int64)
    old2new = np.empty(v, dtype=np.int64)
    old2new[new2old] = np.arange(v)

    # ---- dst side (L1): slots per new-vertex, classes contiguous ----------
    in_w_new = in_w[new2old]
    in_classes = _class_slices(in_w_new)
    slot_start = np.zeros(v + 1, dtype=np.int64)
    np.cumsum(in_w_new, out=slot_start[1:])
    m1 = int(slot_start[v])

    dstn = old2new[dst]
    ord1 = np.lexsort((src, dstn))
    rank1 = _rank_within_groups(dstn[ord1])
    l1_pos = np.empty(e, dtype=np.int64)
    l1_pos[ord1] = _edge_slots(in_classes, dstn[ord1], rank1)

    src_l1 = np.full(m1, INF_DIST, dtype=np.int32)
    src_l1[l1_pos] = src.astype(np.int32)  # ORIGINAL ids: canonical min-parent

    # ---- src side (L2): out-class order over new ids ----------------------
    out_w_new = out_w[new2old]
    outorder2new = np.lexsort((np.arange(v), out_w_new)).astype(np.int64)
    new2outpos = np.empty(v, dtype=np.int64)
    new2outpos[outorder2new] = np.arange(v)
    out_classes = _class_slices(out_w_new[outorder2new])
    slot2_start = np.zeros(v + 1, dtype=np.int64)
    np.cumsum(out_w_new[outorder2new], out=slot2_start[1:])
    m2 = int(slot2_start[v])

    srcpos = new2outpos[old2new[src]]
    ord2 = np.lexsort((dst, srcpos))
    rank2 = _rank_within_groups(srcpos[ord2])
    l2_pos = np.empty(e, dtype=np.int64)
    l2_pos[ord2] = _edge_slots(out_classes, srcpos[ord2], rank2)

    # ---- small network: vertex-order bits -> out-order bits ---------------
    vp = _pow2_at_least(v)
    vperm = np.full(vp, -1, dtype=np.int64)
    vperm[:v] = outorder2new  # output j (out-order) <- input new-id
    used = np.zeros(vp, dtype=bool)
    used[outorder2new] = True
    vperm = benes.pad_perm(vperm, vp, used)
    vperm_masks = benes.route(vperm, bit_major=True)

    # ---- big network: L2 slot -> L1 slot ----------------------------------
    n = _pow2_at_least(max(m1, m2))
    net = np.full(n, -1, dtype=np.int64)
    net[l1_pos] = l2_pos
    used = np.zeros(n, dtype=bool)
    used[l2_pos] = True
    net = benes.pad_perm(net, n, used)
    net_masks = benes.route(net, bit_major=True)

    return RelayGraph(
        num_vertices=v,
        num_edges=e,
        new2old=new2old.astype(np.int32),
        old2new=old2new.astype(np.int32),
        vperm_masks=vperm_masks,
        vperm_size=vp,
        out_classes=tuple(out_classes),
        net_masks=net_masks,
        net_size=n,
        m2=m2,
        in_classes=tuple(in_classes),
        src_l1=src_l1,
    )
