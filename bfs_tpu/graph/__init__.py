from .csr import Graph, DeviceGraph, build_device_graph, INF_DIST, NO_PARENT  # noqa: F401
from .io import read_sedgewick, parse_sedgewick, read_snap_edge_list, write_sedgewick  # noqa: F401
from .generators import rmat_graph, gnm_graph, path_graph, rmat_edges  # noqa: F401
from .vertex import Color, Vertex  # noqa: F401
