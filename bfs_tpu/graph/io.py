"""Graph file ingest: Sedgewick text format and SNAP edge lists.

Reference parity:
  * Sedgewick format (``V\\nE\\nv w\\n...``) reader — mirrors
    ``Graph(In)`` (sequential-libs/algs4.jar!/Graph.java:85-94) and the header
    handling in ``GraphFileUtil.convert`` (GraphFileUtil.java:48-63: read V,
    skip the E line, then read edges).
  * Bi-directing of undirected edges (GraphFileUtil.java:64-65).
  * SNAP edge lists cover the LiveJournal / soc-Pokec configs in
    BASELINE.json.
"""

from __future__ import annotations

import io as _io
import os

import numpy as np

from .csr import Graph


def read_sedgewick(path: str | os.PathLike, *, directed: bool = False) -> Graph:
    """Read a Sedgewick-format graph file: line 1 = V, line 2 = E, then E
    lines ``v w``.  Undirected by default; every edge inserted both ways.

    Uses the native parser (native/graph_gen.cpp) for large files when
    available; identical results via the Python path otherwise."""
    path = os.fspath(path)
    try:
        from .native_gen import native_available, read_sedgewick_native

        if native_available() and os.path.getsize(path) > 1 << 20:
            v, src, dst = read_sedgewick_native(path)
            pairs = np.stack([src, dst], axis=1)
            if directed:
                return Graph.from_directed_edges(v, pairs)
            return Graph.from_undirected_edges(v, pairs)
    except (ImportError, RuntimeError):
        pass
    with open(path, "r") as f:
        return parse_sedgewick(f.read(), directed=directed)


def parse_sedgewick(text: str, *, directed: bool = False) -> Graph:
    data = np.array(text.split(), dtype=np.int64)
    if data.size < 2:
        raise ValueError("Sedgewick graph needs at least V and E header lines")
    v, e = int(data[0]), int(data[1])
    if v < 0 or e < 0:
        raise ValueError("number of vertices/edges must be nonnegative")
    if data.size < 2 + 2 * e:
        raise ValueError(f"expected {e} edges, file has {(data.size - 2) // 2}")
    pairs = data[2 : 2 + 2 * e].reshape(e, 2).astype(np.int32)
    if directed:
        return Graph.from_directed_edges(v, pairs)
    return Graph.from_undirected_edges(v, pairs)


def write_sedgewick(graph: Graph, path: str | os.PathLike) -> None:
    """Write the undirected Sedgewick form: each bi-directed pair once,
    preserving parallel edges (multigraphs round-trip exactly)."""
    mask = graph.src < graph.dst
    pairs = np.stack([graph.src[mask], graph.dst[mask]], axis=1)
    # A self-loop bi-directs to TWO (v, v) copies; write one line per loop.
    loops = graph.src == graph.dst
    if loops.any():
        lv = graph.src[loops]
        if lv.size % 2 != 0:
            raise ValueError("odd self-loop copy count; graph is not bi-directed")
        loop_pairs = np.stack([np.sort(lv)[::2], np.sort(lv)[::2]], axis=1)
        pairs = np.concatenate([pairs, loop_pairs]) if pairs.size else loop_pairs
    buf = _io.StringIO()
    buf.write(f"{graph.num_vertices}\n{len(pairs)}\n")
    for u, w in pairs:
        buf.write(f"{u} {w}\n")
    with open(path, "w") as f:
        f.write(buf.getvalue())


def read_snap_edge_list(
    path: str | os.PathLike,
    *,
    undirected: bool = True,
    num_vertices: int | None = None,
) -> Graph:
    """Read a SNAP-style edge list (``# comment`` lines, then ``u\\tv`` pairs).

    Vertex ids are used as-is; ``num_vertices`` defaults to max id + 1.
    ``undirected=True`` bi-directs edges like the Sedgewick loader.

    Real SNAP graphs run to tens of millions of lines (soc-LiveJournal: 69M),
    so the hot path is NumPy's C tokenizer (``np.loadtxt``, ~7M lines/s) —
    not a per-line Python loop.
    """
    data = np.loadtxt(path, dtype=np.int64, comments=["#", "%"], ndmin=2)
    if data.size and data.shape[1] != 2:
        raise ValueError(
            f"expected u-v edge lines, got {data.shape[1]} columns"
        )
    pairs = data.reshape(-1, 2)
    v = int(pairs.max()) + 1 if pairs.size else 0
    if num_vertices is not None:
        v = max(v, num_vertices)
    pairs = pairs.astype(np.int32)
    if undirected:
        return Graph.from_undirected_edges(v, pairs)
    return Graph.from_directed_edges(v, pairs)


def write_snap_edge_list(
    pairs: np.ndarray,
    path: str | os.PathLike,
    *,
    name: str = "synthetic",
    num_vertices: int | None = None,
) -> None:
    """Write a directed edge list in SNAP's format: ``# Directed graph`` -style
    comment header, then tab-separated ``u\\tv`` lines."""
    pairs = np.asarray(pairs)
    header = (
        f"# Directed graph (each unordered pair of nodes is saved once): {name}\n"
        f"# Nodes: {num_vertices if num_vertices is not None else int(pairs.max()) + 1}"
        f" Edges: {pairs.shape[0]}\n"
        "# FromNodeId\tToNodeId\n"
    )
    with open(path, "w") as f:
        f.write(header)
        np.savetxt(f, pairs, fmt="%d", delimiter="\t")
