"""Per-cell 2D grid layouts over the sharded relay vertex space (ISSUE 17).

The classic 2D BFS decomposition (the design both "Parallel Distributed
BFS on the Kepler Architecture", arXiv 1408.1605, and "Compression and
Sieve", arXiv 1208.5542, build on) places the adjacency on an ``r x c``
logical mesh: cell ``(i, j)`` holds exactly the edges whose SOURCE falls
in the row stripe ``R_i`` and whose DESTINATION falls in the column
stripe ``C_j``.  A superstep then needs two small collectives instead of
one O(V) one — a frontier broadcast along the column axis (each cell
learns the ``R_i`` frontier, |R_i| = V/r bits) and a candidate min-reduce
along the row axis (each mesh column settles its ``C_j`` destinations,
|C_j| = V/c candidates) — per-chip wire O(V/r + V/c) = O(V/√n) on a
square mesh, vs the 1D mesh's O(V).

This module is the HOST side: it derives the per-cell edge layout from
the existing :class:`~bfs_tpu.graph.relay.ShardedRelayGraph` built at
``n = r*c`` shards, so the grid reuses the 1D relabeling, block
structure, own-word tables and checkpoint shard layout unchanged:

  * vertex block ``b`` (the 1D shard) is owned by cell ``(b // c,
    b % c)`` — mesh-row-major, so the row stripe ``R_i`` = blocks
    ``[i*c, (i+1)*c)`` is CONTIGUOUS in the global relabeled space and
    the column-axis all-gather of owned words lands the ``R_i`` frontier
    words already in order;
  * the column stripe ``C_j`` = blocks ``{i'*c + j}`` (strided), local
    destination id ``i'*block + local`` — the row-axis reduce space;
  * per-edge candidate values are ORIGINAL source ids
    (``src_l1[shard][slot]``, the MXU arm's key flavor), because a
    cross-cell min must be over a shard-independent total order — the
    canonical min-parent tie-break every engine shares.

Since the 1D per-shard adjacency (``srg.adj_indptr`` — a CSR over GLOBAL
relabeled source ids) stores edges sorted by source, the edges of cell
``(i, j)`` are r contiguous slices of the 1D CSRs of the shards in
``C_j``: no edge is rebuilt, only re-grouped.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

#: Unreached / min-identity sentinel for original-id candidates —
#: the same lattice top as ops/packed.PACKED_SENTINEL and
#: graph/adj_tiles.KEY_SENTINEL.
GRID_KEY_SENTINEL = np.uint32(0xFFFFFFFF)


@dataclass(frozen=True)
class GridLayout:
    """Per-cell edge layout for an ``r x c`` grid over an n-shard
    ShardedRelayGraph (``n == r*c``).  Arrays are stacked over cells
    (leading dim ``n``, mesh-row-major: cell ``(i, j)`` at ``i*c + j``)
    and padded to the max per-cell edge count for uniform SPMD shapes.
    """

    r: int
    c: int
    block: int
    emax: int  # padded per-cell edge count (>= 1)
    #: int32[n, emax] — edge source, LOCAL to the cell's R_i stripe
    #: (``global_new - i*c*block``); 0 at padding (its key is the
    #: sentinel and its dst is out of range, so it can never win).
    esrc: np.ndarray
    #: int32[n, emax] — edge destination, LOCAL to the cell's C_j stripe
    #: (``pos(i')*block + local`` with pos(i') = i'); ``r*block`` at
    #: padding (out of range -> scatter mode='drop').
    edst: np.ndarray
    #: uint32[n, emax] — ORIGINAL source id; GRID_KEY_SENTINEL at padding.
    ekey: np.ndarray
    #: int32[n, c*block + 2] — CSR over the local source space for the
    #: push (frontier-gather) body; last entry repeated, so the
    #: frontier-list fill index ``c*block`` reads degree 0.
    indptr: np.ndarray

    @property
    def num_cells(self) -> int:
        return self.r * self.c


def parse_mesh_spec(spec: str) -> tuple[int, int]:
    """``"rxc"`` -> ``(r, c)``.  The 1D degenerate is ``"1x8"``; a bare
    integer ``"8"`` is accepted as ``1x8`` so BENCH_MESH keeps working."""
    s = str(spec).strip().lower()
    if "x" in s:
        rs, _, cs = s.partition("x")
        r, c = int(rs), int(cs)
    else:
        r, c = 1, int(s)
    if r < 1 or c < 1:
        raise ValueError(f"mesh spec {spec!r}: both axes must be >= 1")
    return r, c


def build_grid_layout(srg, r: int, c: int) -> GridLayout:
    """Derive the per-cell edge layout from an ``r*c``-shard
    ShardedRelayGraph (host-side, memoized on the layout object by the
    caller).  Edges come out of the 1D CSRs as contiguous slices; within
    a cell they are regrouped by local source (stable), which only
    affects iteration order — every consumer is a min-scatter."""
    from ..parallel.sharded import _sharded_adj_keys

    n = r * c
    if srg.num_shards != n:
        raise ValueError(
            f"grid {r}x{c} needs a {n}-shard ShardedRelayGraph, "
            f"got {srg.num_shards} shards"
        )
    if srg.adj_dst is None:
        raise ValueError(
            "this ShardedRelayGraph ships no per-shard adjacency "
            "(pre-exchange layout); rebuild with build_sharded_relay_graph"
        )
    block = srg.block
    keys_all = _sharded_adj_keys(srg)  # int32[n, emax_1d]; orig src ids
    cells_src, cells_dst, cells_key = [], [], []
    for i in range(r):
        lo, hi = i * c * block, (i + 1) * c * block
        for j in range(c):
            srcs, dsts, keys = [], [], []
            for i2 in range(r):
                b = i2 * c + j  # dst shard (block) at stripe position i2
                ip = srg.adj_indptr[b].astype(np.int64)
                e0, e1 = int(ip[lo]), int(ip[hi])
                if e1 <= e0:
                    continue
                counts = np.diff(ip[lo:hi + 1])
                srcs.append(
                    np.repeat(
                        np.arange(c * block, dtype=np.int64), counts
                    ).astype(np.int32)
                )
                dsts.append(srg.adj_dst[b, e0:e1] + np.int32(i2 * block))
                keys.append(keys_all[b, e0:e1].astype(np.uint32))
            if srcs:
                es = np.concatenate(srcs)
                order = np.argsort(es, kind="stable")
                cells_src.append(es[order])
                cells_dst.append(np.concatenate(dsts)[order])
                cells_key.append(np.concatenate(keys)[order])
            else:
                cells_src.append(np.zeros(0, np.int32))
                cells_dst.append(np.zeros(0, np.int32))
                cells_key.append(np.zeros(0, np.uint32))
    emax = max(1, max(e.size for e in cells_src))
    esrc = np.zeros((n, emax), np.int32)
    edst = np.full((n, emax), r * block, np.int32)
    ekey = np.full((n, emax), GRID_KEY_SENTINEL, np.uint32)
    indptr = np.zeros((n, c * block + 2), np.int32)
    for cell in range(n):
        es, ed, ek = cells_src[cell], cells_dst[cell], cells_key[cell]
        esrc[cell, : es.size] = es
        edst[cell, : ed.size] = ed
        ekey[cell, : ek.size] = ek
        counts = np.bincount(es, minlength=c * block)
        ip = np.zeros(c * block + 2, np.int64)
        ip[1 : c * block + 1] = np.cumsum(counts)
        ip[c * block + 1] = ip[c * block]  # repeated: fill index reads deg 0
        indptr[cell] = ip.astype(np.int32)
    return GridLayout(
        r=r, c=c, block=block, emax=emax,
        esrc=esrc, edst=edst, ekey=ekey, indptr=indptr,
    )


def grid_layout_for(srg, r: int, c: int) -> GridLayout:
    """Memoized :func:`build_grid_layout` on the (frozen) layout object —
    layout data, like the masks and adjacency flavors; must not land
    inside a caller's timed repeats."""
    key = f"_grid_layout_{r}x{c}"
    cached = getattr(srg, key, None)
    if cached is None:
        cached = build_grid_layout(srg, r, c)
        object.__setattr__(srg, key, cached)
    return cached


def grid_tile_placement(srg, r: int, c: int, builder: str | None = None):
    """Tile-superblock placement over the grid (the MXU tile-space view
    of the same partition): which of PR 15's per-shard 128x128 adjacency
    tiles are RESIDENT on each cell.  A tile of shard ``b`` (column
    stripe ``C_{b % c}``) lands on cell ``(i, b % c)`` where ``i`` is the
    row stripe its source tile row falls into — the tile analogue of the
    edge regrouping above, reusing :func:`~bfs_tpu.graph.adj_tiles.
    build_adj_tiles_sharded` verbatim.

    Returns ``{"cells": int32[r, c] resident-tile counts,
    "total_tiles": int, "tile_rows_per_stripe": int}`` — layout evidence
    for the bench detail and the placement test (each shard's tiles
    partition exactly across its mesh column's r cells)."""
    from .adj_tiles import TILE, build_adj_tiles_sharded

    block = srg.block
    per = build_adj_tiles_sharded(srg, builder=builder)
    counts = np.zeros((r, c), np.int64)
    stripe_rows = c * block  # sources per row stripe
    for b, at in enumerate(per):
        j = b % c
        row_src = at.row_idx[: at.nt].astype(np.int64) * TILE
        # A 128-row source tile can straddle a stripe boundary only when
        # c*block is not a multiple of 128; blocks are 1024-multiples in
        # every shipped config, but clamp for odd test blocks.
        stripe = np.clip(row_src // stripe_rows, 0, r - 1)
        counts[:, j] += np.bincount(stripe, minlength=r)
    return {
        "cells": counts.astype(np.int64),
        "total_tiles": int(sum(at.nt for at in per)),
        "tile_rows_per_stripe": int(stripe_rows // TILE),
    }
