"""Serving driver: a long-lived BFS query server CLI (``bfs-tpu-serve``).

Where ``run_parallel`` is the reference's one-shot ``BfsSpark.main`` parity
driver, this is the serving-era entry point: build/ingest a graph ONCE,
keep its layouts and compiled executables warm in a
:class:`~bfs_tpu.serve.BfsServer`, and answer a stream of queries.

Two modes:

  * **demo** (default) — submit ``--queries`` random single/multi-source
    queries through the micro-batcher, oracle-check a sample, and print the
    serve report (p50/p99, batch sizes, cache hit rates).
  * **--repl** — read queries from stdin, one per line (``3`` for
    single-source 3; ``3,17,42`` for collapsed multi-source), answer with
    reachable-vertex count / eccentricity / superstep count per query.

Usage:
    python -m bfs_tpu.runners.run_serve [--rmat SCALE | --gnm V E |
        --graph FILE] [--engine pull|push|relay] [--max-batch B]
        [--tick-ms T] [--queries N] [--repl] [--check]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from ..graph.csr import INF_DIST
from ..utils.logging import get_logger

logger = get_logger(__name__)


def build_graph(args):
    if args.graph:
        from ..graph.io import read_sedgewick

        return read_sedgewick(args.graph), args.graph
    if args.gnm:
        from ..graph.generators import gnm_graph

        v, e = args.gnm
        return gnm_graph(v, e, seed=args.seed), f"gnm_{v}_{e}"
    from ..graph.generators import rmat_graph

    return (
        rmat_graph(args.rmat, args.edge_factor, seed=args.seed),
        f"rmat_s{args.rmat}_ef{args.edge_factor}",
    )


def make_server(args, metrics=None):
    import dataclasses

    from ..serve import DEFAULT_RETRY_POLICY, BfsServer, GraphRegistry

    registry = GraphRegistry(
        device_budget_bytes=(
            args.budget_mb * (1 << 20) if args.budget_mb else None
        ),
        metrics=metrics,
        # Persistent layout bundles: a second serving process registering
        # the same graph loads the finished layout from disk instead of
        # rebuilding it (--cache-dir "" disables).
        layout_cache=args.cache_dir or None,
    )
    return BfsServer(
        registry,
        engine=args.engine,
        max_batch=args.max_batch,
        tick_s=args.tick_ms / 1e3,
        queue_depth=args.queue_depth,
        oracle_max_vertices=args.oracle_max_vertices,
        metrics=metrics,
        # Transient device-path failures retry with backoff before the
        # oracle degradation kicks in (bfs_tpu/resilience/retry.py);
        # --retries 1 restores the old degrade-on-first-failure behavior.
        # Only the attempt count is tunable here — the delays stay the
        # serving-tuned ones (short: backoff sleeps block the single
        # scheduler thread, so every queued query on every graph waits).
        retry_policy=dataclasses.replace(
            DEFAULT_RETRY_POLICY, max_attempts=max(1, args.retries)
        ),
        # Self-healing knobs (ISSUE 9): breaker per compiled executable,
        # hung-call watchdog, sampled on-device integrity checks.
        breaker_failures=args.breaker_failures,
        breaker_cooldown_s=args.breaker_cooldown_s,
        watchdog_s=args.watchdog_s,
        verify_sample=args.verify_sample,
    )


def _describe(reply) -> str:
    dist = reply.dist if reply.dist.ndim == 1 else reply.dist.min(axis=0)
    reached = int((dist != INF_DIST).sum())
    ecc = int(dist[dist != INF_DIST].max(initial=0))
    return (
        f"sources={reply.sources.tolist()} reached={reached} "
        f"eccentricity={ecc} supersteps={reply.num_levels} "
        f"status={reply.record.status} batch={reply.record.batch_size} "
        f"latency={reply.record.total_s * 1e3:.1f}ms"
    )


def repl(server, name: str, num_vertices: int) -> None:
    print(
        f"serving {name!r} (V={num_vertices}); enter a source id or a "
        "comma-separated source list, Ctrl-D to quit",
        flush=True,
    )
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            sources = [int(tok) for tok in line.replace(",", " ").split()]
            fut = (
                server.query(name, sources[0])
                if len(sources) == 1
                else server.query_multi(name, sources)
            )
            print(_describe(fut.result(timeout=600)), flush=True)
        except Exception as exc:
            print(f"error: {exc}", file=sys.stderr, flush=True)


def demo(server, name: str, graph, args) -> dict:
    rng = np.random.default_rng(args.seed)
    v = graph.num_vertices
    futures = []
    for _ in range(args.queries):
        if rng.random() < args.multi_frac:
            width = int(rng.integers(2, max(args.multi_width, 3)))
            srcs = rng.integers(0, v, size=width).tolist()
            futures.append((server.query_multi(name, srcs), srcs))
        else:
            s = int(rng.integers(0, v))
            futures.append((server.query(name, s), [s]))
    checked = wrong = 0
    for fut, srcs in futures:
        reply = fut.result(timeout=600)
        if args.check:
            from ..oracle.bfs import check, queue_bfs

            # Both single and collapsed replies are 1-D multi-source trees.
            od, _ = queue_bfs(graph, srcs)
            ok = (
                np.array_equal(reply.dist, od)
                and check(graph, reply.dist, reply.parent, srcs) == []
            )
            checked += 1
            wrong += 0 if ok else 1
    report = server.report()
    report["checked"] = checked
    report["wrong"] = wrong
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--graph", help="Sedgewick-format problem file")
    src.add_argument("--rmat", type=int, default=10, help="R-MAT scale")
    src.add_argument("--gnm", type=int, nargs=2, metavar=("V", "E"))
    ap.add_argument("--edge-factor", type=int, default=8)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--engine", default="pull", choices=("pull", "push", "relay"))
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--tick-ms", type=float, default=2.0)
    ap.add_argument("--queue-depth", type=int, default=1024)
    ap.add_argument("--budget-mb", type=int, default=0,
                    help="device layout budget in MiB (0 = unlimited)")
    ap.add_argument("--oracle-max-vertices", type=int, default=0,
                    help="serve graphs at/under this size sequentially")
    ap.add_argument("--retries", type=int, default=3,
                    help="max device-path attempts per batch before oracle "
                    "degradation (transient failures only; 1 = no retry)")
    ap.add_argument("--breaker-failures", type=int, default=3,
                    help="consecutive permanent failures per compiled "
                    "executable before its circuit opens and ticks "
                    "short-circuit to the degraded path")
    ap.add_argument("--breaker-cooldown-s", type=float, default=5.0,
                    help="seconds an open circuit waits before admitting "
                    "the half-open canary batch")
    ap.add_argument("--watchdog-s", type=float, default=60.0,
                    help="hung-call watchdog default budget in seconds "
                    "(p99-informed per executable once history exists; "
                    "0 disables)")
    ap.add_argument("--verify-sample", type=int, default=0,
                    help="re-verify one answered root on device every Kth "
                    "executed tick (~28-byte verdict pull; a failed verdict "
                    "quarantines the executable; 0 disables)")
    ap.add_argument("--queries", type=int, default=64, help="demo query count")
    ap.add_argument("--multi-frac", type=float, default=0.25)
    ap.add_argument("--multi-width", type=int, default=4)
    ap.add_argument("--check", action="store_true",
                    help="oracle-check every demo reply")
    ap.add_argument("--repl", action="store_true", help="interactive mode")
    from ..config import layout_cache_dir

    ap.add_argument("--cache-dir", default=layout_cache_dir(),
                    help="persistent layout-bundle dir ('' disables; "
                    "default: the shared artifact-cache root)")
    args = ap.parse_args(argv)

    # Compile caches before the first trace: a restarted server re-loads
    # its executables instead of re-compiling them (the serving cold path).
    from ..config import enable_compile_cache

    logger.info("compile caches: %s", enable_compile_cache())

    graph, name = build_graph(args)
    logger.info(
        "Registering %s: V=%d, E=%d (directed), engine=%s",
        name, graph.num_vertices, graph.num_edges, args.engine,
    )
    with make_server(args) as server:
        t0 = time.perf_counter()
        server.register(name, graph)
        server.query(name, 0).result(timeout=600)  # warm layout + first shape
        li = server.registry.layout_info()
        if li:  # non-relay engines build no relay layout
            logger.info(
                "Graph registered and warm in %.2f s (layout %s, "
                "builder=%s, build %.2f s)",
                time.perf_counter() - t0,
                li.get("cache", "memo"),
                li.get("builder", "host"),
                float(li.get("build_seconds", -1.0)),
            )
        else:
            logger.info(
                "Graph registered and warm in %.2f s",
                time.perf_counter() - t0,
            )
        if args.repl:
            repl(server, name, graph.num_vertices)
            report = server.report()
        else:
            report = demo(server, name, graph, args)
        print(json.dumps(report, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
