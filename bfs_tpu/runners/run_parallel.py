"""Parallel BFS driver: the ``BfsSpark.main`` equivalent (BfsSpark.java:43-120).

For each configured problem file: ingest (the ``GraphFileUtil.convert`` stage),
then run the superstep engine with per-superstep timing (Stopwatch methodology
of BfsSpark.java:59,63,111-112 — compute only, ingest and compile excluded),
optional per-superstep text dumps (``problemFile_i`` parity) and .npz
checkpoints, and a final TEPS summary.

Usage:
    python -m bfs_tpu.runners.run_parallel [service.properties] [--fused]
        [--mesh-graph N] [--mesh-batch N] [--dump] [--source S] [--resume]

``--resume`` restarts a crashed stepped run from its newest valid
``.ckpt_<level>.npz`` (checkpoints are written atomically and validated on
load — bfs_tpu/utils/checkpoint.py — so a kill mid-dump can neither leave
a half-written file under the final name nor poison the resumed state; a
torn newest checkpoint falls back to the one before it).
"""

from __future__ import annotations

import argparse
import os

import jax

from ..config import ServiceConfiguration
from ..graph.io import read_sedgewick
from ..graph.vertex import serialize_state, initial_state_vertices
from ..models.bfs import SuperstepRunner, bfs
from ..oracle.bfs import check
from ..parallel.sharded import bfs_sharded, make_mesh
from ..utils.checkpoint import load_latest_checkpoint, save_checkpoint
from ..utils.logging import get_logger
from ..utils.metrics import RunMetrics
from ..utils.timing import Stopwatch

logger = get_logger(__name__)


def run_problem_file(
    path: str,
    *,
    source: int = 0,
    engine: str = "push",
    dump: bool = False,
    checkpoint_every: int = 0,
    work_dir: str = ".",
    resume: bool = False,
) -> RunMetrics:
    """Stepped run over one problem file with full observability."""
    logger.info("Processing problem file: %s (engine=%s)", path, engine)
    graph = read_sedgewick(path)
    metrics = RunMetrics(num_vertices=graph.num_vertices, num_edges=graph.num_edges)
    runner = SuperstepRunner(graph, engine=engine)
    base = os.path.join(work_dir, os.path.basename(path))

    if dump:
        with open(f"{base}_0", "w") as f:
            f.write("\n".join(v.serialize() for v in initial_state_vertices(graph, source)))

    state = runner.init(source)
    resumed_at = None
    if resume:
        found = load_latest_checkpoint(
            base, expect={"source": source, "engine": engine}
        )
        if found is not None:
            state, resumed_at, ckpt_path = found
            logger.info(
                "Resuming from %s (superstep %d)", ckpt_path, resumed_at
            )
            if not bool(state.changed):
                logger.info(
                    "checkpoint state already converged; nothing to re-run"
                )
        else:
            logger.info("No valid checkpoint under %s.ckpt_*; fresh run", base)
    sw = Stopwatch()
    while bool(state.changed):
        sw.reset().start()
        state = runner.step(state)
        jax.block_until_ready(state)
        sw.stop()
        level = int(state.level)
        metrics.record(level, runner.frontier_size(state), sw.elapsed_s)
        if dump:
            dist, parent, frontier = runner.to_original(state, source=source)
            with open(f"{base}_{level}", "w") as f:
                f.write(
                    serialize_state(graph, dist, parent, frontier, source=source)
                )
        if checkpoint_every and level % checkpoint_every == 0:
            save_checkpoint(
                f"{base}.ckpt_{level}.npz", state,
                source=source, engine=engine,
            )

    for line in metrics.log_lines():
        logger.info("%s", line)
    if resumed_at is not None:
        # Metrics cover only the post-resume tail: a full-run TEPS claim
        # (num_edges / tail seconds) would be inflated by everything the
        # checkpointed process already paid for, so report the segment as
        # a segment.
        logger.info(
            "Total %s: resumed at superstep %d; segment of %d supersteps, "
            "%.3f ms (segment-only timings, not a full-run TEPS)",
            os.path.basename(path),
            resumed_at,
            metrics.num_levels,
            metrics.total_seconds * 1e3,
        )
    else:
        logger.info(
            "Total %s: %d supersteps, %.3f ms, %.2f MTEPS",
            os.path.basename(path),
            metrics.num_levels,
            metrics.total_seconds * 1e3,
            metrics.teps() / 1e6,
        )
    dist, parent, _ = runner.to_original(state, source=source)
    violations = check(graph, dist, parent, source)
    if violations:
        for v in violations[:10]:
            logger.error("invariant violation: %s", v)
        raise AssertionError(f"BFS invariants violated on {path}")
    return metrics


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("config", nargs="?", default="service.properties")
    ap.add_argument("--fused", action="store_true", help="one while_loop, no per-superstep observability")
    ap.add_argument(
        "--engine", default=None, choices=("push", "pull", "relay"),
        help="superstep layout; default: 'pull' for --fused (bfs()'s default),"
        " 'push' for the stepped mode (historical default)",
    )
    ap.add_argument("--sharded", action="store_true", help="use the mesh-sharded engine")
    ap.add_argument("--mesh-graph", type=int, default=None)
    ap.add_argument("--mesh-batch", type=int, default=None)
    ap.add_argument("--dump", action="store_true")
    ap.add_argument("--source", type=int, default=None)
    ap.add_argument(
        "--resume", action="store_true",
        help="resume a stepped run from its newest valid checkpoint "
        "(requires checkpoint-every > 0 in the config to have written any)",
    )
    args = ap.parse_args(argv)

    # Persistent compile caches, set before the first trace so the driver
    # never re-pays a compile it has already done in a previous process
    # (bfs_tpu/config.py; BFS_TPU_CACHE_DIR relocates everything).
    from ..config import enable_compile_cache

    logger.info("compile caches: %s", enable_compile_cache())

    cfg = (
        ServiceConfiguration.load(args.config)
        if os.path.exists(args.config)
        else ServiceConfiguration()
    )
    logger.info("Application name: %s", cfg.app_name)
    source = args.source if args.source is not None else cfg.source
    # CLI flags override service.properties mesh keys; 0/None = all devices.
    mesh_graph = args.mesh_graph if args.mesh_graph is not None else (cfg.mesh_graph or None)
    mesh_batch = args.mesh_batch if args.mesh_batch is not None else cfg.mesh_batch
    if args.sharded and not args.fused:
        logger.info("--sharded implies the fused engine; enabling --fused")
        args.fused = True
    for path in cfg.problem_files or ():
        if args.fused:
            graph = read_sedgewick(path)
            sw = Stopwatch.create_started()
            if args.sharded:
                mesh = make_mesh(graph=mesh_graph, batch=mesh_batch)
                result = bfs_sharded(graph, source, mesh=mesh)
            else:
                result = bfs(graph, source, engine=args.engine or "pull")
            sw.stop()
            logger.info(
                "%s: %d supersteps in %s (fused, includes compile)",
                path, result.num_levels, sw,
            )
        else:
            run_problem_file(
                path,
                source=source,
                engine=args.engine or "push",
                dump=args.dump or cfg.dump_supersteps,
                checkpoint_every=cfg.checkpoint_every,
                work_dir=cfg.work_dir,
                resume=args.resume,
            )


if __name__ == "__main__":
    main()
