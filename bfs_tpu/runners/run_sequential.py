"""Sequential oracle driver: the ``SequentialTest.main`` equivalent
(SequentialTest.java:20-38).

For each configured problem file: build the graph (untimed — the reference
times "excluding the graph construction", paper §1.5), run the sequential
BFS oracle under a stopwatch (SequentialTest.java:25-27), optionally print
the per-vertex report ``s to v (d): path`` / ``(not connected)``
(SequentialTest.java:29-37, debug level), and verify the check() invariants.

Usage:
    python -m bfs_tpu.runners.run_sequential [service.properties]
        [--native|--python] [--report] [--source S]
"""

from __future__ import annotations

import argparse
import os

from ..config import ServiceConfiguration
from ..graph.csr import INF_DIST
from ..graph.io import read_sedgewick
from ..graph.vertex import path_to
from ..oracle.bfs import check, queue_bfs
from ..oracle.native import native_available, native_bfs
from ..utils.logging import get_logger
from ..utils.timing import Stopwatch

logger = get_logger(__name__)


def run_problem_file(path: str, *, source: int = 0, use_native: bool | None = None,
                     report: bool = False) -> float:
    """Returns BFS wall time in seconds (construction excluded)."""
    logger.info("Processing problem file: %s", path)
    graph = read_sedgewick(path)
    if use_native is None:
        use_native = native_available()
    sw = Stopwatch.create_started()
    if use_native:
        dist, parent, _ = native_bfs(graph, source, policy="queue")
    else:
        dist, parent = queue_bfs(graph, source)
    sw.stop()
    logger.info("Elapsed time ==> %s (%s oracle)", sw, "native" if use_native else "python")
    if report:
        for v in range(graph.num_vertices):
            if dist[v] != INF_DIST:
                p = "-".join(str(x) for x in path_to(parent, v))
                logger.debug("%d to %d (%d): %s", source, v, int(dist[v]), p)
            else:
                logger.debug("%d to %d (-): (not connected)", source, v)
    violations = check(graph, dist, parent, source)
    if violations:
        raise AssertionError(f"oracle invariants violated on {path}: {violations[:3]}")
    return sw.elapsed_s


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("config", nargs="?", default="service.properties")
    ap.add_argument("--native", action="store_true")
    ap.add_argument("--python", action="store_true")
    ap.add_argument("--report", action="store_true")
    ap.add_argument("--source", type=int, default=None)
    args = ap.parse_args(argv)
    cfg = (
        ServiceConfiguration.load(args.config)
        if os.path.exists(args.config)
        else ServiceConfiguration()
    )
    logger.info("Application name: %s", cfg.app_name)
    use_native = True if args.native else (False if args.python else None)
    source = args.source if args.source is not None else cfg.source
    for path in cfg.problem_files or ():
        run_problem_file(path, source=source, use_native=use_native, report=args.report)


if __name__ == "__main__":
    main()
