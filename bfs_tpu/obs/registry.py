"""One process-global MetricsRegistry: every counter behind one snapshot.

Before this module the repo had four disconnected metric surfaces —
``ServeMetrics`` (per-server latency/batching aggregates),
``bump_artifact`` (process-global cache counters), the retrace counters
in :mod:`bfs_tpu.analysis.runtime`, and the span buffer.  Each grew its
own ad-hoc report formatting in whichever tool read it.  The registry
absorbs them: free-form counters live here, ``ServeMetrics`` instances
register themselves at construction (weakly — a dropped server must not
be pinned by its own metrics), and :meth:`MetricsRegistry.snapshot`
composes everything into ONE JSON-ready dict that
``tools/serve_loadgen.py``, ``tools/chaos_run.py``, the ``bfs-tpu-obs``
CLI and any embedder print verbatim.  :func:`prometheus_text` renders
the same snapshot as Prometheus exposition text for scrape endpoints.

Stdlib-only by design (like the rest of the package minus telemetry):
the collaborators it reads — ``utils.metrics``, ``analysis.runtime``,
``obs.spans`` — are themselves stdlib-only, so the lint-stub fast path
(tools/lint.py) can print a snapshot without paying a jax import.
"""

from __future__ import annotations

import json
import re
import threading
import weakref


class MetricsRegistry:
    """Thread-safe process-global metrics hub.

    ``counter(name)`` bumps a free-form counter owned by the registry
    itself (e.g. ``graph_evictions``); :meth:`snapshot` additionally
    pulls the artifact counters, retrace counters, span summary and every
    registered ``ServeMetrics`` report, so one call answers "what has
    this process done" across all layers.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}  # guarded-by: _lock
        # Live ServeMetrics instances (weak: metrics must not outlive
        # their server just because the registry saw them once).
        self._serve: list = []  # guarded-by: _lock — weakref.ref list

    # ------------------------------------------------------------ counters --
    def counter(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + by

    def count(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    # --------------------------------------------------------------- serve --
    def register_serve(self, metrics) -> None:
        """Adopt a ServeMetrics instance (idempotent; weakly held)."""
        with self._lock:
            live = [r for r in self._serve if r() is not None]
            if not any(r() is metrics for r in live):
                live.append(weakref.ref(metrics))
            self._serve = live

    def _serve_reports(self) -> list[dict]:
        with self._lock:
            refs = list(self._serve)
        return [m.report() for m in (r() for r in refs) if m is not None]

    # ------------------------------------------------------------ snapshot --
    def snapshot(self, retrace_baseline: dict | None = None) -> dict:
        """The one unified view: registry counters + artifact caches +
        retrace counters (with per-function drift when a post-warmup
        ``retrace_baseline`` snapshot is passed — any non-zero drift names
        a recompile leak) + span summary + every live ServeMetrics
        report."""
        from ..analysis.runtime import retrace_report
        from ..utils.metrics import artifact_report
        from .spans import span_report

        retraces = retrace_report()
        out = {
            "counters": self.counters(),
            "artifact_caches": artifact_report(),
            "retraces": retraces,
            "spans": span_report(),
            "serve": self._serve_reports(),
        }
        if retrace_baseline is not None:
            out["retrace_drift"] = {
                name: n - retrace_baseline.get(name, 0)
                for name, n in retraces.items()
                if n - retrace_baseline.get(name, 0)
            }
        return out

    def to_json(self, **kw) -> str:
        return json.dumps(self.snapshot(**kw), indent=2, sort_keys=True)

    def to_prometheus(self, **kw) -> str:
        return prometheus_text(self.snapshot(**kw))


_REGISTRY_LOCK = threading.Lock()
_REGISTRY: list[MetricsRegistry] = []  # guarded-by: _REGISTRY_LOCK


def get_registry() -> MetricsRegistry:
    """THE process-global registry (created on first use)."""
    with _REGISTRY_LOCK:
        if not _REGISTRY:
            _REGISTRY.append(MetricsRegistry())
        return _REGISTRY[0]


_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(*parts: str) -> str:
    name = "_".join(_NAME_RE.sub("_", str(p)).strip("_") for p in parts if p != "")
    return f"bfs_tpu_{name}"


def _flatten(prefix: tuple, obj, out: list) -> None:
    if isinstance(obj, bool):
        out.append((_prom_name(*prefix), int(obj)))
    elif isinstance(obj, (int, float)):
        out.append((_prom_name(*prefix), obj))
    elif isinstance(obj, dict):
        for k, v in obj.items():
            _flatten(prefix + (str(k),), v, out)
    elif isinstance(obj, (list, tuple)):
        # Lists are indexed only when short and numeric (serve reports
        # nest one dict per server); anything else is not a gauge.
        for i, v in enumerate(obj):
            if isinstance(v, (dict, int, float)) and not isinstance(v, bool):
                _flatten(prefix + (str(i),), v, out)


def prometheus_text(snapshot: dict) -> str:
    """Prometheus exposition text (untyped gauges) for a snapshot dict:
    numeric leaves flattened to ``bfs_tpu_<path> <value>`` lines, names
    sanitized to the metric charset, non-numeric leaves skipped."""
    gauges: list[tuple[str, float]] = []
    _flatten((), snapshot, gauges)
    lines = []
    seen = set()
    for name, value in gauges:
        if name in seen:
            continue
        seen.add(name)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value}")
    return "\n".join(lines) + "\n"
