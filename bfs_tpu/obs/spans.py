"""Nestable wall-clock spans exported as Chrome trace events.

The span buffer is process-global and bounded; each closed span becomes
one Chrome ``"ph": "X"`` complete event (name, ts/dur in µs, pid/tid),
which Perfetto and ``chrome://tracing`` load directly — nesting is
inferred from containment on the same tid, so the API never needs an
explicit parent handle.  ``ts`` is wall-clock epoch µs (not a process
monotonic zero): a bench killed and resumed journals each segment's
events as they happened, and the stitched trace shows the gap between
process generations instead of overlapping them.

Spans are ON by default (``BFS_TPU_SPANS=0`` disables): one
``perf_counter_ns`` pair plus a dict append per span, host-side only —
nothing here ever touches a device value, which is what keeps the API
legal anywhere EXCEPT inside a declared hot region (the analysis pass's
OBS001 polices reads; span *writes* around a hot region are the intended
use: ``with span("repeat"): run()``).

Crash-durable traces: :func:`journal_spans` drains the buffer into a
``RunJournal`` record (``spans:<k>``, one per process generation) and
:func:`stitch_journal_trace` re-reads every generation's record from the
journal file into one trace — the SIGTERM path flushes still-open spans
first (:func:`flush_open_spans`) so an interrupted run leaves a usable
trace instead of a truncated one.

Everything in this module is stdlib-only (no jax, no numpy): the lint
stub path (tools/lint.py, tools/chaos_run.py) imports it for free.
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time

from .. import knobs

#: Buffer bound: a serve process answering queries forever must not leak
#: memory through its own observability.  Past the cap new events are
#: dropped and counted (the drop count rides in every export).
MAX_EVENTS = 200_000

_lock = threading.Lock()
_events: list[dict] = []  # guarded-by: _lock
_dropped = 0  # guarded-by: _lock
_open: dict[int, dict] = {}  # guarded-by: _lock — span id -> start info
_next_id = [0]  # guarded-by: _lock


def spans_enabled() -> bool:
    return knobs.get("BFS_TPU_SPANS")


def _wall_us() -> int:
    return time.time_ns() // 1_000


def _emit(event: dict) -> None:
    global _dropped
    with _lock:
        if len(_events) >= MAX_EVENTS:
            _dropped += 1
            return
        _events.append(event)


class _Span:
    """One span: context manager AND decorator (``@span("name")``)."""

    __slots__ = ("name", "attrs", "_id", "_t0")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self._id = None
        self._t0 = 0

    def __enter__(self):
        if not spans_enabled():
            return self
        self._t0 = time.perf_counter_ns()
        with _lock:
            _next_id[0] += 1
            self._id = _next_id[0]
            _open[self._id] = {
                "name": self.name,
                "ts": _wall_us(),
                "t0": self._t0,
                "tid": threading.get_ident(),
                "args": dict(self.attrs),
            }
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._id is None:
            return False
        dur_us = (time.perf_counter_ns() - self._t0) // 1_000
        with _lock:
            info = _open.pop(self._id, None)
        if info is not None:
            args = info["args"]
            if exc_type is not None:
                args = {**args, "error": exc_type.__name__}
            _emit({
                "name": self.name, "ph": "X", "ts": info["ts"],
                "dur": max(int(dur_us), 1), "pid": os.getpid(),
                "tid": info["tid"], "cat": "bfs_tpu", "args": args,
            })
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with _Span(self.name, self.attrs):
                return fn(*a, **kw)

        return wrapper


def span(name: str, **attrs) -> _Span:
    """``with span("engine_init", scale=24): ...`` or ``@span("tick")``."""
    return _Span(name, attrs)


def instant(name: str, **attrs) -> None:
    """One zero-duration marker event (Chrome ``ph: "i"``) — eviction,
    cache invalidation, fault injection: things that happen, not last."""
    if not spans_enabled():
        return
    _emit({
        "name": name, "ph": "i", "ts": _wall_us(), "s": "p",
        "pid": os.getpid(), "tid": threading.get_ident(),
        "cat": "bfs_tpu", "args": dict(attrs),
    })


def flush_open_spans(note: str = "flushed") -> int:
    """Close every still-open span NOW (SIGTERM/SIGALRM path): each gets
    its real duration so far plus ``args.flushed``, so an interrupted run's
    trace shows exactly which phase the signal landed in.  Returns the
    number of spans flushed.  Thread stacks are not unwound — the process
    is about to exit."""
    now_ns = time.perf_counter_ns()
    with _lock:
        open_now = list(_open.values())
        _open.clear()
    for info in open_now:
        _emit({
            "name": info["name"], "ph": "X", "ts": info["ts"],
            "dur": max((now_ns - info["t0"]) // 1_000, 1),
            "pid": os.getpid(), "tid": info["tid"], "cat": "bfs_tpu",
            "args": {**info["args"], "flushed": note},
        })
    return len(open_now)


def snapshot_events() -> list[dict]:
    with _lock:
        return list(_events)


def drain_events() -> list[dict]:
    """Return and clear the buffer (the journal path: each process
    generation journals its own events exactly once)."""
    global _dropped
    with _lock:
        out = list(_events)
        _events.clear()
        _dropped = 0
        return out


def span_report() -> dict:
    """Per-name count + total seconds of CLOSED spans — the summary the
    metrics registry snapshot embeds."""
    out: dict[str, dict] = {}
    for ev in snapshot_events():
        if ev.get("ph") != "X":
            continue
        rec = out.setdefault(ev["name"], {"count": 0, "total_s": 0.0})
        rec["count"] += 1
        rec["total_s"] += ev.get("dur", 0) / 1e6
    return out


def chrome_trace(events: list[dict] | None = None) -> dict:
    """The Chrome/Perfetto trace document for ``events`` (default: the
    current buffer)."""
    evs = snapshot_events() if events is None else list(events)
    with _lock:
        dropped = _dropped
    doc = {"traceEvents": evs, "displayTimeUnit": "ms"}
    if dropped:
        doc["otherData"] = {"dropped_events": dropped}
    return doc


def export_chrome_trace(path: str, events: list[dict] | None = None) -> str:
    """Write the trace JSON atomically; returns ``path``."""
    doc = chrome_trace(events)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


# --------------------------------------------------------------- journal --

def journal_spans(jr) -> str | None:
    """Drain this process generation's span events into one durable
    ``spans:<k>`` record of ``jr`` (a RunJournal).  ``k`` counts prior
    generations, so a killed-and-resumed bench accumulates one record per
    segment and :func:`stitch_journal_trace` re-assembles them in order.
    No-op (returns None) when there is nothing to journal — with no
    journal the buffer is left intact for a later export, not drained."""
    if jr is None:
        return None
    events = drain_events()
    if not events:
        return None
    k = sum(1 for p in jr.phases() if p.startswith("spans:"))
    phase = f"spans:{k}"
    jr.put(phase, {"events": events})
    return phase


def stitch_journal_trace(journal_path: str) -> dict:
    """Chrome trace stitched from every ``spans:<k>`` record of a journal
    FILE (no config needed — the records are read leniently, crc-checked
    per line, torn tails skipped).  Wall-clock ``ts`` means the segments
    land on one coherent timeline with real gaps between generations."""
    from ..resilience.journal import read_records

    events: list[dict] = []
    spans_recs = []
    for rec in read_records(journal_path):
        if rec["phase"].startswith("spans:"):
            spans_recs.append(rec)
    spans_recs.sort(key=lambda r: int(r["phase"].split(":", 1)[1]))
    for rec in spans_recs:
        events.extend(rec["payload"].get("events", ()))
    return {"traceEvents": events, "displayTimeUnit": "ms"}
