"""bfs_tpu.obs — unified observability: spans, device telemetry, metrics.

Three pillars (ISSUE 6), replacing four disconnected lenses (the phase
ledger, ServeMetrics + artifact counters, retrace counters, the
resilience journal) with one layer:

* **Spans** (:mod:`.spans`) — lightweight nestable wall-clock spans
  (context manager + decorator) exported as Chrome-trace-event JSON
  (Perfetto-loadable) and journaled through
  :class:`~bfs_tpu.resilience.journal.RunJournal` so a resumed bench
  stitches a complete trace across process generations.
* **Device superstep telemetry** (:mod:`.telemetry`) — a small
  accumulator carried as extra ``while_loop`` state by the fused BFS
  programs (per-level frontier occupancy / changed-vertex count /
  packed-cap proximity), pulled ONCE at loop exit — the
  direction-switching input for ROADMAP item 2 and per-level TEPS for
  free.  Imported lazily: it needs jax, the rest of this package is
  stdlib-only (tools/lint.py's stub-parent fast path stays sub-100ms).
* **One registry** (:mod:`.registry`) — a process-global
  :class:`MetricsRegistry` absorbing ServeMetrics, artifact counters and
  retrace counters behind one snapshot API with JSON and
  Prometheus-text exporters.

CLI: ``bfs-tpu-obs`` (= ``python -m bfs_tpu.obs``) stitches a finished
bench journal into a Perfetto trace and prints metric snapshots;
``tools/obs_dashboard.py`` renders trace + level curve + serve
percentiles from a run's artifacts.
"""

from __future__ import annotations

from .registry import MetricsRegistry, get_registry, prometheus_text
from .spans import (
    chrome_trace,
    export_chrome_trace,
    flush_open_spans,
    instant,
    journal_spans,
    snapshot_events,
    span,
    span_report,
    spans_enabled,
    stitch_journal_trace,
)

__all__ = [
    "MetricsRegistry", "get_registry", "prometheus_text",
    "span", "instant", "spans_enabled", "snapshot_events", "span_report",
    "chrome_trace", "export_chrome_trace", "flush_open_spans",
    "journal_spans", "stitch_journal_trace",
]
