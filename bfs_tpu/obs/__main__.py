"""``bfs-tpu-obs`` — observability CLI over run artifacts.

Subcommands:

``trace <journal.jsonl> [-o out.json]``
    Stitch every process generation's journaled span records into ONE
    Perfetto-loadable Chrome trace JSON (default output: the journal
    path with ``.trace.json``).  Works on finished AND interrupted
    journals — the bench's SIGTERM path flushes open spans before dying.

``curve <journal.jsonl>``
    Print the journaled ``details.level_curve`` (from the headline or
    the ``level_curve`` phase record) as an ASCII bar chart.

``snapshot [--prom]``
    Print this process's :class:`~bfs_tpu.obs.registry.MetricsRegistry`
    snapshot as JSON (default) or Prometheus exposition text — the
    embedding demo for the exporter formats.

The module itself never imports jax (journals are parsed directly);
``python -m bfs_tpu.obs`` pays the parent-package import like every other
entry point — tools/obs_dashboard.py reuses the lint stub to skip it.
"""

from __future__ import annotations

import argparse
import json
import sys


def _trace(args) -> int:
    from .spans import stitch_journal_trace

    doc = stitch_journal_trace(args.journal)
    events = doc["traceEvents"]
    import os

    out = args.output or (os.path.splitext(args.journal)[0] + ".trace.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    names = sorted({e.get("name", "?") for e in events})
    gens = len({e.get("pid") for e in events})
    print(
        f"wrote {out}: {len(events)} events, {gens} process generation(s), "
        f"{len(names)} span names"
    )
    for n in names:
        print(f"  {n}")
    if not events:
        print("  (no spans journaled — was the run made with BFS_TPU_SPANS=0?)")
    return 0


def _find_curve(records) -> dict | None:
    curve = None
    for rec in records:
        payload = rec.get("payload") or {}
        if rec["phase"] == "level_curve" and isinstance(payload, dict):
            curve = payload.get("level_curve", curve)
        if rec["phase"] == "headline":
            details = (payload.get("headline") or {}).get("details") or {}
            if isinstance(details.get("level_curve"), dict):
                curve = details["level_curve"]
    return curve if isinstance(curve, dict) else None


def _curve(args) -> int:
    from ..resilience.journal import read_records
    from .telemetry import render_curve_ascii

    curve = _find_curve(read_records(args.journal))
    if curve is None:
        print("no level_curve record in this journal", file=sys.stderr)
        return 1
    print(render_curve_ascii(curve))
    if "cap_proximity" in curve:
        print(
            f"cap proximity: {curve['levels']}/{curve.get('cap')} levels "
            f"({curve['cap_proximity']:.2f})"
        )
    return 0


def _snapshot(args) -> int:
    from .registry import get_registry

    reg = get_registry()
    print(reg.to_prometheus() if args.prom else reg.to_json())
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bfs-tpu-obs", description=__doc__.splitlines()[0]
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("trace", help="stitch a journal's spans into a Perfetto trace")
    p.add_argument("journal")
    p.add_argument("-o", "--output", default="")
    p.set_defaults(fn=_trace)
    p = sub.add_parser("curve", help="print a journal's level curve")
    p.add_argument("journal")
    p.set_defaults(fn=_curve)
    p = sub.add_parser("snapshot", help="print this process's metrics snapshot")
    p.add_argument("--prom", action="store_true", help="Prometheus text format")
    p.set_defaults(fn=_snapshot)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
