"""Device-side superstep telemetry: the level curve, measured in-loop.

The fused BFS programs (models/bfs.py, models/multisource.py, the sharded
relay runner) optionally carry one extra ``while_loop`` state leaf: an
int32[TEL_SLOTS] accumulator where slot ``l`` holds the number of
vertices that entered the frontier at level ``l`` (every engine's
frontier holds exactly the newly settled vertices, so the curve's sum is
the reachable-vertex count).  The relay program additionally derives
per-level frontier OUT-EDGES (float32 — reporting, not dispatch) in one
pass over the final levels AT LOOP EXIT
(:func:`edge_curve_from_levels`), which with per-level seconds from the
superstep profile yields per-level TEPS.

The hot-region contract (enforced statically by analysis rule OBS001 and
dynamically by the transfer guard): telemetry is recorded ON DEVICE as
part of the compiled loop body and pulled exactly ONCE at loop exit —
:func:`read_telemetry` is the single intended ``jax.device_get``.
Nothing in the loop ever syncs.  Telemetry costs one popcount-sum plus
one 4-byte scatter-add per superstep, is OFF in the timed-repeat
programs by default (a separate untimed pass collects the curve), and
the phase ledger (bfs_tpu/profiling.py) measures its full-superstep
overhead so every capture carries the cost next to the curve.

This is the direction-switching input for ROADMAP item 2: Beamer-style
push/pull selection keys on exactly this per-level occupancy.
"""

from __future__ import annotations

import numpy as np

#: Accumulator slots.  Covers the packed 62-level cap with room for the
#: unpacked fallback; deeper levels clamp into the last slot (the curve
#: then reports ``truncated`` — sums stay exact either way).
TEL_SLOTS = 128


def init_level_acc(num_sources: int = 1, slots: int = TEL_SLOTS,
                   *, wide: bool = False):
    """int32[slots] with slot 0 = the sources (level 0 is seeded by init,
    not produced by a superstep).

    ``wide`` (the batched multi-source shape): int32[slots, 2] carrying a
    lo16/hi16 split — a dominant level can settle up to S*V vertices in
    one slot, past int32 (64 sources at scale 26 = 2^32), and jax int64
    is unavailable without the x64 flag.  Per-source counts are < 2^31
    always (int32 vertex ids); splitting them into 16-bit halves before
    the cross-source sum keeps each half under 2^31 for any S < 2^15,
    and :func:`level_curve` reassembles exact int64 on the host."""
    import jax.numpy as jnp

    if wide:
        return (
            jnp.zeros((slots, 2), jnp.int32)
            .at[0, 0].set(jnp.int32(num_sources & 0xFFFF))
            .at[0, 1].set(jnp.int32(num_sources >> 16))
        )
    return jnp.zeros((slots,), jnp.int32).at[0].set(jnp.int32(num_sources))


def _slot(level):
    import jax.numpy as jnp

    return jnp.clip(level, 0, TEL_SLOTS - 1)


# bfs_tpu: hot traced
def record_frontier_words(acc, fwords, level):
    """Accumulate popcount(fwords) into slot ``level`` (the level the
    superstep that produced this frontier settled).  Word-packed frontiers
    (relay/sharded)."""
    import jax
    import jax.numpy as jnp

    n = jax.lax.population_count(fwords).sum(dtype=jnp.int32)
    return acc.at[_slot(level)].add(n)


# bfs_tpu: hot traced
def record_count(acc, level, count):
    """Pre-reduced occupancy twin of :func:`record_frontier_words` for
    programs where NO single device holds the global frontier words (the
    2D grid: each cell owns one block and the settled count arrives as an
    already-replicated ``psum`` scalar — the same scalar the termination
    flag derives from, so occupancy telemetry costs no extra
    collective)."""
    import jax.numpy as jnp

    return acc.at[_slot(level)].add(jnp.asarray(count, jnp.int32))


# bfs_tpu: hot traced
def record_frontier_bools(acc, frontier, level):
    """Bool-frontier twin (push/pull BfsState; batched states sum over the
    sources axis too — the curve is the global occupancy).  A wide acc
    (int32[slots, 2], see :func:`init_level_acc`) gets the overflow-safe
    lo16/hi16 split of the per-source counts."""
    import jax.numpy as jnp

    if acc.ndim == 2:
        per_source = frontier.sum(axis=-1, dtype=jnp.int32)  # each < 2^31
        lo = (per_source & 0xFFFF).sum(dtype=jnp.int32)
        hi = (per_source >> 16).sum(dtype=jnp.int32)
        return acc.at[_slot(level), 0].add(lo).at[_slot(level), 1].add(hi)
    return acc.at[_slot(level)].add(frontier.sum(dtype=jnp.int32))


# bfs_tpu: hot traced
def edge_curve_from_levels(dist, outdeg, unreached):
    """float32[TEL_SLOTS]: out-degree summed by BFS level — the per-level
    frontier OUT-EDGE curve, computed in ONE pass over the final state at
    loop exit (a per-superstep masked sum measured ~25% of a CPU
    superstep; this exit-time scatter-add is free by comparison and
    bit-identical, since each vertex enters the frontier exactly once).
    ``dist`` int32 levels, ``unreached`` the sentinel mask."""
    import jax.numpy as jnp

    idx = jnp.clip(jnp.where(unreached, 0, dist), 0, TEL_SLOTS - 1)
    w = jnp.where(unreached, 0, outdeg).astype(jnp.float32)
    return jnp.zeros(TEL_SLOTS, jnp.float32).at[idx].add(w)


# -------------------------------------------------------- direction codes --
# The per-superstep direction schedule rides the SAME accumulator shape as
# the level curve: int32[TEL_SLOTS] where slot ``l`` records which body the
# superstep that settled level ``l`` ran — the Beamer-style switching
# evidence (ROADMAP item 2) pulled in the ONE loop-exit device_get next to
# the occupancy curve.  0 = level not executed.

DIR_PUSH = 1  # element/frontier body (sparse gather superstep)
DIR_PULL = 2  # dense relay body (full-network superstep)

DIR_NAMES = {DIR_PUSH: "push", DIR_PULL: "pull"}


def init_dir_acc(slots: int = TEL_SLOTS):
    """int32[slots] direction accumulator (slot 0 stays 0: level 0 is
    seeded by init, no superstep ran it)."""
    import jax.numpy as jnp

    return jnp.zeros((slots,), jnp.int32)


# bfs_tpu: hot traced
def record_direction(dacc, level, code):
    """Record the direction ``code`` (DIR_PUSH/DIR_PULL, traced or static)
    of the superstep that settled ``level``.  Each level is settled by
    exactly one superstep, so a plain ``set`` suffices."""
    import jax.numpy as jnp

    return dacc.at[_slot(level)].set(jnp.asarray(code, jnp.int32))


# ---------------------------------------------------------- exchange bytes --
# The sharded relay exchange (parallel/exchange.py) accumulates its
# bytes-on-the-wire and the arm that shipped them per level, riding the
# SAME int32[TEL_SLOTS] accumulator shape and the same one-pull-at-exit
# contract as the level curve and the direction schedule.  Slot ``l``
# holds the payload bytes of the exchange that shipped the level-``l``
# frontier (int32 is exact: one superstep's payload is bounded by the
# flat arm's ``n * block/32 * 4`` bytes, far below 2^31).  Levels past
# TEL_SLOTS clamp into the last slot, which then aggregates the whole
# deep tail — still exact for any search shorter than ~4M supersteps at
# the flat payload; consumers (exchange_report, the sharded ledger) use
# the loop-exit superstep count for per-superstep math, never the
# clamped slot count.


def init_bytes_acc(slots: int = TEL_SLOTS):
    """int32[slots] exchange-bytes accumulator (slot 0 stays 0: the
    source frontier is seeded by init, nothing shipped)."""
    import jax.numpy as jnp

    return jnp.zeros((slots,), jnp.int32)


# bfs_tpu: hot traced
def record_exchange(bacc, aacc, level, nbytes, arm):
    """Record one superstep's exchange: payload bytes added into the
    bytes accumulator, the arm code (parallel/exchange.py EX_*) set in
    the arm accumulator — both at the slot of the level this exchange's
    frontier settled."""
    import jax.numpy as jnp

    s = _slot(level)
    return (
        bacc.at[s].add(jnp.asarray(nbytes, jnp.int32)),
        aacc.at[s].set(jnp.asarray(arm, jnp.int32)),
    )


def direction_schedule(dirs, *, mode: str, alpha: float, beta: float) -> dict:
    """JSON-ready schedule from the host direction accumulator (post
    :func:`read_telemetry`): per-level push/pull labels, switch count, and
    the threshold config that produced them — shipped by bench as
    ``details.direction_schedule`` next to the level curve."""
    dv = np.asarray(dirs, dtype=np.int64)
    nz = np.flatnonzero(dv)
    levels = int(nz[-1]) + 1 if nz.size else 0
    labels = [DIR_NAMES.get(int(c), "none") for c in dv[1:levels]]
    switches = sum(
        1 for a, b in zip(labels, labels[1:])
        if a != b and "none" not in (a, b)
    )
    return {
        "mode": mode,
        "alpha": float(alpha),
        "beta": float(beta),
        "schedule": labels,  # index i = the superstep that settled level i+1
        "switches": switches,
        "push_supersteps": labels.count("push"),
        "pull_supersteps": labels.count("pull"),
        "truncated": bool(dv[TEL_SLOTS - 1] != 0)
        if dv.shape[0] >= TEL_SLOTS
        else False,
    }


def read_telemetry(tel):
    """THE one telemetry pull: one explicit ``jax.device_get`` of the
    whole accumulator pytree at loop exit.  Never call this inside a hot
    region (analysis rule OBS001)."""
    import jax

    return jax.device_get(tel)


def level_curve(
    fvert,
    fedges=None,
    *,
    cap: int | None = None,
    reference_reached: int | None = None,
) -> dict:
    """JSON-ready curve from host accumulator arrays (post
    :func:`read_telemetry`).

    ``occupancy[l]`` = vertices settled at level ``l`` (trimmed after the
    last non-zero); ``reachable`` = sum (equals the oracle's
    reachable-vertex count — asserted against ``reference_reached`` when
    the caller has one); ``cap_proximity`` = levels/cap, the packed-cap
    headroom signal."""
    fv = np.asarray(fvert)
    if fv.ndim == 2:  # wide lo16/hi16 acc -> exact int64 on the host
        fv = fv[:, 0].astype(np.int64) + (fv[:, 1].astype(np.int64) << 16)
    fv = fv.astype(np.int64)
    nz = np.flatnonzero(fv)
    levels = int(nz[-1]) + 1 if nz.size else 0
    occupancy = [int(x) for x in fv[:levels]]
    out: dict = {
        "occupancy": occupancy,
        "levels": levels,
        "reachable": int(fv.sum()),
        "peak_level": int(np.argmax(fv)) if levels else 0,
        "peak_occupancy": int(fv.max()) if levels else 0,
        "truncated": bool(fv[TEL_SLOTS - 1] != 0) if fv.shape[0] >= TEL_SLOTS else False,
    }
    if fedges is not None:
        fe = np.asarray(fedges, dtype=np.float64)
        out["frontier_edges"] = [float(x) for x in fe[:levels]]
    if cap is not None and cap > 0:
        out["cap"] = int(cap)
        out["cap_proximity"] = levels / cap
    if reference_reached is not None:
        out["reference_reached"] = int(reference_reached)
        out["occupancy_sum_matches_reference"] = (
            int(fv.sum()) == int(reference_reached)
        )
    return out


def stream_report(levels: list, *, budget_bytes: int, store: dict,
                  cache: dict) -> dict:
    """JSON-ready ``stream`` ledger phase (pure host — no jax): the
    per-level rows the streamed runner journals (arm, demanded superblock
    count, and the hit/miss/evict/corrupt/bytes deltas for that level)
    plus their per-run totals, the host store shape, and the cache's
    lifetime counter snapshot.  Totals sum the per-level DELTAS, so a
    cache reused across runs (it is memoized on the engine) still reports
    honest per-run streaming volume."""
    total_keys = (
        "bytes_streamed", "hits", "misses", "evictions",
        "corrupt_refetches",
    )
    totals = {
        k: int(sum(int(row.get(k, 0)) for row in levels))
        for k in total_keys
    }
    return {
        "budget_bytes": int(budget_bytes),
        **{k: store[k] for k in sorted(store)},
        "levels": [dict(row) for row in levels],
        **totals,
        "cache": dict(cache),
    }


def render_curve_ascii(curve: dict, width: int = 50) -> str:
    """Terminal bar chart of a level curve (the dashboard/CLI view)."""
    occ = curve.get("occupancy", [])
    if not occ:
        return "(empty level curve)"
    peak = max(occ)
    lines = [
        f"level curve: {curve.get('reachable', sum(occ))} reachable over "
        f"{curve.get('levels', len(occ))} levels"
    ]
    for l, n in enumerate(occ):
        bar = "#" * max(1 if n else 0, round(width * n / peak)) if peak else ""
        lines.append(f"  L{l:>3} {n:>12,d} {bar}")
    if curve.get("truncated"):
        lines.append(f"  (deeper levels clamped into slot {TEL_SLOTS - 1})")
    return "\n".join(lines)
