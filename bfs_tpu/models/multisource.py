"""Batched multi-source BFS: a sources axis instead of a queue of seeds.

The oracle's multi-source BFS (BreadthFirstPaths.java:83-89,114-132) seeds
one queue with many sources and computes ``min_s dist(s, v)``.  The batched
engine here answers the stronger per-source query: independent BFS trees for
S sources in one compiled program, with the sources axis mapped to tensor
batch (and, in the sharded engine, shardable across the mesh's data axis).
``min`` over the batch axis recovers the oracle's multi-source semantics
(:func:`collapse_multi_source`).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..graph.csr import DeviceGraph, Graph, build_device_graph, INF_DIST, NO_PARENT
from ..graph.ell import PullGraph, build_pull_graph
from ..ops.pull import relax_pull_superstep
from ..ops.relax import BfsState, init_batched_state, relax_superstep_batched
from ..analysis.runtime import traced


@functools.partial(
    jax.jit,
    static_argnames=("num_vertices", "max_levels", "packed", "telemetry"),
)
@traced("multisource._bfs_multi_fused")
def _bfs_multi_fused(
    src, dst, sources, num_vertices: int, max_levels: int,
    packed: bool = False, telemetry: bool = False,
):
    """``packed`` carries the fused ``level:6|parent:26`` word state
    (ops/packed.py) through the loop — half the per-superstep dist/parent
    HBM bytes — capped at PACKED_MAX_LEVELS and unpacked ONCE at loop
    exit, so the returned BfsState is identical wherever the cap was not
    hit (callers detect a cap exit via ``packed_truncated``).

    With ``telemetry`` (static) the loop additionally carries the
    per-level occupancy accumulator (summed over the sources axis —
    the GLOBAL curve) and returns ``(BfsState, acc)`` for one pull at
    loop exit (obs/telemetry.py)."""
    from .bfs import _loop_with_acc

    if telemetry:
        from ..obs import telemetry as T

        acc0 = T.init_level_acc(sources.shape[0], wide=True)

        def rec(a, s):
            return T.record_frontier_bools(a, s.frontier, s.level)

    if packed:
        from ..ops.packed import packed_cap
        from ..ops.relax import (
            init_packed_batched_state,
            relax_superstep_batched_packed,
            unpack_bfs_state,
        )

        cap = packed_cap(max_levels)
        pstate = init_packed_batched_state(num_vertices, sources)

        def pcond(s):
            return s.changed & (s.level < cap)

        def pbody(s):
            return relax_superstep_batched_packed(s, src, dst)

        if telemetry:
            out, acc = _loop_with_acc(pcond, pbody, pstate, acc0, rec)
            return unpack_bfs_state(out), acc
        return unpack_bfs_state(jax.lax.while_loop(pcond, pbody, pstate))
    state = init_batched_state(num_vertices, sources)

    def cond(s: BfsState):
        return s.changed & (s.level < max_levels)

    def body(s: BfsState):
        return relax_superstep_batched(s, src, dst)

    if telemetry:
        return _loop_with_acc(cond, body, state, acc0, rec)
    return jax.lax.while_loop(cond, body, state)


@functools.partial(
    jax.jit,
    static_argnames=("num_vertices", "max_levels", "packed", "telemetry"),
)
@traced("multisource._bfs_multi_pull_fused")
def _bfs_multi_pull_fused(
    ell0, folds, sources, num_vertices: int, max_levels: int,
    packed: bool = False, telemetry: bool = False,
):
    """Batched pull: the frontier table carries a leading sources axis and
    the ELL gathers broadcast over it (ops/pull.py pull_candidates), so all
    S trees advance in lock-step supersteps of one compiled loop.
    ``packed`` and ``telemetry`` as in :func:`_bfs_multi_fused`."""
    from .bfs import _loop_with_acc

    if telemetry:
        from ..obs import telemetry as T

        acc0 = T.init_level_acc(sources.shape[0], wide=True)

        def rec(a, s):
            return T.record_frontier_bools(a, s.frontier, s.level)

    if packed:
        from ..ops.packed import packed_cap
        from ..ops.pull import relax_pull_superstep_packed
        from ..ops.relax import init_packed_batched_state, unpack_bfs_state

        cap = packed_cap(max_levels)
        pstate = init_packed_batched_state(num_vertices, sources)

        def pcond(s):
            return s.changed & (s.level < cap)

        def pbody(s):
            return relax_pull_superstep_packed(s, ell0, folds)

        if telemetry:
            out, acc = _loop_with_acc(pcond, pbody, pstate, acc0, rec)
            return unpack_bfs_state(out), acc
        return unpack_bfs_state(jax.lax.while_loop(pcond, pbody, pstate))
    state = init_batched_state(num_vertices, sources)

    def cond(s: BfsState):
        return s.changed & (s.level < max_levels)

    def body(s: BfsState):
        return relax_pull_superstep(s, ell0, folds)

    if telemetry:
        return _loop_with_acc(cond, body, state, acc0, rec)
    return jax.lax.while_loop(cond, body, state)


@functools.partial(
    jax.jit,
    static_argnames=("num_vertices", "max_levels", "packed"),
    donate_argnums=(2,),
)
@traced("multisource._bfs_multi_segment")
def _bfs_multi_segment(
    src, dst, state, seg_end, num_vertices: int, max_levels: int,
    packed: bool = False,
):
    """ONE bounded segment of the batched push loop (ISSUE 14): the same
    superstep body as :func:`_bfs_multi_fused`, stopped at ``seg_end``
    supersteps (a traced operand — no retrace per segment) so the caller
    can snapshot the carry at the boundary and resume bit-identically.
    The carry is donated: a stepped segment consumes its input state
    (callers reassign), so XLA reuses the buffers instead of doubling
    the [S, V] state HBM per segment (IR001).  Unlike the fused program
    this returns the RAW carry — the once-per-run unpack happens at the
    true end (:func:`multi_segment_finish`), never at a segment
    boundary."""
    from ..ops.packed import packed_cap
    from ..ops.relax import relax_superstep_batched_packed

    cap = packed_cap(max_levels) if packed else max_levels

    def cond(s):
        return s.changed & (s.level < cap) & (s.level < seg_end)

    if packed:
        return jax.lax.while_loop(
            cond, lambda s: relax_superstep_batched_packed(s, src, dst),
            state,
        )
    return jax.lax.while_loop(
        cond, lambda s: relax_superstep_batched(s, src, dst), state
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_vertices", "max_levels", "packed"),
    donate_argnums=(2,),
)
@traced("multisource._bfs_multi_pull_segment")
def _bfs_multi_pull_segment(
    ell0, folds, state, seg_end, num_vertices: int, max_levels: int,
    packed: bool = False,
):
    """Pull-engine twin of :func:`_bfs_multi_segment` (the serve batch
    path's segment program)."""
    from ..ops.packed import packed_cap
    from ..ops.pull import relax_pull_superstep_packed

    cap = packed_cap(max_levels) if packed else max_levels

    def cond(s):
        return s.changed & (s.level < cap) & (s.level < seg_end)

    if packed:
        return jax.lax.while_loop(
            cond, lambda s: relax_pull_superstep_packed(s, ell0, folds),
            state,
        )
    return jax.lax.while_loop(
        cond, lambda s: relax_pull_superstep(s, ell0, folds), state
    )


def multi_segment_init(
    num_vertices: int, sources, packed: bool, restore: dict | None = None,
):
    """The segment loop's initial carry: a fresh batched state, or one
    rebuilt from a checkpoint epoch's host arrays (``restore`` maps state
    field names to np arrays; extra keys — checkpoint metadata — are
    ignored)."""
    from ..ops.relax import (
        PackedBfsState,
        init_packed_batched_state,
    )

    if restore is not None:
        cls = PackedBfsState if packed else BfsState
        return cls(**{
            f: jnp.asarray(restore[f]) for f in cls._fields
        })
    if packed:
        return init_packed_batched_state(
            num_vertices, jnp.asarray(np.asarray(sources, np.int32))
        )
    return init_batched_state(
        num_vertices, jnp.asarray(np.asarray(sources, np.int32))
    )


def multi_segment_finish(state, packed: bool) -> BfsState:
    """The ONCE-PER-RUN unpack at true loop exit (the fused programs do
    this inside the loop program; the segmented path defers it past the
    last segment so every intermediate snapshot stays the raw packed
    carry — V/2 state bytes per epoch)."""
    from ..ops.relax import unpack_bfs_state

    return unpack_bfs_state(state) if packed else state


@dataclass
class MultiBfsResult:
    """Per-source BFS trees: ``dist``/``parent`` are int32[S, V]."""

    sources: np.ndarray
    dist: np.ndarray
    parent: np.ndarray
    num_levels: int


def bfs_multi_device(
    graph: Graph | DeviceGraph | PullGraph,
    sources,
    *,
    engine: str = "pull",
    max_levels: int | None = None,
    block: int = 1024,
    packed: bool | None = None,
    telemetry: bool = False,
):
    """DEVICE-resident half of :func:`bfs_multi` for pull/push: returns the
    raw batched BfsState without any host transfer (``int(state.level)`` is
    the cheap sync — the benchmark timing path).  The relay analogue is
    :meth:`RelayEngine.run_multi_device`.

    ``packed=None`` runs the fused-word carry whenever parent ids fit its
    26-bit field; the loop then caps at PACKED_MAX_LEVELS and raw-device
    callers must test ``state.changed`` at the cap (:func:`bfs_multi`
    does, and falls back automatically).

    With ``telemetry`` the state comes back as ``(BfsState, acc)`` —
    the device-resident level accumulator, pulled once at loop exit
    (:func:`bfs_multi_level_curve` is the host-side convenience)."""
    sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
    from ..ops.packed import packed_parent_fits, resolve_packed
    from .bfs import check_sources

    if engine == "pull":
        pg = graph if isinstance(graph, PullGraph) else build_pull_graph(graph)
        check_sources(pg.num_vertices, sources)
        max_levels = int(max_levels) if max_levels is not None else pg.num_vertices
        if packed is None:
            packed = resolve_packed(packed_parent_fits(pg.num_vertices))
        from ..graph.ell import device_ell

        ell0_t, folds_t = device_ell(pg)
        state = _bfs_multi_pull_fused(
            ell0_t,
            folds_t,
            jnp.asarray(sources),
            pg.num_vertices,
            max_levels,
            packed,
            telemetry,
        )
        return state, pg.num_vertices
    if engine != "push":
        raise ValueError(f"unknown engine {engine!r}; use 'pull' or 'push'")
    dg = graph if isinstance(graph, DeviceGraph) else build_device_graph(graph, block=block)
    if dg.num_shards != 1:
        raise ValueError("sharded DeviceGraph requires the parallel engine")
    check_sources(dg.num_vertices, sources)
    max_levels = int(max_levels) if max_levels is not None else dg.num_vertices
    if packed is None:
        packed = resolve_packed(packed_parent_fits(dg.num_vertices))
    state = _bfs_multi_fused(
        jnp.asarray(dg.src), jnp.asarray(dg.dst), jnp.asarray(sources),
        dg.num_vertices, max_levels, packed, telemetry,
    )
    return state, dg.num_vertices


def bfs_multi(
    graph: Graph | DeviceGraph | PullGraph,
    sources,
    *,
    engine: str = "pull",
    max_levels: int | None = None,
    block: int = 1024,
) -> MultiBfsResult:
    """Batched multi-source BFS on one chip.  Engines as in
    :func:`bfs_tpu.models.bfs.bfs` — ``'pull'`` (default), ``'push'``, or
    ``'relay'`` (via :meth:`RelayEngine.run_multi`); all produce bit-exact
    dist AND parent (canonical min-parent).  Runs the packed fused-word
    carry by default and re-runs unpacked past its 62-level cap."""
    from ..ops.packed import (
        packed_parent_fits,
        packed_truncated,
        resolve_packed,
    )

    sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
    if engine == "relay":
        from .bfs import RelayEngine

        return RelayEngine(graph).run_multi(sources, max_levels=max_levels)
    requested = max_levels if max_levels is not None else graph.num_vertices
    packed = resolve_packed(packed_parent_fits(graph.num_vertices))
    state, v = bfs_multi_device(
        graph, sources, engine=engine, max_levels=max_levels, block=block,
        packed=packed,
    )
    state = jax.device_get(state)
    if packed and packed_truncated(state.changed, state.level, int(requested)):
        state, v = bfs_multi_device(
            graph, sources, engine=engine, max_levels=max_levels,
            block=block, packed=False,
        )
        state = jax.device_get(state)
    return MultiBfsResult(
        sources=sources,
        dist=np.asarray(state.dist[:, :v]),
        parent=np.asarray(state.parent[:, :v]),
        num_levels=int(state.level),
    )


def bfs_multi_level_curve(
    graph: Graph | DeviceGraph | PullGraph,
    sources,
    *,
    engine: str = "pull",
    max_levels: int | None = None,
    block: int = 1024,
) -> dict:
    """The GLOBAL level curve of a batched multi-source run (occupancy
    summed over the sources axis; its total is the summed per-tree
    reachable counts).  One accumulator pull — the [S, V] dist/parent
    stay on device.  Packed runs past the 62-level cap re-run unpacked,
    same contract as :func:`bfs_multi`."""
    from ..obs.telemetry import level_curve, read_telemetry
    from ..ops.packed import (
        PACKED_MAX_LEVELS,
        packed_parent_fits,
        packed_truncated,
        resolve_packed,
    )

    sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
    requested = int(max_levels) if max_levels is not None else graph.num_vertices
    packed = resolve_packed(packed_parent_fits(graph.num_vertices))
    (state, acc), _v = bfs_multi_device(
        graph, sources, engine=engine, max_levels=max_levels, block=block,
        packed=packed, telemetry=True,
    )
    fv, changed, level = read_telemetry((acc, state.changed, state.level))
    if packed and packed_truncated(changed, level, requested):
        (state, acc), _v = bfs_multi_device(
            graph, sources, engine=engine, max_levels=max_levels,
            block=block, packed=False, telemetry=True,
        )
        fv, changed, level = read_telemetry((acc, state.changed, state.level))
        packed = False
    cap = min(PACKED_MAX_LEVELS, requested) if packed else requested
    return level_curve(fv, cap=cap)


def bfs_multi_direction(graph, sources, *, max_levels=None, config=None,
                        block: int = 1024):
    """Direction-optimizing batched multi-source BFS (ISSUE 7): the
    lock-step trees share one fused loop carrying BOTH layouts (edge
    list + ELL) and an ``lax.cond`` selects push or pull per superstep
    from the GLOBAL frontier masses (models/direction.py — the Beamer
    predicate and knobs).  Returns ``(MultiBfsResult, schedule)``,
    bit-exact with :func:`bfs_multi` under any schedule."""
    from .direction import bfs_multi_direction as _impl

    return _impl(
        graph, sources, max_levels=max_levels, config=config, block=block
    )


def collapse_multi_source(result: MultiBfsResult):
    """Reduce per-source trees to the oracle's multi-source answer:
    ``dist[v] = min_s dist_s[v]``, parent from the argmin source's tree with
    min-source tie-break (deterministic)."""
    order = np.argsort(result.sources, kind="stable")
    dist_s = result.dist[order]
    parent_s = result.parent[order]
    srcs = result.sources[order]
    best = np.argmin(dist_s, axis=0)  # first (=min source) among ties
    cols = np.arange(dist_s.shape[1])
    dist = dist_s[best, cols]
    parent = parent_s[best, cols]
    # A multi-source tree roots each source at itself (its own parent).
    is_source = np.isin(np.arange(dist.shape[0]), srcs) & (dist == 0)
    parent = np.where(is_source, np.arange(dist.shape[0]), parent)
    parent = np.where(dist == INF_DIST, NO_PARENT, parent)
    return dist.astype(np.int32), parent.astype(np.int32)
