"""Direction-optimizing supersteps: Beamer-style push/pull selection.

The reference (and every engine through PR 6) runs each superstep the same
way regardless of frontier size.  Direction-optimizing BFS (Beamer et al.;
BLEST arxiv 2512.21967 and Graph Traversal on Tensor Cores arxiv 2606.05081
are the tensor-core instantiations) switches bodies per superstep:

  * **push** (element/frontier): walk the frontier's out-edges — cheap on
    SPARSE frontiers (the first and last levels of a low-diameter graph),
    cost ~ frontier out-edge mass.
  * **pull** (dense relay): evaluate every vertex's in-edges against the
    frontier — cheap on the DENSE middle levels, cost ~ fixed per
    superstep but touched-once per vertex.

The classic thresholds, both tunable:

    go pull when  m_f * alpha > m_u      (frontier out-edges vs unexplored)
    stay pull while  n_f * beta > n      (frontier occupancy vs vertices)

evaluated here STATELESSLY per superstep (``pull iff either holds``) so
the decision is a pure function of on-device frontier state — no Python
in the loop, no host sync: the predicate compiles into the fused
``while_loop`` body and an ``lax.cond`` selects the superstep body.  The
unexplored-edge mass ``m_u`` rides the loop carry (decremented by each
new frontier's mass — the masked out-degree sum the predicate needs
anyway), so no extra O(V) pass exists beyond the one sum.

Knobs (resolved once per engine/program, never per superstep):

    BFS_TPU_DIRECTION        push | pull | auto   (default auto)
    BFS_TPU_DIRECTION_ALPHA  float > 0            (default 14.0)
    BFS_TPU_DIRECTION_BETA   float > 0            (default 24.0)

The chosen direction per level is recorded in the telemetry accumulator
(obs/telemetry.py DIR_PUSH/DIR_PULL) and ships as
``details.direction_schedule`` next to the level curve.

This module also hosts the combined-layout programs for the push/pull
engines: :func:`bfs_direction` / :func:`bfs_multi_direction` carry BOTH
operand sets (the dst-sorted edge list for push, the ELL for pull) in one
fused program and cond between :func:`~bfs_tpu.ops.relax.relax_superstep`
and :func:`~bfs_tpu.ops.pull.relax_pull_superstep` per superstep —
bit-exact against either pure engine for ANY schedule, since both bodies
compute the same canonical min-parent candidates.  The relay engine's
switching (sparse gather vs dense relay) lives in models/bfs.py.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs
from ..analysis.runtime import traced
from ..obs import telemetry as T
from ..ops.relax import INT32_MAX

DEFAULT_ALPHA = 14.0
DEFAULT_BETA = 24.0

DIRECTION_MODES = ("push", "pull", "auto")


@dataclass(frozen=True)
class DirectionConfig:
    """Resolved direction policy — hashable, so it can sit in program and
    executable cache keys (the flag must thread through
    ``ExecutableCache`` keys so a knob flip can never reuse a stale
    compiled program, and auto-switching itself never retraces: the cond
    is IN the program)."""

    mode: str = "auto"
    alpha: float = DEFAULT_ALPHA
    beta: float = DEFAULT_BETA

    def key(self) -> tuple:
        return (self.mode, float(self.alpha), float(self.beta))


def resolve_direction(mode: str | None = None) -> DirectionConfig:
    """Env-resolved config; an explicit ``mode`` argument wins over
    ``BFS_TPU_DIRECTION``.  Raises on unknown modes/non-positive
    thresholds (silently clamping a typo'd knob would quietly change
    what a capture measured)."""
    if mode is None:
        mode = knobs.get("BFS_TPU_DIRECTION")
    if mode not in DIRECTION_MODES:
        raise ValueError(
            f"unknown direction {mode!r}; use 'push', 'pull' or 'auto'"
        )
    alpha = float(knobs.get("BFS_TPU_DIRECTION_ALPHA"))
    beta = float(knobs.get("BFS_TPU_DIRECTION_BETA"))
    if alpha <= 0 or beta <= 0:
        raise ValueError(
            f"direction thresholds must be positive (alpha={alpha}, "
            f"beta={beta})"
        )
    return DirectionConfig(mode=mode, alpha=alpha, beta=beta)


# bfs_tpu: hot traced
def take_pull(prev_pull, fsize, fedges, unexplored, num_vertices, alpha,
              beta):
    """THE on-device Beamer predicate (single definition — every fused
    program's cond compiles this), with the classic hysteresis pair:

      * in push mode, switch to pull when the frontier's out-edge mass
        crosses the unexplored mass: ``m_f * alpha > m_u``;
      * in pull mode, switch back to push when the frontier occupancy
        drops under the vertex threshold: stay while ``n_f * beta > n``.

    ``prev_pull`` is the previous superstep's decision (a loop-carried
    bool — deterministic, so a resumed run replays the schedule
    bit-identically).  All masses are float32 (counts are integer-valued
    and exact below 2^24; above it the comparison is far from the
    boundary, so rounding cannot flip it)."""
    fe = fedges.astype(jnp.float32)
    fs = fsize.astype(jnp.float32)
    go_pull = fe * jnp.float32(alpha) > unexplored.astype(jnp.float32)
    stay_pull = fs * jnp.float32(beta) > jnp.float32(np.float32(num_vertices))
    return jnp.where(prev_pull, stay_pull, go_pull)


# bfs_tpu: hot traced
def frontier_masses(frontier_bool, outdeg):
    """(occupancy, out-edge mass float32) of a bool frontier — summed over
    every axis (batched states give the GLOBAL masses: the lock-step
    multi-source programs make one decision for the whole batch)."""
    fsize = frontier_bool.sum(dtype=jnp.int32)
    fedges = jnp.where(frontier_bool, outdeg, 0).astype(jnp.float32).sum()
    return fsize, fedges


# bfs_tpu: hot traced
def frontier_masses_words(fwords, outdeg, n: int):
    """Word-packed twin of :func:`frontier_masses`: (occupancy int32,
    out-edge mass float32) from standard-packed frontier words over an
    ``n``-element id space — ONE popcount + one masked out-degree sum.
    THE single definition of the Beamer predicate's inputs for every
    word-frontier program: the single-chip relay loop
    (models/bfs._frontier_masses_words delegates here) and the sharded
    relay's replicated global-mass computation compile exactly this, so
    mesh and single-chip schedules see identical masses (float32 sums of
    per-vertex integers — exact below 2^24, which is what makes the
    ISSUE 11 bit-identical schedule parity provable rather than
    approximate)."""
    import jax

    from ..ops.relay import unpack_std

    fsize = jax.lax.population_count(fwords).sum(dtype=jnp.int32)
    bools = unpack_std(fwords, n)
    fe = jnp.where(bools != 0, outdeg, 0).astype(jnp.float32).sum()
    return fsize, fe


def _host_outdeg(num_vertices: int, src: np.ndarray) -> np.ndarray:
    """Out-degree per vertex id from the (possibly padded) edge-source
    array: int32[V+1] with an inert sentinel slot, matching the engines'
    ``[V+1]`` state convention."""
    deg = np.bincount(
        np.asarray(src)[np.asarray(src) < num_vertices],
        minlength=num_vertices,
    )
    return np.concatenate([deg, [0]]).astype(np.int32)


# --------------------------------------------------------------------------
# Combined push/pull fused programs (single- and multi-source).


def _dir_code(mode: str, use_pull):
    if mode == "push":
        return jnp.int32(T.DIR_PUSH)
    if mode == "pull":
        return jnp.int32(T.DIR_PULL)
    return jnp.where(use_pull, jnp.int32(T.DIR_PULL), jnp.int32(T.DIR_PUSH))


@functools.partial(
    jax.jit,
    static_argnames=("num_vertices", "max_levels", "packed", "mode"),
)
@traced("direction._bfs_direction_fused")
def _bfs_direction_fused(
    src, dst, ell0, folds, outdeg, sources, alpha, beta,
    num_vertices: int, max_levels: int, packed: bool = False,
    mode: str = "auto",
):
    """One fused loop over BOTH layouts: per superstep an ``lax.cond`` on
    the Beamer predicate selects the push body (edge-list segment-min)
    or the pull body (ELL gather row-min).  ``sources`` is int32[] for a
    single tree or int32[S] for the lock-step batch (one GLOBAL decision
    per superstep — the trees share the loop).  Returns
    ``(state, occupancy_acc, direction_acc)``; the accumulators are
    pulled once at loop exit (obs/telemetry.py contract).  ``alpha`` /
    ``beta`` are TRACED operands, so threshold sweeps never recompile.

    With ``packed`` the carry is the fused ``level:6|parent:26`` word
    state capped at PACKED_MAX_LEVELS; callers detect a cap exit via
    ``packed_truncated`` and re-run unpacked — switching and fallback
    compose (the schedule is a pure function of frontier masses, which
    both carries produce identically)."""
    from ..ops.packed import packed_cap
    from ..ops.pull import relax_pull_superstep, relax_pull_superstep_packed
    from ..ops.relax import (
        init_batched_state,
        init_packed_batched_state,
        init_packed_state,
        init_state,
        relax_superstep,
        relax_superstep_batched,
        relax_superstep_batched_packed,
        relax_superstep_packed,
        unpack_bfs_state,
    )

    batched = sources.ndim == 1
    nsrc = sources.shape[0] if batched else 1
    total_edges = outdeg.astype(jnp.float32).sum() * jnp.float32(nsrc)

    if packed:
        cap = packed_cap(max_levels)
        state = (
            init_packed_batched_state(num_vertices, sources)
            if batched
            else init_packed_state(num_vertices, sources)
        )

        def push_body(s):
            return (
                relax_superstep_batched_packed(s, src, dst)
                if batched
                else relax_superstep_packed(s, src, dst)
            )

        def pull_body(s):
            return relax_pull_superstep_packed(s, ell0, folds)

    else:
        cap = max_levels
        state = (
            init_batched_state(num_vertices, sources)
            if batched
            else init_state(num_vertices, sources)
        )

        def push_body(s):
            return (
                relax_superstep_batched(s, src, dst)
                if batched
                else relax_superstep(s, src, dst)
            )

        def pull_body(s):
            return relax_pull_superstep(s, ell0, folds)

    occ0 = T.init_level_acc(nsrc, wide=batched)
    dir0 = T.init_dir_acc()
    src_edges = (
        outdeg[sources].astype(jnp.float32).sum()
        if batched
        else outdeg[sources].astype(jnp.float32)
    )
    def cond(c):
        s = c[0]
        return s.changed & (s.level < cap)

    if mode == "auto":
        carry0 = (
            state, total_edges - src_edges, src_edges, jnp.bool_(False),
            occ0, dir0,
        )

        def body(c):
            s, mu, fe, prev_pull, occ, dirs = c
            fsize, _ = frontier_masses(s.frontier, outdeg)
            use_pull = take_pull(
                prev_pull, fsize, fe, mu, num_vertices * nsrc, alpha, beta
            )
            s2 = jax.lax.cond(use_pull, pull_body, push_body, s)
            _, fe2 = frontier_masses(s2.frontier, outdeg)
            occ = T.record_frontier_bools(occ, s2.frontier, s2.level)
            dirs = T.record_direction(
                dirs, s2.level, _dir_code(mode, use_pull)
            )
            # Clamp: float32 rounding must not let the unexplored mass
            # dip below zero at the tail (a negative m_u would satisfy
            # ANY pull threshold and perturb the schedule's last
            # entries).
            return s2, jnp.maximum(mu - fe2, 0.0), fe2, use_pull, occ, dirs

        out, _, _, _, occ, dirs = jax.lax.while_loop(cond, body, carry0)
    else:
        # Forced modes: no predicate, so no per-superstep mass sums and
        # no mu/fe/prev carry — the body is the chosen superstep plus
        # the two accumulator writes.
        forced_body = push_body if mode == "push" else pull_body
        code = _dir_code(mode, None)

        def body(c):
            s, occ, dirs = c
            s2 = forced_body(s)
            occ = T.record_frontier_bools(occ, s2.frontier, s2.level)
            dirs = T.record_direction(dirs, s2.level, code)
            return s2, occ, dirs

        out, occ, dirs = jax.lax.while_loop(
            cond, body, (state, occ0, dir0)
        )
    if packed:
        out = unpack_bfs_state(out)
    return out, occ, dirs


def _direction_operands(graph, *, block: int = 1024):
    """Both device layouts + the out-degree table for the combined
    program, built once per call site (tests/serving memoize upstream)."""
    from ..graph.csr import DeviceGraph, build_device_graph
    from ..graph.ell import PullGraph, build_pull_graph, device_ell

    if isinstance(graph, (DeviceGraph, PullGraph)):
        raise ValueError(
            "bfs_direction needs the raw Graph: it builds BOTH the edge "
            "list (push) and ELL (pull) layouts"
        )
    dg = build_device_graph(graph, block=block)
    pg = build_pull_graph(graph)
    ell0, folds = device_ell(pg)
    outdeg = jnp.asarray(_host_outdeg(dg.num_vertices, dg.src))
    return dg, ell0, folds, outdeg


def _run_direction(graph, sources, *, max_levels, config, block):
    from ..ops.packed import (
        packed_parent_fits,
        packed_truncated,
        resolve_packed,
    )
    from .bfs import check_sources

    cfg = config if config is not None else resolve_direction()
    dg, ell0, folds, outdeg = _direction_operands(graph, block=block)
    check_sources(dg.num_vertices, sources)
    limit = int(max_levels) if max_levels is not None else dg.num_vertices
    src_t, dst_t = jnp.asarray(dg.src), jnp.asarray(dg.dst)
    alpha = jnp.float32(cfg.alpha)
    beta = jnp.float32(cfg.beta)

    def run(packed):
        return _bfs_direction_fused(
            src_t, dst_t, ell0, folds, outdeg, jnp.asarray(sources),
            alpha, beta, dg.num_vertices, limit, packed, cfg.mode,
        )

    packed = resolve_packed(packed_parent_fits(dg.num_vertices))
    state, occ, dirs = jax.device_get(run(packed))
    if packed and packed_truncated(state.changed, state.level, limit):
        # Deeper than the packed level field: re-run unpacked — the
        # schedule re-records identically (it is a pure function of the
        # frontier masses both carries share).
        state, occ, dirs = jax.device_get(run(False))
    schedule = T.direction_schedule(
        dirs, mode=cfg.mode, alpha=cfg.alpha, beta=cfg.beta
    )
    return state, occ, schedule, dg.num_vertices


def bfs_direction(
    graph,
    source: int = 0,
    *,
    max_levels: int | None = None,
    config: DirectionConfig | None = None,
    block: int = 1024,
):
    """Single-source direction-optimizing BFS over the push/pull engine
    pair: returns ``(BfsResult, direction_schedule dict)``.  Bit-exact
    against ``bfs(engine='push'/'pull')`` for any schedule."""
    from .bfs import BfsResult

    state, _occ, schedule, v = _run_direction(
        graph, np.int32(source), max_levels=max_levels, config=config,
        block=block,
    )
    result = BfsResult(
        dist=np.asarray(state.dist[:v]),
        parent=np.asarray(state.parent[:v]),
        num_levels=int(state.level),
    )
    return result, schedule


def bfs_multi_direction(
    graph,
    sources,
    *,
    max_levels: int | None = None,
    config: DirectionConfig | None = None,
    block: int = 1024,
):
    """Batched multi-source direction-optimizing BFS (lock-step trees,
    one global per-superstep decision): ``(MultiBfsResult, schedule)``."""
    from .multisource import MultiBfsResult

    sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
    state, _occ, schedule, v = _run_direction(
        graph, sources, max_levels=max_levels, config=config, block=block,
    )
    result = MultiBfsResult(
        sources=sources,
        dist=np.asarray(state.dist[:, :v]),
        parent=np.asarray(state.parent[:, :v]),
        num_levels=int(state.level),
    )
    return result, schedule
