"""Single-chip BFS engine: the flagship "model" of the framework.

TPU-native re-design of the reference driver ``BfsSpark.main``
(BfsSpark.java:43-120).  The reference's superstep loop round-trips through
the driver heap and the filesystem every iteration (collectAsMap + file
write + substring termination test, BfsSpark.java:110-117); here the whole
loop is ONE compiled XLA program: a ``jax.lax.while_loop`` whose carry is the
device-resident state and whose termination condition is an on-device scalar.

Two execution modes (same math):
  * :func:`bfs` — fused ``while_loop``; fastest, used for benchmarks.
  * :class:`SuperstepRunner` — one jitted superstep per Python call, exposing
    per-superstep metrics / state dumps / checkpoints, reproducing the
    observability the reference gets from its per-iteration files and
    Stopwatch logs (BfsSpark.java:59-117) without giving up compilation.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..graph.csr import DeviceGraph, Graph, build_device_graph
from ..graph.ell import PullGraph, build_pull_graph
from ..ops.pull import relax_pull_superstep
from ..ops.relax import (
    INT32_MAX,
    BfsState,
    apply_candidates,
    frontier_size,
    init_batched_state,
    init_state,
    relax_superstep,
)


def check_sources(num_vertices: int, sources) -> None:
    """Host-side validation: an out-of-range source would otherwise be
    silently clipped by XLA's `.at[].set` into the sentinel slot, yielding an
    all-unreachable result instead of an error."""
    arr = np.atleast_1d(np.asarray(sources))
    if arr.size == 0 or arr.min() < 0 or arr.max() >= num_vertices:
        raise ValueError(
            f"source vertices {arr.tolist()} out of range for V={num_vertices}"
        )


@functools.partial(jax.jit, static_argnames=("num_vertices", "max_levels"))
def _bfs_fused(
    src: jax.Array,
    dst: jax.Array,
    source: jax.Array,
    num_vertices: int,
    max_levels: int,
) -> BfsState:
    state = init_state(num_vertices, source)

    def cond(s: BfsState):
        return s.changed & (s.level < max_levels)

    def body(s: BfsState):
        return relax_superstep(s, src, dst)

    return jax.lax.while_loop(cond, body, state)


@dataclass
class BfsResult:
    """Host-side result with the oracle's query API shapes: ``dist`` and
    ``parent`` are int32[V] (sentinel slot stripped).  ``num_levels`` counts
    *executed* supersteps including the final empty one that detects
    termination — 3 on tinyCG, matching the paper's 3 parallel iterations
    (docs/BigData_Project.pdf §1.3; the reference likewise needs a last
    map/reduce pass that finds no GRAY, BfsSpark.java:117)."""

    dist: np.ndarray
    parent: np.ndarray
    num_levels: int

    def has_path_to(self, v: int) -> bool:
        from ..graph.csr import INF_DIST

        return int(self.dist[v]) != INF_DIST

    def dist_to(self, v: int) -> int:
        return int(self.dist[v])

    def path_to(self, v: int) -> list[int]:
        from ..graph.vertex import path_to

        return path_to(self.parent, v)


@functools.partial(jax.jit, static_argnames=("num_vertices", "max_levels"))
def _bfs_pull_fused(
    ell0: jax.Array,
    folds: tuple,
    source: jax.Array,
    num_vertices: int,
    max_levels: int,
) -> BfsState:
    state = init_state(num_vertices, source)

    def cond(s: BfsState):
        return s.changed & (s.level < max_levels)

    def body(s: BfsState):
        return relax_pull_superstep(s, ell0, folds)

    return jax.lax.while_loop(cond, body, state)


def slots_to_parent(parent_slots: np.ndarray, src_l1: np.ndarray) -> np.ndarray:
    """Map relay-engine parent values (L1 slot indices; -1 unreached; the
    source's self-entry is fixed up by callers) to ORIGINAL src ids — the
    once-per-run host gather that replaces a per-superstep int32 table read
    on device (ops/relay.relay_candidates)."""
    slots = np.clip(parent_slots, 0, src_l1.shape[-1] - 1)
    return np.where(parent_slots >= 0, src_l1[slots], parent_slots).astype(np.int32)


@functools.lru_cache(maxsize=16)
def _relay_fused_program(
    num_vertices: int,
    vperm_size: int,
    out_classes: tuple,
    net_size: int,
    m2: int,
    in_classes: tuple,
):
    """Jitted relay BFS loop, cached per static layout shape so two
    :class:`RelayEngine` instances over the same graph (or two graphs with
    identical class structure) share one compiled ~100-stage program instead
    of recompiling from scratch."""
    from ..ops.relay import relay_candidates, relay_superstep

    @functools.partial(jax.jit, static_argnames=("max_levels",))
    def fused(source_new, vperm_masks, net_masks, valid_words, max_levels):
        def cand_fn(frontier):
            return relay_candidates(
                frontier,
                num_vertices=num_vertices,
                vperm_masks=vperm_masks,
                vperm_size=vperm_size,
                out_classes=out_classes,
                net_masks=net_masks,
                net_size=net_size,
                m2=m2,
                in_classes=in_classes,
                valid_words=valid_words,
            )

        # Exact [V] shapes: the relay engine has no padded-edge sentinel to
        # absorb, and the [V+1] convention costs a concat copy per superstep.
        state = init_state(num_vertices, source_new, sentinel=False)

        def cond(s: BfsState):
            return s.changed & (s.level < max_levels)

        def body(s: BfsState):
            return relay_superstep(s, cand_fn)

        return jax.lax.while_loop(cond, body, state)

    return fused


@functools.lru_cache(maxsize=16)
def _relay_step_program(
    num_vertices: int,
    vperm_size: int,
    out_classes: tuple,
    net_size: int,
    m2: int,
    in_classes: tuple,
):
    """One jitted relay superstep (the stepped / observable path): same math
    as one iteration of :func:`_relay_fused_program`, with the layout tensors
    as arguments so they are not baked into the program as constants."""
    from ..ops.relay import relay_candidates, relay_superstep

    @jax.jit
    def step(state, vperm_masks, net_masks, valid_words):
        def cand_fn(frontier):
            return relay_candidates(
                frontier,
                num_vertices=num_vertices,
                vperm_masks=vperm_masks,
                vperm_size=vperm_size,
                out_classes=out_classes,
                net_masks=net_masks,
                net_size=net_size,
                m2=m2,
                in_classes=in_classes,
                valid_words=valid_words,
            )

        return relay_superstep(state, cand_fn)

    return step


@functools.lru_cache(maxsize=16)
def _relay_multi_fused_program(
    num_vertices: int,
    vperm_size: int,
    out_classes: tuple,
    net_size: int,
    m2: int,
    in_classes: tuple,
):
    """Batched (multi-source) relay loop: ``vmap`` lifts the gather-free
    candidate pipeline over a leading sources axis — every stage is dense
    elementwise/reshape math, so batching is mechanical — while all trees
    share one lock-step ``while_loop`` (BASELINE.json config 5 semantics,
    matching the other engines' batched mode)."""
    from ..ops.relay import relay_candidates

    @functools.partial(jax.jit, static_argnames=("max_levels",))
    def fused(sources_new, vperm_masks, net_masks, valid_words, max_levels):
        def cand_fn(frontier):
            return relay_candidates(
                frontier,
                num_vertices=num_vertices,
                vperm_masks=vperm_masks,
                vperm_size=vperm_size,
                out_classes=out_classes,
                net_masks=net_masks,
                net_size=net_size,
                m2=m2,
                in_classes=in_classes,
                valid_words=valid_words,
            )

        cand_batched = jax.vmap(cand_fn)
        state = init_batched_state(num_vertices, sources_new, sentinel=False)

        def cond(s: BfsState):
            return s.changed & (s.level < max_levels)

        def body(s: BfsState):
            return apply_candidates(s, cand_batched(s.frontier))

        return jax.lax.while_loop(cond, body, state)

    return fused


class RelayEngine:
    """Device-resident relay layout + fused BFS loop (engine='relay').

    Build once per graph; call :meth:`run` per source.  The whole superstep
    loop is one XLA program of dense ops — see graph/relay.py.
    """

    def __init__(self, graph):
        from ..graph.relay import RelayGraph, build_relay_graph
        from ..ops.relay import valid_slot_words

        rg = graph if isinstance(graph, RelayGraph) else build_relay_graph(graph)
        self.relay_graph = rg
        # Device-resident layout tensors are passed as jit ARGUMENTS — a
        # closed-over concrete array is baked into the program as a constant,
        # and the routing masks are hundreds of MB at scale >= 20.  The int32
        # src table stays HOST-side (candidates are slot indices; see
        # ops/relay.relay_candidates), freeing ~4 bytes/edge of HBM.
        self._tensors = (
            jnp.asarray(rg.vperm_masks),
            jnp.asarray(rg.net_masks),
            jnp.asarray(valid_slot_words(rg.src_l1, rg.net_size)),
        )
        self._raw_fused = _relay_fused_program(
            rg.num_vertices,
            rg.vperm_size,
            rg.out_classes,
            rg.net_size,
            rg.m2,
            rg.in_classes,
        )

    def _fused(self, source_new, max_levels):
        return self._raw_fused(source_new, *self._tensors, max_levels=max_levels)

    def step(self, state: BfsState) -> BfsState:
        """One compiled relay superstep (state in RELABELED space)."""
        rg = self.relay_graph
        step = _relay_step_program(
            rg.num_vertices,
            rg.vperm_size,
            rg.out_classes,
            rg.net_size,
            rg.m2,
            rg.in_classes,
        )
        return step(state, *self._tensors)

    def run(self, source: int = 0, *, max_levels: int | None = None) -> BfsResult:
        rg = self.relay_graph
        check_sources(rg.num_vertices, source)
        max_levels = int(max_levels) if max_levels is not None else rg.num_vertices
        source_new = int(rg.old2new[source])
        state = jax.device_get(self._fused(jnp.int32(source_new), max_levels))
        # Engine state lives in relabeled space with L1-SLOT parent values;
        # map slots -> original src ids and the index space back (host, once
        # per run).
        dist_new = np.asarray(state.dist[: rg.num_vertices])
        parent_new = slots_to_parent(
            np.asarray(state.parent[: rg.num_vertices]), rg.src_l1
        )
        dist = dist_new[rg.old2new]
        parent = parent_new[rg.old2new]
        parent[source] = source  # init wrote the relabeled id at the source
        return BfsResult(dist=dist, parent=parent, num_levels=int(state.level))

    def run_multi_device(self, sources, *, max_levels: int | None = None) -> BfsState:
        """Batched multi-source BFS, DEVICE-resident result: the raw batched
        :class:`BfsState` in the relabeled space with slot-index parents.
        No host transfer — reading ``int(state.level)`` is the cheap sync
        (benchmark timing path; through a remote-device tunnel the full
        state pull costs several times the traversal itself)."""
        rg = self.relay_graph
        sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
        check_sources(rg.num_vertices, sources)
        max_levels = int(max_levels) if max_levels is not None else rg.num_vertices
        fused = _relay_multi_fused_program(
            rg.num_vertices,
            rg.vperm_size,
            rg.out_classes,
            rg.net_size,
            rg.m2,
            rg.in_classes,
        )
        sources_new = jnp.asarray(rg.old2new[sources])
        return fused(sources_new, *self._tensors, max_levels=max_levels)

    def run_multi(self, sources, *, max_levels: int | None = None):
        """Batched multi-source BFS on the relay layout; returns a
        :class:`~bfs_tpu.models.multisource.MultiBfsResult` in original-id
        space (bit-exact with the other engines' batched modes)."""
        from .multisource import MultiBfsResult

        rg = self.relay_graph
        sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
        state = jax.device_get(
            self.run_multi_device(sources, max_levels=max_levels)
        )
        dist_new = np.asarray(state.dist[:, : rg.num_vertices])
        parent_new = slots_to_parent(
            np.asarray(state.parent[:, : rg.num_vertices]), rg.src_l1
        )
        dist = dist_new[:, rg.old2new]
        parent = parent_new[:, rg.old2new]
        rows = np.arange(sources.shape[0])
        parent[rows, sources] = sources  # init wrote relabeled ids at sources
        return MultiBfsResult(
            sources=sources,
            dist=dist,
            parent=parent,
            num_levels=int(state.level),
        )


def bfs(
    graph: Graph | DeviceGraph | PullGraph,
    source: int = 0,
    *,
    engine: str = "pull",
    max_levels: int | None = None,
    block: int = 1024,
) -> BfsResult:
    """Run single-source BFS fully on-device and return host results.

    Engines (same math, different layouts):
      * ``'relay'`` — gather-free degree-class + Beneš bit-routing layout;
        the fast path on real TPUs (requires the native router).
      * ``'pull'`` (default) — ELL gather/row-min formulation.
      * ``'push'`` — segment_min push formulation, the closest analogue of
        the reference's map/shuffle/reduce (BfsSpark.java:66-108).
    Passing a prebuilt :class:`PullGraph`/:class:`DeviceGraph` skips layout.
    """
    from ..graph.relay import RelayGraph

    if engine not in ("pull", "push", "relay"):
        raise ValueError(f"unknown engine {engine!r}; use 'relay', 'pull' or 'push'")
    if isinstance(graph, PullGraph) and engine != "pull":
        raise ValueError("a prebuilt PullGraph only runs on engine='pull'")
    if isinstance(graph, RelayGraph) and engine != "relay":
        raise ValueError("a prebuilt RelayGraph only runs on engine='relay'")
    if engine == "relay":
        eng = RelayEngine(graph)
        return eng.run(source, max_levels=max_levels)
    if engine == "pull":
        pg = graph if isinstance(graph, PullGraph) else build_pull_graph(graph)
        check_sources(pg.num_vertices, source)
        max_levels = int(max_levels) if max_levels is not None else pg.num_vertices
        state = _bfs_pull_fused(
            jnp.asarray(pg.ell0),
            tuple(jnp.asarray(f) for f in pg.folds),
            jnp.int32(source),
            pg.num_vertices,
            max_levels,
        )
        num_vertices = pg.num_vertices
    else:
        dg = graph if isinstance(graph, DeviceGraph) else build_device_graph(graph, block=block)
        if dg.num_shards != 1:
            raise ValueError("sharded DeviceGraph requires the parallel engine")
        check_sources(dg.num_vertices, source)
        max_levels = int(max_levels) if max_levels is not None else dg.num_vertices
        state = _bfs_fused(
            jnp.asarray(dg.src),
            jnp.asarray(dg.dst),
            jnp.int32(source),
            dg.num_vertices,
            max_levels,
        )
        num_vertices = dg.num_vertices
    state = jax.device_get(state)
    return BfsResult(
        dist=np.asarray(state.dist[:num_vertices]),
        parent=np.asarray(state.parent[:num_vertices]),
        num_levels=int(state.level),
    )


class SuperstepRunner:
    """Stepped execution: one compiled superstep per call, any engine.

    This is the observable path — per-superstep wall time (Stopwatch parity,
    BfsSpark.java:59,63,111-112), frontier sizes, state dumps and
    checkpoint/resume hooks — while each superstep itself stays a single
    fused XLA computation.  ``engine`` selects the same layouts as
    :func:`bfs`: ``'push'`` (default, the reference's map/shuffle/reduce
    analogue), ``'pull'`` (ELL), or ``'relay'`` (the TPU-fast Beneš layout).

    For the relay engine the on-device state lives in the RELABELED vertex
    space; :meth:`to_original` maps any state's ``(dist, parent, frontier)``
    into original-id host arrays for dumps/checkpoints, and is the identity
    for push/pull.  Frontier sizes and levels are permutation-invariant.
    """

    def __init__(
        self,
        graph: Graph | DeviceGraph | PullGraph,
        *,
        engine: str = "push",
        block: int = 1024,
    ):
        from ..graph.relay import RelayGraph

        self.engine = engine
        self.device_graph = None
        self._old2new = None  # relabeling (relay only)
        if engine == "push":
            if isinstance(graph, (PullGraph, RelayGraph)):
                raise ValueError("engine='push' needs a Graph or DeviceGraph")
            self.device_graph = (
                graph
                if isinstance(graph, DeviceGraph)
                else build_device_graph(graph, block=block)
            )
            if self.device_graph.num_shards != 1:
                raise ValueError("sharded DeviceGraph requires the parallel engine")
            self.num_vertices = self.device_graph.num_vertices
            src = jnp.asarray(self.device_graph.src)
            dst = jnp.asarray(self.device_graph.dst)
            self._step = jax.jit(lambda s: relax_superstep(s, src, dst))
        elif engine == "pull":
            pg = graph if isinstance(graph, PullGraph) else build_pull_graph(graph)
            self.num_vertices = pg.num_vertices
            ell0 = jnp.asarray(pg.ell0)
            folds = tuple(jnp.asarray(f) for f in pg.folds)
            self._step = jax.jit(lambda s: relax_pull_superstep(s, ell0, folds))
        elif engine == "relay":
            eng = RelayEngine(graph)
            self._relay = eng
            self.num_vertices = eng.relay_graph.num_vertices
            self._old2new = eng.relay_graph.old2new
            self._step = eng.step
        else:
            raise ValueError(
                f"unknown engine {engine!r}; use 'push', 'pull' or 'relay'"
            )
        self._init = jax.jit(functools.partial(init_state, self.num_vertices))

    def init(self, source: int = 0) -> BfsState:
        check_sources(self.num_vertices, source)
        if self._old2new is not None:
            source = int(self._old2new[source])
        return self._init(jnp.int32(source))

    def step(self, state: BfsState) -> BfsState:
        return self._step(state)

    def frontier_size(self, state: BfsState) -> int:
        return int(frontier_size(state))

    def to_original(self, state: BfsState, *, source: int | None = None):
        """Host ``(dist, parent, frontier)`` in ORIGINAL vertex-id space.

        ``source`` (original id) fixes the relay engine's self-parent entry,
        which init writes in relabeled space."""
        state = jax.device_get(state)
        v = self.num_vertices
        dist = np.asarray(state.dist[:v])
        parent = np.asarray(state.parent[:v])
        frontier = np.asarray(state.frontier[:v])
        if self._old2new is not None:
            parent = slots_to_parent(parent, self._relay.relay_graph.src_l1)
            dist = dist[self._old2new]
            parent = parent[self._old2new]
            frontier = frontier[self._old2new]
            if source is not None:
                parent[source] = source
        return dist, parent, frontier

    def run(self, source: int = 0, *, max_levels: int | None = None, observer=None):
        """Run to termination; ``observer(level, state)`` is called after each
        superstep (metrics/dump/checkpoint hook)."""
        state = self.init(source)
        limit = max_levels if max_levels is not None else self.num_vertices
        while bool(state.changed) and int(state.level) < limit:
            state = self.step(state)
            if observer is not None:
                observer(int(state.level), state)
        num_levels = int(state.level)
        dist, parent, _ = self.to_original(state, source=source)
        return BfsResult(dist=dist, parent=parent, num_levels=num_levels)
