"""Single-chip BFS engine: the flagship "model" of the framework.

TPU-native re-design of the reference driver ``BfsSpark.main``
(BfsSpark.java:43-120).  The reference's superstep loop round-trips through
the driver heap and the filesystem every iteration (collectAsMap + file
write + substring termination test, BfsSpark.java:110-117); here the whole
loop is ONE compiled XLA program: a ``jax.lax.while_loop`` whose carry is the
device-resident state and whose termination condition is an on-device scalar.

Two execution modes (same math):
  * :func:`bfs` — fused ``while_loop``; fastest, used for benchmarks.
  * :class:`SuperstepRunner` — one jitted superstep per Python call, exposing
    per-superstep metrics / state dumps / checkpoints, reproducing the
    observability the reference gets from its per-iteration files and
    Stopwatch logs (BfsSpark.java:59-117) without giving up compilation.
"""

from __future__ import annotations

import functools
import logging
import sys
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

from .. import knobs
from ..analysis.runtime import traced
from ..obs.spans import span as obs_span
from ..graph.csr import DeviceGraph, Graph, build_device_graph
from ..graph.ell import PullGraph, build_pull_graph
from ..ops.pull import relax_pull_superstep
from ..ops.relax import (
    INT32_MAX,
    BfsState,
    apply_candidates,
    frontier_size,
    init_batched_state,
    init_state,
    relax_superstep,
)


def check_sources(num_vertices: int, sources) -> None:
    """Host-side validation: an out-of-range source would otherwise be
    silently clipped by XLA's `.at[].set` into the sentinel slot, yielding an
    all-unreachable result instead of an error."""
    arr = np.atleast_1d(np.asarray(sources))
    if arr.size == 0 or arr.min() < 0 or arr.max() >= num_vertices:
        raise ValueError(
            f"source vertices {arr.tolist()} out of range for V={num_vertices}"
        )


def _loop_with_acc(cond, body, state, acc, record):
    """``while_loop`` carrying ``(state, acc)``: the shared shape of every
    fused program's telemetry variant (obs/telemetry.py).  ``record(acc,
    new_state)`` runs ON DEVICE inside the loop body; the accumulator is
    pulled once at loop exit by the CALLER (the OBS001 contract).  Returns
    ``(final_state, final_acc)``."""

    def cond2(carry):
        return cond(carry[0])

    def body2(carry):
        st, a = carry
        st2 = body(st)
        return st2, record(a, st2)

    return jax.lax.while_loop(cond2, body2, (state, acc))


@functools.partial(
    jax.jit,
    static_argnames=("num_vertices", "max_levels", "packed", "telemetry"),
)
@traced("bfs._bfs_fused")
def _bfs_fused(
    src: jax.Array,
    dst: jax.Array,
    source: jax.Array,
    num_vertices: int,
    max_levels: int,
    packed: bool = False,
    telemetry: bool = False,
):
    """With ``packed``, the loop carries the fused ``level:6|parent:26``
    word state (ops/packed.py — half the per-superstep dist/parent HBM
    bytes), capped at PACKED_MAX_LEVELS and unpacked ONCE at loop exit, so
    the returned BfsState is shape- and value-identical to the unpacked
    path wherever the cap was not hit.  Callers detect a cap exit via
    ``packed_truncated`` and re-run unpacked.

    With ``telemetry`` (static), the loop additionally carries the
    per-level occupancy accumulator and returns ``(BfsState, acc)`` —
    pulled once at loop exit by the caller (obs/telemetry.py)."""
    if telemetry:
        from ..obs import telemetry as T

        acc0 = T.init_level_acc()

        def rec(a, s):
            return T.record_frontier_bools(a, s.frontier, s.level)

    if packed:
        from ..ops.packed import packed_cap
        from ..ops.relax import (
            init_packed_state,
            relax_superstep_packed,
            unpack_bfs_state,
        )

        cap = packed_cap(max_levels)
        pstate = init_packed_state(num_vertices, source)

        def pcond(s):
            return s.changed & (s.level < cap)

        def pbody(s):
            return relax_superstep_packed(s, src, dst)

        if telemetry:
            out, acc = _loop_with_acc(pcond, pbody, pstate, acc0, rec)
            return unpack_bfs_state(out), acc
        return unpack_bfs_state(jax.lax.while_loop(pcond, pbody, pstate))
    state = init_state(num_vertices, source)

    def cond(s: BfsState):
        return s.changed & (s.level < max_levels)

    def body(s: BfsState):
        return relax_superstep(s, src, dst)

    if telemetry:
        return _loop_with_acc(cond, body, state, acc0, rec)
    return jax.lax.while_loop(cond, body, state)


@dataclass
class BfsResult:
    """Host-side result with the oracle's query API shapes: ``dist`` and
    ``parent`` are int32[V] (sentinel slot stripped).  ``num_levels`` counts
    *executed* supersteps including the final empty one that detects
    termination — 3 on tinyCG, matching the paper's 3 parallel iterations
    (docs/BigData_Project.pdf §1.3; the reference likewise needs a last
    map/reduce pass that finds no GRAY, BfsSpark.java:117)."""

    dist: np.ndarray
    parent: np.ndarray
    num_levels: int

    def has_path_to(self, v: int) -> bool:
        from ..graph.csr import INF_DIST

        return int(self.dist[v]) != INF_DIST

    def dist_to(self, v: int) -> int:
        return int(self.dist[v])

    def path_to(self, v: int) -> list[int]:
        from ..graph.vertex import path_to

        return path_to(self.parent, v)


@functools.partial(
    jax.jit,
    static_argnames=("num_vertices", "max_levels", "packed", "telemetry"),
)
@traced("bfs._bfs_pull_fused")
def _bfs_pull_fused(
    ell0: jax.Array,
    folds: tuple,
    source: jax.Array,
    num_vertices: int,
    max_levels: int,
    packed: bool = False,
    telemetry: bool = False,
):
    """``packed`` as in :func:`_bfs_fused`: fused-word carry, one unpack
    at loop exit, PACKED_MAX_LEVELS cap.  ``telemetry`` as in
    :func:`_bfs_fused`: returns ``(BfsState, acc)``."""
    if telemetry:
        from ..obs import telemetry as T

        acc0 = T.init_level_acc()

        def rec(a, s):
            return T.record_frontier_bools(a, s.frontier, s.level)

    if packed:
        from ..ops.packed import packed_cap
        from ..ops.pull import relax_pull_superstep_packed
        from ..ops.relax import init_packed_state, unpack_bfs_state

        cap = packed_cap(max_levels)
        pstate = init_packed_state(num_vertices, source)

        def pcond(s):
            return s.changed & (s.level < cap)

        def pbody(s):
            return relax_pull_superstep_packed(s, ell0, folds)

        if telemetry:
            out, acc = _loop_with_acc(pcond, pbody, pstate, acc0, rec)
            return unpack_bfs_state(out), acc
        return unpack_bfs_state(jax.lax.while_loop(pcond, pbody, pstate))
    state = init_state(num_vertices, source)

    def cond(s: BfsState):
        return s.changed & (s.level < max_levels)

    def body(s: BfsState):
        return relax_pull_superstep(s, ell0, folds)

    if telemetry:
        return _loop_with_acc(cond, body, state, acc0, rec)
    return jax.lax.while_loop(cond, body, state)


def _adj_ranks(rg) -> np.ndarray:
    """Per-edge within-row ranks from the layout's per-edge L1 slots (the
    slot formula ``slot = base + rank*stride`` inverted with the static
    vertex tables).  Host-side, once per engine, only when the sparse
    hybrid ships adjacency at all — keeps the on-disk layout bundles
    slot-based."""
    from ..graph.relay import _vertex_tables

    base1, stride1 = _vertex_tables(list(rg.in_classes), rg.vr)
    d = rg.adj_dst
    return (
        (rg.adj_slot - base1[d]) // np.maximum(stride1[d], 1)
    ).astype(np.int32)


def _adj_keys(rg) -> np.ndarray:
    """Per-edge ORIGINAL src ids (the MXU arm's sparse-path payload):
    ``src_l1[adj_slot]`` — the same table the gather arm's once-per-run
    host map reads, gathered per edge instead.  Sorting (dst, key) IS the
    canonical min-parent tie-break, so the sparse superstep needs no
    changes to emit key candidates."""
    return np.asarray(rg.src_l1)[np.asarray(rg.adj_slot)].astype(np.int32)


def _sparse_third(rg, packed: bool, mxu: bool) -> np.ndarray:
    """The sparse adjacency's third array per carry/arm flavor: keys for
    the mxu arm (either carry), ranks for the packed gather carry, L1
    slots for the unpacked gather carry."""
    if mxu:
        return _adj_keys(rg)
    return _adj_ranks(rg) if packed else rg.adj_slot


def slots_to_parent(parent_slots: np.ndarray, src_l1: np.ndarray) -> np.ndarray:
    """Map relay-engine parent values (L1 slot indices; -1 unreached; the
    source's self-entry is fixed up by callers) to ORIGINAL src ids — the
    once-per-run host gather that replaces a per-superstep int32 table read
    on device (ops/relay.rowmin_candidates)."""
    slots = np.clip(parent_slots, 0, src_l1.shape[-1] - 1)
    return np.where(parent_slots >= 0, src_l1[slots], parent_slots).astype(np.int32)


#: Hybrid sparse-path budgets: a superstep takes the gather path when the
#: frontier has <= SPARSE_BV vertices AND <= SPARSE_BE out-edges.
#: Round-4 measured economics (docs/ARCHITECTURE.md §8): a sparse superstep
#: costs ~25 ms of intrinsic gather work at the TPU's scalar-gather rate
#: (0.02-0.09 G gathers/s measured: extraction 9 ms + degree gathers
#: 3.4 ms + edge gathers, 64K-pair sort, scatters) vs ~13 ms for a dense
#: superstep on the probed Pallas applier — so the hybrid LOSES on the TPU
#: headline config even under the cond-free nested-while dispatch, and
#: bench.py defaults it OFF.  It remains right where a dense full-net
#: superstep is much costlier than ~25 ms: CPU backends (tests run with it
#: on) and high-diameter graphs with long tiny-frontier tails.
SPARSE_BV = 32 * 1024
SPARSE_BE = 64 * 1024


def sparse_budgets(vr: int, num_adj_entries: int) -> tuple[int, int]:
    """Effective (vertex, edge) budgets for the sparse path's STATIC
    shapes, clamped to the graph itself: the module budgets are overflow
    insurance sized for bench-scale graphs, and padding a 6K-edge
    graph's every push superstep to the 64K-lane worst case made the
    sparse path ~10x slower than it needed to be at small scales (the
    gather/sort/scatter all run over the full static budget regardless
    of the live frontier).  A frontier can never exceed the whole vertex
    space or the whole adjacency, so the clamp is exact, and at bench
    scale (vr, E >> budgets) nothing changes."""
    return min(SPARSE_BV, int(vr)), min(SPARSE_BE, int(num_adj_entries))


def _relay_static(rg):
    """Hashable static layout descriptor for program caching."""
    return (
        rg.vr, rg.vperm_size, rg.vperm_table, tuple(rg.out_classes),
        rg.out_space, rg.net_table, rg.net_size, tuple(rg.in_classes),
    )


def _superstep_fn(static, use_pallas: bool, packed: bool = False,
                  phase_sel: tuple | None = None):
    """Dense superstep closure.  ``vperm_m``/``net_m`` are either the flat
    mask array (XLA per-stage path) or the tuple of per-pass arrays from
    :func:`~bfs_tpu.ops.relay_pallas.prepare_pass_masks` (fused TPU path) —
    chosen per network by :func:`_net_uses_pallas`.  With ``packed`` the
    carry is the fused-word PackedRelayState: the row-min emits RANKS and
    the state update is one lexicographic min (ops/relay.py
    apply_relay_candidates_packed) — the routing pipeline is identical.

    ``phase_sel`` is the per-phase kernel selection ``(rowmin,
    state_update)`` with values ``'xla'``/``'pallas'`` (ISSUE 7 tentpole
    b): the packed row-min and packed state-update each run their fused
    Pallas kernel when selected BY MEASUREMENT (RelayEngine
    phase_selection; profiling.probe_phase_kernels is the probe) —
    winners are picked per phase, not globally, and both flavors are
    bit-exact so the selection can never change a result."""
    (vr, vperm_size, vperm_table, out_classes, out_space, net_table,
     net_size, in_classes) = static
    from ..ops import relay as R

    vp_pallas = use_pallas and _net_uses_pallas(vperm_size)
    net_pallas = use_pallas and _net_uses_pallas(net_size)
    rowmin_pallas = bool(packed and phase_sel and phase_sel[0] == "pallas")
    update_pallas = bool(packed and phase_sel and phase_sel[1] == "pallas")
    if vp_pallas or net_pallas or rowmin_pallas or update_pallas:
        from ..ops import relay_pallas as RP

        vp_static = RP.pass_static(vperm_table, vperm_size) if vp_pallas else None
        net_static = RP.pass_static(net_table, net_size) if net_pallas else None

    def superstep(st, vperm_m, net_m, valid_words):
        fw = jnp.concatenate(
            [st.fwords, jnp.zeros((vperm_size - vr) // 32, jnp.uint32)]
        )
        if vp_pallas:
            y = RP.apply_benes_fused(fw, vperm_m, vp_static, vperm_size)
        else:
            y = R.apply_benes_std(fw, vperm_m, vperm_table, vperm_size)
        l2 = R.broadcast_l2(y, out_classes, net_size, out_space)
        if net_pallas:
            l1 = RP.apply_benes_fused(l2, net_m, net_static, net_size)
        else:
            l1 = R.apply_benes_std(l2, net_m, net_table, net_size)
        if packed:
            if rowmin_pallas:
                cand = RP.rowmin_ranks_pallas(
                    l1, valid_words, in_classes, vr
                )
            else:
                cand = R.rowmin_ranks(l1, valid_words, in_classes, vr)
            if update_pallas:
                return RP.apply_relay_candidates_packed_pallas(st, cand)
            return R.apply_relay_candidates_packed(st, cand)
        cand = R.rowmin_candidates(l1, valid_words, in_classes, vr)
        return R.apply_relay_candidates(st, cand)

    return superstep


def _net_uses_pallas(n: int) -> bool:
    from ..ops.relay_pallas import pallas_net_ok

    return pallas_net_ok(n)


def _extract_frontier_list(fwords: jax.Array, vr: int, bv: int) -> jax.Array:
    """Ascending list of set-bit element ids (standard packing), padded with
    ``vr``: int32[bv].

    ``jnp.nonzero`` over the vr-sized unpacked bools costs ~157 ms at s24 on
    the bench chip (XLA lowers it through a full sort — measured round 4);
    this word-level formulation — popcount + cumsum offsets, searchsorted
    owner word per output slot, 5-step binary-search bit-rank select inside
    the word — is ~3 ms and bit-identical (words ascend, bits within a word
    ascend == nonzero's element order)."""
    nw = fwords.shape[0]
    cnt = jax.lax.population_count(fwords).astype(jnp.int32)
    cs = jnp.cumsum(cnt)  # inclusive
    o = jnp.arange(bv, dtype=jnp.int32)
    w = jnp.searchsorted(cs, o, side="right").astype(jnp.int32)
    wc = jnp.clip(w, 0, nw - 1)
    prev = jnp.where(wc > 0, cs[jnp.maximum(wc - 1, 0)], 0)
    r = o - prev  # rank of the wanted bit within its word
    x = fwords[wc]
    pos = jnp.zeros_like(o)
    for k in (16, 8, 4, 2, 1):
        low = jax.lax.population_count(
            x & jnp.uint32((1 << k) - 1)
        ).astype(jnp.int32)
        go_high = r >= low
        r = jnp.where(go_high, r - low, r)
        x = jnp.where(go_high, x >> jnp.uint32(k), x)
        pos = pos + jnp.where(go_high, k, 0)
    return jnp.where(o < cs[-1], wc * 32 + pos, jnp.int32(vr))


def _sparse_superstep(st, adj_indptr, adj_dst, adj_slot, *, vr: int,
                      packed: bool = False):
    """Small-frontier superstep: gather the frontier's out-edges (budgeted
    static shapes), min-merge per destination by (dst, slot) sort, scatter
    the updates.  Bit-exact vs the dense path: slots ascend with original
    src id within a dst row, so min slot == canonical min-parent.

    With ``packed``, ``adj_slot`` carries per-edge within-row RANKS
    (RelayEngine ships the rank flavor of the adjacency — ranks ascend
    with slots within a row, so the (dst, rank) sort picks the same
    canonical winner) and the scatter writes fused ``level:6|rank:26``
    words into the packed carry."""
    from ..ops.relay import PackedRelayState, RelayState

    bv, be = sparse_budgets(vr, adj_dst.shape[0])
    flist = _extract_frontier_list(st.fwords, vr, bv)
    deg = adj_indptr[flist + 1] - adj_indptr[flist]  # 0 at the vr fill slot
    cum = jnp.cumsum(deg)
    starts = adj_indptr[flist]
    j = jnp.arange(be, dtype=jnp.int32)
    owner = jnp.searchsorted(cum, j, side="right").astype(jnp.int32)
    owner_c = jnp.clip(owner, 0, bv - 1)
    prev = jnp.where(owner_c > 0, cum[jnp.maximum(owner_c - 1, 0)], 0)
    eidx = starts[owner_c] + (j - prev)
    valid = j < cum[-1]
    eidx = jnp.where(valid, eidx, 0)
    dstv = adj_dst[eidx]
    slot = adj_slot[eidx]
    dk, sk = jax.lax.sort(
        (jnp.where(valid, dstv, jnp.int32(vr)), slot), num_keys=2
    )
    first = (
        jnp.concatenate([jnp.ones(1, bool), dk[1:] != dk[:-1]]) & (dk < vr)
    )
    if packed:
        from ..ops.packed import PACKED_SENTINEL, level_word

        unreached = st.packed[jnp.clip(dk, 0, vr - 1)] == PACKED_SENTINEL
    else:
        unreached = st.dist[jnp.clip(dk, 0, vr - 1)] == INT32_MAX
    upd = first & unreached
    tgt = jnp.where(upd, dk, jnp.int32(vr))  # vr = out of bounds -> dropped
    new_level = st.level + 1
    fwords = (
        jnp.zeros_like(st.fwords)
        .at[tgt >> 5]
        .add(jnp.uint32(1) << (tgt & 31).astype(jnp.uint32), mode="drop")
    )
    if packed:
        word = sk.astype(jnp.uint32) | level_word(new_level)
        pk = st.packed.at[tgt].set(word, mode="drop")
        return PackedRelayState(pk, fwords, new_level, upd.any())
    dist = st.dist.at[tgt].set(new_level, mode="drop")
    parent = st.parent.at[tgt].set(sk, mode="drop")
    return RelayState(dist, parent, fwords, new_level, upd.any())


def _frontier_stats(st, outdeg, vr: int):
    """(frontier vertex count, frontier out-edge count) — observability
    quantities, cheap word ops on the packed frontier.  ``fedges`` is an
    int32 sum: exact below 2^31 frontier out-edges, wrapped above — fine
    for reporting, NOT for dispatch (use :func:`_take_sparse`)."""
    from ..ops import relay as R

    fsize = jax.lax.population_count(st.fwords).sum(dtype=jnp.int32)
    bools = R.unpack_std(st.fwords, vr)
    fedges = jnp.where(bools != 0, outdeg, 0).sum(dtype=jnp.int32)
    return fsize, fedges


def _take_sparse(st, outdeg, vr: int, num_adj_entries: int):
    """THE sparse-path dispatch predicate (single definition — the fused
    loop's ``small()`` and the stepped ``step_dispatch`` both call this):
    frontier fits the CLAMPED budgets (:func:`sparse_budgets` — the same
    derivation the sparse superstep's static shapes use, so dispatch and
    capacity can never disagree).  Overflow-safe without int64:
    per-vertex degrees are capped at be+1 before the uint32 sum, so any
    frontier small enough to pass the vertex bound sums to at most
    bv*(be+1) < 2^32 — a >2^31-edge frontier on a scale-27+ graph cannot
    wrap into a spuriously-small ``fedges`` and silently overrun the
    sparse path's static edge budget."""
    from ..ops import relay as R

    bv, be = sparse_budgets(vr, num_adj_entries)
    fsize = jax.lax.population_count(st.fwords).sum(dtype=jnp.int32)
    bools = R.unpack_std(st.fwords, vr)
    capped = jnp.minimum(outdeg, be + 1).astype(jnp.uint32)
    fedges = jnp.where(bools != 0, capped, jnp.uint32(0)).sum(
        dtype=jnp.uint32
    )
    return (fsize <= bv) & (fedges <= jnp.uint32(be))


# bfs_tpu: hot traced
def _frontier_masses_words(st, outdeg, vr: int):
    """(occupancy int32, out-edge mass float32) of a word-packed frontier
    — the Beamer predicate's inputs, delegated to the ONE shared
    definition (models/direction.frontier_masses_words) the sharded relay
    program also compiles, so mesh and single-chip schedules see
    identical masses."""
    from .direction import frontier_masses_words

    return frontier_masses_words(st.fwords, outdeg, vr)


def _mxu_body_fn(expansion: tuple, packed: bool):
    """The mxu dense-superstep closure for the fused/segment programs:
    ``expansion = ('mxu', geo, use_kernel)`` (ops/relay_mxu.mxu_static
    geometry + the kernel-vs-twin choice, both static so they live in the
    program cache key).  The closure takes the TILE-OPERAND tuple in the
    slot the gather body reads its vperm masks from — one program
    signature, two arms, byte-identical gather traces."""
    from ..ops import relay_mxu as RM

    _, geo, use_kernel = expansion
    step = RM.mxu_superstep_packed if packed else RM.mxu_superstep

    def superstep(st, tile_ops, net_m, valid_words):
        return step(st, tile_ops, geo, use_kernel)

    return superstep


def _mxu_finish(out):
    """The mxu once-per-run decode: the packed parent field IS the
    original source id (the expansion's candidate value), so the finish
    is two field extracts — no rank->slot reconstruction."""
    from ..ops import relay as R
    from ..ops.packed import packed_dist, packed_parent

    return R.RelayState(
        packed_dist(out.packed), packed_parent(out.packed), out.fwords,
        out.level, out.changed,
    )


@functools.lru_cache(maxsize=16)
def _relay_fused_program(static, sparse: bool, use_pallas: bool,
                         packed: bool = False, telemetry: bool = False,
                         direction: tuple | None = None,
                         phase_sel: tuple | None = None,
                         num_real: int | None = None,
                         expansion: tuple = ("gather",)):
    """Jitted relay BFS loop (v4), cached per static layout shape.

    With ``sparse``, small frontiers (under the SPARSE_BV/BE budgets) take
    the gather path — the TPU analogue of direction-optimizing BFS's
    top-down phase.  The dispatch is structured as nested while-loops
    rather than a per-superstep ``lax.cond``:

        sparse_phase; while live: { dense; sparse_phase }

    where ``sparse_phase`` is itself a while-loop draining consecutive
    small supersteps.  This runs sparse on EXACTLY the supersteps the old
    per-superstep predicate chose (the dense step only executes when the
    sparse phase exited on a big live frontier, and the outer loop exits
    directly when it converged), with no ``lax.cond`` in the body.
    Measured effect (docs/ARCHITECTURE.md §8): removing the cond did NOT
    rescue the hybrid at s24 — the sparse superstep's ~25 ms is intrinsic
    gather work at the TPU's scalar-gather rate, so hybrid-on still
    measured 149 vs 103 ms/search — but the structure is strictly less
    overhead wherever the hybrid IS right (CPU backends, high-diameter
    tails).

    With ``telemetry`` (static), the carry additionally holds the
    per-level accumulators (obs/telemetry.py): frontier occupancy
    (int32[TEL_SLOTS]), frontier out-edges (float32 — ``outdeg`` is
    already a loop operand), and the DIRECTION schedule (DIR_PUSH /
    DIR_PULL per settled level), recorded after every superstep and
    returned alongside the finished state for ONE pull at loop exit.

    ``direction`` (ISSUE 7 tentpole a) selects the superstep body per
    level:

      * ``None`` — legacy: the nested-while hybrid when ``sparse``
        (budget-predicate dispatch), dense-only otherwise.
      * ``('pull', a, b)`` — dense relay every superstep.
      * ``('push', a, b)`` — the legacy hybrid structure (sparse gather
        whenever the static budgets allow — the frontier/element
        preference).
      * ``('auto', alpha, beta)`` — Beamer-style per-superstep
        ``lax.cond``: push (sparse gather) on sparse frontiers, pull
        (dense relay) once the frontier's out-edge mass crosses the
        unexplored mass (``m_f*alpha > m_u``) or its occupancy crosses
        ``n*beta`` (models/direction.py take_pull — the single predicate
        definition).  The unexplored mass rides the carry (one masked
        out-degree sum per superstep — the same sum the predicate
        needs), so the decision is entirely on-device: no host sync, no
        retrace, and the schedule is a pure function of the graph +
        thresholds (a resumed bench replays it bit-identically).
        Push additionally requires the sparse path's static budgets
        (SPARSE_BV/BE) — the gather superstep's shapes are compiled.
    """
    (vr, vperm_size, vperm_table, out_classes, out_space, net_table,
     net_size, in_classes) = static
    from ..ops import relay as R
    from ..ops.packed import packed_cap

    mxu = expansion[0] == "mxu"
    if mxu:
        # The MXU expansion arm (ISSUE 15): the dense (pull) body is the
        # tiled masked matmul of ops/relay_mxu.py; parent VALUES through
        # the whole carry are ORIGINAL source ids (the sparse push body
        # ships the key-flavor adjacency), so the finish decodes fields
        # instead of reconstructing slots.  Everything else — predicates,
        # budgets, telemetry, caps — is the gather program verbatim, so
        # the two arms' schedules are bit-identical by construction.
        superstep = _mxu_body_fn(expansion, packed)
    else:
        superstep = _superstep_fn(static, use_pallas, packed, phase_sel)
    mode = direction[0] if direction is not None else None
    # Static Python floats, hoisted OUT of the jitted program body (the
    # float() casts below run at trace-build time on config values, never
    # on device values).
    dir_alpha = float(direction[1]) if direction is not None else 0.0
    dir_beta = float(direction[2]) if direction is not None else 0.0
    if mode == "pull" or (mode in ("auto", "push") and not sparse):
        # Dense-only body regardless of the hybrid operands.  A 'push'
        # request without the sparse operands is rejected at the ENGINE
        # boundary; this normalization keeps the schedule honest (all
        # supersteps recorded as the pull body they actually run) for
        # any direct program caller.
        sparse = False
        mode = "pull"

    @functools.partial(jax.jit, static_argnames=("max_levels",))
    @traced("bfs.relay_fused")
    def fused(source_new, vperm_masks, net_masks, valid_words,
              adj_indptr, adj_dst, adj_slot, outdeg, max_levels):
        if packed:
            cap = packed_cap(max_levels)
            state = R.init_packed_relay_state(vr, source_new)
        else:
            cap = max_levels
            state = R.init_relay_state(vr, source_new)

        def live(st):
            return st.changed & (st.level < cap)

        def dense(st):
            return superstep(st, vperm_masks, net_masks, valid_words)

        def finish(out):
            # The ONCE-PER-RUN unpack (tentpole contract): the returned
            # state is the same RelayState (slot parents) either way, so
            # every downstream consumer is unchanged.  The mxu arm's
            # parent field is the ORIGINAL id (key), decoded directly.
            if not packed:
                return out
            if mxu:
                return _mxu_finish(out)
            dist, parent = R.unpack_relay_packed(out.packed, in_classes, vr)
            return R.RelayState(
                dist, parent, out.fwords, out.level, out.changed
            )

        if telemetry:
            from ..obs import telemetry as T
            from ..ops.relax import INT32_MAX

            # In-loop carry: the popcount occupancy accumulator plus the
            # int32[TEL_SLOTS] direction schedule (one .set per
            # superstep).  The out-edge curve is derived in one pass at
            # loop exit from the final levels — a per-superstep masked
            # outdeg sum cost ~25% of a CPU superstep, violating the <2%
            # telemetry budget (the AUTO body pays that sum as its
            # dispatch predicate, which the schedule then records).
            acc0 = T.init_level_acc()
            dir0 = T.init_dir_acc()

            def rec(fv, st):
                return T.record_frontier_words(fv, st.fwords, st.level)

            def finish_tel(out, fv, dirs):
                st = finish(out)
                fe = T.edge_curve_from_levels(
                    st.dist, outdeg, st.dist == INT32_MAX
                )
                return st, (fv, fe, dirs)

        if not sparse:
            # Dense-only: every superstep is a pull (relay) superstep.
            if telemetry:

                def dense_t(c):
                    st, fv, dirs = c
                    st2 = dense(st)
                    return (
                        st2,
                        rec(fv, st2),
                        T.record_direction(dirs, st2.level, T.DIR_PULL),
                    )

                out, fv, dirs = jax.lax.while_loop(
                    lambda cc: live(cc[0]), dense_t, (state, acc0, dir0)
                )
                return finish_tel(out, fv, dirs)
            return finish(jax.lax.while_loop(live, dense, state))

        def small(st):
            return _take_sparse(st, outdeg, vr, adj_dst.shape[0])

        def sparse_step(st):
            return _sparse_superstep(
                st, adj_indptr, adj_dst, adj_slot, vr=vr, packed=packed
            )

        if mode == "auto":
            # Beamer-style per-superstep dispatch: ONE lax.cond on the
            # on-device masses.  The unexplored-mass carry ``mu`` holds
            # the out-edge mass of every vertex not settled before the
            # current frontier (mu - fe = the true unexplored mass m_u),
            # so the predicate costs exactly one masked out-degree sum
            # per superstep and nothing ever syncs to the host.
            from .direction import take_pull

            alpha, beta = dir_alpha, dir_beta
            mu0 = outdeg.astype(jnp.float32).sum()
            # The occupancy threshold keys on the REAL vertex count when
            # the caller supplies it (RelayEngine does): the padded vr is
            # layout-dependent, and the sharded relay program — whose
            # padded space differs — must compile the SAME predicate so
            # mesh and single-chip schedules are bit-identical (ISSUE 11
            # mesh-parity; direction.py's push/pull programs already use
            # real V).
            v_thresh = vr if num_real is None else num_real

            def decide(st, mu, prev_pull):
                fsize, fe = _frontier_masses_words(st, outdeg, vr)
                # Clamped: float32 rounding must not let the tail's
                # unexplored mass dip negative (it would satisfy any
                # pull threshold).
                m_u = jnp.maximum(mu - fe, 0.0)
                bv, be = sparse_budgets(vr, adj_dst.shape[0])
                budget_ok = (fsize <= bv) & (fe <= jnp.float32(be))
                use_pull = (
                    take_pull(
                        prev_pull, fsize, fe, m_u, v_thresh, alpha, beta
                    )
                    | ~budget_ok
                )
                return use_pull, m_u

            if telemetry:

                def body_ta(c):
                    st, mu, prev, fv, dirs = c
                    use_pull, m_u = decide(st, mu, prev)
                    st2 = jax.lax.cond(use_pull, dense, sparse_step, st)
                    code = jnp.where(
                        use_pull, jnp.int32(T.DIR_PULL), jnp.int32(T.DIR_PUSH)
                    )
                    return (
                        st2, m_u, use_pull, rec(fv, st2),
                        T.record_direction(dirs, st2.level, code),
                    )

                out, _, _, fv, dirs = jax.lax.while_loop(
                    lambda cc: live(cc[0]), body_ta,
                    (state, mu0, jnp.bool_(False), acc0, dir0),
                )
                return finish_tel(out, fv, dirs)

            def body_a(c):
                st, mu, prev = c
                use_pull, m_u = decide(st, mu, prev)
                st2 = jax.lax.cond(use_pull, dense, sparse_step, st)
                return st2, m_u, use_pull

            out, _, _ = jax.lax.while_loop(
                lambda cc: live(cc[0]), body_a,
                (state, mu0, jnp.bool_(False)),
            )
            return finish(out)

        # mode in (None, 'push'): the legacy nested-while hybrid — sparse
        # (push) whenever the static budgets allow, dense otherwise.
        def sparse_phase(st):
            return jax.lax.while_loop(
                lambda s: live(s) & small(s), sparse_step, st
            )

        def body(st):
            return sparse_phase(dense(st))

        if telemetry:
            # Same nested-while structure, carry extended with the accs:
            # dense and sparse supersteps both record, so the curve and
            # the schedule cover every level regardless of which path
            # settled it.
            def sparse_step_t(c):
                st, fv, dirs = c
                st2 = sparse_step(st)
                return (
                    st2, rec(fv, st2),
                    T.record_direction(dirs, st2.level, T.DIR_PUSH),
                )

            def sparse_phase_t(c):
                return jax.lax.while_loop(
                    lambda cc: live(cc[0]) & small(cc[0]), sparse_step_t, c
                )

            def dense_t(c):
                st, fv, dirs = c
                st2 = dense(st)
                return (
                    st2, rec(fv, st2),
                    T.record_direction(dirs, st2.level, T.DIR_PULL),
                )

            def body_t(c):
                return sparse_phase_t(dense_t(c))

            out, fv, dirs = jax.lax.while_loop(
                lambda cc: live(cc[0]), body_t,
                sparse_phase_t((state, acc0, dir0)),
            )
            return finish_tel(out, fv, dirs)

        return finish(jax.lax.while_loop(live, body, sparse_phase(state)))

    return fused


@functools.lru_cache(maxsize=16)
def _relay_segment_program(static, sparse: bool, use_pallas: bool,
                           packed: bool = False, telemetry: bool = False,
                           direction: tuple | None = None,
                           phase_sel: tuple | None = None,
                           num_real: int | None = None,
                           expansion: tuple = ("gather",)):
    """ONE bounded segment of the relay loop (ISSUE 14) — the
    checkpointable twin of :func:`_relay_fused_program`.

    The carry is a dict of every loop-state leaf: the packed state word
    (or dist/parent), the frontier words, the direction hysteresis pair
    ``(mu, prev)`` in auto mode, and the telemetry accumulators — so a
    snapshot of the carry at a segment boundary IS a complete resume
    point, and a resumed run replays the direction schedule
    bit-identically (the hysteresis state travels with the checkpoint).
    ``seg_end`` is a TRACED operand: advancing it costs no retrace.

    Body dispatch is per-superstep (one ``lax.cond`` on the same
    predicates the fused program's nested-while / auto structures
    evaluate), so a sequence of segments runs EXACTLY the superstep
    bodies the fused program would, in the same order — results, the
    schedule and the telemetry curves are bit-identical for any
    segmentation (tests/test_superstep_ckpt.py pins this against the
    fused program).  The input carry is DONATED (consumed per segment;
    callers reassign), halving the segment call's peak state HBM
    (IR001).  This is a NEW lint-registered program; the fused off-arm
    programs are untouched (``BFS_TPU_CKPT=off`` byte-identity)."""
    (vr, vperm_size, vperm_table, out_classes, out_space, net_table,
     net_size, in_classes) = static
    from ..ops import relay as R
    from ..ops.packed import packed_cap

    if expansion[0] == "mxu":
        # Same arm substitution as the fused program: mxu pull body,
        # key-flavor candidates — the segment boundary semantics are
        # untouched, so kill/resume bit-identity carries to the new arm.
        superstep = _mxu_body_fn(expansion, packed)
    else:
        superstep = _superstep_fn(static, use_pallas, packed, phase_sel)
    mode = direction[0] if direction is not None else None
    dir_alpha = float(direction[1]) if direction is not None else 0.0
    dir_beta = float(direction[2]) if direction is not None else 0.0
    if mode == "pull" or (mode in ("auto", "push") and not sparse):
        # Same normalization as the fused program: no sparse operands
        # means the dense relay is the only body.
        sparse = False
        mode = "pull"
    v_thresh = vr if num_real is None else num_real

    @functools.partial(
        jax.jit, static_argnames=("max_levels",), donate_argnums=(0,)
    )
    @traced("bfs.relay_segment")
    def segment(carry, seg_end, vperm_masks, net_masks, valid_words,
                adj_indptr, adj_dst, adj_slot, outdeg, max_levels):
        cap = packed_cap(max_levels) if packed else max_levels
        if telemetry:
            from ..obs import telemetry as T

        def live(c):
            return (
                c["changed"] & (c["level"] < cap) & (c["level"] < seg_end)
            )

        def mk_state(c):
            if packed:
                return R.PackedRelayState(
                    c["pk"], c["fw"], c["level"], c["changed"]
                )
            return R.RelayState(
                c["dist"], c["parent"], c["fw"], c["level"], c["changed"]
            )

        def dense(st):
            return superstep(st, vperm_masks, net_masks, valid_words)

        def sparse_step(st):
            return _sparse_superstep(
                st, adj_indptr, adj_dst, adj_slot, vr=vr, packed=packed
            )

        def body(c):
            st = mk_state(c)
            use_pull = None
            if mode == "auto":
                from .direction import take_pull

                fsize, fe = _frontier_masses_words(st, outdeg, vr)
                m_u = jnp.maximum(c["mu"] - fe, 0.0)
                bv, be = sparse_budgets(vr, adj_dst.shape[0])
                budget_ok = (fsize <= bv) & (fe <= jnp.float32(be))
                use_pull = (
                    take_pull(
                        c["prev"], fsize, fe, m_u, v_thresh, dir_alpha,
                        dir_beta,
                    )
                    | ~budget_ok
                )
            elif sparse:
                # The legacy hybrid's dispatch, per superstep: sparse
                # exactly when the fused nested-while's ``small()``
                # predicate holds — identical body sequence.
                use_pull = ~_take_sparse(st, outdeg, vr, adj_dst.shape[0])
            if use_pull is None:
                st2 = dense(st)
            else:
                st2 = jax.lax.cond(use_pull, dense, sparse_step, st)
            out = dict(c)
            if packed:
                out["pk"] = st2.packed
            else:
                out["dist"], out["parent"] = st2.dist, st2.parent
            out["fw"] = st2.fwords
            out["level"] = st2.level
            out["changed"] = st2.changed
            if mode == "auto":
                out["mu"] = m_u
                out["prev"] = use_pull
            if telemetry:
                out["occ"] = T.record_frontier_words(
                    c["occ"], st2.fwords, st2.level
                )
                if use_pull is None:
                    code = jnp.int32(T.DIR_PULL)
                else:
                    code = jnp.where(
                        use_pull, jnp.int32(T.DIR_PULL),
                        jnp.int32(T.DIR_PUSH),
                    )
                out["dirs"] = T.record_direction(c["dirs"], st2.level, code)
            return out

        return jax.lax.while_loop(live, body, carry)

    return segment


@functools.lru_cache(maxsize=16)
def _relay_segment_finish_program(in_classes: tuple, vr: int,
                                  mxu: bool = False):
    """Jitted once-per-run unpack for the segmented runner's TRUE loop
    exit (module-level cache — a per-call jit would retrace, RCD001).
    The mxu flavor decodes original-id parents instead of slots."""
    from ..ops import relay as R

    @jax.jit
    def fin(pk, fw, lv, ch):
        if mxu:
            from ..ops.packed import packed_dist, packed_parent

            return R.RelayState(
                packed_dist(pk), packed_parent(pk), fw, lv, ch
            )
        dist, parent = R.unpack_relay_packed(pk, in_classes, vr)
        return R.RelayState(dist, parent, fw, lv, ch)

    return fin


@functools.lru_cache(maxsize=8)
def _relay_elem_program(static, pt: int, groups: int, use_pallas: bool):
    """Element-major batched multi-source loop: 32 trees per uint32 element,
    one mask stream amortized over every tree (ops/relay_elem.py)."""
    (vr, vperm_size, vperm_table, out_classes, out_space, net_table,
     net_size, in_classes) = static
    from ..ops import relay_elem as RE

    plane_offsets, _ = RE.rank_plane_layout(in_classes)
    if use_pallas:
        from ..ops import relay_pallas as RP

        step = RP.elem_superstep_tpu_factory(
            static, plane_offsets, pt
        )
    else:

        def step(st, vperm_m, net_m, valid_words):
            return RE.elem_superstep(
                st,
                vperm_masks=vperm_m, vperm_table=vperm_table,
                vperm_size=vperm_size, out_classes=out_classes,
                net_masks=net_m, net_table=net_table, net_size=net_size,
                in_classes=in_classes, valid_words=valid_words, vr=vr,
                plane_offsets=plane_offsets, pt=pt,
            )

    @functools.partial(jax.jit, static_argnames=("max_levels",))
    @traced("bfs.relay_elem_fused")
    def fused(sources_new, vperm_m, net_m, valid_words, max_levels):
        state = RE.init_elem_state(vr, sources_new, pt)

        def cond(st):
            return st.changed & (st.level < max_levels)

        def body(st):
            return step(st, vperm_m, net_m, valid_words)

        return jax.lax.while_loop(cond, body, state)

    return fused


@functools.lru_cache(maxsize=8)
def _relay_multi_fused_program(static, use_pallas: bool,
                               packed: bool = False,
                               phase_sel: tuple | None = None,
                               expansion: tuple = ("gather",)):
    """Batched (multi-source) relay loop: ``vmap`` lifts the dense superstep
    over a leading sources axis while all trees share one lock-step
    ``while_loop`` (BASELINE.json config 5 semantics).  ``packed`` as in
    :func:`_relay_fused_program`: fused-word carry per tree, one unpack
    at loop exit, same RelayState return shape."""
    (vr, vperm_size, vperm_table, out_classes, out_space, net_table,
     net_size, in_classes) = static
    from ..ops import relay as R
    from ..ops.packed import packed_cap

    mxu = expansion[0] == "mxu"
    if mxu:
        # Batched arm: the XLA twin always (kernel-under-vmap is not a
        # shape Mosaic supports; the twin is bit-identical by the PAL005
        # contract, so the batch path can never diverge from it).
        superstep = _mxu_body_fn((expansion[0], expansion[1], False), packed)
    else:
        superstep = _superstep_fn(static, use_pallas, packed, phase_sel)

    @functools.partial(jax.jit, static_argnames=("max_levels",))
    @traced("bfs.relay_multi_fused")
    def fused(sources_new, vperm_masks, net_masks, valid_words, max_levels):
        if packed:
            cap = packed_cap(max_levels)
            per0 = jax.vmap(lambda s: R.init_packed_relay_state(vr, s))(
                sources_new
            )
            state = R.PackedRelayState(
                per0.packed, per0.fwords, jnp.int32(0), jnp.bool_(True)
            )

            def body(st):
                per = jax.vmap(
                    lambda pk, f: superstep(
                        R.PackedRelayState(pk, f, st.level, st.changed),
                        vperm_masks, net_masks, valid_words,
                    )
                )(st.packed, st.fwords)
                return R.PackedRelayState(
                    per.packed, per.fwords, st.level + 1, per.changed.any()
                )

            out = jax.lax.while_loop(
                lambda st: st.changed & (st.level < cap), body, state
            )
            if mxu:
                from ..ops.packed import packed_dist, packed_parent

                dist, parent = packed_dist(out.packed), packed_parent(
                    out.packed
                )
            else:
                dist, parent = jax.vmap(
                    lambda pk: R.unpack_relay_packed(pk, in_classes, vr)
                )(out.packed)
            return R.RelayState(
                dist, parent, out.fwords, out.level, out.changed
            )

        per0 = jax.vmap(lambda s: R.init_relay_state(vr, s))(sources_new)
        state = R.RelayState(
            per0.dist, per0.parent, per0.fwords, jnp.int32(0), jnp.bool_(True)
        )

        def cond(st):
            return st.changed & (st.level < max_levels)

        def body(st):
            per = jax.vmap(
                lambda d, p, f: superstep(
                    R.RelayState(d, p, f, st.level, st.changed),
                    vperm_masks, net_masks, valid_words,
                )
            )(st.dist, st.parent, st.fwords)
            return R.RelayState(
                per.dist, per.parent, per.fwords,
                st.level + 1, per.changed.any(),
            )

        return jax.lax.while_loop(cond, body, state)

    return fused


def compile_exe_cached(lowered, compiler_options):
    """Compile a lowered program, going through the on-disk EXECUTABLE
    cache when ``BFS_TPU_EXE_CACHE`` names a directory.

    Needed because jax's persistent compilation cache is inert under the
    axon remote-compile transport (verified: >5 s compiles write no
    entries and fresh processes recompile), and the remote service takes
    TENS OF MINUTES for the bench-scale fused programs — the direct cause
    of round 4's rc=124 driver capture.  The key is a hash of the lowered
    StableHLO + compiler options + platform version, so a code or backend
    change can never load a stale executable; a deserialization failure
    falls back to a fresh compile."""
    import hashlib
    import os
    import pickle

    cache_dir = knobs.raw("BFS_TPU_EXE_CACHE") or ""
    if not cache_dir or jax.default_backend() != "tpu":
        with obs_span("compile"):
            return lowered.compile(compiler_options=compiler_options)
    try:
        hlo = lowered.as_text().encode()
    except Exception:
        return lowered.compile(compiler_options=compiler_options)
    from jax._src import xla_bridge

    salt = (
        repr(sorted((compiler_options or {}).items()))
        + jax.__version__
        + getattr(xla_bridge.get_backend(), "platform_version", "")
    ).encode()
    digest = hashlib.sha256(hlo + salt).hexdigest()[:32]
    path = os.path.join(cache_dir, f"exe_{digest}.pkl")
    from ..utils.metrics import bump_artifact

    if os.path.exists(path):
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            print(
                f"[exe-cache] loading {os.path.basename(path)} "
                f"({os.path.getsize(path) >> 20} MB)...",
                file=sys.stderr, flush=True,
            )
            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
            compiled = deserialize_and_load(payload, in_tree, out_tree)
            print("[exe-cache] loaded", file=sys.stderr, flush=True)
            bump_artifact("exe_cache_hits")
            return compiled
        except Exception:
            logger.warning(
                "stale/corrupt executable cache %s; recompiling", path
            )
            try:
                os.remove(path)
            except OSError:
                pass
    bump_artifact("exe_cache_misses")
    with obs_span("compile", exe_cache="miss"):
        compiled = lowered.compile(compiler_options=compiler_options)
    try:
        from jax.experimental.serialize_executable import serialize

        payload, in_tree, out_tree = serialize(compiled)
        os.makedirs(cache_dir, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump((payload, in_tree, out_tree), f)
        os.replace(tmp, path)
    except Exception:
        logger.warning("could not serialize executable", exc_info=True)
    return compiled


def _probe_appliers(rg, compiler_options, loops: int = 16) -> dict:
    """Time BOTH Beneš appliers on the engine's own big net masks and pick
    the faster — ground truth, not a bandwidth model.

    Returns ``(results_dict, winner_net_masks)``: per-apply seconds for each
    applier, the implied mask-stream bandwidth (the masks are the
    irreducible per-superstep traffic), a dense-read bandwidth reference,
    and the actual per-measurement loop counts — plus the WINNER's
    device-resident mask buffers, which the engine keeps as its net operand
    so nothing is re-shipped through the tunnel after init.

    The probe runs against its own wall budget (``BFS_TPU_PROBE_BUDGET``
    seconds, default 600): in the bench chip's write-collapsed windows
    shipping the ~GB mask operands alone can take many minutes, and round
    4's driver capture timed out inside exactly this phase with zero
    output.  Order (VERDICT r5 weak #2): pallas masks ship + compile +
    warm first (a budget exit keeps its buffers), then the XLA reference
    arm is FULLY measured, then pallas' adaptive repeat loop — so the
    reference measurement can never be starved by the repeat loop.  Every
    result dict carries ``selection_basis``, and it is ALWAYS a
    measurement (VERDICT r5 item 8): a budget exit downgrades to coarse
    arms — one K-loop timing pair for pallas and, if the full XLA arm has
    not run yet, the per-stage applier timed on a ~100 MB stage PREFIX of
    the mask stream scaled by mask bytes — instead of ever shipping
    ``"selected by default"``.  Progress stamps go to stderr (the probe
    only runs on TPU backends).
    """
    import os
    import sys
    import time

    from ..ops import relay as R
    from ..ops import relay_pallas as RP

    t0_probe = time.perf_counter()
    probe_budget = knobs.get("BFS_TPU_PROBE_BUDGET")
    # BFS_TPU_PROBE_COARSE=1 (set by bench.py when the RUN is behind its
    # own budget) forces the coarse arms unconditionally: the full flat
    # mask ship + adaptive repeat loops never start, so the probe's cost
    # is bounded by the pallas warm + one K-loop pair + a ~100 MB prefix
    # regardless of what the probe's own clock says.
    coarse_forced = knobs.get("BFS_TPU_PROBE_COARSE")

    def _pstamp(msg):
        print(
            f"[probe +{time.perf_counter() - t0_probe:6.1f}s] {msg}",
            file=sys.stderr, flush=True,
        )

    def over_budget():
        return time.perf_counter() - t0_probe > probe_budget

    n = rg.net_size
    mask_bytes = int(rg.net_masks.nbytes)
    x0 = jnp.zeros(n // 32, jnp.uint32)
    k1 = jnp.int32(loops)

    def timed(compiled, *args):
        t0 = time.perf_counter()
        r = compiled(*args)
        # Sync via a VALUE read of ONE element (block_until_ready can
        # return early through the tunnel) — device-side slice first, so
        # the 256 MB write-probe carry is not shipped to the host per call.
        leaf = jax.tree_util.tree_leaves(r)[0]
        _ = int(np.asarray(jax.device_get(leaf.ravel()[:1]))[0])
        return time.perf_counter() - t0

    def per_iter(compiled, *args):
        """Time at K and 2K loop iterations; the DIFFERENCE cancels the
        constant tunnel/dispatch/sync overhead exactly (separately-measured
        sync floors over-subtract on small nets — verify, round 4).  K is a
        TRACED loop bound, so it adaptively doubles — no recompile — until
        the measurement holds >=0.4 s of device work, keeping the ~0.1 s
        round-trip variance out of the difference."""
        k = loops
        while True:
            t1 = min(timed(compiled, jnp.int32(k), *args) for _ in range(2))
            if t1 >= 0.8 or k >= 4096:
                break
            k *= 2
        t2 = min(timed(compiled, jnp.int32(2 * k), *args) for _ in range(2))
        if t2 - t1 < 0.25 * t1 and k < 4096:
            # Difference still noise-level (t1 was mostly dispatch/sync, not
            # work — seen at s25 where 16 applies ~ the 0.4s gate): double
            # once more so the work term dominates.
            k *= 2
            t1, t2 = t2, min(
                timed(compiled, jnp.int32(2 * k), *args) for _ in range(2)
            )
        return max(t2 - t1, 1e-7) / k, k

    results = {}

    # --- fused Pallas passes FIRST (the winner of every recorded capture:
    # a budget exit keeps its buffers and never ships the XLA operand) -----
    _pstamp(f"preparing + shipping pallas pass masks ({mask_bytes >> 20} MB)...")
    net_static = RP.pass_static(rg.net_table, n)
    prepared = tuple(
        jnp.asarray(a)
        for a in RP.prepare_pass_masks(rg.net_masks, rg.net_table, n)
    )

    def loop_pallas(k, x, *m):
        def body(i, x):
            return RP.apply_benes_fused(x, m, net_static, n) ^ (x & jnp.uint32(1))

        return jax.lax.fori_loop(0, k, body, x)

    c_pal = compile_exe_cached(
        jax.jit(loop_pallas).lower(k1, x0, *prepared), compiler_options
    )
    _pstamp("pallas compiled; warming...")
    timed(c_pal, k1, x0, *prepared)  # warm
    results["net_mask_bytes"] = mask_bytes

    def coarse_pallas():
        """One K / 2K timing pair on the already-warm pallas loop — the
        first rung of per_iter without the adaptive doubling.  The
        difference cancels the tunnel sync, so this is a real (if noisy)
        measurement, never a default."""
        t1 = min(timed(c_pal, k1, x0, *prepared) for _ in range(2))
        t2 = min(
            timed(c_pal, jnp.int32(2 * loops), x0, *prepared)
            for _ in range(2)
        )
        return max(t2 - t1, 1e-7) / loops

    def xla_prefix_estimate(target_mb: float = 100.0):
        """Behind-budget XLA arm (VERDICT r5 item 8): time the per-stage
        applier on the longest STAGE PREFIX under ~target_mb of stored
        masks (stage storage is contiguous from offset 0, so the prefix
        slice is exact) and scale by total/prefix mask bytes — the
        applier is mask-stream-bound, so bytes are the honest scaling
        axis.  ~100 MB ships in seconds even through a degraded tunnel,
        vs the multi-GB full-stream arm the old path skipped entirely."""
        limit_words = int(target_mb * (1 << 20) / 4)
        sub, cum = [], 0
        for st in rg.net_table:
            if sub and cum + st.nwords > limit_words:
                break
            sub.append(st)
            cum += st.nwords
        sub_table = tuple(sub)
        _pstamp(
            f"xla prefix arm: {len(sub_table)} stages, "
            f"{cum * 4 >> 20} MB of masks..."
        )
        flat_prefix = jnp.asarray(rg.net_masks[:cum])

        def loop_prefix(k, x, m):
            def body(i, x):
                return R.apply_benes_std(x, m, sub_table, n) ^ (
                    x & jnp.uint32(1)
                )

            return jax.lax.fori_loop(0, k, body, x)

        c_pre = compile_exe_cached(
            jax.jit(loop_prefix).lower(k1, x0, flat_prefix),
            compiler_options,
        )
        timed(c_pre, k1, x0, flat_prefix)  # warm
        t1 = min(timed(c_pre, k1, x0, flat_prefix) for _ in range(2))
        t2 = min(
            timed(c_pre, jnp.int32(2 * loops), x0, flat_prefix)
            for _ in range(2)
        )
        t_prefix = max(t2 - t1, 1e-7) / loops
        scale_by = mask_bytes / max(cum * 4, 1)
        info = {
            "prefix_mb": cum * 4 / (1 << 20),
            "prefix_stages": len(sub_table),
            "prefix_apply_seconds": t_prefix,
            "scaled_by_mask_bytes": scale_by,
        }
        return t_prefix * scale_by, info

    if over_budget() or coarse_forced:
        # Behind budget (or coarse mode forced): BOTH arms still get
        # measured — pallas as one coarse K-loop pair, the XLA arm on a
        # subsampled mask prefix — so the selection is a comparison,
        # never a default (VERDICT r5 item 8: no capture ships "selected
        # by default").
        _pstamp(
            "coarse probe arms (K-loop pallas + subsampled xla prefix)"
            + (" [forced]" if coarse_forced else " [budget exhausted]")
        )
        t_pal = coarse_pallas()
        results["pallas_net_apply_seconds"] = t_pal
        results["pallas_mask_stream_gbs"] = mask_bytes / t_pal / 1e9
        t_xla_est, pre = xla_prefix_estimate()
        results["xla_net_apply_seconds"] = t_xla_est
        results["xla_prefix_probe"] = pre
        results["selected"] = "pallas" if t_pal <= t_xla_est else "xla"
        results["selection_basis"] = "measured (coarse)"
        results["note"] = (
            "probe budget exhausted: pallas timed with one K-loop pair, "
            "xla arm timed on a stage prefix and scaled by mask bytes — "
            "a comparison, not a default"
        )
        _pstamp(
            f"coarse: pallas {t_pal * 1e3:.1f} ms vs xla(est) "
            f"{t_xla_est * 1e3:.1f} ms -> {results['selected']}"
        )
        if results["selected"] == "pallas":
            return results, prepared
        return results, jnp.asarray(rg.net_masks)

    # --- XLA reference arm FIRST (VERDICT r5 weak #2): it is measured
    # before the pallas adaptive repeat loop can exhaust the probe budget,
    # so a budget exit still leaves a real reference number in the capture
    # instead of a default masquerading as a measurement. -------------------
    _pstamp("shipping flat masks for the xla path...")
    flat = jnp.asarray(rg.net_masks)

    def loop_xla(k, x, m):
        def body(i, x):
            return R.apply_benes_std(x, m, rg.net_table, n) ^ (x & jnp.uint32(1))

        return jax.lax.fori_loop(0, k, body, x)

    c_xla = compile_exe_cached(
        jax.jit(loop_xla).lower(k1, x0, flat), compiler_options
    )
    timed(c_xla, k1, x0, flat)  # warm
    t_xla, k_xla = per_iter(c_xla, x0, flat)
    results["xla_net_apply_seconds"] = t_xla
    results["xla_mask_stream_gbs"] = mask_bytes / t_xla / 1e9
    _pstamp(f"xla: {t_xla * 1e3:.1f} ms/apply")

    if over_budget():
        # The XLA arm is fully measured; give pallas a coarse K-loop pair
        # so the selection is still a comparison of two measurements.
        _pstamp(
            "probe budget exhausted before the pallas repeat loop; "
            "coarse pallas measurement instead of a default"
        )
        t_pal = coarse_pallas()
        results["pallas_net_apply_seconds"] = t_pal
        results["pallas_mask_stream_gbs"] = mask_bytes / t_pal / 1e9
        results["probe_loops"] = {"xla": k_xla}
        results["selected"] = "pallas" if t_pal <= t_xla else "xla"
        results["selection_basis"] = "measured (coarse pallas)"
        results["note"] = (
            "probe budget exhausted after the xla measurement; pallas "
            "timed with one coarse K-loop pair — a comparison, not a "
            "default"
        )
        _pstamp(
            f"coarse: pallas {t_pal * 1e3:.1f} ms vs xla "
            f"{t_xla * 1e3:.1f} ms -> {results['selected']}"
        )
        return results, (prepared if results["selected"] == "pallas" else flat)

    # --- pallas repeat loop (the adaptive-doubling measurement) ------------
    t_pal, k_pal = per_iter(c_pal, x0, *prepared)
    results["pallas_net_apply_seconds"] = t_pal
    results["pallas_mask_stream_gbs"] = mask_bytes / t_pal / 1e9
    _pstamp(f"pallas: {t_pal * 1e3:.1f} ms/apply")
    results["selected"] = "pallas" if t_pal <= t_xla else "xla"
    results["selection_basis"] = "measured"
    winner_net = prepared if results["selected"] == "pallas" else flat

    if over_budget():
        _pstamp("probe budget exhausted; skipping bandwidth references")
        results["probe_loops"] = {"xla": k_xla, "pallas": k_pal}
        results["note"] = (
            "probe budget exhausted after the applier measurements; "
            "bandwidth references skipped"
        )
        return results, winner_net

    _pstamp("bandwidth references (read, then write)...")
    # Dense-read reference over the same bytes; the carry feeds an XOR (not
    # an addend — sum(m + acc) factors to sum(m) + N*acc and gets hoisted)
    # so XLA must re-read the array every iteration.
    def loop_read(k, m):
        def body(i, acc):
            return acc ^ (m ^ acc).sum(dtype=jnp.uint32)

        return jax.lax.fori_loop(0, k, body, jnp.uint32(1))

    c_read = compile_exe_cached(
        jax.jit(loop_read).lower(k1, flat), compiler_options
    )
    timed(c_read, k1, flat)
    t_read, k_read = per_iter(c_read, flat)
    results["dense_read_gbs"] = mask_bytes / t_read / 1e9

    # Write-bandwidth reference: the chip's HBM WRITE path collapses by
    # orders of magnitude in some windows while reads stay fast (measured
    # round 4: plain elementwise read+write at ~1 GB/s in the same minutes
    # a read-only stream held 33-274 GB/s).  The engine's superstep writes
    # ~170-300 MB (pass outputs + dist/parent/fwords updates), so a capture
    # taken in such a window is write-bound regardless of applier; this
    # field stamps each capture with the window's write health.
    # Must exceed physical VMEM (~128 MB on v5e) so the loop carry cannot
    # stay resident — a VMEM-resident carry writes no HBM at all and
    # measured ~2.9 TB/s (the inflated rw figure in the first capture,
    # taken with a 16 MB buffer).
    wb = jnp.zeros(1 << 26, jnp.uint32)  # 256 MB

    def loop_write(k, w):
        def body(i, w):
            # index-dependent so the iterated xor cannot constant-fold away
            return w ^ (i.astype(jnp.uint32) | jnp.uint32(1))

        return jax.lax.fori_loop(0, k, body, w)

    c_write = compile_exe_cached(
        jax.jit(loop_write).lower(k1, wb), compiler_options
    )
    timed(c_write, k1, wb)
    t_write, k_write = per_iter(c_write, wb)
    results["rw_stream_gbs"] = 2 * wb.nbytes / t_write / 1e9

    # ACTUAL loop counts each measurement settled at (adaptive doubling).
    results["probe_loops"] = {"xla": k_xla, "read": k_read, "write": k_write, "pallas": k_pal}
    _pstamp(
        f"done: selected={results['selected']} "
        f"read={results['dense_read_gbs']:.0f} GB/s "
        f"rw={results['rw_stream_gbs']:.0f} GB/s"
    )
    # Hand the winner's device-resident mask buffers back so init does not
    # re-ship ~GBs through the tunnel; the loser's buffers are freed when
    # this frame drops.
    return results, winner_net


class RelayEngine:
    """Device-resident relay layout + fused BFS loop (engine='relay').

    Build once per graph; call :meth:`run` per source, or
    :meth:`run_many_device` for Graph500-style chained timing.  The whole
    superstep loop is one XLA program of dense ops — see graph/relay.py.
    ``sparse_hybrid`` enables the small-frontier gather path in the loop.

    ``applier`` selects how the Beneš networks are applied each superstep:
    ``'pallas'`` (3 fused passes, masks DMA-streamed in-kernel), ``'xla'``
    (one roll-form kernel per stage), or ``'auto'`` (default) — on TPU
    backends both appliers are TIMED at engine init on the real mask arrays
    and the faster one is kept.  The bench device's effective bandwidth is
    time-varying and path-dependent (XLA dense reads vs in-kernel DMA have
    been observed 20x apart in the same minute — docs/ARCHITECTURE.md §1),
    so a static default can be arbitrarily wrong; measurement at init is the
    only reliable selector (VERDICT round 3, weak #1).  The probe outcome is
    recorded in :attr:`applier_probe`.  ``BFS_TPU_PALLAS=0/1`` still forces
    a path, bypassing the probe.
    """

    def __init__(self, graph, *, sparse_hybrid: bool = True,
                 applier: str = "auto", direction: str | None = None,
                 expansion: str | None = None,
                 tiles_mode: str | None = None):
        from ..graph.relay import RelayGraph, build_relay_graph, valid_slot_words

        rg = graph if isinstance(graph, RelayGraph) else build_relay_graph(graph)
        self.relay_graph = rg
        self.sparse_hybrid = sparse_hybrid
        if applier not in ("auto", "pallas", "xla"):
            raise ValueError(
                f"unknown applier {applier!r}; use 'auto', 'pallas' or 'xla'"
            )
        # Direction-optimizing superstep policy (ISSUE 7 tentpole a):
        # push|pull|auto with Beamer alpha/beta thresholds, env-resolved
        # (BFS_TPU_DIRECTION / _ALPHA / _BETA) unless forced by argument.
        # Frozen per engine — every program and executable key carries it,
        # so auto-switching (an in-program lax.cond) never retraces and a
        # knob flip can never reuse a stale compiled program.
        from .direction import resolve_direction

        self.direction = resolve_direction(direction)
        if self.direction.mode == "push" and not sparse_hybrid:
            # Same contract as the sharded engine: without the sparse
            # adjacency there is no push body — running dense while the
            # schedule claims 'push' would ship a lying capture.
            raise ValueError(
                "direction='push' needs sparse_hybrid=True (the push body "
                "is the sparse gather superstep); use 'pull' or 'auto'"
            )
        # Packed fused-word state (ops/packed.py): on by default whenever
        # every parent rank fits the 26-bit field; BFS_TPU_PACKED=0/1
        # forces.  Searches deeper than PACKED_MAX_LEVELS detect the cap
        # exit and re-run on the unpacked path (run / run_multi).
        from ..ops.packed import packed_rank_fits, resolve_packed

        self.packed = resolve_packed(packed_rank_fits(rg.in_classes))
        if self.packed and not packed_rank_fits(rg.in_classes):
            raise ValueError(
                "BFS_TPU_PACKED=1 forced but a degree-class width exceeds "
                "the 26-bit parent-rank field"
            )
        # Expansion arm (ISSUE 15): gather (the Beneš relay pipeline) vs
        # mxu (the tiled masked matmul of ops/relay_mxu.py), selected like
        # every other arm here — forced by knob or picked by measurement
        # (probe_phase_kernels' expansion phase on TPU backends), never by
        # a static default.  Forced 'mxu' resolves NOW (it constrains the
        # packed carry: the parent field must hold ORIGINAL ids); 'auto'
        # on a TPU backend defers to the phase probe below.
        self.adj_tiles = None
        self._mxu_dev = None
        self.expansion_probe = None
        self._resolve_expansion_static(expansion)
        # Tile residency (ISSUE 18): resident keeps the whole tile layout
        # in HBM (the PR 15 contract); stream pages it per column
        # superblock from the host store under BFS_TPU_STREAM_CACHE_GB;
        # auto streams exactly when the layout outgrows the cache budget.
        # Resolved and frozen now, like direction/expansion — routing
        # happens per run (run / run_segmented), not per program.
        from ..ops.relay_mxu import resolve_tiles_mode

        self.tiles_mode = resolve_tiles_mode(tiles_mode)
        self.applier_probe = None
        self._probe_net_arg = None

        def _istamp(msg):
            # Init-progress stamps on TPU only: at bench scale the mask
            # shipping below moves multi-GB through the tunnel and can take
            # minutes in the chip's write-collapsed windows — exactly where
            # round 4's driver capture died silently (VERDICT r4 #1b).
            if jax.default_backend() == "tpu":
                print(f"[engine] {msg}", file=sys.stderr, flush=True)

        self._istamp = _istamp
        # Span the whole init (mask prep + shipping dominate it at scale);
        # entered/exited manually — a `with` would reindent the body, and
        # an init that raises leaves the span open for flush_open_spans.
        _init_span = obs_span(
            "engine_init", engine="relay", vr=int(rg.vr), applier=applier
        )
        _init_span.__enter__()
        _istamp(f"init: resolving applier ({applier!r})...")
        self.applier = self._resolve_applier(applier)
        # Device-resident layout tensors are passed as jit ARGUMENTS — a
        # closed-over concrete array is baked into the program as a constant,
        # and the routing masks are hundreds of MB at scale >= 20.  The int32
        # src table stays HOST-side (candidates are slot indices).  On the
        # fused TPU path the mask arg is the tuple of per-pass arrays
        # (outer stages re-chunked so every mask DMA is contiguous).
        if self._use_pallas():
            from ..ops import relay_pallas as RP

            def mask_arg(masks, table, size):
                if _net_uses_pallas(size):
                    return tuple(
                        jnp.asarray(a)
                        for a in RP.prepare_pass_masks(masks, table, size)
                    )
                return jnp.asarray(masks)

            _istamp(
                f"shipping vperm masks ({rg.vperm_masks.nbytes >> 20} MB)..."
            )
            vperm_arg = mask_arg(rg.vperm_masks, rg.vperm_table, rg.vperm_size)
            net_arg = self._probe_net_arg
            if net_arg is None or not isinstance(net_arg, tuple):
                _istamp(
                    f"shipping net masks ({rg.net_masks.nbytes >> 20} MB)..."
                )
                net_arg = mask_arg(rg.net_masks, rg.net_table, rg.net_size)
        else:
            vperm_arg = jnp.asarray(rg.vperm_masks)
            net_arg = self._probe_net_arg
            if net_arg is None or isinstance(net_arg, tuple):
                net_arg = jnp.asarray(rg.net_masks)
        self._probe_net_arg = None
        _istamp("shipping valid-slot words + sparse adjacency...")
        self._tensors = (
            vperm_arg,
            net_arg,
            jnp.asarray(valid_slot_words(rg.src_l1, rg.net_size)),
        )
        outdeg = np.diff(rg.adj_indptr[: rg.vr + 1].astype(np.int64)).astype(
            np.int32
        )
        if sparse_hybrid:
            # The packed sparse superstep consumes per-edge RANKS (the
            # parent field of the fused word); the unpacked one consumes
            # L1 slots; the MXU arm consumes per-edge KEYS (original src
            # ids — the sort key IS the canonical tie-break, and the
            # payload matches the mxu pull body's candidates).  Each
            # flavor is derived host-side once per engine so the on-disk
            # layout bundles stay slot-based and cache-compatible.
            # _sparse_flavor records which flavor SHIPPED — distinct from
            # self.packed, which callers may downgrade (bench's
            # warm-phase truncation guard), and from self.expansion,
            # which the TPU phase probe may still flip to mxu.
            self._sparse_flavor = (self.packed, self.expansion == "mxu")
            self._sparse_tensors = (
                jnp.asarray(rg.adj_indptr),
                jnp.asarray(rg.adj_dst),
                jnp.asarray(_sparse_third(rg, *self._sparse_flavor)),
                jnp.asarray(outdeg),
            )
        else:
            # The fused program traces (and XLA drops) the sparse operands
            # when the hybrid is off; ship 1-element dummies instead of the
            # ~2*E adjacency (6.4 GB at scale 26 — the difference between
            # fitting and not fitting the single-chip HBM envelope,
            # ARCHITECTURE §7).  indptr/outdeg stay real: frontier_stats
            # and the superstep profiler read outdeg.
            self._sparse_tensors = (
                jnp.asarray(rg.adj_indptr),
                jnp.zeros(1, jnp.int32),
                jnp.zeros(1, jnp.int32),
                jnp.asarray(outdeg),
            )
        self._static = _relay_static(rg)
        self._compiled = {}
        _istamp("resolving per-phase kernel selection...")
        self.phase_probe = None
        self.phase_selection = self._resolve_phase_selection()
        _init_span.__exit__(None, None, None)
        _istamp("init done")

    def _resolve_phase_selection(self) -> dict:
        """Per-phase kernel choice for the packed row-min and packed
        state-update (ISSUE 7 tentpole b): ``BFS_TPU_ROWMIN`` /
        ``BFS_TPU_STATE_UPDATE`` force ``pallas``/``xla``; ``auto`` (the
        default) MEASURES both arms on TPU backends
        (profiling.probe_phase_kernels, K-loop difference timing on the
        engine's real shapes) and picks per phase — never a static
        default.  Off-TPU the fused kernels only exist in interpret mode
        (measured for the ledger's verdict, never competitive), so auto
        resolves to the XLA arms with the basis recorded."""
        sel, basis = {}, {}
        forced = {
            "rowmin": knobs.get("BFS_TPU_ROWMIN"),
            "state_update": knobs.get("BFS_TPU_STATE_UPDATE"),
        }
        need_auto = [p for p, v in forced.items() if v == "auto"]
        # The expansion arm's measured half rides the SAME probe (ISSUE
        # 15): 'auto' that survived the static gates builds the tile
        # layout (budget-gated) and lets probe_phase_kernels time the
        # gather-vs-mxu dense supersteps next to the rowmin/state-update
        # arms.  BFS_TPU_PHASE_PROBE=force runs the probe on any backend
        # (the interpret-arm measurement the ledger also takes).
        probe_exp = self.expansion == "auto-probe"
        force_probe = knobs.get("BFS_TPU_PHASE_PROBE") == "force"
        on_tpu = jax.default_backend() == "tpu" or force_probe
        if probe_exp:
            if not on_tpu:
                self.expansion = "gather"
                self.expansion_basis = (
                    "auto -> gather: non-tpu backend (mxu arm is "
                    "interpret-only; force BFS_TPU_EXPANSION=mxu to run "
                    "it anyway)"
                )
                probe_exp = False
            elif not self._build_tiles(require=False):
                self.expansion = "gather"
                probe_exp = False
        if ((need_auto and self.packed) or probe_exp) and on_tpu:
            from ..profiling import probe_phase_kernels

            probe = self._probe_memoized(probe_phase_kernels)
            self.phase_probe = probe
            if probe_exp:
                rec = probe.get("expansion") if probe else None
                if rec is not None and "selected" in rec:
                    self.expansion = rec["selected"]
                    self.expansion_basis = rec["selection_basis"]
                    self.expansion_probe = rec
                else:
                    self.expansion = "gather"
                    self.expansion_basis = "fallback (probe failed)"
            for p in forced:
                if forced[p] != "auto":
                    sel[p], basis[p] = forced[p], "forced (env)"
                elif probe is not None and p in probe:
                    sel[p] = probe[p]["selected"]
                    basis[p] = probe[p]["selection_basis"]
                else:
                    sel[p], basis[p] = "xla", "fallback (probe failed)"
            if not (need_auto and self.packed):
                # Expansion-only probe: the rowmin/state-update phases
                # keep their static resolution below.
                for p in forced:
                    if forced[p] != "auto":
                        sel[p], basis[p] = forced[p], "forced (env)"
                    elif not self.packed:
                        sel[p], basis[p] = (
                            "xla", "unpacked carry (no fused arm)"
                        )
        else:
            for p in forced:
                if forced[p] != "auto":
                    sel[p], basis[p] = forced[p], "forced (env)"
                elif not self.packed:
                    sel[p], basis[p] = "xla", "unpacked carry (no fused arm)"
                else:
                    sel[p], basis[p] = (
                        "xla",
                        "non-tpu backend (pallas arm is interpret-only; "
                        "the phase ledger still measures it)",
                    )
        return {
            "rowmin": sel["rowmin"],
            "state_update": sel["state_update"],
            "basis": basis,
        }

    def _phase_sel(self) -> tuple:
        """Hashable per-phase selection for program/executable keys."""
        return (
            self.phase_selection["rowmin"],
            self.phase_selection["state_update"],
        )

    def _probe_memoized(self, probe_fn):
        """The K-loop phase probe, MEMOIZED content-keyed next to the
        layout bundle (ISSUE 15 satellite): a bundle-cache warm hit used
        to re-pay the probe on every engine init — serve registered N
        graphs, paid N probes per process start.  The verdict is a pure
        function of (layout shapes, kernel sources, backend, probe
        knobs), which is exactly the memo key (cache/layout.py)."""
        from ..cache.layout import load_probe_verdict, save_probe_verdict

        key = None
        try:
            from ..cache.layout import probe_verdict_key

            key = probe_verdict_key(self)
            cached = load_probe_verdict(key)
            if cached is not None:
                cached["memo"] = "hit"
                return cached
        except Exception as exc:
            logger.warning("probe memo unavailable: %r", exc)
        try:
            probe = probe_fn(self)
        except Exception as exc:  # pragma: no cover - TPU-only path
            logger.warning("phase-kernel probe failed: %r", exc)
            return None
        if key is not None and probe is not None:
            probe["memo"] = "miss"
            try:
                save_probe_verdict(key, probe)
            except Exception as exc:
                logger.warning("probe memo write failed: %r", exc)
        return probe

    # ---------------------------------------------------------- expansion --
    def _resolve_expansion_static(self, requested: str | None) -> None:
        """The static half of the expansion-arm choice (ISSUE 15): forced
        modes resolve here (and constrain the packed carry — the mxu
        parent field holds ORIGINAL ids, so ``V`` must fit 26 bits);
        'auto' applies its static gates and defers the measured half to
        the phase probe (``expansion == 'auto-probe'`` until then)."""
        import os

        from ..ops.packed import packed_parent_fits
        from ..ops.relay_mxu import resolve_expansion

        req = resolve_expansion(requested)
        self.expansion_requested = req
        self.expansion = "gather"
        self.expansion_basis = "default"
        if req == "gather":
            self.expansion_basis = "forced (env/arg)"
            return
        fits = packed_parent_fits(self.relay_graph.num_vertices)
        if req == "mxu":
            if self.packed and not fits:
                if knobs.get("BFS_TPU_PACKED") == "1":
                    raise ValueError(
                        "BFS_TPU_EXPANSION=mxu with BFS_TPU_PACKED=1 "
                        "needs V <= 2^26: the mxu arm's packed parent "
                        "field carries ORIGINAL ids"
                    )
                self.packed = False
            self._build_tiles(require=True)
            self.expansion = "mxu"
            self.expansion_basis = "forced (env/arg)"
            return
        if self.packed and not fits:
            self.expansion_basis = (
                "auto -> gather: V exceeds the 26-bit packed parent "
                "field for original-id candidates"
            )
            return
        # Measured half rides the phase probe (needs the shipped engine
        # tensors) — _resolve_phase_selection finishes this.
        self.expansion = "auto-probe"

    def _build_tiles(self, require: bool) -> bool:
        """Build/load the tiled adjacency (graph/adj_tiles.py) under the
        BFS_TPU_MXU_TILE_GB budget; ``require`` raises instead of
        degrading to gather (the forced-mxu contract: a capture must
        never silently measure the other arm)."""
        if self.adj_tiles is not None:
            return True
        from ..cache.layout import load_or_build_tiles
        from ..ops.relay_mxu import tiles_budget_bytes

        try:
            at, info = load_or_build_tiles(
                self.relay_graph, budget_bytes=tiles_budget_bytes()
            )
        except Exception as exc:
            if require:
                raise
            logger.warning("mxu tile build rejected: %r", exc)
            self.expansion_basis = f"auto -> gather: tiles build ({exc!r})"
            return False
        self.adj_tiles = at
        self.tiles_info = info
        return True

    def _mxu_ops(self) -> tuple:
        """Device-resident tile operands, shipped once per engine."""
        cached = self._mxu_dev
        if cached is None:
            from ..ops.relay_mxu import mxu_device_operands

            at = self.adj_tiles
            self._istamp(
                f"shipping adjacency tiles ({at.nbytes >> 20} MB, "
                f"{at.nt} tiles)..."
            )
            cached = mxu_device_operands(at)
            self._mxu_dev = cached
        return cached

    def _mxu_mask_args(self) -> tuple:
        """The mxu arm's substitution for the (vperm, net, valid) mask
        operand slots: the tile tuple plus two 1-element dummies (XLA
        drops unused operands, same trick as the hybrid-off adjacency
        dummies)."""
        dummy = getattr(self, "_mxu_dummy", None)
        if dummy is None:
            dummy = self._mxu_dummy = jnp.zeros(1, jnp.uint32)
        return (self._mxu_ops(), dummy, dummy)

    def _expansion_key(self, kernel_ok: bool = True) -> tuple:
        """Hashable expansion-arm element for program/executable keys:
        ``('gather',)`` or ``('mxu', geometry, use_kernel)``."""
        if self.expansion != "mxu":
            return ("gather",)
        from ..ops.relay_mxu import mxu_static, resolve_mxu_kernel

        use_kernel = kernel_ok and resolve_mxu_kernel() == "pallas"
        return ("mxu", mxu_static(self.adj_tiles), use_kernel)

    def _resolve_applier(self, applier: str) -> str:
        """Forced env/arg choice, or the measured probe on TPU 'auto'."""
        from ..ops.relay_pallas import pallas_enabled

        env = knobs.get("BFS_TPU_PALLAS")
        if env in ("0", "1"):
            return "pallas" if env == "1" else "xla"
        if not pallas_enabled():
            return "xla"
        if applier != "auto":
            return applier
        if not _net_uses_pallas(self.relay_graph.net_size):
            return "xla"  # too small for the fused passes; nothing to probe
        with obs_span("applier_probe", net_size=int(self.relay_graph.net_size)):
            probe, net_arg = _probe_appliers(
                self.relay_graph, self._COMPILER_OPTIONS
            )
        self.applier_probe = probe
        self._probe_net_arg = net_arg
        return probe["selected"]

    def _use_pallas(self) -> bool:
        return self.applier == "pallas"

    def _elem_use_pallas(self) -> bool:
        """Element-major mode follows the BACKEND, not the single-source
        probe: the probe's applier choice reflects single-tree mask-stream
        economics, while elem mode amortizes the mask stream over the whole
        32*G-tree batch — and the XLA elem applier's pair reshapes cannot
        tile on TPU at bench scale at all (a [N, 2, d] u32 view pads x16 to
        ~20 GB at net 2^28; measured round 4, the round-3 elem bench's
        silent blocker).  BFS_TPU_PALLAS=0 still forces the XLA reference
        path (CPU tests)."""
        from ..ops.relay_pallas import pallas_enabled

        return pallas_enabled()

    #: XLA keeps Pallas operands/results VMEM-resident when they fit under
    #: its scoped-vmem budget; mid-size nets (2^25..2^26 words arrays of
    #: 4-8 MB) then blow the 16 MB default limit at compile time.  The TPU
    #: flag cannot go through XLA_FLAGS (the local CPU XLA aborts on unknown
    #: flags), so fused programs are AOT-compiled with per-compile options.
    _COMPILER_OPTIONS = {"xla_tpu_scoped_vmem_limit_kib": "98304"}

    def _compile_maybe_cached(self, lowered):
        return compile_exe_cached(lowered, self._COMPILER_OPTIONS)

    def _sparse_tensors_for(self, packed: bool):
        """Device sparse-adjacency operands matching the carry/arm
        flavor: keys for the mxu arm, ranks for the packed gather carry,
        slots for the unpacked one.  The engine ships its default flavor
        at init; others (the deep-graph fallback, or an expansion arm the
        TPU probe flipped after shipping) are built lazily and
        memoized."""
        flavor = (packed, self.expansion == "mxu")
        if not self.sparse_hybrid or flavor == getattr(
            self, "_sparse_flavor", (self.packed, False)
        ):
            return self._sparse_tensors
        memo = getattr(self, "_sparse_alt_memo", None)
        if memo is None:
            memo = self._sparse_alt_memo = {}
        alt = memo.get(flavor)
        if alt is None:
            alt = (
                self._sparse_tensors[0],
                self._sparse_tensors[1],
                jnp.asarray(_sparse_third(self.relay_graph, *flavor)),
                self._sparse_tensors[3],
            )
            memo[flavor] = alt
        return alt

    def _fused(self, source_new, max_levels, packed: bool | None = None,
               telemetry: bool = False):
        if packed is None:
            packed = self.packed
        expansion = self._expansion_key()
        fused = _relay_fused_program(
            self._static, self.sparse_hybrid, self._use_pallas(), packed,
            telemetry, self.direction.key(), self._phase_sel(),
            self.relay_graph.num_vertices, expansion,
        )
        masks = (
            self._mxu_mask_args()
            if self.expansion == "mxu"
            else self._tensors
        )
        args = (source_new, *masks, *self._sparse_tensors_for(packed))
        if not self._use_pallas():
            return fused(*args, max_levels=max_levels)
        key = (
            "fused", max_levels, packed, telemetry, self.direction.key(),
            self._phase_sel(), expansion,
        )
        compiled = self._compiled.get(key)
        if compiled is None:
            compiled = self._compile_maybe_cached(
                fused.lower(*args, max_levels=max_levels)
            )
            self._compiled[key] = compiled
        return compiled(*args)

    def init_state(self, source: int):
        """UNPACKED per-superstep state — the SuperstepRunner/observability
        contract (dist/parent directly readable, no level cap)."""
        from ..ops.relay import init_relay_state

        rg = self.relay_graph
        check_sources(rg.num_vertices, source)
        return init_relay_state(rg.vr, int(rg.old2new[source]))

    def init_packed_state(self, source: int):
        """Packed per-superstep state — what the fused hot loop carries;
        use for profiling the real superstep bodies
        (bench superstep_profile / the phase ledger)."""
        from ..ops.relay import init_packed_relay_state

        rg = self.relay_graph
        check_sources(rg.num_vertices, source)
        return init_packed_relay_state(rg.vr, int(rg.old2new[source]))

    def init_hot_state(self, source: int):
        """The state flavor the fused program actually carries for this
        engine (packed when :attr:`packed`, else unpacked)."""
        if self.packed:
            return self.init_packed_state(source)
        return self.init_state(source)

    def take_sparse(self, state) -> bool:
        """Evaluate THE dispatch predicate (:func:`_take_sparse` — the same
        function the fused loop's ``small()`` compiles) for this state, as
        a host bool."""
        if not self.sparse_hybrid:
            return False
        key = ("take_sparse",)
        compiled = self._compiled.get(key)
        if compiled is None:
            vr = self.relay_graph.vr
            n_adj = int(self._sparse_tensors[1].shape[0])
            compiled = jax.jit(
                lambda st, od: _take_sparse(st, od, vr, n_adj)
            )
            self._compiled[key] = compiled
        return bool(
            jax.device_get(compiled(state, self._sparse_tensors[3]))
        )

    def _step_fn(self, kind: str, packed: bool):
        """The jit program one superstep body compiles to, with the state
        carry DONATED (argnum 0): a stepped superstep consumes its input
        state — it is dead the moment the step returns — so donation lets
        XLA write the output into the input's buffers instead of holding
        both, halving the step's peak state HBM (IR lint rule IR001; at
        s24 the packed relay carry is ~69 MB, the unpacked push state
        ~151 MB — un-donated, each step doubles that).  Callers must not
        reuse a state they have stepped; every stepped path reassigns
        (``state = step(state)``)."""
        if kind == "sparse":
            vr = self.relay_graph.vr

            def fn(st, indptr, adst, aslot):
                return _sparse_superstep(
                    st, indptr, adst, aslot, vr=vr, packed=packed
                )
        elif self.expansion == "mxu":
            from ..ops import relay_mxu as RM

            _, geo, use_kernel = self._expansion_key()
            step = RM.mxu_superstep_packed if packed else RM.mxu_superstep

            def fn(st, *tile_ops):
                return step(st, tile_ops, geo, use_kernel)
        else:
            fn = _superstep_fn(
                self._static, self._use_pallas(), packed,
                self._phase_sel(),
            )
        return jax.jit(fn, donate_argnums=0)

    def _step_body(self, kind: str, state):
        """AOT-compiled dense or sparse superstep body (cached per engine;
        scoped-vmem options on TPU backends only — the CPU XLA rejects the
        TPU flag).  The body flavor follows the STATE flavor: a
        PackedRelayState gets the packed body (what the fused hot loop
        runs), an unpacked RelayState the int32 one (the SuperstepRunner
        observability path)."""
        from ..ops.relay import PackedRelayState

        packed = isinstance(state, PackedRelayState)
        key = (kind + "_step", packed, self.expansion)
        compiled = self._compiled.get(key)
        if compiled is None:
            if kind == "sparse":
                args = (state, *self._sparse_tensors_for(packed)[:3])
            else:
                args = (state, *self._dense_step_operands())
            opts = (
                self._COMPILER_OPTIONS
                if jax.default_backend() == "tpu"
                else None
            )
            compiled = compile_exe_cached(
                self._step_fn(kind, packed).lower(*args), opts
            )
            self._compiled[key] = compiled
        return compiled

    def warm_step_bodies(self, state) -> None:
        """Pre-compile both superstep bodies so stepped timing
        (:meth:`step_dispatch` in bench.py's superstep_profile) never pays
        compile time inside a timed superstep."""
        self._step_body("dense", state)
        if self.sparse_hybrid:
            self._step_body("sparse", state)

    def step_dispatch(self, state, take_sparse: bool | None = None):
        """One compiled superstep on the path the fused program would take
        for this frontier, returning ``(new_state, "sparse"|"dense")``.
        The decision comes from :meth:`take_sparse` — the single dispatch
        predicate — so a stepped decomposition (bench.py
        superstep_profile) runs and labels exactly the bodies the fused
        loop's nested-while structure would run.  Pass a precomputed
        ``take_sparse`` to keep the predicate's device round-trip out of a
        timed window."""
        if take_sparse is None:
            take_sparse = self.take_sparse(state)
        elif take_sparse and not self.sparse_hybrid:
            # Without the hybrid, the engine ships 1-element dummy adjacency
            # tensors — running the sparse body against them would return
            # plausible-looking wrong state.
            raise ValueError(
                "take_sparse=True on an engine built with sparse_hybrid=False"
            )
        if take_sparse:
            from ..ops.relay import PackedRelayState

            body = self._step_body("sparse", state)
            tensors = self._sparse_tensors_for(
                isinstance(state, PackedRelayState)
            )
            return body(state, *tensors[:3]), "sparse"
        body = self._step_body("dense", state)
        return body(state, *self._dense_step_operands()), "dense"

    def _dense_step_operands(self) -> tuple:
        """The dense superstep body's non-state operands for this
        engine's expansion arm (masks for gather, the tile tuple for
        mxu)."""
        if self.expansion == "mxu":
            return self._mxu_ops()
        return self._tensors

    def frontier_stats(self, state):
        """(frontier vertices, frontier out-edges) for a RelayState — the
        sparse-dispatch quantities, as host ints."""
        key = ("frontier_stats",)
        compiled = self._compiled.get(key)
        if compiled is None:
            vr = self.relay_graph.vr
            compiled = jax.jit(
                lambda st, od: _frontier_stats(st, od, vr)
            )
            self._compiled[key] = compiled
        fsize, fedges = jax.device_get(
            compiled(state, self._sparse_tensors[3])
        )
        return int(fsize), int(fedges)

    def step(self, state):
        """One compiled relay superstep (RelayState, RELABELED space).

        Compiled once per engine and reused, so stepped execution
        (SuperstepRunner) hits the cache instead of retracing every
        superstep (ADVICE.md round 3).  Delegates to the same AOT-compiled
        dense body as :meth:`step_dispatch` — the tile-major local pass's
        ~73 MB VMEM scratch needs the raised scoped-vmem compile budget,
        which plain ``jax.jit`` would not apply."""
        return self._step_body("dense", state)(
            state, *self._dense_step_operands()
        )

    def _to_result(self, state, source: int) -> BfsResult:
        rg = self.relay_graph
        dist = np.asarray(state.dist)[rg.old2new]
        if self.expansion == "mxu":
            # The mxu arm's parent VALUES are already ORIGINAL ids (the
            # expansion's min-key candidates) — only the index space
            # needs the relabel gather.
            parent = np.asarray(state.parent)[rg.old2new].copy()
        else:
            parent = slots_to_parent(np.asarray(state.parent), rg.src_l1)[
                rg.old2new
            ]
        parent[source] = source  # init wrote the relabeled id at the source
        return BfsResult(dist=dist, parent=parent, num_levels=int(state.level))

    def _orig_tables_device(self):
        """Device-resident old2new + src_l1 tables for
        :meth:`to_original_device`, shipped once per engine (they are the
        same tables :meth:`_to_result` gathers through host-side)."""
        cached = getattr(self, "_orig_dev", None)
        if cached is None:
            rg = self.relay_graph
            self._istamp(
                "shipping original-id tables for on-device check "
                f"(old2new {rg.old2new.nbytes >> 20} MB, "
                f"src_l1 {rg.src_l1.nbytes >> 20} MB)..."
            )
            cached = (jnp.asarray(rg.old2new), jnp.asarray(rg.src_l1))
            self._orig_dev = cached
        return cached

    def _map_original_device(self, dist_new, parent_slots, source: int,
                             flavor: str | None = None):
        """Relabeled-space device (dist, parent) -> ORIGINAL id space, on
        device (the device twin of :meth:`_to_result`).  ``flavor``
        overrides the engine's expansion arm for callers whose parent
        values are ALWAYS slots (the elem-tree extraction)."""
        flavor = self.expansion if flavor is None else flavor
        o2n, s1 = self._orig_tables_device()
        key = ("to_original", flavor)
        fn = self._compiled.get(key)
        if fn is None:
            m1 = int(self.relay_graph.src_l1.shape[0])
            mxu = flavor == "mxu"

            def _map(dist, parent, o2n, s1, src):
                if mxu:
                    par = parent  # values are original ids already
                else:
                    par = jnp.where(
                        parent >= 0, s1[jnp.clip(parent, 0, m1 - 1)],
                        parent,
                    )
                # init wrote a non-sentinel word at the source's
                # self-entry; fix it up exactly like the host path does.
                return dist[o2n], par[o2n].at[src].set(src)

            fn = jax.jit(_map)
            self._compiled[key] = fn
        return fn(dist_new, parent_slots, o2n, s1, jnp.int32(int(source)))

    def to_original_device(self, state, source: int):
        """Device-resident ``(dist, parent)`` in ORIGINAL id space — the
        device twin of the host mapping in :meth:`_to_result`, with NO
        host transfer.  Feeds the on-device verifier
        (:class:`bfs_tpu.oracle.device.DeviceChecker`) so per-root
        verification pulls a handful of counters instead of the 128 MB
        dist+parent arrays (ISSUE 2 tentpole c).  ``source`` is the
        ORIGINAL source id (traced — no recompile per root)."""
        return self._map_original_device(state.dist, state.parent, source)

    def _rank_tables_device(self):
        """Device-resident base/stride slot tables (rank -> L1 slot) for
        on-device elem-tree extraction, shipped once per engine."""
        cached = getattr(self, "_rank_dev", None)
        if cached is None:
            from ..graph.relay import _vertex_tables

            rg = self.relay_graph
            base1, stride1 = _vertex_tables(list(rg.in_classes), rg.vr)
            self._istamp(
                "shipping rank->slot tables for on-device tree extraction "
                f"({(base1.nbytes + stride1.nbytes) >> 20} MB)..."
            )
            cached = (jnp.asarray(base1), jnp.asarray(stride1))
            self._rank_dev = cached
        return cached

    def multi_tree_to_original_device(self, state, i: int, source: int):
        """Device-resident ``(dist, parent)`` in ORIGINAL id space for
        tree ``i`` of a batched device state — either the bit-sliced
        ElemState (element-major mode) or a batched RelayState (the
        vmapped fallback).  The device twin of the per-tree host
        extraction in ops/relay_elem.extract_results: feeds
        :class:`~bfs_tpu.oracle.device.DeviceChecker` so multi-source
        verification pulls counters per tree instead of S full
        dist+parent arrays (VERDICT r5 item 6)."""
        from ..ops.relay_elem import ElemState

        if not isinstance(state, ElemState):
            return self._map_original_device(
                state.dist[i], state.parent[i], source
            )
        base1, stride1 = self._rank_tables_device()
        key = ("elem_tree",)
        fn = self._compiled.get(key)
        if fn is None:
            from ..ops.relay_elem import DIST_PLANES, rank_plane_layout

            rg = self.relay_graph
            offsets, _pt = rank_plane_layout(rg.in_classes)
            in_classes = tuple(rg.in_classes)
            vr = rg.vr

            def _extract(visited, dist_planes, rank_planes, gi, t, b1, s1):
                vis = (visited[gi] >> t) & 1
                dv = jnp.zeros(vr, jnp.int32)
                for b in range(DIST_PLANES):
                    dv = dv | (
                        ((dist_planes[b, gi] >> t) & 1).astype(jnp.int32)
                        << b
                    )
                rank = jnp.zeros(vr, jnp.int32)
                row = rank_planes[gi]
                for cs in in_classes:
                    off, nb = offsets[cs.va]
                    acc = jnp.zeros(cs.count, jnp.int32)
                    for j in range(nb):
                        seg = jax.lax.slice_in_dim(
                            row, off + j * cs.count, off + (j + 1) * cs.count
                        )
                        acc = acc | (((seg >> t) & 1).astype(jnp.int32) << j)
                    rank = jax.lax.dynamic_update_slice_in_dim(
                        rank, acc, cs.va, axis=0
                    )
                slot = b1 + rank * s1
                dist = jnp.where(vis == 1, dv, jnp.int32(INT32_MAX))
                parent = jnp.where(vis == 1, slot, jnp.int32(-1))
                return dist, parent

            fn = jax.jit(_extract)
            self._compiled[key] = fn
        dist_new, parent_slots = fn(
            state.visited, state.dist_planes, state.rank_planes,
            jnp.int32(i // 32), jnp.uint32(i % 32), base1, stride1,
        )
        # Elem-mode parents are ALWAYS slots regardless of the engine's
        # expansion arm (the elem pipeline is the gather formulation).
        return self._map_original_device(
            dist_new, parent_slots, source, flavor="gather"
        )

    def _stream_effective(self) -> bool:
        """Whether this engine's runs page adjacency from the host store
        (ISSUE 18): only the mxu arm has a superblock decomposition, so
        gather engines stay resident whatever the knob says; ``auto``
        streams exactly when the tile layout outgrows the stream cache
        budget (the resident upload would not have fit anyway)."""
        if self.expansion != "mxu" or self.adj_tiles is None:
            return False
        if self.tiles_mode == "stream":
            return True
        if self.tiles_mode == "auto":
            from ..ops.relay_mxu import stream_cache_budget_bytes

            return self.adj_tiles.nbytes > stream_cache_budget_bytes()
        return False

    def run_streamed(self, source: int = 0, *, ckpt=None,
                     max_levels: int | None = None,
                     telemetry: bool = False,
                     cache_budget_bytes: int | None = None):
        """Streamed single-source BFS (ISSUE 18): the host-paged twin of
        :meth:`run_segmented` — adjacency superblocks stream host->HBM
        through the budgeted LRU cache, dist/parent and the direction
        schedule stay bit-identical to the resident arms, and the stream
        ledger lands on :attr:`stream_report`.  Delegates to
        stream/runner.py (imported lazily: the package imports this
        module)."""
        from ..stream.runner import run_streamed as _run

        check_sources(self.relay_graph.num_vertices, source)
        return _run(
            self, source, ckpt=ckpt, max_levels=max_levels,
            telemetry=telemetry, cache_budget_bytes=cache_budget_bytes,
        )

    def run(self, source: int = 0, *, max_levels: int | None = None) -> BfsResult:
        from ..ops.packed import packed_truncated

        if self._stream_effective():
            return self.run_streamed(source, max_levels=max_levels)
        rg = self.relay_graph
        check_sources(rg.num_vertices, source)
        max_levels = int(max_levels) if max_levels is not None else rg.vr
        source_new = int(rg.old2new[source])
        state = jax.device_get(self._fused(jnp.int32(source_new), max_levels))
        if self.packed and packed_truncated(
            state.changed, state.level, max_levels
        ):
            # Deeper than the packed level field: re-run on the unpacked
            # path (same detect-and-fallback contract as elem mode's
            # 31-level planes).
            state = jax.device_get(
                self._fused(jnp.int32(source_new), max_levels, packed=False)
            )
        return self._to_result(state, source)

    def run_level_curve(self, source: int = 0, *,
                        max_levels: int | None = None,
                        reference_reached: int | None = None) -> dict:
        """One UNTIMED fused search with the device telemetry accumulator
        (obs/telemetry.py) carried as extra loop state; returns the
        JSON-ready level curve — per-level frontier occupancy + out-edge
        counts, packed-cap proximity.

        Transfer cost: ONE ``device_get`` of the ~1 KB accumulators plus
        the loop-exit scalars — the 128 MB dist/parent stay on device
        (the whole point: the curve is the direction-switching input for
        ROADMAP item 2 and must be readable without breaking the
        hot-region transfer rules)."""
        from ..obs.telemetry import (
            direction_schedule,
            level_curve,
            read_telemetry,
        )
        from ..ops.packed import PACKED_MAX_LEVELS, packed_truncated

        rg = self.relay_graph
        check_sources(rg.num_vertices, source)
        max_levels = int(max_levels) if max_levels is not None else rg.vr
        src = jax.device_put(np.int32(rg.old2new[source]))
        state, (fv_d, fe_d, dir_d) = self._fused(
            src, max_levels, telemetry=True
        )
        fv, fe, dirs, changed, level = read_telemetry(
            (fv_d, fe_d, dir_d, state.changed, state.level)
        )
        packed_run = self.packed
        if packed_run and packed_truncated(changed, level, max_levels):
            # Deeper than the packed level field: the curve would be
            # truncated at the cap — re-run unpacked, same contract as run().
            state, (fv_d, fe_d, dir_d) = self._fused(
                src, max_levels, packed=False, telemetry=True
            )
            fv, fe, dirs, changed, level = read_telemetry(
                (fv_d, fe_d, dir_d, state.changed, state.level)
            )
            packed_run = False
        # The loop's REAL cap: the packed level field AND the caller's
        # max_levels both bound it — reporting the raw 62 would hide a
        # caller-limit truncation behind a healthy-looking proximity.
        cap = min(PACKED_MAX_LEVELS, max_levels) if packed_run else max_levels
        curve = level_curve(fv, fe, cap=cap,
                            reference_reached=reference_reached)
        # The per-superstep push/pull schedule rides the same telemetry
        # pull — shipped by bench as details.direction_schedule next to
        # the curve (ISSUE 7 tentpole a).
        curve["direction_schedule"] = direction_schedule(
            dirs, mode=self.direction.mode, alpha=self.direction.alpha,
            beta=self.direction.beta,
        )
        return curve

    def segment_keys(self, packed: bool, telemetry: bool) -> list[str]:
        """The segment carry's key set for one flavor — the ONE
        definition :meth:`segment_carry` builds from and the restore
        gate validates against (an epoch lacking any of these cannot
        resume this flavor)."""
        keys = (["pk"] if packed else ["dist", "parent"]) + [
            "fw", "level", "changed",
        ]
        if self.direction.mode == "auto" and self.sparse_hybrid:
            keys += ["mu", "prev"]
        if telemetry:
            keys += ["occ", "dirs"]
        return keys

    def segment_carry(self, source: int, *, packed: bool | None = None,
                      telemetry: bool = False,
                      restore: dict | None = None) -> dict:
        """Initial (or checkpoint-restored) carry for the segment program
        (:func:`_relay_segment_program`): every loop-state leaf, incl.
        the direction hysteresis pair in auto mode and the telemetry
        accumulators — the carry IS the checkpoint.  ``restore`` maps
        carry keys to host arrays from an epoch; metadata keys are
        ignored."""
        from ..ops import relay as Rops

        if packed is None:
            packed = self.packed
        rg = self.relay_graph
        auto = self.direction.mode == "auto" and self.sparse_hybrid
        keys = self.segment_keys(packed, telemetry)
        if restore is not None:
            return {k: jnp.asarray(restore[k]) for k in keys}
        check_sources(rg.num_vertices, source)
        sn = jnp.int32(int(rg.old2new[source]))
        if packed:
            st = Rops.init_packed_relay_state(rg.vr, sn)
            carry = {"pk": st.packed}
        else:
            st = Rops.init_relay_state(rg.vr, sn)
            carry = {"dist": st.dist, "parent": st.parent}
        carry.update(fw=st.fwords, level=st.level, changed=st.changed)
        if auto:
            # Same unexplored-mass seed the fused auto program computes
            # (float32 sum of per-vertex integer out-degrees — exact
            # below 2^24 edges, the mass-parity contract of
            # models/direction.frontier_masses_words).
            carry["mu"] = self._sparse_tensors[3].astype(jnp.float32).sum()
            carry["prev"] = jnp.bool_(False)
        if telemetry:
            from ..obs import telemetry as T

            carry["occ"] = T.init_level_acc()
            carry["dirs"] = T.init_dir_acc()
        return carry

    def _segment_call(self, prog, carry, seg_end, tensors, max_levels):
        """One segment-program call, AOT-compiled with the scoped-vmem
        options on the pallas path (mirrors :meth:`_fused`)."""
        if not self._use_pallas():
            return prog(carry, seg_end, *tensors, max_levels=max_levels)
        key = ("segment", max_levels, tuple(sorted(carry)), self.expansion)
        compiled = self._compiled.get(key)
        if compiled is None:
            compiled = self._compile_maybe_cached(
                prog.lower(carry, seg_end, *tensors, max_levels=max_levels)
            )
            self._compiled[key] = compiled
        return compiled(carry, seg_end, *tensors)

    def _run_segmented_flavor(self, source: int, ckpt, max_levels: int,
                              packed: bool, telemetry: bool):
        """Drive one carry flavor through bounded segments with per-epoch
        checkpoints; returns ``(host RelayState, curve|None)``."""
        import time as _time

        from ..ops import relay as Rops
        from ..ops.packed import PACKED_MAX_LEVELS, packed_cap

        rg = self.relay_graph
        prog = _relay_segment_program(
            self._static, self.sparse_hybrid, self._use_pallas(), packed,
            telemetry, self.direction.key(), self._phase_sel(),
            rg.num_vertices, self._expansion_key(),
        )
        masks = (
            self._mxu_mask_args()
            if self.expansion == "mxu"
            else self._tensors
        )
        tensors = (*masks, *self._sparse_tensors_for(packed))
        cap = packed_cap(max_levels) if packed else max_levels
        from ..resilience.superstep_ckpt import restore_arrays

        arrays, _shards = restore_arrays(
            ckpt, packed, require=tuple(self.segment_keys(packed, telemetry))
        )
        carry = self.segment_carry(
            source, packed=packed, telemetry=telemetry,
            restore=arrays,
        )
        level, changed = jax.device_get((carry["level"], carry["changed"]))
        while bool(changed) and int(level) < cap:
            seg_end = jax.device_put(
                np.int32(min(int(level) + ckpt.interval(), cap))
            )
            t0 = _time.perf_counter()
            carry = self._segment_call(
                prog, carry, seg_end, tensors, max_levels
            )
            new_level, changed = jax.device_get(
                (carry["level"], carry["changed"])
            )
            seg_s = _time.perf_counter() - t0
            # A disabled store still marks the fault boundary but must
            # not pay the O(V) device->host carry pull per segment.
            snap = {}
            if ckpt.enabled:
                snap = {k: np.asarray(v) for k, v in
                        jax.device_get(carry).items()}
                snap["packed_flag"] = np.int32(packed)
            ckpt.save_epoch(int(new_level), snap)
            ckpt.note_segment(int(new_level) - int(level), seg_s)
            level = new_level
        # The ONCE-PER-RUN unpack, at the TRUE end — intermediate epochs
        # stay the raw packed carry (V/2 state bytes per snapshot).
        if packed:
            state_dev = _relay_segment_finish_program(
                tuple(rg.in_classes), rg.vr, self.expansion == "mxu"
            )(carry["pk"], carry["fw"], carry["level"], carry["changed"])
        else:
            state_dev = Rops.RelayState(
                carry["dist"], carry["parent"], carry["fw"],
                carry["level"], carry["changed"],
            )
        curve = None
        if telemetry:
            from ..obs.telemetry import (
                direction_schedule,
                edge_curve_from_levels,
                level_curve,
                read_telemetry,
            )

            fe_key = ("segment_edge_curve",)
            fe_fn = self._compiled.get(fe_key)
            if fe_fn is None:
                fe_fn = jax.jit(edge_curve_from_levels)
                self._compiled[fe_key] = fe_fn
            fe_dev = fe_fn(
                state_dev.dist, self._sparse_tensors[3],
                state_dev.dist == INT32_MAX,
            )
            fv, fe, dirs = read_telemetry(
                (carry["occ"], fe_dev, carry["dirs"])
            )
            curve_cap = (
                min(PACKED_MAX_LEVELS, max_levels) if packed else max_levels
            )
            curve = level_curve(fv, fe, cap=curve_cap)
            curve["direction_schedule"] = direction_schedule(
                dirs, mode=self.direction.mode, alpha=self.direction.alpha,
                beta=self.direction.beta,
            )
        return jax.device_get(state_dev), curve

    def run_segmented(self, source: int = 0, *, ckpt,
                      max_levels: int | None = None,
                      telemetry: bool = False):
        """Segmented-with-checkpoints single-source BFS (ISSUE 14): the
        resumable twin of :meth:`run` — bit-identical dist/parent and
        (with ``telemetry``) direction schedule for any segmentation,
        resumable mid-traversal from ``ckpt``'s newest valid epoch.
        Returns a BfsResult, or ``(BfsResult, curve)`` with telemetry.
        Epochs are cleared on completion (a finished traversal's
        checkpoints are dead weight; resume is for killed runs)."""
        from ..ops.packed import packed_truncated

        if self._stream_effective():
            # Streamed engines run the host-paged loop: same carry keys,
            # same checkpoint epochs (a streamed run resumes a segmented
            # epoch and vice versa), adjacency through the cache.
            return self.run_streamed(
                source, ckpt=ckpt, max_levels=max_levels,
                telemetry=telemetry,
            )
        rg = self.relay_graph
        check_sources(rg.num_vertices, source)
        max_levels = int(max_levels) if max_levels is not None else rg.vr
        packed = self.packed
        state, curve = self._run_segmented_flavor(
            source, ckpt, max_levels, packed, telemetry
        )
        if packed and packed_truncated(
            state.changed, state.level, max_levels
        ):
            # Deeper than the packed level field: same detect-and-rerun
            # contract as run(); packed epochs cannot feed the unpacked
            # re-run, so the store is cleared first.
            ckpt.clear()
            state, curve = self._run_segmented_flavor(
                source, ckpt, max_levels, False, telemetry
            )
        ckpt.clear()
        result = self._to_result(state, source)
        if telemetry:
            return result, curve
        return result

    def run_many_device(self, sources, *, max_levels: int | None = None):
        """Graph500-style batched timing path: dispatch one fused BFS per
        source WITHOUT syncing in between (a synchronized round-trip through
        the axon tunnel costs ~107 ms — tools/microbench_r3.py; chained
        dispatch amortizes it to ~10 ms/search).  Returns the device states;
        callers sync once by reading a value off the last one.

        Runs the packed carry when the engine is packed: searches deeper
        than PACKED_MAX_LEVELS come back with ``changed`` still set (the
        chained no-sync contract cannot fall back per root); result
        consumers must test that flag — bench verification does via the
        component-coverage compare, and :meth:`run` is the safe
        single-root path."""
        rg = self.relay_graph
        sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
        check_sources(rg.num_vertices, sources)
        max_levels = int(max_levels) if max_levels is not None else rg.vr
        # Explicit per-root scalar upload: under jax.transfer_guard
        # ("disallow", BFS_TPU_TRANSFER_GUARD=1) the old implicit
        # jnp.int32 conversion raised inside the bench's guarded
        # timed-repeat region; device_put declares the 4-byte ship.
        return [
            self._fused(jax.device_put(np.int32(rg.old2new[s])), max_levels)
            for s in sources
        ]

    def run_multi_device(self, sources, *, max_levels: int | None = None,
                         packed: bool | None = None):
        """Batched multi-source BFS (lock-step trees), device-resident
        result: the raw batched RelayState in the relabeled space with
        slot-index parents.  Reading ``int(state.level)`` is the cheap
        sync.  On the packed carry (the default when the layout fits) the
        loop caps at PACKED_MAX_LEVELS; raw-device callers must test
        ``state.changed`` at that cap, exactly as for elem mode —
        :meth:`run_multi` does and falls back automatically."""
        rg = self.relay_graph
        sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
        check_sources(rg.num_vertices, sources)
        max_levels = int(max_levels) if max_levels is not None else rg.vr
        if packed is None:
            packed = self.packed
        expansion = self._expansion_key(kernel_ok=False)
        fused = _relay_multi_fused_program(
            self._static, self._use_pallas(), packed, self._phase_sel(),
            expansion,
        )
        sources_new = jax.device_put(rg.old2new[sources])  # explicit: guard-clean in timed repeats
        masks = (
            self._mxu_mask_args()
            if self.expansion == "mxu"
            else self._tensors
        )
        args = (sources_new, *masks)
        if not self._use_pallas():
            return fused(*args, max_levels=max_levels)
        key = (
            "multi", sources_new.shape[0], max_levels, packed,
            self._phase_sel(), expansion,
        )
        compiled = self._compiled.get(key)
        if compiled is None:
            compiled = self._compile_maybe_cached(
                fused.lower(*args, max_levels=max_levels)
            )
            self._compiled[key] = compiled
        return compiled(*args)

    def run_multi_elem_device(self, sources, *, max_levels: int | None = None):
        """Element-major batched multi-source BFS: sources count must be a
        multiple of 32; all trees run lock-step in ONE program with the
        routing masks read once per superstep for the whole batch.  Returns
        the device ElemState (sync = reading ``int(state.level)``).

        The bit-sliced distance planes carry at most ``MAX_ELEM_LEVELS`` (31)
        levels, so on a graph with eccentricity > 31 the loop stops
        unconverged — ``state.changed`` is still True.  (The default run
        allows one EXTRA superstep beyond the cap: a non-changing step at
        level 32 writes no distances and proves an eccentricity-exactly-31
        search converged; a changing one leaves ``changed`` set and its
        writes are discarded by the fallback.)  Callers of this RAW device
        path must test that flag; :meth:`run_multi_elem` does, and
        automatically falls back to the vmapped engine (:meth:`run_multi`,
        host results; ADVICE.md round 3)."""
        from ..ops.relay_elem import MAX_ELEM_LEVELS, rank_plane_layout

        rg = self.relay_graph
        sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
        if sources.shape[0] % 32 != 0:
            raise ValueError("element-major batching needs a multiple of 32 sources")
        check_sources(rg.num_vertices, sources)
        if max_levels is None:
            # One step past the cap: the extra step either confirms
            # convergence without writing (eccentricity == 31) or leaves
            # ``changed`` set for the fallback (see docstring).
            max_levels = MAX_ELEM_LEVELS + 1
        else:
            max_levels = int(max_levels)
            if max_levels > MAX_ELEM_LEVELS:
                raise ValueError(
                    f"element-major mode carries {MAX_ELEM_LEVELS} levels max; "
                    "use run_multi_device for deeper graphs"
                )
        groups = sources.shape[0] // 32
        _, pt = rank_plane_layout(rg.in_classes)
        fused = _relay_elem_program(
            self._static, pt, groups, self._elem_use_pallas()
        )
        src_new = jax.device_put(rg.old2new[sources].reshape(groups, 32))  # explicit: guard-clean in timed repeats
        args = (src_new, *self._elem_tensors())
        if not self._elem_use_pallas():
            return fused(*args, max_levels=max_levels)
        key = ("elem", groups, max_levels)
        compiled = self._compiled.get(key)
        if compiled is None:
            compiled = self._compile_maybe_cached(
                fused.lower(*args, max_levels=max_levels)
            )
            self._compiled[key] = compiled
        return compiled(*args)

    def _elem_tensors(self):
        """Mask tensors for element-major mode: vertically-repacked per-pass
        arrays for the fused TPU path (ops/relay_pallas.py elem mode), flat
        arrays otherwise.  Prepared lazily once per engine."""
        cached = getattr(self, "_elem_mask_tensors", None)
        if cached is not None:
            return cached
        rg = self.relay_graph
        if self._elem_use_pallas():
            from ..ops import relay_pallas as RP

            def mask_arg(masks, table, size):
                if RP.pallas_net_ok(size):
                    return tuple(
                        jnp.asarray(a)
                        for a in RP.prepare_elem_pass_masks(masks, table, size)
                    )
                return jnp.asarray(masks)

            tensors = (
                mask_arg(rg.vperm_masks, rg.vperm_table, rg.vperm_size),
                mask_arg(rg.net_masks, rg.net_table, rg.net_size),
                self._tensors[2],
            )
        else:
            tensors = (
                jnp.asarray(rg.vperm_masks),
                jnp.asarray(rg.net_masks),
                self._tensors[2],
            )
        self._elem_mask_tensors = tensors
        return tensors

    def run_multi_elem(self, sources, *, max_levels: int | None = None):
        """Element-major batched multi-source BFS, host results
        (MultiBfsResult in original-id space, bit-exact vs run_multi).

        If the graph is deeper than the element-major engine's 31-level
        distance planes the lock-step loop cannot converge; rather than
        return silently truncated distances, this detects the unconverged
        ``changed`` flag and falls back to :meth:`run_multi` (the vmapped
        engine, no depth limit)."""
        from ..ops.relay_elem import extract_results
        from .multisource import MultiBfsResult

        sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
        state = jax.device_get(
            self.run_multi_elem_device(sources, max_levels=max_levels)
        )
        if max_levels is None and bool(state.changed):
            # Unconverged at MAX_ELEM_LEVELS: eccentricity > 31 from at
            # least one source.  The vmapped engine carries full int32
            # distances and has no depth cap.
            return self.run_multi(sources)
        dist, parent = extract_results(state, self.relay_graph, sources)
        return MultiBfsResult(
            sources=sources, dist=dist, parent=parent,
            num_levels=int(state.level),
        )

    def run_multi(self, sources, *, max_levels: int | None = None):
        """Batched multi-source BFS on the relay layout; returns a
        :class:`~bfs_tpu.models.multisource.MultiBfsResult` in original-id
        space (bit-exact with the other engines' batched modes)."""
        from .multisource import MultiBfsResult

        from ..ops.packed import packed_truncated

        rg = self.relay_graph
        sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
        requested = (
            int(max_levels) if max_levels is not None else rg.vr
        )
        state = jax.device_get(
            self.run_multi_device(sources, max_levels=max_levels)
        )
        if self.packed and packed_truncated(
            state.changed, state.level, requested
        ):
            state = jax.device_get(
                self.run_multi_device(
                    sources, max_levels=max_levels, packed=False
                )
            )
        dist = np.asarray(state.dist)[:, rg.old2new]
        if self.expansion == "mxu":
            parent = np.asarray(state.parent)[:, rg.old2new].copy()
        else:
            parent = slots_to_parent(np.asarray(state.parent), rg.src_l1)[
                :, rg.old2new
            ]
        rows = np.arange(sources.shape[0])
        parent[rows, sources] = sources  # init wrote relabeled ids at sources
        return MultiBfsResult(
            sources=sources,
            dist=dist,
            parent=parent,
            num_levels=int(state.level),
        )


def bfs(
    graph: Graph | DeviceGraph | PullGraph,
    source: int = 0,
    *,
    engine: str = "pull",
    max_levels: int | None = None,
    block: int = 1024,
) -> BfsResult:
    """Run single-source BFS fully on-device and return host results.

    Engines (same math, different layouts):
      * ``'relay'`` — gather-free degree-class + Beneš bit-routing layout;
        the fast path on real TPUs (requires the native router).
      * ``'pull'`` (default) — ELL gather/row-min formulation.
      * ``'push'`` — segment_min push formulation, the closest analogue of
        the reference's map/shuffle/reduce (BfsSpark.java:66-108).
    Passing a prebuilt :class:`PullGraph`/:class:`DeviceGraph` skips layout.
    """
    from ..graph.relay import RelayGraph

    if engine not in ("pull", "push", "relay"):
        raise ValueError(f"unknown engine {engine!r}; use 'relay', 'pull' or 'push'")
    if isinstance(graph, PullGraph) and engine != "pull":
        raise ValueError("a prebuilt PullGraph only runs on engine='pull'")
    if isinstance(graph, RelayGraph) and engine != "relay":
        raise ValueError("a prebuilt RelayGraph only runs on engine='relay'")
    if engine == "relay":
        eng = RelayEngine(graph)
        return eng.run(source, max_levels=max_levels)
    from ..ops.packed import (
        packed_parent_fits,
        packed_truncated,
        resolve_packed,
    )

    if engine == "pull":
        pg = graph if isinstance(graph, PullGraph) else build_pull_graph(graph)
        check_sources(pg.num_vertices, source)
        max_levels = int(max_levels) if max_levels is not None else pg.num_vertices
        from ..graph.ell import device_ell

        ell0_t, folds_t = device_ell(pg)

        def run_pull(packed):
            return _bfs_pull_fused(
                ell0_t,
                folds_t,
                jnp.int32(source),
                pg.num_vertices,
                max_levels,
                packed,
            )

        packed = resolve_packed(packed_parent_fits(pg.num_vertices))
        state = jax.device_get(run_pull(packed))
        if packed and packed_truncated(state.changed, state.level, max_levels):
            state = jax.device_get(run_pull(False))
        num_vertices = pg.num_vertices
    else:
        dg = graph if isinstance(graph, DeviceGraph) else build_device_graph(graph, block=block)
        if dg.num_shards != 1:
            raise ValueError("sharded DeviceGraph requires the parallel engine")
        check_sources(dg.num_vertices, source)
        max_levels = int(max_levels) if max_levels is not None else dg.num_vertices

        def run_push(packed):
            return _bfs_fused(
                jnp.asarray(dg.src),
                jnp.asarray(dg.dst),
                jnp.int32(source),
                dg.num_vertices,
                max_levels,
                packed,
            )

        packed = resolve_packed(packed_parent_fits(dg.num_vertices))
        state = jax.device_get(run_push(packed))
        if packed and packed_truncated(state.changed, state.level, max_levels):
            state = jax.device_get(run_push(False))
        num_vertices = dg.num_vertices
    return BfsResult(
        dist=np.asarray(state.dist[:num_vertices]),
        parent=np.asarray(state.parent[:num_vertices]),
        num_levels=int(state.level),
    )


def bfs_level_curve(
    graph: Graph | DeviceGraph | PullGraph,
    source: int = 0,
    *,
    engine: str = "pull",
    max_levels: int | None = None,
    block: int = 1024,
    reference_reached: int | None = None,
) -> dict:
    """The level curve (per-level frontier occupancy, obs/telemetry.py)
    of one single-source search — :func:`bfs`'s telemetry twin for the
    push/pull engines, pulling ONE ~0.5 KB accumulator instead of the
    V-sized result arrays.  Relay callers use
    :meth:`RelayEngine.run_level_curve` (it also carries per-level
    frontier out-edges)."""
    from ..obs.telemetry import level_curve, read_telemetry
    from ..ops.packed import (
        PACKED_MAX_LEVELS,
        packed_parent_fits,
        packed_truncated,
        resolve_packed,
    )
    from ..graph.relay import RelayGraph

    if engine == "relay" or isinstance(graph, RelayGraph):
        return RelayEngine(graph).run_level_curve(
            source, max_levels=max_levels,
            reference_reached=reference_reached,
        )
    if engine == "pull":
        pg = graph if isinstance(graph, PullGraph) else build_pull_graph(graph)
        check_sources(pg.num_vertices, source)
        n = pg.num_vertices
        limit = int(max_levels) if max_levels is not None else n
        from ..graph.ell import device_ell

        ell0_t, folds_t = device_ell(pg)

        def run(packed):
            return _bfs_pull_fused(
                ell0_t, folds_t, jnp.int32(source), n, limit, packed, True
            )

        packed = resolve_packed(packed_parent_fits(n))
    elif engine == "push":
        dg = (
            graph
            if isinstance(graph, DeviceGraph)
            else build_device_graph(graph, block=block)
        )
        check_sources(dg.num_vertices, source)
        n = dg.num_vertices
        limit = int(max_levels) if max_levels is not None else n
        src_t, dst_t = jnp.asarray(dg.src), jnp.asarray(dg.dst)

        def run(packed):
            return _bfs_fused(
                src_t, dst_t, jnp.int32(source), n, limit, packed, True
            )

        packed = resolve_packed(packed_parent_fits(n))
    else:
        raise ValueError(f"unknown engine {engine!r}; use relay/pull/push")
    state, acc = run(packed)
    fv, changed, level = read_telemetry((acc, state.changed, state.level))
    if packed and packed_truncated(changed, level, limit):
        state, acc = run(False)
        fv, changed, level = read_telemetry((acc, state.changed, state.level))
        packed = False
    cap = min(PACKED_MAX_LEVELS, limit) if packed else limit
    return level_curve(fv, cap=cap, reference_reached=reference_reached)


class SuperstepRunner:
    """Stepped execution: one compiled superstep per call, any engine.

    This is the observable path — per-superstep wall time (Stopwatch parity,
    BfsSpark.java:59,63,111-112), frontier sizes, state dumps and
    checkpoint/resume hooks — while each superstep itself stays a single
    fused XLA computation.  ``engine`` selects the same layouts as
    :func:`bfs`: ``'push'`` (default, the reference's map/shuffle/reduce
    analogue), ``'pull'`` (ELL), or ``'relay'`` (the TPU-fast Beneš layout).

    For the relay engine the on-device state lives in the RELABELED vertex
    space; :meth:`to_original` maps any state's ``(dist, parent, frontier)``
    into original-id host arrays for dumps/checkpoints, and is the identity
    for push/pull.  Frontier sizes and levels are permutation-invariant.
    """

    def __init__(
        self,
        graph: Graph | DeviceGraph | PullGraph,
        *,
        engine: str = "push",
        block: int = 1024,
    ):
        from ..graph.relay import RelayGraph

        self.engine = engine
        self.device_graph = None
        self._old2new = None  # relabeling (relay only)
        if engine == "push":
            if isinstance(graph, (PullGraph, RelayGraph)):
                raise ValueError("engine='push' needs a Graph or DeviceGraph")
            self.device_graph = (
                graph
                if isinstance(graph, DeviceGraph)
                else build_device_graph(graph, block=block)
            )
            if self.device_graph.num_shards != 1:
                raise ValueError("sharded DeviceGraph requires the parallel engine")
            self.num_vertices = self.device_graph.num_vertices
            src = jnp.asarray(self.device_graph.src)
            dst = jnp.asarray(self.device_graph.dst)
            # donate_argnums=0: the stepped state is consumed — run()'s
            # loop and every external caller reassign (state = step(state))
            # — so the output reuses the input's buffers instead of
            # doubling the V-sized state HBM per step (IR lint IR001).
            self._step = jax.jit(traced("bfs.push_step")(lambda s: relax_superstep(s, src, dst)), donate_argnums=0)
        elif engine == "pull":
            pg = graph if isinstance(graph, PullGraph) else build_pull_graph(graph)
            self.num_vertices = pg.num_vertices
            from ..graph.ell import device_ell

            ell0, folds = device_ell(pg)
            self._step = jax.jit(traced("bfs.pull_step")(lambda s: relax_pull_superstep(s, ell0, folds)), donate_argnums=0)
        elif engine == "relay":
            eng = RelayEngine(graph)
            self._relay = eng
            self.num_vertices = eng.relay_graph.num_vertices
            self._old2new = eng.relay_graph.old2new
            self._step = eng.step
        else:
            raise ValueError(
                f"unknown engine {engine!r}; use 'push', 'pull' or 'relay'"
            )
        if engine != "relay":
            self._init = jax.jit(
                functools.partial(init_state, self.num_vertices)
            )

    def init(self, source: int = 0):
        check_sources(self.num_vertices, source)
        if self.engine == "relay":
            return self._relay.init_state(source)
        return self._init(jnp.int32(source))

    def step(self, state):
        return self._step(state)

    def frontier_size(self, state) -> int:
        if self.engine == "relay":
            return int(
                jax.lax.population_count(state.fwords).sum(dtype=jnp.int32)
            )
        return int(frontier_size(state))

    def to_original(self, state, *, source: int | None = None):
        """Host ``(dist, parent, frontier)`` in ORIGINAL vertex-id space.

        ``source`` (original id) fixes the relay engine's self-parent entry,
        which init writes in relabeled space — REQUIRED for relay (a relay
        parent mapped without it would silently pass the source's relabeled
        id through the slot table, yielding a plausible-looking wrong id —
        ADVICE.md round 2)."""
        state = jax.device_get(state)
        v = self.num_vertices
        if self._old2new is not None:
            if source is None:
                raise ValueError(
                    "to_original requires source= for the relay engine"
                )
            from ..ops.relay import unpack_std

            rg = self._relay.relay_graph
            dist = np.asarray(state.dist)[self._old2new]
            if self._relay.expansion == "mxu":
                # mxu-arm parent VALUES are already original ids (the
                # expansion's min-key candidates) — slot-mapping them
                # would gather nonsense through src_l1.
                parent = np.asarray(state.parent)[self._old2new].copy()
            else:
                parent = slots_to_parent(
                    np.asarray(state.parent), rg.src_l1
                )[self._old2new]
            fbits = np.asarray(
                unpack_std(jnp.asarray(state.fwords), rg.vr)
            ).astype(bool)[self._old2new]
            parent[source] = source
            return dist, parent, fbits
        dist = np.asarray(state.dist[:v])
        parent = np.asarray(state.parent[:v])
        frontier = np.asarray(state.frontier[:v])
        return dist, parent, frontier

    def run(self, source: int = 0, *, max_levels: int | None = None, observer=None):
        """Run to termination; ``observer(level, state)`` is called after each
        superstep (metrics/dump/checkpoint hook)."""
        state = self.init(source)
        limit = max_levels if max_levels is not None else self.num_vertices
        while bool(state.changed) and int(state.level) < limit:
            state = self.step(state)
            if observer is not None:
                observer(int(state.level), state)
        num_levels = int(state.level)
        dist, parent, _ = self.to_original(state, source=source)
        return BfsResult(dist=dist, parent=parent, num_levels=num_levels)
