from .bfs import bfs, BfsResult, SuperstepRunner  # noqa: F401
from .multisource import bfs_multi, MultiBfsResult, collapse_multi_source  # noqa: F401
