"""The pinned host tile store: per-superblock operand slabs (ISSUE 18).

An :class:`~bfs_tpu.graph.adj_tiles.AdjTiles` layout (built in-process or
loaded from the cache/layout.py sidecar bundle — possibly memmapped) is
cut once, at store init, into per-column-superblock operand slabs held in
plain host RAM:

  * ``tiles``     uint32[ntp_g, 128, 4] — the superblock's real tiles,
                  padded to a power-of-two count with INERT tiles (zero
                  bits, ``row_idx = rtp // TILE`` = the guaranteed-zero
                  frontier pad block, ``col_local = SB_TILES`` = the
                  dropped overflow segment) so the per-superblock
                  expansion program compiles once per pow2 bucket, not
                  once per superblock;
  * ``row_idx``   int32[ntp_g] — frontier row-block per tile (the 4-word
                  block the kernel's early-out reads);
  * ``col_local`` int32[ntp_g] — column tile WITHIN the superblock
                  (``col_id - g * SB_TILES``), the segment-min key.

Each slab carries a blake2b-16 CONTENT fingerprint over its padded bytes
— the HBM cache's key (content-addressed: two identical superblocks, e.g.
two empty ones, share one device entry) and the corruption oracle the
cache's verify-on-hit re-hashes against.

The store also precomputes each superblock's unique row-block set: the
demand-derivation input (prefetch.demand_set) — a superblock whose every
row block is dead is, by the kernel's own per-tile early-out predicate,
untouched by the superstep, so its tiles need never reach HBM.

``keys2d`` (O(V), like the packed state) stays a single resident operand;
only the O(E) tile slabs stream.
"""

from __future__ import annotations

import hashlib

import numpy as np

from ..graph.adj_tiles import (
    SB_TILES,
    SB_VERTS,
    TILE,
    TILE_WORDS,
    AdjTiles,
    sb_span,
)

__all__ = ["HostTileStore", "superblock_fingerprint"]


def superblock_fingerprint(tiles: np.ndarray, row_idx: np.ndarray,
                           col_local: np.ndarray) -> str:
    """Content key of one PADDED superblock slab: blake2b-16 over the
    dtype/shape-tagged bytes of the three operand arrays — the same
    derivation for the host slab at store init and for device bytes
    pulled back by the cache's verify-on-hit, so a single flipped bit on
    either side is a key mismatch."""
    h = hashlib.blake2b(digest_size=16)
    for a in (tiles, row_idx, col_local):
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(memoryview(a))
    return h.hexdigest()


def _pow2_pad(n: int) -> int:
    """Smallest power of two >= max(n, 1) — the compile-count bound: the
    per-superblock expansion program is keyed on the padded tile count,
    so a graph compiles at most log2(largest superblock) programs."""
    p = 1
    while p < max(int(n), 1):
        p <<= 1
    return p


class HostTileStore:
    """Immutable per-superblock slabs of one tile layout, in host RAM.

    Single-threaded by design (the streamed superstep loop is one host
    thread driving async device work); nothing here takes a lock."""

    def __init__(self, at: AdjTiles):
        self.rows = int(at.rows)
        self.cols = int(at.cols)
        self.rtp = int(at.rtp)
        self.vtp = int(at.vtp)
        self.nt = int(at.nt)
        self.num_superblocks = int(at.vtp // SB_VERTS)
        self.keys2d = np.ascontiguousarray(at.keys2d, dtype=np.uint32)
        pad_block = self.rtp // TILE  # the guaranteed-zero frontier block
        self._tiles: list[np.ndarray] = []
        self._row_idx: list[np.ndarray] = []
        self._col_local: list[np.ndarray] = []
        self._row_blocks: list[np.ndarray] = []
        self._fingerprints: list[str] = []
        self._real_tiles: list[int] = []
        for g in range(self.num_superblocks):
            lo, hi = sb_span(at, g)
            nt_g = hi - lo
            ntp_g = _pow2_pad(nt_g)
            tiles = np.zeros((ntp_g, TILE, TILE_WORDS), dtype=np.uint32)
            row_idx = np.full(ntp_g, pad_block, dtype=np.int32)
            col_local = np.full(ntp_g, SB_TILES, dtype=np.int32)
            if nt_g:
                tiles[:nt_g] = at.tiles[lo:hi]
                row_idx[:nt_g] = at.row_idx[lo:hi]
                col_local[:nt_g] = (
                    np.asarray(at.col_id[lo:hi], dtype=np.int32)
                    - g * SB_TILES
                )
            self._tiles.append(tiles)
            self._row_idx.append(row_idx)
            self._col_local.append(col_local)
            self._row_blocks.append(np.unique(row_idx[:nt_g]))
            self._fingerprints.append(
                superblock_fingerprint(tiles, row_idx, col_local)
            )
            self._real_tiles.append(int(nt_g))

    # ------------------------------------------------------------ geometry --
    def real_tiles(self, g: int) -> int:
        return self._real_tiles[g]

    def pad_tiles(self, g: int) -> int:
        return int(self._tiles[g].shape[0])

    def row_blocks(self, g: int) -> np.ndarray:
        """Ascending unique frontier row blocks superblock ``g`` reads."""
        return self._row_blocks[g]

    def fingerprint(self, g: int) -> str:
        return self._fingerprints[g]

    def fetch(self, g: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The padded host slab ``(tiles, row_idx, col_local)`` — what the
        cache uploads on a miss (and re-uploads on a corrupt hit)."""
        return self._tiles[g], self._row_idx[g], self._col_local[g]

    def sb_bytes(self, g: int) -> int:
        """Device bytes of superblock ``g``'s padded slab — the cache's
        budget-accounting unit."""
        return int(
            self._tiles[g].nbytes + self._row_idx[g].nbytes
            + self._col_local[g].nbytes
        )

    @property
    def nbytes(self) -> int:
        """Host bytes pinned by the slabs + the resident key table."""
        return (
            sum(self.sb_bytes(g) for g in range(self.num_superblocks))
            + int(self.keys2d.nbytes)
        )

    def report(self) -> dict:
        """JSON-ready store shape for the stream ledger / cache_warm."""
        return {
            "num_superblocks": self.num_superblocks,
            "real_tiles": int(self.nt),
            "host_store_bytes": int(self.nbytes),
            "max_superblock_bytes": max(
                self.sb_bytes(g) for g in range(self.num_superblocks)
            ),
        }
