"""Beyond-HBM traversal: frontier-driven superblock streaming (ISSUE 18).

Every resident arm caps a single chip near s26-s27 because the adjacency
must fit HBM next to the packed state.  The PR 15 tile layout
(graph/adj_tiles.py) was built to be independently loadable: tiles sort
by (column superblock, row block), ``sb_indptr`` bounds each superblock's
span, and the kernel's per-tile empty-frontier early-out means the
frontier's live ROW BLOCKS fully determine which superblocks a superstep
can touch.  This package exploits exactly that:

  * :mod:`.store`    — the pinned HOST tile store: per-superblock operand
                       slabs (pow2-padded, content-fingerprinted) cut from
                       an AdjTiles layout or its sidecar bundle;
  * :mod:`.cache`    — the content-addressed HBM superblock cache: an LRU
                       budget-accounted like the serve registry
                       (``BFS_TPU_STREAM_CACHE_GB``), corrupt or evicted
                       entries re-fetched from host and counted;
  * :mod:`.prefetch` — the hoisted demand predicate (the kernel early-out
                       computed host-side per level) and the
                       one-superblock-lookahead prefetch iterator;
  * :mod:`.runner`   — the streamed superstep loop: bit-identical
                       dist/parent and direction schedule to the resident
                       mxu arm (uint32 min is exact and order-free, so
                       the per-superblock decomposition cannot perturb a
                       byte), resumable via the PR 14 superstep
                       checkpoints (the carry keys are the segment
                       program's own).

Wired as ``BFS_TPU_TILES=resident|stream|auto`` through
models/bfs.RelayEngine: packed state stays resident, adjacency does not —
the s28-s30 scale class no resident engine can reach.
"""

from .cache import SuperblockCache
from .prefetch import demand_set, iter_prefetched
from .store import HostTileStore
from .runner import run_streamed

__all__ = [
    "HostTileStore",
    "SuperblockCache",
    "demand_set",
    "iter_prefetched",
    "run_streamed",
]
