"""The content-addressed HBM superblock cache (ISSUE 18).

An LRU over device-resident superblock operand slabs, budget-accounted
exactly like the serve registry's graph-residency map
(serve/registry.py): room is made BEFORE an upload, a single entry larger
than the whole budget is allowed in alone (the documented oversized
allowance), and every eviction lands a trace marker plus a metrics
counter so HBM thrash is visible in the same dashboards.

Keys are the store's CONTENT fingerprints (stream/store.py), which makes
corruption detectable: with verify-on-hit enabled
(``BFS_TPU_STREAM_VERIFY=1``, or ``verify=True``), a hit pulls the device
bytes back and re-hashes them — a mismatch drops the entry, counts a
``corrupt_refetch``, and falls through to the host re-fetch path instead
of expanding against rotten adjacency.  Verify costs a device->host copy
per hit, so it is OFF by default and ON in the pathology tests.

Eviction drops the cache's REFERENCE; an in-flight expand holding the
operands keeps the buffers alive until it retires (the same transient
overshoot semantics as the registry's resident map), so the budget is a
working-set target, not a hard allocator limit.

Lock-free by design: the streamed superstep loop is one host thread
driving async device work, so unlike the registry there is no
cross-thread registration path to guard."""

from __future__ import annotations

import os
from collections import OrderedDict

import numpy as np

from .. import knobs
from .store import HostTileStore, superblock_fingerprint

__all__ = ["SuperblockCache", "stream_verify_enabled"]

#: Counter names every report/delta carries, in ledger order.
COUNTER_KEYS = (
    "hits", "misses", "evictions", "corrupt_refetches", "bytes_streamed",
)


def stream_verify_enabled(verify: bool | None = None) -> bool:
    """``BFS_TPU_STREAM_VERIFY=1`` (an explicit argument wins)."""
    if verify is not None:
        return bool(verify)
    return knobs.get("BFS_TPU_STREAM_VERIFY")


class SuperblockCache:
    """LRU of device superblock slabs under a byte budget."""

    def __init__(self, store: HostTileStore, *,
                 budget_bytes: int | None = None,
                 verify: bool | None = None):
        from ..ops.relay_mxu import stream_cache_budget_bytes

        self.store = store
        self.budget_bytes = (
            stream_cache_budget_bytes()
            if budget_bytes is None
            else int(budget_bytes)
        )
        self.verify = stream_verify_enabled(verify)
        # fingerprint -> (nbytes, device operands, superblock id); order
        # = LRU (the id is reporting provenance — content-addressing may
        # serve one entry to several identical superblocks).
        self._resident: OrderedDict[str, tuple[int, tuple, int]] = (
            OrderedDict()
        )
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.corrupt_refetches = 0
        self.bytes_streamed = 0

    # ----------------------------------------------------------- accounting --
    def resident_bytes(self) -> int:
        return sum(n for n, _ops, _g in self._resident.values())

    def counters(self) -> dict:
        """Current counter snapshot — the runner diffs consecutive
        snapshots into the per-level stream ledger rows."""
        return {k: int(getattr(self, k)) for k in COUNTER_KEYS}

    def report(self) -> dict:
        """JSON-ready cache summary for ``details.stream``."""
        return {
            "budget_bytes": int(self.budget_bytes),
            "resident_bytes": int(self.resident_bytes()),
            "resident_entries": len(self._resident),
            "verify": bool(self.verify),
            **self.counters(),
        }

    # ---------------------------------------------------------------- fetch --
    def get(self, g: int) -> tuple:
        """Device operands ``(tiles, row_idx, col_local)`` for superblock
        ``g`` — LRU hit, or host fetch + upload with room made first."""
        import jax.numpy as jnp

        key = self.store.fingerprint(g)
        ent = self._resident.get(key)
        if ent is not None:
            if self.verify and not self._verify_entry(key, ent):
                # Rotten device bytes: drop our reference and fall
                # through to the host re-fetch — counted, never crashed,
                # never silently expanded against.
                self._drop_corrupt(key, ent, g)
            else:
                self._resident.move_to_end(key)
                # A hit still settles any transient overshoot left by an
                # oversized entry or an in-flight-pinned deferral.
                self._make_room(0, keep=key)
                self.hits += 1
                return ent[1]
        tiles, row_idx, col_local = self.store.fetch(g)
        nbytes = self.store.sb_bytes(g)
        # Room BEFORE the upload (the registry discipline): the budget
        # bounds cache + incoming, not cache-then-oops.
        self._make_room(nbytes, keep=key)
        ops = (
            jnp.asarray(tiles), jnp.asarray(row_idx),
            jnp.asarray(col_local),
        )
        self._resident[key] = (nbytes, ops, int(g))
        self.misses += 1
        self.bytes_streamed += nbytes
        return ops

    # ------------------------------------------------------------- internals --
    def _verify_entry(self, key: str, ent: tuple) -> bool:
        import jax

        _nbytes, ops, _g = ent
        host = [np.asarray(a) for a in jax.device_get(ops)]
        return superblock_fingerprint(*host) == key

    def _drop_corrupt(self, key: str, ent: tuple, g: int) -> None:
        from ..obs import get_registry, instant

        nbytes, _ops, _g = ent
        self._resident.pop(key, None)
        self.corrupt_refetches += 1
        instant("stream.corrupt_refetch", superblock=g, bytes=nbytes)
        get_registry().counter("superblock_corrupt_refetches")

    def _make_room(self, incoming: int, *, keep: str) -> None:
        while (
            self._resident
            and self.resident_bytes() + incoming > self.budget_bytes
        ):
            victim = next(
                (k for k in self._resident if k != keep), None
            )
            if victim is None:
                # ``keep`` alone exceeds the budget: the documented
                # single-oversized-superblock allowance (the registry's
                # rule) — it comes in alone and leaves first.
                return
            self._evict(victim)

    def _evict(self, key: str) -> None:
        from ..obs import get_registry, instant

        nbytes, _ops, g = self._resident.pop(key)
        self.evictions += 1
        instant("stream.evict", superblock=g, bytes=nbytes)
        get_registry().counter("superblock_evictions")
        get_registry().counter("superblock_evicted_bytes", nbytes)
