"""The streamed superstep loop: host-paged adjacency, resident state.

Drives the SAME superstep bodies the segmented relay program
(models/bfs._relay_segment_program, mxu flavor) runs — the per-superstep
direction decision compiles the identical predicate
(direction.frontier_masses_words + take_pull with the sparse-budget
override), the push levels run the engine's own AOT sparse body, and the
pull levels run the mxu expansion DECOMPOSED per column superblock:

    resident:  segment_min over ALL tiles' candidate rows, keyed col_id
    streamed:  per-superblock segment_min over the superblock's tiles,
               keyed col_local, placed at rows [g*128, (g+1)*128)

Superblocks partition the destination columns, uint32 min is exact and
order-free, and an empty segment fills with the sentinel — so the
streamed candidate grid is byte-identical to the resident one for ANY
demand subset that covers every live tile, which is exactly what the
hoisted early-out predicate (prefetch.demand_set) guarantees.  Undemanded
superblocks contribute all-sentinel rows = the grid's initial value;
skipping their transfer perturbs nothing.

Checkpoints: the carry keys are the segment program's own
(RelayEngine.segment_keys), snapshots ride the same
SuperstepCheckpointer epochs, and the restore gate is the shared
restore_arrays — a streamed run can resume a segmented run's epoch and
vice versa, and a SIGKILL mid-traversal resumes with a COLD cache but a
bit-identical schedule (the hysteresis pair travels in the carry, and the
cache holds derived content only).

This is a HOST-DRIVEN loop (per-level demand needs the frontier words
host-side — that is the point of hoisting the predicate), so it is not a
hot region; its jitted sub-programs are module-level lru_cache factories
(the RCD001 discipline).
"""

from __future__ import annotations

import functools
import time as _time

import numpy as np

from ..graph.adj_tiles import SB_TILES, TILE, TILE_WORDS
from .cache import SuperblockCache
from .prefetch import demand_set, iter_prefetched
from .store import HostTileStore

__all__ = ["run_streamed"]


# ---------------------------------------------------------------------------
# Jitted sub-programs (module-level lru_cache factories — RCD001).
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=8)
def _frontier_blocks_program(rows: int, rtp: int):
    """Frontier words -> uint32[rtp//TILE + 1, 4] row blocks, on device
    (the per-tile gather operand; the demand set reads the host twin)."""
    import jax

    from ..ops.relay_mxu import _pad_frontier_words

    @jax.jit
    def prep(fwords):
        return _pad_frontier_words(fwords, rows, rtp).reshape(
            -1, TILE_WORDS
        )

    return prep


@functools.lru_cache(maxsize=8)
def _cand_init_program(vtp: int):
    """The all-sentinel candidate grid uint32[vtp//TILE, TILE] — the
    segment-min identity every undemanded superblock's rows keep."""
    import jax
    import jax.numpy as jnp

    from ..ops.relay_mxu import SENT

    @jax.jit
    def init():
        return jnp.full((vtp // TILE, TILE), SENT, jnp.uint32)

    return init


@functools.lru_cache(maxsize=32)
def _sb_expand_program(ntp_g: int):
    """One superblock's expansion into the candidate grid: the EXACT
    per-tile math of expand_frontier_mxu_xla's ``per_chunk`` (same
    chunked lax.map shape), with the global segment_min replaced by the
    superblock-local one (col_local keys, pad tiles in the dropped
    SB_TILES segment) and a dynamic-slice placement at the superblock's
    output rows.  Keyed on the pow2-padded tile count, so a graph
    compiles one program per bucket.  The grid carry is donated — it is
    dead the moment the placement returns (callers chain
    ``cand2d = prog(cand2d, ...)``)."""
    import jax
    import jax.numpy as jnp

    from ..ops.relay_mxu import SENT

    chunk = min(256, ntp_g)
    nc = ntp_g // chunk

    @functools.partial(jax.jit, donate_argnums=(0,))
    def expand(cand2d, fwp4, keys2d, tiles, row_idx, col_local, g):
        fblk = fwp4[row_idx]  # [ntp_g, 4]
        shifts = jnp.arange(32, dtype=jnp.uint32)

        def per_chunk(args):
            tk, fb, rk = args
            lane = jnp.arange(TILE, dtype=jnp.int32)
            fbits = (fb[:, lane >> 5] >> (lane & 31).astype(jnp.uint32)) & 1
            rowmask = jnp.uint32(0) - fbits  # 0 / ~0 per (tile, u)
            contrib = tk & rowmask[:, :, None]  # [chunk, 128, 4]
            bits = (contrib[:, :, :, None] >> shifts) & 1
            keyrow = keys2d[rk]  # [chunk, 128]
            cand = jnp.min(
                jnp.where(
                    bits != 0,
                    keyrow[:, :, None, None],
                    SENT,
                ),
                axis=1,
            )  # [chunk, 4, 32]
            return cand.reshape(-1, TILE)

        cands = jax.lax.map(
            per_chunk,
            (
                tiles.reshape(nc, chunk, TILE, TILE_WORDS),
                fblk.reshape(nc, chunk, TILE_WORDS),
                row_idx.reshape(nc, chunk),
            ),
        ).reshape(-1, TILE)
        block = jax.ops.segment_min(
            cands, col_local, num_segments=SB_TILES + 1,
            indices_are_sorted=False,
        )[:SB_TILES]
        return jax.lax.dynamic_update_slice(
            cand2d, block, (g * SB_TILES, jnp.int32(0))
        )

    return expand


@functools.lru_cache(maxsize=8)
def _apply_program(packed: bool, cols: int):
    """Candidate grid -> state update: exactly the mxu superstep's apply
    half (ops/relay_mxu.mxu_superstep[_packed] after ``_expand``).  Both
    the state and the grid are donated — each is dead once the superstep
    returns."""
    import jax
    import jax.numpy as jnp

    from ..ops import relay as R
    from ..ops.relax import INT32_MAX
    from ..ops.relay_mxu import SENT

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def apply(st, cand2d):
        cand = cand2d.reshape(-1)[:cols]
        if packed:
            return R.apply_relay_candidates_packed(st, cand)
        cand_i = jnp.where(
            cand == SENT, jnp.int32(INT32_MAX), cand.astype(jnp.int32)
        )
        return R.apply_relay_candidates(st, cand_i)

    return apply


@functools.lru_cache(maxsize=8)
def _decide_program(vr: int, num_adj: int, v_thresh: int, alpha: float,
                    beta: float):
    """The auto-mode per-superstep direction decision — the same
    functions, operands and float32 order the segment program's body
    compiles (frontier_masses_words + the sparse-budget override +
    take_pull), so the streamed schedule replays the resident one
    bit-identically."""
    import jax
    import jax.numpy as jnp

    from ..models.bfs import sparse_budgets
    from ..models.direction import frontier_masses_words, take_pull

    @jax.jit
    def decide(fwords, outdeg, mu, prev):
        fsize, fe = frontier_masses_words(fwords, outdeg, vr)
        m_u = jnp.maximum(mu - fe, 0.0)
        bv, be = sparse_budgets(vr, num_adj)
        budget_ok = (fsize <= bv) & (fe <= jnp.float32(be))
        use_pull = (
            take_pull(prev, fsize, fe, m_u, v_thresh, alpha, beta)
            | ~budget_ok
        )
        return use_pull, m_u

    return decide


@functools.lru_cache(maxsize=8)
def _take_sparse_program(vr: int, num_adj: int):
    """The legacy hybrid's dispatch predicate (mode=push with the sparse
    operands): sparse exactly when the fused ``small()`` holds."""
    import jax

    from ..models.bfs import _take_sparse

    @jax.jit
    def pred(st, outdeg):
        return _take_sparse(st, outdeg, vr, num_adj)

    return pred


@functools.lru_cache(maxsize=4)
def _record_program():
    """Telemetry accumulation — the segment body's own record calls."""
    import jax

    from ..obs import telemetry as T

    @jax.jit
    def rec(occ, dirs, fwords, level, code):
        return (
            T.record_frontier_words(occ, fwords, level),
            T.record_direction(dirs, level, code),
        )

    return rec


# ---------------------------------------------------------------------------
# Engine-attached store/cache memos.
# ---------------------------------------------------------------------------

def _store_for(eng) -> HostTileStore:
    store = getattr(eng, "_stream_store", None)
    if store is None:
        store = HostTileStore(eng.adj_tiles)
        eng._stream_store = store
    return store


def _cache_for(eng, store: HostTileStore,
               budget_bytes: int | None) -> SuperblockCache:
    from ..ops.relay_mxu import stream_cache_budget_bytes

    budget = (
        stream_cache_budget_bytes()
        if budget_bytes is None
        else int(budget_bytes)
    )
    cached = getattr(eng, "_stream_cache", None)
    if cached is None or cached.budget_bytes != budget:
        cached = SuperblockCache(store, budget_bytes=budget)
        eng._stream_cache = cached
    return cached


def _keys2d_for(eng, store: HostTileStore):
    """The resident key-table operand, shipped once per engine (O(V) like
    the state — only the O(E) tile slabs stream)."""
    import jax.numpy as jnp

    dev = getattr(eng, "_stream_keys2d", None)
    if dev is None:
        dev = jnp.asarray(store.keys2d)
        eng._stream_keys2d = dev
    return dev


# ---------------------------------------------------------------------------
# The loop.
# ---------------------------------------------------------------------------

def _counters_delta(after: dict, before: dict) -> dict:
    return {k: int(after[k]) - int(before[k]) for k in after}


def _run_streamed_flavor(eng, store, cache, source: int, ckpt,
                         max_levels: int, packed: bool, telemetry: bool):
    """One carry flavor through the streamed per-level loop; returns
    ``(host RelayState, curve|None, stream ledger)``.  Mirrors
    models/bfs._run_segmented_flavor's carry, checkpoint and finish
    semantics superstep-for-superstep."""
    import jax
    import jax.numpy as jnp

    from ..obs import telemetry as T
    from ..ops import relay as Rops
    from ..ops.packed import PACKED_MAX_LEVELS, packed_cap
    from ..ops.relax import INT32_MAX
    from ..ops.relay_mxu import mxu_static
    from ..resilience.superstep_ckpt import restore_arrays

    rg = eng.relay_graph
    vr = rg.vr
    rows, cols, rtp, vtp, _ntp = mxu_static(eng.adj_tiles)
    outdeg = eng._sparse_tensors[3]
    num_adj = int(eng._sparse_tensors[1].shape[0])
    # The segment program's mode normalization: without the sparse
    # operands the dense mxu body is the only body.
    mode = eng.direction.mode
    sparse = eng.sparse_hybrid
    if mode == "pull" or (mode in ("auto", "push") and not sparse):
        sparse = False
        mode = "pull"
    cap = packed_cap(max_levels) if packed else max_levels
    keys = tuple(eng.segment_keys(packed, telemetry))
    arrays = None
    if ckpt is not None:
        arrays, _shards = restore_arrays(ckpt, packed, require=keys)
    carry = eng.segment_carry(
        source, packed=packed, telemetry=telemetry, restore=arrays
    )
    keys2d_dev = _keys2d_for(eng, store)
    fwp4_prog = _frontier_blocks_program(rows, rtp)
    cand_init = _cand_init_program(vtp)
    apply_prog = _apply_program(packed, cols)
    per_level: list[dict] = []

    def mk_state(c):
        if packed:
            return Rops.PackedRelayState(
                c["pk"], c["fw"], c["level"], c["changed"]
            )
        return Rops.RelayState(
            c["dist"], c["parent"], c["fw"], c["level"], c["changed"]
        )

    level, changed = jax.device_get((carry["level"], carry["changed"]))
    while bool(changed) and int(level) < cap:
        interval = ckpt.interval() if ckpt is not None else cap
        seg_end = min(int(level) + interval, cap)
        t0 = _time.perf_counter()
        while bool(changed) and int(level) < seg_end:
            st = mk_state(carry)
            use_pull = None
            m_u_dev = None
            use_pull_dev = None
            if mode == "auto":
                use_pull_dev, m_u_dev = _decide_program(
                    vr, num_adj, rg.num_vertices, eng.direction.alpha,
                    eng.direction.beta,
                )(carry["fw"], outdeg, carry["mu"], carry["prev"])
                use_pull = bool(jax.device_get(use_pull_dev))
            elif sparse:
                use_pull = not bool(
                    jax.device_get(
                        _take_sparse_program(vr, num_adj)(st, outdeg)
                    )
                )
            before = cache.counters()
            if use_pull is None or use_pull:
                fw_host = np.asarray(jax.device_get(carry["fw"]))
                demand = demand_set(store, fw_host)
                fwp4 = fwp4_prog(carry["fw"])
                cand2d = cand_init()
                for g, ops in iter_prefetched(cache, demand):
                    cand2d = _sb_expand_program(store.pad_tiles(g))(
                        cand2d, fwp4, keys2d_dev, *ops, jnp.int32(g)
                    )
                st2 = apply_prog(st, cand2d)
                row = {"arm": "pull", "demanded": int(demand.shape[0])}
            else:
                st2 = eng._step_body("sparse", st)(
                    st, *eng._sparse_tensors_for(packed)[:3]
                )
                row = {"arm": "push", "demanded": 0}
            if packed:
                carry["pk"] = st2.packed
            else:
                carry["dist"], carry["parent"] = st2.dist, st2.parent
            carry["fw"] = st2.fwords
            carry["level"] = st2.level
            carry["changed"] = st2.changed
            if mode == "auto":
                carry["mu"] = m_u_dev
                carry["prev"] = use_pull_dev
            if telemetry:
                code = (
                    T.DIR_PULL
                    if (use_pull is None or use_pull)
                    else T.DIR_PUSH
                )
                carry["occ"], carry["dirs"] = _record_program()(
                    carry["occ"], carry["dirs"], st2.fwords, st2.level,
                    np.int32(code),
                )
            level, changed = jax.device_get(
                (carry["level"], carry["changed"])
            )
            row.update(
                level=int(level),
                **_counters_delta(cache.counters(), before),
            )
            per_level.append(row)
        seg_s = _time.perf_counter() - t0
        if ckpt is not None:
            # Same disabled-store contract as the segmented driver: the
            # fault boundary is still marked, the O(V) carry pull is not
            # paid.
            snap = {}
            if ckpt.enabled:
                snap = {
                    k: np.asarray(v)
                    for k, v in jax.device_get(carry).items()
                }
                snap["packed_flag"] = np.int32(packed)
            seg_levels = int(level) - (
                seg_end - interval if seg_end - interval >= 0 else 0
            )
            ckpt.save_epoch(int(level), snap)
            ckpt.note_segment(min(seg_levels, interval), seg_s)
    from ..models.bfs import _relay_segment_finish_program

    if packed:
        state_dev = _relay_segment_finish_program(
            tuple(rg.in_classes), rg.vr, True
        )(carry["pk"], carry["fw"], carry["level"], carry["changed"])
    else:
        state_dev = Rops.RelayState(
            carry["dist"], carry["parent"], carry["fw"], carry["level"],
            carry["changed"],
        )
    curve = None
    if telemetry:
        from ..obs.telemetry import (
            direction_schedule,
            edge_curve_from_levels,
            level_curve,
            read_telemetry,
        )

        fe_key = ("segment_edge_curve",)
        fe_fn = eng._compiled.get(fe_key)
        if fe_fn is None:
            fe_fn = jax.jit(edge_curve_from_levels)
            eng._compiled[fe_key] = fe_fn
        fe_dev = fe_fn(
            state_dev.dist, eng._sparse_tensors[3],
            state_dev.dist == INT32_MAX,
        )
        fv, fe, dirs = read_telemetry(
            (carry["occ"], fe_dev, carry["dirs"])
        )
        curve_cap = (
            min(PACKED_MAX_LEVELS, max_levels) if packed else max_levels
        )
        curve = level_curve(fv, fe, cap=curve_cap)
        curve["direction_schedule"] = direction_schedule(
            dirs, mode=eng.direction.mode, alpha=eng.direction.alpha,
            beta=eng.direction.beta,
        )
    ledger = T.stream_report(
        per_level, budget_bytes=cache.budget_bytes, store=store.report(),
        cache=cache.report(),
    )
    return jax.device_get(state_dev), curve, ledger


def run_streamed(eng, source: int = 0, *, ckpt=None,
                 max_levels: int | None = None, telemetry: bool = False,
                 cache_budget_bytes: int | None = None):
    """Streamed single-source BFS on a forced-mxu RelayEngine: adjacency
    paged per superblock from the host store under the
    ``BFS_TPU_STREAM_CACHE_GB`` budget (``cache_budget_bytes`` forces),
    dist/parent and the direction schedule bit-identical to the resident
    arms, resumable from ``ckpt`` epochs.  Returns a BfsResult, or
    ``(BfsResult, curve)`` with ``telemetry``; the stream ledger
    (per-level bytes/hit/miss/evict rows) lands on
    ``eng.stream_report``."""
    from ..ops.packed import packed_truncated

    if eng.expansion != "mxu":
        raise ValueError(
            "streamed traversal needs the mxu expansion arm "
            "(BFS_TPU_EXPANSION=mxu / expansion='mxu')"
        )
    rg = eng.relay_graph
    max_levels = int(max_levels) if max_levels is not None else rg.vr
    store = _store_for(eng)
    cache = _cache_for(eng, store, cache_budget_bytes)
    packed = eng.packed
    state, curve, ledger = _run_streamed_flavor(
        eng, store, cache, source, ckpt, max_levels, packed, telemetry
    )
    if packed and packed_truncated(state.changed, state.level, max_levels):
        # Deeper than the packed level field: same detect-and-rerun
        # contract as run()/run_segmented (packed epochs cannot feed the
        # unpacked re-run).
        if ckpt is not None:
            ckpt.clear()
        state, curve, ledger = _run_streamed_flavor(
            eng, store, cache, source, ckpt, max_levels, False, telemetry
        )
    if ckpt is not None:
        ckpt.clear()
    eng.stream_report = ledger
    result = eng._to_result(state, source)
    if telemetry:
        return result, curve
    return result
