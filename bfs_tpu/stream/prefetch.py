"""Frontier-driven demand + prefetch for the streamed arm (ISSUE 18).

The mxu kernel's per-tile early-out (ops/relay_mxu.py) skips a tile —
before its 2 KB DMA is even issued — iff the tile's 4-word frontier block
is all zero.  :func:`demand_set` HOISTS exactly that predicate out of the
kernel: pad the frontier words the way ``_pad_frontier_words`` does,
reshape to row blocks, and a superblock is DEMANDED iff any of its tiles'
row blocks is live.  Undemanded superblocks expand to all-sentinel
candidate rows (the segment-min identity), so skipping their transfer is
bit-free: the streamed candidate grid matches the resident expansion's
bytes exactly (tests/test_stream.py pins demand against the brute-force
per-tile predicate on star/path/gnm/rmat).

:func:`iter_prefetched` is the overlap half: a one-superblock lookahead
that issues the NEXT slab's ``cache.get`` (an async host->HBM upload —
JAX dispatch returns before the copy lands) before yielding the current
one, so the copy rides under the previous block's expand instead of
serializing after it.
"""

from __future__ import annotations

import numpy as np

from ..graph.adj_tiles import TILE, TILE_WORDS
from .cache import SuperblockCache
from .store import HostTileStore

__all__ = ["frontier_blocks", "demand_set", "iter_prefetched"]


def frontier_blocks(fwords: np.ndarray, rtp: int) -> np.ndarray:
    """Host twin of ``_pad_frontier_words``: frontier words padded to the
    row space + one zero pad block, reshaped uint32[rtp//TILE + 1, 4] —
    row ``b`` is exactly the block the kernel's early-out reads for a
    tile with ``row_idx == b``."""
    fw = np.asarray(fwords, dtype=np.uint32).reshape(-1)
    want = rtp // 32 + TILE // 32
    out = np.zeros(want, dtype=np.uint32)
    out[: fw.shape[0]] = fw
    return out.reshape(-1, TILE_WORDS)


def demand_set(store: HostTileStore, fwords: np.ndarray) -> np.ndarray:
    """Ascending superblock ids this frontier can touch: superblock ``g``
    is demanded iff any of its tiles' frontier row blocks is nonzero —
    the kernel early-out predicate, evaluated per superblock instead of
    per tile.  An empty superblock (no real tiles) is never demanded."""
    blocks = frontier_blocks(fwords, store.rtp)
    live = (blocks != 0).any(axis=1)
    out = [
        g
        for g in range(store.num_superblocks)
        if store.real_tiles(g) and bool(live[store.row_blocks(g)].any())
    ]
    return np.asarray(out, dtype=np.int32)


def iter_prefetched(cache: SuperblockCache, demand):
    """Yield ``(g, device_operands)`` over the demand set with a
    one-superblock lookahead: the next slab's upload is dispatched before
    the current one is yielded, so the host->HBM copy overlaps the
    consumer's expand of the current block (both are async dispatches;
    the device interleaves them)."""
    it = iter(demand)
    try:
        g = next(it)
    except StopIteration:
        return
    ops = cache.get(int(g))
    for nxt in it:
        nxt_ops = cache.get(int(nxt))  # in flight under g's expand
        yield int(g), ops
        g, ops = nxt, nxt_ops
    yield int(g), ops
