"""Version-spanning ``shard_map`` / ``pjit`` compat shim (ROADMAP item 1).

JAX moved the mesh SPMD surface twice across the versions this repo must
run on:

  * **jax >= 0.6** exposes ``jax.shard_map`` whose manual axes are named
    POSITIVELY via ``axis_names={...}`` and whose replication transfer
    uses ``jax.lax.pcast(..., to="varying")``.
  * **jax 0.4.x** (the harness container pins 0.4.37) has only
    ``jax.experimental.shard_map.shard_map`` whose manual axes are named
    NEGATIVELY via ``auto=frozenset(...)`` (axes left automatic), whose
    replication checker predates ``pcast``, and which rejects the
    ``axis_names`` kwarg outright — the exact seed-identical 40-test
    failure tier-1 carried through PRs 1-6.

This module is the ONE translation point: every mesh program in
:mod:`bfs_tpu.parallel.sharded` (and the mesh tests) calls
:func:`shard_map` / :func:`pcast_varying` from here instead of touching
the jax API directly.

Old-API semantics: the sharded programs either communicate over every
mesh axis they run on or are simply replicated along the unused axis
(the ``axis_names={GRAPH_AXIS}`` single-source programs never touch
``batch``), so the old call runs FULLY MANUAL over all mesh axes with
``check_rep=False`` — the positive/negative axis-naming difference and
the missing ``pcast`` both disappear: a value that new jax must
explicitly pcast to "varying" before a ``while_loop`` carry is simply
not rep-checked on the old path, and an axis absent from an out_spec
means "replicated along it" under both APIs.
"""

from __future__ import annotations

import jax

try:  # JAX >= 0.6 exposes shard_map at top level (axis_names API)
    from jax import shard_map as _shard_map_new

    _HAS_AXIS_NAMES_API = True
except ImportError:  # pragma: no cover - exercised on jax 0.4.x only
    from jax.experimental.shard_map import shard_map as _shard_map_old

    _HAS_AXIS_NAMES_API = False

try:  # jax.experimental.pjit is the pre-unification entry point
    from jax.experimental.pjit import pjit as _pjit
except ImportError:  # pragma: no cover - pjit folded into jax.jit
    _pjit = jax.jit

#: ``pjit`` resolved once at import: modern jax unifies it into
#: ``jax.jit`` (in_shardings/out_shardings kwargs); 0.4.x still ships the
#: experimental entry point with the same signature.
pjit = _pjit


def has_axis_names_api() -> bool:
    """True when this jax exposes ``jax.shard_map`` (the axis_names API)."""
    return _HAS_AXIS_NAMES_API


def shard_map_available() -> bool:
    """True when SOME shard_map exists (it does on every jax this repo
    supports — kept for symmetric test gating; the mesh tests used to skip
    on :func:`has_axis_names_api`, which the shim makes unnecessary)."""
    return True


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None):
    """``shard_map`` spanning both APIs.

    ``axis_names`` carries the NEW API's semantics: the set of mesh axes
    the body is manual over (None = all of them).  On old jax the program
    runs fully manual over every mesh axis with ``check_rep=False`` — see
    the module docstring for why that is equivalent for this repo's
    programs (no partial-auto program exists here; an axis outside
    ``axis_names`` is never communicated over, only replicated along).
    """
    if _HAS_AXIS_NAMES_API:
        kwargs = {}
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return _shard_map_new(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    return _shard_map_old(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=False,
    )


def pcast_varying(x, axes):
    """``jax.lax.pcast(x, axes, to="varying")`` where it exists; identity
    on old jax (whose ``check_rep=False`` path never tracks replication,
    so there is nothing to cast — the carry/body rep mismatch pcast fixes
    on new jax cannot arise)."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, tuple(axes), to="varying")


def pcast_carry(tree, axes):
    """:func:`pcast_varying` mapped over a pytree of loop-carry leaves.

    The sharded relay's carry grew replicated-initialized leaves whose
    BODY outputs derive from graph-axis-varying values (the telemetry
    accumulators fed the all-gathered frontier words, the Beamer
    ``mu``/``prev`` fed the frontier masses): new jax's replication
    checker requires the init side of such a ``while_loop`` carry to be
    cast to "varying" up front, exactly like the frontier words
    themselves.  Identity on jax 0.4.x (same contract as
    :func:`pcast_varying`)."""
    return jax.tree_util.tree_map(lambda x: pcast_varying(x, axes), tree)
