"""Mesh-sharded BFS: `shard_map` over edge shards with ICI all-reduce merge.

TPU-native re-design of the reference's only parallelism strategy — Spark
data-parallel map/shuffle over hash-partitioned Vertex records
(BfsSpark.java:66-108, SURVEY.md §2.4/§2.5):

  * Spark's hash-partitioned RDD blocks  ->  balanced dst-sorted edge shards,
    one per device along the mesh's ``graph`` axis (csr.build_device_graph).
  * The shuffle (`reduceByKey`) + driver collect (`collectAsMap`)  ->  one
    ``lax.pmin`` all-reduce of the per-destination candidate-parent array per
    superstep, riding ICI.  No host round-trip: the whole superstep loop is
    a single compiled program, and dist/parent/frontier stay replicated
    device-resident.
  * The driver's file-based termination scan (BfsSpark.java:117)  ->  an
    on-device replicated scalar.

A second mesh axis ``batch`` shards the sources axis of batched multi-source
BFS (data parallelism); ``graph`` is the model/context-parallel analogue.
This is the scaling design for graphs that exceed one chip's HBM: per-device
edge memory is E/n while V-sized state is replicated (SURVEY.md §5
long-context row: graph sharding is this workload's context parallelism).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import Mesh, PartitionSpec as P

from .compat import pcast_carry, pcast_varying, shard_map as _shard_map
from .. import knobs

from ..graph.csr import DeviceGraph, Graph, build_device_graph
from ..graph.ell import ShardedPullGraph, build_sharded_pull_graph
from ..models.bfs import BfsResult, check_sources
from ..models.multisource import MultiBfsResult
from ..ops.pull import (
    pack_frontier_block,
    pull_candidates_rows,
    unpack_frontier_blocks,
)
from ..ops.relax import (
    INT32_MAX,
    BfsState,
    init_batched_state,
    init_state,
    relax_superstep,
    relax_superstep_batched,
)

GRAPH_AXIS = "graph"
BATCH_AXIS = "batch"


def make_mesh(
    graph: int | None = None,
    batch: int = 1,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a ``(batch, graph)`` mesh.  ``graph=None`` uses all remaining
    devices.  Single-host multi-device or multi-host both work — the mesh is
    the cluster-bootstrap analogue of the Spark master/worker setup
    (service.properties ip/port + README.md:27-31), minus the processes."""
    devices = list(devices if devices is not None else jax.devices())
    if graph is None:
        graph = len(devices) // batch
    if batch * graph > len(devices):
        raise ValueError(f"mesh {batch}x{graph} needs {batch * graph} devices, have {len(devices)}")
    arr = np.asarray(devices[: batch * graph]).reshape(batch, graph)
    return Mesh(arr, (BATCH_AXIS, GRAPH_AXIS))


def _graph_shards(mesh: Mesh) -> int:
    return mesh.shape[GRAPH_AXIS]


def _reject_wrong_layout_for_push(graph) -> None:
    from ..graph.relay import ShardedRelayGraph

    if isinstance(graph, ShardedPullGraph):
        raise ValueError("a ShardedPullGraph only runs on engine='pull'")
    if isinstance(graph, ShardedRelayGraph):
        raise ValueError("a ShardedRelayGraph only runs on engine='relay'")


def _prepare(graph: Graph | DeviceGraph, mesh: Mesh, block: int) -> DeviceGraph:
    n = _graph_shards(mesh)
    if isinstance(graph, DeviceGraph):
        if graph.num_shards != n:
            raise ValueError(
                f"DeviceGraph has {graph.num_shards} shards but mesh axis "
                f"'{GRAPH_AXIS}' has {n}; rebuild with build_device_graph(num_shards={n})"
            )
        return graph
    return build_device_graph(graph, num_shards=n, block=block)


@functools.partial(
    jax.jit, static_argnames=("mesh", "num_vertices", "max_levels")
)
def _bfs_sharded_fused(src, dst, source, *, mesh, num_vertices, max_levels):
    def inner(src_blk, dst_blk, source):
        src_e = src_blk.reshape(-1)
        dst_e = dst_blk.reshape(-1)
        state = init_state(num_vertices, source)

        def cond(s: BfsState):
            return s.changed & (s.level < max_levels)

        def body(s: BfsState):
            return relax_superstep(s, src_e, dst_e, axis_name=GRAPH_AXIS)

        return jax.lax.while_loop(cond, body, state)

    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(GRAPH_AXIS, None), P(GRAPH_AXIS, None), P()),
        out_specs=BfsState(P(), P(), P(), P(), P()),
        axis_names={GRAPH_AXIS},
    )
    return fn(src, dst, source)


def _init_block_state(source, block: int):
    """Per-device dist/parent init over the owned vertex block (ids are
    GLOBAL: ``axis_index*block + local``); the source's parent self-entry is
    in whatever id space ``source`` lives in — host wrappers fix it up."""
    lo = jax.lax.axis_index(GRAPH_AXIS).astype(jnp.int32) * block
    ids_local = lo + jnp.arange(block, dtype=jnp.int32)
    is_src = ids_local == source
    dist = jnp.where(is_src, jnp.int32(0), INT32_MAX)
    parent = jnp.where(is_src, source, jnp.int32(-1))
    return dist, parent


def _packed_source_frontier(source, block: int, n: int):
    """Initial global standard-packed frontier words with only the source
    bit set.  Every device computes it identically (no collective), then
    `pcast` aligns the carry with the all_gather-refreshed words of the loop
    body, which are graph-axis-varying."""
    fwords = (
        jnp.zeros((n * block // 32,), jnp.uint32)
        .at[source >> 5]
        .set(jnp.uint32(1) << (source & 31).astype(jnp.uint32))
    )
    return pcast_varying(fwords, (GRAPH_AXIS,))


def _apply_block_candidates(carry, cand, nw: int):
    """Shared superstep tail for block-partitioned engines: mark newly
    reached owned vertices, advance the level, exchange the new frontier as
    a bit-packed all-gather, and all-reduce the termination flag."""
    dist, parent, _, level, _ = carry
    improved = (cand != INT32_MAX) & (dist == INT32_MAX)
    level = level + 1
    dist = jnp.where(improved, level, dist)
    parent = jnp.where(improved, cand, parent)
    fwords = jax.lax.all_gather(
        pack_frontier_block(improved, nw), GRAPH_AXIS, tiled=True
    )
    changed = jax.lax.pmax(improved.any().astype(jnp.int32), GRAPH_AXIS) > 0
    return dist, parent, fwords, level, changed


@functools.partial(jax.jit, static_argnames=("mesh", "block", "max_levels"))
def _bfs_sharded_pull_fused(ell0, folds, source, *, mesh, block, max_levels):
    """Vertex-partitioned pull BFS: per-device ELL over owned destinations,
    replicated frontier refreshed by a bit-packed all-gather (1 bit/vertex
    over ICI per superstep — vs the full int32[V+1] `pmin` of the push
    formulation, a 256x smaller exchange), dist/parent fully distributed."""
    n = mesh.shape[GRAPH_AXIS]
    vtot = n * block
    nw = block // 32

    def inner(ell0_blk, folds_blk, source):
        ell0_blk = ell0_blk[0]
        folds_blk = tuple(f[0] for f in folds_blk)
        dist, parent = _init_block_state(source, block)
        fwords = _packed_source_frontier(source, block, n)
        gids = jnp.arange(vtot, dtype=jnp.int32)
        inf1 = jnp.full((1,), INT32_MAX, dtype=jnp.int32)

        def cond(carry):
            _, _, _, level, changed = carry
            return changed & (level < max_levels)

        def body(carry):
            bits = unpack_frontier_blocks(carry[2], n, nw)
            ftab_ext = jnp.concatenate([jnp.where(bits, gids, INT32_MAX), inf1])
            cand = pull_candidates_rows(ftab_ext, ell0_blk, folds_blk, block)
            return _apply_block_candidates(carry, cand, nw)

        dist, parent, _, level, _ = jax.lax.while_loop(
            cond, body, (dist, parent, fwords, jnp.int32(0), jnp.bool_(True))
        )
        return dist, parent, level

    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            P(GRAPH_AXIS, None, None),
            tuple(P(GRAPH_AXIS, None, None) for _ in folds),
            P(),
        ),
        out_specs=(P(GRAPH_AXIS), P(GRAPH_AXIS), P()),
        axis_names={GRAPH_AXIS},
    )
    return fn(ell0, folds, source)


def _relay_candidates_shard(
    fwords_global, vperm_blk, net_blk, valid_blk, *, static
):
    """One shard's gather-free candidate pipeline (v4): global standard-
    packed frontier words -> this shard's per-owned-vertex min active L1
    slot (unpacked path) or min active within-row RANK as
    ``uint32 | PACKED_SENTINEL`` (packed path — ops/relay.rowmin_ranks,
    the masked row-min over valid slots only).  With v4's standard packing
    the all-gathered words ARE the global frontier in vperm element order
    (relabeling is shard-major), so they feed the butterflies directly
    with no repacking.

    With ``use_pallas`` in ``static`` the networks run as the SAME fused
    3-pass Pallas kernels as the single-chip engine (ops/relay_pallas.py) —
    inside ``shard_map`` a Pallas call is a per-device kernel, so the mesh
    path no longer pays the per-stage launch train (~55 x ~0.4 ms/superstep
    on real chips — VERDICT r3 weak #5); mask operands are then the
    per-pass prepared arrays (tuples), not the flat stream."""
    from ..ops import relay as R

    (block, vperm_size, vperm_table, out_classes, out_space, net_table,
     net_size, in_classes, n, use_pallas, packed, _expansion) = static
    if use_pallas:
        from ..ops import relay_pallas as RP
    nw = block // 32
    zpad = jnp.zeros(vperm_size // 32 - n * nw, jnp.uint32)
    fw = jnp.concatenate([fwords_global, zpad])
    if use_pallas and isinstance(vperm_blk, tuple):
        y = RP.apply_benes_fused(
            fw, vperm_blk, RP.pass_static(vperm_table, vperm_size),
            vperm_size, vma={GRAPH_AXIS},
        )
    else:
        y = R.apply_benes_std(fw, vperm_blk, vperm_table, vperm_size)
    l2 = R.broadcast_l2(y, out_classes, net_size, out_space)
    if use_pallas and isinstance(net_blk, tuple):
        l1 = RP.apply_benes_fused(
            l2, net_blk, RP.pass_static(net_table, net_size),
            net_size, vma={GRAPH_AXIS},
        )
    else:
        l1 = R.apply_benes_std(l2, net_blk, net_table, net_size)
    if packed:
        return R.rowmin_ranks(l1, valid_blk, in_classes, block)
    return R.rowmin_candidates(l1, valid_blk, in_classes, block)


def _sharded_relay_static(srg, n: int, use_pallas: bool = False,
                          packed: bool = False,
                          expansion: tuple = ("gather",)):
    """The sharded program's hashable static tuple.  ``expansion`` is the
    arm element (ISSUE 15): ``('gather',)`` or ``('mxu', geometry,
    use_kernel)`` — appended last, read back via :func:`_static_parts`."""
    return (
        srg.block, srg.vperm_size, srg.vperm_table, tuple(srg.out_classes),
        srg.out_space, srg.net_table, srg.net_size, tuple(srg.in_classes), n,
        use_pallas, packed, expansion,
    )


def _static_parts(static) -> tuple:
    """(block, in_classes, packed, expansion) from the static tuple."""
    return static[0], static[7], static[10], static[11]


def _mxu_candidates_shard(fw_global, tile_blk, *, expansion, packed):
    """One shard's MXU candidate pipeline (ISSUE 15): the all-gathered
    global frontier words against this shard's (global src x local dst)
    adjacency tiles — min ORIGINAL source id per owned destination, in
    the shared candidate format (``uint32 | PACKED_SENTINEL`` packed,
    ``int32 | INT32_MAX`` unpacked) so the body-agnostic superstep tail
    (sieve, exchange, state update) is untouched."""
    from ..ops import relay_mxu as RM

    _, geo, use_kernel = expansion
    rows, cols, rtp, vtp, _ntp = geo
    tiles, row_idx, col_id, sb_indptr, keys2d = tile_blk
    if use_kernel:
        cand = RM.expand_frontier_mxu(
            fw_global, (tiles, row_idx, col_id, sb_indptr, keys2d),
            rows=rows, cols=cols, rtp=rtp, vtp=vtp,
        )
    else:
        cand = RM.expand_frontier_mxu_xla(
            fw_global, (tiles, row_idx, col_id, sb_indptr, keys2d),
            rows=rows, cols=cols, rtp=rtp, vtp=vtp,
        )
    if packed:
        return cand
    return jnp.where(
        cand == jnp.uint32(0xFFFFFFFF),
        jnp.int32(INT32_MAX), cand.astype(jnp.int32),
    )


def _resolve_sharded_applier(applier: str) -> bool:
    """'auto' -> fused Pallas on TPU backends (sizes permitting), XLA
    elsewhere; 'pallas'/'xla' force.  No per-init probe here — the sharded
    program is AOT-compiled once per mesh and the single-chip probe's
    selection applies to the same kernels."""
    from ..ops.relay_pallas import pallas_enabled

    if applier == "pallas":
        return True
    if applier == "xla":
        return False
    if applier != "auto":
        raise ValueError(
            f"unknown applier {applier!r}; use 'auto', 'pallas' or 'xla'"
        )
    return pallas_enabled()


def _sharded_relay_mask_args(srg, use_pallas: bool):
    """Device mask operands, stacked over the shard axis.  Pallas form: per
    network a TUPLE of per-pass arrays, each [n_shards, rows, 128] with the
    per-shard rearranged copies (ops/relay_pallas.prepare_pass_masks)."""
    if not use_pallas:
        return jnp.asarray(srg.vperm_masks), jnp.asarray(srg.net_masks)
    from ..ops import relay_pallas as RP

    def prep(masks_all, table, size):
        if not RP.pallas_net_ok(size):
            return jnp.asarray(masks_all)
        per = [
            RP.prepare_pass_masks(np.asarray(masks_all[s]), table, size)
            for s in range(srg.num_shards)
        ]
        return tuple(
            jnp.asarray(np.stack([p[i] for p in per]))
            for i in range(len(per[0]))
        )

    return (
        prep(srg.vperm_masks, srg.vperm_table, srg.vperm_size),
        prep(srg.net_masks, srg.net_table, srg.net_size),
    )


def _strip_shard_dim(x):
    """Remove the leading shard axis from a mask operand (array or tuple of
    per-pass arrays) inside ``shard_map``."""
    return tuple(a[0] for a in x) if isinstance(x, tuple) else x[0]


def _mask_specs(x):
    """Matching in_specs pytree for a mask operand."""
    return (
        tuple(P(GRAPH_AXIS) for _ in x)
        if isinstance(x, tuple)
        else P(GRAPH_AXIS, None)
    )


#: AOT-compiled sharded relay programs (the scoped-vmem compiler options the
#: fused kernels need cannot go through XLA_FLAGS — models/bfs.py).
#: Bounded: oldest executable evicted past 8 entries (keys are
#: graph-specific, so a long-lived process over many graphs/scales would
#: otherwise retain every compiled program forever).
_SHARDED_AOT_CACHE: dict = {}
_SHARDED_AOT_CACHE_MAX = 8


def _sharded_push_candidates(
    fw, adj_indptr, adj_dst, adj_slot, unreached, *,
    gtot: int, block: int, bv: int, be: int, packed: bool,
):
    """Push (sparse gather) candidate producer for one shard: extract the
    GLOBAL frontier list from the all-gathered words, fan out to this
    shard's dst-owned adjacency slice, min-merge per owned destination by
    a (local dst, slot) sort, and emit candidates in the SAME per-owned-
    vertex format as the dense relay pipeline (min L1 slot unpacked, min
    within-row rank ``| PACKED_SENTINEL`` packed) — so the shared
    superstep tail (sieve, exchange, state update) is body-agnostic and
    the two bodies are bit-exact for any schedule.

    ``unreached``: bool[block] — the SIEVE applied at the producer: a
    settled destination never yields a candidate, so its bit can never
    re-enter the exchange.  The shapes are the clamped sparse budgets
    (``bv`` global frontier vertices, ``be`` edges into this shard);
    dispatch guarantees they hold (models/bfs.sparse_budgets — the same
    derivation the predicate uses, so capacity and dispatch can never
    disagree)."""
    from ..models.bfs import _extract_frontier_list
    from ..ops.packed import PACKED_SENTINEL

    flist = _extract_frontier_list(fw, gtot, bv)
    deg = adj_indptr[flist + 1] - adj_indptr[flist]  # 0 at the gtot fill
    cum = jnp.cumsum(deg)
    starts = adj_indptr[flist]
    j = jnp.arange(be, dtype=jnp.int32)
    owner = jnp.searchsorted(cum, j, side="right").astype(jnp.int32)
    owner_c = jnp.clip(owner, 0, bv - 1)
    prev = jnp.where(owner_c > 0, cum[jnp.maximum(owner_c - 1, 0)], 0)
    eidx = starts[owner_c] + (j - prev)
    valid = j < cum[-1]
    eidx = jnp.where(valid, eidx, 0)
    dstv = adj_dst[eidx]  # LOCAL owned ids [0, block)
    slot = adj_slot[eidx]  # L1 slots (unpacked) / within-row ranks (packed)
    dk, sk = jax.lax.sort(
        (jnp.where(valid, dstv, jnp.int32(block)), slot), num_keys=2
    )
    first = (
        jnp.concatenate([jnp.ones(1, bool), dk[1:] != dk[:-1]])
        & (dk < block)
    )
    upd = first & unreached[jnp.clip(dk, 0, block - 1)]
    tgt = jnp.where(upd, dk, jnp.int32(block))  # block = dropped
    if packed:
        return (
            jnp.full(block, PACKED_SENTINEL, jnp.uint32)
            .at[tgt].set(sk.astype(jnp.uint32), mode="drop")
        )
    return (
        jnp.full(block, INT32_MAX, jnp.int32)
        .at[tgt].set(sk, mode="drop")
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "static", "max_levels", "telemetry", "direction",
        "exchange", "sparse",
    ),
)
def _bfs_sharded_relay_fused(
    vperm_masks, net_masks, valid_words, own_words,
    adj_indptr, adj_dst, adj_slot, outdeg, source_new, *,
    mesh, static, max_levels, telemetry: bool = False,
    direction: tuple | None = None, exchange: tuple = ("bitmap", 8),
    sparse: bool = False,
):
    """Vertex-partitioned relay BFS (v4): per-shard Beneš layouts (one
    unified SPMD program, per-device mask data), frontier exchanged
    through the compressed-exchange arms of :mod:`..parallel.exchange`.
    State lives in the GLOBAL RELABELED space — dist/parent fully
    distributed, parent VALUES are per-shard L1 slot indices (converted
    to original src ids on the host, bfs_sharded).

    ``exchange`` is the resolved :class:`~.exchange.ExchangeConfig` key:
    ``flat`` all-gathers the whole owned word range (the oracle),
    ``bitmap`` the sieved real-word table, ``auto``/``delta`` the
    word-list arm with its on-device density fallback.  The superstep is
    structured for OVERLAP: the exchange collective on the new frontier
    words is issued as soon as the improvement mask exists, BEFORE the
    O(V/n) state writes — the gathered words land in a fresh buffer (the
    previous frontier stays live as the candidate pipeline's operand, a
    double-buffered carry), so XLA's scheduler can fly the all-gather
    over the local state update and the termination ``pmax`` rides the
    same window.

    ``direction`` — ``(mode, alpha, beta, V_real, E_real)`` — selects the
    superstep body per level once ``sparse`` ships the per-shard
    dst-owned adjacency: ``pull`` runs the dense relay pipeline every
    superstep, ``push`` the sparse gather wherever the static budgets
    allow (the legacy hybrid dispatch), ``auto`` the Beamer predicate
    (models/direction.py take_pull — the SAME single definition the
    single-chip programs compile, fed the real V/E so the schedule is
    bit-identical to the single-chip relay engine's for the same graph
    and thresholds).  Both bodies emit candidates in one format; the
    decision is a pure function of replicated on-device state (the
    global frontier words), so no collective and no host sync is needed
    to agree on the branch.

    With ``packed`` in ``static`` each shard carries ONE uint32
    ``level:6|rank:26`` word per owned vertex (half the per-superstep
    state HBM bytes), the update is one lexicographic min, and the
    dist/parent-slot outputs are unpacked once at loop exit — the
    exchange ships frontier bits either way.  The loop caps at
    PACKED_MAX_LEVELS; ``changed`` is returned so the host wrapper can
    detect a cap exit and re-run unpacked.

    With ``telemetry`` (static) the carry additionally holds the
    per-level occupancy, direction-schedule, exchange-bytes and
    exchange-arm accumulators (obs/telemetry.py), fed the GLOBAL
    all-gathered frontier words — identical on every shard, so the accs
    stay replicated with no extra collective — and returned as outputs
    4..7 for ONE pull at loop exit."""
    from ..ops.packed import PACKED_SENTINEL, level_word, packed_cap
    from ..ops.relay import pack_std, unpack_relay_packed
    from .exchange import ExchangeConfig, make_exchange

    n = mesh.shape[GRAPH_AXIS]
    block, in_classes, packed, expansion = _static_parts(static)
    mxu = expansion[0] == "mxu"
    nw = block // 32
    gtot = n * block
    cap = packed_cap(max_levels) if packed else max_levels
    ex_cfg = ExchangeConfig(*exchange)
    mode = direction[0] if direction is not None else None
    if mode in ("auto", "push") and not sparse:
        # No adjacency operands shipped: the dense relay is the only
        # body.  Normalized here (not silently at the engine) so the
        # recorded schedule stays honest for any direct program caller.
        mode = None
    if mode in ("auto", "push"):
        from ..models.bfs import sparse_budgets

        # STATIC Python values (jit static_argnames tuple members), cast
        # at trace-build time — never a device sync.
        dir_alpha = float(direction[1])  # bfs_tpu: ok TRC002 static tuple member
        dir_beta = float(direction[2])  # bfs_tpu: ok TRC002 static tuple member
        v_real = int(direction[3])  # bfs_tpu: ok TRC002 static tuple member
        e_real = int(direction[4])  # bfs_tpu: ok TRC002 static tuple member
        bv, _ = sparse_budgets(gtot, gtot)
        _, be = sparse_budgets(gtot, adj_dst.shape[-1])
        _, be_pred = sparse_budgets(gtot, e_real)

    def inner(vperm_blk, net_blk, valid_blk, own_all, indptr, adj_d,
              adj_s, outdeg, source):
        vperm_blk = _strip_shard_dim(vperm_blk)
        net_blk = _strip_shard_dim(net_blk)
        valid_blk = valid_blk[0]
        own_local = own_all[jax.lax.axis_index(GRAPH_AXIS)]
        if sparse:
            indptr = indptr[0]
            adj_d = adj_d[0]
            adj_s = adj_s[0]
        fwords = _packed_source_frontier(source, block, n)
        exchange_fn = make_exchange(
            ex_cfg, own_all.shape[1], nw, GRAPH_AXIS
        )

        def cond(c):
            return c["changed"] & (c["level"] < cap)

        if mxu:

            def dense_cand(fw):
                return _mxu_candidates_shard(
                    fw, vperm_blk, expansion=expansion, packed=packed
                )

        else:

            def dense_cand(fw):
                return _relay_candidates_shard(
                    fw, vperm_blk, net_blk, valid_blk, static=static
                )

        def push_cand(fw, unreached):
            return _sharded_push_candidates(
                fw, indptr, adj_d, adj_s, unreached,
                gtot=gtot, block=block, bv=bv, be=be, packed=packed,
            )

        if mode in ("auto", "push"):
            from ..models.direction import frontier_masses_words

            def global_masses(fw):
                # Replicated math on replicated inputs (the all-gathered
                # words + the replicated outdeg table): every shard
                # computes the identical masses, no collective needed to
                # agree on the branch.
                return frontier_masses_words(fw, outdeg, gtot)

            def budget_ok(fsize, fe):
                return (fsize <= bv) & (fe <= jnp.float32(be_pred))

        if telemetry:
            from ..obs import telemetry as T

        def body(c):
            fw, level = c["fw"], c["level"]
            if packed:
                pk = c["pk"]
                unreached = pk == PACKED_SENTINEL
            else:
                dist, parent = c["dist"], c["parent"]
                unreached = dist == INT32_MAX

            # ---- per-superstep body selection (pure replicated math) ----
            if mode == "auto":
                from ..models.direction import take_pull

                fsize, fe = global_masses(fw)
                m_u = jnp.maximum(c["mu"] - fe, 0.0)
                use_pull = (
                    take_pull(
                        c["prev"], fsize, fe, m_u, v_real, dir_alpha,
                        dir_beta,
                    )
                    | ~budget_ok(fsize, fe)
                )
            elif mode == "push":
                fsize, fe = global_masses(fw)
                use_pull = ~budget_ok(fsize, fe)
            else:
                use_pull = None

            if use_pull is None:
                cand = dense_cand(fw)
            else:
                cand = jax.lax.cond(
                    use_pull,
                    dense_cand,
                    lambda f: push_cand(f, unreached),
                    fw,
                )

            # ---- improvement mask + the SIEVE (settled never ships) -----
            level2 = level + 1
            if packed:
                candw = cand | level_word(level2)
                improved = candw < pk
            else:
                improved = (cand != INT32_MAX) & unreached

            # ---- exchange issued BEFORE the state writes (overlap) ------
            fw2, xbytes, xarm = exchange_fn(
                pack_std(improved), own_local, own_all
            )
            changed = (
                jax.lax.pmax(improved.any().astype(jnp.int32), GRAPH_AXIS)
                > 0
            )

            # ---- local state update (flies under the collective) --------
            out = dict(c)
            if packed:
                out["pk"] = jnp.minimum(pk, candw)
            else:
                out["dist"] = jnp.where(improved, level2, dist)
                out["parent"] = jnp.where(improved, cand, parent)
            out["fw"] = fw2
            out["level"] = level2
            out["changed"] = changed
            if mode == "auto":
                out["mu"] = m_u
                out["prev"] = use_pull
            if telemetry:
                out["occ"] = T.record_frontier_words(c["occ"], fw2, level2)
                if use_pull is None:
                    code = jnp.int32(T.DIR_PULL)
                else:
                    code = jnp.where(
                        use_pull, jnp.int32(T.DIR_PULL),
                        jnp.int32(T.DIR_PUSH),
                    )
                out["dirs"] = T.record_direction(c["dirs"], level2, code)
                out["xb"], out["xa"] = T.record_exchange(
                    c["xb"], c["xa"], level2, xbytes, xarm
                )
            return out

        carry = {
            "fw": fwords,
            "level": jnp.int32(0),
            "changed": jnp.bool_(True),
        }
        if packed:
            lo = jax.lax.axis_index(GRAPH_AXIS).astype(jnp.int32) * block
            ids_local = lo + jnp.arange(block, dtype=jnp.int32)
            carry["pk"] = jnp.where(
                ids_local == source, jnp.uint32(0), PACKED_SENTINEL
            )
        else:
            carry["dist"], carry["parent"] = _init_block_state(source, block)
        # Replicated-initialized leaves whose body outputs derive from
        # graph-axis-varying values: cast the init side like the frontier
        # words (compat.pcast_carry — identity on jax 0.4.x).
        extras = {}
        if mode == "auto":
            extras["mu"] = outdeg.astype(jnp.float32).sum()
            extras["prev"] = jnp.bool_(False)
        if telemetry:
            extras["occ"] = T.init_level_acc()
            extras["dirs"] = T.init_dir_acc()
            extras["xb"] = T.init_bytes_acc()
            extras["xa"] = T.init_dir_acc()
        carry.update(pcast_carry(extras, (GRAPH_AXIS,)))

        out = jax.lax.while_loop(cond, body, carry)
        if packed:
            if mxu:
                from ..ops.packed import packed_dist, packed_parent

                dist, parent = packed_dist(out["pk"]), packed_parent(
                    out["pk"]
                )
            else:
                dist, parent = unpack_relay_packed(
                    out["pk"], in_classes, block
                )
        else:
            dist, parent = out["dist"], out["parent"]
        if telemetry:
            return (
                dist, parent, out["level"], out["changed"],
                out["occ"], out["dirs"], out["xb"], out["xa"],
            )
        return dist, parent, out["level"], out["changed"]

    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            _mask_specs(vperm_masks),
            _mask_specs(net_masks),
            P(GRAPH_AXIS, None),
            P(),
            P(GRAPH_AXIS, None),
            P(GRAPH_AXIS, None),
            P(GRAPH_AXIS, None),
            P(),
            P(),
        ),
        out_specs=(
            (P(GRAPH_AXIS), P(GRAPH_AXIS), P(), P(), P(), P(), P(), P())
            if telemetry
            else (P(GRAPH_AXIS), P(GRAPH_AXIS), P(), P())
        ),
        # Fully manual over BOTH mesh axes: a partially-manual program (the
        # batch axis left in auto mode) would require the SPMD partitioner
        # to partition the Mosaic custom calls over the auto axis, which it
        # cannot do — even at axis size 1.  The program never communicates
        # over batch; it is simply replicated along it.
        axis_names={GRAPH_AXIS, BATCH_AXIS},
    )
    return fn(
        vperm_masks, net_masks, valid_words, own_words,
        adj_indptr, adj_dst, adj_slot, outdeg, source_new,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "static", "max_levels", "telemetry", "direction",
        "exchange", "sparse",
    ),
)
def _bfs_sharded_relay_segment(
    carry, seg_end, vperm_masks, net_masks, valid_words, own_words,
    adj_indptr, adj_dst, adj_slot, outdeg, *,
    mesh, static, max_levels, telemetry: bool = False,
    direction: tuple | None = None, exchange: tuple = ("bitmap", 8),
    sparse: bool = False,
):
    """ONE bounded segment of the sharded relay loop (ISSUE 14): the
    checkpointable twin of :func:`_bfs_sharded_relay_fused` — identical
    superstep body (same candidate pipelines, sieve, overlapped exchange
    arms, direction cond and telemetry/exchange accumulators), stopped at
    ``seg_end`` supersteps so the host can snapshot the carry at the
    EXCHANGE BOUNDARY (the per-superstep consistency point) and write
    per-shard checkpoint shards.  The carry dict holds the global view of
    every loop leaf: the per-shard state (``pk`` or ``dist``/``parent``,
    shard-major, split over the ``graph`` axis), the replicated global
    frontier words, the direction hysteresis pair and the telemetry /
    exchange-arm accumulators — a snapshot is a complete resume point and
    a resumed run replays the direction schedule AND the exchange-arm
    sequence bit-identically.  NEW lint-registered program; the fused
    off-arm is untouched."""
    from ..ops.packed import PACKED_SENTINEL, level_word, packed_cap
    from ..ops.relay import pack_std
    from .exchange import ExchangeConfig, make_exchange

    n = mesh.shape[GRAPH_AXIS]
    block, _in_classes, packed, expansion = _static_parts(static)
    mxu = expansion[0] == "mxu"
    nw = block // 32
    gtot = n * block
    cap = packed_cap(max_levels) if packed else max_levels
    ex_cfg = ExchangeConfig(*exchange)
    mode = direction[0] if direction is not None else None
    if mode in ("auto", "push") and not sparse:
        mode = None
    if mode in ("auto", "push"):
        from ..models.bfs import sparse_budgets

        dir_alpha = float(direction[1])  # bfs_tpu: ok TRC002 static tuple member
        dir_beta = float(direction[2])  # bfs_tpu: ok TRC002 static tuple member
        v_real = int(direction[3])  # bfs_tpu: ok TRC002 static tuple member
        e_real = int(direction[4])  # bfs_tpu: ok TRC002 static tuple member
        bv, _ = sparse_budgets(gtot, gtot)
        _, be = sparse_budgets(gtot, adj_dst.shape[-1])
        _, be_pred = sparse_budgets(gtot, e_real)

    state_keys = ("pk",) if packed else ("dist", "parent")

    def inner(c, seg_end, vperm_blk, net_blk, valid_blk, own_all, indptr,
              adj_d, adj_s, outdeg):
        vperm_blk = _strip_shard_dim(vperm_blk)
        net_blk = _strip_shard_dim(net_blk)
        valid_blk = valid_blk[0]
        own_local = own_all[jax.lax.axis_index(GRAPH_AXIS)]
        if sparse:
            indptr = indptr[0]
            adj_d = adj_d[0]
            adj_s = adj_s[0]
        exchange_fn = make_exchange(
            ex_cfg, own_all.shape[1], nw, GRAPH_AXIS
        )

        # Replicated-in leaves whose body outputs are graph-axis-varying
        # must be cast on entry, exactly like the fused program's init
        # side (compat.pcast_carry — identity on jax 0.4.x).
        c = dict(c)
        c["fw"] = pcast_varying(c["fw"], (GRAPH_AXIS,))
        extras = {
            k: c[k] for k in ("mu", "prev", "occ", "dirs", "xb", "xa")
            if k in c
        }
        c.update(pcast_carry(extras, (GRAPH_AXIS,)))

        def cond(c):
            return (
                c["changed"] & (c["level"] < cap)
                & (c["level"] < seg_end)
            )

        if mxu:

            def dense_cand(fw):
                return _mxu_candidates_shard(
                    fw, vperm_blk, expansion=expansion, packed=packed
                )

        else:

            def dense_cand(fw):
                return _relay_candidates_shard(
                    fw, vperm_blk, net_blk, valid_blk, static=static
                )

        def push_cand(fw, unreached):
            return _sharded_push_candidates(
                fw, indptr, adj_d, adj_s, unreached,
                gtot=gtot, block=block, bv=bv, be=be, packed=packed,
            )

        if mode in ("auto", "push"):
            from ..models.direction import frontier_masses_words

            def global_masses(fw):
                return frontier_masses_words(fw, outdeg, gtot)

            def budget_ok(fsize, fe):
                return (fsize <= bv) & (fe <= jnp.float32(be_pred))

        if telemetry:
            from ..obs import telemetry as T

        def body(c):
            fw, level = c["fw"], c["level"]
            if packed:
                pk = c["pk"]
                unreached = pk == PACKED_SENTINEL
            else:
                dist, parent = c["dist"], c["parent"]
                unreached = dist == INT32_MAX

            if mode == "auto":
                from ..models.direction import take_pull

                fsize, fe = global_masses(fw)
                m_u = jnp.maximum(c["mu"] - fe, 0.0)
                use_pull = (
                    take_pull(
                        c["prev"], fsize, fe, m_u, v_real, dir_alpha,
                        dir_beta,
                    )
                    | ~budget_ok(fsize, fe)
                )
            elif mode == "push":
                fsize, fe = global_masses(fw)
                use_pull = ~budget_ok(fsize, fe)
            else:
                use_pull = None

            if use_pull is None:
                cand = dense_cand(fw)
            else:
                cand = jax.lax.cond(
                    use_pull,
                    dense_cand,
                    lambda f: push_cand(f, unreached),
                    fw,
                )

            level2 = level + 1
            if packed:
                candw = cand | level_word(level2)
                improved = candw < pk
            else:
                improved = (cand != INT32_MAX) & unreached

            fw2, xbytes, xarm = exchange_fn(
                pack_std(improved), own_local, own_all
            )
            changed = (
                jax.lax.pmax(improved.any().astype(jnp.int32), GRAPH_AXIS)
                > 0
            )

            out = dict(c)
            if packed:
                out["pk"] = jnp.minimum(pk, candw)
            else:
                out["dist"] = jnp.where(improved, level2, dist)
                out["parent"] = jnp.where(improved, cand, parent)
            out["fw"] = fw2
            out["level"] = level2
            out["changed"] = changed
            if mode == "auto":
                out["mu"] = m_u
                out["prev"] = use_pull
            if telemetry:
                out["occ"] = T.record_frontier_words(c["occ"], fw2, level2)
                if use_pull is None:
                    code = jnp.int32(T.DIR_PULL)
                else:
                    code = jnp.where(
                        use_pull, jnp.int32(T.DIR_PULL),
                        jnp.int32(T.DIR_PUSH),
                    )
                out["dirs"] = T.record_direction(c["dirs"], level2, code)
                out["xb"], out["xa"] = T.record_exchange(
                    c["xb"], c["xa"], level2, xbytes, xarm
                )
            return out

        return jax.lax.while_loop(cond, body, c)

    carry_in_specs = {
        k: (P(GRAPH_AXIS) if k in state_keys else P()) for k in carry
    }
    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            carry_in_specs,
            P(),
            _mask_specs(vperm_masks),
            _mask_specs(net_masks),
            P(GRAPH_AXIS, None),
            P(),
            P(GRAPH_AXIS, None),
            P(GRAPH_AXIS, None),
            P(GRAPH_AXIS, None),
            P(),
        ),
        out_specs=carry_in_specs,
        axis_names={GRAPH_AXIS, BATCH_AXIS},
    )
    return fn(
        carry, seg_end, vperm_masks, net_masks, valid_words, own_words,
        adj_indptr, adj_dst, adj_slot, outdeg,
    )


@functools.lru_cache(maxsize=8)
def _sharded_segment_unpack_program(in_classes: tuple, block: int, n: int,
                                    mxu: bool = False):
    """Jitted per-shard unpack for the segmented runner's TRUE loop exit
    (cached at module level — a per-call jit would retrace, RCD001).
    The mxu flavor decodes original-id parents (no slot pass)."""
    from ..ops.relay import unpack_relay_packed

    @jax.jit
    def unpack(pk):
        if mxu:
            from ..ops.packed import packed_dist, packed_parent

            p2 = pk.reshape(n, block)
            return packed_dist(p2), packed_parent(p2)
        return jax.vmap(
            lambda p: unpack_relay_packed(p, in_classes, block)
        )(pk.reshape(n, block))

    return unpack


def sharded_segment_keys(packed: bool, auto: bool,
                         telemetry: bool) -> list[str]:
    """The sharded segment carry's key set — the ONE definition
    :func:`sharded_segment_carry` builds from and the restore gate
    validates against."""
    keys = (["pk"] if packed else ["dist", "parent"]) + [
        "fw", "level", "changed",
    ]
    if auto:
        keys += ["mu", "prev"]
    if telemetry:
        keys += ["occ", "dirs", "xb", "xa"]
    return keys


def sharded_segment_carry(srg, n: int, source_new: int, packed: bool,
                          auto: bool, telemetry: bool, outdeg_dev,
                          restore: dict | None = None) -> dict:
    """Initial (or checkpoint-restored) global-view carry for
    :func:`_bfs_sharded_relay_segment`.  ``restore`` maps carry keys to
    host arrays (the reassembled epoch — per-shard state concatenated
    shard-major); metadata keys are ignored."""
    from ..ops.packed import PACKED_SENTINEL

    block = srg.block
    gtot = n * block
    nw = block // 32
    keys = sharded_segment_keys(packed, auto, telemetry)
    if restore is not None:
        return {k: jnp.asarray(restore[k]) for k in keys}
    if packed:
        pk = np.full(gtot, PACKED_SENTINEL, np.uint32)
        pk[source_new] = np.uint32(0)
        carry = {"pk": jnp.asarray(pk)}
    else:
        dist = np.full(gtot, INT32_MAX, np.int32)
        dist[source_new] = 0
        parent = np.full(gtot, -1, np.int32)
        parent[source_new] = source_new
        carry = {"dist": jnp.asarray(dist), "parent": jnp.asarray(parent)}
    fw = np.zeros(gtot // 32, np.uint32)
    fw[source_new >> 5] = np.uint32(1) << np.uint32(source_new & 31)
    carry.update(
        fw=jnp.asarray(fw), level=jnp.int32(0), changed=jnp.bool_(True)
    )
    if auto:
        # Same seed as the fused program's replicated init (float32 sum
        # of integer out-degrees — exact below 2^24 edges).
        carry["mu"] = outdeg_dev.astype(jnp.float32).sum()
        carry["prev"] = jnp.bool_(False)
    if telemetry:
        from ..obs import telemetry as T

        carry["occ"] = T.init_level_acc()
        carry["dirs"] = T.init_dir_acc()
        carry["xb"] = T.init_bytes_acc()
        carry["xa"] = T.init_dir_acc()
    return carry


def bfs_sharded_segmented(
    graph,
    source: int = 0,
    *,
    mesh: Mesh | None = None,
    ckpt,
    max_levels: int | None = None,
    applier: str = "auto",
    telemetry: bool = False,
    direction: str | None = None,
    exchange: str | None = None,
    expansion: str | None = None,
):
    """Segmented-with-checkpoints sharded relay BFS (ISSUE 14): the
    resumable twin of :func:`bfs_sharded` ``engine='relay'`` —
    bit-identical dist/parent, direction schedule and exchange-arm
    sequence for any segmentation.  Each segment ends at the exchange
    boundary; the checkpointer writes one epoch = PER-SHARD state shards
    plus a meta file (replicated frontier words, hysteresis, telemetry/
    exchange accumulators).  Shard-loss recovery: epochs are host
    arrays, so the newest COMPLETE epoch re-admits onto any freshly
    built mesh of the same shape — a damaged or missing shard file makes
    that epoch incomplete and the loader falls back to the last complete
    one (or a fresh traversal), counters naming the fallback.

    ``ckpt`` must be a :class:`~bfs_tpu.resilience.superstep_ckpt.
    SuperstepCheckpointer` built with ``shards == mesh graph axis``."""
    import time as _time

    from ..models.direction import resolve_direction
    from ..ops.packed import (
        PACKED_MAX_LEVELS,
        packed_cap,
        packed_rank_fits,
        packed_truncated,
        resolve_packed,
    )
    from .exchange import resolve_exchange

    mesh = mesh if mesh is not None else make_mesh()
    dir_cfg = resolve_direction(direction)
    ex_cfg = resolve_exchange(exchange)
    srg = _prepare_relay(graph, mesh)
    n = _graph_shards(mesh)
    if getattr(ckpt, "shards", 1) != n:
        raise ValueError(
            f"checkpointer built for {getattr(ckpt, 'shards', 1)} shards "
            f"but the mesh graph axis has {n}"
        )
    check_sources(srg.num_vertices, source)
    max_levels = (
        int(max_levels) if max_levels is not None else srg.num_vertices
    )
    source_new = int(srg.old2new[source])
    use_pallas = _resolve_sharded_applier(applier)
    block = srg.block
    has_adj = srg.adj_dst is not None and srg.outdeg is not None
    if dir_cfg.mode == "push" and not has_adj:
        raise ValueError(
            "direction='push' needs the per-shard adjacency this "
            "ShardedRelayGraph predates"
        )
    sparse = has_adj and dir_cfg.mode in ("auto", "push")
    auto = sparse and dir_cfg.mode == "auto"
    direction_static = (
        dir_cfg.mode, dir_cfg.alpha, dir_cfg.beta,
        srg.num_vertices, srg.num_edges,
    )
    outdeg_dev = (
        jnp.asarray(srg.outdeg) if sparse else jnp.zeros((1,), jnp.int32)
    )
    # Loop-invariant operands hoisted OUT of the segment loop (the fused
    # path builds them once per call; rebuilding the valid-words table per
    # segment would both waste an O(n*net_size) host pass + upload per
    # superstep and inflate the measured superstep seconds the Young/Daly
    # interval is derived from).
    packed0 = resolve_packed(packed_rank_fits(srg.in_classes))
    exp_static, packed0 = _resolve_sharded_expansion(expansion, srg, packed0)
    mxu = exp_static[0] == "mxu"
    if mxu:
        vperm_arg = _sharded_tiles_dev(srg)[0]
        net_arg = jnp.zeros((n, 1), jnp.uint32)
        valid_dev = jnp.zeros((n, 1), jnp.uint32)
    else:
        vperm_arg, net_arg = _sharded_relay_mask_args(srg, use_pallas)
        valid_dev = _relay_valid_words(srg)
    own_dev = _own_word_table_dev(srg)

    def run_flavor(packed: bool):
        static = _sharded_relay_static(srg, n, use_pallas, packed, exp_static)
        adj = (
            _sharded_adj_dev(srg, packed, mxu) if sparse
            else _sharded_adj_dummies(n)
        )
        cap = packed_cap(max_levels) if packed else max_levels
        state_keys = ("pk",) if packed else ("dist", "parent")
        from ..resilience.superstep_ckpt import restore_arrays

        meta_arrays, shard_arrays = restore_arrays(
            ckpt, packed,
            require=tuple(
                k for k in sharded_segment_keys(packed, auto, telemetry)
                if k not in state_keys
            ),
            require_shards=state_keys,
        )
        restore = None
        if meta_arrays is not None:
            # Re-admit the surviving epoch: per-shard state shards
            # reassemble shard-major into the global carry view.
            restore = dict(meta_arrays)
            for k in state_keys:
                restore[k] = np.concatenate([sa[k] for sa in shard_arrays])
        carry = sharded_segment_carry(
            srg, n, source_new, packed, auto, telemetry, outdeg_dev,
            restore=restore,
        )
        level, changed = jax.device_get((carry["level"], carry["changed"]))
        while bool(changed) and int(level) < cap:
            seg_end = jax.device_put(
                np.int32(min(int(level) + ckpt.interval(), cap))
            )
            t0 = _time.perf_counter()
            carry = _bfs_sharded_relay_segment(
                carry, seg_end, vperm_arg, net_arg, valid_dev, own_dev,
                *adj, outdeg_dev,
                mesh=mesh, static=static, max_levels=max_levels,
                telemetry=telemetry, direction=direction_static,
                exchange=ex_cfg.key(), sparse=sparse,
            )
            new_level, changed = jax.device_get(
                (carry["level"], carry["changed"])
            )
            seg_s = _time.perf_counter() - t0
            # Disabled store: mark the boundary, skip the O(V) pull.
            meta_arrays, shard_arrays = {}, []
            if ckpt.enabled:
                host = {k: np.asarray(v) for k, v in
                        jax.device_get(carry).items()}
                meta_arrays = {
                    k: v for k, v in host.items() if k not in state_keys
                }
                meta_arrays["packed_flag"] = np.int32(packed)
                shard_arrays = [
                    {k: host[k][s * block:(s + 1) * block]
                     for k in state_keys}
                    for s in range(n)
                ]
            ckpt.save_epoch(int(new_level), meta_arrays, shard_arrays)
            ckpt.note_segment(int(new_level) - int(level), seg_s)
            level = new_level
        # The once-per-run unpack at the TRUE end, per shard block (the
        # same per-shard math the fused program runs at its loop exit).
        if packed:
            dist, parent = _sharded_segment_unpack_program(
                tuple(srg.in_classes), block, n, mxu
            )(carry["pk"])
            dist = jax.device_get(dist).reshape(-1)
            parent = jax.device_get(parent).reshape(-1)
        else:
            dist = np.asarray(jax.device_get(carry["dist"]))
            parent = np.asarray(jax.device_get(carry["parent"]))
        return carry, dist, parent, int(level), bool(changed)

    packed = packed0
    carry, dist, parent, level, changed = run_flavor(packed)
    if packed and packed_truncated(changed, level, max_levels):
        ckpt.clear()
        carry, dist, parent, level, changed = run_flavor(False)
        packed = False
    dist, parent = _relay_map_back(
        srg, dist, parent, source, "mxu" if mxu else "gather"
    )
    result = BfsResult(dist=dist, parent=parent, num_levels=level)
    ckpt.clear()
    if not telemetry:
        return result
    from ..obs.telemetry import (
        direction_schedule,
        level_curve,
        read_telemetry,
    )
    from .exchange import exchange_report

    fv, dirs, xb, xa = read_telemetry(
        (carry["occ"], carry["dirs"], carry["xb"], carry["xa"])
    )
    cap = min(PACKED_MAX_LEVELS, max_levels) if packed else max_levels
    curve = level_curve(fv, cap=cap)
    curve["direction_schedule"] = direction_schedule(
        dirs, mode=dir_cfg.mode, alpha=dir_cfg.alpha, beta=dir_cfg.beta
    )
    curve["exchange"] = exchange_report(
        xb, xa, ex_cfg, int(own_dev.shape[1]),
        block // 32, n, num_levels=result.num_levels,
    )
    return result, curve


@functools.partial(
    jax.jit, static_argnames=("mesh", "static", "max_levels")
)
def _bfs_sharded_relay_multi_fused(
    vperm_masks, net_masks, valid_words, own_words, sources_new, *,
    mesh, static, max_levels,
):
    """Batched multi-source relay BFS on a 2-D mesh: sources data-parallel
    over ``batch``, vertices (and the relay pipeline) partitioned over
    ``graph``.  The per-superstep exchange is one frontier-word all-gather
    PER LOCAL TREE; the routing masks are read once per superstep per shard
    and shared by every tree in the local batch (the amortization config 5
    is about).  ``packed`` in ``static`` as in the single-source variant:
    one fused word per (tree, owned vertex), unpacked per tree at exit."""
    from ..ops.packed import PACKED_SENTINEL, level_word, packed_cap
    from ..ops.relay import pack_std, unpack_relay_packed

    n = mesh.shape[GRAPH_AXIS]
    # The batched sharded program is gather-only (the multi twin of the
    # single-chip rule: batch paths run the XLA formulation).
    block, in_classes, packed, _expansion = _static_parts(static)
    nw = block // 32
    cap = packed_cap(max_levels) if packed else max_levels

    def inner(vperm_blk, net_blk, valid_blk, own_all, sources_blk):
        vperm_blk = _strip_shard_dim(vperm_blk)
        net_blk = _strip_shard_dim(net_blk)
        valid_blk = valid_blk[0]
        own_local = own_all[jax.lax.axis_index(GRAPH_AXIS)]
        s_l = sources_blk.shape[0]
        lo = jax.lax.axis_index(GRAPH_AXIS).astype(jnp.int32) * block
        ids_local = lo + jnp.arange(block, dtype=jnp.int32)
        is_src = ids_local[None, :] == sources_blk[:, None]
        fwords = (
            jnp.zeros((s_l, n * nw), jnp.uint32)
            .at[jnp.arange(s_l), sources_blk >> 5]
            .set(jnp.uint32(1) << (sources_blk & 31).astype(jnp.uint32))
        )
        fwords = pcast_varying(fwords, (GRAPH_AXIS,))

        def cond(carry):
            level, changed = carry[-2], carry[-1]
            return changed & (level < cap)

        def candidates(fw):
            return jax.vmap(
                lambda f: _relay_candidates_shard(
                    f, vperm_blk, net_blk, valid_blk, static=static
                )
            )(fw)

        if packed:
            pk0 = jnp.where(is_src, jnp.uint32(0), PACKED_SENTINEL)

            def body(carry):
                pk, fw, level, _ = carry
                cand = candidates(fw)
                pk2 = jnp.minimum(pk, cand | level_word(level + 1))
                improved = pk2 != pk
                fw = _exchange_compact(
                    pack_std(improved), own_local, own_all, nw
                )
                any_local = improved.any().astype(jnp.int32)
                changed = (
                    jax.lax.pmax(
                        jax.lax.pmax(any_local, GRAPH_AXIS), BATCH_AXIS
                    )
                    > 0
                )
                return pk2, fw, level + 1, changed

            pk, _, level, changed = jax.lax.while_loop(
                cond, body, (pk0, fwords, jnp.int32(0), jnp.bool_(True))
            )
            dist, parent = jax.vmap(
                lambda p: unpack_relay_packed(p, in_classes, block)
            )(pk)
            return dist, parent, level, changed

        dist = jnp.where(is_src, jnp.int32(0), INT32_MAX)
        parent = jnp.where(is_src, sources_blk[:, None], jnp.int32(-1))

        def body(carry):
            dist, parent, fw, level, _ = carry
            cand = candidates(fw)
            improved = (cand != INT32_MAX) & (dist == INT32_MAX)
            level = level + 1
            dist = jnp.where(improved, level, dist)
            parent = jnp.where(improved, cand, parent)
            fw = _exchange_compact(pack_std(improved), own_local, own_all, nw)
            any_local = improved.any().astype(jnp.int32)
            changed = (
                jax.lax.pmax(
                    jax.lax.pmax(any_local, GRAPH_AXIS), BATCH_AXIS
                )
                > 0
            )
            return dist, parent, fw, level, changed

        dist, parent, _, level, changed = jax.lax.while_loop(
            cond, body, (dist, parent, fwords, jnp.int32(0), jnp.bool_(True))
        )
        return dist, parent, level, changed

    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            _mask_specs(vperm_masks),
            _mask_specs(net_masks),
            P(GRAPH_AXIS, None),
            P(),
            P(BATCH_AXIS),
        ),
        out_specs=(
            P(BATCH_AXIS, GRAPH_AXIS),
            P(BATCH_AXIS, GRAPH_AXIS),
            P(),
            P(),
        ),
        axis_names={GRAPH_AXIS, BATCH_AXIS},
    )
    return fn(vperm_masks, net_masks, valid_words, own_words, sources_new)


def _prepare_relay(graph, mesh: Mesh):
    from ..graph.relay import ShardedRelayGraph, build_sharded_relay_graph

    n = _graph_shards(mesh)
    if isinstance(graph, ShardedPullGraph):
        raise ValueError("a ShardedPullGraph only runs on engine='pull'")
    if isinstance(graph, ShardedRelayGraph):
        if graph.num_shards != n:
            raise ValueError(
                f"ShardedRelayGraph has {graph.num_shards} shards but mesh "
                f"axis '{GRAPH_AXIS}' has {n}; rebuild with num_shards={n}"
            )
        return graph
    return build_sharded_relay_graph(graph, n)


def _own_word_table(srg):
    """Real-word index table for the COMPACT frontier exchange:
    ``int32[n_shards, kw]`` of LOCAL word indices (within each shard's
    ``block/32`` frontier words) that contain at least one real vertex,
    right-padded by repeating the last real index.

    The unified per-shard class structure pads every shard's class counts
    to the max over shards, so the naive ``block``-bit all-gather ships
    padding that GROWS with shard count (+27% at 8 shards on the
    Pokec-shape — VERDICT r4 weak #4).  Gathering only real words keeps
    the exchange flat at ~V/8 bytes: senders gather ``kw`` words through
    this table, receivers scatter them back into the global padded word
    space (pad duplicates rewrite identical values, so the scatter is
    deterministic)."""
    n, block = srg.num_shards, srg.block
    nw = block // 32
    real = (
        (srg.new2old.reshape(n, block) != -1).reshape(n, nw, 32).any(axis=2)
    )
    kw = max(int(real.sum(axis=1).max()), 1)
    rows = []
    for s in range(n):
        idx = np.flatnonzero(real[s]).astype(np.int32)
        if idx.size == 0:
            idx = np.zeros(1, np.int32)
        rows.append(
            np.concatenate([idx, np.full(kw - idx.size, idx[-1], np.int32)])
        )
    return np.stack(rows)


def _own_word_table_dev(srg):
    """Device-resident :func:`_own_word_table`, memoized on the layout
    object: the host table is an O(V) scan + per-shard loop and must not
    land inside a caller's timed repeats (it is layout data, like the
    masks).  ``object.__setattr__`` because ShardedRelayGraph is frozen."""
    cached = getattr(srg, "_own_words_dev", None)
    if cached is None:
        cached = jnp.asarray(_own_word_table(srg))
        object.__setattr__(srg, "_own_words_dev", cached)
    return cached


def _exchange_compact(improved_words, own_local, own_all, nw: int):
    """Compact (bitmap-arm) frontier exchange: local packed words ->
    global packed words, via the ONE bitmap wire-move implementation
    (parallel/exchange.bitmap_gather — the multi-source program and the
    single-source arms must never diverge).  ``improved_words``:
    uint32[..., nw] (this shard's new frontier bits); ``own_local``:
    int32[kw] this shard's real-word indices; ``own_all``: int32[n, kw]
    every shard's table (replicated).  Returns uint32[..., n*nw]."""
    from .exchange import bitmap_gather

    send = jnp.take(improved_words, own_local, axis=-1)
    return bitmap_gather(send, own_all, nw, GRAPH_AXIS)


def _relay_valid_words(srg):
    """Per-shard valid-slot bitmasks (graph/relay.valid_slot_words), stacked
    over shards: uint32[n, net_size/32]."""
    from ..graph.relay import valid_slot_words

    return jnp.asarray(
        np.stack(
            [valid_slot_words(srg.src_l1[s], srg.net_size)
             for s in range(srg.num_shards)]
        )
    )


def _sharded_adj_ranks(srg) -> np.ndarray:
    """Per-edge within-row RANKS from the per-shard adjacency's L1 slots
    (slot = base + rank*stride inverted with the shared local vertex
    tables) — the packed carry's parent-field flavor, derived host-side
    once so the layout stays slot-based (same contract as the single-chip
    engine's ``_adj_ranks``)."""
    from ..graph.relay import _vertex_tables

    base1, stride1 = _vertex_tables(list(srg.in_classes), srg.block)
    d = np.clip(srg.adj_dst, 0, srg.block - 1)
    return (
        (srg.adj_slot - base1[d]) // np.maximum(stride1[d], 1)
    ).astype(np.int32)


def _sharded_adj_dev(srg, packed: bool, mxu: bool = False):
    """Device-resident per-shard adjacency operands ``(indptr, dst,
    slot-or-rank-or-key)``, memoized per flavor on the layout object
    (layout data, like the masks — must not land inside a caller's timed
    repeats).  Raises if this layout predates per-shard adjacency."""
    if srg.adj_dst is None:
        raise ValueError(
            "this ShardedRelayGraph ships no per-shard adjacency "
            "(pre-exchange layout); rebuild with build_sharded_relay_graph"
        )
    key = "_adj_dev_ranks" if packed else "_adj_dev_slots"
    if mxu:
        key = "_adj_dev_keys"
    cached = getattr(srg, key, None)
    if cached is None:
        if mxu:
            third = _sharded_adj_keys(srg)
        else:
            third = _sharded_adj_ranks(srg) if packed else srg.adj_slot
        cached = (
            jnp.asarray(srg.adj_indptr),
            jnp.asarray(srg.adj_dst),
            jnp.asarray(third),
        )
        object.__setattr__(srg, key, cached)
    return cached


def _sharded_adj_dummies(n: int):
    """1-element traced-and-dropped adjacency stand-ins for the dense-only
    program flavors (mirrors RelayEngine's hybrid-off dummies: the fused
    program keeps ONE signature, XLA drops the unused operands)."""
    return (
        jnp.zeros((n, 1), jnp.int32),
        jnp.zeros((n, 1), jnp.int32),
        jnp.zeros((n, 1), jnp.int32),
    )


def _sharded_adj_keys(srg) -> np.ndarray:
    """Per-edge ORIGINAL src ids (the mxu arm's sparse-path payload):
    ``src_l1[shard][slot]`` per shard — sorting (dst, key) is the
    canonical tie-break, the single-chip ``_adj_keys`` contract."""
    slots = np.clip(srg.adj_slot, 0, srg.src_l1.shape[1] - 1)
    shard = np.arange(srg.adj_slot.shape[0])[:, None]
    return np.where(
        srg.adj_slot >= 0, srg.src_l1[shard, slots], srg.adj_slot
    ).astype(np.int32)


def _sharded_tiles_dev(srg):
    """Stacked per-shard MXU tile operands ``(tiles, row_idx, col_id,
    sb_indptr, keys2d)`` (leading shard axis, per-shard tile counts
    padded to the max with inert tiles) + the shared static geometry —
    memoized on the layout object like the adjacency flavors."""
    cached = getattr(srg, "_mxu_tiles_dev", None)
    if cached is not None:
        return cached
    from ..graph.adj_tiles import TILE, TILE_WORDS, build_adj_tiles_sharded
    from ..ops.relay_mxu import tiles_budget_bytes

    per = build_adj_tiles_sharded(srg, budget_bytes=tiles_budget_bytes())
    ntp = max(at.ntp for at in per)

    def pad(at):
        k = ntp - at.ntp
        if not k:
            return at.tiles, at.row_idx, at.col_id
        return (
            np.concatenate(
                [at.tiles, np.zeros((k, TILE, TILE_WORDS), np.uint32)]
            ),
            np.concatenate(
                [at.row_idx, np.full(k, at.rtp // TILE, np.int32)]
            ),
            np.concatenate(
                [at.col_id, np.full(k, at.vtp // TILE, np.int32)]
            ),
        )

    padded = [pad(at) for at in per]
    ops = (
        jnp.asarray(np.stack([p[0] for p in padded])),
        jnp.asarray(np.stack([p[1] for p in padded])),
        jnp.asarray(np.stack([p[2] for p in padded])),
        jnp.asarray(np.stack([at.sb_indptr for at in per])),
        jnp.asarray(np.stack([at.keys2d for at in per])),
    )
    geo = (per[0].rows, per[0].cols, per[0].rtp, per[0].vtp, ntp)
    cached = (ops, geo)
    object.__setattr__(srg, "_mxu_tiles_dev", cached)
    return cached


def _resolve_sharded_expansion(expansion, srg, packed: bool):
    """The sharded expansion-arm resolution: forced modes only — 'auto'
    runs gather (the mesh program is AOT-compiled once; the single-chip
    probe's verdict is the measured signal, and the first TPU window
    re-probes).  Returns ``(expansion_static, packed)``; forcing mxu with
    a forced packed carry that cannot hold original ids is an error."""
    import os

    from ..ops.packed import packed_parent_fits
    from ..ops.relay_mxu import resolve_expansion, resolve_mxu_kernel

    req = resolve_expansion(expansion)
    if req != "mxu":
        return ("gather",), packed
    if srg.adj_dst is None:
        raise ValueError(
            "BFS_TPU_EXPANSION=mxu needs the per-shard adjacency this "
            "ShardedRelayGraph predates (the tile builder reads it); "
            "rebuild with build_sharded_relay_graph"
        )
    if packed and not packed_parent_fits(srg.num_vertices):
        if knobs.get("BFS_TPU_PACKED") == "1":
            raise ValueError(
                "BFS_TPU_EXPANSION=mxu with BFS_TPU_PACKED=1 needs "
                "V <= 2^26: the mxu packed parent field carries "
                "ORIGINAL ids"
            )
        packed = False
    _, geo = _sharded_tiles_dev(srg)
    use_kernel = resolve_mxu_kernel() == "pallas"
    return ("mxu", geo, use_kernel), packed


def _relay_map_back(srg, dist, parent, source_or_sources,
                    expansion: str = "gather"):
    """Global-relabeled sharded state -> original-id arrays.  Parent values
    are per-shard L1 slot indices; vertex at global new id g is owned by
    shard g // block with src table src_l1[shard].  On the mxu arm parent
    VALUES are already original ids — only the index space remaps."""
    dist = np.asarray(dist)
    parent = np.asarray(parent)
    if expansion == "mxu":
        parent = parent.astype(np.int32).copy()
    else:
        shard_of = np.arange(parent.shape[-1]) // srg.block
        slots = np.clip(parent, 0, srg.src_l1.shape[1] - 1)
        parent = np.where(
            parent >= 0, srg.src_l1[shard_of, slots], parent
        ).astype(np.int32)
    dist = dist[..., srg.old2new]
    parent = parent[..., srg.old2new]
    if np.ndim(source_or_sources) == 0:
        parent[int(source_or_sources)] = int(source_or_sources)
    else:
        rows = np.arange(len(source_or_sources))
        parent[rows, source_or_sources] = source_or_sources
    return dist, parent


def _prepare_pull(
    graph: Graph | DeviceGraph | ShardedPullGraph, mesh: Mesh, block_multiple: int
) -> ShardedPullGraph:
    from ..graph.relay import ShardedRelayGraph

    n = _graph_shards(mesh)
    if isinstance(graph, ShardedRelayGraph):
        raise ValueError("a ShardedRelayGraph only runs on engine='relay'")
    if isinstance(graph, ShardedPullGraph):
        if graph.num_shards != n:
            raise ValueError(
                f"ShardedPullGraph has {graph.num_shards} shards but mesh axis "
                f"'{GRAPH_AXIS}' has {n}; rebuild with num_shards={n}"
            )
        return graph
    return build_sharded_pull_graph(graph, n, block_multiple=block_multiple)


def bfs_sharded(
    graph: Graph | DeviceGraph | ShardedPullGraph,
    source: int = 0,
    *,
    mesh: Mesh | None = None,
    engine: str = "pull",
    max_levels: int | None = None,
    block: int = 1024,
    vertex_block_multiple: int = 1024,
    applier: str = "auto",
    telemetry: bool = False,
    direction: str | None = None,
    exchange: str | None = None,
    expansion: str | None = None,
):
    """Single-source BFS sharded over the mesh's ``graph`` axis.

    Engines:
      * ``'relay'`` — per-shard Beneš relay layouts; the gather-free
        TPU-fast formulation, multi-chip.  ``applier='auto'`` runs the
        networks as the fused 3-pass Pallas kernels on TPU backends
        (per-device inside ``shard_map``; sizes permitting) and as the
        per-stage XLA path elsewhere; 'pallas'/'xla' force.
      * ``'pull'`` (default) — vertex-partitioned ELL + bit-packed frontier
        bitmap all-gather; portable multi-chip formulation.
      * ``'push'`` — edge-sharded ``segment_min`` + full candidate `pmin`;
        the direct analogue of the reference's map/shuffle/reduce, kept for
        differential testing.

    ``telemetry`` (relay engine only) carries the per-level occupancy,
    direction-schedule and exchange accumulators through the sharded
    loop (obs/telemetry.py) and returns ``(BfsResult, level_curve)`` —
    one extra replicated pull at exit, the curve carrying
    ``direction_schedule`` and ``exchange`` (bytes-on-the-wire per
    level, per-level arm schedule).

    ``direction`` resolves like the single-chip engine's knob
    (BFS_TPU_DIRECTION; models/direction.py).  With the per-shard
    dst-owned adjacency the sharded builder now ships, every mode runs
    across the mesh: ``'pull'`` is the dense relay pipeline every
    superstep, ``'push'`` the sparse gather body wherever the static
    budgets allow, ``'auto'`` the Beamer predicate — bit-identical
    schedules to the single-chip relay engine for the same graph and
    thresholds.  A prebuilt pre-adjacency layout still runs
    ``'pull'``/``'auto'`` (dense only) and rejects ``'push'``.

    ``exchange`` resolves the frontier-exchange arm
    (BFS_TPU_EXCHANGE; parallel/exchange.py):
    ``auto|bitmap|delta|flat``, flat being the uncompressed oracle.  All
    arms are bit-identical in results; only wire bytes differ.
    """
    from ..models.direction import resolve_direction
    from .exchange import resolve_exchange

    mesh = mesh if mesh is not None else make_mesh()
    if telemetry and engine != "relay":
        raise ValueError("telemetry is carried by the sharded relay engine only")
    dir_cfg = resolve_direction(direction)
    if engine == "relay":
        from ..ops.packed import (
            packed_rank_fits,
            packed_truncated,
            resolve_packed,
        )

        ex_cfg = resolve_exchange(exchange)
        srg = _prepare_relay(graph, mesh)
        check_sources(srg.num_vertices, source)
        max_levels = int(max_levels) if max_levels is not None else srg.num_vertices
        source_new = jnp.int32(int(srg.old2new[source]))
        use_pallas = _resolve_sharded_applier(applier)
        n = _graph_shards(mesh)
        has_adj = srg.adj_dst is not None
        if dir_cfg.mode == "push" and not has_adj:
            raise ValueError(
                "direction='push' needs the per-shard adjacency this "
                "ShardedRelayGraph predates; rebuild it with "
                "build_sharded_relay_graph (use 'pull' or 'auto' to run "
                "dense-only)"
            )
        sparse = has_adj and dir_cfg.mode in ("auto", "push")
        direction_static = (
            dir_cfg.mode, dir_cfg.alpha, dir_cfg.beta,
            srg.num_vertices, srg.num_edges,
        )
        outdeg_dev = (
            jnp.asarray(srg.outdeg)
            if sparse and srg.outdeg is not None
            else jnp.zeros((1,), jnp.int32)
        )
        sparse = sparse and srg.outdeg is not None
        packed0 = resolve_packed(packed_rank_fits(srg.in_classes))
        exp_static, packed0 = _resolve_sharded_expansion(
            expansion, srg, packed0
        )
        mxu = exp_static[0] == "mxu"
        if mxu:
            # The tile tuple rides the vperm mask-operand slot (the
            # single-chip trick: one program signature, two arms); the
            # Beneš masks — multi-GB at bench scale — are never even
            # built on this arm, and the valid words become dummies.
            vperm_arg = _sharded_tiles_dev(srg)[0]
            net_arg = jnp.zeros((n, 1), jnp.uint32)
        else:
            vperm_arg, net_arg = _sharded_relay_mask_args(srg, use_pallas)

        def run_prog(packed: bool):
            static = _sharded_relay_static(
                srg, n, use_pallas, packed, exp_static
            )
            adj = (
                _sharded_adj_dev(srg, packed, mxu)
                if sparse
                else _sharded_adj_dummies(n)
            )
            valid_arg = (
                jnp.zeros((n, 1), jnp.uint32)
                if mxu
                else _relay_valid_words(srg)
            )
            args = (
                vperm_arg, net_arg, valid_arg,
                _own_word_table_dev(srg), *adj, outdeg_dev, source_new,
            )
            kwargs = dict(
                mesh=mesh, static=static, max_levels=max_levels,
                telemetry=telemetry, direction=direction_static,
                exchange=ex_cfg.key(), sparse=sparse,
            )
            if use_pallas:
                from ..models.bfs import RelayEngine

                key = ("single", static, mesh, max_levels, telemetry,
                       direction_static, ex_cfg.key(), sparse)
                compiled = _SHARDED_AOT_CACHE.get(key)
                if compiled is None:
                    from ..models.bfs import compile_exe_cached

                    compiled = compile_exe_cached(
                        _bfs_sharded_relay_fused.lower(*args, **kwargs),
                        RelayEngine._COMPILER_OPTIONS,
                    )
                    while len(_SHARDED_AOT_CACHE) >= _SHARDED_AOT_CACHE_MAX:
                        _SHARDED_AOT_CACHE.pop(next(iter(_SHARDED_AOT_CACHE)))
                    _SHARDED_AOT_CACHE[key] = compiled
                return compiled(*args)
            return _bfs_sharded_relay_fused(*args, **kwargs)

        packed = packed0
        out = run_prog(packed)
        dist, parent, level, changed = out[:4]
        if packed and packed_truncated(
            jax.device_get(changed), jax.device_get(level), max_levels
        ):
            # Deeper than the packed level field: re-run unpacked (same
            # contract as the single-chip engine and elem mode).
            out = run_prog(False)
            dist, parent, level, changed = out[:4]
            packed = False
        dist, parent = _relay_map_back(
            srg, jax.device_get(dist), jax.device_get(parent), source,
            "mxu" if mxu else "gather",
        )
        result = BfsResult(dist=dist, parent=parent, num_levels=int(level))
        if not telemetry:
            return result
        from ..obs.telemetry import (
            direction_schedule,
            level_curve,
            read_telemetry,
        )
        from ..ops.packed import PACKED_MAX_LEVELS
        from .exchange import exchange_report

        fv, dirs, xb, xa = read_telemetry(
            (out[4], out[5], out[6], out[7])
        )
        cap = min(PACKED_MAX_LEVELS, max_levels) if packed else max_levels
        curve = level_curve(fv, cap=cap)
        curve["direction_schedule"] = direction_schedule(
            dirs, mode=dir_cfg.mode, alpha=dir_cfg.alpha, beta=dir_cfg.beta
        )
        curve["exchange"] = exchange_report(
            xb, xa, ex_cfg, int(_own_word_table_dev(srg).shape[1]),
            srg.block // 32, n, num_levels=result.num_levels,
        )
        return result, curve
    if engine == "pull":
        spg = _prepare_pull(graph, mesh, vertex_block_multiple)
        check_sources(spg.num_vertices, source)
        max_levels = int(max_levels) if max_levels is not None else spg.num_vertices
        from ..graph.ell import device_ell_sharded

        ell0_t, folds_t = device_ell_sharded(spg)
        dist, parent, level = _bfs_sharded_pull_fused(
            ell0_t,
            folds_t,
            jnp.int32(source),
            mesh=mesh,
            block=spg.block,
            max_levels=max_levels,
        )
        return BfsResult(
            dist=np.asarray(jax.device_get(dist))[: spg.num_vertices],
            parent=np.asarray(jax.device_get(parent))[: spg.num_vertices],
            num_levels=int(level),
        )
    if engine != "push":
        raise ValueError(
            f"unknown engine {engine!r}; use 'relay', 'pull' or 'push'"
        )
    _reject_wrong_layout_for_push(graph)
    dg = _prepare(graph, mesh, block)
    check_sources(dg.num_vertices, source)
    max_levels = int(max_levels) if max_levels is not None else dg.num_vertices
    state = _bfs_sharded_fused(
        jnp.asarray(dg.src).reshape(dg.num_shards, -1),
        jnp.asarray(dg.dst).reshape(dg.num_shards, -1),
        jnp.int32(source),
        mesh=mesh,
        num_vertices=dg.num_vertices,
        max_levels=max_levels,
    )
    state = jax.device_get(state)
    return BfsResult(
        dist=np.asarray(state.dist[: dg.num_vertices]),
        parent=np.asarray(state.parent[: dg.num_vertices]),
        num_levels=int(state.level),
    )


@functools.partial(
    jax.jit, static_argnames=("mesh", "num_vertices", "max_levels")
)
def _bfs_sharded_multi_fused(src, dst, sources, *, mesh, num_vertices, max_levels):
    def inner(src_blk, dst_blk, sources_blk):
        src_e = src_blk.reshape(-1)
        dst_e = dst_blk.reshape(-1)
        state = init_batched_state(num_vertices, sources_blk)

        def cond(s: BfsState):
            return s.changed & (s.level < max_levels)

        def body(s: BfsState):
            return relax_superstep_batched(
                s, src_e, dst_e, axis_name=GRAPH_AXIS, batch_axis_name=BATCH_AXIS
            )

        return jax.lax.while_loop(cond, body, state)

    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(GRAPH_AXIS, None), P(GRAPH_AXIS, None), P(BATCH_AXIS)),
        out_specs=BfsState(
            P(BATCH_AXIS, None), P(BATCH_AXIS, None), P(BATCH_AXIS, None), P(), P()
        ),
        axis_names={GRAPH_AXIS, BATCH_AXIS},
    )
    return fn(src, dst, sources)


@functools.partial(jax.jit, static_argnames=("mesh", "block", "max_levels"))
def _bfs_sharded_pull_multi_fused(ell0, folds, sources, *, mesh, block, max_levels):
    """Batched multi-source pull BFS on a 2-D mesh: sources data-parallel
    over ``batch``, vertices partitioned over ``graph``.  State is sharded
    over BOTH axes — [S/nb, block] per device — so per-chip memory scales as
    S·V/(nb·n); the per-superstep exchange stays the bit-packed frontier
    all-gather, one bitmap per local source."""
    n = mesh.shape[GRAPH_AXIS]
    vtot = n * block
    nw = block // 32

    def inner(ell0_blk, folds_blk, sources_blk):
        ell0_blk = ell0_blk[0]
        folds_blk = tuple(f[0] for f in folds_blk)
        s_l = sources_blk.shape[0]
        lo = jax.lax.axis_index(GRAPH_AXIS).astype(jnp.int32) * block
        ids_local = lo + jnp.arange(block, dtype=jnp.int32)
        is_src = ids_local[None, :] == sources_blk[:, None]
        dist = jnp.where(is_src, jnp.int32(0), INT32_MAX)
        parent = jnp.where(is_src, sources_blk[:, None], jnp.int32(-1))
        fwords = (
            jnp.zeros((s_l, n * nw), jnp.uint32)
            .at[jnp.arange(s_l), sources_blk >> 5]
            .set(jnp.uint32(1) << (sources_blk & 31).astype(jnp.uint32))
        )
        # See the single-source variant: the all_gather in the body makes
        # the frontier carry graph-axis-varying.
        fwords = pcast_varying(fwords, (GRAPH_AXIS,))
        gids = jnp.arange(vtot, dtype=jnp.int32)
        inf1 = jnp.full((s_l, 1), INT32_MAX, dtype=jnp.int32)

        def cond(carry):
            _, _, _, level, changed = carry
            return changed & (level < max_levels)

        def body(carry):
            dist, parent, fwords, level, _ = carry
            bits = unpack_frontier_blocks(fwords, n, nw)
            ftab_ext = jnp.concatenate(
                [jnp.where(bits, gids[None, :], INT32_MAX), inf1], axis=-1
            )
            cand = pull_candidates_rows(ftab_ext, ell0_blk, folds_blk, block)
            improved = (cand != INT32_MAX) & (dist == INT32_MAX)
            level = level + 1
            dist = jnp.where(improved, level, dist)
            parent = jnp.where(improved, cand, parent)
            fwords = jax.lax.all_gather(
                pack_frontier_block(improved, nw), GRAPH_AXIS, tiled=True, axis=1
            )
            any_local = improved.any().astype(jnp.int32)
            changed = jax.lax.pmax(
                jax.lax.pmax(any_local, GRAPH_AXIS), BATCH_AXIS
            ) > 0
            return dist, parent, fwords, level, changed

        dist, parent, _, level, _ = jax.lax.while_loop(
            cond, body, (dist, parent, fwords, jnp.int32(0), jnp.bool_(True))
        )
        return dist, parent, level

    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            P(GRAPH_AXIS, None, None),
            tuple(P(GRAPH_AXIS, None, None) for _ in folds),
            P(BATCH_AXIS),
        ),
        out_specs=(P(BATCH_AXIS, GRAPH_AXIS), P(BATCH_AXIS, GRAPH_AXIS), P()),
        axis_names={GRAPH_AXIS, BATCH_AXIS},
    )
    return fn(ell0, folds, sources)


def bfs_sharded_multi(
    graph: Graph | DeviceGraph | ShardedPullGraph,
    sources,
    *,
    mesh: Mesh | None = None,
    engine: str = "pull",
    max_levels: int | None = None,
    block: int = 1024,
    vertex_block_multiple: int = 1024,
) -> MultiBfsResult:
    """Batched multi-source BFS: sources sharded over ``batch`` (DP), the
    graph over ``graph`` (the context-parallel analogue).  Sources count must
    be a multiple of the batch axis size.  ``engine`` as in
    :func:`bfs_sharded`."""
    mesh = mesh if mesh is not None else make_mesh()
    sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
    nb = mesh.shape[BATCH_AXIS]
    if sources.shape[0] % nb != 0:
        raise ValueError(f"{sources.shape[0]} sources not divisible by batch axis {nb}")
    if engine == "relay":
        from ..ops.packed import (
            packed_rank_fits,
            packed_truncated,
            resolve_packed,
        )

        srg = _prepare_relay(graph, mesh)
        check_sources(srg.num_vertices, sources)
        max_levels = int(max_levels) if max_levels is not None else srg.num_vertices
        sources_new = jnp.asarray(srg.old2new[sources])

        # The batched variant vmaps the candidate pipeline over local trees;
        # it stays on the per-stage XLA appliers (vmap over the fused Pallas
        # calls is not exercised — the element-major engine is the batched
        # fast path on real hardware, models/bfs.run_multi_elem_device).
        def run_prog(packed: bool):
            return _bfs_sharded_relay_multi_fused(
                jnp.asarray(srg.vperm_masks),
                jnp.asarray(srg.net_masks),
                _relay_valid_words(srg),
                _own_word_table_dev(srg),
                sources_new,
                mesh=mesh,
                static=_sharded_relay_static(
                    srg, _graph_shards(mesh), False, packed
                ),
                max_levels=max_levels,
            )

        packed = resolve_packed(packed_rank_fits(srg.in_classes))
        dist, parent, level, changed = run_prog(packed)
        if packed and packed_truncated(
            jax.device_get(changed), jax.device_get(level), max_levels
        ):
            dist, parent, level, changed = run_prog(False)
        dist, parent = _relay_map_back(
            srg, jax.device_get(dist), jax.device_get(parent), sources
        )
        return MultiBfsResult(
            sources=sources, dist=dist, parent=parent, num_levels=int(level)
        )
    if engine == "pull":
        spg = _prepare_pull(graph, mesh, vertex_block_multiple)
        check_sources(spg.num_vertices, sources)
        max_levels = int(max_levels) if max_levels is not None else spg.num_vertices
        from ..graph.ell import device_ell_sharded

        ell0_t, folds_t = device_ell_sharded(spg)
        dist, parent, level = _bfs_sharded_pull_multi_fused(
            ell0_t,
            folds_t,
            jnp.asarray(sources),
            mesh=mesh,
            block=spg.block,
            max_levels=max_levels,
        )
        v = spg.num_vertices
        return MultiBfsResult(
            sources=sources,
            dist=np.asarray(jax.device_get(dist))[:, :v],
            parent=np.asarray(jax.device_get(parent))[:, :v],
            num_levels=int(level),
        )
    if engine != "push":
        raise ValueError(
            f"unknown engine {engine!r}; use 'relay', 'pull' or 'push'"
        )
    _reject_wrong_layout_for_push(graph)
    dg = _prepare(graph, mesh, block)
    check_sources(dg.num_vertices, sources)
    max_levels = int(max_levels) if max_levels is not None else dg.num_vertices
    state = _bfs_sharded_multi_fused(
        jnp.asarray(dg.src).reshape(dg.num_shards, -1),
        jnp.asarray(dg.dst).reshape(dg.num_shards, -1),
        jnp.asarray(sources),
        mesh=mesh,
        num_vertices=dg.num_vertices,
        max_levels=max_levels,
    )
    state = jax.device_get(state)
    v = dg.num_vertices
    return MultiBfsResult(
        sources=sources,
        dist=np.asarray(state.dist[:, :v]),
        parent=np.asarray(state.parent[:, :v]),
        num_levels=int(state.level),
    )
