"""Mesh-sharded BFS: `shard_map` over edge shards with ICI all-reduce merge.

TPU-native re-design of the reference's only parallelism strategy — Spark
data-parallel map/shuffle over hash-partitioned Vertex records
(BfsSpark.java:66-108, SURVEY.md §2.4/§2.5):

  * Spark's hash-partitioned RDD blocks  ->  balanced dst-sorted edge shards,
    one per device along the mesh's ``graph`` axis (csr.build_device_graph).
  * The shuffle (`reduceByKey`) + driver collect (`collectAsMap`)  ->  one
    ``lax.pmin`` all-reduce of the per-destination candidate-parent array per
    superstep, riding ICI.  No host round-trip: the whole superstep loop is
    a single compiled program, and dist/parent/frontier stay replicated
    device-resident.
  * The driver's file-based termination scan (BfsSpark.java:117)  ->  an
    on-device replicated scalar.

A second mesh axis ``batch`` shards the sources axis of batched multi-source
BFS (data parallelism); ``graph`` is the model/context-parallel analogue.
This is the scaling design for graphs that exceed one chip's HBM: per-device
edge memory is E/n while V-sized state is replicated (SURVEY.md §5
long-context row: graph sharding is this workload's context parallelism).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import Mesh, PartitionSpec as P

try:  # JAX >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..graph.csr import DeviceGraph, Graph, build_device_graph
from ..models.bfs import BfsResult, check_sources
from ..models.multisource import MultiBfsResult
from ..ops.relax import (
    BfsState,
    init_batched_state,
    init_state,
    relax_superstep,
    relax_superstep_batched,
)

GRAPH_AXIS = "graph"
BATCH_AXIS = "batch"


def make_mesh(
    graph: int | None = None,
    batch: int = 1,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a ``(batch, graph)`` mesh.  ``graph=None`` uses all remaining
    devices.  Single-host multi-device or multi-host both work — the mesh is
    the cluster-bootstrap analogue of the Spark master/worker setup
    (service.properties ip/port + README.md:27-31), minus the processes."""
    devices = list(devices if devices is not None else jax.devices())
    if graph is None:
        graph = len(devices) // batch
    if batch * graph > len(devices):
        raise ValueError(f"mesh {batch}x{graph} needs {batch * graph} devices, have {len(devices)}")
    arr = np.asarray(devices[: batch * graph]).reshape(batch, graph)
    return Mesh(arr, (BATCH_AXIS, GRAPH_AXIS))


def _graph_shards(mesh: Mesh) -> int:
    return mesh.shape[GRAPH_AXIS]


def _prepare(graph: Graph | DeviceGraph, mesh: Mesh, block: int) -> DeviceGraph:
    n = _graph_shards(mesh)
    if isinstance(graph, DeviceGraph):
        if graph.num_shards != n:
            raise ValueError(
                f"DeviceGraph has {graph.num_shards} shards but mesh axis "
                f"'{GRAPH_AXIS}' has {n}; rebuild with build_device_graph(num_shards={n})"
            )
        return graph
    return build_device_graph(graph, num_shards=n, block=block)


@functools.partial(
    jax.jit, static_argnames=("mesh", "num_vertices", "max_levels")
)
def _bfs_sharded_fused(src, dst, source, *, mesh, num_vertices, max_levels):
    def inner(src_blk, dst_blk, source):
        src_e = src_blk.reshape(-1)
        dst_e = dst_blk.reshape(-1)
        state = init_state(num_vertices, source)

        def cond(s: BfsState):
            return s.changed & (s.level < max_levels)

        def body(s: BfsState):
            return relax_superstep(s, src_e, dst_e, axis_name=GRAPH_AXIS)

        return jax.lax.while_loop(cond, body, state)

    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(GRAPH_AXIS, None), P(GRAPH_AXIS, None), P()),
        out_specs=BfsState(P(), P(), P(), P(), P()),
        axis_names={GRAPH_AXIS},
    )
    return fn(src, dst, source)


def bfs_sharded(
    graph: Graph | DeviceGraph,
    source: int = 0,
    *,
    mesh: Mesh | None = None,
    max_levels: int | None = None,
    block: int = 1024,
) -> BfsResult:
    """Single-source BFS with edges sharded over the mesh's ``graph`` axis."""
    mesh = mesh if mesh is not None else make_mesh()
    dg = _prepare(graph, mesh, block)
    check_sources(dg.num_vertices, source)
    max_levels = int(max_levels) if max_levels is not None else dg.num_vertices
    state = _bfs_sharded_fused(
        jnp.asarray(dg.src).reshape(dg.num_shards, -1),
        jnp.asarray(dg.dst).reshape(dg.num_shards, -1),
        jnp.int32(source),
        mesh=mesh,
        num_vertices=dg.num_vertices,
        max_levels=max_levels,
    )
    state = jax.device_get(state)
    return BfsResult(
        dist=np.asarray(state.dist[: dg.num_vertices]),
        parent=np.asarray(state.parent[: dg.num_vertices]),
        num_levels=int(state.level),
    )


@functools.partial(
    jax.jit, static_argnames=("mesh", "num_vertices", "max_levels")
)
def _bfs_sharded_multi_fused(src, dst, sources, *, mesh, num_vertices, max_levels):
    def inner(src_blk, dst_blk, sources_blk):
        src_e = src_blk.reshape(-1)
        dst_e = dst_blk.reshape(-1)
        state = init_batched_state(num_vertices, sources_blk)

        def cond(s: BfsState):
            return s.changed & (s.level < max_levels)

        def body(s: BfsState):
            return relax_superstep_batched(
                s, src_e, dst_e, axis_name=GRAPH_AXIS, batch_axis_name=BATCH_AXIS
            )

        return jax.lax.while_loop(cond, body, state)

    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(GRAPH_AXIS, None), P(GRAPH_AXIS, None), P(BATCH_AXIS)),
        out_specs=BfsState(
            P(BATCH_AXIS, None), P(BATCH_AXIS, None), P(BATCH_AXIS, None), P(), P()
        ),
        axis_names={GRAPH_AXIS, BATCH_AXIS},
    )
    return fn(src, dst, sources)


def bfs_sharded_multi(
    graph: Graph | DeviceGraph,
    sources,
    *,
    mesh: Mesh | None = None,
    max_levels: int | None = None,
    block: int = 1024,
) -> MultiBfsResult:
    """Batched multi-source BFS: sources sharded over ``batch`` (DP), edges
    over ``graph`` (the context-parallel analogue).  Sources count must be a
    multiple of the batch axis size."""
    mesh = mesh if mesh is not None else make_mesh()
    dg = _prepare(graph, mesh, block)
    sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
    check_sources(dg.num_vertices, sources)
    nb = mesh.shape[BATCH_AXIS]
    if sources.shape[0] % nb != 0:
        raise ValueError(f"{sources.shape[0]} sources not divisible by batch axis {nb}")
    max_levels = int(max_levels) if max_levels is not None else dg.num_vertices
    state = _bfs_sharded_multi_fused(
        jnp.asarray(dg.src).reshape(dg.num_shards, -1),
        jnp.asarray(dg.dst).reshape(dg.num_shards, -1),
        jnp.asarray(sources),
        mesh=mesh,
        num_vertices=dg.num_vertices,
        max_levels=max_levels,
    )
    state = jax.device_get(state)
    v = dg.num_vertices
    return MultiBfsResult(
        sources=sources,
        dist=np.asarray(state.dist[:, :v]),
        parent=np.asarray(state.parent[:, :v]),
        num_levels=int(state.level),
    )
