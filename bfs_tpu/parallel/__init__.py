from .exchange import ExchangeConfig, resolve_exchange  # noqa: F401
from .sharded import make_mesh, bfs_sharded, bfs_sharded_multi, GRAPH_AXIS, BATCH_AXIS  # noqa: F401
