"""Compressed frontier exchange for the sharded relay path (ROADMAP item 1).

The per-superstep exchange is where multi-chip BFS lives or dies
(Compression-and-Sieve, arXiv 1208.5542): a level-synchronous superstep
must hand every shard the global new-frontier bitmap, and shipping it
"flat" — every owned word, every superstep — costs the same wire bytes at
a 3-vertex tail frontier as at the peak level.  This module packages the
exchange as three arms behind one knob:

    BFS_TPU_EXCHANGE = auto | bitmap | delta | flat     (default auto)

  * ``flat`` — the uncompressed oracle: all-gather EVERY owned frontier
    word, padding included (``block/32`` words per shard).  Trivially
    correct, maximally dumb; the arm every other arm is parity-tested
    and byte-compared against.
  * ``bitmap`` — the sieved packed-bitmap arm: each shard gathers only
    its REAL owned words (the per-shard real-word table of
    :func:`bfs_tpu.parallel.sharded._own_word_table` — padding words are
    structurally zero and never ship), after the SIEVE has masked
    already-settled vertices out of the new-frontier bits (the
    ``& unreached`` / lexicographic-min improvement test every superstep
    body applies before packing — a settled vertex can never re-enter
    the wire).  Payload: ``kw`` words/shard, flat in the shard count.
  * ``delta`` — the word-list arm for SPARSE frontiers: each shard ships
    ``(word index, word value)`` pairs for its nonzero frontier words
    only, padded to a static budget of ``B`` entries (``2B`` u32 words on
    the wire vs ``kw``).  Selected per superstep by MEASURED frontier
    density: when any shard's nonzero-word count exceeds ``B`` the
    superstep falls back to the bitmap arm inside the same compiled
    program (one ``lax.cond`` whose predicate is a replicated ``pmax`` of
    the per-shard counts — every shard provably takes the same branch,
    and only the taken branch's collective executes, so the byte saving
    is real, not cosmetic).
  * ``auto`` — the delta arm with its density fallback, i.e. word-lists
    whenever the frontier is sparse enough to fit the budget and sieved
    bitmaps on the dense mid-levels.  ``delta`` differs from ``auto``
    only in the budget default: forced delta sizes ``B`` at ``kw`` so the
    word-list path runs on EVERY superstep (the parity/forcing arm);
    auto sizes it at ``kw/BFS_TPU_EXCHANGE_DIV`` (default 8) so taking
    the delta branch is always a >= 4x payload cut vs the flat arm.

Every arm returns ``(global_words, payload_bytes, arm_code)`` — the bytes
actually placed on the interconnect this superstep (``n * payload_words *
4``; the all-gather convention counts each shard's contribution once) and
the arm that shipped them, both accumulated device-side into the
telemetry level curves (obs/telemetry.py) so every capture reports
bytes-on-the-wire per level next to occupancy.

Wire format (docs/ARCHITECTURE.md has the worked example):

    bitmap payload   u32[kw]        shard s's real owned words, in
                                    ascending local word index (the
                                    own-word table order)
    delta payload    u32[2B]        [0:B)  = local COMPACT word indices of
                                    the nonzero words, ascending, padded
                                    with ``kw`` (= "no entry");
                                    [B:2B) = the matching word values
    flat payload     u32[block/32]  shard s's whole owned word range

Receivers scatter payloads back into the global standard-packed word
space (shard s's words at ``[s*block/32, (s+1)*block/32)``); the compact
arms resolve local word indices through the replicated own-word table.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .. import knobs

#: Arm codes, recorded per level in the telemetry exchange-arm
#: accumulator (0 = level not executed, same convention as the
#: direction codes).
EX_FLAT = 1
EX_BITMAP = 2
EX_DELTA = 3

EX_NAMES = {EX_FLAT: "flat", EX_BITMAP: "bitmap", EX_DELTA: "delta"}

EXCHANGE_MODES = ("auto", "bitmap", "delta", "flat")

#: Default density divisor for the auto arm's delta budget:
#: ``B = ceil(kw / div)`` compact entries -> ``2B ~ kw/4`` payload words
#: when taken, a >= 4x cut vs the flat arm's ``nw >= kw`` words.
DEFAULT_BUDGET_DIV = 8


@dataclass(frozen=True)
class ExchangeConfig:
    """Resolved exchange policy — hashable, so it keys programs and
    journal configs the way DirectionConfig does (a knob flip must map to
    a different compiled program and a different bench journal)."""

    mode: str = "auto"
    budget_div: int = DEFAULT_BUDGET_DIV

    def key(self) -> tuple:
        return (self.mode, int(self.budget_div))

    def delta_budget(self, kw: int) -> int:
        """Static word-list entry budget for a ``kw``-word compact space.
        Forced delta covers every frontier (``B = kw``: the word-list arm
        must be able to ship ANY superstep); auto/bitmap size it at the
        density divisor, and flat never builds a delta branch."""
        if self.mode == "delta":
            return int(kw)
        return max(1, math.ceil(int(kw) / int(self.budget_div)))


def resolve_exchange(mode: str | None = None) -> ExchangeConfig:
    """Env-resolved exchange config; an explicit ``mode`` argument wins
    over ``BFS_TPU_EXCHANGE``.  Unknown modes / non-positive divisors
    raise (same contract as resolve_direction: silently clamping a typo'd
    knob would change what a capture measured)."""
    if mode is None:
        mode = knobs.get("BFS_TPU_EXCHANGE")
    if mode not in EXCHANGE_MODES:
        raise ValueError(
            f"unknown exchange {mode!r}; use 'auto', 'bitmap', 'delta' or "
            "'flat'"
        )
    div = knobs.get("BFS_TPU_EXCHANGE_DIV")
    if div < 1:
        raise ValueError(f"BFS_TPU_EXCHANGE_DIV must be >= 1 (got {div})")
    return ExchangeConfig(mode=mode, budget_div=div)


def bitmap_gather(send, own_all, nw: int, axis_name: str):
    """THE bitmap wire move (single implementation — the standalone
    bitmap arm, the delta arm's density fallback AND the multi-source
    program's exchange all call this): all-gather each shard's compact
    real words and scatter them back into the global padded word space
    through the replicated own-word table (pad duplicates rewrite
    identical values, so the set is deterministic).

    ``send``: u32[..., kw] — this shard's compact words, optional leading
    batch (per-tree) dims.  Returns u32[..., n*nw]."""
    n = own_all.shape[0]
    if send.ndim == 1:
        gath = jax.lax.all_gather(send, axis_name)  # [n, kw]
    else:
        gath = jax.lax.all_gather(send, axis_name, axis=1)  # [s_l, n, kw]
    base = (jnp.arange(n, dtype=jnp.int32) * nw)[:, None]
    flat_idx = (own_all + base).reshape(-1)
    lead = send.shape[:-1]
    out = jnp.zeros((*lead, n * nw), jnp.uint32)
    return out.at[..., flat_idx].set(
        gath.reshape(*lead, -1), unique_indices=False
    )


# bfs_tpu: hot traced
def exchange_flat(send_words, n: int, axis_name: str):
    """The uncompressed oracle arm: all-gather the whole owned word range
    (padding words included).  ``send_words``: uint32[nw] local."""
    fw = jax.lax.all_gather(send_words, axis_name, tiled=True)
    nbytes = jnp.int32(4 * n * send_words.shape[-1])
    return fw, nbytes, jnp.int32(EX_FLAT)


def _bitmap_from_send(send, own_all, nw: int, axis_name: str):
    """:func:`bitmap_gather` plus the arm's byte/code accounting."""
    n, kw = own_all.shape
    fw = bitmap_gather(send, own_all, nw, axis_name)
    return fw, jnp.int32(4 * n * kw), jnp.int32(EX_BITMAP)


# bfs_tpu: hot traced
def exchange_bitmap(send_words, own_local, own_all, nw: int, axis_name: str):
    """Sieved compact-bitmap arm: gather the shard's REAL owned words only
    (``own_local``: int32[kw] local real-word indices; ``own_all``:
    int32[n, kw] every shard's table, replicated), then scatter them back
    into the global padded word space."""
    send = jnp.take(send_words, own_local, axis=-1)
    return _bitmap_from_send(send, own_all, nw, axis_name)


def _dedup_mask(own_local):
    """True at the first occurrence of each real word index (the own-word
    table right-pads by REPEATING the last real index; a duplicated tail
    word must not double-count in the delta arm's density measure or ship
    twice in its word list)."""
    kw = own_local.shape[0]
    first = jnp.ones((1,), bool)
    if kw == 1:
        return first
    return jnp.concatenate([first, own_local[1:] != own_local[:-1]])


# bfs_tpu: hot traced
def exchange_delta(
    send_words, own_local, own_all, nw: int, budget: int, axis_name: str,
    fits_axes=None,
):
    """Word-list arm with density fallback: ship ``(compact index, word)``
    pairs for nonzero words when every shard fits ``budget`` entries, else
    the bitmap arm — ONE replicated ``lax.cond``, only the taken branch's
    collective executes.

    ``fits_axes`` widens the density vote past the gather axis (the 2D
    grid votes over BOTH mesh axes so the whole machine takes one arm per
    superstep per axis — per-group votes would let different mesh rows
    diverge and break the replicated arm-schedule telemetry); ``None``
    keeps the 1D behavior (vote == gather axis)."""
    n = own_all.shape[0]
    kw = own_all.shape[1]
    send = jnp.take(send_words, own_local, axis=-1)
    live = (send != 0) & _dedup_mask(own_local)
    count = live.sum(dtype=jnp.int32)
    fits = jax.lax.pmax(
        count, axis_name if fits_axes is None else fits_axes
    ) <= jnp.int32(budget)

    def delta(send):
        idx = jnp.sort(
            jnp.where(live, jnp.arange(kw, dtype=jnp.int32), jnp.int32(kw))
        )[:budget]
        vals = jnp.where(
            idx < kw, send[jnp.clip(idx, 0, kw - 1)], jnp.uint32(0)
        )
        payload = jnp.concatenate([idx.astype(jnp.uint32), vals])
        gath = jax.lax.all_gather(payload, axis_name)  # [n, 2B]
        gi = gath[:, :budget].astype(jnp.int32)
        gv = gath[:, budget:]
        # Local compact index -> real owned word -> global padded word.
        word = jnp.take_along_axis(own_all, jnp.clip(gi, 0, kw - 1), axis=1)
        base = (jnp.arange(n, dtype=jnp.int32) * nw)[:, None]
        flat = jnp.where(gi < kw, word + base, jnp.int32(n * nw)).reshape(-1)
        out = jnp.zeros((n * nw,), jnp.uint32)
        fw = out.at[flat].set(gv.reshape(-1), mode="drop")
        return fw, jnp.int32(4 * n * 2 * budget), jnp.int32(EX_DELTA)

    def bitmap(send):
        return _bitmap_from_send(send, own_all, nw, axis_name)

    return jax.lax.cond(fits, delta, bitmap, send)


def make_exchange(cfg: ExchangeConfig, kw: int, nw: int, axis_name: str):
    """The per-superstep exchange closure for one resolved config:
    ``(send_words u32[nw], own_local, own_all) -> (global_words u32[n*nw],
    payload_bytes i32, arm_code i32)``.  Static per arm — the knob is part
    of the compiled program, selection inside it is the delta arm's
    density cond only."""
    if cfg.mode == "flat":
        return lambda w, ol, oa: exchange_flat(w, oa.shape[0], axis_name)
    if cfg.mode == "bitmap":
        return lambda w, ol, oa: exchange_bitmap(w, ol, oa, nw, axis_name)
    budget = cfg.delta_budget(kw)
    return lambda w, ol, oa: exchange_delta(
        w, ol, oa, nw, budget, axis_name
    )


def exchange_report(bytes_acc, arm_acc, cfg: ExchangeConfig, kw: int,
                    nw: int, num_shards: int,
                    num_levels: int | None = None) -> dict:
    """JSON-ready ``details.exchange`` from the host accumulators (post
    ``read_telemetry``): per-level bytes-on-the-wire, the per-level arm
    schedule, totals, and the flat-arm baseline the reduction is measured
    against (``n * nw * 4`` bytes per EXECUTED superstep — what the
    uncompressed exchange would have shipped for the SAME search).

    ``num_levels`` is the loop-exit superstep count — exact even when
    the search runs deeper than the TEL_SLOTS accumulator (slots clamp
    the per-level view, not the totals; a trimmed-slot baseline would
    undercount the flat comparison on >127-level searches)."""
    import numpy as np

    bv = np.asarray(bytes_acc, dtype=np.int64)
    av = np.asarray(arm_acc, dtype=np.int64)
    nz = np.flatnonzero(av)
    levels = int(nz[-1]) + 1 if nz.size else 0
    executed = (
        int(num_levels) if num_levels is not None
        else (levels - 1 if levels else 0)
    )
    schedule = [EX_NAMES.get(int(c), "none") for c in av[1:levels]]
    total = int(bv.sum())
    flat_total = int(executed * num_shards * nw * 4)
    out = {
        "arm": cfg.mode,
        "budget_words": int(cfg.delta_budget(kw)),
        "bytes_per_level": [int(x) for x in bv[1:levels]],
        "schedule": schedule,  # index i = the superstep that settled level i+1
        "total_bytes": total,
        "flat_total_bytes": flat_total,
        "reduction_vs_flat": (flat_total / total) if total else None,
        "supersteps": executed,
        "truncated": bool(av[-1] != 0) and executed > levels - 1,
        "delta_supersteps": schedule.count("delta"),
        "bitmap_supersteps": schedule.count("bitmap"),
        "flat_supersteps": schedule.count("flat"),
    }
    return out


# ---------------------------------------------------------------------------
# 2D grid: per-axis arms (ISSUE 17)
#
# On the r x c mesh a superstep has TWO wire moves, armed independently:
#
#   column axis (frontier broadcast) — each cell all-gathers its owned
#     frontier words along the mesh row's c cells, producing the row
#     stripe R_i's frontier [c*nw words].  Semantically the 1D exchange
#     at group size c, so the three 1D arms are reused verbatim (with
#     the delta density vote widened to both axes); the own-word tables
#     passed in are the mesh row's c rows of the replicated [n, kw]
#     table, so the sieve carries over unchanged.
#   row axis (candidate min-reduce) — each mesh column min-reduces
#     per-destination ORIGINAL-source-id candidates (u32, 0xFFFFFFFF =
#     "no candidate") over its r cells, settling the column stripe C_j.
#     Candidates are 32-bit per VERTEX (not packed bits), so the dense
#     reduce is 32x a frontier word and arming matters even more:
#       flat    — lax.pmin over the whole r*block candidate vector
#       bitmap  — compact pmin: candidates regrouped through the mesh
#                 column's own-word tables first, so structurally-padded
#                 words never ship (the row-axis analogue of the sieved
#                 bitmap; same payload shape on every cell of the column,
#                 which is what makes the elementwise pmin correct)
#       delta   — budgeted (index, value) list of live candidates,
#                 all-gather + scatter-min, with the compact-pmin
#                 fallback under ONE both-axes replicated density vote.
#                 Unlike the 1D delta, FORCED delta keeps the fallback:
#                 a static budget covering the dense worst case would be
#                 r*block entries (the flat arm), so the forced budget is
#                 r*kw entries and dense supersteps spill to compact pmin
#                 (docs/ARCHITECTURE.md §25 records the deviation).
#
# Byte accounting keeps the 1D convention (each participant's payload
# counted once: 4 * group_size * payload_words per group) and scales by
# the number of groups (r mesh rows for the column axis, c mesh columns
# for the row axis) so the accumulators record MACHINE totals — divide by
# r*c for per-chip wire.  A size-1 axis is the identity: zero bytes, arm
# code 0 ("none" in the schedule), which is exactly how 1x8 degenerates
# to the 1D semantics.
# ---------------------------------------------------------------------------


def grid_row_budget(cfg: ExchangeConfig, r: int, kw: int) -> int:
    """Static entry budget for the row-axis candidate list: ``r*kw``
    entries forced-delta (one live candidate per real owned word of the
    column stripe — past that density the compact arm is the cheaper
    ship anyway), ``ceil(r*kw / div)`` for auto."""
    if cfg.mode == "delta":
        return int(r * kw)
    return max(1, math.ceil(int(r) * int(kw) / int(cfg.budget_div)))


def make_grid_col_exchange(cfg: ExchangeConfig, kw: int, nw: int,
                           r: int, c: int,
                           col_axis: str = "col", row_axis: str = "row"):
    """Column-axis frontier broadcast closure: ``(send_words u32[nw],
    own_local i32[kw], own_row i32[c, kw]) -> (stripe_words u32[c*nw],
    machine_bytes i32, arm_code i32)``.  ``own_row`` is the mesh row's
    slice of the replicated own-word table (rows ``[i*c, i*c+c)``)."""
    if c == 1:
        return lambda w, ol, orow: (w, jnp.int32(0), jnp.int32(0))
    scale = jnp.int32(r)
    if cfg.mode == "flat":
        def col_flat(w, ol, orow):
            fw, nb, arm = exchange_flat(w, c, col_axis)
            return fw, nb * scale, arm
        return col_flat
    if cfg.mode == "bitmap":
        def col_bitmap(w, ol, orow):
            fw, nb, arm = exchange_bitmap(w, ol, orow, nw, col_axis)
            return fw, nb * scale, arm
        return col_bitmap
    budget = cfg.delta_budget(kw)

    def col_delta(w, ol, orow):
        fw, nb, arm = exchange_delta(
            w, ol, orow, nw, budget, col_axis,
            fits_axes=(row_axis, col_axis),
        )
        return fw, nb * scale, arm
    return col_delta


def make_grid_row_reduce(cfg: ExchangeConfig, kw: int, nw: int,
                         r: int, c: int,
                         row_axis: str = "row", col_axis: str = "col"):
    """Row-axis candidate min-reduce closure: ``(cand u32[r*block],
    own_cj i32[r, kw]) -> (candg u32[r*block], machine_bytes i32,
    arm_code i32)``.  ``cand`` holds min-ORIGINAL-source-id candidates
    for the column stripe C_j (stripe position i2 covers block
    ``i2*c + j`` at ``[i2*block, (i2+1)*block)``), already sieved by the
    caller's reached-carry; ``own_cj`` is the column stripe's own-word
    tables (``own_table[i2*c + j]`` stacked over i2 — identical on every
    cell of the mesh column).  ``candg`` is the replicated min."""
    block = nw * 32
    rb = r * block
    sent = jnp.uint32(0xFFFFFFFF)
    if r == 1:
        return lambda cand, own_cj: (cand, jnp.int32(0), jnp.int32(0))
    groups = jnp.int32(c)

    def row_flat(cand, own_cj):
        candg = jax.lax.pmin(cand, row_axis)
        return candg, jnp.int32(4 * r * rb) * groups, jnp.int32(EX_FLAT)

    def _compact_pmin(cand, own_cj):
        comp = jnp.take_along_axis(
            cand.reshape(r, nw, 32), own_cj[:, :, None], axis=1
        )  # [r, kw, 32] — the column stripe's REAL words only
        comp = jax.lax.pmin(comp, row_axis)
        out3 = jnp.full((r, nw, 32), sent, jnp.uint32)
        out3 = out3.at[jnp.arange(r)[:, None], own_cj, :].set(comp)
        return out3.reshape(rb)

    def row_bitmap(cand, own_cj):
        candg = _compact_pmin(cand, own_cj)
        return candg, jnp.int32(4 * r * (r * kw * 32)) * groups, \
            jnp.int32(EX_BITMAP)

    if cfg.mode == "flat":
        return row_flat
    if cfg.mode == "bitmap":
        return row_bitmap
    budget = grid_row_budget(cfg, r, kw)

    def row_delta(cand, own_cj):
        live = cand != sent
        count = live.sum(dtype=jnp.int32)
        fits = jax.lax.pmax(
            count, (row_axis, col_axis)
        ) <= jnp.int32(budget)

        def lst(cand):
            idx = jnp.sort(
                jnp.where(live, jnp.arange(rb, dtype=jnp.int32),
                          jnp.int32(rb))
            )[:budget]
            vals = jnp.where(idx < rb, cand[jnp.clip(idx, 0, rb - 1)], sent)
            payload = jnp.concatenate([idx.astype(jnp.uint32), vals])
            gath = jax.lax.all_gather(payload, row_axis)  # [r, 2B]
            gi = gath[:, :budget].astype(jnp.int32)
            gv = gath[:, budget:]
            flat = jnp.where(gi < rb, gi, jnp.int32(rb)).reshape(-1)
            candg = jnp.full((rb,), sent, jnp.uint32).at[flat].min(
                gv.reshape(-1), mode="drop"
            )
            return candg, jnp.int32(4 * r * 2 * budget) * groups, \
                jnp.int32(EX_DELTA)

        def fall(cand):
            return row_bitmap(cand, own_cj)

        return jax.lax.cond(fits, lst, fall, cand)
    return row_delta


def grid_exchange_report(col_bytes, col_arms, row_bytes, row_arms,
                         cfg: ExchangeConfig, kw: int, nw: int,
                         r: int, c: int,
                         num_levels: int | None = None) -> dict:
    """JSON-ready ``details.exchange`` for a grid run: the per-level
    byte/arm curves for EACH axis plus the combined totals, against the
    same 1D-flat baseline the 1D report uses (``n * nw * 4`` bytes per
    executed superstep at ``n = r*c`` — what the 1D uncompressed
    exchange ships for the SAME search on the same shard layout).  The
    per-axis column names (``col_bytes``/``row_bytes``) are the contract
    ``tools/ledger_compare.py --exact`` diffs."""
    import numpy as np

    n = r * c
    bvc = np.asarray(col_bytes, dtype=np.int64)
    avc = np.asarray(col_arms, dtype=np.int64)
    bvr = np.asarray(row_bytes, dtype=np.int64)
    avr = np.asarray(row_arms, dtype=np.int64)
    nz = np.flatnonzero(avc | avr)
    levels = int(nz[-1]) + 1 if nz.size else 0
    executed = (
        int(num_levels) if num_levels is not None
        else (levels - 1 if levels else 0)
    )
    if num_levels is not None and executed + 1 < len(avc):
        # Size-1-axis runs leave one accumulator all-zero; trust the
        # loop-exit count for the per-level window in that case too.
        levels = max(levels, min(executed + 1, len(avc)))
    col_sched = [EX_NAMES.get(int(x), "none") for x in avc[1:levels]]
    row_sched = [EX_NAMES.get(int(x), "none") for x in avr[1:levels]]
    col_total = int(bvc.sum())
    row_total = int(bvr.sum())
    total = col_total + row_total
    flat_total = int(executed * n * nw * 4)
    per_level = [int(a + b) for a, b in zip(bvc[1:levels], bvr[1:levels])]
    return {
        "arm": cfg.mode,
        "mesh": f"{r}x{c}",
        "col_budget_words": int(cfg.delta_budget(kw)),
        "row_budget_entries": int(grid_row_budget(cfg, r, kw)),
        "col_bytes": [int(x) for x in bvc[1:levels]],
        "row_bytes": [int(x) for x in bvr[1:levels]],
        "bytes_per_level": per_level,
        "col_schedule": col_sched,
        "row_schedule": row_sched,
        # index i = the superstep that settled level i+1 (1D convention)
        "schedule": [
            f"{a}+{b}" for a, b in zip(col_sched, row_sched)
        ],
        "col_total_bytes": col_total,
        "row_total_bytes": row_total,
        "total_bytes": total,
        "flat_total_bytes": flat_total,
        "reduction_vs_flat": (flat_total / total) if total else None,
        "per_chip_bytes": (total / n) if n else 0.0,
        "supersteps": executed,
        "truncated": bool((avc[-1] | avr[-1]) != 0) and executed > levels - 1,
        "axes": {
            "col": {"size": c, "groups": r, "total_bytes": col_total},
            "row": {"size": r, "groups": c, "total_bytes": row_total},
        },
    }
