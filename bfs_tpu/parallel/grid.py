"""2D grid-sharded BFS: row/column mesh axes over the tile space (ISSUE 17).

The 1D dst-owned mesh (parallel/sharded.py) moves O(V) frontier words per
chip per superstep no matter how many chips participate — the exchange is
one global all-gather, so adding chips shrinks compute but not wire.
This module places the adjacency on an ``r x c`` logical mesh instead
(the classic 2D decomposition of arXiv 1408.1605 / 1208.5542, carried
onto the TPU tile space): cell ``(i, j)`` holds the edges from row
stripe ``R_i`` (source blocks ``[i*c, (i+1)*c)``) into column stripe
``C_j`` (destination blocks ``{i'*c + j}``), and a superstep is

  1. candidate production LOCAL to the cell — dense masked scatter-min
     over the resident edge block, or the budgeted frontier-list gather,
     selected per superstep by the SAME Beamer predicate as every other
     engine (global masses via one scalar ``psum`` over the row axis);
  2. the reduce-axis SIEVE — each cell carries the reached-view of its
     column stripe, so settled destinations never enter the wire;
  3. a ROW-AXIS armed min-reduce of per-destination ORIGINAL-source-id
     candidates (exchange.make_grid_row_reduce) — the mesh column
     settles ``C_j`` (V/c destinations);
  4. the local state update on the owned block, then a COL-AXIS armed
     broadcast of the cell's new frontier words
     (exchange.make_grid_col_exchange) — the mesh row reassembles the
     ``R_i`` frontier (V/r bits) for the next superstep.

Per-chip wire is O(V/r + V/c) = O(V/sqrt(n)) on a square mesh.  At
``1 x n`` the program degenerates to the 1D semantics exactly: the row
reduce is the identity (zero bytes, arm "none") and the column broadcast
IS the 1D exchange — same arms, same budgets, same per-level bytes.

Bit-identity contract (tests/test_grid.py): candidates are min ORIGINAL
source ids — the MXU arm's parent flavor — so dist/parent equal the 1D
mesh and the single-chip engines bit-for-bit at ANY mesh shape; the
direction schedule is bit-identical because the predicate sees the exact
same masses (float32 sums of per-vertex integer out-degrees are exact
below 2^24 edges, so the row-axis ``psum`` re-association cannot drift);
and the column-axis arm schedule and per-level bytes equal the 1D
exchange's, because the column broadcast ships the same sieved frontier
words under the same density vote.

The packed carry is the ``level:6 | origid:26`` word (ops/packed.py, the
mxu flavor), gated on ``packed_parent_fits`` and capped at 62 levels
with the standard truncation re-run, and the segmented twin checkpoints
per-CELL epochs cut at the axis-exchange boundary (resilience/).
"""

from __future__ import annotations

import functools
import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import Mesh, PartitionSpec as P

from .compat import pcast_carry, pcast_varying, shard_map as _shard_map
from .. import knobs
from ..graph.grid_layout import (
    GRID_KEY_SENTINEL,
    grid_layout_for,
    parse_mesh_spec,
)
from ..models.bfs import BfsResult, check_sources
from ..ops.relax import INT32_MAX

GRID_ROW_AXIS = "row"
GRID_COL_AXIS = "col"


def resolve_grid_mesh(spec: str | None = None) -> tuple[int, int]:
    """``(r, c)`` from an explicit spec or ``BFS_TPU_MESH`` (``"rxc"``);
    no knob -> the 1D degenerate ``1 x num_devices``."""
    if spec is None:
        spec = knobs.get("BFS_TPU_MESH") or ""
    if not spec:
        return 1, len(jax.devices())
    return parse_mesh_spec(spec)


def make_grid_mesh(
    r: int, c: int, *, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build the ``(row, col)`` mesh — row-major over the device list,
    matching the cell index ``i*c + j`` of the layout and the
    checkpoint-shard order."""
    devices = list(devices if devices is not None else jax.devices())
    if r * c > len(devices):
        raise ValueError(
            f"mesh {r}x{c} needs {r * c} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[: r * c]).reshape(r, c)
    return Mesh(arr, (GRID_ROW_AXIS, GRID_COL_AXIS))


def _grid_shape(mesh: Mesh) -> tuple[int, int]:
    return mesh.shape[GRID_ROW_AXIS], mesh.shape[GRID_COL_AXIS]


def _grid_static(layout, packed: bool) -> tuple:
    """Hashable static tuple: (r, c, block, emax, packed)."""
    return (layout.r, layout.c, layout.block, layout.emax, packed)


def _grid_dev_operands(srg, r: int, c: int):
    """Device-resident stacked per-cell layout operands, memoized on the
    layout object (layout data — must not land inside timed repeats)."""
    key = f"_grid_dev_{r}x{c}"
    cached = getattr(srg, key, None)
    if cached is None:
        lo = grid_layout_for(srg, r, c)
        cached = (
            jnp.asarray(lo.esrc), jnp.asarray(lo.edst),
            jnp.asarray(lo.ekey), jnp.asarray(lo.indptr),
        )
        object.__setattr__(srg, key, cached)
    return cached


def _grid_superstep_builder(
    esrc, edst, ekey, indptr, own_all, outdeg, *,
    r: int, c: int, block: int, emax: int, packed: bool,
    cap, telemetry: bool, mode, dir_params, ex_cfg,
):
    """Shared cond/body construction for the fused and segmented grid
    programs (ONE superstep definition — the segment twin must replay the
    fused schedule bit-identically, so they compile the same closure).
    Called INSIDE the shard_map body with per-cell operands."""
    from ..ops.packed import level_word
    from ..ops.relay import pack_std, unpack_std
    from .exchange import make_grid_col_exchange, make_grid_row_reduce

    nw = block // 32
    rb = r * block
    gtot = r * c * block
    kw = own_all.shape[1]
    sent = jnp.uint32(GRID_KEY_SENTINEL)
    i_idx = jax.lax.axis_index(GRID_ROW_AXIS).astype(jnp.int32)
    j_idx = jax.lax.axis_index(GRID_COL_AXIS).astype(jnp.int32)
    cell = i_idx * c + j_idx
    own_local = own_all[cell]
    own_row = jax.lax.dynamic_slice(
        own_all, (i_idx * c, jnp.int32(0)), (c, kw)
    )
    own_cj = jnp.take(own_all.reshape(r, c, kw), j_idx, axis=1)  # [r, kw]
    col_fn = make_grid_col_exchange(
        ex_cfg, kw, nw, r, c, GRID_COL_AXIS, GRID_ROW_AXIS
    )
    row_fn = make_grid_row_reduce(
        ex_cfg, kw, nw, r, c, GRID_ROW_AXIS, GRID_COL_AXIS
    )

    if mode in ("auto", "push"):
        from ..models.bfs import sparse_budgets
        from ..models.direction import frontier_masses_words

        dir_alpha, dir_beta, v_real, e_real = dir_params
        # Global budgets: the SAME derivation as the 1D predicate (so the
        # dispatch agrees superstep-for-superstep); per-cell capacities
        # clamp to the stripe/cell sizes the global predicate bounds.
        bv, _ = sparse_budgets(gtot, gtot)
        _, be_pred = sparse_budgets(gtot, e_real)
        bv_cell, _ = sparse_budgets(c * block, 1)
        _, be_cell = sparse_budgets(gtot, emax)
        outdeg_stripe = jax.lax.dynamic_slice(
            outdeg, (i_idx * c * block,), (c * block,)
        )

        def global_masses(fwr):
            # Per-stripe masses + one scalar psum over the row axis: the
            # R_i stripes partition the vertex space, and float32 sums of
            # integer out-degrees are exact below 2^24 edges, so the
            # re-association vs the 1D single-pass sum cannot drift.
            fs_i, fe_i = frontier_masses_words(
                fwr, outdeg_stripe, c * block
            )
            return (
                jax.lax.psum(fs_i, GRID_ROW_AXIS),
                jax.lax.psum(fe_i, GRID_ROW_AXIS),
            )

        def budget_ok(fsize, fe):
            return (fsize <= bv) & (fe <= jnp.float32(be_pred))

    if telemetry:
        from ..obs import telemetry as T

    def dense_cand(fwr):
        """Dense body: masked scatter-min over ALL resident edges — the
        per-edge frontier bit gates the ORIGINAL-src-id key."""
        w = fwr[esrc >> 5]
        active = ((w >> (esrc & 31).astype(jnp.uint32)) & 1) == 1
        keys = jnp.where(active, ekey, sent)
        return (
            jnp.full((rb,), sent, jnp.uint32)
            .at[edst].min(keys, mode="drop")
        )

    def push_cand(fwr):
        """Push body: budgeted frontier-list gather over the cell CSR
        (the grid twin of _sharded_push_candidates, min-scatter form)."""
        from ..models.bfs import _extract_frontier_list

        flist = _extract_frontier_list(fwr, c * block, bv_cell)
        deg = indptr[flist + 1] - indptr[flist]  # 0 at the c*block fill
        cum = jnp.cumsum(deg)
        starts = indptr[flist]
        j = jnp.arange(be_cell, dtype=jnp.int32)
        owner = jnp.searchsorted(cum, j, side="right").astype(jnp.int32)
        owner_c = jnp.clip(owner, 0, bv_cell - 1)
        prev = jnp.where(owner_c > 0, cum[jnp.maximum(owner_c - 1, 0)], 0)
        eidx = starts[owner_c] + (j - prev)
        valid = j < cum[-1]
        eidx = jnp.where(valid, eidx, 0)
        keys = jnp.where(valid, ekey[eidx], sent)
        dst = jnp.where(valid, edst[eidx], jnp.int32(rb))
        return (
            jnp.full((rb,), sent, jnp.uint32)
            .at[dst].min(keys, mode="drop")
        )

    def cond(c_):
        return c_["changed"] & (c_["level"] < cap)

    def body(c_):
        fwr, level, rcv = c_["fw"], c_["level"], c_["rc"]

        # ---- per-superstep body selection (replicated scalar psum) ----
        if mode == "auto":
            from ..models.direction import take_pull

            fsize, fe = global_masses(fwr)
            m_u = jnp.maximum(c_["mu"] - fe, 0.0)
            use_pull = (
                take_pull(
                    c_["prev"], fsize, fe, m_u, v_real, dir_alpha, dir_beta
                )
                | ~budget_ok(fsize, fe)
            )
        elif mode == "push":
            fsize, fe = global_masses(fwr)
            use_pull = ~budget_ok(fsize, fe)
        else:
            use_pull = None

        if use_pull is None:
            cand = dense_cand(fwr)
        else:
            cand = jax.lax.cond(use_pull, dense_cand, push_cand, fwr)

        # ---- reduce-axis SIEVE: settled C_j dsts never enter the wire --
        reached = unpack_std(rcv, rb) != 0
        cand = jnp.where(reached, sent, cand)

        # ---- row-axis armed min-reduce: the column settles C_j ---------
        candg, xbr, xar = row_fn(cand, own_cj)

        # ---- improvement + state update on the owned block -------------
        level2 = level + 1
        imp = pack_std(candg != sent)  # [r*nw] — C_j's new frontier bits
        candg_own = jax.lax.dynamic_slice(candg, (i_idx * block,), (block,))
        fw_own = jax.lax.dynamic_slice(imp, (i_idx * nw,), (nw,))
        out = dict(c_)
        out["rc"] = rcv | imp
        if packed:
            candw = candg_own | level_word(level2)
            out["pk"] = jnp.minimum(c_["pk"], candw)
        else:
            improved = candg_own != sent
            out["dist"] = jnp.where(improved, level2, c_["dist"])
            out["parent"] = jnp.where(
                improved, candg_own.astype(jnp.int32), c_["parent"]
            )

        # ---- col-axis armed broadcast: the row reassembles R_i ---------
        fwr2, xbc, xac = col_fn(fw_own, own_local, own_row)
        cnt = jax.lax.psum(
            jax.lax.population_count(fw_own).sum(dtype=jnp.int32),
            (GRID_ROW_AXIS, GRID_COL_AXIS),
        )
        out["fw"] = fwr2
        out["level"] = level2
        out["changed"] = cnt > 0
        if mode == "auto":
            out["mu"] = m_u
            out["prev"] = use_pull
        if telemetry:
            out["occ"] = T.record_count(c_["occ"], level2, cnt)
            if use_pull is None:
                code = jnp.int32(T.DIR_PULL)
            else:
                code = jnp.where(
                    use_pull, jnp.int32(T.DIR_PULL), jnp.int32(T.DIR_PUSH)
                )
            out["dirs"] = T.record_direction(c_["dirs"], level2, code)
            out["xbc"], out["xac"] = T.record_exchange(
                c_["xbc"], c_["xac"], level2, xbc, xac
            )
            out["xbr"], out["xar"] = T.record_exchange(
                c_["xbr"], c_["xar"], level2, xbr, xar
            )
        return out

    return cond, body, (i_idx, j_idx, cell)


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "static", "max_levels", "telemetry", "direction", "exchange",
    ),
)
def _bfs_grid_fused(
    esrc, edst, ekey, indptr, own_words, outdeg, source_new, *,
    mesh, static, max_levels, telemetry: bool = False,
    direction: tuple | None = None, exchange: tuple = ("bitmap", 8),
):
    """The fused 2D grid BFS program: one compiled superstep loop over
    the r x c mesh, two armed collectives per superstep (one per axis),
    per-axis byte/arm telemetry accumulated device-side and pulled once
    at loop exit.  ``static`` is :func:`_grid_static`; ``direction`` the
    ``(mode, alpha, beta, V, E)`` tuple; ``exchange`` the resolved
    ExchangeConfig key."""
    from ..ops.packed import PACKED_SENTINEL, packed_cap
    from .exchange import ExchangeConfig

    r, c, block, emax, packed = static
    nw = block // 32
    gtot = r * c * block
    cap = packed_cap(max_levels) if packed else max_levels
    ex_cfg = ExchangeConfig(*exchange)
    mode = direction[0] if direction is not None else None
    if mode in ("auto", "push"):
        dir_params = (
            float(direction[1]),  # bfs_tpu: ok TRC002 static tuple member
            float(direction[2]),  # bfs_tpu: ok TRC002 static tuple member
            int(direction[3]),  # bfs_tpu: ok TRC002 static tuple member
            int(direction[4]),  # bfs_tpu: ok TRC002 static tuple member
        )
    else:
        dir_params = None

    def inner(esrc_b, edst_b, ekey_b, indptr_b, own_all, outdeg, source):
        cond, body, (i_idx, j_idx, cell) = _grid_superstep_builder(
            esrc_b[0], edst_b[0], ekey_b[0], indptr_b[0], own_all, outdeg,
            r=r, c=c, block=block, emax=emax, packed=packed, cap=cap,
            telemetry=telemetry, mode=mode, dir_params=dir_params,
            ex_cfg=ex_cfg,
        )
        # Initial R_i stripe frontier: the source bit, sliced from the
        # replicated global word space by the row index.
        gw = (
            jnp.zeros((gtot // 32,), jnp.uint32)
            .at[source >> 5]
            .set(jnp.uint32(1) << (source & 31).astype(jnp.uint32))
        )
        fwr = jax.lax.dynamic_slice(gw, (i_idx * c * nw,), (c * nw,))
        fwr = pcast_varying(fwr, (GRID_ROW_AXIS,))
        # Initial reached-view of C_j: the source bit iff the source
        # block sits in this mesh column.
        sb = source // block
        within = source - sb * block
        present = (sb % c) == j_idx
        widx = (sb // c) * nw + (within >> 5)
        rc0 = (
            jnp.zeros((r * nw,), jnp.uint32)
            .at[widx]
            .set(
                jnp.where(
                    present,
                    jnp.uint32(1) << (within & 31).astype(jnp.uint32),
                    jnp.uint32(0),
                )
            )
        )
        rc0 = pcast_varying(rc0, (GRID_COL_AXIS,))

        carry = {
            "fw": fwr,
            "rc": rc0,
            "level": jnp.int32(0),
            "changed": jnp.bool_(True),
        }
        lo = cell * block
        ids_local = lo + jnp.arange(block, dtype=jnp.int32)
        if packed:
            carry["pk"] = jnp.where(
                ids_local == source, jnp.uint32(0), PACKED_SENTINEL
            )
        else:
            carry["dist"] = jnp.where(
                ids_local == source, jnp.int32(0), INT32_MAX
            )
            carry["parent"] = jnp.where(
                ids_local == source, source, jnp.int32(-1)
            )
        extras = {}
        if mode == "auto":
            extras["mu"] = outdeg.astype(jnp.float32).sum()
            extras["prev"] = jnp.bool_(False)
        if telemetry:
            from ..obs import telemetry as T

            extras["occ"] = T.init_level_acc()
            extras["dirs"] = T.init_dir_acc()
            extras["xbc"] = T.init_bytes_acc()
            extras["xac"] = T.init_dir_acc()
            extras["xbr"] = T.init_bytes_acc()
            extras["xar"] = T.init_dir_acc()
        carry.update(
            pcast_carry(extras, (GRID_ROW_AXIS, GRID_COL_AXIS))
        )

        out = jax.lax.while_loop(cond, body, carry)
        if packed:
            from ..ops.packed import packed_dist, packed_parent

            dist, parent = packed_dist(out["pk"]), packed_parent(out["pk"])
        else:
            dist, parent = out["dist"], out["parent"]
        if telemetry:
            return (
                dist, parent, out["level"], out["changed"],
                out["occ"], out["dirs"],
                out["xbc"], out["xac"], out["xbr"], out["xar"],
            )
        return dist, parent, out["level"], out["changed"]

    both = (GRID_ROW_AXIS, GRID_COL_AXIS)
    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            P(both, None), P(both, None), P(both, None), P(both, None),
            P(), P(), P(),
        ),
        out_specs=(
            (P(both), P(both), P(), P(), P(), P(), P(), P(), P(), P())
            if telemetry
            else (P(both), P(both), P(), P())
        ),
        # Fully manual over both mesh axes (same contract as the 1D
        # programs: no partial-auto program exists in this repo).
        axis_names={GRID_ROW_AXIS, GRID_COL_AXIS},
    )
    return fn(esrc, edst, ekey, indptr, own_words, outdeg, source_new)


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "static", "max_levels", "telemetry", "direction", "exchange",
    ),
)
def _bfs_grid_segment(
    carry, seg_end, esrc, edst, ekey, indptr, own_words, outdeg, *,
    mesh, static, max_levels, telemetry: bool = False,
    direction: tuple | None = None, exchange: tuple = ("bitmap", 8),
):
    """ONE bounded segment of the grid loop: the checkpointable twin of
    :func:`_bfs_grid_fused` — the identical superstep body (same builder
    closure), stopped at ``seg_end`` supersteps so the host can snapshot
    the carry at the AXIS-EXCHANGE BOUNDARY (after the column broadcast —
    the per-superstep consistency point) and write per-CELL checkpoint
    shards.  A resumed run replays the direction schedule AND both
    per-axis arm sequences bit-identically (the hysteresis pair, the
    reached-views and all six accumulators ride the carry)."""
    from ..ops.packed import packed_cap
    from .exchange import ExchangeConfig

    r, c, block, emax, packed = static
    cap = packed_cap(max_levels) if packed else max_levels
    ex_cfg = ExchangeConfig(*exchange)
    mode = direction[0] if direction is not None else None
    if mode in ("auto", "push"):
        dir_params = (
            float(direction[1]),  # bfs_tpu: ok TRC002 static tuple member
            float(direction[2]),  # bfs_tpu: ok TRC002 static tuple member
            int(direction[3]),  # bfs_tpu: ok TRC002 static tuple member
            int(direction[4]),  # bfs_tpu: ok TRC002 static tuple member
        )
    else:
        dir_params = None
    state_keys = ("pk",) if packed else ("dist", "parent")

    def inner(c_, seg_end, esrc_b, edst_b, ekey_b, indptr_b, own_all,
              outdeg):
        cond0, body, _ = _grid_superstep_builder(
            esrc_b[0], edst_b[0], ekey_b[0], indptr_b[0], own_all, outdeg,
            r=r, c=c, block=block, emax=emax, packed=packed, cap=cap,
            telemetry=telemetry, mode=mode, dir_params=dir_params,
            ex_cfg=ex_cfg,
        )
        c_ = dict(c_)
        c_["fw"] = pcast_varying(c_["fw"], (GRID_ROW_AXIS,))
        extras = {
            k: c_[k]
            for k in ("mu", "prev", "occ", "dirs", "xbc", "xac",
                      "xbr", "xar")
            if k in c_
        }
        c_.update(pcast_carry(extras, (GRID_ROW_AXIS, GRID_COL_AXIS)))

        def cond(c_):
            return cond0(c_) & (c_["level"] < seg_end)

        return jax.lax.while_loop(cond, body, c_)

    both = (GRID_ROW_AXIS, GRID_COL_AXIS)
    carry_specs = {}
    for k in carry:
        if k in state_keys or k == "rc":
            carry_specs[k] = P(both)
        elif k == "fw":
            carry_specs[k] = P(GRID_ROW_AXIS)
        else:
            carry_specs[k] = P()
    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            carry_specs, P(),
            P(both, None), P(both, None), P(both, None), P(both, None),
            P(), P(),
        ),
        out_specs=carry_specs,
        axis_names={GRID_ROW_AXIS, GRID_COL_AXIS},
    )
    return fn(carry, seg_end, esrc, edst, ekey, indptr, own_words, outdeg)


def grid_segment_keys(packed: bool, auto: bool, telemetry: bool) -> list[str]:
    """The grid segment carry's key set — the ONE definition
    :func:`grid_segment_carry` builds from and the restore gate validates
    against.  ``rc`` (the per-cell reached-views) is exact loop state: a
    resume without it would re-admit settled destinations into the
    row-axis wire and change the per-axis byte curves."""
    keys = (["pk"] if packed else ["dist", "parent"]) + [
        "fw", "rc", "level", "changed",
    ]
    if auto:
        keys += ["mu", "prev"]
    if telemetry:
        keys += ["occ", "dirs", "xbc", "xac", "xbr", "xar"]
    return keys


def grid_segment_carry(srg, r: int, c: int, source_new: int, packed: bool,
                       auto: bool, telemetry: bool, outdeg_dev,
                       restore: dict | None = None) -> dict:
    """Initial (or checkpoint-restored) global-view carry for
    :func:`_bfs_grid_segment`.  Global layouts: state ``[gtot]``
    (cell-major — cell ``i*c+j`` owns block ``i*c+j``), ``fw``
    ``[gtot/32]`` (the full frontier word space, row-stripe partitioned),
    ``rc`` ``[n * r*nw]`` (cell-major stack of per-cell C_j
    reached-views)."""
    from ..obs import telemetry as T
    from ..ops.packed import PACKED_SENTINEL

    n = r * c
    block = srg.block
    gtot = n * block
    nw = block // 32
    keys = grid_segment_keys(packed, auto, telemetry)
    if restore is not None:
        return {k: jnp.asarray(restore[k]) for k in keys}
    if packed:
        pk = np.full(gtot, PACKED_SENTINEL, np.uint32)
        pk[source_new] = np.uint32(0)
        carry = {"pk": jnp.asarray(pk)}
    else:
        dist = np.full(gtot, INT32_MAX, np.int32)
        dist[source_new] = 0
        parent = np.full(gtot, -1, np.int32)
        parent[source_new] = source_new
        carry = {"dist": jnp.asarray(dist), "parent": jnp.asarray(parent)}
    fw = np.zeros(gtot // 32, np.uint32)
    fw[source_new >> 5] = np.uint32(1) << np.uint32(source_new & 31)
    rc = np.zeros((n, r * nw), np.uint32)
    sb = source_new // block
    widx = (sb // c) * nw + ((source_new % block) >> 5)
    bit = np.uint32(1) << np.uint32(source_new & 31)
    for i in range(r):
        rc[i * c + sb % c, widx] = bit
    carry.update(
        fw=jnp.asarray(fw), rc=jnp.asarray(rc.reshape(-1)),
        level=jnp.int32(0), changed=jnp.bool_(True),
    )
    if auto:
        carry["mu"] = outdeg_dev.astype(jnp.float32).sum()
        carry["prev"] = jnp.bool_(False)
    if telemetry:
        carry["occ"] = T.init_level_acc()
        carry["dirs"] = T.init_dir_acc()
        carry["xbc"] = T.init_bytes_acc()
        carry["xac"] = T.init_dir_acc()
        carry["xbr"] = T.init_bytes_acc()
        carry["xar"] = T.init_dir_acc()
    return carry


def _prepare_grid(graph, n: int):
    from ..graph.relay import ShardedRelayGraph, build_sharded_relay_graph

    if isinstance(graph, ShardedRelayGraph):
        if graph.num_shards != n:
            raise ValueError(
                f"ShardedRelayGraph has {graph.num_shards} shards but the "
                f"grid has {n} cells; rebuild with num_shards={n}"
            )
        return graph
    return build_sharded_relay_graph(graph, n)


def _grid_curve(accs, *, dir_cfg, ex_cfg, kw, nw, r, c, cap, num_levels):
    from ..obs.telemetry import (
        direction_schedule,
        level_curve,
        read_telemetry,
    )
    from .exchange import grid_exchange_report

    fv, dirs, xbc, xac, xbr, xar = read_telemetry(accs)
    curve = level_curve(fv, cap=cap)
    curve["direction_schedule"] = direction_schedule(
        dirs, mode=dir_cfg.mode, alpha=dir_cfg.alpha, beta=dir_cfg.beta
    )
    curve["exchange"] = grid_exchange_report(
        xbc, xac, xbr, xar, ex_cfg, kw, nw, r, c, num_levels=num_levels
    )
    return curve


def bfs_grid(
    graph,
    source: int = 0,
    *,
    mesh: Mesh | None = None,
    max_levels: int | None = None,
    telemetry: bool = False,
    direction: str | None = None,
    exchange: str | None = None,
):
    """2D grid-sharded BFS — the host entry point (``BFS_TPU_MESH=rxc``
    selects the mesh shape when ``mesh`` is not given).  Accepts a
    :class:`~bfs_tpu.graph.csr.Graph` or a prebuilt ``r*c``-shard
    ShardedRelayGraph; returns :class:`~bfs_tpu.models.bfs.BfsResult`
    (plus the level curve with per-axis ``details.exchange`` under
    ``telemetry=True``) — dist/parent bit-identical to the 1D mesh and
    the single-chip engines."""
    from ..models.direction import resolve_direction
    from ..ops.packed import (
        PACKED_MAX_LEVELS,
        packed_parent_fits,
        packed_truncated,
        resolve_packed,
    )
    from .exchange import resolve_exchange
    from .sharded import _own_word_table_dev, _relay_map_back

    if mesh is None:
        r, c = resolve_grid_mesh()
        mesh = make_grid_mesh(r, c)
    r, c = _grid_shape(mesh)
    n = r * c
    dir_cfg = resolve_direction(direction)
    ex_cfg = resolve_exchange(exchange)
    srg = _prepare_grid(graph, n)
    check_sources(srg.num_vertices, source)
    max_levels = (
        int(max_levels) if max_levels is not None else srg.num_vertices
    )
    source_new = int(srg.old2new[source])
    layout = grid_layout_for(srg, r, c)
    operands = _grid_dev_operands(srg, r, c)
    own_dev = _own_word_table_dev(srg)
    outdeg_dev = jnp.asarray(srg.outdeg)
    direction_static = (
        dir_cfg.mode, dir_cfg.alpha, dir_cfg.beta,
        srg.num_vertices, srg.num_edges,
    )
    src_dev = jnp.int32(source_new)

    def run_flavor(packed: bool):
        out = _bfs_grid_fused(
            *operands, own_dev, outdeg_dev, src_dev,
            mesh=mesh, static=_grid_static(layout, packed),
            max_levels=max_levels, telemetry=telemetry,
            direction=direction_static, exchange=ex_cfg.key(),
        )
        dist, parent, level, changed = out[:4]
        return (
            np.asarray(jax.device_get(dist)),
            np.asarray(jax.device_get(parent)),
            int(jax.device_get(level)), bool(jax.device_get(changed)),
            out[4:],
        )

    packed = resolve_packed(packed_parent_fits(srg.num_vertices))
    dist, parent, level, changed, accs = run_flavor(packed)
    if packed and packed_truncated(changed, level, max_levels):
        # Cap exit with room left: the search is deeper than the 62-level
        # packed field — re-run unpacked (same contract as every packed
        # engine; the host wrapper owns the fallback).
        packed = False
        dist, parent, level, changed, accs = run_flavor(packed)
    dist, parent = _relay_map_back(srg, dist, parent, source, "mxu")
    result = BfsResult(dist=dist, parent=parent, num_levels=level)
    if not telemetry:
        return result
    cap = min(PACKED_MAX_LEVELS, max_levels) if packed else max_levels
    curve = _grid_curve(
        accs, dir_cfg=dir_cfg, ex_cfg=ex_cfg, kw=int(own_dev.shape[1]),
        nw=srg.block // 32, r=r, c=c, cap=cap,
        num_levels=result.num_levels,
    )
    return result, curve


def bfs_grid_segmented(
    graph,
    source: int = 0,
    *,
    mesh: Mesh | None = None,
    ckpt,
    max_levels: int | None = None,
    telemetry: bool = False,
    direction: str | None = None,
    exchange: str | None = None,
):
    """Segmented-with-checkpoints grid BFS: the resumable twin of
    :func:`bfs_grid` — bit-identical dist/parent, direction schedule and
    BOTH per-axis exchange-arm sequences for any segmentation.  Each
    segment ends at the axis-exchange boundary; one epoch = per-CELL
    state shards (``ckpt.shards == r*c``, cell-major — the same shard
    files a 1D run at ``n`` shards would cut, so shard-loss fallback is
    shared machinery) plus a meta file carrying the frontier words, the
    reached-views, the hysteresis pair and all six accumulators."""
    import time as _time

    from ..models.direction import resolve_direction
    from ..ops.packed import (
        PACKED_MAX_LEVELS,
        packed_cap,
        packed_parent_fits,
        packed_truncated,
        resolve_packed,
    )
    from ..resilience.superstep_ckpt import restore_arrays
    from .exchange import resolve_exchange
    from .sharded import _own_word_table_dev, _relay_map_back

    if mesh is None:
        r, c = resolve_grid_mesh()
        mesh = make_grid_mesh(r, c)
    r, c = _grid_shape(mesh)
    n = r * c
    dir_cfg = resolve_direction(direction)
    ex_cfg = resolve_exchange(exchange)
    srg = _prepare_grid(graph, n)
    if getattr(ckpt, "shards", 1) != n:
        raise ValueError(
            f"checkpointer built for {getattr(ckpt, 'shards', 1)} shards "
            f"but the {r}x{c} grid has {n} cells"
        )
    check_sources(srg.num_vertices, source)
    max_levels = (
        int(max_levels) if max_levels is not None else srg.num_vertices
    )
    source_new = int(srg.old2new[source])
    block = srg.block
    layout = grid_layout_for(srg, r, c)
    operands = _grid_dev_operands(srg, r, c)
    own_dev = _own_word_table_dev(srg)
    outdeg_dev = jnp.asarray(srg.outdeg)
    auto = dir_cfg.mode == "auto"
    direction_static = (
        dir_cfg.mode, dir_cfg.alpha, dir_cfg.beta,
        srg.num_vertices, srg.num_edges,
    )

    def run_flavor(packed: bool):
        cap = packed_cap(max_levels) if packed else max_levels
        state_keys = ("pk",) if packed else ("dist", "parent")
        meta_arrays, shard_arrays = restore_arrays(
            ckpt, packed,
            require=tuple(
                k for k in grid_segment_keys(packed, auto, telemetry)
                if k not in state_keys
            ),
            require_shards=state_keys,
        )
        restore = None
        if meta_arrays is not None:
            restore = dict(meta_arrays)
            for k in state_keys:
                restore[k] = np.concatenate([sa[k] for sa in shard_arrays])
        carry = grid_segment_carry(
            srg, r, c, source_new, packed, auto, telemetry, outdeg_dev,
            restore=restore,
        )
        level, changed = jax.device_get((carry["level"], carry["changed"]))
        while bool(changed) and int(level) < cap:
            seg_end = jax.device_put(
                np.int32(min(int(level) + ckpt.interval(), cap))
            )
            t0 = _time.perf_counter()
            carry = _bfs_grid_segment(
                carry, seg_end, *operands, own_dev, outdeg_dev,
                mesh=mesh, static=_grid_static(layout, packed),
                max_levels=max_levels, telemetry=telemetry,
                direction=direction_static, exchange=ex_cfg.key(),
            )
            new_level, changed = jax.device_get(
                (carry["level"], carry["changed"])
            )
            seg_s = _time.perf_counter() - t0
            meta_arrays, shard_arrays = {}, []
            if ckpt.enabled:
                host = {
                    k: np.asarray(v)
                    for k, v in jax.device_get(carry).items()
                }
                meta_arrays = {
                    k: v for k, v in host.items() if k not in state_keys
                }
                meta_arrays["packed_flag"] = np.int32(packed)
                shard_arrays = [
                    {k: host[k][s * block:(s + 1) * block]
                     for k in state_keys}
                    for s in range(n)
                ]
            ckpt.save_epoch(int(new_level), meta_arrays, shard_arrays)
            ckpt.note_segment(int(new_level) - int(level), seg_s)
            level = new_level
        if packed:
            from ..ops.packed import unpack_host

            dist, parent = unpack_host(
                np.asarray(jax.device_get(carry["pk"]))
            )
        else:
            dist = np.asarray(jax.device_get(carry["dist"]))
            parent = np.asarray(jax.device_get(carry["parent"]))
        return carry, dist, parent, int(level), bool(changed)

    packed = resolve_packed(packed_parent_fits(srg.num_vertices))
    carry, dist, parent, level, changed = run_flavor(packed)
    if packed and packed_truncated(changed, level, max_levels):
        ckpt.clear()
        packed = False
        carry, dist, parent, level, changed = run_flavor(packed)
    dist, parent = _relay_map_back(srg, dist, parent, source, "mxu")
    result = BfsResult(dist=dist, parent=parent, num_levels=level)
    ckpt.clear()
    if not telemetry:
        return result
    cap = min(PACKED_MAX_LEVELS, max_levels) if packed else max_levels
    curve = _grid_curve(
        (carry["occ"], carry["dirs"], carry["xbc"], carry["xac"],
         carry["xbr"], carry["xar"]),
        dir_cfg=dir_cfg, ex_cfg=ex_cfg, kw=int(own_dev.shape[1]),
        nw=block // 32, r=r, c=c, cap=cap, num_levels=result.num_levels,
    )
    return result, curve
