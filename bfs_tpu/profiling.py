"""Per-phase on-chip superstep ledger: attribute the non-mask residual.

Round 5 left the headline at ~47% of the repo's own mask-stream roofline
with a ~6.8 ms/superstep residual that no capture could attribute — the
superstep profile times WHOLE supersteps only (VERDICT r5 weak #5, task
#4).  This module decomposes one dense relay superstep into its five
phases and times each as an ISOLATED K-loop jit over the engine's real
device operands, so the residual is measured, not guessed:

    vperm         frontier words through the small Beneš network
    broadcast     vperm output words -> L2 slot words (class replication)
    net_apply     L2 -> L1 through the big Beneš network (the mask stream)
    rowmin        masked per-class row-min tournament over L1 slots
    state_update  candidate merge into the dist/parent carry + frontier
                  repack — timed in BOTH layouts (packed fused-word vs
                  unpacked int32 pair) with analytic byte accounting, the
                  before/after evidence for the packed-state tentpole

plus the full dense superstep for cross-checking (``sum_of_phases`` vs
``full_superstep``).  Every K-loop body feeds its output back into its
input (xor) so XLA cannot hoist the work out of the loop, and the K / 2K
timing difference cancels dispatch + sync overhead — the same
methodology as the applier probe (models/bfs.py).

The ledger is CPU-runnable (tests and ``python -m bfs_tpu.profiling``
run it on a small R-MAT without any TPU), ships in the bench headline as
``details.superstep_phases``, and backs tools/profile_superstep.py.

Analytic bytes are the MINIMUM HBM traffic of each phase (operands read
once + outputs written once); a measured phase time far above
``bytes / available_bandwidth`` marks compute- or layout-bound work.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "superstep_phase_ledger",
    "state_update_bytes",
    "probe_phase_kernels",
]


def state_update_bytes(vr: int, packed: bool) -> dict:
    """Analytic per-superstep HBM bytes of the state-update phase.

    The dist/parent carry term — the tentpole's target — is 8 bytes/vertex
    (one uint32 read + one written) packed vs 16 (two int32s each way)
    unpacked: exactly halved.  The candidate read and frontier-word write
    are layout-independent."""
    word = 4 * vr if packed else 8 * vr
    return {
        "dist_parent_read": word,
        "dist_parent_written": word,
        "candidate_read": 4 * vr,
        "frontier_words_written": vr // 8,
        "total": 2 * word + 4 * vr + vr // 8,
    }


def _compile(fn, args, compiler_options):
    from .models.bfs import compile_exe_cached

    opts = compiler_options if jax.default_backend() == "tpu" else None
    return compile_exe_cached(jax.jit(fn).lower(jnp.int32(1), *args), opts)


def _sync(result):
    leaf = jax.tree_util.tree_leaves(result)[0]
    return int(np.asarray(jax.device_get(leaf.ravel()[:1]))[0])


def _measure(fn, args, loops: int, repeats: int, compiler_options) -> float:
    """Seconds per iteration of ``fn(k, *args)``'s K-loop: compile, warm
    BOTH loop counts, then (min-of-repeats at 2K) - (min-of-repeats at K)
    over K.  The min per count rejects one-off contamination (first-call
    cache effects, a tenant burst) that a paired-sample difference would
    fold straight into the result."""
    compiled = _compile(fn, args, compiler_options)

    def timed(k):
        t0 = time.perf_counter()
        _sync(compiled(jnp.int32(k), *args))
        return time.perf_counter() - t0

    timed(loops)
    timed(2 * loops)  # warm both counts
    r = max(repeats, 2)
    t1 = min(timed(loops) for _ in range(r))
    t2 = min(timed(2 * loops) for _ in range(r))
    return max(t2 - t1, 1e-9) / loops


def superstep_phase_ledger(eng, *, loops: int = 4, repeats: int = 2) -> dict:
    """Measure the per-phase superstep ledger on a RelayEngine's own
    device operands.  Returns a JSON-ready dict (the bench ships it as
    ``details.superstep_phases``)."""
    from .ops import relay as R

    rg = eng.relay_graph
    static = eng._static
    (vr, vperm_size, vperm_table, out_classes, out_space, net_table,
     net_size, in_classes) = static
    vperm_m, net_m, valid = eng._tensors
    opts = eng._COMPILER_OPTIONS
    vp_pallas = isinstance(vperm_m, tuple)
    net_pallas = isinstance(net_m, tuple)
    if vp_pallas or net_pallas:
        from .ops import relay_pallas as RP

        vp_static = RP.pass_static(vperm_table, vperm_size) if vp_pallas else None
        net_static = RP.pass_static(net_table, net_size) if net_pallas else None

    def mb(fn, args):
        return _measure(fn, args, loops, repeats, opts)

    phases: dict = {}

    # ---- vperm ------------------------------------------------------------
    def k_vperm(k, x, *m):
        def body(i, x):
            if vp_pallas:
                y = RP.apply_benes_fused(x, m, vp_static, vperm_size)
            else:
                y = R.apply_benes_std(x, m[0], vperm_table, vperm_size)
            return y ^ (x & jnp.uint32(1))

        return jax.lax.fori_loop(0, k, body, x)

    x_vp = jnp.zeros(vperm_size // 32, jnp.uint32).at[0].set(1)
    vp_args = (x_vp, *vperm_m) if vp_pallas else (x_vp, vperm_m)
    vperm_mask_bytes = int(rg.vperm_masks.nbytes)
    phases["vperm"] = {
        "seconds": mb(k_vperm, vp_args),
        "mask_bytes": vperm_mask_bytes,
        "word_bytes_rw": vperm_size // 8,
    }

    # ---- broadcast --------------------------------------------------------
    def k_bcast(k, y):
        # Feed only the overlapping prefix back: tiny layouts can have
        # net_size < vperm_size, where a full-width slice would overrun.
        w = min(y.shape[0], net_size // 32)

        def body(i, c):
            l2 = R.broadcast_l2(y ^ c, out_classes, net_size, out_space)
            bit = jax.lax.slice_in_dim(l2, 0, w) & jnp.uint32(1)
            pad = jnp.zeros(y.shape[0] - w, jnp.uint32)
            return c ^ jnp.concatenate([bit, pad])

        return jax.lax.fori_loop(0, k, body, jnp.zeros_like(y))

    phases["broadcast"] = {
        "seconds": mb(k_bcast, (x_vp,)),
        "word_bytes_rw": (vperm_size + net_size) // 8,
    }

    # ---- net apply (the mask stream) --------------------------------------
    def k_net(k, x, *m):
        def body(i, x):
            if net_pallas:
                y = RP.apply_benes_fused(x, m, net_static, net_size)
            else:
                y = R.apply_benes_std(x, m[0], net_table, net_size)
            return y ^ (x & jnp.uint32(1))

        return jax.lax.fori_loop(0, k, body, x)

    x_net = jnp.zeros(net_size // 32, jnp.uint32)
    net_args = (x_net, *net_m) if net_pallas else (x_net, net_m)
    net_mask_bytes = int(rg.net_masks.nbytes)
    phases["net_apply"] = {
        "seconds": mb(k_net, net_args),
        "mask_bytes": net_mask_bytes,
        "word_bytes_rw": net_size // 8,
    }

    # ---- masked row-min ----------------------------------------------------
    # Packed layouts measure BOTH implementations (ISSUE 7 tentpole b):
    # the XLA word tournament and the fused Pallas kernel — compiled on
    # TPU backends, interpret-mode elsewhere (a real if slow measurement,
    # so the verdict is always a comparison).  ``seconds`` reports the
    # arm the ENGINE actually selected (phase_selection), keeping the
    # before/after ledger comparable with what timed repeats ran.
    packed = bool(getattr(eng, "packed", False))
    sel = getattr(eng, "phase_selection", None) or {
        "rowmin": "xla", "state_update": "xla", "basis": {},
    }
    from .ops.relay_pallas import pallas_interpret

    interp = pallas_interpret()

    def k_rowmin_arm(use_pallas_arm):
        def k_rowmin(k, l1, vw):
            def body(i, c):
                lx = l1 ^ jax.lax.slice_in_dim(c, 0, l1.shape[0])
                if packed:
                    if use_pallas_arm:
                        from .ops import relay_pallas as RP

                        cand = RP.rowmin_ranks_pallas(
                            lx, vw, in_classes, vr, interpret=interp
                        )
                    else:
                        cand = R.rowmin_ranks(lx, vw, in_classes, vr)
                    bit = cand & jnp.uint32(1)
                else:
                    cand = R.rowmin_candidates(lx, vw, in_classes, vr)
                    bit = cand.astype(jnp.uint32) & jnp.uint32(1)
                w = max(l1.shape[0], vr)
                pad = jnp.zeros(w - vr, jnp.uint32)
                return c ^ jnp.concatenate([bit, pad])

            size = max(net_size // 32, vr)
            return jax.lax.fori_loop(0, k, body, jnp.zeros(size, jnp.uint32))

        return k_rowmin

    def _effective(arms: dict, wanted: str, basis: str):
        """(selected, basis, seconds) — if the engine's wanted arm has no
        measurement here (the pallas arm errored), the ledger must SAY
        the fallback happened, never attribute the other arm's seconds
        to the wanted one."""
        if wanted in arms:
            return wanted, basis, arms[wanted]
        return (
            "xla",
            f"fallback: {wanted} arm unmeasured "
            f"({arms.get('pallas_error', 'missing')})",
            arms["xla"],
        )

    rowmin_arms = {"xla": mb(k_rowmin_arm(False), (x_net, valid))}
    if packed:
        try:
            rowmin_arms["pallas"] = mb(k_rowmin_arm(True), (x_net, valid))
        except Exception as exc:
            rowmin_arms["pallas_error"] = repr(exc)
    rm_sel, rm_basis, rm_seconds = _effective(
        rowmin_arms,
        sel["rowmin"] if packed else "xla",
        sel.get("basis", {}).get("rowmin", "unpacked carry (no fused arm)"),
    )
    phases["rowmin"] = {
        "seconds": rm_seconds,
        "selected": rm_sel,
        "selection_basis": rm_basis,
        "arms": rowmin_arms,
        "interpret_arm": interp,
        "flavor": "ranks (packed)" if packed else "slots (unpacked)",
        "word_bytes_read": 2 * (net_size // 8),
        "candidate_bytes_written": 4 * vr,
    }

    # ---- state update: BOTH layouts (the tentpole's before/after) ----------
    def k_apply_packed(k, pk, fw, cand):
        st0 = R.PackedRelayState(pk, fw, jnp.int32(0), jnp.bool_(True))

        def body(i, st):
            s2 = R.apply_relay_candidates_packed(
                st, cand ^ (st.packed & jnp.uint32(1))
            )
            return R.PackedRelayState(
                s2.packed, s2.fwords, jnp.int32(0), s2.changed
            )

        return jax.lax.fori_loop(0, k, body, st0).packed

    def k_apply_unpacked(k, dist, parent, fw, cand):
        st0 = R.RelayState(dist, parent, fw, jnp.int32(0), jnp.bool_(True))

        def body(i, st):
            s2 = R.apply_relay_candidates(st, cand ^ (st.dist & 1))
            return R.RelayState(
                s2.dist, s2.parent, s2.fwords, jnp.int32(0), s2.changed
            )

        return jax.lax.fori_loop(0, k, body, st0).dist

    from .ops.packed import PACKED_SENTINEL

    fw0 = jnp.zeros(vr // 32, jnp.uint32)
    pk0 = jnp.full(vr, PACKED_SENTINEL, jnp.uint32)
    cand_r = jnp.full(vr, PACKED_SENTINEL, jnp.uint32).at[:64].set(
        jnp.arange(64, dtype=jnp.uint32)
    )
    d0 = jnp.full(vr, np.int32(2**31 - 1), jnp.int32)
    p0 = jnp.full(vr, -1, jnp.int32)
    cand_s = jnp.full(vr, np.int32(2**31 - 1), jnp.int32).at[:64].set(
        jnp.arange(64, dtype=jnp.int32)
    )
    def k_apply_packed_pallas(k, pk, fw, cand):
        from .ops import relay_pallas as RP

        st0 = R.PackedRelayState(pk, fw, jnp.int32(0), jnp.bool_(True))

        def body(i, st):
            s2 = RP.apply_relay_candidates_packed_pallas(
                st, cand ^ (st.packed & jnp.uint32(1)), interpret=interp
            )
            return R.PackedRelayState(
                s2.packed, s2.fwords, jnp.int32(0), s2.changed
            )

        return jax.lax.fori_loop(0, k, body, st0).packed

    t_packed = mb(k_apply_packed, (pk0, fw0, cand_r))
    t_unpacked = mb(k_apply_unpacked, (d0, p0, fw0, cand_s))
    update_arms = {"xla": t_packed}
    if packed:
        try:
            update_arms["pallas"] = mb(
                k_apply_packed_pallas, (pk0, fw0, cand_r)
            )
        except Exception as exc:
            update_arms["pallas_error"] = repr(exc)
    up_sel, up_basis, up_seconds = _effective(
        update_arms,
        sel["state_update"] if packed else "xla",
        sel.get("basis", {}).get(
            "state_update", "unpacked carry (no fused arm)"
        ),
    )
    phases["state_update"] = {
        "seconds": up_seconds if packed else t_unpacked,
        "selected": up_sel,
        "selection_basis": up_basis,
        "arms": update_arms,
        "interpret_arm": interp,
        "packed": {
            "seconds": t_packed, "bytes": state_update_bytes(vr, True),
        },
        "unpacked": {
            "seconds": t_unpacked, "bytes": state_update_bytes(vr, False),
        },
        "dist_parent_bytes_ratio": (
            state_update_bytes(vr, False)["dist_parent_written"]
            / state_update_bytes(vr, True)["dist_parent_written"]
        ),
    }

    # ---- expansion arms (ISSUE 15) -----------------------------------------
    # Present whenever the engine carries a tile layout: the gather
    # (Beneš) dense superstep vs the mxu tiled masked matmul, measured on
    # a pinned fully-dense frontier (the regime the direction optimizer
    # hands to the pull/expansion body).  ``seconds`` reports the arm the
    # engine actually runs, keeping the ledger comparable with the timed
    # repeats (the _effective contract above).
    if getattr(eng, "adj_tiles", None) is not None:
        try:
            exp = _expansion_arms(eng, mb)
        except Exception as exc:
            exp = {"probe_error": repr(exc), "arms": {}}
        eng_arm = getattr(eng, "expansion", "gather")
        if eng_arm in exp.get("arms", {}):
            exp["seconds"] = exp["arms"][eng_arm]
        exp["selected"] = eng_arm
        exp["selection_basis"] = getattr(eng, "expansion_basis", None)
        exp["interpret_arm"] = interp
        phases["expansion"] = exp

    # ---- full dense superstep (cross-check) --------------------------------
    from .models.bfs import _superstep_fn

    superstep = _superstep_fn(
        static, eng._use_pallas(), packed,
        eng._phase_sel() if hasattr(eng, "_phase_sel") else None,
    )
    flat_masks = []
    for m in (vperm_m, net_m):
        flat_masks.extend(m if isinstance(m, tuple) else (m,))
    n_vp = len(vperm_m) if isinstance(vperm_m, tuple) else 1

    def k_full(k, pk_or_d, maybe_p, fw, *ms):
        vm = ms[:n_vp] if isinstance(vperm_m, tuple) else ms[0]
        nm = ms[n_vp:-1] if isinstance(net_m, tuple) else ms[1]
        vw = ms[-1]
        if packed:
            st0 = R.PackedRelayState(
                pk_or_d, fw, jnp.int32(0), jnp.bool_(True)
            )

            def body(i, st):
                s2 = superstep(st, vm, nm, vw)
                return R.PackedRelayState(
                    s2.packed, s2.fwords, st.level, st.changed
                )

        else:
            st0 = R.RelayState(
                pk_or_d, maybe_p, fw, jnp.int32(0), jnp.bool_(True)
            )

            def body(i, st):
                s2 = superstep(st, vm, nm, vw)
                return R.RelayState(
                    s2.dist, s2.parent, s2.fwords, st.level, st.changed
                )

        return jax.lax.fori_loop(0, k, body, st0)

    fw_src = jnp.zeros(vr // 32, jnp.uint32).at[0].set(1)
    full_args = (pk0 if packed else d0, p0, fw_src, *flat_masks, valid)
    phases["full_superstep"] = {"seconds": mb(k_full, full_args)}

    # ---- full superstep + device telemetry (the OBS overhead arm) ----------
    # Same K-loop with the obs/telemetry level accumulator folded into the
    # carry (one popcount-sum + one 4-byte scatter-add per superstep): the
    # measured cost of carrying the level curve, shipped in every capture
    # next to the curve itself so "telemetry changes timed medians by <2%"
    # is a number, not a promise.
    from .obs import telemetry as T

    def k_full_tel(k, pk_or_d, maybe_p, fw, *ms):
        vm = ms[:n_vp] if isinstance(vperm_m, tuple) else ms[0]
        nm = ms[n_vp:-1] if isinstance(net_m, tuple) else ms[1]
        vw = ms[-1]
        acc0 = T.init_level_acc()
        if packed:
            st0 = R.PackedRelayState(
                pk_or_d, fw, jnp.int32(0), jnp.bool_(True)
            )

            def body(i, c):
                st, acc = c
                s2 = superstep(st, vm, nm, vw)
                acc = T.record_frontier_words(acc, s2.fwords, s2.level)
                return (
                    R.PackedRelayState(
                        s2.packed, s2.fwords, st.level, st.changed
                    ),
                    acc,
                )

        else:
            st0 = R.RelayState(
                pk_or_d, maybe_p, fw, jnp.int32(0), jnp.bool_(True)
            )

            def body(i, c):
                st, acc = c
                s2 = superstep(st, vm, nm, vw)
                acc = T.record_frontier_words(acc, s2.fwords, s2.level)
                return (
                    R.RelayState(
                        s2.dist, s2.parent, s2.fwords, st.level, st.changed
                    ),
                    acc,
                )

        return jax.lax.fori_loop(0, k, body, (st0, acc0))

    t_tel = mb(k_full_tel, full_args)
    phases["full_superstep_telemetry"] = {"seconds": t_tel}

    accounted = sum(
        phases[p]["seconds"]
        for p in ("vperm", "broadcast", "net_apply", "rowmin", "state_update")
    )
    return {
        "packed_state": packed,
        "applier": getattr(eng, "applier", "xla"),
        "loops": loops,
        "repeats": repeats,
        "device": str(jax.devices()[0]),
        "phases": phases,
        "sum_of_phases_seconds": accounted,
        "full_superstep_seconds": phases["full_superstep"]["seconds"],
        "telemetry_overhead_ratio": (
            phases["full_superstep_telemetry"]["seconds"]
            / max(phases["full_superstep"]["seconds"], 1e-12)
        ),
        "mask_bytes_total": vperm_mask_bytes + net_mask_bytes,
        "note": (
            "phase-isolated K-loop jits on the engine's real operands; "
            "K/2K timing difference cancels dispatch+sync; state_update "
            "reports BOTH layouts — dist/parent bytes halved packed"
        ),
    }


def _expansion_arms(eng, mb) -> dict:
    """K-loop both EXPANSION arms — the gather (Beneš relay) dense
    superstep vs the mxu tiled masked matmul — on the engine's real
    operands with a PINNED fully-dense frontier (the regime the arm
    targets; an evolving state would empty the frontier after one
    superstep and time the mxu early-out instead of the expand).  The
    packed-word feedback keeps XLA from hoisting either body."""
    import jax

    from .models.bfs import _superstep_fn
    from .ops import relay as R
    from .ops import relay_mxu as RM
    from .ops.packed import PACKED_SENTINEL

    packed = bool(getattr(eng, "packed", False))
    static = eng._static
    vr = static[0]
    superstep = _superstep_fn(
        static, eng._use_pallas(), packed,
        eng._phase_sel() if hasattr(eng, "_phase_sel") else None,
    )
    vperm_m, net_m, valid = eng._tensors
    geo = RM.mxu_static(eng.adj_tiles)
    use_kernel = RM.resolve_mxu_kernel() == "pallas"
    tile_ops = eng._mxu_ops()
    mxu_step = RM.mxu_superstep_packed if packed else RM.mxu_superstep

    nw = vr // 32
    fw_dense = jnp.full(nw, 0xFFFFFFFF, jnp.uint32)
    pk0 = jnp.full(vr, PACKED_SENTINEL, jnp.uint32)
    d0 = jnp.full(vr, np.int32(2**31 - 1), jnp.int32)
    p0 = jnp.full(vr, -1, jnp.int32)

    def feedback(st):
        word = st.packed if packed else st.dist.astype(jnp.uint32)
        return fw_dense ^ (jax.lax.slice_in_dim(word, 0, nw) & 1)

    def mk(st_words, fw):
        if packed:
            return R.PackedRelayState(
                st_words, fw, jnp.int32(0), jnp.bool_(True)
            )
        return R.RelayState(
            st_words, p0, fw, jnp.int32(0), jnp.bool_(True)
        )

    def k_arm(run_body):
        # Operands arrive as ARGS (pytrees), never closed over — a
        # closed-over mask/tile array bakes into the program as a
        # constant (GBs at bench scale; the RelayEngine._tensors rule).
        def fn(k, st_words, fw, *ops):
            st0 = mk(st_words, fw)

            def body(i, st):
                s2 = run_body(
                    mk(st.packed if packed else st.dist, feedback(st)),
                    *ops,
                )
                if packed:
                    return R.PackedRelayState(
                        s2.packed, st.fwords, jnp.int32(0), st.changed
                    )
                return R.RelayState(
                    s2.dist, p0, st.fwords, jnp.int32(0), st.changed
                )

            out = jax.lax.fori_loop(0, k, body, st0)
            return out.packed if packed else out.dist

        return fn

    def gather_body(st, vm, nm, vw):
        return superstep(st, vm, nm, vw)

    def mxu_body(st, ops):
        return mxu_step(st, ops, geo, use_kernel)

    init = pk0 if packed else d0
    arms = {
        "gather": mb(
            k_arm(gather_body), (init, fw_dense, vperm_m, net_m, valid)
        )
    }
    try:
        arms["mxu"] = mb(k_arm(mxu_body), (init, fw_dense, tile_ops))
    except Exception as exc:
        arms["mxu_error"] = repr(exc)
    from .ops.relay_pallas import pallas_interpret

    interp = pallas_interpret()
    rec = {
        "arms": arms,
        "gather_seconds": arms["gather"],
        "tiles": int(eng.adj_tiles.nt),
        "mxu_kernel": "pallas" if use_kernel else "xla",
        "frontier": "pinned dense (all bits set)",
    }
    if "mxu" in arms:
        rec["mxu_seconds"] = arms["mxu"]
        rec["selected"] = "mxu" if arms["mxu"] <= arms["gather"] else "gather"
        rec["selection_basis"] = (
            "measured (interpret arm)" if interp else "measured"
        )
    else:
        rec["selected"] = "gather"
        rec["selection_basis"] = "measured (mxu arm failed)"
    return rec


def probe_phase_kernels(eng, *, loops: int = 4, repeats: int = 2) -> dict:
    """Measure the pallas-vs-XLA arms of the packed row-min and packed
    state-update on a RelayEngine's real shapes and pick per phase — the
    engine-init selector (RelayEngine._resolve_phase_selection) on TPU
    backends, where the fused kernels compile for real.  K-loop / 2K-loop
    difference timing, same methodology as the applier probe and the
    ledger; ``selection_basis`` is always ``"measured"`` — a failed
    pallas arm records its error and selects xla, still a comparison
    with the failure on record, never a silent default.

    Runs anywhere (interpret-mode kernels off-TPU — the ledger uses the
    same arms to ship the verdict in every capture), but only the TPU
    engine init consults it for production selection: interpret arms
    measure real work at interpreter speed and must not steer the timed
    repeats."""
    from .ops import relay as R
    from .ops import relay_pallas as RP
    from .ops.packed import PACKED_SENTINEL

    rg = eng.relay_graph
    (vr, _vs, _vt, _oc, _os, _nt, net_size, in_classes) = eng._static
    valid = eng._tensors[2]
    opts = eng._COMPILER_OPTIONS
    interp = RP.pallas_interpret()
    x_net = jnp.zeros(net_size // 32, jnp.uint32)
    fw0 = jnp.zeros(vr // 32, jnp.uint32)
    pk0 = jnp.full(vr, PACKED_SENTINEL, jnp.uint32)
    cand_r = jnp.full(vr, PACKED_SENTINEL, jnp.uint32).at[:64].set(
        jnp.arange(64, dtype=jnp.uint32)
    )

    def mb(fn, args):
        return _measure(fn, args, loops, repeats, opts)

    def k_rowmin(use_pallas_arm):
        def fn(k, l1, vw):
            def body(i, c):
                lx = l1 ^ jax.lax.slice_in_dim(c, 0, l1.shape[0])
                if use_pallas_arm:
                    cand = RP.rowmin_ranks_pallas(
                        lx, vw, in_classes, vr, interpret=interp
                    )
                else:
                    cand = R.rowmin_ranks(lx, vw, in_classes, vr)
                bit = cand & jnp.uint32(1)
                w = max(l1.shape[0], vr)
                return c ^ jnp.concatenate(
                    [bit, jnp.zeros(w - vr, jnp.uint32)]
                )

            size = max(net_size // 32, vr)
            return jax.lax.fori_loop(
                0, k, body, jnp.zeros(size, jnp.uint32)
            )

        return fn

    def k_update(use_pallas_arm):
        def fn(k, pk, fw, cand):
            st0 = R.PackedRelayState(pk, fw, jnp.int32(0), jnp.bool_(True))

            def body(i, st):
                c = cand ^ (st.packed & jnp.uint32(1))
                if use_pallas_arm:
                    s2 = RP.apply_relay_candidates_packed_pallas(
                        st, c, interpret=interp
                    )
                else:
                    s2 = R.apply_relay_candidates_packed(st, c)
                return R.PackedRelayState(
                    s2.packed, s2.fwords, jnp.int32(0), s2.changed
                )

            return jax.lax.fori_loop(0, k, body, st0).packed

        return fn

    out = {"interpret": interp, "device": str(jax.devices()[0])}
    for phase, maker, args in (
        ("rowmin", k_rowmin, (x_net, valid)),
        ("state_update", k_update, (pk0, fw0, cand_r)),
    ):
        t_xla = mb(maker(False), args)
        rec = {"xla_seconds": t_xla}
        try:
            t_pal = mb(maker(True), args)
            rec["pallas_seconds"] = t_pal
            rec["selected"] = "pallas" if t_pal <= t_xla else "xla"
            rec["selection_basis"] = (
                "measured (interpret arm)" if interp else "measured"
            )
        except Exception as exc:
            rec["pallas_error"] = repr(exc)
            rec["selected"] = "xla"
            rec["selection_basis"] = "measured (pallas arm failed)"
        out[phase] = rec
    # The EXPANSION arm (ISSUE 15): measured whenever the engine carries
    # a tile layout (auto-probe built it, or the arm was forced) — the
    # gather-vs-mxu verdict rides the same memoized probe document.
    if getattr(eng, "adj_tiles", None) is not None:
        try:
            out["expansion"] = _expansion_arms(eng, mb)
        except Exception as exc:
            # No "selected" entry: the engine falls back to gather with
            # the failure on record, never a silent default.
            out["expansion"] = {"probe_error": repr(exc)}
    return out


def main() -> None:
    """CPU-runnable microbench: build a small R-MAT, run the ledger, print
    JSON (the standalone evidence path; tools/profile_superstep.py is the
    TPU-scale twin)."""
    import argparse
    import json

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--scale", type=int, default=12)
    parser.add_argument("--edge-factor", type=int, default=8)
    parser.add_argument("--loops", type=int, default=4)
    parser.add_argument("--repeats", type=int, default=2)
    args = parser.parse_args()

    from .graph.generators import rmat_graph
    from .models.bfs import RelayEngine

    g = rmat_graph(args.scale, args.edge_factor, seed=7)
    eng = RelayEngine(g, sparse_hybrid=False)
    ledger = superstep_phase_ledger(
        eng, loops=args.loops, repeats=args.repeats
    )
    print(json.dumps(ledger, indent=2))


if __name__ == "__main__":
    main()
