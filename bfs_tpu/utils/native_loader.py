"""Shared loader for the repo's native C++ libraries (native/*.cpp).

One place for the build-on-demand + ctypes-load + failure-latch logic used by
the oracle (:mod:`bfs_tpu.oracle.native`) and the data loader
(:mod:`bfs_tpu.graph.native_gen`).  pybind11 is not in the image, so the
native layer is plain C ABI + ctypes.

Loading never raises: any compile/IO failure latches the library as
unavailable and callers fall back to their NumPy/Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from collections.abc import Callable


class NativeLib:
    """Lazily built, lazily loaded shared library.

    ``register`` is called once with the loaded CDLL to set
    restype/argtypes; if it raises, the library is latched unavailable.
    """

    def __init__(self, src: str, so: str, register: Callable[[ctypes.CDLL], None]):
        self._src = src
        self._so = so
        self._register = register
        self._lock = threading.Lock()
        self._lib: ctypes.CDLL | None = None
        self._failed = False

    def _needs_build(self) -> bool:
        if not os.path.exists(self._so):
            return True
        try:
            return os.path.getmtime(self._so) < os.path.getmtime(self._src)
        except OSError:
            # Source missing (installed package without native/): use the
            # prebuilt .so as-is.
            return False

    def _build(self) -> bool:
        if not os.path.exists(self._src):
            return False
        os.makedirs(os.path.dirname(self._so), exist_ok=True)
        # Compile to a per-process temp path and publish atomically: the
        # in-process lock does not cover concurrent Python processes (pytest
        # alongside bench.py), and CDLL-loading a half-written .so would
        # latch the library unavailable.
        tmp = f"{self._so}.tmp.{os.getpid()}"
        cmd = [
            os.environ.get("CXX", "g++"),
            "-O3", "-march=native", "-std=c++17", "-fPIC", "-shared",
            "-pthread", "-o", tmp, self._src,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, self._so)
            return True
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False

    def load(self) -> ctypes.CDLL | None:
        with self._lock:
            if self._lib is not None or self._failed:
                return self._lib
            if self._needs_build() and not self._build():
                self._failed = True
                return None
            try:
                lib = ctypes.CDLL(self._so)
                self._register(lib)
            except Exception:
                self._failed = True
                return None
            self._lib = lib
            return self._lib

    def available(self) -> bool:
        return self.load() is not None
