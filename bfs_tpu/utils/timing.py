"""Wall-clock stopwatch, parity with Guava ``Stopwatch`` as used by the
reference for per-superstep timing (BfsSpark.java:59,63,111-112) and oracle
timing (SequentialTest.java:25-27).

The reference's methodology — time only the map/reduce stage, accumulate
across supersteps, exclude startup and graph construction (paper §1.5) — is
reproduced by the runners via ``start``/``stop`` around each superstep.
JAX note: callers must block on device results (``block_until_ready``)
before ``stop`` or the async dispatch makes timings meaningless.
"""

from __future__ import annotations

import time


class Stopwatch:
    """start/stop accumulate; ``elapsed_s`` is total accumulated seconds."""

    def __init__(self):
        self._acc = 0.0
        self._started_at: float | None = None

    @classmethod
    def create_started(cls) -> "Stopwatch":
        sw = cls()
        sw.start()
        return sw

    def start(self) -> "Stopwatch":
        if self._started_at is not None:
            raise RuntimeError("stopwatch already running")
        self._started_at = time.perf_counter()
        return self

    def stop(self) -> "Stopwatch":
        if self._started_at is None:
            raise RuntimeError("stopwatch not running")
        self._acc += time.perf_counter() - self._started_at
        self._started_at = None
        return self

    def reset(self) -> "Stopwatch":
        self._acc = 0.0
        self._started_at = None
        return self

    @property
    def running(self) -> bool:
        return self._started_at is not None

    @property
    def elapsed_s(self) -> float:
        extra = time.perf_counter() - self._started_at if self.running else 0.0
        return self._acc + extra

    def __str__(self) -> str:  # human form like Guava's "342.8 ms"
        s = self.elapsed_s
        if s >= 1.0:
            return f"{s:.3f} s"
        if s >= 1e-3:
            return f"{s * 1e3:.3f} ms"
        return f"{s * 1e6:.1f} us"
