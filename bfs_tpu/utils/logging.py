"""Logging setup, parity with the reference's log4j2 layer.

The reference logs through log4j2 with a pattern carrying class/method/line
(src/main/resources/log4j2.xml), project loggers at TRACE.  Python's stdlib
logging gives the same capability; :func:`configure` installs an equivalent
console format and :func:`get_logger` mirrors the per-class static logger
idiom (BfsSpark.java:33 etc.).
"""

from __future__ import annotations

import logging
import os

from .. import knobs

_FORMAT = (
    "%(asctime)s %(levelname)-5s [%(name)s.%(funcName)s:%(lineno)d] %(message)s"
)
_configured = False


def configure(level: int | str | None = None) -> None:
    global _configured
    if _configured:
        return
    if level is None:
        level = knobs.get("BFS_TPU_LOG")
    logging.basicConfig(level=level, format=_FORMAT)
    _configured = True


def get_logger(name: str) -> logging.Logger:
    configure()
    return logging.getLogger(name)
