"""Structured per-superstep metrics and TEPS accounting.

The reference's observability is a per-iteration elapsed-time log line
(``Elapsed time [i] ==> ...``, BfsSpark.java:112) plus the per-superstep
state files themselves.  Here each superstep records frontier size, newly
settled vertices, and wall time; the run-level summary reports traversed
edges per second (TEPS, Graph500 convention: directed edge count / total BFS
time), the metric named in BASELINE.json.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, asdict


@dataclass
class SuperstepRecord:
    level: int
    frontier_size: int
    seconds: float


@dataclass
class RunMetrics:
    """Accumulated metrics for one BFS run."""

    num_vertices: int = 0
    num_edges: int = 0  # directed
    supersteps: list[SuperstepRecord] = field(default_factory=list)

    def record(self, level: int, frontier_size: int, seconds: float) -> None:
        self.supersteps.append(SuperstepRecord(level, frontier_size, seconds))

    @property
    def total_seconds(self) -> float:
        return sum(r.seconds for r in self.supersteps)

    @property
    def num_levels(self) -> int:
        return len(self.supersteps)

    @property
    def vertices_settled(self) -> int:
        return sum(r.frontier_size for r in self.supersteps)

    def teps(self, *, num_traversals: int = 1) -> float:
        """Traversed edges / second; ``num_traversals`` scales for batched
        multi-source runs (each source traverses the edge set once)."""
        t = self.total_seconds
        return (self.num_edges * num_traversals / t) if t > 0 else float("inf")

    def to_json(self) -> str:
        d = asdict(self)
        d["total_seconds"] = self.total_seconds
        d["teps"] = self.teps()
        return json.dumps(d)

    def log_lines(self):
        """Per-iteration lines in the reference's log style
        (BfsSpark.java:112)."""
        for r in self.supersteps:
            yield (
                f"Elapsed time [{r.level}] ==> {r.seconds * 1e3:.3f} ms "
                f"(frontier {r.frontier_size})"
            )
