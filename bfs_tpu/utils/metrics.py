"""Structured per-superstep metrics and TEPS accounting.

The reference's observability is a per-iteration elapsed-time log line
(``Elapsed time [i] ==> ...``, BfsSpark.java:112) plus the per-superstep
state files themselves.  Here each superstep records frontier size, newly
settled vertices, and wall time; the run-level summary reports traversed
edges per second (TEPS, Graph500 convention: directed edge count / total BFS
time), the metric named in BASELINE.json.

The serving layer (``bfs_tpu.serve``) adds REQUEST-level metrics on top of
the run-level ones: every admitted query leaves a :class:`QueryRecord`
(queue wait, batch size it rode in, compile/result-cache hits, superstep
count, end-to-end latency) and :class:`ServeMetrics` aggregates them into
the throughput/latency report (p50/p99, queries/sec, cache hit rates).

The ARTIFACT caches (ISSUE 2: layout bundles, serialized executables) get
process-global hit/miss counters here — :func:`bump_artifact` /
:func:`artifact_report` — because their callers span layers (graph build,
engine init, serve registry, bench) that share no metrics object; every
report surface (bench details, serve report, cache_warm) includes them so
a cold-path regression shows up as a miss count, not a silent stall.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field, asdict

from ..analysis.runtime import make_lock

_artifact_lock = threading.Lock()
_artifact_counters: dict[str, int] = {}  # guarded-by: _artifact_lock


def bump_artifact(name: str, by: int = 1) -> None:
    """Count one artifact-cache event (e.g. ``layout_cache_hits``,
    ``exe_cache_misses``).  Thread-safe, process-global."""
    with _artifact_lock:
        _artifact_counters[name] = _artifact_counters.get(name, 0) + by


def artifact_report() -> dict:
    """Snapshot of the artifact-cache counters plus derived hit rates
    (``None`` when a cache saw no traffic this process)."""
    with _artifact_lock:
        out: dict = dict(_artifact_counters)
    for cache in ("layout_cache", "exe_cache"):
        h, m = out.get(f"{cache}_hits", 0), out.get(f"{cache}_misses", 0)
        out[f"{cache}_hit_rate"] = h / (h + m) if h + m else None
    return out


@dataclass
class SuperstepRecord:
    level: int
    frontier_size: int
    seconds: float


@dataclass
class RunMetrics:
    """Accumulated metrics for one BFS run."""

    num_vertices: int = 0
    num_edges: int = 0  # directed
    supersteps: list[SuperstepRecord] = field(default_factory=list)

    def record(self, level: int, frontier_size: int, seconds: float) -> None:
        self.supersteps.append(SuperstepRecord(level, frontier_size, seconds))

    @property
    def total_seconds(self) -> float:
        return sum(r.seconds for r in self.supersteps)

    @property
    def num_levels(self) -> int:
        return len(self.supersteps)

    @property
    def vertices_settled(self) -> int:
        return sum(r.frontier_size for r in self.supersteps)

    def teps(self, *, num_traversals: int = 1) -> float:
        """Traversed edges / second; ``num_traversals`` scales for batched
        multi-source runs (each source traverses the edge set once)."""
        t = self.total_seconds
        return (self.num_edges * num_traversals / t) if t > 0 else float("inf")

    def to_json(self) -> str:
        d = asdict(self)
        d["total_seconds"] = self.total_seconds
        d["teps"] = self.teps()
        return json.dumps(d)

    def log_lines(self):
        """Per-iteration lines in the reference's log style
        (BfsSpark.java:112)."""
        for r in self.supersteps:
            yield (
                f"Elapsed time [{r.level}] ==> {r.seconds * 1e3:.3f} ms "
                f"(frontier {r.frontier_size})"
            )


def percentile(values, q: float) -> float:
    """Linear-interpolation percentile (``q`` in [0, 100]) of a sequence;
    0.0 on an empty input.  Dependency-free so report paths never pull in
    numpy for a handful of scalars."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return 0.0
    if len(vals) == 1:
        return vals[0]
    pos = (len(vals) - 1) * (q / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(vals) - 1)
    frac = pos - lo
    return vals[lo] * (1.0 - frac) + vals[hi] * frac


@dataclass
class QueryRecord:
    """Per-request structured record, attached to every served reply.

    ``status`` is one of ``'ok'`` (device batch), ``'result_cache'`` (LRU
    hit, never queued), ``'oracle'`` (sequential degradation), ``'timeout'``
    or ``'error'``.  ``compile_hit`` is None for paths that never reach the
    executable cache (cache hits, oracle, failures before dispatch)."""

    graph: str = ""
    engine: str = ""
    status: str = "ok"
    epoch: int = 0  # graph epoch the answer was computed against (ISSUE 9)
    num_sources: int = 1
    batch_size: int = 0  # padded device batch the request rode in
    supersteps: int = 0
    queue_wait_s: float = 0.0  # admission -> batch formation
    service_s: float = 0.0  # device (or oracle) execution, batch-shared
    total_s: float = 0.0  # admission -> reply
    compile_hit: bool | None = None
    result_cache_hit: bool = False


class ServeMetrics:
    """Thread-safe aggregator for the serving layer.

    Counters are free-form (``bump('evictions')``) and exact for the
    process lifetime; query records feed the latency/batching statistics
    and are kept in a BOUNDED window (``max_records``, default 100k) so a
    server that "answers searches forever" cannot leak memory through its
    own observability — percentiles are therefore over the most recent
    window, which is what a serving dashboard wants anyway.  ``report()``
    returns a JSON-ready dict — the loadgen and ``run_serve`` print it
    verbatim."""

    def __init__(self, max_records: int = 100_000):
        from collections import deque

        self._lock = make_lock("metrics._lock")
        self.records: deque[QueryRecord] = deque(maxlen=max_records)  # guarded-by: _lock
        self.counters: dict[str, int] = {}  # guarded-by: _lock
        self._first_ts: float | None = None  # guarded-by: _lock
        self._last_ts: float | None = None  # guarded-by: _lock
        # One registry to find them (ISSUE 6): every ServeMetrics is
        # weakly visible in the process-global MetricsRegistry snapshot,
        # so loadgen/chaos/dashboards read ONE surface instead of
        # threading per-server objects around.
        from ..obs.registry import get_registry

        get_registry().register_serve(self)

    def bump(self, name: str, by: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + by

    def count(self, name: str) -> int:
        with self._lock:
            return self.counters.get(name, 0)

    def record_query(self, rec: QueryRecord, *, ts: float | None = None) -> None:
        with self._lock:
            self.records.append(rec)
            if ts is not None:
                if self._first_ts is None:
                    self._first_ts = ts
                self._last_ts = ts

    @staticmethod
    def _rate(counters: dict, hits: str, misses: str) -> float | None:
        h, m = counters.get(hits, 0), counters.get(misses, 0)
        return h / (h + m) if h + m else None

    def report(self) -> dict:
        with self._lock:
            records = list(self.records)
            counters = dict(self.counters)
            span = (
                (self._last_ts - self._first_ts)
                if self._first_ts is not None and self._last_ts is not None
                else 0.0
            )
        ok = [r for r in records if r.status in ("ok", "result_cache", "oracle")]
        lat = [r.total_s for r in ok]
        waits = [r.queue_wait_s for r in records if r.batch_size > 0]
        batches = [r.batch_size for r in records if r.batch_size > 0]
        out = {
            "queries": len(records),
            "served": len(ok),
            "timeouts": sum(r.status == "timeout" for r in records),
            "errors": sum(r.status == "error" for r in records),
            "latency_p50_ms": percentile(lat, 50) * 1e3,
            "latency_p99_ms": percentile(lat, 99) * 1e3,
            "latency_mean_ms": (sum(lat) / len(lat) * 1e3) if lat else 0.0,
            "queue_wait_p99_ms": percentile(waits, 99) * 1e3,
            "batch_size_mean": (sum(batches) / len(batches)) if batches else 0.0,
            "batch_size_max": max(batches, default=0),
            "queries_per_sec": (len(ok) / span) if span > 0 else 0.0,
            "counters": counters,
        }
        # Resilience counters, surfaced explicitly (not just inside the
        # free-form counter dict): a dashboard needs retries-vs-degradations
        # at a glance — rising device_retries with zero device_errors is a
        # flaky-but-recovering transport; rising device_errors means the
        # oracle is quietly serving what the device should.
        out["retries"] = {
            "device_retries": counters.get("device_retries", 0),
            "device_retry_successes": counters.get("device_retry_successes", 0),
            "device_errors": counters.get("device_errors", 0),
        }
        out["compile_hit_rate"] = self._rate(
            counters, "compile_hits", "compile_misses"
        )
        out["result_cache_hit_rate"] = self._rate(
            counters, "result_cache_hits", "result_cache_misses"
        )
        # Process-global artifact caches (layout bundles, executables): a
        # serving dashboard wants cold-path health next to the hot-path
        # latencies — a second process re-registering a graph should show
        # a layout_cache hit here, not a 434 s rebuild.
        out["artifact_caches"] = artifact_report()
        return out

    def to_json(self) -> str:
        return json.dumps(self.report(), indent=2, sort_keys=True)
