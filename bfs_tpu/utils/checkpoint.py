"""Superstep-granular checkpoint / resume.

The reference checkpoints implicitly: every superstep serialises complete
state to ``problemFile_{i}`` and the next iteration re-reads it
(BfsSpark.java:62,115-116) — a crashed run resumes from the last file.  Here
checkpointing is explicit and dual-format:

  * binary ``.npz`` of the loop carry (fast path, exact);
  * optional reference-wire-format text dump (``problemFile_i`` parity,
    human-inspectable, interchangeable with :func:`bfs_tpu.graph.vertex.parse_state`).

Resume rebuilds a :class:`~bfs_tpu.ops.relax.BfsState` and re-enters the
superstep loop — the carry IS the checkpoint (SURVEY.md §5).
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from ..ops.relax import BfsState


def save_checkpoint(path: str | os.PathLike, state: BfsState) -> None:
    np.savez(
        path,
        dist=np.asarray(state.dist),
        parent=np.asarray(state.parent),
        frontier=np.asarray(state.frontier),
        level=np.asarray(state.level),
        changed=np.asarray(state.changed),
    )


def load_checkpoint(path: str | os.PathLike) -> BfsState:
    with np.load(path) as z:
        return BfsState(
            dist=jnp.asarray(z["dist"]),
            parent=jnp.asarray(z["parent"]),
            frontier=jnp.asarray(z["frontier"]),
            level=jnp.asarray(z["level"]),
            changed=jnp.asarray(z["changed"]),
        )


def state_from_arrays(dist, parent, frontier, level: int) -> BfsState:
    """Build a resumable carry from host arrays sized [V] or [V+1]; pads the
    sentinel slot if missing (e.g. state parsed from a text dump)."""
    dist = np.asarray(dist, dtype=np.int32)
    parent = np.asarray(parent, dtype=np.int32)
    frontier = np.asarray(frontier, dtype=bool)
    from ..graph.csr import INF_DIST

    def pad(a, fill):
        return np.concatenate([a, np.asarray([fill], dtype=a.dtype)])

    if dist.ndim == 1:
        dist, parent, frontier = pad(dist, INF_DIST), pad(parent, -1), pad(frontier, False)
    return BfsState(
        dist=jnp.asarray(dist),
        parent=jnp.asarray(parent),
        frontier=jnp.asarray(frontier),
        level=jnp.int32(level),
        changed=jnp.bool_(bool(frontier.any())),
    )
