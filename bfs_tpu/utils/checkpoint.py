"""Superstep-granular checkpoint / resume.

The reference checkpoints implicitly: every superstep serialises complete
state to ``problemFile_{i}`` and the next iteration re-reads it
(BfsSpark.java:62,115-116) — a crashed run resumes from the last file.  Here
checkpointing is explicit and dual-format:

  * binary ``.npz`` of the loop carry (fast path, exact);
  * optional reference-wire-format text dump (``problemFile_i`` parity,
    human-inspectable, interchangeable with :func:`bfs_tpu.graph.vertex.parse_state`).

Resume rebuilds a :class:`~bfs_tpu.ops.relax.BfsState` and re-enters the
superstep loop — the carry IS the checkpoint (SURVEY.md §5).

Durability contract (resilience round): every ``.npz`` dump is written to
a same-directory temp file and renamed into place, so a kill mid-dump can
never leave a half-written file under the final name; and loads verify the
archive is complete (the zip end-record only exists once the whole file
was written), raising :class:`CheckpointError` on truncation instead of
poisoning a resume with garbage arrays.  The journal's sidecar arrays
(:mod:`bfs_tpu.resilience.journal`) ride the same two helpers.
"""

from __future__ import annotations

import os
import zipfile

import jax.numpy as jnp
import numpy as np

from ..ops.relax import BfsState


class CheckpointError(RuntimeError):
    """A checkpoint/sidecar file is truncated or corrupt.  The clean
    remedy is to delete it and resume from an earlier one (or from
    scratch) — loading it would silently poison the resumed state."""


def save_npz_atomic(path: str | os.PathLike, **arrays) -> str:
    """``np.savez`` with crash atomicity: write to ``<path>.tmp.<pid>`` in
    the same directory, fsync, then ``os.replace`` into place.  Returns
    the final path (``.npz`` appended if missing, matching np.savez)."""
    path = os.fspath(path)
    if not path.endswith(".npz"):
        path += ".npz"
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except FileNotFoundError:
            pass
        raise
    return path


def load_npz_strict(path: str | os.PathLike) -> dict[str, np.ndarray]:
    """Load an ``.npz`` as a plain dict, rejecting truncated/corrupt
    archives with :class:`CheckpointError`.  A missing file raises
    ``FileNotFoundError`` (a different condition: nothing to resume,
    rather than a damaged resume)."""
    path = os.fspath(path)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    try:
        with np.load(path, allow_pickle=False) as z:
            return {k: np.asarray(z[k]) for k in z.files}
    except (zipfile.BadZipFile, ValueError, KeyError, EOFError, OSError) as exc:
        raise CheckpointError(
            f"checkpoint {path!r} is truncated or corrupt ({exc!r}); "
            "delete it and resume from an earlier checkpoint"
        ) from exc


def save_checkpoint(path: str | os.PathLike, state: BfsState, **meta) -> str:
    """Atomic dump of the loop carry; returns the written path.

    ``meta`` scalars (source, engine, ...) are stored as ``meta_<k>``
    fields so resume can refuse a checkpoint that belongs to a different
    run configuration (see :func:`load_latest_checkpoint`)."""
    return save_npz_atomic(
        path,
        dist=np.asarray(state.dist),
        parent=np.asarray(state.parent),
        frontier=np.asarray(state.frontier),
        level=np.asarray(state.level),
        changed=np.asarray(state.changed),
        **{f"meta_{k}": np.asarray(v) for k, v in meta.items()},
    )


def _state_from_npz(z: dict, path: str) -> BfsState:
    """The BfsState carry from a loaded checkpoint dict (``meta_*``
    fields ignored); :class:`CheckpointError` on a missing field."""
    try:
        return BfsState(
            dist=jnp.asarray(z["dist"]),
            parent=jnp.asarray(z["parent"]),
            frontier=jnp.asarray(z["frontier"]),
            level=jnp.asarray(z["level"]),
            changed=jnp.asarray(z["changed"]),
        )
    except KeyError as exc:
        raise CheckpointError(
            f"checkpoint {path!r} is missing field {exc}; "
            "not a BfsState dump"
        ) from exc


def load_checkpoint(path: str | os.PathLike) -> BfsState:
    return _state_from_npz(load_npz_strict(path), os.fspath(path))


def _checkpoint_candidates(base: str) -> list[tuple[int, str]]:
    """``[(level, path)]`` of every ``{base}.ckpt_<level>.npz``, newest
    first."""
    import glob

    out = []
    for path in glob.glob(f"{base}.ckpt_*.npz"):
        stem = path[len(base) + len(".ckpt_"):-len(".npz")]
        if stem.isdigit():
            out.append((int(stem), path))
    return sorted(out, reverse=True)


def latest_checkpoint(base: str | os.PathLike) -> tuple[str, int] | None:
    """``(path, level)`` of the newest valid ``{base}.ckpt_<level>.npz``,
    skipping (and warning about) damaged ones — a torn final dump must
    not block resuming from the one before it.  Thin probe over
    :func:`load_latest_checkpoint`, which resuming callers should use
    directly (it returns the state from the same single read)."""
    found = load_latest_checkpoint(base)
    return (found[2], found[1]) if found is not None else None


def load_latest_checkpoint(
    base: str | os.PathLike,
    expect: dict | None = None,
) -> tuple[BfsState, int, str] | None:
    """``(state, level, path)`` from the newest valid checkpoint in ONE
    read (resume startup at scale is I/O-bound; validating then
    re-loading would pay it twice).  Damaged dumps are skipped with a
    warning, same contract as :func:`latest_checkpoint`.

    ``expect`` maps meta keys to required values (e.g. ``{"source": 5,
    "engine": "push"}``): a checkpoint recording a DIFFERENT value for
    one of them was written by another run configuration and is skipped
    with a warning — resuming it would burn the whole tail before dying
    at the final invariant check.  Checkpoints predating the metadata
    (no ``meta_<k>`` field) are accepted for compatibility."""
    import logging

    log = logging.getLogger(__name__)
    for level, path in _checkpoint_candidates(os.fspath(base)):
        try:
            z = load_npz_strict(path)
        except CheckpointError as exc:
            log.warning("skipping %s", exc)
            continue
        mismatch = None
        for k, v in (expect or {}).items():
            stored = z.get(f"meta_{k}")
            if stored is not None and stored.item() != v:
                mismatch = f"{k}={stored.item()!r} (this run: {v!r})"
                break
        if mismatch is not None:
            log.warning(
                "skipping %s: written by a different run config — %s",
                path, mismatch,
            )
            continue
        try:
            return _state_from_npz(z, path), level, path
        except CheckpointError as exc:
            log.warning("skipping %s", exc)
    return None


def state_from_arrays(dist, parent, frontier, level: int) -> BfsState:
    """Build a resumable carry from host arrays sized [V] or [V+1]; pads the
    sentinel slot if missing (e.g. state parsed from a text dump)."""
    dist = np.asarray(dist, dtype=np.int32)
    parent = np.asarray(parent, dtype=np.int32)
    frontier = np.asarray(frontier, dtype=bool)
    from ..graph.csr import INF_DIST

    def pad(a, fill):
        return np.concatenate([a, np.asarray([fill], dtype=a.dtype)])

    if dist.ndim == 1:
        dist, parent, frontier = pad(dist, INF_DIST), pad(parent, -1), pad(frontier, False)
    return BfsState(
        dist=jnp.asarray(dist),
        parent=jnp.asarray(parent),
        frontier=jnp.asarray(frontier),
        level=jnp.int32(level),
        changed=jnp.bool_(bool(frontier.any())),
    )
