"""Persistent artifact caches (ISSUE 2: delete the cold path).

``bfs_tpu.cache.layout`` — content-addressed on-disk layout bundles
(relay masks / ELL folds), memmap-loaded with integrity checks, so a warm
engine init is seconds instead of the 434 s cold relay build.  The compile
side (JAX persistent cache + serialized executables) is configured by
:func:`bfs_tpu.config.enable_compile_cache`.
"""

from .layout import (  # noqa: F401
    LayoutCache,
    STORE_VERSION,
    graph_content_hash,
    load_or_build_pull,
    load_or_build_relay,
    pull_key,
    relay_key,
)
