"""Persistent layout-bundle cache: the 434-second build, paid once ever.

Round 5's driver-verified headline was gated on the COLD path, not the
kernel: a fresh process burned 434 s rebuilding the relay layout before a
single superstep ran (VERDICT r5 "missing" #1).  The layout is a pure
function of (graph content, layout parameters, layout code version), so it
is a cacheable artifact — this module stores finished layouts
(:class:`~bfs_tpu.graph.relay.RelayGraph` permutation masks / ELL folds /
sparse adjacency / class metadata) as content-addressed on-disk bundles:

  * **bundle** = one directory ``<root>/<key>/`` holding ``meta.json`` plus
    one ``.npy`` file per array field.  Large arrays load back as
    ``np.memmap`` views, so a warm load is directory-walk + header-read
    cheap — the mask gigabytes stream lazily when the engine ships them to
    the device (which it was going to do anyway).
  * **key** = ``{kind}_{layout params}_s{STORE_VERSION}_{graph hash}``
    where the graph hash is a blake2b over ``(V, E, src, dst)`` — a code
    bump (LAYOUT_VERSION / STORE_VERSION), a parameter change, or a
    different graph can never alias a stale bundle.
  * **integrity** — every field records dtype/shape and a head+tail
    fingerprint in ``meta.json``; a failed check (truncated write, manual
    tampering) drops the bundle and reports a miss, so the worst case is a
    rebuild, never a wrong layout.
  * **atomicity** — bundles are written to a ``.tmp.<pid>`` sibling and
    renamed into place; concurrent builders race benignly (first rename
    wins, the loser discards its copy).
  * **tags** — optional human-readable aliases (``tags/<name>.json`` ->
    key) so callers that know their graph only by config (the bench's
    scale-fallback estimator, before the graph is even generated) can
    probe warmth without hashing anything.

The serializers live next to the dataclasses they flatten
(:func:`~bfs_tpu.graph.relay.relay_to_arrays`,
:func:`~bfs_tpu.graph.ell.pull_to_arrays`); this module only owns the disk
format.  Hit/miss counts feed :func:`bfs_tpu.utils.metrics.bump_artifact`.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import time
from typing import Any

import numpy as np

from .. import knobs
from ..utils.metrics import bump_artifact

logger = logging.getLogger(__name__)

#: Bump on any change to the bundle disk format (meta schema, fingerprint
#: rule, file layout).  Part of every key, so old bundles simply miss.
STORE_VERSION = 1

#: Elements hashed from each end of an array for the integrity fingerprint.
#: Full-array hashing would re-read gigabytes and defeat the memmap load;
#: head+tail+length catches the real corruption modes (truncation, partial
#: writes, wrong file) without touching the middle.
_FPR_ELEMS = 16384

#: Arrays at or under this byte size load eagerly (a 0-d scalar or a class
#: table is cheaper to read than to memmap); everything larger memmaps.
_MMAP_MIN_BYTES = 1 << 23


def default_root() -> str:
    from ..config import layout_cache_dir

    return layout_cache_dir()


def graph_content_hash(graph) -> str:
    """blake2b-128 over ``(num_vertices, E, src bytes, dst bytes)``.

    Accepts anything with ``num_vertices``/``src``/``dst`` (host
    :class:`~bfs_tpu.graph.csr.Graph` or a padded
    :class:`~bfs_tpu.graph.csr.DeviceGraph` — padding bytes hash too, which
    is conservative: a padding change rebuilds rather than aliases).
    Memoized on the object; ~1-2 s for the 1.6 GB s24 edge arrays.
    """
    cached = getattr(graph, "_content_hash", None)
    if cached is not None:
        return cached
    h = hashlib.blake2b(digest_size=16)
    src = np.ascontiguousarray(np.asarray(graph.src).reshape(-1))
    dst = np.ascontiguousarray(np.asarray(graph.dst).reshape(-1))
    h.update(np.int64(graph.num_vertices).tobytes())
    h.update(np.int64(src.shape[0]).tobytes())
    h.update(str(src.dtype).encode())
    h.update(memoryview(src))
    h.update(memoryview(dst))
    digest = h.hexdigest()
    try:
        object.__setattr__(graph, "_content_hash", digest)
    except (AttributeError, TypeError):
        pass
    return digest


def relay_key(graph) -> str:
    from ..graph.relay import COMPACT_MIN_D, LAYOUT_VERSION

    return (
        f"relay_v{LAYOUT_VERSION}c{COMPACT_MIN_D}_s{STORE_VERSION}"
        f"_{graph_content_hash(graph)}"
    )


def pull_key(graph, k: int, row_multiple: int) -> str:
    return (
        f"pull_k{k}r{row_multiple}_s{STORE_VERSION}"
        f"_{graph_content_hash(graph)}"
    )


def _fingerprint(arr: np.ndarray) -> str:
    """Cheap integrity fingerprint: dtype + shape + head/tail sample.
    Works on memmaps without faulting in the full array."""
    arr = np.asarray(arr)
    h = hashlib.blake2b(digest_size=8)
    h.update(str(arr.dtype).encode())
    h.update(repr(tuple(arr.shape)).encode())
    flat = arr.reshape(-1)
    take = min(int(flat.shape[0]), _FPR_ELEMS)
    h.update(np.ascontiguousarray(flat[:take]).tobytes())
    h.update(np.ascontiguousarray(flat[flat.shape[0] - take :]).tobytes())
    return h.hexdigest()


class LayoutCache:
    """Content-addressed bundle store under one root directory."""

    def __init__(self, root: str | None = None):
        self.root = root or default_root()

    # ------------------------------------------------------------ bundles --
    def _dir(self, key: str) -> str:
        return os.path.join(self.root, key)

    def has(self, key: str) -> bool:
        return os.path.isfile(os.path.join(self._dir(key), "meta.json"))

    def save(
        self,
        key: str,
        arrays: dict[str, np.ndarray],
        meta: dict[str, Any] | None = None,
        *,
        tag: str | None = None,
    ) -> None:
        """Write a bundle atomically; ``meta`` is free-form JSON (build
        seconds, provenance).  A concurrent save of the same key races
        benignly — the first finished rename wins."""
        final = self._dir(key)
        tmp = f"{final}.tmp.{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        try:
            fields = {}
            for name, arr in arrays.items():
                arr = np.asarray(arr)
                np.save(os.path.join(tmp, f"{name}.npy"), arr)
                fields[name] = {
                    "dtype": str(arr.dtype),
                    "shape": list(arr.shape),
                    "fingerprint": _fingerprint(arr),
                }
            doc = {
                "key": key,
                "store_version": STORE_VERSION,
                "created": time.time(),
                "fields": fields,
                "meta": meta or {},
            }
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            if os.path.isdir(final):
                shutil.rmtree(tmp, ignore_errors=True)  # lost the race
            else:
                try:
                    os.rename(tmp, final)
                except OSError:
                    shutil.rmtree(tmp, ignore_errors=True)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        if tag:
            self.tag(tag, key)

    def load(self, key: str, *, mmap: bool = True):
        """``(meta_doc, arrays)`` for a valid bundle, else None.

        Every field is checked against its recorded dtype/shape/fingerprint;
        any mismatch (or a stale key / store version) drops the bundle so
        the caller rebuilds — corruption can only cost time, not
        correctness."""
        d = self._dir(key)
        meta_path = os.path.join(d, "meta.json")
        if not os.path.isfile(meta_path):
            return None
        try:
            with open(meta_path) as f:
                doc = json.load(f)
            if doc.get("key") != key or doc.get("store_version") != STORE_VERSION:
                raise ValueError("bundle key/store-version mismatch")
            arrays = {}
            for name, spec in doc["fields"].items():
                nbytes = int(
                    np.dtype(spec["dtype"]).itemsize
                    * max(int(np.prod(spec["shape"] or [1])), 1)
                )
                arr = np.load(
                    os.path.join(d, f"{name}.npy"),
                    mmap_mode="r" if (mmap and nbytes > _MMAP_MIN_BYTES) else None,
                )
                if (
                    str(arr.dtype) != spec["dtype"]
                    or list(arr.shape) != spec["shape"]
                    or _fingerprint(arr) != spec["fingerprint"]
                ):
                    raise ValueError(f"integrity check failed on field {name!r}")
                arrays[name] = arr
            return doc, arrays
        except (OSError, MemoryError) as exc:
            # Environmental failure (fd pressure, remote-FS hiccup, OOM):
            # report a miss but do NOT delete — the bundle may be intact
            # and a 434 s artifact must not die to a transient error.
            logger.warning("layout bundle %s unreadable (kept): %s", key, exc)
            return None
        except Exception as exc:
            logger.warning("dropping corrupt/stale layout bundle %s: %s", key, exc)
            self.invalidate(key)
            return None

    def invalidate(self, key: str) -> None:
        shutil.rmtree(self._dir(key), ignore_errors=True)

    # --------------------------------------------------------------- tags --
    def _tag_path(self, tag: str) -> str:
        safe = "".join(c if (c.isalnum() or c in "._-") else "_" for c in tag)
        return os.path.join(self.root, "tags", f"{safe}.json")

    def tag(self, tag: str, key: str) -> None:
        """Alias ``tag`` -> ``key`` (atomic single-file write)."""
        path = self._tag_path(tag)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"key": key}, f)
        os.replace(tmp, path)

    def resolve_tag(self, tag: str) -> str | None:
        """The key a tag points at, iff that bundle exists — the
        hash-free warmth probe the bench estimator uses before the graph
        is generated."""
        try:
            with open(self._tag_path(tag)) as f:
                key = json.load(f)["key"]
        except (OSError, ValueError, KeyError):
            return None
        return key if self.has(key) else None


# ---------------------------------------------------------------------------
# High-level load-or-build: the one call sites use.
# ---------------------------------------------------------------------------

def _load_or_build(graph, *, cache, tag, kind, key_fn, build_fn, to_arrays,
                   from_arrays, build_meta: dict | None = None,
                   prepare_build=None):
    """Shared load-or-build skeleton; the ``info`` dict contract lives in
    ONE place: ``cache`` ("hit"/"miss"/"disabled"), ``key``,
    ``load_seconds`` (hit) or ``save_seconds`` (miss), and
    ``build_seconds`` — on a hit the COLD build time recorded when the
    bundle was written, so every warm run can report its warm-vs-cold
    speedup.  ``build_meta`` is builder provenance (flavor, per-stage
    seconds): recorded in the bundle on a miss, replayed from the bundle's
    ``meta`` on a hit, and merged into the returned info either way.
    ``prepare_build`` runs only when a build is actually imminent, OUTSIDE
    the timed window — once-per-process costs (module imports, worker-pool
    start) stay off both the warm path and the build clock."""
    from ..obs.spans import span as obs_span

    build_meta = build_meta if build_meta is not None else {}
    if cache is None:
        if prepare_build is not None:
            prepare_build()
        t0 = time.perf_counter()
        with obs_span("layout.build", kind=kind):
            obj = build_fn()
        return obj, {
            "cache": "disabled",
            "build_seconds": time.perf_counter() - t0,
            **build_meta,
        }
    t0 = time.perf_counter()
    key = key_fn()
    with obs_span("layout.bundle_load", kind=kind):
        loaded = cache.load(key)
    if loaded is not None:
        doc, arrays = loaded
        obj = from_arrays(arrays)
        bump_artifact("layout_cache_hits")
        if tag:
            cache.tag(tag, key)
        meta = doc["meta"]
        return obj, {
            "cache": "hit",
            "key": key,
            "load_seconds": time.perf_counter() - t0,
            "build_seconds": float(meta.get("build_seconds", -1.0)),
            # provenance of the COLD build that wrote this bundle
            **{
                k: meta[k]
                for k in ("builder", "build_stages")
                if k in meta
            },
        }
    bump_artifact("layout_cache_misses")
    if prepare_build is not None:
        prepare_build()
    t1 = time.perf_counter()
    with obs_span("layout.build", kind=kind):
        obj = build_fn()
    build_seconds = time.perf_counter() - t1
    t2 = time.perf_counter()
    with obs_span("layout.bundle_save", kind=kind):
        cache.save(
            key,
            to_arrays(obj),
            {
                "kind": kind,
                "build_seconds": build_seconds,
                # getattr: sidecar artifacts (adj tiles) size differently
                "num_vertices": int(getattr(obj, "num_vertices", -1)),
                "num_edges": int(getattr(obj, "num_edges", -1)),
                **build_meta,
            },
            tag=tag,
        )
    return obj, {
        "cache": "miss",
        "key": key,
        "build_seconds": build_seconds,
        "save_seconds": time.perf_counter() - t2,
        **build_meta,
    }


def resolve_builder(builder: str | None = None) -> str:
    """Relay builder flavor: explicit arg > ``BFS_TPU_LAYOUT_BUILD`` >
    ``device`` (the first-touch default since ISSUE 10; ``host`` is the
    pinned oracle builder)."""
    builder = builder or knobs.get("BFS_TPU_LAYOUT_BUILD")
    if builder not in ("device", "host"):
        raise ValueError(
            f"unknown layout builder {builder!r}; use device|host"
        )
    return builder


def load_or_build_relay(graph, *, cache: LayoutCache | None = None,
                        tag: str | None = None, builder: str | None = None):
    """``(RelayGraph, info)`` — disk-cached build of the relay layout
    (info contract: :func:`_load_or_build`).

    ``builder`` selects the DEVICE pipeline (graph/relay_device.py — the
    default first-touch path) or the host oracle builder
    (``BFS_TPU_LAYOUT_BUILD=host``); the resulting bundles are
    byte-identical either way (parity-tested), so the flavor never splits
    the content-addressed cache.  A device-build failure falls back to the
    host builder with a logged warning — a build must never be less
    available than it was before the device path existed."""
    from ..graph.relay import build_relay_graph, relay_from_arrays, relay_to_arrays

    builder = resolve_builder(builder)
    stage_times: dict = {}
    build_meta = {"builder": builder, "build_stages": stage_times}
    device_builder: list = []

    def prepare():
        # Import only when a build is imminent (warm hits never pay the
        # module + worker-pool startup), and OUTSIDE the timed window —
        # the host flavor's module is imported long before its build is
        # timed, so the device flavor gets the same treatment.
        if builder == "device" and not device_builder:
            from ..graph.relay_device import build_relay_graph_device

            device_builder.append(build_relay_graph_device)

    def build():
        if builder == "device":
            try:
                return device_builder[0](graph, stage_times=stage_times)
            except Exception as exc:
                logger.warning(
                    "device layout build failed (%r); falling back to the "
                    "host builder", exc,
                )
                stage_times.clear()
                stage_times["fallback"] = repr(exc)
                build_meta["builder"] = "host"  # what actually built it
        return build_relay_graph(graph)

    return _load_or_build(
        graph,
        cache=cache,
        tag=tag,
        kind="relay",
        key_fn=lambda: relay_key(graph),
        build_fn=build,
        to_arrays=relay_to_arrays,
        from_arrays=relay_from_arrays,
        build_meta=build_meta,
        prepare_build=prepare,
    )


def tiles_key(rg) -> str:
    """Content key for the MXU adjacency-tile SIDECAR bundle (ISSUE 15):
    blake2b over the relay layout's relabeled edge structure + relabel
    table — everything the tile builder consumes.  A SIDECAR next to —
    never inside — the relay bundle, so the relay schema (and every
    existing bundle) stays byte-identical."""
    from ..graph.adj_tiles import TILES_VERSION

    h = hashlib.blake2b(digest_size=16)
    for arr in (rg.adj_indptr, rg.adj_dst, rg.new2old):
        a = np.ascontiguousarray(np.asarray(arr))
        h.update(str(a.dtype).encode())
        h.update(memoryview(a))
    h.update(np.int64(rg.vr).tobytes())
    return f"adjtiles_v{TILES_VERSION}_s{STORE_VERSION}_{h.hexdigest()}"


def load_or_build_tiles(rg, *, cache: LayoutCache | None = None,
                        builder: str | None = None,
                        budget_bytes: int | None = None):
    """``(AdjTiles, info)`` — the MXU arm's tiled adjacency, disk-cached
    as a sidecar bundle (info contract: :func:`_load_or_build`).  The
    host builder is the pinned oracle; the device arm
    (``BFS_TPU_TILES_BUILD``, default device) is bit-identical and falls
    back to host on failure.  ``BFS_TPU_TILES_CACHE=1`` enables the
    default on-disk cache when the caller passes none (engine inits stay
    build-only by default — fixture-scale tiles build in milliseconds)."""
    from ..graph.adj_tiles import (
        build_adj_tiles_from_relay,
        resolve_tiles_builder,
        tiles_from_arrays,
        tiles_to_arrays,
    )

    if cache is None and knobs.get("BFS_TPU_TILES_CACHE"):
        cache = LayoutCache()
    builder = resolve_tiles_builder(builder)
    at, info = _load_or_build(
        rg,
        cache=cache,
        tag=None,
        kind="adj_tiles",
        key_fn=lambda: tiles_key(rg),
        build_fn=lambda: build_adj_tiles_from_relay(
            rg, builder, budget_bytes
        ),
        to_arrays=tiles_to_arrays,
        from_arrays=tiles_from_arrays,
        build_meta={"builder": builder},
    )
    # The budget must gate WARM HITS too: the key does not include the
    # budget knob, so a bundle built under a looser BFS_TPU_MXU_TILE_GB
    # would otherwise ship right past a tightened one.
    if budget_bytes is not None and at.nbytes > budget_bytes:
        raise ValueError(
            f"cached adjacency tile layout is {at.nbytes >> 20} MB, over "
            f"the {budget_bytes >> 20} MB budget (BFS_TPU_MXU_TILE_GB)"
        )
    return at, info


def verify_tiles_bundle(rg, *, cache: LayoutCache | None = None) -> dict:
    """Integrity report of the adj-tiles sidecar bundle for ``rg``
    WITHOUT building on a miss (the cache_warm ``--tiles`` check): loads
    the bundle — every array fingerprint-checked by :meth:`LayoutCache.load`,
    a corrupt field surfaces as ``absent`` — then validates the geometry
    invariants the streamed host store (stream/store.py) leans on:
    version/shape agreement with the relay graph, a monotone
    ``sb_indptr`` closing at ``nt``, and every real tile's row/column ids
    inside the padded spaces.  Returns a JSON-ready dict; never raises on
    a bad bundle."""
    from ..graph.adj_tiles import (
        SB_VERTS,
        TILE,
        TILES_VERSION,
        tiles_from_arrays,
    )

    cache = cache if cache is not None else LayoutCache()
    key = tiles_key(rg)
    loaded = cache.load(key)
    if loaded is None:
        return {"key": key, "ok": False, "status": "absent"}
    _doc, arrays = loaded
    try:
        at = tiles_from_arrays(arrays)
    except Exception as exc:  # stale dims row / shape drift
        return {"key": key, "ok": False, "status": f"unreadable: {exc}"}
    problems = []
    if int(arrays["dims"][0]) != TILES_VERSION:
        problems.append(
            f"tiles version {int(arrays['dims'][0])} != {TILES_VERSION}"
        )
    if at.rows != rg.vr:
        problems.append(f"rows {at.rows} != relay vr {rg.vr}")
    sb = np.asarray(at.sb_indptr)
    if not (np.all(np.diff(sb) >= 0) and int(sb[0]) == 0
            and int(sb[-1]) == at.nt):
        problems.append("sb_indptr not a monotone span table closing at nt")
    nt = at.nt
    if nt:
        if int(np.asarray(at.row_idx[:nt]).max()) >= at.rtp // TILE:
            problems.append("real tile row_idx outside the padded row space")
        if int(np.asarray(at.col_id[:nt]).max()) >= at.vtp // TILE:
            problems.append("real tile col_id outside the padded col space")
    return {
        "key": key,
        "ok": not problems,
        "status": "ok" if not problems else "; ".join(problems),
        "num_tiles": int(at.nt),
        "num_superblocks": int(at.vtp // SB_VERTS),
        "tile_bytes": int(at.nbytes),
    }


def labels_key(graph, k: int) -> str:
    """Content key for the landmark distance-label SIDECAR bundle
    (ISSUE 20): (graph content, K, label code version).  Landmark
    SAMPLING is itself seeded from the graph content hash
    (:func:`bfs_tpu.serve.labels.sample_landmarks`), so the key needs no
    landmark list — same graph + same K always means the same index."""
    from ..serve.labels import LABELS_VERSION

    return (
        f"labels_k{int(k)}_v{LABELS_VERSION}_s{STORE_VERSION}"
        f"_{graph_content_hash(graph)}"
    )


def load_or_build_labels(graph, k: int, *, cache: LayoutCache | None = None,
                         engine: str = "pull",
                         ckpt_dir: str | os.PathLike | None = None):
    """``(LabelIndex, info)`` — the serve label tier's landmark index,
    disk-cached as a sidecar bundle next to the layout bundle (info
    contract: :func:`_load_or_build`).  The K-root sweep itself is
    chunk-checkpointed (:func:`bfs_tpu.serve.labels.build_label_index`),
    so a killed COLD build resumes; a warm hit never recomputes."""
    from ..serve.labels import (
        build_label_index,
        labels_from_arrays,
        labels_to_arrays,
    )

    return _load_or_build(
        graph,
        cache=cache,
        tag=None,
        kind="labels",
        key_fn=lambda: labels_key(graph, k),
        build_fn=lambda: build_label_index(
            graph, k, engine=engine, ckpt_dir=ckpt_dir
        ),
        to_arrays=labels_to_arrays,
        from_arrays=labels_from_arrays,
        build_meta={"engine": engine, "k": int(k)},
    )


def verify_labels_bundle(graph, k: int, *,
                         cache: LayoutCache | None = None) -> dict:
    """Integrity report of the label sidecar bundle WITHOUT building on a
    miss (the cache_warm ``--labels`` check): loads the bundle — every
    array fingerprint-checked by :meth:`LayoutCache.load` — then
    validates the label invariants the oracle leans on: version/shape
    agreement with the graph, landmark ids in range, each landmark at
    distance 0 from itself and its own parent, and the unreachable
    sentinel agreeing between dist and parent.  Returns a JSON-ready
    dict; never raises on a bad bundle."""
    from ..serve.labels import LABEL_INF, LABELS_VERSION, labels_from_arrays

    cache = cache if cache is not None else LayoutCache()
    key = labels_key(graph, k)
    loaded = cache.load(key)
    if loaded is None:
        return {"key": key, "ok": False, "status": "absent"}
    _doc, arrays = loaded
    try:
        idx = labels_from_arrays(arrays)
    except Exception as exc:  # version bump / shape drift
        return {"key": key, "ok": False, "status": f"unreadable: {exc}"}
    problems = []
    dims = np.asarray(arrays["dims"])
    if int(dims[0]) != LABELS_VERSION:
        problems.append(f"labels version {int(dims[0])} != {LABELS_VERSION}")
    if idx.num_vertices != graph.num_vertices:
        problems.append(
            f"num_vertices {idx.num_vertices} != graph "
            f"{graph.num_vertices}"
        )
    if idx.dist.shape != (idx.k, idx.num_vertices):
        problems.append(f"dist shape {idx.dist.shape} != (K, V)")
    if idx.parent.shape != idx.dist.shape:
        problems.append("parent shape differs from dist")
    lm = np.asarray(idx.landmarks)
    if lm.size and (
        int(lm.min()) < 0 or int(lm.max()) >= idx.num_vertices
    ):
        problems.append("landmark id outside the vertex space")
    if not problems and lm.size:
        rows = np.arange(idx.k)
        if np.asarray(idx.dist)[rows, lm].any():
            problems.append("a landmark is not at distance 0 from itself")
        if (np.asarray(idx.parent)[rows, lm] != lm).any():
            problems.append("a landmark is not its own parent")
        sent = np.asarray(idx.dist) == LABEL_INF
        orphan = np.asarray(idx.parent) < 0
        if (sent != orphan).any():
            problems.append(
                "unreachable sentinel disagrees between dist and parent"
            )
    return {
        "key": key,
        "ok": not problems,
        "status": "ok" if not problems else "; ".join(problems),
        "k": int(idx.k),
        "index_bytes": int(idx.nbytes),
        "device_bytes": int(idx.device_bytes),
    }


# ---------------------------------------------------------------------------
# Phase-probe verdict memo (ISSUE 15 satellite): probe_phase_kernels is a
# pure function of (layout shapes, kernel/probe sources, backend, knobs) —
# serve cold-start used to re-pay its K-loops per registered graph even when
# the layout bundle itself warm-hit.  Verdicts are tiny JSON files stored
# content-keyed next to the layout bundles.
# ---------------------------------------------------------------------------

#: Source files whose bytes key the probe verdict: the kernels and the
#: probe itself — an arm implementation change must re-probe.
_PROBE_SOURCES = (
    "ops/relay.py", "ops/relay_pallas.py", "ops/relay_mxu.py",
    "profiling.py",
)

#: Knob env keying the probe verdict — DERIVED from the registry
#: (``affects`` contains ``probe``); KNB002 proves membership against
#: bfs_tpu/knobs.py instead of a hand list.
_PROBE_ENV = knobs.flavor_env("probe")


def probe_verdict_key(eng) -> str:
    """Content key of one engine's probe verdict: layout geometry (the
    probe's operand shapes), expansion-arm geometry when tiles exist,
    kernel sources, jax version + backend + device kind, and the knob
    env."""
    import jax

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.blake2b(digest_size=16)
    for rel in _PROBE_SOURCES:
        try:
            with open(os.path.join(pkg, rel), "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"missing:" + rel.encode())
    rg = eng.relay_graph
    geo = (
        rg.vr, rg.net_size, rg.vperm_size,
        tuple((c.width, c.va, c.vb, c.sa, c.sb, c.vertex_major)
              for c in rg.in_classes),
        bool(eng.packed),
    )
    if eng.adj_tiles is not None:
        geo = geo + (eng.adj_tiles.nt, eng.adj_tiles.vtp, eng.adj_tiles.rtp)
    h.update(repr(geo).encode())
    dev = jax.devices()[0]
    h.update(
        f"{jax.__version__}|{jax.default_backend()}|"
        f"{getattr(dev, 'device_kind', '?')}".encode()
    )
    for knob in _PROBE_ENV:
        h.update(f"{knob}={os.environ.get(knob, '')}".encode())
    return f"probe_{h.hexdigest()}"


def _probe_dir(root: str | None = None) -> str:
    return os.path.join(root or default_root(), "probe")


def load_probe_verdict(key: str, root: str | None = None) -> dict | None:
    path = os.path.join(_probe_dir(root), f"{key}.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("key") != key:
            raise ValueError("probe verdict key mismatch")
        bump_artifact("phase_probe_memo_hits")
        return doc["verdict"]
    except OSError:
        return None
    except Exception as exc:
        logger.warning("dropping corrupt probe verdict %s: %s", key, exc)
        try:
            os.remove(path)
        except OSError:
            pass
        return None


def save_probe_verdict(key: str, verdict: dict,
                       root: str | None = None) -> None:
    d = _probe_dir(root)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{key}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"key": key, "created": time.time(), "verdict": verdict},
                  f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    bump_artifact("phase_probe_memo_writes")


def load_or_build_pull(graph, *, k: int | None = None, row_multiple: int = 64,
                       cache: LayoutCache | None = None,
                       tag: str | None = None):
    """``(PullGraph, info)`` — disk-cached build of the ELL pull layout
    (info contract: :func:`_load_or_build`)."""
    from ..graph.ell import (
        DEFAULT_K,
        build_pull_graph,
        pull_from_arrays,
        pull_to_arrays,
    )

    k = DEFAULT_K if k is None else int(k)
    return _load_or_build(
        graph,
        cache=cache,
        tag=tag,
        kind="pull",
        key_fn=lambda: pull_key(graph, k, row_multiple),
        build_fn=lambda: build_pull_graph(graph, k=k, row_multiple=row_multiple),
        to_arrays=pull_to_arrays,
        from_arrays=pull_from_arrays,
    )
