"""Crash resilience: run journaling, fault injection, retry/backoff.

The reference implementation inherits fault tolerance from Spark — RDD
lineage means a lost executor never loses a superstep, and the paper lists
superstep checkpointing as a core capability.  This package is the TPU
reproduction's equivalent, split by failure mode:

  * :mod:`~bfs_tpu.resilience.journal` — :class:`RunJournal`, an
    append-only crash-safe JSONL journal of phase results.  ``bench.py``
    journals every completed phase (layout, reference run, each timed
    repeat, each verification verdict, the headline) and replays it on
    restart, so a SIGKILLed driver run finishes its verified headline on
    the next invocation instead of starting over (the r5 failure mode:
    rc=124 forty seconds before the final check line).
  * :mod:`~bfs_tpu.resilience.faults` — ``BFS_TPU_FAULT`` phase-boundary
    fault injection (raise or SIGKILL at the nth arrival) plus file
    corruption injectors, used by tests and ``tools/chaos_run.py`` to
    prove resume-equals-uninterrupted.
  * :mod:`~bfs_tpu.resilience.retry` — deadline-aware exponential backoff
    with jitter and a transient/permanent error classifier; the serving
    layer retries transient device errors before degrading to the
    sequential oracle, and the bench retries engine init/compile.
  * :mod:`~bfs_tpu.resilience.superstep_ckpt` — superstep-granular
    checkpoint/restore (ISSUE 14): fused traversals run as bounded
    segments whose full loop carry is snapshotted per epoch, so a kill
    40 supersteps into a deep search resumes mid-traversal
    bit-identically instead of restarting (``BFS_TPU_CKPT``).
"""

# superstep_ckpt is NOT re-exported here: this package must stay
# importable under the no-jax lint stub (obs tooling reads journals
# through it), and the checkpoint store pulls in utils.checkpoint.
# Import bfs_tpu.resilience.superstep_ckpt directly.
from .faults import FaultInjected, corrupt_file, fault_point, fault_spec
from .journal import RunJournal, config_key
from .retry import (
    CircuitBreaker,
    PermanentError,
    RetryError,
    RetryPolicy,
    TransientError,
    default_classify,
    retry_call,
)

__all__ = [
    "CircuitBreaker",
    "FaultInjected",
    "PermanentError",
    "RetryError",
    "RetryPolicy",
    "RunJournal",
    "TransientError",
    "config_key",
    "corrupt_file",
    "default_classify",
    "fault_point",
    "fault_spec",
    "retry_call",
]
