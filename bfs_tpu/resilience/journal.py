"""RunJournal: append-only, crash-safe JSONL journal of phase results.

One journal file per (bench config, graph) pair, content-addressed the
same way :mod:`bfs_tpu.cache.layout` keys layout bundles: the file name is
a blake2b over the canonical config JSON, and the journaled ``graph``
phase carries the graph content hash so a resumed run can prove it is
looking at the same graph before trusting any record.

Disk format — one JSON object per line:

    {"i": 3, "phase": "repeat:0", "t": 1722.4, "crc": "deadbeef",
     "payload": {...}, "arrays": "s8_..._reference.npz"}

  * ``i`` — strictly increasing record index (a splice or a lost write in
    the middle breaks the sequence and invalidates the tail);
  * ``crc`` — crc32 over the canonical JSON of ``(i, phase, payload)``;
    a torn or bit-flipped record fails the check and invalidates the
    TAIL from that record on (everything before it is still trusted —
    an append-only log is only ever damaged at the end by a crash,
    and anything else is corruption the injectors simulate);
  * ``arrays`` — optional sidecar ``.npz`` (written atomically via
    :func:`bfs_tpu.utils.checkpoint.save_npz_atomic`) for payloads that
    are arrays rather than scalars (the reference run's reached-mask);
    the record stores the file name plus a fingerprint, and a missing or
    corrupt sidecar invalidates that record alone.

Writes are append + flush + fsync, so a SIGKILL can lose at most the
record being written — which the crc/partial-line check then trims on
the next open.  Rewrites only happen on invalidation (config or graph
mismatch), which rotates the whole file aside to ``*.stale.<n>`` and
starts fresh; a journal is never edited in place.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import zlib
from typing import Any

from .. import knobs

JOURNAL_VERSION = 1

_HEADER_PHASE = "_header"


def _canon(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _crc(i: int, phase: str, payload: Any) -> str:
    return f"{zlib.crc32(_canon([i, phase, payload]).encode()):08x}"


def config_key(config: dict) -> str:
    """blake2b-64 over the canonical config JSON — the journal's file
    stem, so one config maps to one journal the way one graph maps to one
    layout bundle."""
    return hashlib.blake2b(_canon(config).encode(), digest_size=8).hexdigest()


#: Knob names that must ride in every bench journal config — DERIVED
#: from the registry (``affects`` contains ``journal``); KNB002 proves
#: membership both ways against bfs_tpu/knobs.py.
ENV_CONFIG_KEYS = knobs.flavor_env("journal")


def env_config() -> dict:
    """``{journal config key: effective raw value}`` for every
    journal-affecting knob: the env value when set and non-empty, else
    the registered default — so a default run and an explicit-default
    run resume each other, and any knob flip maps to a different
    :func:`config_key` (never to a resume blending two configs)."""
    out = {}
    for jk, name in knobs.journal_map().items():
        v = knobs.raw(name)
        out[jk] = v if v else knobs.KNOBS[name].default
    return out


def read_records(path: str) -> list:
    """Lenient read-only replay of a journal FILE: every crc-valid record
    in index order, stopping at the first torn/invalid line — no config
    needed and nothing is locked or truncated.  The observability CLI
    (``bfs-tpu-obs``) stitches traces from finished journals through this
    without having to reconstruct the exact bench config that keyed them."""
    records = []
    expect_i = 0
    if not os.path.exists(path):
        return records
    with open(path, "rb") as f:
        for raw in f:
            if not raw.endswith(b"\n"):
                break
            try:
                rec = json.loads(raw)
                ok = (
                    isinstance(rec, dict)
                    and rec.get("i") == expect_i
                    and isinstance(rec.get("phase"), str)
                    and _crc(rec["i"], rec["phase"], rec["payload"])
                    == rec.get("crc")
                )
            except (ValueError, KeyError, TypeError):
                break
            if not ok:
                break
            records.append(rec)
            expect_i += 1
    return records


class RunJournal:
    """Append-only phase journal for one run configuration.

    ``get(phase)`` returns the payload of a completed phase (or None);
    ``put(phase, payload, arrays=...)`` appends one durable record.
    Phases are free-form strings; per-item phases use ``"name:<i>"``.
    """

    #: Seconds to wait for a draining predecessor's file lock before
    #: failing; tests shrink it.
    LOCK_TIMEOUT_S = 10.0

    def __init__(self, path: str, config: dict):
        self.path = path
        self.config = dict(config)
        self._records: dict[str, dict] = {}
        self._arrays_cache: dict[str, dict | None] = {}
        self._fh = None
        self.resumed_phases: list[str] = []
        self.invalidated: str | None = None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._open()

    @classmethod
    def open_for(cls, root: str, config: dict) -> "RunJournal":
        """The journal for ``config`` under ``root`` (the content-addressed
        path: ``<root>/<config_key>.jsonl``)."""
        return cls(os.path.join(root, f"{config_key(config)}.jsonl"), config)

    # ----------------------------------------------------------- lifecycle --
    def _flock(self, fh, timeout_s: float | None = None) -> None:
        """Exclusive inter-process lock on the journal file: two live
        processes with the same config (a driver re-invoking while the
        previous run drains its SIGTERM handler) must never interleave
        appends — an interleaved ``i`` sequence would make the next replay
        trim validly-fsync'd records.  Waits briefly for a draining
        predecessor, then fails loudly rather than corrupting."""
        try:
            import fcntl
        except ImportError:  # non-POSIX: single-process use only
            return
        if timeout_s is None:
            timeout_s = self.LOCK_TIMEOUT_S
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                fcntl.flock(fh.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
                return
            except OSError:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"journal {self.path} is locked by another live "
                        "process; two runs of the same config cannot share "
                        "a journal"
                    )
                time.sleep(0.1)

    def _open(self) -> None:
        # Lock BEFORE replaying: otherwise a concurrent process could
        # append between our read and our first write.
        self._fh = open(self.path, "ab")
        self._flock(self._fh)
        good_bytes, records = self._replay()
        if records is None:  # header mismatch / corrupt header: fresh file
            self._fh.close()  # releases the lock with the old inode
            self._rotate()
            self._fh = open(self.path, "ab")
            self._flock(self._fh)
            good_bytes, records = 0, {}
        self._records = records
        size = self._fh.tell()
        if good_bytes < size:
            # Torn tail from a mid-write crash (or injected corruption):
            # trim to the last good record and continue appending.
            self._fh.truncate(good_bytes)
            self._fh.seek(good_bytes)
        if not self._records:
            self._append(_HEADER_PHASE, {
                "journal_version": JOURNAL_VERSION,
                "config": self.config,
            })
        self.resumed_phases = [
            p for p in self._records if p != _HEADER_PHASE
        ]

    def _replay(self):
        """``(good_byte_count, {phase: record})`` from the existing file;
        ``records is None`` means the whole file is untrustworthy (missing
        or mismatched header) and must be rotated aside."""
        if not os.path.exists(self.path):
            return 0, {}
        records: dict[str, dict] = {}
        good = 0
        expect_i = 0
        try:
            with open(self.path, "rb") as f:
                for raw in f:
                    if not raw.endswith(b"\n"):
                        break  # torn final record
                    try:
                        # Any malformed-but-parseable shape (non-object
                        # line, a flipped byte landing in a key name,
                        # wrong field types) must TRIM here like a torn
                        # tail — never escape and wedge every future run
                        # of this config on an unreadable journal.
                        rec = json.loads(raw)
                        ok = (
                            isinstance(rec, dict)
                            and rec.get("i") == expect_i
                            and isinstance(rec.get("phase"), str)
                            and _crc(rec["i"], rec["phase"], rec["payload"])
                            == rec.get("crc")
                        )
                    except (ValueError, KeyError, TypeError):
                        break
                    if not ok:
                        break
                    if rec["phase"] == _HEADER_PHASE:
                        hdr = rec["payload"]
                        if (
                            not isinstance(hdr, dict)
                            or hdr.get("journal_version") != JOURNAL_VERSION
                            or hdr.get("config") != self.config
                        ):
                            self.invalidated = "config mismatch"
                            return 0, None
                    records[rec["phase"]] = rec
                    good += len(raw)
                    expect_i += 1
        except OSError:
            return 0, None
        if _HEADER_PHASE not in records and good:
            return 0, None
        if not records and os.path.getsize(self.path) > 0:
            # Zero valid records in a NON-EMPTY file: this is not a torn
            # tail — it is a foreign file at the journal path (the classic
            # case: a pre-journal-schema capture like the round-1..5
            # MULTICHIP_r0*.json driver outputs, which parse as JSON but
            # carry no record sequence).  Truncating it (the old torn-tail
            # path) would DESTROY evidence; rotate it aside instead and
            # start a fresh journal.
            self.invalidated = "foreign/pre-journal file"
            return 0, None
        return good, records

    def _rotate(self) -> None:
        """Move a stale/foreign journal aside (never delete: it is
        evidence) and start fresh."""
        if not os.path.exists(self.path):
            return
        n = 0
        while os.path.exists(f"{self.path}.stale.{n}"):
            n += 1
        os.replace(self.path, f"{self.path}.stale.{n}")

    def restart(self, reason: str) -> None:
        """Invalidate everything (e.g. graph-hash mismatch): rotate the
        file aside and begin a fresh journal for the same config."""
        if self._fh is not None:
            self._fh.close()
        self._rotate()
        self._records = {}
        self._arrays_cache = {}
        self.invalidated = reason
        self._fh = open(self.path, "ab")
        self._flock(self._fh)
        self._append(_HEADER_PHASE, {
            "journal_version": JOURNAL_VERSION,
            "config": self.config,
        })
        self.resumed_phases = []

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # --------------------------------------------------------------- writes --
    def _append(self, phase: str, payload: Any, arrays_name: str | None = None):
        i = max((r["i"] for r in self._records.values()), default=-1) + 1
        rec = {
            "i": i,
            "phase": phase,
            "t": time.time(),
            "crc": _crc(i, phase, payload),
            "payload": payload,
        }
        if arrays_name is not None:
            rec["arrays"] = arrays_name
        line = (_canon(rec) + "\n").encode()
        self._fh.write(line)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._records[phase] = rec

    def put(self, phase: str, payload: Any, *, arrays: dict | None = None) -> None:
        """Record phase completion durably (payload must be JSON-safe;
        ``arrays`` go to an atomic sidecar ``.npz``)."""
        arrays_name = None
        self._arrays_cache.pop(phase, None)
        if arrays:
            from ..utils.checkpoint import save_npz_atomic

            stem = os.path.basename(self.path).rsplit(".", 1)[0]
            safe = "".join(c if (c.isalnum() or c in "._-") else "_" for c in phase)
            arrays_name = f"{stem}_{safe}.npz"
            save_npz_atomic(
                os.path.join(os.path.dirname(self.path), arrays_name), **arrays
            )
        self._append(phase, payload, arrays_name)

    # ---------------------------------------------------------------- reads --
    def get(self, phase: str) -> Any | None:
        """Payload of a completed phase, or None.

        Sidecar integrity (ISSUE 14 satellite): a record whose sidecar
        ``.npz`` is DAMAGED (present but truncated/bit-flipped —
        ``load_npz_strict`` rejects it) ROTATES the whole journal aside
        and starts fresh: the index row is intact but the payload it
        vouches for is gone, and later phases that consumed those arrays
        (timed repeats measured against the reference mask, resume
        carries) can no longer be proven consistent — replaying them
        against a re-derived sidecar could blend two runs into one
        capture.  A MISSING sidecar file keeps the old semantics (the
        phase alone reads as not-completed and re-runs): absence is an
        incomplete write, not corruption.  Either way: corruption costs
        time, never correctness."""
        rec = self._records.get(phase)
        if rec is None:
            return None
        if rec.get("arrays") and self.load_arrays(phase) is None:
            sidecar = os.path.join(
                os.path.dirname(self.path), rec["arrays"]
            )
            if os.path.exists(sidecar):
                self.restart(f"corrupt sidecar for phase {phase!r}")
            return None
        return rec["payload"]

    def load_arrays(self, phase: str) -> dict | None:
        """The sidecar arrays of a completed phase (None if absent or
        unreadable).  The loaded dict is cached: ``get()`` validates a
        sidecar-bearing record by loading it, and the caller's own
        ``load_arrays`` must not pay the archive read twice."""
        if phase in self._arrays_cache:
            return self._arrays_cache[phase]
        rec = self._records.get(phase)
        if rec is None or not rec.get("arrays"):
            return None
        from ..utils.checkpoint import CheckpointError, load_npz_strict

        path = os.path.join(os.path.dirname(self.path), rec["arrays"])
        try:
            out = load_npz_strict(path)
        except (CheckpointError, OSError):
            out = None
        self._arrays_cache[phase] = out
        return out

    def phases(self) -> list[str]:
        return [p for p in self._records if p != _HEADER_PHASE]

    def __contains__(self, phase: str) -> bool:
        return self.get(phase) is not None
