"""Deadline-aware exponential backoff with jitter + error classification.

The device path fails two ways and they must not be treated the same:

  * **transient** — the axon tunnel drops a connection, a dispatch times
    out, the backend reports UNAVAILABLE/ABORTED mid-window.  Round 4's
    ledger shows the tunnel's bandwidth swinging by orders of magnitude
    within minutes; a failure in a bad window often succeeds seconds
    later.  These deserve a bounded retry with backoff before any
    degradation.
  * **permanent** — shape errors, lowering failures, OOM, plain bugs.
    Retrying reruns the same deterministic failure; these must fall
    through immediately (the serving layer degrades to the sequential
    oracle exactly once, the bench fails loudly).

:func:`default_classify` encodes that split; :func:`retry_call` is the
wrapper both layers share.  Backoff is capped exponential with
multiplicative jitter (so N clients retrying the same bad window do not
re-synchronize), and the whole loop is bounded by both an attempt count
and an optional wall-clock deadline — a serving tick with a 50 ms budget
left does not sleep 500 ms to find out.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable


class TransientError(RuntimeError):
    """Marker: always classified transient (tests, stubs, wrappers)."""


class PermanentError(RuntimeError):
    """Marker: always classified permanent."""


class RetryError(RuntimeError):
    """All attempts exhausted (or deadline passed); ``__cause__`` is the
    last underlying error and ``attempts`` the number made."""

    def __init__(self, msg: str, attempts: int):
        super().__init__(msg)
        self.attempts = attempts


#: Substrings that mark a transient device/transport failure.  Matched
#: case-insensitively against ``repr(exc)`` so gRPC-style status names and
#: plain-prose socket errors both hit.
TRANSIENT_MARKERS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "aborted",
    "cancelled",
    "connection reset",
    "connection refused",
    "broken pipe",
    "timed out",
    "timeout",
    "temporarily",
    "tunnel",
    "socket closed",
    "transient",
)


def default_classify(exc: BaseException) -> str:
    """``'transient'`` or ``'permanent'`` for one failure.

    Marker classes win; then Python's own transport/timeout exception
    types; then the :data:`TRANSIENT_MARKERS` message probe.  Everything
    unrecognized is permanent — an unknown failure repeated is two
    failures, not a recovery strategy."""
    if isinstance(exc, TransientError):
        return "transient"
    if isinstance(exc, PermanentError):
        return "permanent"
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError)):
        return "transient"
    if isinstance(exc, MemoryError):
        return "permanent"
    text = repr(exc).lower()
    if any(m in text for m in TRANSIENT_MARKERS):
        return "transient"
    return "permanent"


@dataclass(frozen=True)
class RetryPolicy:
    """Shape of one retry loop.  ``deadline_s`` is a per-call wall budget
    measured from the first attempt; callers with an external deadline
    (a request in a serving tick) pass the tighter of the two to
    :func:`retry_call` directly."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5  # delay *= uniform(1, 1 + jitter)
    deadline_s: float | None = None
    classify: Callable[[BaseException], str] = field(default=default_classify)

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry ``attempt`` (1-based)."""
        d = min(self.base_delay_s * self.multiplier ** (attempt - 1),
                self.max_delay_s)
        return d * rng.uniform(1.0, 1.0 + self.jitter)


def retry_call(
    fn: Callable,
    *,
    policy: RetryPolicy | None = None,
    deadline_s: float | None = None,
    on_retry: Callable[[int, BaseException, float], None] | None = None,
    describe: str = "",
    _rng: random.Random | None = None,
):
    """Call ``fn()`` with transient-failure retries.

    Permanent failures re-raise immediately and untouched.  Transient
    failures back off and retry until ``policy.max_attempts`` or the
    deadline (the tighter of ``policy.deadline_s`` and ``deadline_s``)
    runs out, then raise :class:`RetryError` from the last failure.
    ``on_retry(attempt, exc, delay)`` fires before each sleep — the hook
    the metrics counters hang off."""
    policy = policy or RetryPolicy()
    rng = _rng or random.Random()
    limits = [d for d in (policy.deadline_s, deadline_s) if d is not None]
    deadline = (time.monotonic() + min(limits)) if limits else None
    last: BaseException | None = None
    for attempt in range(1, max(1, policy.max_attempts) + 1):
        try:
            return fn()
        except BaseException as exc:
            if policy.classify(exc) != "transient":
                raise
            last = exc
            if attempt >= policy.max_attempts:
                break
            delay = policy.delay(attempt, rng)
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                delay = min(delay, remaining)
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            if delay > 0:
                time.sleep(delay)
    what = describe or getattr(fn, "__name__", "call")
    raise RetryError(
        f"{what}: transient failure persisted after {attempt} attempts: "
        f"{last!r}",
        attempt,
    ) from last
