"""Deadline-aware exponential backoff with jitter + error classification.

The device path fails two ways and they must not be treated the same:

  * **transient** — the axon tunnel drops a connection, a dispatch times
    out, the backend reports UNAVAILABLE/ABORTED mid-window.  Round 4's
    ledger shows the tunnel's bandwidth swinging by orders of magnitude
    within minutes; a failure in a bad window often succeeds seconds
    later.  These deserve a bounded retry with backoff before any
    degradation.
  * **permanent** — shape errors, lowering failures, OOM, plain bugs.
    Retrying reruns the same deterministic failure; these must fall
    through immediately (the serving layer degrades to the sequential
    oracle exactly once, the bench fails loudly).

:func:`default_classify` encodes that split; :func:`retry_call` is the
wrapper both layers share.  Backoff is capped exponential with
multiplicative jitter (so N clients retrying the same bad window do not
re-synchronize), and the whole loop is bounded by both an attempt count
and an optional wall-clock deadline — a serving tick with a 50 ms budget
left does not sleep 500 ms to find out.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable


class TransientError(RuntimeError):
    """Marker: always classified transient (tests, stubs, wrappers)."""


class PermanentError(RuntimeError):
    """Marker: always classified permanent."""


class RetryError(RuntimeError):
    """All attempts exhausted (or deadline passed); ``__cause__`` is the
    last underlying error and ``attempts`` the number made."""

    def __init__(self, msg: str, attempts: int):
        super().__init__(msg)
        self.attempts = attempts


#: Substrings that mark a transient device/transport failure.  Matched
#: case-insensitively against ``repr(exc)`` so gRPC-style status names and
#: plain-prose socket errors both hit.
TRANSIENT_MARKERS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "aborted",
    "cancelled",
    "connection reset",
    "connection refused",
    "broken pipe",
    "timed out",
    "timeout",
    "temporarily",
    "tunnel",
    "socket closed",
    "transient",
)


def default_classify(exc: BaseException) -> str:
    """``'transient'`` or ``'permanent'`` for one failure.

    Marker classes win; then Python's own transport/timeout exception
    types; then the :data:`TRANSIENT_MARKERS` message probe.  Everything
    unrecognized is permanent — an unknown failure repeated is two
    failures, not a recovery strategy."""
    if isinstance(exc, TransientError):
        return "transient"
    if isinstance(exc, PermanentError):
        return "permanent"
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError)):
        return "transient"
    if isinstance(exc, MemoryError):
        return "permanent"
    text = repr(exc).lower()
    if any(m in text for m in TRANSIENT_MARKERS):
        return "transient"
    return "permanent"


@dataclass(frozen=True)
class RetryPolicy:
    """Shape of one retry loop.  ``deadline_s`` is a per-call wall budget
    measured from the first attempt; callers with an external deadline
    (a request in a serving tick) pass the tighter of the two to
    :func:`retry_call` directly."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5  # delay *= uniform(1, 1 + jitter)
    deadline_s: float | None = None
    classify: Callable[[BaseException], str] = field(default=default_classify)

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry ``attempt`` (1-based)."""
        d = min(self.base_delay_s * self.multiplier ** (attempt - 1),
                self.max_delay_s)
        return d * rng.uniform(1.0, 1.0 + self.jitter)


def retry_call(
    fn: Callable,
    *,
    policy: RetryPolicy | None = None,
    deadline_s: float | None = None,
    on_retry: Callable[[int, BaseException, float], None] | None = None,
    describe: str = "",
    _rng: random.Random | None = None,
):
    """Call ``fn()`` with transient-failure retries.

    Permanent failures re-raise immediately and untouched.  Transient
    failures back off and retry until ``policy.max_attempts`` or the
    deadline (the tighter of ``policy.deadline_s`` and ``deadline_s``)
    runs out, then raise :class:`RetryError` from the last failure.
    ``on_retry(attempt, exc, delay)`` fires before each sleep — the hook
    the metrics counters hang off."""
    policy = policy or RetryPolicy()
    rng = _rng or random.Random()
    limits = [d for d in (policy.deadline_s, deadline_s) if d is not None]
    deadline = (time.monotonic() + min(limits)) if limits else None
    last: BaseException | None = None
    for attempt in range(1, max(1, policy.max_attempts) + 1):
        try:
            return fn()
        except BaseException as exc:
            if policy.classify(exc) != "transient":
                raise
            last = exc
            if attempt >= policy.max_attempts:
                break
            delay = policy.delay(attempt, rng)
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                delay = min(delay, remaining)
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            if delay > 0:
                time.sleep(delay)
    what = describe or getattr(fn, "__name__", "call")
    raise RetryError(
        f"{what}: transient failure persisted after {attempt} attempts: "
        f"{last!r}",
        attempt,
    ) from last


# --------------------------------------------------------- circuit breaker --

#: Breaker states.  ``closed`` = traffic flows; ``open`` = short-circuit
#: (callers serve their fallback path without touching the guarded
#: resource); ``half_open`` = the cooldown elapsed and exactly ONE canary
#: call is allowed through to probe recovery.
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class _Circuit:
    """Per-key breaker cell; all fields guarded by the owning breaker's
    lock (this is a plain struct, not a lock-owning class)."""

    __slots__ = ("state", "failures", "opened_at", "probing", "reason")

    def __init__(self):
        self.state = CLOSED
        self.failures = 0  # consecutive permanent failures while closed
        self.opened_at = 0.0
        self.probing = False  # half-open: the one canary is in flight
        self.reason = ""


class CircuitBreaker:
    """Keyed circuit breaker: the retry layer's complement.

    :func:`retry_call` handles the failure a bounded backoff can outlive;
    the breaker handles the failure that persists — after
    ``failure_threshold`` consecutive permanent failures for a key the
    circuit opens and :meth:`allow` answers False, so the caller serves
    its degraded path instead of burning a full retry loop (and a serving
    tick) on a resource that is known-bad.  After ``cooldown_s`` the next
    :meth:`allow` admits exactly one canary call (``half_open``); its
    success closes the circuit, its failure re-opens it for another
    cooldown.  :meth:`force_open` is the quarantine entry: a caller that
    PROVED the resource wrong (a failed integrity verdict) opens the
    circuit immediately, consecutive-failure count notwithstanding.

    Keys are arbitrary hashables (the serving layer uses
    ``(graph, epoch, engine, bucket)`` — one circuit per compiled
    executable).  ``on_transition(key, old, new, reason)`` fires OUTSIDE
    the lock for every state change — the metrics/span hook.
    Thread-safe; time comes from ``clock`` (injectable for tests).
    """

    def __init__(
        self,
        *,
        failure_threshold: int = 3,
        cooldown_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Callable[[tuple, str, str, str], None] | None = None,
    ):
        self.failure_threshold = max(1, int(failure_threshold))  # immutable after init
        self.cooldown_s = float(cooldown_s)  # immutable after init
        self._clock = clock  # immutable after init
        self._on_transition = on_transition  # immutable after init
        self._lock = threading.Lock()
        self._circuits: dict = {}  # guarded-by: _lock

    # bfs_tpu: holds _lock
    def _cell(self, key) -> _Circuit:
        cell = self._circuits.get(key)
        if cell is None:
            cell = self._circuits[key] = _Circuit()
        return cell

    # bfs_tpu: holds _lock
    def _set(self, cell: _Circuit, key, new: str, reason: str) -> list:
        old, cell.state, cell.reason = cell.state, new, reason
        return [(key, old, new, reason)] if old != new else []

    def _emit(self, transitions: list) -> None:
        if self._on_transition is not None:
            for key, old, new, reason in transitions:
                self._on_transition(key, old, new, reason)

    def state(self, key) -> str:
        """Effective state (``open`` reports ``half_open`` once the
        cooldown has elapsed, without mutating — :meth:`allow` is what
        admits the canary)."""
        with self._lock:
            cell = self._circuits.get(key)
            if cell is None:
                return CLOSED
            if (
                cell.state == OPEN
                and self._clock() - cell.opened_at >= self.cooldown_s
            ):
                return HALF_OPEN
            return cell.state

    def allow(self, key) -> bool:
        """True iff the caller may touch the guarded resource now.  In
        half-open, exactly one caller per probe window gets True (the
        canary); everyone else short-circuits until it resolves."""
        transitions: list = []
        with self._lock:
            cell = self._circuits.get(key)
            if cell is None or cell.state == CLOSED:
                return True
            now = self._clock()
            if cell.state == OPEN:
                if now - cell.opened_at < self.cooldown_s:
                    return False
                transitions = self._set(cell, key, HALF_OPEN, "cooldown elapsed")
                cell.probing = True
                allowed = True
            else:  # HALF_OPEN
                allowed = not cell.probing
                cell.probing = True
        self._emit(transitions)
        return allowed

    def record_success(self, key) -> None:
        """A guarded call succeeded: closed resets the failure streak,
        half-open closes the circuit (the canary came back healthy)."""
        with self._lock:
            cell = self._circuits.get(key)
            if cell is None:
                return
            cell.failures = 0
            cell.probing = False
            transitions = (
                self._set(cell, key, CLOSED, "canary succeeded")
                if cell.state != CLOSED
                else []
            )
        self._emit(transitions)

    def record_failure(self, key, reason: str = "") -> None:
        """A guarded call failed permanently: half-open re-opens (the
        canary failed), closed opens after ``failure_threshold``
        consecutive failures."""
        with self._lock:
            cell = self._cell(key)
            cell.probing = False
            cell.failures += 1
            transitions = []
            if cell.state == HALF_OPEN:
                cell.opened_at = self._clock()
                transitions = self._set(cell, key, OPEN, "canary failed")
            elif cell.state == CLOSED and cell.failures >= self.failure_threshold:
                cell.opened_at = self._clock()
                transitions = self._set(
                    cell, key, OPEN,
                    reason or f"{cell.failures} consecutive failures",
                )
        self._emit(transitions)

    def force_open(self, key, reason: str = "quarantined") -> None:
        """Quarantine: open the circuit NOW regardless of the failure
        count (e.g. a failed integrity verdict — one provably wrong
        answer outweighs any streak of plausible ones)."""
        with self._lock:
            cell = self._cell(key)
            cell.probing = False
            cell.opened_at = self._clock()
            transitions = self._set(cell, key, OPEN, reason)
        self._emit(transitions)

    def forget(self, match: Callable[[tuple], bool]) -> int:
        """Drop every circuit whose key satisfies ``match`` and return the
        count.  The retirement hook: per-key cells are created on demand
        and otherwise live forever, so a caller that keys circuits by a
        finite-lifetime resource (the serving layer's graph epochs) must
        prune when the resource dies or the dict — and every
        :meth:`snapshot` serialized from it — grows with each swap."""
        with self._lock:
            dead = [k for k in self._circuits if match(k)]
            for k in dead:
                del self._circuits[k]
        return len(dead)

    def snapshot(self) -> dict:
        """JSON-ready per-key view (state/failures/reason/opened-for
        seconds) for reports and dashboards."""
        with self._lock:
            now = self._clock()
            return {
                "/".join(str(p) for p in (key if isinstance(key, tuple) else (key,))): {
                    "state": cell.state,
                    "failures": cell.failures,
                    "reason": cell.reason,
                    "open_for_s": (
                        round(now - cell.opened_at, 3)
                        if cell.state == OPEN
                        else 0.0
                    ),
                }
                for key, cell in self._circuits.items()
            }
