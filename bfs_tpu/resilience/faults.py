"""Phase-boundary fault injection (``BFS_TPU_FAULT``) + corruption injectors.

Instrumented code calls :func:`fault_point(name)` at every phase boundary
(in the bench: right AFTER the phase's journal record lands, which is what
"boundary" means for resume semantics — the phase is durably complete, the
next one has not started).  The hook is inert unless ``BFS_TPU_FAULT`` is
set:

    BFS_TPU_FAULT=kill:<phase>[:nth]    SIGKILL the process (no cleanup,
                                        no atexit, no signal handlers —
                                        the honest crash)
    BFS_TPU_FAULT=raise:<phase>[:nth]   raise FaultInjected (tests the
                                        exception path / SIGTERM-ish exits)
    BFS_TPU_FAULT=phase:<phase>[:nth]   alias for kill: (the spelling the
                                        issue tracker uses)
    BFS_TPU_FAULT=delay:<phase>[:secs]  sleep ``secs`` (default 1.0) at
                                        EVERY arrival — the hung-call
                                        shape the serve watchdog exists
                                        for (a wedged XLA dispatch looks
                                        exactly like a sleep)

``nth`` (default 1) selects the nth arrival at that phase — so
``kill:repeat:2`` dies after the second timed repeat.  Per-item boundaries
are named ``family:<item>`` (``repeat:0``, ``verify:17``) and a spec phase
matches either the exact boundary name or the family prefix, so
``kill:verify:3`` means "the third verification boundary" without the
caller knowing which root id that is.  ``delay`` takes SECONDS (a float)
where the others take ``nth``, and fires on every matching arrival: a
degraded transport stays degraded until the operator (or the chaos
schedule) clears the env var.

The serving path exposes two boundaries of its own: ``serve.batch`` fires
inside every watchdog-guarded device batch call (so ``delay:serve.batch:2``
wedges the tick and ``raise:serve.batch`` fails it permanently), and
``serve.verify`` fires inside the sampled integrity check (where a
``raise`` is interpreted as a FAILED verdict — the injected-corruption
shape that exercises executable quarantine).

The SUPERSTEP family (ISSUE 14): segmented traversals
(resilience/superstep_ckpt.py) mark ``superstep:<level>`` right after
each segment's checkpoint epoch lands durably, so
``kill:superstep:<n>`` / ``raise:superstep:<n>`` dies at the n-th
segment boundary of a mid-flight traversal — the chaos-traversal
driver's kill point (``tools/chaos_run.py --mode traversal``).  The
serve twin is ``serve.segment``, fired between segments of a
checkpointing batch tick (``delay:serve.segment:s`` is a wedged
mid-traversal dispatch the hung-call resume loop must survive).

The corruption injectors simulate the non-crash failure modes the journal
and checkpoint layers must reject: truncation (a torn write) and byte
flips (bit rot / a torn page).  They are plain file edits so tests and
``tools/chaos_run.py`` can damage artifacts without knowing formats.
"""

from __future__ import annotations

import os
import signal
import threading

from .. import knobs


class FaultInjected(RuntimeError):
    """Raised by :func:`fault_point` under ``BFS_TPU_FAULT=raise:...``."""


_lock = threading.Lock()
_counts: dict[str, int] = {}


def reset() -> None:
    """Forget arrival counts (tests)."""
    with _lock:
        _counts.clear()


def fault_spec(env: str | None = None) -> tuple[str, str, float] | None:
    """Parse ``BFS_TPU_FAULT`` into ``(action, phase, arg)`` or None.

    ``action`` is ``'kill'``, ``'raise'`` or ``'delay'`` (the documented
    ``phase:`` prefix is an alias for ``kill``); ``arg`` is the 1-based
    nth-arrival count for kill/raise and the sleep SECONDS for delay."""
    spec = env if env is not None else knobs.get("BFS_TPU_FAULT")
    spec = spec.strip()
    if not spec:
        return None
    action, _, rest = spec.partition(":")
    if action == "phase":
        action = "kill"
    if action not in ("kill", "raise", "delay") or not rest:
        raise ValueError(
            f"bad BFS_TPU_FAULT {spec!r}; use "
            "kill:<phase>[:nth] | raise:<phase>[:nth] | phase:<phase>[:nth]"
            " | delay:<phase>[:seconds]"
        )
    head, _, tail = rest.rpartition(":")
    if action == "delay":
        phase, seconds = rest, 1.0
        # A positive trailing float is the sleep; anything else (including
        # "0", mirroring the nth rule below) is part of the phase NAME.
        try:
            if head and float(tail) > 0:
                phase, seconds = head, float(tail)
        except ValueError:
            pass
        return action, phase, seconds
    phase, nth = rest, 1
    # nth is 1-based; a trailing 0 (or any non-positive integer) is part
    # of the phase NAME, not a count — so ``kill:repeat:0`` targets the
    # exact boundary "repeat:0" (first arrival) rather than parsing as an
    # nth=0 that could never fire.
    if head and tail.isdigit() and int(tail) >= 1:
        phase, nth = head, int(tail)
    return action, phase, nth


def fault_point(name: str) -> None:
    """Mark a phase boundary; dies here iff ``BFS_TPU_FAULT`` targets the
    nth arrival at ``name``.  Free when the env var is unset."""
    spec = fault_spec()
    if spec is None:
        return
    action, phase, nth = spec
    if name != phase and not name.startswith(phase + ":"):
        return
    if action == "delay":
        # Every matching arrival sleeps: a degraded transport does not
        # recover after one slow call, and the serve watchdog must see a
        # REPEATABLY wedged boundary to prove its breaker interplay.
        import time

        time.sleep(nth)  # nth carries seconds for delay specs
        return
    with _lock:
        _counts[phase] = _counts.get(phase, 0) + 1
        hit = _counts[phase] == nth
    if not hit:
        return
    if action == "kill":
        # The driver-timeout shape: instant death, nothing flushed beyond
        # what is already durable.  stderr note first so a captured tail
        # shows the kill was injected, not organic.
        import sys

        print(
            f"[fault] SIGKILL at phase boundary {name!r}",
            file=sys.stderr, flush=True,
        )
        os.kill(os.getpid(), signal.SIGKILL)
    raise FaultInjected(f"injected fault at phase boundary {name!r}")


def corrupt_file(path: str, *, mode: str = "truncate", at: int | None = None) -> None:
    """Damage ``path`` in place: ``mode='truncate'`` cuts the file to
    ``at`` bytes (default: half), ``mode='flip'`` XOR-flips the byte at
    ``at`` (default: middle).  Used by tests to prove the journal /
    checkpoint loaders reject damage instead of resuming from it."""
    size = os.path.getsize(path)
    if mode == "truncate":
        cut = size // 2 if at is None else at
        with open(path, "r+b") as f:
            f.truncate(cut)
        return
    if mode == "flip":
        pos = size // 2 if at is None else at
        with open(path, "r+b") as f:
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
        return
    raise ValueError(f"unknown corruption mode {mode!r}")
