"""Superstep-granular checkpoint/restore: mid-traversal resume (ISSUE 14).

The bench journal (resilience/journal.py) resumes at *phase* granularity:
a kill 40 supersteps into a deep traversal loses every superstep already
executed, because the whole loop is one fused XLA program whose carry
never leaves the device.  This module cuts the traversal at the natural
consistency point distributed BFS already synchronizes on — the
per-superstep frontier exchange (Compression-and-Sieve, arXiv 1208.5542;
the same boundary PR 11's exchange protocol rides) — by running the fused
programs as **bounded segments of K supersteps**:

    carry = init(source)                      # or restore(epoch N)
    while carry.changed and carry.level < cap:
        carry = segment_program(carry, seg_end=level + K, ...)
        snapshot(carry)                       # atomic .npz epoch
        fault_point(f"superstep:{level}")     # the chaos boundary

Segment programs are NEW compiled artifacts (lint-registered next to the
fused ones); with ``BFS_TPU_CKPT=off`` (the default) every caller runs
today's single-segment fused programs byte-for-byte — the off arm's
IR/HLO fingerprints are unchanged.

Bit-identity contract: a segment boundary changes WHERE the loop pauses,
never what it computes — each superstep body is the same compiled math
as the fused program's, dispatched in the same order (the direction
hysteresis state ``(mu, prev)``, the telemetry accumulators and the
exchange-arm history all RIDE THE CARRY and therefore the checkpoint),
so a resumed run reproduces the killed run's final dist/parent, its
``details.direction_schedule`` and its exchange-arm sequence exactly.
``tools/chaos_run.py --mode traversal`` is the acceptance harness.

Checkpoint interval: ``BFS_TPU_CKPT=every:<k>`` forces K supersteps per
segment; ``auto`` sizes it Young/Daly-style from the measured superstep
seconds and snapshot seconds (:func:`daly_interval` — the classic
``T_opt = sqrt(2 * delta * MTBF)`` with ``BFS_TPU_CKPT_MTBF_S`` as the
failure-rate prior), re-derived after every segment.  The measured
overhead ships in every capture as ``details.superstep_ckpt``.

Durability: epochs are written through
:func:`bfs_tpu.utils.checkpoint.save_npz_atomic` into the journal's
sidecar directory, content-keyed by the run config exactly like every
other capture (``ckpt_<blake2b(config)>.epoch<N>.npz``); loads go
through ``load_npz_strict`` — a truncated or bit-flipped epoch is
SKIPPED (counted, warned) and the loader falls back to the previous
epoch, and a run with every epoch damaged falls back to a clean fresh
traversal (counters name the fallback; corruption costs time, never
correctness).  Sharded runs write per-shard epoch shards at the exchange
boundary plus one meta file; an epoch is complete only when the meta AND
every shard validate, so losing one shard's file falls back to the last
complete epoch — and because epochs are host arrays, the surviving epoch
re-admits onto a freshly built mesh (the shard-loss recovery path the
chaos driver exercises by corrupting a single shard file).
"""

from __future__ import annotations

import glob
import logging
import math
import os
import time
from dataclasses import dataclass

import numpy as np

# utils.checkpoint (and with it jax) is imported lazily inside the store
# methods — journal.py's idiom — so resolve_ckpt()/CkptConfig stay
# importable in no-jax contexts (the lint stub, config-only callers).
from .. import knobs
from .faults import fault_point
from .journal import config_key

logger = logging.getLogger(__name__)

#: The fault-family name of the segment boundary (resilience/faults.py):
#: boundaries are ``superstep:<level>``, so ``BFS_TPU_FAULT=
#: kill:superstep:<n>`` kills at the n-th segment boundary and
#: ``raise:superstep:<n>`` raises there (family matching — the caller
#: never needs to know which level the n-th boundary lands on).
TRAVERSAL_BOUNDARY = "superstep"

CKPT_MODES = ("off", "every", "auto")

#: Default segment length the auto arm starts from (before any
#: measurement exists) and the forced arm falls back to on a bare
#: ``every:``.
DEFAULT_K0 = 8

#: Young/Daly failure-rate prior (seconds).  There is no failure
#: telemetry to estimate a real MTBF from inside one process; this knob
#: is the operator's statement of how often the environment kills runs
#: (driver timeouts, preemptions).
DEFAULT_MTBF_S = 600.0


@dataclass(frozen=True)
class CkptConfig:
    """Resolved checkpoint policy — hashable, like DirectionConfig /
    ExchangeConfig, so it can sit in journal configs and cache keys."""

    mode: str = "off"
    k: int = DEFAULT_K0

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    def key(self) -> tuple:
        return (self.mode, int(self.k))


def resolve_ckpt(spec: str | None = None) -> CkptConfig:
    """Parse ``BFS_TPU_CKPT`` (or an explicit ``spec``, which wins):
    ``off`` | ``every:<k>`` | ``auto``.  Unknown modes and non-positive
    intervals raise — silently clamping a typo'd knob would change what a
    capture measured (the resolve_direction contract)."""
    if spec is None:
        spec = knobs.get("BFS_TPU_CKPT")
    spec = spec.strip()
    mode, _, arg = spec.partition(":")
    if mode not in CKPT_MODES:
        raise ValueError(
            f"unknown BFS_TPU_CKPT {spec!r}; use off | every:<k> | auto"
        )
    if mode == "every":
        k = int(arg) if arg else DEFAULT_K0
        if k < 1:
            raise ValueError(
                f"BFS_TPU_CKPT=every:<k> needs k >= 1 (got {k})"
            )
        return CkptConfig(mode="every", k=k)
    if arg:
        raise ValueError(
            f"BFS_TPU_CKPT {spec!r}: only 'every' takes an argument"
        )
    return CkptConfig(mode=mode)


def daly_interval(
    superstep_s: float, snapshot_s: float, mtbf_s: float = DEFAULT_MTBF_S
) -> int:
    """Young/Daly checkpoint interval in SUPERSTEPS:
    ``T_opt = sqrt(2 * delta * M)`` seconds between checkpoints (delta =
    one snapshot's cost, M = mean time between failures), divided by the
    measured per-superstep seconds and clamped to [1, 4096].  Monotone in
    the ratio snapshot-cost : superstep-cost — cheap snapshots or slow
    supersteps checkpoint often, the reverse rarely."""
    superstep_s = max(float(superstep_s), 1e-9)
    t_opt = math.sqrt(2.0 * max(float(snapshot_s), 1e-6) * float(mtbf_s))
    return max(1, min(4096, int(round(t_opt / superstep_s))))


class SuperstepCheckpointer:
    """Epoch store + interval policy for one segmented traversal.

    ``config`` is the run identity (graph hash / engine statics /
    direction / packed / source ...): the file stem is
    ``ckpt_<blake2b(config)>`` so two different run configurations can
    never feed each other's epochs — content-keying, the way the journal
    and the layout cache key everything else.  ``shards`` > 1 switches to
    per-shard epoch files (meta + one file per shard; an epoch is
    complete only when all validate).

    A disabled checkpointer (mode ``off``) is a no-op store: callers may
    still drive the segmented loop (tests do), nothing touches disk.
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        config: dict,
        *,
        cfg: CkptConfig | None = None,
        shards: int = 1,
        retain: int = 2,
        mtbf_s: float | None = None,
    ):
        self.cfg = cfg if cfg is not None else resolve_ckpt()
        self.directory = os.fspath(directory)
        self.config = dict(config)
        self.key = config_key(self.config)
        self.stem = os.path.join(self.directory, f"ckpt_{self.key}")
        self.shards = int(shards)
        self.retain = max(2, int(retain))
        self.mtbf_s = (
            float(mtbf_s)
            if mtbf_s is not None
            else knobs.get("BFS_TPU_CKPT_MTBF_S")
        )
        self._k = self.cfg.k if self.cfg.mode == "every" else DEFAULT_K0
        # Measured economics (medians are overkill: both costs are
        # smoothed with a simple running mean — the interval only needs
        # the right order of magnitude).
        self._superstep_s: float | None = None
        self._snapshot_s: float | None = None
        self.counters = {
            "epochs_written": 0,
            "segments": 0,
            "epochs_corrupt_skipped": 0,
            "fresh_fallbacks": 0,
        }
        self.snapshot_bytes = 0
        self.snapshot_seconds = 0.0
        self.resumed_from_epoch: int | None = None
        if self.cfg.enabled:
            os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------ interval --
    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    def interval(self) -> int:
        """Current segment length in supersteps."""
        return self._k

    def note_segment(self, supersteps: int, seg_seconds: float) -> None:
        """Feed one segment's measurement; in ``auto`` mode re-derive the
        Young/Daly interval from the running means."""
        self.counters["segments"] += 1
        if supersteps > 0 and seg_seconds > 0:
            per = seg_seconds / supersteps
            self._superstep_s = (
                per
                if self._superstep_s is None
                else 0.5 * (self._superstep_s + per)
            )
        if (
            self.cfg.mode == "auto"
            and self._superstep_s is not None
            and self._snapshot_s is not None
        ):
            self._k = daly_interval(
                self._superstep_s, self._snapshot_s, self.mtbf_s
            )

    # -------------------------------------------------------------- naming --
    def _epoch_path(self, superstep: int, shard: int | None = None) -> str:
        base = f"{self.stem}.epoch{int(superstep):06d}"
        if shard is None:
            return f"{base}.npz"
        return f"{base}.shard{int(shard)}.npz"

    def _meta_path(self, superstep: int) -> str:
        return f"{self.stem}.epoch{int(superstep):06d}.meta.npz"

    def epochs(self) -> list[int]:
        """Superstep numbers of every epoch with at least one file on
        disk, ascending."""
        found = set()
        for path in glob.glob(f"{self.stem}.epoch*.npz"):
            tail = os.path.basename(path).split(".epoch", 1)[1]
            digits = tail.split(".", 1)[0]
            if digits.isdigit():
                found.add(int(digits))
        return sorted(found)

    # --------------------------------------------------------------- writes --
    def save_epoch(
        self,
        superstep: int,
        arrays: dict[str, np.ndarray],
        shard_arrays: list[dict[str, np.ndarray]] | None = None,
    ) -> None:
        """Write one durable epoch (atomic per file), prune past the
        retention window, then mark the ``superstep:<n>`` fault boundary
        — the kill point lands AFTER the epoch is durable, which is what
        "boundary" means for resume semantics (same contract as the
        bench's journal boundaries)."""
        if not self.cfg.enabled:
            fault_point(f"{TRAVERSAL_BOUNDARY}:{int(superstep)}")
            return
        from ..utils.checkpoint import save_npz_atomic

        t0 = time.perf_counter()
        meta = {
            f"meta_{k}": np.asarray(v)
            for k, v in (
                ("config", self.key),
                ("superstep", int(superstep)),
                ("shards", self.shards),
            )
        }
        nbytes = 0
        if shard_arrays is None:
            payload = {**arrays, **meta}
            save_npz_atomic(self._epoch_path(superstep), **payload)
            nbytes += sum(int(np.asarray(a).nbytes) for a in arrays.values())
        else:
            if len(shard_arrays) != self.shards:
                raise ValueError(
                    f"expected {self.shards} shard payloads, got "
                    f"{len(shard_arrays)}"
                )
            # Meta file LAST: its presence marks "every shard landed", so
            # a kill mid-epoch can never leave a meta pointing at missing
            # shards (shard files without a meta are an incomplete epoch
            # the loader skips).
            for s, sa in enumerate(shard_arrays):
                save_npz_atomic(self._epoch_path(superstep, s), **sa, **meta)
                nbytes += sum(
                    int(np.asarray(a).nbytes) for a in sa.values()
                )
            save_npz_atomic(self._meta_path(superstep), **arrays, **meta)
            nbytes += sum(int(np.asarray(a).nbytes) for a in arrays.values())
        dt = time.perf_counter() - t0
        self.counters["epochs_written"] += 1
        self.snapshot_bytes = nbytes
        self.snapshot_seconds += dt
        self._snapshot_s = (
            dt if self._snapshot_s is None else 0.5 * (self._snapshot_s + dt)
        )
        self._prune()
        fault_point(f"{TRAVERSAL_BOUNDARY}:{int(superstep)}")

    def _epoch_files(self, ep: int) -> list[str]:
        """Every file a given epoch may own.  Exact names, not a bare
        ``epoch<N>*`` glob — on a >999999-level traversal the 6-digit
        padding widens and a prefix glob for epoch 100000 would also
        match epoch 1000000's files."""
        return [
            self._epoch_path(ep),
            self._meta_path(ep),
            *glob.glob(f"{self.stem}.epoch{int(ep):06d}.shard*.npz"),
        ]

    def _prune(self) -> None:
        for ep in self.epochs()[: -self.retain]:
            for path in self._epoch_files(ep):
                try:
                    os.remove(path)
                except OSError:
                    pass

    def clear(self) -> None:
        """Delete every epoch (the traversal finished — its checkpoints
        are dead weight, and a later run of the same config must start
        fresh, not resume a finished carry)."""
        for path in glob.glob(f"{self.stem}.epoch*.npz"):
            try:
                os.remove(path)
            except OSError:
                pass

    # ---------------------------------------------------------------- reads --
    def _load_one(self, path: str) -> dict | None:
        from ..utils.checkpoint import CheckpointError, load_npz_strict

        try:
            z = load_npz_strict(path)
        except (CheckpointError, FileNotFoundError, OSError) as exc:
            logger.warning("skipping damaged checkpoint %s (%r)", path, exc)
            self.counters["epochs_corrupt_skipped"] += 1
            return None
        cfg = z.get("meta_config")
        if cfg is None or str(cfg) != self.key:
            logger.warning(
                "skipping %s: written by a different run config", path
            )
            self.counters["epochs_corrupt_skipped"] += 1
            return None
        return z

    def load_latest(self):
        """``(superstep, arrays, shard_arrays)`` from the newest COMPLETE
        valid epoch, or None (fresh traversal).  Damaged / foreign /
        incomplete epochs are skipped newest-first — the corruption
        matrix contract: a flipped byte in the newest epoch falls back to
        the previous one, all epochs damaged falls back to a clean fresh
        run (``fresh_fallbacks`` counts it — corruption is visible,
        never silent)."""
        if not self.cfg.enabled:
            return None
        had_any = False
        for ep in reversed(self.epochs()):
            had_any = True
            if self.shards == 1:
                z = self._load_one(self._epoch_path(ep))
                if z is None:
                    continue
                arrays = {
                    k: v for k, v in z.items() if not k.startswith("meta_")
                }
                self.resumed_from_epoch = ep
                return ep, arrays, None
            meta_path = self._meta_path(ep)
            if not os.path.exists(meta_path):
                # The NORMAL mid-epoch kill shape: meta is written LAST,
                # so shard files without one are an incomplete epoch —
                # expected wreckage, not corruption (no counter).
                logger.info(
                    "skipping incomplete epoch %d (no meta file)", ep
                )
                continue
            meta = self._load_one(meta_path)
            if meta is None:
                continue
            if int(meta.get("meta_shards", -1)) != self.shards:
                logger.warning(
                    "skipping epoch %d: shard count mismatch", ep
                )
                self.counters["epochs_corrupt_skipped"] += 1
                continue
            shard_arrays = []
            ok = True
            for s in range(self.shards):
                z = self._load_one(self._epoch_path(ep, s))
                if z is None:
                    ok = False  # shard loss: this epoch is incomplete
                    break
                shard_arrays.append({
                    k: v for k, v in z.items() if not k.startswith("meta_")
                })
            if not ok:
                continue
            arrays = {
                k: v for k, v in meta.items() if not k.startswith("meta_")
            }
            self.resumed_from_epoch = ep
            return ep, arrays, shard_arrays
        if had_any:
            self.counters["fresh_fallbacks"] += 1
        return None

    # --------------------------------------------------------------- report --
    def report(self) -> dict:
        """JSON-ready ``details.superstep_ckpt``: the policy, the
        measured economics, and the fallback counters — every capture
        carries the cost, none hides it."""
        return {
            "mode": self.cfg.mode,
            "interval": int(self._k),
            "shards": self.shards,
            "superstep_seconds": self._superstep_s,
            "snapshot_seconds_mean": self._snapshot_s,
            "snapshot_seconds_total": self.snapshot_seconds,
            "snapshot_bytes": int(self.snapshot_bytes),
            "mtbf_s": self.mtbf_s,
            "resumed_from_epoch": self.resumed_from_epoch,
            **self.counters,
        }


# ---------------------------------------------------------------------------
# Host drivers: the segmented loop over each engine family's segment
# program.  Each drives DEVICE state through bounded segments, snapshots
# the full carry per segment, and restores it on resume.  The engine- and
# mesh-specific segment programs live next to their fused twins
# (models/bfs.py, models/multisource.py, parallel/sharded.py).
# ---------------------------------------------------------------------------

def restore_arrays(ckpt: SuperstepCheckpointer, packed: bool,
                   require: tuple = (), require_shards: tuple = ()):
    """THE shared restore gate every disk-backed segmented driver uses:
    ``(meta/carry arrays, shard arrays)`` of the newest valid epoch iff
    it matches the requested carry flavor AND carries every key in
    ``require``, else ``(None, None)`` — the flavor/key checks live in
    ONE place so the relay / multisource / sharded drivers cannot
    diverge on them.  The key check matters because the config key does
    not encode every carry-shaping flag (telemetry on/off): an epoch
    from a plainer drive of the same config must fall back to a fresh
    traversal, never KeyError mid-restore (the "corruption costs time,
    never correctness" contract).  ``resumed_from_epoch`` is reset on
    ENTRY and only re-set by a successful load, so the report always
    describes the flavor that actually produced the result — the
    packed-truncation fallback (clear + fresh unpacked re-run) must not
    keep advertising the packed arm's resume (the honesty signal the
    chaos driver's silent-fresh-restart check relies on)."""
    ckpt.resumed_from_epoch = None
    found = ckpt.load_latest()
    if found is None:
        return None, None
    _ep, arrays, shard_arrays = found
    missing = [k for k in require if k not in arrays]
    for sa in shard_arrays or ():
        missing += [k for k in require_shards if k not in sa]
    if (
        int(np.asarray(arrays.get("packed_flag", -1))) != int(packed)
        or missing
    ):
        if missing:
            logger.warning(
                "checkpoint epoch lacks carry keys %s; fresh traversal",
                missing,
            )
        ckpt.resumed_from_epoch = None
        return None, None
    return arrays, shard_arrays

def run_multi_segmented(
    graph,
    sources,
    *,
    ckpt: SuperstepCheckpointer,
    engine: str = "push",
    max_levels: int | None = None,
    block: int = 1024,
):
    """Segmented batched multi-source BFS (push/pull engines): the
    checkpointed twin of :func:`bfs_tpu.models.multisource.bfs_multi`,
    bit-identical results for any segmentation.  Returns a
    MultiBfsResult."""
    import jax
    import jax.numpy as jnp

    from ..graph.csr import build_device_graph
    from ..graph.ell import build_pull_graph, device_ell
    from ..models.bfs import check_sources
    from ..models.multisource import (
        MultiBfsResult,
        _bfs_multi_pull_segment,
        _bfs_multi_segment,
        multi_segment_finish,
        multi_segment_init,
    )
    from ..ops.packed import (
        packed_cap,
        packed_parent_fits,
        packed_truncated,
        resolve_packed,
    )

    sources = np.atleast_1d(np.asarray(sources, dtype=np.int32))
    if engine == "pull":
        pg = build_pull_graph(graph)
        v = pg.num_vertices
        ell0, folds = device_ell(pg)

        def seg(state, seg_end, packed):
            return _bfs_multi_pull_segment(
                ell0, folds, state, seg_end, v, limit, packed
            )
    elif engine == "push":
        dg = build_device_graph(graph, block=block)
        v = dg.num_vertices
        src_t, dst_t = jnp.asarray(dg.src), jnp.asarray(dg.dst)

        def seg(state, seg_end, packed):
            return _bfs_multi_segment(
                src_t, dst_t, state, seg_end, v, limit, packed
            )
    else:
        raise ValueError(f"unknown engine {engine!r}; use 'push' or 'pull'")
    check_sources(v, sources)
    limit = int(max_levels) if max_levels is not None else v

    def run_flavor(packed: bool):
        from ..ops.relax import PackedBfsState
        from ..ops.relax import BfsState as _BfsState

        cap = packed_cap(limit) if packed else limit
        cls = PackedBfsState if packed else _BfsState
        arrays, _shards = restore_arrays(ckpt, packed, require=cls._fields)
        state = multi_segment_init(v, sources, packed, restore=arrays)
        level, changed = jax.device_get((state.level, state.changed))
        while bool(changed) and int(level) < cap:
            k = ckpt.interval()
            seg_end = jnp.int32(min(int(level) + k, cap))
            t0 = time.perf_counter()
            state = seg(state, seg_end, packed)
            new_level, changed = jax.device_get(
                (state.level, state.changed)
            )
            seg_s = time.perf_counter() - t0
            # Disabled store: mark the boundary, skip the O(S*V) pull.
            snap = {}
            if ckpt.enabled:
                snap = {
                    k2: np.asarray(val)
                    for k2, val in jax.device_get(state)._asdict().items()
                }
                snap["packed_flag"] = np.int32(packed)
            ckpt.save_epoch(int(new_level), snap)
            ckpt.note_segment(int(new_level) - int(level), seg_s)
            level = new_level
        return multi_segment_finish(state, packed), int(level), bool(changed)

    packed = resolve_packed(packed_parent_fits(v))
    state, level, changed = run_flavor(packed)
    if packed and packed_truncated(changed, level, limit):
        ckpt.clear()  # packed epochs cannot feed the unpacked re-run
        state, level, changed = run_flavor(False)
    ckpt.clear()
    return MultiBfsResult(
        sources=sources,
        dist=np.asarray(state.dist[:, :v]),
        parent=np.asarray(state.parent[:, :v]),
        num_levels=int(level),
    )


# ---------------------------------------------------------------------------
# CLI runner: the chaos-traversal subject process.
#
#   python -m bfs_tpu.resilience.superstep_ckpt \
#       --config relay|multi|sharded|grid --ckpt-dir D --out result.json
#
# Runs one traversal segmented-with-checkpoints and writes a result
# document with dist/parent content hashes, the direction schedule, the
# exchange-arm sequence (sharded) and the checkpoint report.  Under
# ``BFS_TPU_FAULT=kill:superstep:<n>`` it dies at the n-th segment
# boundary; re-invoking with the same --ckpt-dir resumes from the newest
# valid epoch.  tools/chaos_run.py --mode traversal drives this and
# diffs resumed vs golden.
# ---------------------------------------------------------------------------

def _hash(a: np.ndarray) -> str:
    import hashlib

    return hashlib.blake2b(
        np.ascontiguousarray(a).tobytes(), digest_size=16
    ).hexdigest()


def _runner_main(argv=None) -> int:
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", required=True,
                    choices=("relay", "multi", "sharded", "grid", "stream"))
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--scale", type=int, default=8)
    ap.add_argument("--edge-factor", type=int, default=4)
    ap.add_argument("--seed", type=int, default=3)
    # Default 3, not 0: R-MAT leaves many low-id vertices in tiny
    # components at toy scale, and a 1-level traversal has no interior
    # boundary to chaos.
    ap.add_argument("--source", type=int, default=3)
    ap.add_argument("--interval", type=int, default=2,
                    help="forced supersteps per segment (every:<k>)")
    ap.add_argument("--shards", type=int, default=8,
                    help="sharded config: mesh size over the graph axis")
    ap.add_argument("--mesh", default="2x4",
                    help="grid config: 'rxc' mesh spec over (row, col)")
    args = ap.parse_args(argv)

    # Virtual multi-device CPU platform for the sharded config, set
    # before jax initializes (same contract as tests/conftest.py and the
    # analysis CLI).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    from ..graph.generators import rmat_graph

    graph = rmat_graph(args.scale, args.edge_factor, seed=args.seed)
    cfg = CkptConfig(mode="every", k=args.interval)
    base_config = {
        "runner": args.config, "scale": args.scale,
        "edge_factor": args.edge_factor, "seed": args.seed,
        "source": args.source, "interval": args.interval,
    }
    doc: dict = {"config": args.config}

    if args.config == "relay":
        from ..models.bfs import RelayEngine

        eng = RelayEngine(graph, sparse_hybrid=True, direction="auto")
        ckpt = SuperstepCheckpointer(args.ckpt_dir, base_config, cfg=cfg)
        result, curve = eng.run_segmented(
            args.source, ckpt=ckpt, telemetry=True
        )
        doc.update(
            dist_hash=_hash(result.dist), parent_hash=_hash(result.parent),
            num_levels=result.num_levels,
            direction_schedule=curve["direction_schedule"],
        )
    elif args.config == "stream":
        # The host-paged mxu arm (ISSUE 18): adjacency superblocks
        # stream through the budgeted HBM cache; a kill loses the cache
        # (it holds derived content only) but NOT the carry — resume from
        # the epoch must replay dist/parent AND the direction schedule
        # bit-identically with a cold cache.  The budget is pinned to one
        # max-size superblock so even the toy graph exercises real
        # eviction under chaos.
        from ..models.bfs import RelayEngine
        from ..stream import HostTileStore

        eng = RelayEngine(
            graph, sparse_hybrid=True, direction="auto", expansion="mxu",
            tiles_mode="stream",
        )
        store = HostTileStore(eng.adj_tiles)
        budget = max(
            store.sb_bytes(g) for g in range(store.num_superblocks)
        )
        ckpt = SuperstepCheckpointer(args.ckpt_dir, base_config, cfg=cfg)
        result, curve = eng.run_streamed(
            args.source, ckpt=ckpt, telemetry=True,
            cache_budget_bytes=budget,
        )
        doc.update(
            dist_hash=_hash(result.dist), parent_hash=_hash(result.parent),
            num_levels=result.num_levels,
            direction_schedule=curve["direction_schedule"],
        )
        # The stream ledger rides the doc for the journal/inspection, but
        # the chaos differ must NOT pin it: a resumed run's cache starts
        # cold, so hit/miss/bytes curves legitimately differ from golden.
        doc["stream"] = eng.stream_report
    elif args.config == "multi":
        ckpt = SuperstepCheckpointer(args.ckpt_dir, base_config, cfg=cfg)
        v = graph.num_vertices
        sources = [(args.source + 7 * i) % v for i in range(4)]
        result = run_multi_segmented(
            graph, sources, ckpt=ckpt, engine="push"
        )
        doc.update(
            dist_hash=_hash(result.dist), parent_hash=_hash(result.parent),
            num_levels=result.num_levels,
        )
    elif args.config == "grid":
        from ..graph.grid_layout import parse_mesh_spec
        from ..parallel.grid import bfs_grid_segmented, make_grid_mesh

        r, c = parse_mesh_spec(args.mesh)
        mesh = make_grid_mesh(r, c)
        base_config["mesh"] = f"{r}x{c}"
        ckpt = SuperstepCheckpointer(
            args.ckpt_dir, base_config, cfg=cfg, shards=r * c
        )
        result, curve = bfs_grid_segmented(
            graph, args.source, mesh=mesh, ckpt=ckpt,
            direction="auto", exchange="auto", telemetry=True,
        )
        # Both per-axis arm sequences and byte curves in the result doc:
        # the chaos driver diffs resumed-vs-golden on exactly these, so a
        # resume that re-voted an axis arm or re-shipped a settled
        # destination is a hard diff, not a silent pass.
        doc.update(
            dist_hash=_hash(result.dist), parent_hash=_hash(result.parent),
            num_levels=result.num_levels,
            direction_schedule=curve["direction_schedule"],
            exchange_schedule=curve["exchange"]["schedule"],
            exchange_bytes=curve["exchange"]["bytes_per_level"],
            col_schedule=curve["exchange"]["col_schedule"],
            col_bytes=curve["exchange"]["col_bytes"],
            row_schedule=curve["exchange"]["row_schedule"],
            row_bytes=curve["exchange"]["row_bytes"],
        )
    else:  # sharded
        from ..parallel.sharded import bfs_sharded_segmented, make_mesh

        mesh = make_mesh(graph=args.shards, batch=1)
        ckpt = SuperstepCheckpointer(
            args.ckpt_dir, base_config, cfg=cfg, shards=args.shards
        )
        result, curve = bfs_sharded_segmented(
            graph, args.source, mesh=mesh, ckpt=ckpt,
            direction="auto", exchange="auto", telemetry=True,
        )
        doc.update(
            dist_hash=_hash(result.dist), parent_hash=_hash(result.parent),
            num_levels=result.num_levels,
            direction_schedule=curve["direction_schedule"],
            exchange_schedule=curve["exchange"]["schedule"],
            exchange_bytes=curve["exchange"]["bytes_per_level"],
        )
    doc["superstep_ckpt"] = ckpt.report()
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
    print(json.dumps({"ok": True, **{k: doc[k] for k in ("config",)}}),
          file=sys.stderr)
    return 0


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    raise SystemExit(_runner_main())
