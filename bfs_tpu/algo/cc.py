"""Connected components as label-min propagation on the superstep machine.

The ``cc`` semiring row (:data:`bfs_tpu.algo.substrate.SEMIRINGS`): every
vertex starts labeled with its own id, active vertices contribute their
LABEL along out-edges, the combine is the same segmented min, and a vertex
whose label improves joins the next frontier.  The fixpoint labels every
vertex with the minimum id reachable over edges — on the repo's standard
bi-directed graphs, exactly the minimum vertex id of its connected
component, the canonical representative the union-find oracle
(:func:`bfs_tpu.oracle.cc.union_find_labels`) computes.

Rootless: the initial frontier is ALL vertices (every vertex is its own
best-known label), there is no source argument, and isolated vertices
terminate immediately — the per-algorithm analog of the per-tile
empty-frontier early-out: a vertex whose label cannot improve never
re-enters the frontier, and the traversal ends when the frontier drains
globally.  Monotone label descent makes ANY superstep schedule converge
to the same fixpoint, which is why the push arm, the ELL pull arm and the
sharded arm are value-identical by construction (tests pin it).

The pull arm reuses the BFS ELL machinery verbatim:
:func:`bfs_tpu.ops.pull.pull_candidates` is already a value-agnostic
gather + row-min — BFS feeds it the frontier-id table, CC feeds it
``where(frontier, label, INF)`` — so the scatter-free superstep needs no
new kernel, just a different table.

No packed arm: the label IS the entire per-vertex state word already
(``packable=False`` in the contract table).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.runtime import traced
from ..graph.csr import Graph, build_device_graph
from ..ops.relax import INT32_MAX, combine_min


class CcState(NamedTuple):
    """Loop carry: ``label`` int32[V+1] (slot V inert, holds V);
    ``frontier`` marks vertices whose label improved last superstep."""

    label: jax.Array  # int32[V+1]
    frontier: jax.Array  # bool[V+1]
    rounds: jax.Array  # int32 scalar
    changed: jax.Array  # bool scalar


def init_cc_state(num_vertices: int) -> CcState:
    n = num_vertices + 1
    label = jnp.arange(n, dtype=jnp.int32)
    frontier = jnp.ones((n,), dtype=bool).at[num_vertices].set(False)
    return CcState(label, frontier, jnp.int32(0), jnp.bool_(True))


# bfs_tpu: hot traced
def cc_superstep(
    state: CcState,
    src: jax.Array,
    dst: jax.Array,
    *,
    axis_name: str | None = None,
) -> CcState:
    """One label-min superstep (push): active vertices broadcast their
    label along out-edges; per destination the minimum wins.  With
    ``axis_name``, per-shard candidates merge with ``lax.pmin``."""
    n = state.label.shape[0]
    active = state.frontier[src]
    cand = combine_min(
        jnp.where(active, state.label[src], INT32_MAX), dst, n
    )
    if axis_name is not None:
        cand = jax.lax.pmin(cand, axis_name)
    return _apply_labels(state, cand)


# bfs_tpu: hot traced
def _apply_labels(state: CcState, cand: jax.Array) -> CcState:
    """Shared apply tail of the push and pull arms: strict label descent,
    improved set = next frontier, termination = nothing improved."""
    improved = cand < state.label
    label = jnp.where(improved, cand, state.label)
    return CcState(label, improved, state.rounds + 1, improved.any())


# bfs_tpu: hot traced
def cc_superstep_pull(state: CcState, ell0, folds) -> CcState:
    """Pull twin: gather + row-min over the ELL in-neighbour matrices
    (:func:`bfs_tpu.ops.pull.pull_candidates` with the LABEL table in
    place of BFS's frontier-id table — the op is value-agnostic)."""
    from ..ops.pull import pull_candidates

    tab = jnp.where(state.frontier, state.label, INT32_MAX)
    cand = pull_candidates(tab, ell0, folds)
    return _apply_labels(state, cand)


@functools.partial(
    jax.jit, static_argnames=("num_vertices", "max_rounds")
)
@traced("algo.cc_fused")
def _cc_fused(src, dst, num_vertices: int, max_rounds: int):
    """Fused push CC: one ``while_loop`` to the label fixpoint."""
    state = init_cc_state(num_vertices)

    def cond(s):
        return s.changed & (s.rounds < max_rounds)

    def body(s):
        return cc_superstep(s, src, dst)

    return jax.lax.while_loop(cond, body, state)


@functools.partial(
    jax.jit, static_argnames=("num_vertices", "max_rounds")
)
@traced("algo.cc_pull_fused")
def _cc_pull_fused(ell0, folds, num_vertices: int, max_rounds: int):
    """Fused pull CC over the ELL layout (same fixpoint, scatter-free)."""
    state = init_cc_state(num_vertices)

    def cond(s):
        return s.changed & (s.rounds < max_rounds)

    def body(s):
        return cc_superstep_pull(s, ell0, folds)

    return jax.lax.while_loop(cond, body, state)


@functools.partial(
    jax.jit, static_argnames=("num_vertices",), donate_argnums=(0,)
)
@traced("algo.cc_segment")
def _cc_segment(state, seg_end, src, dst, num_vertices: int):
    """ONE bounded segment of the push loop (checkpointable twin;
    ``seg_end`` traced — no retrace per segment advance)."""

    def cond(s):
        return s.changed & (s.rounds < seg_end)

    def body(s):
        return cc_superstep(s, src, dst)

    return jax.lax.while_loop(cond, body, state)


# ------------------------------------------------------------ host driver --

@dataclass
class CcResult:
    """Host-side labels (int32[V], sentinel slot stripped): ``label[v]``
    is the minimum vertex id of v's component.  ``rounds`` counts
    executed supersteps including the final empty one that detects the
    fixpoint."""

    label: np.ndarray
    rounds: int
    engine: str

    @property
    def num_components(self) -> int:
        return int(np.unique(self.label).size)

    def same_component(self, u: int, v: int) -> bool:
        return int(self.label[u]) == int(self.label[v])


def _resolve_engine(engine: str, graph: Graph) -> str:
    """``auto`` picks pull past the same density point the BFS engines
    use as a rule of thumb (gather beats scatter on dense in-neighbour
    rows); any choice is value-identical — monotone label descent has one
    fixpoint — so this only shapes the superstep cost."""
    if engine != "auto":
        return engine
    v = max(graph.num_vertices, 1)
    return "pull" if graph.num_edges / v >= 8 else "push"


def cc(
    graph: Graph,
    *,
    engine: str = "push",
    max_rounds: int | None = None,
    block: int = 1024,
) -> CcResult:
    """Connected components (``engine`` = push | pull | auto).  On a
    bi-directed graph the labels are exactly union-find's min-id
    representatives; on a directed graph this computes the min REACHABLE
    id fixpoint instead (pass the bi-directed form for components)."""
    engine = _resolve_engine(engine, graph)
    v = graph.num_vertices
    if engine == "pull":
        from ..graph.ell import build_pull_graph, device_ell

        pg = build_pull_graph(graph)
        ell0, folds = device_ell(pg)
        return cc_device_pull(
            ell0, folds, pg.num_vertices, max_rounds=max_rounds
        )
    if engine == "push":
        dg = build_device_graph(graph, block=block)
        return cc_device(
            jnp.asarray(dg.src), jnp.asarray(dg.dst), v,
            max_rounds=max_rounds,
        )
    raise ValueError(
        f"unknown engine {engine!r}; use 'push', 'pull' or 'auto'"
    )


def cc_device(
    src_dev, dst_dev, num_vertices: int, *, max_rounds: int | None = None
) -> CcResult:
    """The push arm against ALREADY-RESIDENT sentinel-padded device edge
    arrays — the serve registry's residency form
    (:func:`bfs_tpu.serve.algo.registry_cc`)."""
    v = int(num_vertices)
    cap = int(max_rounds) if max_rounds is not None else v + 1
    state = _cc_fused(src_dev, dst_dev, num_vertices=v, max_rounds=cap)
    label = np.asarray(jax.device_get(state.label))
    return CcResult(
        label=label[:v],
        rounds=int(jax.device_get(state.rounds)),
        engine="push",
    )


def cc_device_pull(
    ell0, folds, num_vertices: int, *, max_rounds: int | None = None
) -> CcResult:
    """The pull arm against resident ELL operands (same fixpoint)."""
    v = int(num_vertices)
    cap = int(max_rounds) if max_rounds is not None else v + 1
    state = _cc_pull_fused(ell0, folds, num_vertices=v, max_rounds=cap)
    label = np.asarray(jax.device_get(state.label))
    return CcResult(
        label=label[:v],
        rounds=int(jax.device_get(state.rounds)),
        engine="pull",
    )


def cc_segmented(
    graph: Graph,
    *,
    ckpt,
    max_rounds: int | None = None,
    block: int = 1024,
) -> CcResult:
    """Checkpointed twin of the push arm: bounded segments, a durable
    epoch per boundary, bit-identical labels for any segmentation
    (:func:`bfs_tpu.algo.substrate.drive_segments`)."""
    from .substrate import drive_segments

    dg = build_device_graph(graph, block=block)
    v = dg.num_vertices
    cap = int(max_rounds) if max_rounds is not None else v + 1
    src_dev, dst_dev = jnp.asarray(dg.src), jnp.asarray(dg.dst)

    def init(arrays):
        if arrays is not None:
            return CcState(**{
                k: jnp.asarray(arrays[k]) for k in CcState._fields
            })
        return init_cc_state(v)

    def seg(carry, seg_end):
        return _cc_segment(carry, seg_end, src_dev, dst_dev, num_vertices=v)

    state, rounds, _ = drive_segments(
        ckpt, init=init, seg=seg, fields=CcState._fields,
        packed=False, cap=cap,
    )
    label = np.asarray(jax.device_get(state.label))
    ckpt.clear()
    return CcResult(label=label[:v], rounds=rounds, engine="push")
