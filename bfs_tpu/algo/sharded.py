"""Sharded SSSP and CC: the semiring supersteps over an edge-sharded mesh.

The exact shape of :func:`bfs_tpu.parallel.sharded._bfs_sharded_fused`
with the semiring swapped: each device holds one round-robin edge shard
(``build_device_graph(num_shards=n)``), per-vertex state is replicated,
per-shard candidates merge with ONE ``lax.pmin`` over the graph axis, and
every device then computes identical state updates — no further
collectives, the replicated-carry contract the BFS mesh programs
established (version-spanning via :mod:`bfs_tpu.parallel.compat`).

SSSP needs no weight operand plumbing: weights are a hash of the
endpoints (:func:`bfs_tpu.algo.substrate.edge_weights`), so each mesh
body recomputes its own shard's weights from the edge block it already
holds — re-sharding can never misalign them.  The exit-time parent
canonicalization runs OUTSIDE the mesh on the flat edge arrays (the
replicated final dists make it shard-count-independent by construction).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from ..analysis.runtime import traced
from ..graph.csr import Graph, build_device_graph
from ..parallel.compat import shard_map as _shard_map
from ..parallel.sharded import GRAPH_AXIS, make_mesh
from .cc import CcResult, CcState, cc_superstep, init_cc_state
from .sssp import (
    SsspResult,
    SsspState,
    _finish,
    _rounds_cap,
    init_sssp_state,
    sssp_superstep,
)
from .substrate import DEFAULT_MAX_WEIGHT, edge_weights, resolve_delta


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "num_vertices", "max_weight", "delta", "max_rounds",
    ),
)
@traced("algo.sssp_sharded_fused")
def _sssp_sharded_fused(
    src, dst, source, *, mesh, num_vertices, max_weight, delta, max_rounds
):
    def inner(src_blk, dst_blk, source):
        src_e = src_blk.reshape(-1)
        dst_e = dst_blk.reshape(-1)
        w_e = edge_weights(src_e, dst_e, max_weight)
        state = init_sssp_state(num_vertices, source, delta)

        def cond(s):
            return s.changed & (s.rounds < max_rounds)

        def body(s):
            return sssp_superstep(
                s, src_e, dst_e, w_e, delta, axis_name=GRAPH_AXIS
            )

        return jax.lax.while_loop(cond, body, state)

    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(GRAPH_AXIS, None), P(GRAPH_AXIS, None), P()),
        out_specs=SsspState(P(), P(), P(), P(), P()),
        axis_names={GRAPH_AXIS},
    )
    return fn(src, dst, source)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "num_vertices", "max_rounds"),
)
@traced("algo.cc_sharded_fused")
def _cc_sharded_fused(src, dst, *, mesh, num_vertices, max_rounds):
    def inner(src_blk, dst_blk):
        src_e = src_blk.reshape(-1)
        dst_e = dst_blk.reshape(-1)
        state = init_cc_state(num_vertices)

        def cond(s):
            return s.changed & (s.rounds < max_rounds)

        def body(s):
            return cc_superstep(s, src_e, dst_e, axis_name=GRAPH_AXIS)

        return jax.lax.while_loop(cond, body, state)

    fn = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(GRAPH_AXIS, None), P(GRAPH_AXIS, None)),
        out_specs=CcState(P(), P(), P(), P()),
        axis_names={GRAPH_AXIS},
    )
    return fn(src, dst)


def sssp_sharded(
    graph: Graph,
    source: int = 0,
    *,
    num_shards: int | None = None,
    mesh=None,
    max_weight: int = DEFAULT_MAX_WEIGHT,
    delta: int | str | None = None,
    max_rounds: int | None = None,
    block: int = 1024,
) -> SsspResult:
    """Edge-sharded SSSP (unpacked carry).  ``num_shards`` defaults to
    the mesh's graph-axis extent; results are bit-identical to the
    single-device :func:`bfs_tpu.algo.sssp.sssp` unpacked arm — the pmin
    merge commutes with the segmented min."""
    if mesh is None:
        mesh = make_mesh(graph=num_shards, batch=1)
    n_shards = mesh.shape[GRAPH_AXIS]
    dg = build_device_graph(graph, num_shards=n_shards, block=block)
    v = dg.num_vertices
    delta_i = resolve_delta(delta)
    cap = _rounds_cap(v, max_weight, max_rounds)
    state = _sssp_sharded_fused(
        jnp.asarray(dg.src), jnp.asarray(dg.dst), jnp.int32(source),
        mesh=mesh, num_vertices=v, max_weight=max_weight,
        delta=delta_i, max_rounds=cap,
    )
    flat_src = jnp.asarray(np.ascontiguousarray(dg.src.reshape(-1)))
    flat_dst = jnp.asarray(np.ascontiguousarray(dg.dst.reshape(-1)))
    dist, parent = _finish(
        state.dist, flat_src, flat_dst, source, v + 1, max_weight
    )
    return SsspResult(
        dist=dist[:v], parent=parent[:v],
        rounds=int(jax.device_get(state.rounds)),
        max_weight=max_weight, delta=delta_i, packed=False,
    )


def cc_sharded(
    graph: Graph,
    *,
    num_shards: int | None = None,
    mesh=None,
    max_rounds: int | None = None,
    block: int = 1024,
) -> CcResult:
    """Edge-sharded connected components; labels bit-identical to the
    single-device push arm (one label fixpoint)."""
    if mesh is None:
        mesh = make_mesh(graph=num_shards, batch=1)
    n_shards = mesh.shape[GRAPH_AXIS]
    dg = build_device_graph(graph, num_shards=n_shards, block=block)
    v = dg.num_vertices
    cap = int(max_rounds) if max_rounds is not None else v + 1
    state = _cc_sharded_fused(
        jnp.asarray(dg.src), jnp.asarray(dg.dst),
        mesh=mesh, num_vertices=v, max_rounds=cap,
    )
    label = np.asarray(jax.device_get(state.label))
    return CcResult(
        label=label[:v],
        rounds=int(jax.device_get(state.rounds)),
        engine=f"push_sharded_x{n_shards}",
    )
