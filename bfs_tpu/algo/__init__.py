"""Semiring-parameterized graph algorithms on the superstep machinery.

One substrate (:mod:`bfs_tpu.algo.substrate` — the contribute/combine/
identity/state contract), many algorithms: BFS (the original instance,
:mod:`bfs_tpu.models.bfs`), weighted SSSP as min-plus supersteps with
delta-stepping buckets (:mod:`bfs_tpu.algo.sssp`), connected components
as label-min propagation (:mod:`bfs_tpu.algo.cc`), each riding the
fused / segmented / sharded program families with oracle-exact results
(docs/ARCHITECTURE.md §24).
"""

from .cc import CcResult, cc, cc_segmented
from .sharded import cc_sharded, sssp_sharded
from .sssp import SsspResult, sssp, sssp_segmented
from .substrate import (
    DEFAULT_MAX_WEIGHT,
    SEMIRINGS,
    Semiring,
    edge_weights_np,
    resolve_delta,
)

__all__ = [
    "CcResult",
    "DEFAULT_MAX_WEIGHT",
    "SEMIRINGS",
    "Semiring",
    "SsspResult",
    "cc",
    "cc_segmented",
    "cc_sharded",
    "edge_weights_np",
    "resolve_delta",
    "sssp",
    "sssp_segmented",
    "sssp_sharded",
]
